"""Host parameter service for out-of-HBM embedding tables.

Reference: the pserver stack — listen_and_serv_op.cc (RunSyncLoop:109),
RPCClient/RPCServer + VariableMessage wire form (operators/distributed/),
parameter_prefetch.cc (sparse rows pulled on demand), and the transpiler's
distributed lookup table (distribute_transpiler.py:1428-1583).

TPU-first scope (SURVEY §2c): DENSE parameters never touch this — allreduce
over ICI owns them.  What survives is the capability the pserver actually
carried: embedding tables too big for HBM, sharded on HOSTS, with rows
pulled before the step and sparse row gradients pushed after.  The wire is
a length-prefixed binary protocol over TCP sockets (no gRPC in the image);
the server applies the optimizer row-update itself (SGD/Adagrad), which is
exactly the listen_and_serv optimize-block role.

Use with the SelectedRows machinery: run the device program with the
pulled rows as a feed, read the lookup's SelectedRows gradient, push it.
`HostTableEmbedding` below packages that loop.
"""
from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .core import locks

_MAGIC = b"PTPS"


def _merge_rows(ids: np.ndarray, grads: np.ndarray):
    """MergeAdd (reference selected_rows_functor): sum duplicate rows."""
    uniq, inv = np.unique(np.asarray(ids, np.int64).reshape(-1),
                          return_inverse=True)
    merged = np.zeros((uniq.size,) + grads.shape[1:], grads.dtype)
    np.add.at(merged, inv, grads)
    return uniq, merged


def _send_msg(sock, op: bytes, payload: bytes):
    sock.sendall(_MAGIC + op + struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("parameter server connection closed")
        buf += chunk
    return buf


def _recv_msg(sock) -> Tuple[bytes, bytes]:
    head = _recv_exact(sock, 13)
    if head[:4] != _MAGIC:
        raise ValueError("parameter server: bad magic")
    op = head[4:5]
    (n,) = struct.unpack("<Q", head[5:13])
    return op, _recv_exact(sock, n)


def _pack_arr(a: np.ndarray) -> bytes:
    a = np.ascontiguousarray(a)
    dt = a.dtype.str.encode()
    return (struct.pack("<I", len(dt)) + dt + struct.pack("<I", a.ndim)
            + struct.pack(f"<{a.ndim}q", *a.shape) + a.tobytes())


def _unpack_arr(b: bytes, off: int = 0):
    (dl,) = struct.unpack_from("<I", b, off)
    off += 4
    dt = np.dtype(b[off:off + dl].decode())
    off += dl
    (nd,) = struct.unpack_from("<I", b, off)
    off += 4
    shape = struct.unpack_from(f"<{nd}q", b, off)
    off += 8 * nd
    size = int(np.prod(shape)) if nd else 1
    arr = np.frombuffer(b, dt, count=size, offset=off).reshape(shape)
    return arr, off + arr.nbytes


class ParameterServer:
    """Row-sharded host table server (one shard per server process/port).

    Protocol ops: b"P" pull(name, ids) -> rows; b"G" push(name, ids, grads)
    applying the configured row update; b"C" create(name, array);
    b"F" fetch full table (checkpointing); b"Q" shutdown."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 optimizer: str = "sgd", lr: float = 0.1):
        self.tables: Dict[str, np.ndarray] = {}
        self.accums: Dict[str, np.ndarray] = {}
        self.optimizer = optimizer
        self.lr = lr
        self._lock = locks.named_lock("ps.tables", rank=34)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        op, payload = _recv_msg(self.request)
                        if op == b"Q":
                            _send_msg(self.request, b"q", b"")
                            outer._srv.shutdown()
                            return
                        try:
                            resp = outer._dispatch(op, payload)
                        except Exception as e:  # error REPLY, not a dead socket
                            _send_msg(self.request, b"e",
                                      f"{type(e).__name__}: {e}".encode())
                            continue
                        _send_msg(self.request, op.lower(), resp)
                except (ConnectionError, OSError):
                    return

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Srv((host, port), Handler)
        self.endpoint = f"{self._srv.server_address[0]}:{self._srv.server_address[1]}"
        self._thread: Optional[threading.Thread] = None

    # -- server-side ops ---------------------------------------------------
    def _dispatch(self, op: bytes, payload: bytes) -> bytes:
        (nl,) = struct.unpack_from("<I", payload, 0)
        name = payload[4:4 + nl].decode()
        off = 4 + nl
        if op == b"C":
            arr, _ = _unpack_arr(payload, off)
            with self._lock:
                self.tables[name] = np.array(arr)
                self.accums[name] = np.zeros_like(self.tables[name])
            return b""
        if op == b"P":
            ids, _ = _unpack_arr(payload, off)
            with self._lock:
                rows = self.tables[name][ids.astype(np.int64)]
            return _pack_arr(rows)
        if op == b"G":
            ids, off2 = _unpack_arr(payload, off)
            grads, _ = _unpack_arr(payload, off2)
            with self._lock:
                t = self.tables[name]
                # MergeAdd first: duplicate rows sum BEFORE the accumulator
                # update, or adagrad drifts
                uniq, merged = _merge_rows(ids, grads)
                if self.optimizer == "adagrad":
                    acc = self.accums[name]
                    acc[uniq] += merged * merged
                    t[uniq] += -self.lr * merged / (np.sqrt(acc[uniq]) + 1e-6)
                else:  # sgd
                    t[uniq] += -self.lr * merged
            return b""
        if op == b"F":
            with self._lock:
                return _pack_arr(self.tables[name])
        raise ValueError(f"parameter server: unknown op {op!r}")

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


class KVClient:
    def __init__(self, endpoint: str):
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)))
        self._lock = locks.named_lock("ps.client", rank=36)

    def _call(self, op: bytes, name: str, *arrays) -> bytes:
        payload = struct.pack("<I", len(name)) + name.encode()
        for a in arrays:
            payload += _pack_arr(np.asarray(a))
        with self._lock:  # lock-ok: one request/response exchange on one shared socket — serializing the framed protocol IS the lock's purpose (interleaved frames from two threads would corrupt the stream)
            _send_msg(self._sock, op, payload)
            rop, resp = _recv_msg(self._sock)
        if rop == b"e":
            raise RuntimeError(f"parameter server error: {resp.decode()}")
        return resp

    def create(self, name: str, array: np.ndarray):
        self._call(b"C", name, array)

    def pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        resp = self._call(b"P", name, np.asarray(ids, np.int64))
        return _unpack_arr(resp)[0]

    def push(self, name: str, ids: np.ndarray, grads: np.ndarray):
        self._call(b"G", name, np.asarray(ids, np.int64), grads)

    def fetch_table(self, name: str) -> np.ndarray:
        return _unpack_arr(self._call(b"F", name))[0]

    def close(self):
        self._sock.close()


class HostTableEmbedding:
    """Out-of-HBM embedding: the device program sees only the pulled rows
    (a [B*, D] dense feed whose lookup ids are batch-local positions); the
    V×D table lives on the parameter server (reference
    parameter_prefetch.cc flow).

    Per step: (unique_ids, local_ids) <- batch ids; rows <- pull;
    run program with rows + local ids; push SelectedRows grad back."""

    def __init__(self, client: KVClient, name: str, dim: int):
        self.client = client
        self.name = name
        self.dim = dim

    def prepare_batch(self, ids: np.ndarray):
        uniq, local = np.unique(ids.reshape(-1), return_inverse=True)
        rows = self.client.pull(self.name, uniq)
        return uniq, local.reshape(ids.shape).astype(np.int64), rows

    def push_grad(self, uniq: np.ndarray, grad_rows: np.ndarray):
        self.client.push(self.name, uniq, np.asarray(grad_rows))


class AsyncCommunicator:
    """Asynchronous push/pull for host tables (reference
    operators/distributed/communicator.cc — SendThread:104 batches+merges
    queued grads, RecvThread:200 refreshes params periodically; async-PS
    semantics: no barriers, bounded staleness).

    push_async() enqueues and returns immediately; a background thread
    merges queued slabs per table (MergeAdd) and pushes.  pull() reads
    through to the server (rows may be stale by whatever is still queued —
    that staleness IS the async contract)."""

    def __init__(self, client: KVClient, send_interval_s: float = 0.01):
        self._client = client
        self._interval = send_interval_s
        self._queues: Dict[str, list] = {}
        self._lock = locks.named_lock("ps.queue", rank=32)
        # serializes in-flight drains (rank 30: held ACROSS ps.queue and
        # the ps.client push — that span is the flush() barrier contract)
        self._drain_lock = locks.named_lock("ps.drain", rank=30)
        self._stop = threading.Event()
        self._woke = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()
        return self

    def push_async(self, name: str, ids: np.ndarray, grads: np.ndarray):
        if self._error is not None:
            raise RuntimeError("AsyncCommunicator sender died") from self._error
        with self._lock:
            self._queues.setdefault(name, []).append(
                (np.asarray(ids, np.int64).reshape(-1), np.asarray(grads)))
        self._woke.set()

    def pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        return self._client.pull(name, ids)

    def _drain_one(self):
        # _drain_lock makes drains mutually exclusive, so flush() returns
        # only after any in-flight send completes (the barrier contract)
        with self._drain_lock:  # lock-ok: the flush() barrier contract REQUIRES holding this across the merge+push — push_async never takes it, so producers stay unblocked
            with self._lock:
                items = {n: q for n, q in self._queues.items() if q}
                self._queues = {}
            for name, slabs in items.items():
                ids = np.concatenate([i for i, _ in slabs])
                grads = np.concatenate([g for _, g in slabs])
                uniq, merged = _merge_rows(ids, grads)
                self._client.push(name, uniq, merged)

    def _send_loop(self):
        while not self._stop.is_set():
            self._woke.wait(timeout=self._interval)
            self._woke.clear()
            try:
                self._drain_one()
            except BaseException as e:  # surface on next push/flush
                self._error = e
                return

    def flush(self):
        """Synchronize: drain everything queued AND wait out any in-flight
        send (barrier for eval/save)."""
        if self._error is not None:
            raise RuntimeError("AsyncCommunicator sender died") from self._error
        self._drain_one()

    def stop(self):
        self._stop.set()
        self._woke.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._drain_one()
        if self._error is not None:
            raise RuntimeError("AsyncCommunicator sender died") from self._error
