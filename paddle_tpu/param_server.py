"""Host parameter service for out-of-HBM embedding tables.

Reference: the pserver stack — listen_and_serv_op.cc (RunSyncLoop:109),
RPCClient/RPCServer + VariableMessage wire form (operators/distributed/),
parameter_prefetch.cc (sparse rows pulled on demand), and the transpiler's
distributed lookup table (distribute_transpiler.py:1428-1583).

TPU-first scope (SURVEY §2c): DENSE parameters never touch this — allreduce
over ICI owns them.  What survives is the capability the pserver actually
carried: embedding tables too big for HBM, sharded on HOSTS, with rows
pulled before the step and sparse row gradients pushed after.  The wire is
a length-prefixed binary protocol over TCP sockets (no gRPC in the image);
the server applies the optimizer row-update itself (SGD/Adagrad), which is
exactly the listen_and_serv optimize-block role.

Fault hardening (ISSUE 19) — the tier is a supervised, survivable,
integrity-checked service:

  * every socket carries a deadline (`FLAGS_ps_timeout_s`) and every
    failure classifies onto `errors.ParamServerError` with the same
    transient/terminal split `StorageError` has;
  * `KVClient` retries transient failures with reconnect + seeded
    backoff (`FLAGS_ps_retries`); pushes carry a per-client sequence
    number the server dedups, so a retried push — the reply was lost,
    not the apply — lands EXACTLY once;
  * frames are capped (`FLAGS_ps_max_frame_mb`): a corrupt length
    prefix raises terminal instead of mallocing unbounded;
  * with a `snapshot_dir` the server is DURABLE: every mutating op is
    write-ahead journaled (`io.append_record`, fsynced before apply)
    and tables snapshot through the io.py atomic choke point every
    `FLAGS_ps_snapshot_every_ops` ops; a crash-restarted server
    recovers tables, accumulators, and the dedup map bit-identical;
  * `PServerSupervisor` crash-restarts the server process under a
    restart budget, reusing the PR-18 `ReplicaBeat`/`FleetHealth`
    liveness plane — a SIGKILLed or wedged pserver comes back inside
    one health deadline, and the client's retry loop rides it out.

Use with the SelectedRows machinery: run the device program with the
pulled rows as a feed, read the lookup's SelectedRows gradient, push it.
`HostTableEmbedding` below packages that loop (and its bounded degraded
mode while the tier is down).
"""
from __future__ import annotations

import hashlib
import json
import os
import signal
import socket
import socketserver
import struct
import subprocess
import sys
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from .core import locks
from .errors import (ParamServerError, StorageError, TRANSIENT_PS_ERRNOS,
                     attach_context, classify)
from .flags import flag as _flag
from .monitor import MONITOR as _MON, record_fleet_event

__all__ = ["ParameterServer", "KVClient", "PServerSupervisor",
           "HostTableEmbedding", "AsyncCommunicator"]

_MAGIC = b"PTPS"

# snapshot/journal layout inside a server's snapshot_dir
PS_MANIFEST = "__ps_manifest__.json"
PS_COMMITTED = "PS_COMMITTED"


def _max_frame_bytes() -> int:
    mb = _flag("FLAGS_ps_max_frame_mb")
    return int(float(mb or 256) * (1 << 20))


def _timeout_s() -> Optional[float]:
    t = float(_flag("FLAGS_ps_timeout_s") or 0.0)
    return t if t > 0 else None


def _merge_rows(ids: np.ndarray, grads: np.ndarray):
    """MergeAdd (reference selected_rows_functor): sum duplicate rows."""
    uniq, inv = np.unique(np.asarray(ids, np.int64).reshape(-1),
                          return_inverse=True)
    merged = np.zeros((uniq.size,) + grads.shape[1:], grads.dtype)
    np.add.at(merged, inv, grads)
    return uniq, merged


def _send_msg(sock, op: bytes, payload: bytes):
    cap = _max_frame_bytes()
    if len(payload) > cap:
        raise ParamServerError(
            f"refusing to send a {len(payload)}-byte frame past the "
            f"FLAGS_ps_max_frame_mb cap ({cap} bytes) — split the push "
            f"or raise the cap", transient=False)
    sock.sendall(_MAGIC + op + struct.pack("<Q", len(payload)) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("parameter server connection closed")
        buf += chunk
    return buf


def _recv_msg(sock) -> Tuple[bytes, bytes]:
    head = _recv_exact(sock, 13)
    if head[:4] != _MAGIC:
        raise ParamServerError(
            "parameter server: bad magic — the stream is corrupt or "
            "something other than a pserver peer wrote to this socket",
            transient=False)
    op = head[4:5]
    (n,) = struct.unpack("<Q", head[5:13])
    cap = _max_frame_bytes()
    if n > cap:
        # a corrupt length prefix must never malloc unbounded; past this
        # point the stream is unsynchronized, so the connection dies too
        raise ParamServerError(
            f"parameter server: frame length {n} exceeds the "
            f"FLAGS_ps_max_frame_mb cap ({cap} bytes) — corrupt length "
            f"prefix", transient=False)
    return op, _recv_exact(sock, n)


def _pack_arr(a: np.ndarray) -> bytes:
    a = np.ascontiguousarray(a)
    dt = a.dtype.str.encode()
    return (struct.pack("<I", len(dt)) + dt + struct.pack("<I", a.ndim)
            + struct.pack(f"<{a.ndim}q", *a.shape) + a.tobytes())


def _unpack_arr(b: bytes, off: int = 0):
    (dl,) = struct.unpack_from("<I", b, off)
    off += 4
    dt = np.dtype(b[off:off + dl].decode())
    off += dl
    (nd,) = struct.unpack_from("<I", b, off)
    off += 4
    shape = struct.unpack_from(f"<{nd}q", b, off)
    off += 8 * nd
    size = int(np.prod(shape)) if nd else 1
    arr = np.frombuffer(b, dt, count=size, offset=off).reshape(shape)
    return arr, off + arr.nbytes


class ParameterServer:
    """Row-sharded host table server (one shard per server process/port).

    Protocol ops: b"P" pull(name, ids) -> rows; b"G" push(name, ids,
    grads) applying the configured row update; b"S" sequenced push
    (client id + seq prefix, deduped server-side for exactly-once);
    b"C" create(name, array); b"F" fetch full table (checkpointing);
    b"D" content digest of a table (+ accumulator); b"Q" shutdown.

    With `snapshot_dir`, mutating ops (C/G/S) are write-ahead journaled
    and the tables snapshot every `snapshot_every_ops` mutations; a
    fresh server over the same dir recovers bit-identical state."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 optimizer: str = "sgd", lr: float = 0.1,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every_ops: Optional[int] = None):
        self.tables: Dict[str, np.ndarray] = {}
        self.accums: Dict[str, np.ndarray] = {}
        self.optimizer = optimizer
        self.lr = lr
        self._lock = locks.named_lock("ps.tables", rank=34)
        # durability state (all mutated under ps.tables): the WAL the
        # choke point fsyncs before each apply, the total mutating-op
        # count (snapshot cadence + journal file naming), and the
        # per-client last-applied sequence map (exactly-once)
        self.snapshot_dir = snapshot_dir
        self._snap_every = (int(_flag("FLAGS_ps_snapshot_every_ops") or 0)
                            if snapshot_every_ops is None
                            else int(snapshot_every_ops))
        self.op_count = 0
        self.applied: Dict[str, int] = {}
        self._journal_path: Optional[str] = None
        if snapshot_dir:
            os.makedirs(snapshot_dir, exist_ok=True)
            self._recover()
            if self._journal_path is None:
                self._journal_path = os.path.join(
                    snapshot_dir, f"journal-{self.op_count}.log")
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    while True:
                        try:
                            op, payload = _recv_msg(self.request)
                        except ParamServerError as e:
                            # protocol violation: the stream is
                            # unsynchronized — reply best-effort, drop
                            # the connection (never malloc the frame)
                            _MON.counter("ps.frame_rejects").inc()
                            try:
                                _send_msg(self.request, b"e",
                                          f"{type(e).__name__}: {e}"
                                          .encode())
                            except OSError:
                                pass
                            return
                        if op == b"Q":
                            _send_msg(self.request, b"q", b"")
                            outer._srv.shutdown()
                            return
                        try:
                            resp = outer._dispatch(op, payload)
                        except Exception as e:  # error REPLY, not a dead socket
                            _send_msg(self.request, b"e",
                                      f"{type(e).__name__}: {e}".encode())
                            continue
                        _send_msg(self.request, op.lower(), resp)
                except (ConnectionError, OSError):
                    return

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._srv = Srv((host, port), Handler)
        self.endpoint = f"{self._srv.server_address[0]}:{self._srv.server_address[1]}"
        self._thread: Optional[threading.Thread] = None

    # -- durability --------------------------------------------------------
    def _journal(self, op: bytes, payload: bytes):
        """Write-ahead: the op is durable BEFORE it applies, so replay
        after a crash reproduces exactly the applies that happened (plus
        at most the one the crash interrupted — whose client never got a
        reply and will retry, deduped by its sequence number).  A failing
        journal write (injected ENOSPC, full disk) degrades durability,
        never availability: counted + recorded, the op still applies."""
        if self._journal_path is None:
            return
        from . import io as _io

        try:
            _io.append_record(self._journal_path, op + payload)
        except (OSError, StorageError) as e:
            _MON.counter("ps.journal_errors").inc()
            _MON.record_step({"kind": "sparse_event",
                              "action": "ps_journal_degraded",
                              "detail": f"{type(e).__name__}: {e}"})

    def _snapshot_locked(self):
        """Commit a full table snapshot through the io.py atomic choke
        point: per-table .npy payloads, a digest-stamped manifest, and a
        COMMITTED marker last — torn snapshots are invisible to
        recovery.  Old snapshots/journals are pruned after commit."""
        from . import integrity as _integrity
        from . import io as _io

        snap = os.path.join(self.snapshot_dir, f"snap-{self.op_count}")
        os.makedirs(snap, exist_ok=True)
        entries = []
        for name in sorted(self.tables):
            safe = name.replace("/", "%2F")
            tf, af = f"{safe}.table.npy", f"{safe}.accum.npy"
            _io.save_array(os.path.join(snap, tf), self.tables[name])
            _io.save_array(os.path.join(snap, af), self.accums[name])
            entries.append({
                "name": name, "table_file": tf, "accum_file": af,
                "table_stamp": _integrity.stamp_file(os.path.join(snap, tf)),
                "accum_stamp": _integrity.stamp_file(os.path.join(snap, af)),
            })
        _io.atomic_write(os.path.join(snap, PS_MANIFEST), json.dumps({
            "op_count": self.op_count, "optimizer": self.optimizer,
            "lr": self.lr, "applied": dict(self.applied),
            "tables": entries}, indent=1))
        _io.atomic_write(os.path.join(snap, PS_COMMITTED), "")
        _MON.counter("ps.snapshots").inc()
        # prune: everything older than the snapshot just committed is
        # re-derivable from it (best-effort — a failed unlink costs disk,
        # not correctness)
        self._journal_path = os.path.join(
            self.snapshot_dir, f"journal-{self.op_count}.log")
        import glob as _glob
        import shutil

        for jp in _glob.glob(os.path.join(self.snapshot_dir, "journal-*.log")):
            try:
                if int(os.path.basename(jp)[8:-4]) < self.op_count:
                    os.remove(jp)
            except (ValueError, OSError):
                pass
        for sp in _glob.glob(os.path.join(self.snapshot_dir, "snap-*")):
            try:
                if int(os.path.basename(sp)[5:]) < self.op_count:
                    shutil.rmtree(sp, ignore_errors=True)
            except ValueError:
                pass

    def snapshot(self):
        """Force a snapshot commit now (stop() does this; tests too)."""
        if not self.snapshot_dir:
            return
        with self._lock:  # lock-ok: the stop-the-world snapshot IS the consistency cut — mutating ops must not interleave with table serialization, and pruning the superseded snap rides the same cut
            try:
                self._snapshot_locked()
            except (OSError, StorageError) as e:
                _MON.counter("ps.snapshot_errors").inc()
                _MON.record_step({"kind": "sparse_event",
                                  "action": "ps_snapshot_failed",
                                  "detail": f"{type(e).__name__}: {e}"})

    def _recover(self):
        """Rebuild tables/accums/dedup map from the newest COMMITTED
        snapshot plus every journaled op after it — bit-identical to the
        state the dead server had applied."""
        from . import integrity as _integrity
        from . import io as _io
        import glob as _glob

        snaps = []
        for sp in _glob.glob(os.path.join(self.snapshot_dir, "snap-*")):
            if os.path.exists(os.path.join(sp, PS_COMMITTED)):
                try:
                    snaps.append((int(os.path.basename(sp)[5:]), sp))
                except ValueError:
                    pass
        base = 0
        if snaps:
            base, snap = max(snaps)
            man = _io.read_json(os.path.join(snap, PS_MANIFEST))
            for e in man["tables"]:
                # a flipped byte in a host-tier table at rest must fail
                # the recovery, never serve (same contract as checkpoint
                # shards): verify the manifest stamps before use
                _integrity.verify_file_entry(
                    snap, e["table_file"], e["table_stamp"]["sha256"],
                    e["table_stamp"]["bytes"])
                _integrity.verify_file_entry(
                    snap, e["accum_file"], e["accum_stamp"]["sha256"],
                    e["accum_stamp"]["bytes"])
                self.tables[e["name"]] = np.array(
                    _io.load_array(os.path.join(snap, e["table_file"])))
                self.accums[e["name"]] = np.array(
                    _io.load_array(os.path.join(snap, e["accum_file"])))
            self.applied = {str(k): int(v)
                            for k, v in man.get("applied", {}).items()}
            self.op_count = int(man["op_count"])
        journals = []
        for jp in _glob.glob(os.path.join(self.snapshot_dir, "journal-*.log")):
            try:
                start = int(os.path.basename(jp)[8:-4])
            except ValueError:
                continue
            if start >= base:
                journals.append((start, jp))
        replayed = 0
        for _start, jp in sorted(journals):
            self._journal_path = jp
            for rec in _io.read_journal(jp):
                self._apply(rec[:1], rec[1:], journal=False)
                replayed += 1
        if snaps or replayed:
            _MON.counter("ps.recoveries").inc()
            _MON.record_step({"kind": "sparse_event",
                              "action": "ps_recovered",
                              "snapshot_ops": base, "replayed": replayed,
                              "op_count": self.op_count})

    def table_digest(self, name: str) -> str:
        """sha256 over the table + accumulator bytes (+ shape/dtype) —
        the host-tier content digest the integrity story compares across
        a crash-restart or against a snapshot."""
        with self._lock:
            t, a = self.tables[name], self.accums[name]
            h = hashlib.sha256()
            for arr in (t, a):
                h.update(str(arr.dtype).encode())
                h.update(str(arr.shape).encode())
                h.update(np.ascontiguousarray(arr).tobytes())
            return h.hexdigest()

    # -- server-side ops ---------------------------------------------------
    def _apply(self, op: bytes, payload: bytes, journal: bool = True) -> bytes:
        """One mutating op, under ps.tables: journal (write-ahead), then
        apply, then bump the op count / dedup map.  `journal=False` is
        the recovery replay (the record is already durable)."""
        (nl,) = struct.unpack_from("<I", payload, 0)
        name = payload[4:4 + nl].decode()
        off = 4 + nl
        cid = seq = None
        if op == b"S":
            cid_raw, seq = struct.unpack_from("<QQ", payload, off)
            cid = f"{cid_raw:016x}"
            off += 16
            if seq <= self.applied.get(cid, -1):
                # the apply happened; the REPLY died with the old socket.
                # Exactly-once is this branch.
                _MON.counter("ps.push_dedup").inc()
                return b""
        if journal:
            self._journal(op, payload)
        if op == b"C":
            arr, _ = _unpack_arr(payload, off)
            self.tables[name] = np.array(arr)
            self.accums[name] = np.zeros_like(self.tables[name])
        else:  # b"G" / b"S": sparse row-gradient push
            ids, off2 = _unpack_arr(payload, off)
            grads, _ = _unpack_arr(payload, off2)
            t = self.tables[name]
            # MergeAdd first: duplicate rows sum BEFORE the accumulator
            # update, or adagrad drifts
            uniq, merged = _merge_rows(ids, grads)
            if self.optimizer == "adagrad":
                acc = self.accums[name]
                acc[uniq] += merged * merged
                t[uniq] += -self.lr * merged / (np.sqrt(acc[uniq]) + 1e-6)
            else:  # sgd
                t[uniq] += -self.lr * merged
        if cid is not None:
            self.applied[cid] = int(seq)
        self.op_count += 1
        if (journal and self.snapshot_dir and self._snap_every
                and self.op_count % self._snap_every == 0):
            try:
                self._snapshot_locked()
            except (OSError, StorageError) as e:
                _MON.counter("ps.snapshot_errors").inc()
                _MON.record_step({"kind": "sparse_event",
                                  "action": "ps_snapshot_failed",
                                  "detail": f"{type(e).__name__}: {e}"})
        return b""

    def _dispatch(self, op: bytes, payload: bytes) -> bytes:
        (nl,) = struct.unpack_from("<I", payload, 0)
        name = payload[4:4 + nl].decode()
        off = 4 + nl
        if op in (b"C", b"G", b"S"):
            with self._lock:  # lock-ok: the op-cadence snapshot inside _apply must commit AT the op_count boundary it names — releasing between apply and snapshot would let another mutation slip into the named cut
                return self._apply(op, payload)
        if op == b"P":
            ids, _ = _unpack_arr(payload, off)
            with self._lock:
                rows = self.tables[name][ids.astype(np.int64)]
            return _pack_arr(rows)
        if op == b"F":
            with self._lock:
                return _pack_arr(self.tables[name])
        if op == b"D":
            return self.table_digest(name).encode()
        raise ValueError(f"parameter server: unknown op {op!r}")

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()
        self.snapshot()


class KVClient:
    """Pserver RPC client with fault tolerance: socket deadlines
    (`FLAGS_ps_timeout_s`), transparent reconnect + seeded-backoff retry
    of transient failures (`FLAGS_ps_retries`), classified
    `ParamServerError`s, and exactly-once pushes — every push carries
    this client's id and a monotonically increasing sequence number the
    server dedups, so a retry whose original APPLY landed (only the
    reply died) is a no-op server-side."""

    def __init__(self, endpoint: str, timeout_s: Optional[float] = None,
                 retries: Optional[int] = None,
                 backoff_base_s: float = 0.05, seed: int = 0):
        host, port = endpoint.rsplit(":", 1)
        self.endpoint = endpoint
        self._addr = (host, int(port))
        self._timeout = _timeout_s() if timeout_s is None else (
            timeout_s if timeout_s > 0 else None)
        self._retries = max(1, int(_flag("FLAGS_ps_retries") or 1)
                            if retries is None else int(retries))
        self._backoff = float(backoff_base_s)
        self._rng = np.random.RandomState(seed)
        # exactly-once identity: survives reconnects (same client object
        # = same dedup stream); a NEW client is a new stream by design
        self.client_id = int.from_bytes(os.urandom(8), "little")
        self._seq = 0
        self._lock = locks.named_lock("ps.client", rank=36)
        self._sock: Optional[socket.socket] = None
        with self._lock:  # lock-ok: connect is part of the serialized framed exchange (a second thread must not write frames to a half-connected socket); the FLAGS_ps_timeout_s deadline bounds the hold
            self._connect_locked()

    # -- wiring ------------------------------------------------------------
    def _connect_locked(self):
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)

    def _close_locked(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _ps_error(self, exc: BaseException, op: str,
                  attempts: int) -> ParamServerError:
        attach_context(exc, phase="pserver")
        e = classify(exc)
        if not isinstance(e, ParamServerError):
            e = ParamServerError(f"{type(exc).__name__}: {exc}")
            e.__cause__ = exc
        e.op = op
        e.endpoint = self.endpoint
        if attempts > 1 and e.transient:
            e.args = (f"{e.args[0]} (after {attempts} attempts — is the "
                      f"pserver's supervisor out of restart budget?)",)
        return e

    def _call(self, op: bytes, name: str, *arrays,
              seq_prefix: bytes = b"") -> bytes:
        opname = {b"P": "pull", b"G": "push", b"S": "push", b"C": "create",
                  b"F": "fetch", b"D": "digest", b"Q": "shutdown"}.get(
                      op, op.decode(errors="replace"))
        payload = struct.pack("<I", len(name)) + name.encode() + seq_prefix
        for a in arrays:
            payload += _pack_arr(np.asarray(a))
        attempt = 0
        while True:
            attempt += 1
            try:
                with self._lock:  # lock-ok: one request/response exchange on one shared socket — serializing the framed protocol IS the lock's purpose (interleaved frames from two threads would corrupt the stream)
                    if self._sock is None:
                        self._connect_locked()
                    _send_msg(self._sock, op, payload)
                    rop, resp = _recv_msg(self._sock)
                break
            except ParamServerError as e:
                # protocol violation (bad magic / oversized frame): the
                # stream is unsynchronized — terminal, connection dies
                with self._lock:
                    self._close_locked()
                e.op, e.endpoint = opname, self.endpoint
                raise
            except (OSError, TimeoutError) as e:
                with self._lock:
                    self._close_locked()
                pe = self._ps_error(e, opname, attempt)
                if not pe.transient or attempt >= self._retries:
                    raise pe from e
                _MON.counter("ps.retries").inc()
                # seeded exponential backoff with jitter, the
                # RetryPolicy discipline: the supervisor needs a beat or
                # two to notice the corpse and respawn
                time.sleep(self._backoff * (2 ** (attempt - 1))
                           * (0.5 + self._rng.rand()))
        if rop == b"e":
            raise ParamServerError(
                f"parameter server error: {resp.decode()}", op=opname,
                endpoint=self.endpoint, transient=False)
        return resp

    # -- ops ---------------------------------------------------------------
    def create(self, name: str, array: np.ndarray):
        self._call(b"C", name, array)

    def pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        resp = self._call(b"P", name, np.asarray(ids, np.int64))
        return _unpack_arr(resp)[0]

    def push(self, name: str, ids: np.ndarray, grads: np.ndarray):
        """Sequenced push: the sequence number is allocated ONCE per
        logical push, before any wire attempt, so every retry of this
        push carries the same one and the server applies it exactly
        once no matter how many times the reply is lost."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        self._call(b"S", name, np.asarray(ids, np.int64), grads,
                   seq_prefix=struct.pack("<QQ", self.client_id, seq))

    def fetch_table(self, name: str) -> np.ndarray:
        return _unpack_arr(self._call(b"F", name))[0]

    def table_digest(self, name: str) -> str:
        """Server-side content digest of table + accumulator — the
        cross-restart / cross-snapshot integrity comparison point."""
        return self._call(b"D", name).decode()

    def close(self):
        with self._lock:
            self._close_locked()


# ---- supervised pserver process (ISSUE 19) ----------------------------------

def _free_port(host: str) -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


class PServerSupervisor:
    """Crash-restart supervision for a pserver PROCESS, the PR-18
    replica-supervision pattern applied to the host tier: the server runs
    as a child process (so a SIGKILL is survivable), writes `ReplicaBeat`
    beats, and this supervisor's watch thread uses `FleetHealth` to
    classify it — a dead OR wedged (beating stopped: SIGSTOP, hard hang)
    child is killed and respawned under `max_restarts`, recovering its
    tables from the journal.  The endpoint is FIXED across incarnations,
    so `KVClient`'s reconnect-retry loop rides a restart out without any
    coordination.  Past the budget the supervisor gives up loudly
    (`pserver_give_up` fleet event) and clients fail into the embedding
    tier's bounded degraded mode."""

    def __init__(self, snapshot_dir: str, host: str = "127.0.0.1",
                 port: int = 0, optimizer: str = "sgd", lr: float = 0.1,
                 max_restarts: int = 3, poll_interval_s: float = 0.1,
                 beat_interval_s: float = 0.2, miss_factor: float = 6.0,
                 startup_grace_s: float = 60.0,
                 snapshot_every_ops: Optional[int] = None):
        from .dist_resilience import FleetHealth

        self.snapshot_dir = snapshot_dir
        os.makedirs(snapshot_dir, exist_ok=True)
        self.host = host
        self.port = port or _free_port(host)
        self.endpoint = f"{host}:{self.port}"
        self.optimizer, self.lr = optimizer, lr
        self.max_restarts = int(max_restarts)
        self.restarts = 0
        self.failed = False
        self._poll = float(poll_interval_s)
        self._snap_every = snapshot_every_ops
        self.hb_dir = os.path.join(snapshot_dir, "hb")
        os.makedirs(self.hb_dir, exist_ok=True)
        self._health = FleetHealth(self.hb_dir, world=1,
                                   interval_s=beat_interval_s,
                                   miss_factor=miss_factor,
                                   startup_grace_s=startup_grace_s)
        self._beat_interval = beat_interval_s
        self._proc: Optional[subprocess.Popen] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = locks.named_lock("ps.supervisor", rank=28)

    # -- child lifecycle ---------------------------------------------------
    def _spawn_locked(self):
        argv = [sys.executable, "-m", "paddle_tpu.param_server",
                "--host", self.host, "--port", str(self.port),
                "--optimizer", self.optimizer, "--lr", str(self.lr),
                "--snapshot-dir", self.snapshot_dir,
                "--hb-dir", self.hb_dir,
                "--beat-interval-s", str(self._beat_interval)]
        if self._snap_every is not None:
            argv += ["--snapshot-every-ops", str(self._snap_every)]
        env = dict(os.environ)
        # the child is a host service: never let it grab a TPU, and keep
        # any fault spec aimed at the TRAINING process out of it
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("FLAGS_fault_spec", None)
        self._health.note_restart(0)
        self._proc = subprocess.Popen(argv, env=env)
        _MON.gauge("ps.supervisor_restarts").set(self.restarts)

    def start(self) -> "PServerSupervisor":
        with self._lock:  # lock-ok: child lifecycle transitions (spawn/kill/respawn) must serialize — that is this lock's whole purpose; nothing hot contends it
            if self._proc is None:
                self._spawn_locked()
                record_fleet_event("pserver_started", endpoint=self.endpoint,
                                   pid=self._proc.pid)
        if self._thread is None:
            self._thread = threading.Thread(target=self._watch,
                                            name="pt-ps-supervisor",
                                            daemon=True)
            self._thread.start()
        return self

    def wait_ready(self, timeout_s: float = 60.0):
        """Block until the child's first beat lands (it is accepting
        connections before beat 0 — the server binds before beating)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._health.poll()[0]["status"] in ("alive", "draining"):
                return self
            if self.failed:
                break
            time.sleep(self._poll)
        raise ParamServerError(
            f"pserver at {self.endpoint} never became ready within "
            f"{timeout_s}s", endpoint=self.endpoint, transient=False)

    def _watch(self):
        while not self._stop.wait(self._poll):
            with self._lock:  # lock-ok: the death-verdict + respawn sequence must be atomic against kill()/stop() (chaos hooks) or two incarnations could race for the fixed endpoint; the proc.wait is deadline-bounded
                proc = self._proc
                if proc is None or self.failed:
                    continue
                dead = proc.poll() is not None
                stalled = (not dead
                           and self._health.poll()[0]["status"] == "dead")
                if not dead and not stalled:
                    continue
                reason = "exit" if dead else "stalled"
                if stalled:
                    # a wedged child (SIGSTOP, hard hang) is as gone as a
                    # dead one: make the verdict physical, then respawn
                    _MON.counter("ps.stall_kills").inc()
                    try:
                        proc.kill()
                        proc.wait(timeout=10)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                record_fleet_event("pserver_dead", endpoint=self.endpoint,
                                   reason=reason, pid=proc.pid,
                                   returncode=proc.returncode)
                if self.restarts >= self.max_restarts:
                    self.failed = True
                    record_fleet_event("pserver_give_up",
                                       endpoint=self.endpoint,
                                       restarts=self.restarts)
                    continue
                self.restarts += 1
                self._spawn_locked()
                record_fleet_event("pserver_restarted",
                                   endpoint=self.endpoint,
                                   restarts=self.restarts,
                                   pid=self._proc.pid)

    # -- chaos hooks (paddle_tpu/faults.py kill_pserver / stall_pserver) ---
    def kill(self, sig: int = signal.SIGKILL):
        """SIGKILL the child (the kill_pserver chaos arm): the watch
        thread notices the corpse within one poll and respawns it."""
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                try:
                    os.kill(self._proc.pid, sig)
                except OSError:
                    pass

    def stall(self, seconds: float):
        """SIGSTOP the child for `seconds` (the stall_pserver chaos arm):
        its beats stop, FleetHealth calls it dead past the deadline, and
        the watch thread kill+respawns — a wedged pserver is not a
        special case, it is a dead one that still holds a port."""
        with self._lock:
            proc = self._proc
        if proc is None or proc.poll() is not None:
            return
        try:
            os.kill(proc.pid, signal.SIGSTOP)
        except OSError:
            return

        def _resume():
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except OSError:
                pass

        t = threading.Timer(seconds, _resume)
        t.daemon = True
        t.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            proc, self._proc = self._proc, None
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)


def _serve_main(argv=None) -> int:
    """`python -m paddle_tpu.param_server`: the supervised child.  Runs a
    ParameterServer (recovering from --snapshot-dir) plus a ReplicaBeat
    the supervisor's FleetHealth watches; SIGTERM snapshots and exits."""
    import argparse

    ap = argparse.ArgumentParser(prog="paddle_tpu.param_server")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--optimizer", default="sgd")
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--snapshot-dir", default=None)
    ap.add_argument("--snapshot-every-ops", type=int, default=None)
    ap.add_argument("--hb-dir", default=None)
    ap.add_argument("--beat-interval-s", type=float, default=0.2)
    args = ap.parse_args(argv)
    srv = ParameterServer(args.host, args.port, args.optimizer, args.lr,
                          snapshot_dir=args.snapshot_dir,
                          snapshot_every_ops=args.snapshot_every_ops)
    beat = None
    if args.hb_dir:
        from .dist_resilience import ReplicaBeat

        beat = ReplicaBeat(
            args.hb_dir, rank=0, world=1, interval_s=args.beat_interval_s,
            payload_fn=lambda: {"ops": srv.op_count,
                                "tables": sorted(srv.tables),
                                "endpoint": srv.endpoint}).start()
    done = threading.Event()

    def _term(_sig, _frm):
        done.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    srv.start()
    done.wait()
    if beat is not None:
        beat.stop(mark_down=True)
    srv.stop()
    return 0


class HostTableEmbedding:
    """Out-of-HBM embedding: the device program sees only the pulled rows
    (a [B*, D] dense feed whose lookup ids are batch-local positions); the
    V×D table lives on the parameter server (reference
    parameter_prefetch.cc flow).

    Per step: (unique_ids, local_ids) <- batch ids; rows <- pull;
    run program with rows + local ids; push SelectedRows grad back.

    Degraded mode (ISSUE 19): with `degraded_ok=True`, a TRANSIENT
    pserver failure (its supervisor is mid-restart, or out of budget)
    does not wedge the step — `prepare_batch` serves ZERO rows for the
    cold tail and `push_grad` drops the slab (counted), while the
    `sparse.host_lag_steps` gauge tracks how many consecutive steps ran
    degraded.  Past `FLAGS_max_host_lag_steps` (when set) the next
    failure re-raises TERMINAL: online learning must not silently
    diverge from its cold tail forever."""

    def __init__(self, client: KVClient, name: str, dim: int,
                 degraded_ok: bool = False):
        self.client = client
        self.name = name
        self.dim = dim
        self.degraded_ok = bool(degraded_ok)
        self.host_lag_steps = 0

    def _degrade(self, e: ParamServerError, action: str):
        if not (self.degraded_ok and e.transient):
            raise e
        self.host_lag_steps += 1
        _MON.gauge("sparse.host_lag_steps").set(self.host_lag_steps)
        _MON.counter("sparse.degraded_steps").inc()
        _MON.record_step({"kind": "sparse_event",
                          "action": "host_tier_degraded", "table": self.name,
                          "during": action, "lag_steps": self.host_lag_steps,
                          "detail": str(e)})
        bound = int(_flag("FLAGS_max_host_lag_steps") or 0)
        if bound and self.host_lag_steps > bound:
            raise ParamServerError(
                f"host table tier down for {self.host_lag_steps} "
                f"consecutive degraded steps, past "
                f"FLAGS_max_host_lag_steps={bound} — the cold tail of "
                f"{self.name!r} has diverged too far to keep training",
                op=action, endpoint=self.client.endpoint,
                transient=False) from e

    def _recovered(self):
        if self.host_lag_steps:
            _MON.record_step({"kind": "sparse_event",
                              "action": "host_tier_recovered",
                              "table": self.name,
                              "lag_steps": self.host_lag_steps})
        self.host_lag_steps = 0
        _MON.gauge("sparse.host_lag_steps").set(0)

    def prepare_batch(self, ids: np.ndarray):
        uniq, local = np.unique(ids.reshape(-1), return_inverse=True)
        try:
            rows = self.client.pull(self.name, uniq)
            self._recovered()
        except ParamServerError as e:
            self._degrade(e, "pull")
            rows = np.zeros((uniq.size, self.dim), np.float32)
        return uniq, local.reshape(ids.shape).astype(np.int64), rows

    def push_grad(self, uniq: np.ndarray, grad_rows: np.ndarray):
        try:
            self.client.push(self.name, uniq, np.asarray(grad_rows))
        except ParamServerError as e:
            # a degraded step trains hot-shard-only: this slab is
            # DROPPED, never queued — queueing would reorder against the
            # sequenced stream and break the exactly-once story
            self._degrade(e, "push")
            _MON.counter("sparse.dropped_pushes").inc()


class AsyncCommunicator:
    """Asynchronous push/pull for host tables (reference
    operators/distributed/communicator.cc — SendThread:104 batches+merges
    queued grads, RecvThread:200 refreshes params periodically; async-PS
    semantics: no barriers, bounded staleness).

    push_async() enqueues and returns immediately; a background thread
    merges queued slabs per table (MergeAdd) and pushes.  pull() reads
    through to the server (rows may be stale by whatever is still queued —
    that staleness IS the async contract)."""

    def __init__(self, client: KVClient, send_interval_s: float = 0.01):
        self._client = client
        self._interval = send_interval_s
        self._queues: Dict[str, list] = {}
        self._lock = locks.named_lock("ps.queue", rank=32)
        # serializes in-flight drains (rank 30: held ACROSS ps.queue and
        # the ps.client push — that span is the flush() barrier contract)
        self._drain_lock = locks.named_lock("ps.drain", rank=30)
        self._stop = threading.Event()
        self._woke = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()
        return self

    def push_async(self, name: str, ids: np.ndarray, grads: np.ndarray):
        if self._error is not None:
            raise RuntimeError("AsyncCommunicator sender died") from self._error
        with self._lock:
            self._queues.setdefault(name, []).append(
                (np.asarray(ids, np.int64).reshape(-1), np.asarray(grads)))
        self._woke.set()

    def pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        return self._client.pull(name, ids)

    def _drain_one(self):
        # _drain_lock makes drains mutually exclusive, so flush() returns
        # only after any in-flight send completes (the barrier contract)
        with self._drain_lock:  # lock-ok: the flush() barrier contract REQUIRES holding this across the merge+push — push_async never takes it, so producers stay unblocked
            with self._lock:
                items = {n: q for n, q in self._queues.items() if q}
                self._queues = {}
            for name, slabs in items.items():
                ids = np.concatenate([i for i, _ in slabs])
                grads = np.concatenate([g for _, g in slabs])
                uniq, merged = _merge_rows(ids, grads)
                self._client.push(name, uniq, merged)

    def _send_loop(self):
        while not self._stop.is_set():
            self._woke.wait(timeout=self._interval)
            self._woke.clear()
            try:
                self._drain_one()
            except BaseException as e:  # surface on next push/flush
                self._error = e
                return

    def flush(self):
        """Synchronize: drain everything queued AND wait out any in-flight
        send (barrier for eval/save)."""
        if self._error is not None:
            raise RuntimeError("AsyncCommunicator sender died") from self._error
        self._drain_one()

    def stop(self):
        self._stop.set()
        self._woke.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._drain_one()
        if self._error is not None:
            raise RuntimeError("AsyncCommunicator sender died") from self._error


if __name__ == "__main__":
    sys.exit(_serve_main())
