// RecordIO: chunked, CRC-checked record file format.
//
// Reference: paddle/fluid/recordio/{header.h:39,chunk.h:27,scanner.h:26,
// writer.h:22} — magic-numbered chunk headers, per-chunk CRC32, sequential
// scanner.  This is the TPU build's native (C++) implementation, exposed to
// Python through a plain C ABI (ctypes — no pybind11 in the image).
//
// On-disk layout (little-endian):
//   per chunk: u32 MAGIC | u32 num_records | u64 payload_len | u32 crc32
//              payload = { u32 len | bytes } * num_records
//
// The scanner validates magic + CRC per chunk and streams records; a
// corrupt chunk fails loudly (rio_error) instead of yielding garbage.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x01020304;  // reference header.h magic

// CRC-32 (IEEE 802.3), small table implementation.  The table is built
// eagerly at load time (static initializer) — scanners run from multiple
// Python threads and a lazy non-atomic init would race.
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const CrcTable crc_table;

uint32_t crc32(const uint8_t* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table.t[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

thread_local std::string g_error;

struct Writer {
  FILE* f = nullptr;
  std::vector<uint8_t> buf;
  uint32_t n_records = 0;
  uint32_t max_records = 0;

  bool flush_chunk() {
    if (n_records == 0) return true;
    uint32_t magic = kMagic;
    uint64_t len = buf.size();
    uint32_t crc = crc32(buf.data(), buf.size());
    if (fwrite(&magic, 4, 1, f) != 1 || fwrite(&n_records, 4, 1, f) != 1 ||
        fwrite(&len, 8, 1, f) != 1 || fwrite(&crc, 4, 1, f) != 1 ||
        (len && fwrite(buf.data(), 1, len, f) != len)) {
      g_error = "recordio: short write";
      return false;
    }
    buf.clear();
    n_records = 0;
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  long file_size = 0;
  std::vector<uint8_t> chunk;
  size_t pos = 0;
  uint32_t remaining = 0;

  bool load_chunk() {
    uint32_t magic, n, crc;
    uint64_t len;
    if (fread(&magic, 4, 1, f) != 1) return false;  // clean EOF
    if (magic != kMagic) {
      g_error = "recordio: bad chunk magic";
      return false;
    }
    if (fread(&n, 4, 1, f) != 1 || fread(&len, 8, 1, f) != 1 ||
        fread(&crc, 4, 1, f) != 1) {
      g_error = "recordio: truncated chunk header";
      return false;
    }
    // a corrupt len must fail via rio_error, not via a std::bad_alloc
    // escaping the C ABI (CRC can't validate it — it's read before payload)
    long here = ftell(f);
    if (here < 0 || len > static_cast<uint64_t>(file_size - here)) {
      g_error = "recordio: chunk length exceeds file size (corrupt header)";
      return false;
    }
    chunk.resize(len);
    if (len && fread(chunk.data(), 1, len, f) != len) {
      g_error = "recordio: truncated chunk payload";
      return false;
    }
    if (crc32(chunk.data(), chunk.size()) != crc) {
      g_error = "recordio: chunk CRC mismatch";
      return false;
    }
    pos = 0;
    remaining = n;
    return true;
  }
};

}  // namespace

extern "C" {

const char* rio_error() { return g_error.c_str(); }

void* rio_writer_open(const char* path, uint32_t max_chunk_records) {
  g_error.clear();
  FILE* f = fopen(path, "wb");
  if (!f) {
    g_error = std::string("recordio: cannot open for write: ") + path;
    return nullptr;
  }
  Writer* w = new Writer();
  w->f = f;
  w->max_records = max_chunk_records ? max_chunk_records : 1024;
  return w;
}

int rio_write(void* handle, const uint8_t* data, uint32_t len) {
  Writer* w = static_cast<Writer*>(handle);
  uint32_t l = len;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&l);
  w->buf.insert(w->buf.end(), p, p + 4);
  w->buf.insert(w->buf.end(), data, data + len);
  w->n_records++;
  if (w->n_records >= w->max_records) return w->flush_chunk() ? 0 : -1;
  return 0;
}

int rio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  bool ok = w->flush_chunk();
  fclose(w->f);
  delete w;
  return ok ? 0 : -1;
}

void* rio_scanner_open(const char* path) {
  g_error.clear();
  FILE* f = fopen(path, "rb");
  if (!f) {
    g_error = std::string("recordio: cannot open for read: ") + path;
    return nullptr;
  }
  Scanner* s = new Scanner();
  s->f = f;
  fseek(f, 0, SEEK_END);
  s->file_size = ftell(f);
  fseek(f, 0, SEEK_SET);
  return s;
}

// Returns pointer to the next record (valid until the next call) and sets
// *len; returns nullptr at EOF (rio_error() empty) or on error (non-empty).
const uint8_t* rio_next(void* handle, uint32_t* len) {
  Scanner* s = static_cast<Scanner*>(handle);
  g_error.clear();
  if (s->remaining == 0) {
    if (!s->load_chunk()) return nullptr;  // EOF or error (g_error set)
  }
  if (s->pos + 4 > s->chunk.size()) {
    g_error = "recordio: record header past chunk end";
    return nullptr;
  }
  uint32_t l;
  memcpy(&l, s->chunk.data() + s->pos, 4);
  s->pos += 4;
  if (s->pos + l > s->chunk.size()) {
    g_error = "recordio: record payload past chunk end";
    return nullptr;
  }
  const uint8_t* out = s->chunk.data() + s->pos;
  s->pos += l;
  s->remaining--;
  *len = l;
  return out;
}

void rio_scanner_close(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
