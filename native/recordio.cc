// RecordIO: chunked, CRC-checked record file format.
//
// Reference: paddle/fluid/recordio/{header.h:39,chunk.h:27,scanner.h:26,
// writer.h:22} — magic-numbered chunk headers, per-chunk CRC32, sequential
// scanner.  This is the TPU build's native (C++) implementation, exposed to
// Python through a plain C ABI (ctypes — no pybind11 in the image).
//
// On-disk layout (little-endian):
//   per chunk: u32 MAGIC | u32 num_records | u64 payload_len | u32 crc32
//              payload = { u32 len | bytes } * num_records
//
// The scanner validates magic + CRC per chunk and streams records; a
// corrupt chunk fails loudly (rio_error) instead of yielding garbage.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x01020304;  // reference header.h magic

// CRC-32 (IEEE 802.3), small table implementation.  The table is built
// eagerly at load time (static initializer) — scanners run from multiple
// Python threads and a lazy non-atomic init would race.
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const CrcTable crc_table;

uint32_t crc32(const uint8_t* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table.t[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

thread_local std::string g_error;

struct Writer {
  FILE* f = nullptr;
  std::vector<uint8_t> buf;
  uint32_t n_records = 0;
  uint32_t max_records = 0;

  bool flush_chunk() {
    if (n_records == 0) return true;
    uint32_t magic = kMagic;
    uint64_t len = buf.size();
    uint32_t crc = crc32(buf.data(), buf.size());
    if (fwrite(&magic, 4, 1, f) != 1 || fwrite(&n_records, 4, 1, f) != 1 ||
        fwrite(&len, 8, 1, f) != 1 || fwrite(&crc, 4, 1, f) != 1 ||
        (len && fwrite(buf.data(), 1, len, f) != len)) {
      g_error = "recordio: short write";
      return false;
    }
    buf.clear();
    n_records = 0;
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  long file_size = 0;
  std::vector<uint8_t> chunk;
  size_t pos = 0;
  uint32_t remaining = 0;
  // --- stream-state + corruption-tolerance bookkeeping -------------------
  bool tolerant = false;        // skip corrupt chunks instead of erroring
  long long corrupt_chunks = 0; // chunks dropped (CRC fail / truncation)
  long long chunk_index = -1;   // ordinal of the currently loaded chunk
  uint32_t chunk_nrecs = 0;     // record count of the loaded chunk

  // Loads the next chunk.  Returns false at clean EOF (g_error empty) or
  // on error (g_error set).  In tolerant mode a CRC-failed chunk is
  // skipped (counted, next chunk tried); a truncated / frame-broken tail
  // ends the file cleanly after counting one corrupt chunk — resyncing a
  // lost frame would require scanning for magic, and a truncated tail has
  // no more data either way.
  bool load_chunk() {
    for (;;) {
      uint32_t magic, n, crc;
      uint64_t len;
      if (fread(&magic, 4, 1, f) != 1) return false;  // clean EOF
      chunk_index++;  // a chunk frame begins here
      if (magic != kMagic) {
        if (tolerant) { corrupt_chunks++; return false; }
        g_error = "recordio: bad chunk magic";
        return false;
      }
      if (fread(&n, 4, 1, f) != 1 || fread(&len, 8, 1, f) != 1 ||
          fread(&crc, 4, 1, f) != 1) {
        if (tolerant) { corrupt_chunks++; return false; }
        g_error = "recordio: truncated chunk header";
        return false;
      }
      // a corrupt len must fail via rio_error, not via a std::bad_alloc
      // escaping the C ABI (CRC can't validate it — it's read before payload)
      long here = ftell(f);
      if (here < 0 || len > static_cast<uint64_t>(file_size - here)) {
        if (tolerant) { corrupt_chunks++; return false; }
        g_error = "recordio: chunk length exceeds file size (corrupt header)";
        return false;
      }
      chunk.resize(len);
      if (len && fread(chunk.data(), 1, len, f) != len) {
        if (tolerant) { corrupt_chunks++; return false; }
        g_error = "recordio: truncated chunk payload";
        return false;
      }
      if (crc32(chunk.data(), chunk.size()) != crc) {
        if (tolerant) { corrupt_chunks++; continue; }  // skip, try the next
        g_error = "recordio: chunk CRC mismatch";
        return false;
      }
      pos = 0;
      remaining = n;
      chunk_nrecs = n;
      return true;
    }
  }
};

}  // namespace

extern "C" {

const char* rio_error() { return g_error.c_str(); }

void* rio_writer_open(const char* path, uint32_t max_chunk_records) {
  g_error.clear();
  FILE* f = fopen(path, "wb");
  if (!f) {
    g_error = std::string("recordio: cannot open for write: ") + path;
    return nullptr;
  }
  Writer* w = new Writer();
  w->f = f;
  w->max_records = max_chunk_records ? max_chunk_records : 1024;
  return w;
}

int rio_write(void* handle, const uint8_t* data, uint32_t len) {
  Writer* w = static_cast<Writer*>(handle);
  uint32_t l = len;
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&l);
  w->buf.insert(w->buf.end(), p, p + 4);
  w->buf.insert(w->buf.end(), data, data + len);
  w->n_records++;
  if (w->n_records >= w->max_records) return w->flush_chunk() ? 0 : -1;
  return 0;
}

int rio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  bool ok = w->flush_chunk();
  fclose(w->f);
  delete w;
  return ok ? 0 : -1;
}

void* rio_scanner_open(const char* path) {
  g_error.clear();
  FILE* f = fopen(path, "rb");
  if (!f) {
    g_error = std::string("recordio: cannot open for read: ") + path;
    return nullptr;
  }
  Scanner* s = new Scanner();
  s->f = f;
  fseek(f, 0, SEEK_END);
  s->file_size = ftell(f);
  fseek(f, 0, SEEK_SET);
  return s;
}

// Returns pointer to the next record (valid until the next call) and sets
// *len; returns nullptr at EOF (rio_error() empty) or on error (non-empty).
const uint8_t* rio_next(void* handle, uint32_t* len) {
  Scanner* s = static_cast<Scanner*>(handle);
  g_error.clear();
  if (s->remaining == 0) {
    if (!s->load_chunk()) return nullptr;  // EOF or error (g_error set)
  }
  if (s->pos + 4 > s->chunk.size()) {
    g_error = "recordio: record header past chunk end";
    return nullptr;
  }
  uint32_t l;
  memcpy(&l, s->chunk.data() + s->pos, 4);
  s->pos += 4;
  if (s->pos + l > s->chunk.size()) {
    g_error = "recordio: record payload past chunk end";
    return nullptr;
  }
  const uint8_t* out = s->chunk.data() + s->pos;
  s->pos += l;
  s->remaining--;
  *len = l;
  return out;
}

void rio_scanner_close(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

// --- stream-state + corruption-tolerance entries ---------------------------

void rio_scanner_set_tolerant(void* handle, int tolerant) {
  static_cast<Scanner*>(handle)->tolerant = tolerant != 0;
}

long long rio_scanner_corrupt_chunks(void* handle) {
  return static_cast<Scanner*>(handle)->corrupt_chunks;
}

// Chunk frames seen so far (loaded or skipped) — the `data.chunks_scanned`
// denominator on the Python side.
long long rio_scanner_chunks_seen(void* handle) {
  return static_cast<Scanner*>(handle)->chunk_index + 1;
}

// Position of the NEXT record rio_next would return, as (chunk ordinal,
// record index within that chunk).  A freshly opened scanner reports (0, 0);
// an exhausted chunk reports the next frame at record 0.
int rio_scanner_tell(void* handle, long long* chunk_idx, long long* rec_idx) {
  Scanner* s = static_cast<Scanner*>(handle);
  if (s->remaining > 0) {
    *chunk_idx = s->chunk_index;
    *rec_idx = static_cast<long long>(s->chunk_nrecs - s->remaining);
  } else {
    *chunk_idx = s->chunk_index + 1;
    *rec_idx = 0;
  }
  return 0;
}

// O(1)-per-chunk seek to (chunk ordinal, record index): chunk payloads
// between here and the target are skipped with fseek (header reads only —
// no payload IO, no CRC work), then the target chunk alone is loaded and
// validated and `rec_idx` records are stepped over in memory.  This is the
// `rio_scanner_seek` entry the resumable-stream protocol uses: resuming a
// scan costs one chunk load, not a re-read of the dataset.
int rio_scanner_seek(void* handle, long long chunk_idx, long long rec_idx) {
  Scanner* s = static_cast<Scanner*>(handle);
  g_error.clear();
  if (chunk_idx < 0 || rec_idx < 0) {
    g_error = "recordio: negative seek target";
    return -1;
  }
  if (fseek(s->f, 0, SEEK_SET) != 0) {
    g_error = "recordio: seek rewind failed";
    return -1;
  }
  s->chunk_index = -1;
  s->remaining = 0;
  s->chunk_nrecs = 0;
  s->pos = 0;
  for (long long c = 0; c < chunk_idx; c++) {
    uint32_t magic, n, crc;
    uint64_t len;
    if (fread(&magic, 4, 1, s->f) != 1) {
      g_error = "recordio: seek target past EOF";
      return -1;
    }
    if (magic != kMagic) {
      g_error = "recordio: bad chunk magic during seek";
      return -1;
    }
    if (fread(&n, 4, 1, s->f) != 1 || fread(&len, 8, 1, s->f) != 1 ||
        fread(&crc, 4, 1, s->f) != 1) {
      g_error = "recordio: truncated chunk header during seek";
      return -1;
    }
    long here = ftell(s->f);
    if (here < 0 || len > static_cast<uint64_t>(s->file_size - here)) {
      g_error = "recordio: chunk length exceeds file size during seek";
      return -1;
    }
    if (fseek(s->f, static_cast<long>(len), SEEK_CUR) != 0) {
      g_error = "recordio: payload skip failed during seek";
      return -1;
    }
    s->chunk_index++;
  }
  if (rec_idx == 0) return 0;  // next load_chunk() lands on the target
  // the target chunk must load STRICTLY: a tolerant load would silently
  // skip a corrupt target and step rec_idx records into the NEXT chunk —
  // a mispositioned resume training on wrong data
  bool was_tolerant = s->tolerant;
  s->tolerant = false;
  bool loaded = s->load_chunk();
  s->tolerant = was_tolerant;
  if (!loaded) {
    if (g_error.empty())
      g_error = "recordio: seek target chunk missing or corrupt";
    return -1;
  }
  if (static_cast<uint64_t>(rec_idx) > s->remaining) {
    g_error = "recordio: seek record index past chunk end";
    return -1;
  }
  for (long long r = 0; r < rec_idx; r++) {
    if (s->pos + 4 > s->chunk.size()) {
      g_error = "recordio: record header past chunk end during seek";
      return -1;
    }
    uint32_t l;
    memcpy(&l, s->chunk.data() + s->pos, 4);
    s->pos += 4;
    if (s->pos + l > s->chunk.size()) {
      g_error = "recordio: record payload past chunk end during seek";
      return -1;
    }
    s->pos += l;
    s->remaining--;
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Multithreaded slot-batch queue (reference: framework/data_feed.cc
// MultiSlotInMemoryDataFeed — C++ worker threads parse slot files so the
// trainer never waits on the Python GIL).  Files hold _pack_arrays records
// (see paddle_tpu/recordio.py): u32 nslots, then per slot {u32 dtype_len,
// dtype str, u32 ndim, i64 shape[ndim], u64 raw_len, raw}.  The fast path
// requires every sample to repeat the first record's per-slot dtype/shape
// (dense slots — the CTR/train_from_dataset shape); a mismatch fails
// loudly so ragged data falls back to the Python path.

#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>

namespace {

struct SlotLayout {
  std::string dtype;
  std::vector<int64_t> shape;  // per-sample
  uint64_t raw_len = 0;
};

struct ParsedRec {
  // offsets into `bytes` for each slot's raw payload
  std::vector<uint8_t> bytes;
  std::vector<size_t> slot_off;
};

struct SlotQueue {
  std::vector<std::string> files;
  std::vector<SlotLayout> layout;
  size_t batch = 0;
  bool drop_last = true;
  bool tolerant = false;  // skip corrupt chunks instead of killing the run
  std::atomic<long long> corrupt_chunks{0};
  std::atomic<long long> chunks_seen{0};

  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<ParsedRec> q;  // FIFO: preserves file order at n_threads=1
  size_t cap = 8192;
  bool done = false;
  std::string error;
  std::atomic<size_t> next_file{0};
  int active_workers = 0;  // guarded by mu; signals end-of-stream at 0
  std::vector<std::thread> workers;

  ~SlotQueue() {
    {
      std::unique_lock<std::mutex> lk(mu);
      done = true;  // release any blocked producer
      cv_put.notify_all();
      cv_get.notify_all();
    }
    for (auto& t : workers)
      if (t.joinable()) t.join();
  }

  bool parse_record(const uint8_t* p, uint32_t len, ParsedRec* out,
                    std::string* err) {
    size_t off = 0;
    auto need = [&](size_t n) { return off + n <= len; };
    if (!need(4)) { *err = "slotq: truncated record"; return false; }
    uint32_t nslots;
    memcpy(&nslots, p + off, 4); off += 4;
    if (nslots != layout.size()) {
      *err = "slotq: record slot count changed mid-stream";
      return false;
    }
    out->bytes.assign(p, p + len);
    out->slot_off.resize(nslots);
    for (uint32_t s = 0; s < nslots; s++) {
      if (!need(4)) { *err = "slotq: truncated dtype len"; return false; }
      uint32_t dl; memcpy(&dl, p + off, 4); off += 4;
      if (!need(dl)) { *err = "slotq: truncated dtype"; return false; }
      std::string dt(reinterpret_cast<const char*>(p + off), dl); off += dl;
      if (!need(4)) { *err = "slotq: truncated ndim"; return false; }
      uint32_t nd; memcpy(&nd, p + off, 4); off += 4;
      std::vector<int64_t> shape(nd);
      if (!need(8 * nd)) { *err = "slotq: truncated shape"; return false; }
      memcpy(shape.data(), p + off, 8 * nd); off += 8 * nd;
      if (!need(8)) { *err = "slotq: truncated raw len"; return false; }
      uint64_t rl; memcpy(&rl, p + off, 8); off += 8;
      if (!need(rl)) { *err = "slotq: truncated payload"; return false; }
      const SlotLayout& L = layout[s];
      if (dt != L.dtype || shape != L.shape || rl != L.raw_len) {
        *err = "slotq: sample shape/dtype differs from the first record "
               "(ragged data — use the Python dataset path)";
        return false;
      }
      out->slot_off[s] = off;
      off += rl;
    }
    return true;
  }

  void worker() {
    worker_loop();
    std::unique_lock<std::mutex> lk(mu);
    active_workers--;
    cv_get.notify_all();
  }

  void worker_loop() {
    for (;;) {
      size_t idx = next_file.fetch_add(1);
      if (idx >= files.size()) return;
      Scanner sc;
      sc.f = fopen(files[idx].c_str(), "rb");
      if (!sc.f) {
        std::unique_lock<std::mutex> lk(mu);
        error = "slotq: cannot open " + files[idx];
        done = true; cv_get.notify_all();
        return;
      }
      fseek(sc.f, 0, SEEK_END); sc.file_size = ftell(sc.f); fseek(sc.f, 0, SEEK_SET);
      sc.tolerant = tolerant;
      for (;;) {
        g_error.clear();  // tolerant load_chunk EOFs must not read stale state
        if (sc.remaining == 0 && !sc.load_chunk()) {
          bool clean = g_error.empty();
          if (!clean) {
            std::unique_lock<std::mutex> lk(mu);
            error = g_error;
            done = true; cv_get.notify_all();
          }
          break;
        }
        if (sc.pos + 4 > sc.chunk.size()) {
          std::unique_lock<std::mutex> lk(mu);
          error = "slotq: record header past chunk end";
          done = true; cv_get.notify_all();
          fclose(sc.f);
          return;
        }
        uint32_t rl;
        memcpy(&rl, sc.chunk.data() + sc.pos, 4);
        if (sc.pos + 4 + (uint64_t)rl > sc.chunk.size()) {
          std::unique_lock<std::mutex> lk(mu);
          error = "slotq: record length past chunk end";
          done = true; cv_get.notify_all();
          fclose(sc.f);
          return;
        }
        const uint8_t* rec = sc.chunk.data() + sc.pos + 4;
        sc.pos += 4 + rl;
        sc.remaining--;
        ParsedRec pr;
        std::string err;
        if (!parse_record(rec, rl, &pr, &err)) {
          std::unique_lock<std::mutex> lk(mu);
          error = err;
          done = true; cv_get.notify_all();
          fclose(sc.f);
          return;
        }
        std::unique_lock<std::mutex> lk(mu);
        cv_put.wait(lk, [&] { return q.size() < cap || done; });
        if (done) { fclose(sc.f); return; }
        q.push_back(std::move(pr));
        cv_get.notify_one();
      }
      corrupt_chunks += sc.corrupt_chunks;
      chunks_seen += sc.chunk_index + 1;
      fclose(sc.f);
    }
  }
};

bool parse_layout(const uint8_t* p, uint32_t len,
                  std::vector<SlotLayout>* out, std::string* err) {
  size_t off = 0;
  auto need = [&](size_t n) { return off + n <= len; };
  uint32_t nslots;
  if (!need(4)) { *err = "slotq: truncated record header"; return false; }
  memcpy(&nslots, p + off, 4); off += 4;
  if (nslots == 0 || nslots > 1024) {
    *err = "slotq: implausible slot count (not a slot-record file)";
    return false;
  }
  out->resize(nslots);
  for (uint32_t s = 0; s < nslots; s++) {
    SlotLayout& L = (*out)[s];
    uint32_t dl;
    if (!need(4)) { *err = "slotq: truncated dtype len"; return false; }
    memcpy(&dl, p + off, 4); off += 4;
    if (dl == 0 || dl > 16 || !need(dl)) {
      *err = "slotq: implausible dtype (not a slot-record file)";
      return false;
    }
    L.dtype.assign(reinterpret_cast<const char*>(p + off), dl); off += dl;
    uint32_t nd;
    if (!need(4)) { *err = "slotq: truncated ndim"; return false; }
    memcpy(&nd, p + off, 4); off += 4;
    if (nd > 8 || !need(8ull * nd)) {
      *err = "slotq: implausible ndim"; return false;
    }
    L.shape.resize(nd);
    memcpy(L.shape.data(), p + off, 8ull * nd); off += 8ull * nd;
    if (!need(8)) { *err = "slotq: truncated raw len"; return false; }
    memcpy(&L.raw_len, p + off, 8); off += 8;
    if (!need(L.raw_len)) { *err = "slotq: truncated payload"; return false; }
    // raw_len must equal prod(shape) * itemsize or the Python-side numpy
    // buffers (sized from shape/dtype) would be overflowed by the memcpy
    uint64_t elems = 1;
    for (int64_t d : L.shape) {
      if (d < 0) { *err = "slotq: negative dim"; return false; }
      elems *= (uint64_t)d;
    }
    uint64_t item = 0;
    for (char c : L.dtype)
      if (c >= '0' && c <= '9') item = item * 10 + (c - '0');
    if (item == 0 || item > 16 || elems * item != L.raw_len) {
      *err = "slotq: raw_len inconsistent with shape*itemsize";
      return false;
    }
    off += L.raw_len;
  }
  return true;
}

bool peek_layout(const std::string& path, std::vector<SlotLayout>* out,
                 bool tolerant = false) {
  Scanner sc;
  sc.f = fopen(path.c_str(), "rb");
  if (!sc.f) { g_error = "slotq: cannot open " + path; return false; }
  fseek(sc.f, 0, SEEK_END); sc.file_size = ftell(sc.f); fseek(sc.f, 0, SEEK_SET);
  sc.tolerant = tolerant;  // layout may have to come from the 2nd+ chunk
  g_error.clear();
  if (!sc.load_chunk() || sc.remaining == 0) {
    if (g_error.empty()) g_error = "slotq: empty file " + path;
    fclose(sc.f);
    return false;
  }
  if (sc.pos + 4 > sc.chunk.size()) {
    g_error = "slotq: record header past chunk end";
    fclose(sc.f);
    return false;
  }
  uint32_t rl;
  memcpy(&rl, sc.chunk.data() + sc.pos, 4);
  if (sc.pos + 4 + rl > sc.chunk.size()) {
    g_error = "slotq: record length past chunk end";
    fclose(sc.f);
    return false;
  }
  std::string err;
  bool ok = parse_layout(sc.chunk.data() + sc.pos + 4, rl, out, &err);
  if (!ok) g_error = err;
  fclose(sc.f);
  return ok;
}

}  // namespace

extern "C" {

void* slotq_open(const char** paths, int n_files, long long batch_size,
                 int n_threads, int drop_last, int tolerant) {
  g_error.clear();
  auto* sq = new SlotQueue();
  for (int i = 0; i < n_files; i++) sq->files.emplace_back(paths[i]);
  sq->batch = static_cast<size_t>(batch_size);
  sq->drop_last = drop_last != 0;
  sq->tolerant = tolerant != 0;
  if (sq->files.empty()
      || !peek_layout(sq->files[0], &sq->layout, sq->tolerant)) {
    if (g_error.empty()) g_error = "slotq: empty file list";
    delete sq;
    return nullptr;
  }
  int nt = n_threads > 0 ? n_threads : 1;
  if (static_cast<size_t>(nt) > sq->files.size()) nt = (int)sq->files.size();
  sq->active_workers = nt;
  for (int i = 0; i < nt; i++)
    sq->workers.emplace_back([sq] { sq->worker(); });
  return sq;
}

int slotq_nslots(void* h) {
  return (int)static_cast<SlotQueue*>(h)->layout.size();
}

int slotq_slot_info(void* h, int slot, char* dtype_buf, int cap,
                    long long* shape_buf, int* ndim) {
  auto* sq = static_cast<SlotQueue*>(h);
  if (slot < 0 || slot >= (int)sq->layout.size()) return -1;
  const SlotLayout& L = sq->layout[slot];
  if ((int)L.dtype.size() + 1 > cap || (int)L.shape.size() > 8) return -1;
  memcpy(dtype_buf, L.dtype.c_str(), L.dtype.size() + 1);
  *ndim = (int)L.shape.size();
  for (size_t i = 0; i < L.shape.size(); i++) shape_buf[i] = L.shape[i];
  return 0;
}

// Fill caller-allocated per-slot buffers (each batch*raw_len bytes); returns
// rows filled (may be < batch only at end with drop_last=0), 0 at end,
// -1 on error (slotq_error).  Called WITHOUT the GIL (ctypes releases it):
// the memcpy assembly overlaps Python-side device dispatch.
long long slotq_next_batch(void* h, void** bufs) {
  auto* sq = static_cast<SlotQueue*>(h);
  std::vector<ParsedRec> local;
  local.reserve(sq->batch);
  {
    std::unique_lock<std::mutex> lk(sq->mu);
    while (local.size() < sq->batch) {
      if (!sq->error.empty()) { g_error = sq->error; return -1; }
      if (!sq->q.empty()) {
        local.push_back(std::move(sq->q.front()));
        sq->q.pop_front();
        sq->cv_put.notify_one();
        continue;
      }
      if (sq->active_workers == 0) break;  // drained and finished
      sq->cv_get.wait(lk);
    }
  }
  size_t rows = local.size();
  if (rows == 0) return 0;
  if (rows < sq->batch && sq->drop_last) return 0;
  for (size_t s = 0; s < sq->layout.size(); s++) {
    uint8_t* dst = static_cast<uint8_t*>(bufs[s]);
    const uint64_t rl = sq->layout[s].raw_len;
    for (size_t r = 0; r < rows; r++)
      memcpy(dst + r * rl, local[r].bytes.data() + local[r].slot_off[s], rl);
  }
  return (long long)rows;
}

long long slotq_corrupt_chunks(void* h) {
  return static_cast<SlotQueue*>(h)->corrupt_chunks.load();
}

long long slotq_chunks_seen(void* h) {
  return static_cast<SlotQueue*>(h)->chunks_seen.load();
}

void slotq_close(void* h) { delete static_cast<SlotQueue*>(h); }

}  // extern "C"
