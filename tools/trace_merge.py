#!/usr/bin/env python
"""Merge per-rank gang telemetry into one timeline + straggler attribution.

The gang telemetry plane (paddle_tpu.launch run_gang exports
PADDLE_TELEMETRY_DIR; fleet.init arms each worker via
monitor.init_worker_telemetry) leaves one directory per incarnation:

    <telemetry_root>/i<k>/metrics.p<rank>.jsonl   rank-tagged step records
    <telemetry_root>/i<k>/trace.p<rank>.json      per-rank Chrome trace
    <telemetry_root>/i<k>/BLACKBOX.p<rank>.json   flight-recorder dumps

This tool turns N disjoint per-rank files into answers:

    python tools/trace_merge.py DIR --out merged.json
        Merge every rank's Chrome trace into ONE timeline with one pid
        lane per rank (perfetto/chrome://tracing renders one row per
        worker, collectives and steps aligned).

    python tools/trace_merge.py DIR [--report skew.json]
        Correlate collective-bearing steps across ranks by
        (collective_signature digest, step number) from the per-rank
        step-record streams, and print per-collective SKEW ATTRIBUTION:
        which rank arrived last at each correlated step's dispatch, by
        how much, and which rank is the gang's straggler overall.

    python tools/trace_merge.py DIR --check --max-step-skew-frac 0.5
        CI gate: fail when the mean per-step cross-rank skew exceeds the
        given fraction of the MEDIAN step time (median, not mean: a
        periodic slow step — checkpoint flush, re-compile — must not
        inflate the denominator and hide real skew).

Arrival time is the record's `ts_dispatch` (wall clock when the step
entered dispatch, BEFORE the blocking collective) — the rank that arrives
last is the rank everyone else waited for.  Single-host gangs share one
clock; across hosts the numbers inherit NTP skew, so treat sub-millisecond
attribution there with suspicion.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

_METRICS_RE = re.compile(r"metrics\.p(\d+)\.jsonl$")
_TRACE_RE = re.compile(r"trace\.p(\d+)\.json$")
_INC_RE = re.compile(r"^i(\d+)$")


def _incarnation_of(path: str) -> int:
    """The i<k> incarnation a telemetry file belongs to (0 for files that
    sit directly in a single-incarnation dir)."""
    m = _INC_RE.match(os.path.basename(os.path.dirname(path)))
    return int(m.group(1)) if m else 0


def find_rank_files(root: str) -> Dict[str, Dict[int, str]]:
    """Walk `root` (a telemetry dir, or a telemetry root holding i<k>
    incarnation dirs) and collect per-rank metrics/trace files.  When the
    same rank appears in several incarnation dirs, the newest (highest
    NUMERIC incarnation — i10 sorts after i9, not between i1 and i2) wins
    for traces; metrics files are all kept per rank, incarnation order,
    so a restarted gang's history stays whole."""
    metrics: Dict[int, List[str]] = {}
    traces: Dict[int, str] = {}
    paths = sorted(glob.glob(os.path.join(root, "**", "*"), recursive=True),
                   key=lambda p: (_incarnation_of(p), p))
    for path in paths:
        base = os.path.basename(path)
        m = _METRICS_RE.match(base)
        if m:
            metrics.setdefault(int(m.group(1)), []).append(path)
            continue
        m = _TRACE_RE.match(base)
        if m:
            traces[int(m.group(1))] = path
    return {"metrics": metrics, "traces": traces}


def load_records(paths) -> List[dict]:
    """All JSONL records from one rank's metrics file(s), in file order;
    unparseable lines are skipped (a SIGKILL can tear the last line).
    Each record is stamped with its source file's incarnation (`_inc`) so
    cross-rank correlation never pairs step N of incarnation 0 with step
    N of incarnation 1 — global step numbering restarts with the gang,
    and conflating them reads the restart gap as skew."""
    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        inc = _incarnation_of(p)
        try:
            with open(p) as f:
                for ln in f:
                    ln = ln.strip()
                    if not ln:
                        continue
                    try:
                        rec = json.loads(ln)
                    except json.JSONDecodeError:
                        continue
                    if isinstance(rec, dict):
                        rec.setdefault("_inc", inc)
                        out.append(rec)
        except OSError:
            continue
    return out


def merge_traces(traces: Dict[int, str], out_path: str) -> int:
    """Merge per-rank Chrome traces into one timeline, pid = rank; returns
    the number of span events written."""
    merged = []
    n = 0
    for rank in sorted(traces):
        try:
            with open(traces[rank]) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank{rank}"}})
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") == "M":
                continue  # one fresh metadata row per rank, above
            ev = dict(ev)
            ev["pid"] = rank
            merged.append(ev)
            n += 1
    with open(out_path, "w") as f:
        json.dump({"traceEvents": merged}, f)
    return n


def _arrival(rec: dict) -> Optional[float]:
    """A step record's dispatch-entry wall time (ts_dispatch; records
    predating the field fall back to the record timestamp)."""
    ts = rec.get("ts_dispatch", rec.get("ts"))
    try:
        return float(ts)
    except (TypeError, ValueError):
        return None


def correlate(per_rank: Dict[int, List[dict]], steady_after: int = 2) -> dict:
    """Cross-rank skew attribution over per-rank step-record streams.

    Steps are correlated by (incarnation, csig, step number): csig is the
    digest of the program's static collective signature (identical on
    every rank by construction — the build-time lint guarantees the
    order), so a key names ONE gang-wide collective-bearing step; the
    incarnation component keeps a restarted gang's replayed step numbers
    from pairing across incarnations (the restart gap is downtime, not
    skew).  For each key observed on >= 2 ranks: skew_s = last arrival -
    first arrival, and the last rank is the one the collective waited
    for.

    The first `steady_after` correlated steps of each csig are marked
    warm-in and excluded from the aggregate skew/straggler stats (same
    convention as perf_report --steady-after): ranks pay compile at
    different moments, and that startup skew would otherwise drown the
    steady-state signal the gates care about.  Per-step entries keep the
    warm-in rows, flagged."""
    arrivals: Dict[tuple, Dict[int, float]] = {}
    step_times: Dict[int, List[float]] = {}
    for rank, recs in per_rank.items():
        prev_ts = prev_inc = None
        for r in recs:
            if r.get("kind", "step") != "step":
                continue
            ts = _arrival(r)
            if ts is None:
                continue
            inc = r.get("_inc", 0)
            if inc != prev_inc:
                prev_ts = None  # restart gap is downtime, not a step time
                prev_inc = inc
            if prev_ts is not None and ts > prev_ts:
                step_times.setdefault(rank, []).append(ts - prev_ts)
            prev_ts = ts
            csig = r.get("csig")
            if csig is None:
                continue  # no collectives: nothing to correlate
            arrivals.setdefault(
                (r.get("_inc", 0), csig, r.get("step")), {})[rank] = ts

    def _median(v):
        s = sorted(v)
        return s[len(s) // 2] if s else 0.0

    median_step_s = _median([t for ts in step_times.values() for t in ts])
    entries = []
    seen_per_csig: Dict[tuple, int] = {}
    for (inc, csig, step), by_rank in sorted(
            arrivals.items(), key=lambda kv: min(kv[1].values())):
        if len(by_rank) < 2:
            continue
        first = min(by_rank, key=by_rank.get)
        last = max(by_rank, key=by_rank.get)
        skew_s = by_rank[last] - by_rank[first]
        idx = seen_per_csig.get((inc, csig), 0)
        seen_per_csig[(inc, csig)] = idx + 1
        e = {
            "csig": csig, "step": step, "incarnation": inc,
            "skew_s": round(skew_s, 6),
            "skew_frac": (round(skew_s / median_step_s, 4)
                          if median_step_s else None),
            "first_rank": first, "last_rank": last,
            "arrivals": {str(r): ts for r, ts in sorted(by_rank.items())},
        }
        if idx < steady_after:
            e["warmup"] = True
        entries.append(e)
    # NO fallback to warm-in rows when nothing steady survives: compile
    # skew is exactly what the exclusion exists to keep out of the
    # aggregates, and a gate fed warm-in data would name a healthy rank
    # straggler.  Too-short runs report entries only; the --check gate
    # treats missing aggregates as missing evidence (fail), not as clean.
    steady = [e for e in entries if not e.get("warmup")]
    last_counts: Dict[int, int] = {}
    for e in steady:
        last_counts[e["last_rank"]] = last_counts.get(e["last_rank"], 0) + 1
    report = {
        "kind": "skew_report",
        "ranks": sorted(per_rank),
        "steps_correlated": len(entries),
        "steady_steps": len(steady),
        "median_step_s": round(median_step_s, 6),
        "entries": entries,
        "last_arrival_counts": {str(r): c
                                for r, c in sorted(last_counts.items())},
    }
    if steady:
        skews = [e["skew_s"] for e in steady]
        report["max_skew_s"] = round(max(skews), 6)
        report["mean_skew_s"] = round(sum(skews) / len(skews), 6)
        if median_step_s:
            report["max_skew_frac"] = round(max(skews) / median_step_s, 4)
            report["mean_skew_frac"] = round(
                sum(skews) / len(skews) / median_step_s, 4)
        # the straggler: the rank the gang waited for most often — only
        # attributed when it was last for a clear majority of the
        # correlated steps (50/50 on two ranks is noise, not a straggler)
        # AND the waiting was material (>10% of a step when it was last;
        # on a healthy gang SOMEONE is always technically last, by µs)
        straggler, n_last = max(last_counts.items(), key=lambda kv: kv[1])
        frac_last = n_last / len(steady)
        skew_when_last = sum(e["skew_s"] for e in steady
                             if e["last_rank"] == straggler) / n_last
        # no step-time baseline (a single correlated step) means no way
        # to judge materiality — never attribute from that little data
        if (frac_last > 0.5 and median_step_s
                and skew_when_last > 0.1 * median_step_s):
            report["straggler"] = {
                "rank": straggler, "last_frac": round(frac_last, 4),
                "mean_skew_s_when_last": round(skew_when_last, 6),
            }
    return report


def skew_from_dir(root: str) -> Optional[dict]:
    """Skew report over every rank's metrics stream under `root` (used by
    bench.py to embed skew records in multi-process rounds); None when
    fewer than two ranks left telemetry."""
    files = find_rank_files(root)
    if len(files["metrics"]) < 2:
        return None
    per_rank = {r: load_records(ps) for r, ps in files["metrics"].items()}
    return correlate(per_rank)


def render(report: dict) -> str:
    parts = [f"# gang skew report  ranks={report['ranks']}  "
             f"{report['steps_correlated']} correlated steps  "
             f"({report.get('steady_steps', 0)} steady)  "
             f"median step {report['median_step_s'] * 1e3:.3f} ms"]
    if report.get("entries") and report.get("mean_skew_s") is None:
        parts.append("all correlated steps are warm-in (compile skew): "
                     "no steady-state aggregates — run longer to gate")
    if report.get("mean_skew_s") is not None:
        parts.append(
            f"skew: mean {report['mean_skew_s'] * 1e3:.3f} ms "
            f"(frac {report.get('mean_skew_frac')}), "
            f"max {report['max_skew_s'] * 1e3:.3f} ms "
            f"(frac {report.get('max_skew_frac')})")
        parts.append("last-arrival counts: " + ", ".join(
            f"rank{r}={c}" for r, c in report["last_arrival_counts"].items()))
        st = report.get("straggler")
        if st:
            parts.append(
                f"STRAGGLER: rank {st['rank']} arrived last on "
                f"{st['last_frac'] * 100:.0f}% of correlated steps, "
                f"mean skew {st['mean_skew_s_when_last'] * 1e3:.3f} ms "
                f"when last")
        else:
            parts.append("no dominant straggler (last arrivals balanced)")
        head = report["entries"][:20]
        parts.append("per-step (first 20):")
        for e in head:
            parts.append(
                f"  step {e['step']} csig {e['csig']}: rank "
                f"{e['last_rank']} last by {e['skew_s'] * 1e3:.3f} ms "
                f"(frac {e['skew_frac']})")
    elif not report.get("entries"):
        parts.append("no cross-rank correlated steps (need csig-stamped "
                     "step records from >= 2 ranks)")
    return "\n".join(parts)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("dir", help="telemetry dir (or telemetry root with "
                                "i<k> incarnation dirs)")
    ap.add_argument("--out", default=None, metavar="MERGED_JSON",
                    help="write the merged per-rank-lane Chrome trace here")
    ap.add_argument("--report", default=None, metavar="SKEW_JSON",
                    help="write the skew report JSON here")
    ap.add_argument("--check", action="store_true",
                    help="gate mode: exit 1 when the skew gate fails")
    ap.add_argument("--max-step-skew-frac", type=float, default=0.5,
                    metavar="FRAC",
                    help="--check: ceiling on MEAN per-step skew as a "
                         "fraction of the MEDIAN step time (default 0.5)")
    args = ap.parse_args(argv)

    files = find_rank_files(args.dir)
    if args.out:
        n = merge_traces(files["traces"], args.out)
        print(f"trace_merge: wrote {n} events from "
              f"{len(files['traces'])} rank trace(s) to {args.out}")
    if not files["metrics"]:
        print(f"trace_merge: no metrics.p<rank>.jsonl under {args.dir}")
        if args.check:
            # a gate with zero evidence must not pass green
            return 1
        return 0 if args.out else 2
    per_rank = {r: load_records(ps) for r, ps in files["metrics"].items()}
    report = correlate(per_rank)
    print(render(report))
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=1)
    if args.check:
        frac = report.get("mean_skew_frac")
        if frac is None:
            print("trace_merge --check: no correlated steps to gate on")
            return 1
        if frac > args.max_step_skew_frac:
            st = report.get("straggler", {})
            print(f"trace_merge --check: mean step skew fraction {frac} "
                  f"exceeds --max-step-skew-frac={args.max_step_skew_frac}"
                  + (f" — rank {st['rank']} is the straggler" if st else ""))
            return 1
        print(f"trace_merge --check: mean step skew fraction {frac} <= "
              f"{args.max_step_skew_frac}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
