#!/usr/bin/env python
"""Render / diff / CI-gate monitor output (paddle_tpu.monitor).

    python tools/perf_report.py snapshot.json
        Render span, counter, gauge, and step-breakdown tables from a
        monitor.export_json() snapshot.

    python tools/perf_report.py --diff before.json after.json
        Per-span total/avg deltas and counter deltas between two snapshots
        (the A/B view the perf rounds kept rebuilding by hand).

    python tools/perf_report.py --check metrics.jsonl [--steady-after N]
        CI/bench gate: assert the JSONL metrics file (MonitorLogger output)
        exists, contains step records, and that the recompile count stayed
        FLAT across steady-state steps (index >= N, default 2).  A rising
        recompile count in steady state is the compile-cache-thrash
        signature behind NMT-style run-to-run variance (BENCH r5: 26.3%
        spread); exit 1 names the offending steps.

    python tools/perf_report.py --check metrics.jsonl --max-host-blocked-frac 0.5
        Additionally gate the pipelined loop's steady-state host-blocked
        fraction (from paddle_tpu.pipeline.train_loop's pipeline_step
        records): above the threshold, the host is back to waiting on the
        device — an overlap regression.

    python tools/perf_report.py --check metrics.jsonl --max-retry-frac 0.1
        Additionally gate recovery events per executed step (skip-batch /
        skip-step / retry / rollback resilience_event records from
        paddle_tpu.resilience.resilient_train_loop): a healthy run sits
        near 0; above the threshold the run is burning its budget
        re-doing work (flaky data source, NaN-prone config, sick device).

    python tools/perf_report.py --check metrics.jsonl --max-heartbeat-miss-frac 0.02
        Gate the distributed health layer (paddle_tpu.dist_resilience):
        heartbeat-miss transitions over beats sent, read from the newest
        counter snapshot in the file (MonitorLogger.write_snapshot).  A
        creeping fraction means peers keep falling past the liveness
        deadline — flaky network, GC pauses, or a host about to die.

    python tools/perf_report.py --check metrics.jsonl --max-step-skew-frac 0.5
        Gate the per-step cross-rank skew metric (ISSUE 8): the live
        straggler detector's `straggler` dist_event records (falling back
        to the dist.step_skew_frac gauge in the newest counter snapshot
        — counters-only files work, same as the dist gates below).  Each
        unit is one full step of sustained lag behind the gang: a rank
        was slow-but-alive and everyone else waited for it.

    python tools/perf_report.py --postmortem TELEMETRY_DIR
        Render a merged gang post-mortem from the flight-recorder black
        boxes (BLACKBOX.p<rank>.json) and supervisor INCIDENT files a
        paddle_tpu.launch gang left in its telemetry root: names the
        dead rank(s) and folds every rank's last-N step records into one
        timeline.  See also tools/trace_merge.py for the merged Chrome
        trace + straggler attribution over the same directory.

    python tools/perf_report.py --check metrics.jsonl --max-gang-restarts 1
        Gate gang restarts (paddle_tpu.launch run_gang dist_event records
        / dist.gang_restarts counter): each one is a full
        rollback-and-relaunch, so a chaos budget above the expected
        schedule means workers are dying for reasons the fault spec does
        not explain.

    python tools/perf_report.py --check metrics.jsonl --max-data-corrupt-frac 0.01
        Gate the data layer (paddle_tpu.recordio): corrupt chunks dropped
        per chunk scanned, from the newest counter snapshot.  The corrupt
        budget keeps a run alive through isolated rot; this gate notices
        when the rot rate itself is the problem.

    python tools/perf_report.py --check metrics.jsonl --max-replay-batches 0
        Gate the resume cost: batches replayed just to fast-forward a
        stateless data source (replay_fast_forward resilience events).
        0 asserts every source resumed via the O(1) stream-state seek.

    python tools/perf_report.py --check metrics.jsonl --max-shed-frac 0.05
        Gate the serving runtime's admission control (paddle_tpu.serving):
        requests shed over requests offered, from the newest counter
        snapshot (serving.shed / serving.requests; serving_event records
        as fallback — counters-only files work).  Shedding is the DESIGNED
        overload response, so the budget is "how much overload the round
        was allowed to see", not "is shedding broken".

    python tools/perf_report.py --check metrics.jsonl --max-p99-ms 50
        Gate the serving tail: p99 request latency from the newest
        snapshot's serving.p99_ms gauge (lat_ms_max over serving_batch
        records as fallback).  The SLO number the overload arm of
        `bench.py --serve` must hold WITH shedding active — bounded-queue
        admission is what keeps it flat while load climbs.

    python tools/perf_report.py --check metrics.jsonl --require-quant-parity
        Gate a quantized-serving round (bench.py --serve --quant): the
        file must carry at least one `quant_parity` serving_event — the
        publish ladder's accuracy gate over a quantized snapshot
        (FLAGS_serving_quant_atol vs the fp32 parent's outputs) — with
        max_abs_diff within its recorded atol, and no quant-parity
        publish rejection.  A file with no quant evidence FAILS (zero
        evidence must not gate green).

    python tools/perf_report.py --check metrics.jsonl --max-lock-wait-frac 0.2
        Gate named-lock contention (paddle_tpu/core/locks.py, recorded
        when the run sets FLAGS_lock_telemetry=1): of all time threads
        spent holding-or-waiting-on named locks, the share spent WAITING
        (sum lock.*.wait_us / (wait_us + hold_us), newest counter
        snapshot).  A file with no lock.* counters FAILS the gate — zero
        evidence must not gate green (the PR 8/10 convention).  The
        failure message names the worst locks so the fix starts at the
        right critical section.

    python tools/perf_report.py --check metrics.jsonl --max-integrity-mismatches 0
        Gate silent-corruption detections (paddle_tpu/integrity.py):
        live cross-rank digest divergences + at-rest file digest
        mismatches (integrity_event records, integrity.* counter
        fallback).  Walk-back ckpt_rejected events are the downstream
        consequence of a detection that already counted — rendered, not
        double-billed.  0 asserts the run saw NO corruption at all; a
        chaos round budgets exactly its injected count.  A file with no
        integrity evidence FAILS the gate — zero evidence must not gate
        green.

    python tools/perf_report.py --check metrics.jsonl --max-chaos-violations 0
        Gate the chaos campaign's verdict (paddle_tpu/chaos.py, ISSUE
        20): invariant violations recorded by seeded multi-fault
        schedules (chaos.invariant_violations counter, failed-schedule
        chaos_event records as the floor).  0 asserts every schedule the
        campaign drew left the cross-subsystem invariants intact; any
        failure's minimal repro lives in the campaign's
        CHAOS_REPRO.json.  A file with no chaos evidence at all FAILS
        the gate — zero evidence must not gate green.

    python tools/perf_report.py --check-bench BENCH_rNN.json
        Ratcheted bench-round gate (ISSUE 7): analytic MFU must clear the
        MFU_FLOORS landed with the last accepted round (resnet50's floor
        is EXCLUSIVE — a new round must beat it, not tie it), window
        spread must sit under MAX_SPREAD_PCT per model (the NMT warm-in
        fix makes that honest), no model may report a genuinely frozen
        param (dead optimizer state — the donation-drop class
        tools/donation_audit.py pins statically), and an embedded overlap
        A/B record must confirm the bucketed all-reduce beats serial at
        bit parity.  Accepts a raw bench.py JSON line or the round
        wrapper ({"tail": ...}).  When a round ratchets a floor, edit
        MFU_FLOORS in the same PR — that is the "never regress silently"
        contract.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _load_snapshot(path):
    with open(path) as f:
        return json.load(f)


def _fmt_table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def render(path: str) -> str:
    snap = _load_snapshot(path)
    parts = [f"# monitor snapshot  lane={snap.get('lane_name', '?')}  "
             f"ts={snap.get('ts', 0):.3f}"]

    spans = snap.get("spans", {})
    if spans:
        rows = [(n, s["calls"], f"{s['total_s']*1e3:.3f}",
                 f"{s['total_s']/max(s['calls'],1)*1e3:.3f}",
                 f"{s['max_s']*1e3:.3f}")
                for n, s in sorted(spans.items(),
                                   key=lambda kv: -kv[1]["total_s"])]
        parts.append("\n## spans\n" + _fmt_table(
            rows, ["name", "calls", "total_ms", "avg_ms", "max_ms"]))

    counters = snap.get("counters", {})
    if counters:
        rows = [(n, v) for n, v in counters.items()]
        parts.append("\n## counters\n" + _fmt_table(rows, ["name", "value"]))

    gauges = snap.get("gauges", {})
    if gauges:
        rows = [(n, v) for n, v in gauges.items()]
        parts.append("\n## gauges\n" + _fmt_table(rows, ["name", "value"]))

    records = snap.get("steps", [])
    steps = [s for s in records if s.get("kind", "step") == "step"]
    if steps:
        phases = ("t_lower_s", "t_compile_s", "t_dispatch_s", "t_execute_s",
                  "t_fetch_s", "t_total_s")
        rows = []
        for ph in phases:
            # average only over records that carry the phase: async-dispatch
            # records have no execute/fetch/total, and zero-filling them
            # would report device time as near-free
            vals = [s[ph] for s in steps if ph in s]
            if not vals:
                continue
            rows.append((ph[2:-2], f"{sum(vals)*1e3:.3f}",
                         f"{sum(vals)/len(vals)*1e3:.3f}",
                         f"{max(vals)*1e3:.3f}",
                         len(vals)))
        parts.append(f"\n## step breakdown ({len(steps)} steps)\n"
                     + _fmt_table(rows, ["phase", "total_ms", "avg_ms",
                                         "max_ms", "records"]))
        hits = sum(1 for s in steps if s.get("cache_hit"))
        rec = sum(1 for s in steps if s.get("recompiled"))
        parts.append(f"cache hits {hits}/{len(steps)}, recompiles {rec}")

    psteps = [s for s in records if s.get("kind") == "pipeline_step"]
    if psteps:
        blocked, wall, frac = host_blocked_fraction(psteps)
        depths = [s.get("inflight", 0) for s in psteps]
        logged = sum(1 for s in psteps if s.get("logged"))
        parts.append(
            f"\n## pipeline ({len(psteps)} steps, {logged} logged)\n"
            f"host-blocked {blocked*1e3:.3f} ms of {wall*1e3:.3f} ms wall "
            f"-> fraction {frac:.3f}\n"
            f"inflight depth avg {sum(depths)/len(depths):.2f} "
            f"max {max(depths)}")

    devents = [s for s in records if s.get("kind") == "dist_event"]
    counters = snap.get("counters", {})
    if devents or any(n.startswith("dist.") for n in counters):
        rows = [(r.get("action", "?"),
                 r.get("rank", r.get("incarnation", "")),
                 r.get("peers", r.get("peer", r.get("what",
                       r.get("after_death_of", "")))))
                for r in devents]
        hb = heartbeat_miss_fraction([snap] if counters else [])
        parts.append(f"\n## distributed ({len(devents)} events, "
                     f"heartbeat-miss fraction {hb:.4f}, "
                     f"gang restarts {counters.get('dist.gang_restarts', 0)})\n"
                     + (_fmt_table(rows, ["action", "rank/inc", "detail"])
                        if rows else "(counters only)"))

    sbatches = [s for s in records if s.get("kind") == "serving_batch"]
    sevents = [s for s in records if s.get("kind") == "serving_event"]
    straces = [s for s in records if s.get("kind") == "serving_trace"]
    if sbatches or sevents or straces:
        lines = records + [snap]  # snap's counters/gauges = newest state
        occ = [s.get("occupancy", 0.0) for s in sbatches]
        parts.append(
            f"\n## serving ({len(sbatches)} batches, {len(sevents)} "
            f"events, shed frac {shed_fraction(lines):.4f}, "
            f"p99 {serving_p99_ms(lines):.1f} ms"
            + (f", mean occupancy {sum(occ)/len(occ):.3f}" if occ else "")
            + (f", queue-wait frac {queue_wait_fraction(lines):.4f}"
               if _has_queue_wait_evidence(lines) else "")
            + (f", pad frac {pad_fraction(lines):.4f}"
               if _has_pad_evidence(lines) else "")
            + (f", {len(straces)} request traces — tools/serve_trace.py "
               f"renders them" if straces else "")
            + ")")
        rows = [(r.get("action", "?"), r.get("model", ""),
                 r.get("reason", r.get("detail", r.get("rows", ""))))
                for r in sevents]
        if rows:
            parts.append(_fmt_table(rows, ["action", "model", "detail"]))

    ievents = [s for s in records if s.get("kind") == "integrity_event"]
    icounters = {n: v for n, v in snap.get("counters", {}).items()
                 if n.startswith("integrity.")}
    if ievents or icounters:
        rows = [(r.get("action", "?"),
                 r.get("corrupt_ranks", r.get("rank", "")),
                 r.get("safe_step", r.get("step", "")),
                 r.get("file", r.get("dir", r.get("digests", ""))))
                for r in ievents]
        parts.append(
            f"\n## integrity ({len(ievents)} events, "
            f"digest epochs {icounters.get('integrity.digests', 0)}, "
            f"files verified "
            f"{icounters.get('integrity.files_verified', 0)}, "
            f"mismatches {icounters.get('integrity.file_mismatches', 0)}"
            f"+{icounters.get('integrity.divergences', 0)} div, "
            f"rollbacks {icounters.get('integrity.rollbacks', 0)})\n"
            + (_fmt_table(rows, ["action", "ranks", "step", "detail"])
               if rows else "(counters only)"))

    revents = [s for s in records if s.get("kind") == "resilience_event"]
    if revents:
        rows = [(r.get("action", "?"), r.get("class", "?"),
                 r.get("at_step", r.get("at_batch", "")),
                 r.get("code", r.get("restored_step",
                                     r.get("max_inflight", ""))))
                for r in revents]
        frac = retry_fraction(records)
        parts.append(f"\n## resilience ({len(revents)} events, "
                     f"recovery fraction {frac:.3f})\n"
                     + _fmt_table(rows, ["action", "class", "at", "detail"]))

    sevs = [s for s in records if s.get("kind") == "resilience_event"
            and s.get("action") in STORAGE_ACTIONS]
    scnt = {n: v for n, v in snap.get("counters", {}).items()
            if n.startswith("checkpoint.")
            or n.startswith("resilience.ckpt")
            or n in ("resilience.storage_degraded",
                     "serving.publish_retries")}
    if sevs or any(scnt.values()):
        g = snap.get("gauges", {})
        parts.append(
            f"\n## storage ({len(sevs)} events, "
            f"saves {scnt.get('checkpoint.saves', 0)}, "
            f"save retries {scnt.get('resilience.ckpt_save_retries', 0)}, "
            f"degraded entries "
            f"{scnt.get('resilience.storage_degraded', 0)}, "
            f"recoveries {scnt.get('resilience.ckpt_recovered', 0)}, "
            f"fallback saves "
            f"{scnt.get('resilience.ckpt_fallback_saves', 0)}, "
            f"publish retries {scnt.get('serving.publish_retries', 0)}, "
            f"ckpt lag {g.get('resilience.ckpt_lag_steps', 0)} steps)"
            + ("\n" + _fmt_table(
                [(r.get("action", "?"), r.get("at_step", ""),
                  r.get("lag_steps", ""), r.get("cause", r.get("dir", "")))
                 for r in sevs],
                ["action", "at_step", "lag", "detail"]) if sevs else ""))

    # sparse host tier + publish cadence (ISSUE 19)
    spevs = [s for s in records if s.get("kind") == "sparse_event"]
    pubevs = [s for s in records if s.get("kind") == "resilience_event"
              and s.get("action") in ("publish", "publish_failed")]
    pscnt = {n: v for n, v in snap.get("counters", {}).items()
             if n.startswith("ps.") or n.startswith("sparse.")
             or n in ("serving.publishes", "serving.publish_errors")}
    if spevs or pubevs or any(pscnt.values()):
        g = snap.get("gauges", {})
        parts.append(
            f"\n## sparse tier ({len(spevs)} host-tier events, "
            f"publishes {pscnt.get('serving.publishes', 0)}, "
            f"publish errors {pscnt.get('serving.publish_errors', 0)}, "
            f"pserver retries {pscnt.get('ps.retries', 0)}, "
            f"push dedups {pscnt.get('ps.push_dedup', 0)}, "
            f"degraded steps {pscnt.get('sparse.degraded_steps', 0)}, "
            f"host lag {g.get('sparse.host_lag_steps', 0)} steps, "
            f"publish staleness "
            f"{g.get('serving.publish_staleness_steps', 0)} steps)"
            + ("\n" + _fmt_table(
                [(r.get("action", "?"),
                  r.get("at_step", r.get("step", "")),
                  r.get("lag_steps", r.get("staleness", "")),
                  str(r.get("detail", r.get("table", "")))[:60])
                 for r in (spevs + pubevs)[:40]],
                ["action", "at_step", "lag", "detail"])
               if spevs or pubevs else ""))

    # chaos campaign (ISSUE 20)
    cevs = [s for s in records if s.get("kind") == "chaos_event"]
    ccnt = {n: v for n, v in snap.get("counters", {}).items()
            if n.startswith("chaos.")}
    if cevs or any(ccnt.values()):
        lines = records + [snap]
        parts.append(
            f"\n## chaos campaign ({len(cevs)} events, "
            f"schedules {ccnt.get('chaos.schedules_run', 0)}, "
            f"invariant checks {ccnt.get('chaos.invariants_checked', 0)}, "
            f"violations {chaos_violation_count(lines)})"
            + ("\n" + _fmt_table(
                [(r.get("event", "?"), r.get("scenario", ""),
                  r.get("verdict", ""),
                  str(r.get("shrunk_spec", r.get("spec", "")))[:50])
                 for r in cevs[:40]],
                ["event", "scenario", "verdict", "spec"]) if cevs else ""))
    return "\n".join(parts)


RECOVERY_ACTIONS = ("skip_batch", "skip_step", "retry", "rollback")

# storage-resilience events (ISSUE 15, paddle_tpu/checkpoint_manager.py):
# each degraded/skipped round carries the lag it left training unprotected
# for — the number --max-ckpt-lag-steps gates
STORAGE_ACTIONS = ("storage_degraded", "ckpt_round_skipped",
                   "storage_recovered", "ckpt_fallback")


def _has_storage_evidence(lines):
    """True when the file carries ANY checkpoint-storage signal: storage
    resilience_event records, checkpoint.* counters, or the
    resilience.ckpt_lag_steps gauge in a snapshot.  The lag gate fails on
    a file with none — a run that never checkpointed (or never logged)
    must not gate green (the zero-evidence-fails convention, PR 8/10/13)."""
    if any(r.get("kind") == "resilience_event"
           and r.get("action") in STORAGE_ACTIONS for r in lines):
        return True
    if _latest_counters(lines, "checkpoint."):
        return True
    g = _latest_gauges(lines, "resilience.")
    return "resilience.ckpt_lag_steps" in g


def ckpt_lag_steps(lines):
    """The worst checkpoint lag the run saw: max lag_steps over
    storage_degraded / ckpt_round_skipped resilience events, falling back
    to the resilience.ckpt_lag_steps gauge in the newest snapshot (which
    reads 0 after recovery — the events are the durable evidence).  0 on
    healthy storage: every save committed, no step ran unprotected."""
    lags = [float(r.get("lag_steps", 0) or 0) for r in lines
            if r.get("kind") == "resilience_event"
            and r.get("action") in ("storage_degraded", "ckpt_round_skipped")]
    if lags:
        return max(lags)
    g = _latest_gauges(lines, "resilience.")
    try:
        return float(g.get("resilience.ckpt_lag_steps", 0.0) or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _has_publish_evidence(lines):
    """True when the file carries ANY publish-cadence signal: publish /
    publish_failed resilience events, serving.publishes/publish_errors
    counters, or the serving.publish_staleness_steps gauge in a
    snapshot.  The staleness gate fails on a file with none — a run
    whose publish hook never armed (or never logged) must not gate
    green (zero-evidence-fails, PR 8/10)."""
    if any(r.get("kind") == "resilience_event"
           and r.get("action") in ("publish", "publish_failed")
           for r in lines):
        return True
    c = _latest_counters(lines, "serving.")
    if c.get("serving.publishes") or c.get("serving.publish_errors"):
        return True
    g = _latest_gauges(lines, "serving.")
    return "serving.publish_staleness_steps" in g


def publish_staleness_steps(lines):
    """The worst publish-to-serving staleness the run saw: max staleness
    over publish_failed resilience events (each failed period stamps how
    far training ran past the last served snapshot), with the newest
    serving.publish_staleness_steps gauge as the end-of-run floor (it
    reads the gap at the final dispatch, catching a cadence that stalled
    silently at the tail)."""
    vals = [float(r.get("staleness", 0) or 0) for r in lines
            if r.get("kind") == "resilience_event"
            and r.get("action") == "publish_failed"]
    g = _latest_gauges(lines, "serving.")
    try:
        vals.append(float(g.get("serving.publish_staleness_steps", 0.0)
                          or 0.0))
    except (TypeError, ValueError):
        pass
    return max(vals) if vals else 0.0


def _has_chaos_evidence(lines):
    """True when the file carries ANY chaos-campaign signal: chaos_event
    records (one per schedule run, plus one per shrink) or chaos.*
    counters in a snapshot.  The --max-chaos-violations gate fails on a
    file with none — a campaign that never ran (or ran with the monitor
    muted) must not gate green (zero-evidence-fails, PR 8/10)."""
    if any(r.get("kind") == "chaos_event" for r in lines):
        return True
    return bool(_latest_counters(lines, "chaos."))


def chaos_violation_count(lines):
    """Invariant violations the chaos campaign saw: the newest
    chaos.invariant_violations counter, with a recount of failed
    schedule chaos_event records as the floor (the events survive even
    when no final counter snapshot was written)."""
    n_events = sum(1 for r in lines if r.get("kind") == "chaos_event"
                   and r.get("event") == "schedule"
                   and r.get("verdict") == "fail")
    c = _latest_counters(lines, "chaos.")
    try:
        n_counter = int(c.get("chaos.invariant_violations", 0) or 0)
    except (TypeError, ValueError):
        n_counter = 0
    return max(n_events, n_counter)


def _has_sparse_evidence(lines):
    """True when the file carries ANY host-tier signal: sparse_event
    records (host_tier_degraded/recovered, pserver recovery/journal
    events), sparse.* or ps.* counters, or the sparse.host_lag_steps
    gauge.  The host-lag gate fails on a file with none."""
    if any(r.get("kind") == "sparse_event" for r in lines):
        return True
    if _latest_counters(lines, "sparse.") or _latest_counters(lines, "ps."):
        return True
    g = _latest_gauges(lines, "sparse.")
    return "sparse.host_lag_steps" in g


def host_lag_steps(lines):
    """The worst host-tier outage the run saw, in consecutive degraded
    steps: max lag_steps over host_tier_degraded sparse events, falling
    back to the newest sparse.host_lag_steps gauge (which reads 0 after
    the tier recovers — the events are the durable evidence)."""
    lags = [float(r.get("lag_steps", 0) or 0) for r in lines
            if r.get("kind") == "sparse_event"
            and r.get("action") == "host_tier_degraded"]
    if lags:
        return max(lags)
    g = _latest_gauges(lines, "sparse.")
    try:
        return float(g.get("sparse.host_lag_steps", 0.0) or 0.0)
    except (TypeError, ValueError):
        return 0.0


def retry_fraction(records):
    """Recovery events per executed step — the resilience-health number a
    chaos bench / CI run gates on.  A fraction creeping up means the run
    is spending its life re-doing work (flaky data, NaN-prone config,
    sick device) even if it technically still converges."""
    steps = sum(1 for r in records if r.get("kind", "step") == "step")
    rec = sum(1 for r in records if r.get("kind") == "resilience_event"
              and r.get("action") in RECOVERY_ACTIONS)
    return rec / steps if steps else 0.0


def _latest_counters(lines, prefix):
    """`prefix`-named counters from the NEWEST record carrying a counter
    map (a MonitorLogger.write_snapshot line, or a rendered snapshot
    dict)."""
    for rec in reversed(lines):
        counters = rec.get("counters")
        if isinstance(counters, dict):
            return {n: v for n, v in counters.items() if n.startswith(prefix)}
    return {}


def _latest_gauges(lines, prefix):
    for rec in reversed(lines):
        gauges = rec.get("gauges")
        if isinstance(gauges, dict):
            return {n: v for n, v in gauges.items() if n.startswith(prefix)}
    return {}


def step_skew_frac(lines):
    """The per-step cross-rank skew metric (ISSUE 8): the maximum skew
    fraction over the live straggler detector's `straggler` dist_event
    records, falling back to the `dist.step_skew_frac` gauge in the
    newest snapshot (counters/gauges-only files, same as the PR-4 dist
    gates).  ~0 on a healthy lock-step gang; each unit is one full step
    of sustained lag behind the gang."""
    fracs = [float(r.get("skew_frac", r.get("lag_steps", 0)) or 0)
             for r in lines if r.get("kind") == "dist_event"
             and r.get("action") == "straggler"]
    if fracs:
        return max(fracs)
    g = _latest_gauges(lines, "dist.")
    try:
        return float(g.get("dist.step_skew_frac", 0.0) or 0.0)
    except (TypeError, ValueError):
        return 0.0


def _latest_dist_counters(lines):
    return _latest_counters(lines, "dist.")


def heartbeat_miss_fraction(lines):
    """Missed-liveness transitions per beat sent, from the newest counter
    snapshot in a metrics stream.  The distributed-health number: ~0 on a
    healthy gang; each unit of the numerator is one peer observed falling
    past the deadline (paddle_tpu.dist_resilience heartbeat)."""
    c = _latest_dist_counters(lines)
    sent = c.get("dist.heartbeat.sent", 0)
    missed = c.get("dist.heartbeat.missed", 0)
    return missed / sent if sent else 0.0


def gang_restart_count(lines):
    """Gang restarts: the launcher's dist_event records, falling back to
    the dist.gang_restarts counter snapshot when the event lines were
    rotated away."""
    n = sum(1 for r in lines if r.get("kind") == "dist_event"
            and r.get("action") == "gang_restart")
    if n:
        return n
    return int(_latest_dist_counters(lines).get("dist.gang_restarts", 0))


def gang_resize_count(lines):
    """Elastic world-size changes (paddle_tpu.launch `gang_resize`
    dist_events; dist.gang_resizes counter fallback).  Each shrink is a
    worker's capacity genuinely lost, each grow an interruption of the
    shrunk gang — both legitimate under chaos, both worth a budget."""
    n = sum(1 for r in lines if r.get("kind") == "dist_event"
            and r.get("action") == "gang_resize")
    if n:
        return n
    return int(_latest_dist_counters(lines).get("dist.gang_resizes", 0))


def data_corrupt_fraction(lines):
    """Corrupt RecordIO chunks dropped per chunk scanned, from the newest
    counter snapshot (`data.corrupt_chunks` / `data.chunks_scanned`,
    paddle_tpu.recordio).  ~0 on healthy storage; a creeping fraction
    means the dataset files are rotting (torn writes, bad disks) even
    while the corrupt budget keeps the run alive."""
    c = _latest_counters(lines, "data.")
    scanned = c.get("data.chunks_scanned", 0)
    corrupt = c.get("data.corrupt_chunks", 0)
    return corrupt / scanned if scanned else 0.0


def replayed_batches(lines):
    """Batches pulled-and-discarded to fast-forward a stateless data
    source on resume (`replay_fast_forward` resilience events, counter
    fallback).  The resume-cost number: 0 when every source speaks the
    stream-state protocol (O(1) seek); anything else is an O(dataset)
    resume eating the recovery budget."""
    n = sum(int(r.get("batches", 0)) for r in lines
            if r.get("kind") == "resilience_event"
            and r.get("action") == "replay_fast_forward")
    if n:
        return n
    return int(_latest_counters(lines, "resilience.")
               .get("resilience.replayed_batches", 0))


def _has_serving_evidence(lines):
    """True when the file carries ANY serving signal (records, counters,
    or gauges).  The serving gates fail on a file with none — a typo'd
    path or a run that silently logged nothing must not gate green
    (the trace_merge zero-evidence class, PR 8)."""
    if any(r.get("kind") in ("serving_batch", "serving_event")
           for r in lines):
        return True
    return bool(_latest_counters(lines, "serving.")
                or _latest_gauges(lines, "serving."))


def _has_fleet_evidence(lines):
    """True when the file carries ANY serving-fleet signal (fleet_event
    records, serving.fleet.* counters or gauges) — the ISSUE-18 fleet
    gates fail without one (zero evidence must not gate green)."""
    if any(r.get("kind") == "fleet_event" for r in lines):
        return True
    return bool(_latest_counters(lines, "serving.fleet.")
                or _latest_gauges(lines, "serving.fleet."))


def fleet_healthy_replicas(lines):
    """Newest `serving.fleet.healthy_replicas` gauge, or None when no
    snapshot in the file carries it."""
    return _latest_gauges(lines, "serving.fleet.").get(
        "serving.fleet.healthy_replicas")


def roll_convergence_failures(lines):
    """Rolling publishes that HALTED without converging.  Exact from
    fleet_event records (per roll ctl id: a `roll_halted` with no
    `roll_rolled_back`/`roll_converged` after it); counters-only files
    fall back to the events[*] counter balance."""
    events = [r for r in lines if r.get("kind") == "fleet_event"]
    if events:
        rolls = {}
        for e in events:
            if e.get("ctl"):
                rolls.setdefault(e["ctl"], []).append(e.get("action"))
        return [ctl for ctl, actions in rolls.items()
                if "roll_halted" in actions
                and "roll_rolled_back" not in actions
                and "roll_converged" not in actions]
    c = _latest_counters(lines, "serving.fleet.")
    halted = c.get("serving.fleet.events[roll_halted]", 0)
    settled = (c.get("serving.fleet.events[roll_rolled_back]", 0)
               + c.get("serving.fleet.events[roll_converged]", 0))
    if halted > settled:
        return [f"{halted:g} roll_halted vs {settled:g} "
                f"rolled_back+converged (counters)"]
    return []


def shed_fraction(lines):
    """Requests shed by serving admission control per request offered
    (paddle_tpu.serving.Server), from the newest counter snapshot
    (serving.shed / serving.requests), falling back to counting shed
    serving_event records against completed+shed when the file carries
    records but no snapshot.  ~0 on an unloaded server; each unit of the
    numerator is one client told 'no' in O(1) instead of 'yes' late."""
    c = _latest_counters(lines, "serving.")
    req = c.get("serving.requests", 0)
    if req:
        return c.get("serving.shed", 0) / req
    shed = sum(1 for r in lines if r.get("kind") == "serving_event"
               and r.get("action") == "shed")
    done = sum(int(r.get("requests", 0)) for r in lines
               if r.get("kind") == "serving_batch")
    total = shed + done
    return shed / total if total else 0.0


def serving_p99_ms(lines):
    """p99 request latency (ms) from the newest snapshot's
    serving.p99_ms gauge (the server keeps a sliding latency window),
    falling back to the p99 of lat_ms_max over serving_batch records.
    0.0 when the file carries no serving evidence."""
    g = _latest_gauges(lines, "serving.")
    try:
        v = float(g.get("serving.p99_ms", 0.0) or 0.0)
    except (TypeError, ValueError):
        v = 0.0
    if v:
        return v
    lats = [float(r.get("lat_ms_max", 0.0) or 0.0) for r in lines
            if r.get("kind") == "serving_batch"]
    lats = [x for x in lats if x > 0]
    if not lats:
        return 0.0
    lats.sort()
    return lats[min(int(0.99 * len(lats)), len(lats) - 1)]


def _has_queue_wait_evidence(lines):
    """True when the file carries ANY queue-wait attribution signal:
    serving_trace records (span trees carry the queue phase),
    serving_batch records stamped with queue_wait_frac, or the
    serving.queue_wait_frac gauge in a snapshot.  The queue-wait gate
    fails on a file with none (zero-evidence-fails convention)."""
    if any(r.get("kind") == "serving_trace" for r in lines):
        return True
    if any(r.get("kind") == "serving_batch" and "queue_wait_frac" in r
           for r in lines):
        return True
    return "serving.queue_wait_frac" in _latest_gauges(lines, "serving.")


def queue_wait_fraction(lines):
    """Of all the wall time completed requests spent in the server, the
    fraction spent QUEUED (waiting for a batch) rather than being built,
    on device, or split — the latency-attribution number ISSUE 16's
    tracing exists to produce.  High under overload by design; high at
    modest load means batches are too slow or workers too few.
    Preference order: serving_trace span trees (exact, per-request) ->
    the serving.queue_wait_frac windowed gauge -> request-weighted
    per-batch queue_wait_frac stamps on serving_batch records."""
    q = tot = 0.0
    for r in lines:
        if r.get("kind") != "serving_trace" \
                or r.get("outcome") != "completed":
            continue
        tot += float(r.get("total_ms", 0.0) or 0.0)
        q += sum(float(s.get("dur_ms", 0.0) or 0.0)
                 for s in r.get("spans", ()) if s.get("name") == "queue")
    if tot > 0:
        return q / tot
    g = _latest_gauges(lines, "serving.")
    try:
        v = float(g.get("serving.queue_wait_frac", 0.0) or 0.0)
    except (TypeError, ValueError):
        v = 0.0
    if v:
        return v
    pairs = [(float(r.get("queue_wait_frac", 0.0) or 0.0),
              int(r.get("requests", 1) or 1))
             for r in lines if r.get("kind") == "serving_batch"
             and "queue_wait_frac" in r]
    n = sum(w for _, w in pairs)
    return sum(f * w for f, w in pairs) / n if n else 0.0


def _has_pad_evidence(lines):
    """True when the file carries ANY pad-waste signal: serving.pad_rows
    / serving.padded_rows counters in a snapshot, or serving_batch
    records (bucket + rows reconstruct the pad even on pre-ISSUE-16
    files)."""
    c = _latest_counters(lines, "serving.")
    if "serving.pad_rows" in c or "serving.padded_rows" in c:
        return True
    return any(r.get("kind") == "serving_batch" for r in lines)


def pad_fraction(lines):
    """Pad rows per padded-batch row: the fraction of serving device
    compute spent on rows no client asked for (pad-to-bucket waste).
    From the newest counter snapshot (serving.pad_rows /
    (serving.rows + serving.pad_rows)), falling back to summing
    serving_batch records — where pre-ISSUE-16 files reconstruct
    pad_rows as bucket - rows."""
    c = _latest_counters(lines, "serving.")
    pad = c.get("serving.pad_rows", c.get("serving.padded_rows", 0))
    rows = c.get("serving.rows", 0)
    if rows + pad:
        return pad / (rows + pad)
    pad = rows = 0
    for r in lines:
        if r.get("kind") != "serving_batch":
            continue
        b = int(r.get("bucket", 0) or 0)
        rw = int(r.get("rows", 0) or 0)
        pad += int(r.get("pad_rows", max(b - rw, 0)))
        rows += rw
    return pad / (rows + pad) if rows + pad else 0.0


def quant_parity_events(lines):
    """The publisher's `quant_parity` serving_event records: one per
    quantized snapshot that PASSED the accuracy-parity gate
    (FLAGS_serving_quant_atol vs the serving fp32 parent's outputs,
    paddle_tpu/serving/publisher.py).  A drifted snapshot never emits
    one — it rejects with a publish_rejected event whose detail names
    'quant parity' instead."""
    return [r for r in lines if r.get("kind") == "serving_event"
            and r.get("action") == "quant_parity"]


def _has_integrity_evidence(lines):
    """True when the file carries ANY integrity signal: integrity_event
    records or integrity.* counters/gauges in a snapshot.  The integrity
    gate fails on a file with none — a run that never armed the sentinel
    (FLAGS_integrity_check_period=0, no digested manifests touched) must
    not gate green (the zero-evidence-fails convention)."""
    if any(r.get("kind") == "integrity_event" for r in lines):
        return True
    return bool(_latest_counters(lines, "integrity.")
                or _latest_gauges(lines, "integrity."))


# PRIMARY detections only: a walk-back ckpt_rejected is the downstream
# CONSEQUENCE of a file mismatch (its event already counted) or of a
# divergence's quarantine markers — counting it too would double-bill
# one injected rot (one rotted checkpoint = one file_mismatch event AND
# one ckpt_rejected event); it still renders in the integrity section.
INTEGRITY_MISMATCH_ACTIONS = ("divergence", "file_mismatch")


def integrity_mismatches(lines):
    """Silent-corruption detections: integrity_event records (live
    digest divergences + at-rest file digest mismatches), falling back
    to the integrity.* counter snapshot when the event lines were
    rotated away.  0 on healthy hardware + storage; anything else is
    real rot the sentinel caught — budget it explicitly (a chaos round
    expects exactly its injected count)."""
    n = sum(1 for r in lines if r.get("kind") == "integrity_event"
            and r.get("action") in INTEGRITY_MISMATCH_ACTIONS)
    if n:
        return n
    c = _latest_counters(lines, "integrity.")
    return int(c.get("integrity.divergences", 0)
               + c.get("integrity.file_mismatches", 0))


def _has_lock_evidence(lines):
    """True when the file carries named-lock telemetry (lock.* counters
    from FLAGS_lock_telemetry, paddle_tpu/core/locks.py).  The lock gate
    fails on a file with none — gating a run that never measured its
    locks green would be the zero-evidence class again."""
    return bool(_latest_counters(lines, "lock."))


def lock_wait_fraction(lines):
    """(fraction, per_lock) — of all time threads spent in named-lock
    critical sections plus the queues in front of them, the share spent
    WAITING: sum(lock.*.wait_us) / (sum wait_us + sum hold_us), from the
    newest counter snapshot.  0 on an uncontended process; creeping up
    means a hot lock is serializing threads (the contention ledger names
    which — per_lock maps name -> (wait_us, hold_us, contended)).
    Thread-count independent, which is what makes it gateable: it does
    not change just because the run got longer or wider."""
    c = _latest_counters(lines, "lock.")
    per_lock = {}
    for k, v in c.items():
        if k == "lock.order_inversions":
            continue
        base, _, leaf = k.rpartition(".")
        name = base[len("lock."):]
        if leaf in ("wait_us", "hold_us", "contended"):
            slot = per_lock.setdefault(name, {"wait_us": 0, "hold_us": 0,
                                              "contended": 0})
            slot[leaf] = v
    wait = sum(s["wait_us"] for s in per_lock.values())
    hold = sum(s["hold_us"] for s in per_lock.values())
    frac = wait / (wait + hold) if (wait + hold) else 0.0
    return frac, per_lock


def host_blocked_fraction(pipeline_steps):
    """(blocked_s, wall_s, fraction) over `kind="pipeline_step"` records.
    The overlap-health number: a serial loop sits near 1.0 whenever the
    device step dominates; the pipelined loop's win is how far below
    that it lands."""
    blocked = sum(s.get("t_host_blocked_s", 0.0) for s in pipeline_steps)
    wall = sum(s.get("t_step_wall_s", 0.0) for s in pipeline_steps)
    return blocked, wall, (blocked / wall if wall > 0 else 0.0)


def diff(path_a: str, path_b: str) -> str:
    a, b = _load_snapshot(path_a), _load_snapshot(path_b)
    parts = [f"# monitor diff  A={path_a}  B={path_b}"]
    sa, sb = a.get("spans", {}), b.get("spans", {})
    rows = []
    for n in sorted(set(sa) | set(sb)):
        ta = sa.get(n, {}).get("total_s", 0.0)
        tb = sb.get(n, {}).get("total_s", 0.0)
        ca = sa.get(n, {}).get("calls", 0)
        cb = sb.get(n, {}).get("calls", 0)
        aa = ta / max(ca, 1)
        ab = tb / max(cb, 1)
        pct = (ab - aa) / aa * 100 if aa else float("inf") if ab else 0.0
        rows.append((n, f"{aa*1e3:.3f}", f"{ab*1e3:.3f}", f"{pct:+.1f}%"))
    if rows:
        parts.append("\n## span avg_ms A -> B\n"
                     + _fmt_table(rows, ["name", "A", "B", "delta"]))
    ca, cb = a.get("counters", {}), b.get("counters", {})
    rows = [(n, ca.get(n, 0), cb.get(n, 0), cb.get(n, 0) - ca.get(n, 0))
            for n in sorted(set(ca) | set(cb))
            if ca.get(n, 0) != cb.get(n, 0)]
    if rows:
        parts.append("\n## counter deltas\n"
                     + _fmt_table(rows, ["name", "A", "B", "delta"]))
    return "\n".join(parts)


def check(path: str, steady_after: int = 2,
          max_host_blocked_frac: float = None,
          max_retry_frac: float = None,
          max_heartbeat_miss_frac: float = None,
          max_gang_restarts: int = None,
          max_data_corrupt_frac: float = None,
          max_replay_batches: int = None,
          max_step_skew_frac: float = None,
          max_gang_resizes: int = None,
          max_shed_frac: float = None,
          max_p99_ms: float = None,
          max_lock_wait_frac: float = None,
          max_integrity_mismatches: int = None,
          max_ckpt_lag_steps: float = None,
          max_publish_staleness_steps: float = None,
          max_host_lag_steps: float = None,
          max_queue_wait_frac: float = None,
          max_pad_frac: float = None,
          require_quant_parity: bool = False,
          min_healthy_replicas: float = None,
          check_roll_convergence: bool = False,
          max_chaos_violations: int = None) -> int:
    """Return 0 when the metrics file is healthy, 1 otherwise (printed
    diagnosis either way).  Made for CI/bench scripts:

        python tools/perf_report.py --check metrics.jsonl || exit 1

    Two gates: recompile count must stay FLAT across steady-state steps,
    and — when --max-host-blocked-frac is given — the pipeline's
    steady-state host-blocked fraction must not exceed it (an overlap
    regression: the host is back to waiting on the device)."""
    try:
        with open(path) as f:
            lines = [json.loads(ln) for ln in f if ln.strip()]
    except FileNotFoundError:
        print(f"perf_report --check: {path} does not exist "
              f"(was a MonitorLogger attached?)")
        return 1
    except json.JSONDecodeError as e:
        print(f"perf_report --check: {path} is not valid JSONL: {e}")
        return 1
    steps = [r for r in lines if r.get("kind") == "step"]
    # a launcher- or loader-side metrics file (gang restarts, dist events,
    # data-layer counters) carries no executor step records; those gates
    # must still be checkable on it
    dist_gates_only = (max_heartbeat_miss_frac is not None
                       or max_gang_restarts is not None
                       or max_data_corrupt_frac is not None
                       or max_replay_batches is not None
                       or max_step_skew_frac is not None
                       or max_gang_resizes is not None
                       or max_shed_frac is not None
                       or max_p99_ms is not None
                       or max_lock_wait_frac is not None
                       or max_integrity_mismatches is not None
                       or max_ckpt_lag_steps is not None
                       or max_publish_staleness_steps is not None
                       or max_host_lag_steps is not None
                       or max_queue_wait_frac is not None
                       or max_pad_frac is not None
                       or require_quant_parity
                       or min_healthy_replicas is not None
                       or check_roll_convergence
                       or max_chaos_violations is not None) \
        and max_host_blocked_frac is None and max_retry_frac is None
    if not steps and not dist_gates_only:
        print(f"perf_report --check: {path} contains no step records "
              f"({len(lines)} lines)")
        return 1
    failures = []
    steady = steps[steady_after:]
    if not steps:
        pass  # dist-gates-only file: no recompile gate to run
    elif not steady:
        print(f"perf_report --check: only {len(steps)} steps, fewer than "
              f"--steady-after={steady_after}; recompile gate skipped")
    else:
        base = steady[0].get("recompiles_total", 0)
        bad = [(i + steady_after, s.get("recompiles_total", 0))
               for i, s in enumerate(steady)
               if s.get("recompiles_total", 0) != base]
        if bad:
            failures.append(
                f"recompile count moved in steady state (started at {base}): "
                f"steps {bad[:10]} — the executor is re-tracing; check feed "
                f"shape/dtype churn and _lowering_flags toggles")
        else:
            print(f"perf_report --check: recompile count flat at {base} "
                  f"across {len(steady)} steady-state steps")
    if max_host_blocked_frac is not None:
        psteps = [r for r in lines if r.get("kind") == "pipeline_step"]
        steady_p = psteps[steady_after:]
        if not steady_p:
            failures.append(
                f"--max-host-blocked-frac given but no steady-state "
                f"pipeline_step records in {path} (found {len(psteps)} "
                f"total) — was train_loop run with the monitor enabled?")
        else:
            blocked, wall, frac = host_blocked_fraction(steady_p)
            if frac > max_host_blocked_frac:
                failures.append(
                    f"host-blocked fraction {frac:.3f} exceeds the "
                    f"--max-host-blocked-frac={max_host_blocked_frac} gate "
                    f"over {len(steady_p)} steady-state pipeline steps "
                    f"({blocked*1e3:.1f} ms blocked of {wall*1e3:.1f} ms) — "
                    f"overlap regression: raise max_inflight / log_period, "
                    f"or look for a new sync point in the step")
            else:
                print(f"perf_report --check: host-blocked fraction "
                      f"{frac:.3f} <= {max_host_blocked_frac} across "
                      f"{len(steady_p)} steady-state pipeline steps")
    if max_retry_frac is not None:
        frac = retry_fraction(lines)
        if frac > max_retry_frac:
            n_ev = sum(1 for r in lines
                       if r.get("kind") == "resilience_event"
                       and r.get("action") in RECOVERY_ACTIONS)
            failures.append(
                f"recovery fraction {frac:.3f} ({n_ev} skip/retry/rollback "
                f"events over {len(steps)} steps) exceeds the "
                f"--max-retry-frac={max_retry_frac} gate — the run is "
                f"spending its budget re-doing work; check the data "
                f"source, NaN guard hits, and device health")
        else:
            print(f"perf_report --check: recovery fraction {frac:.3f} <= "
                  f"{max_retry_frac}")
    if max_heartbeat_miss_frac is not None:
        frac = heartbeat_miss_fraction(lines)
        if frac > max_heartbeat_miss_frac:
            failures.append(
                f"heartbeat-miss fraction {frac:.4f} exceeds the "
                f"--max-heartbeat-miss-frac={max_heartbeat_miss_frac} gate "
                f"— peers keep falling past the liveness deadline "
                f"(flaky network, long GC/compile pauses, or a host on "
                f"its way out); check dist.heartbeat.* counters and the "
                f"stack dumps in worker stderr")
        else:
            print(f"perf_report --check: heartbeat-miss fraction "
                  f"{frac:.4f} <= {max_heartbeat_miss_frac}")
    if max_gang_restarts is not None:
        n = gang_restart_count(lines)
        if n > max_gang_restarts:
            failures.append(
                f"{n} gang restart(s) exceed the "
                f"--max-gang-restarts={max_gang_restarts} gate — each one "
                f"is a full rollback to the last coordinated checkpoint; "
                f"workers are dying beyond what the fault schedule "
                f"explains (see worker_death dist_event records)")
        else:
            print(f"perf_report --check: gang restarts {n} <= "
                  f"{max_gang_restarts}")
    if max_gang_resizes is not None:
        n = gang_resize_count(lines)
        if n > max_gang_resizes:
            shrinks = sum(1 for r in lines if r.get("kind") == "dist_event"
                          and r.get("action") == "gang_resize"
                          and r.get("direction") == "shrink")
            failures.append(
                f"{n} gang resize(s) ({shrinks} shrink(s)) exceed the "
                f"--max-gang-resizes={max_gang_resizes} gate — the gang's "
                f"world size is churning beyond what the fault schedule "
                f"explains (each shrink is lost capacity, each grow an "
                f"interruption of the shrunk gang; see gang_resize "
                f"dist_event records)")
        else:
            print(f"perf_report --check: gang resizes {n} <= "
                  f"{max_gang_resizes}")
    if max_data_corrupt_frac is not None:
        frac = data_corrupt_fraction(lines)
        if frac > max_data_corrupt_frac:
            failures.append(
                f"data-corrupt fraction {frac:.4f} exceeds the "
                f"--max-data-corrupt-frac={max_data_corrupt_frac} gate — "
                f"the dataset files are rotting faster than the corrupt "
                f"budget should have to cover (torn writes, bad disks, a "
                f"broken producer); check data.corrupt_chunks vs "
                f"data.chunks_scanned and regenerate the files")
        else:
            print(f"perf_report --check: data-corrupt fraction {frac:.4f} "
                  f"<= {max_data_corrupt_frac}")
    if max_step_skew_frac is not None:
        frac = step_skew_frac(lines)
        if frac > max_step_skew_frac:
            stragglers = sorted({r.get("rank") for r in lines
                                 if r.get("kind") == "dist_event"
                                 and r.get("action") == "straggler"})
            failures.append(
                f"per-step cross-rank skew fraction {frac} exceeds the "
                f"--max-step-skew-frac={max_step_skew_frac} gate — a rank "
                f"is holding the gang back "
                f"(straggler suspect(s): {stragglers or 'see gauge'}); "
                f"check dist.straggler_* counters, the offender's "
                f"telemetry in the straggler dist_events, and "
                f"tools/trace_merge.py over the gang's telemetry dir")
        else:
            print(f"perf_report --check: step skew fraction {frac} <= "
                  f"{max_step_skew_frac}")
    if (max_shed_frac is not None or max_p99_ms is not None) \
            and not _has_serving_evidence(lines):
        failures.append(
            f"serving gates given but {path} carries no serving evidence "
            f"(no serving_batch/serving_event records and no serving.* "
            f"counters/gauges in any snapshot) — was the monitor enabled "
            f"and a MonitorLogger attached to the serving run?")
        max_shed_frac = max_p99_ms = None  # no data to gate meaningfully
    if max_shed_frac is not None:
        frac = shed_fraction(lines)
        if frac > max_shed_frac:
            failures.append(
                f"serving shed fraction {frac:.4f} exceeds the "
                f"--max-shed-frac={max_shed_frac} gate — the server is "
                f"shedding more of its offered load than the round "
                f"budgeted; either traffic genuinely exceeds capacity "
                f"(scale out, widen buckets, raise the queue bound) or "
                f"batches got slower (check serving_batch t_infer_s and "
                f"the recompile gate above)")
        else:
            print(f"perf_report --check: serving shed fraction "
                  f"{frac:.4f} <= {max_shed_frac}")
    if max_p99_ms is not None:
        p99 = serving_p99_ms(lines)
        if p99 > max_p99_ms:
            failures.append(
                f"serving p99 latency {p99:.1f} ms exceeds the "
                f"--max-p99-ms={max_p99_ms} gate — the tail SLO broke; "
                f"with admission control on, suspects are batch execution "
                f"time (serving_batch t_infer_s), an inline recompile "
                f"(recompile gate above), or a queue bound sized past the "
                f"latency budget (max_queue x batch time is the worst-"
                f"case wait)")
        else:
            print(f"perf_report --check: serving p99 {p99:.1f} ms <= "
                  f"{max_p99_ms}")
    if max_queue_wait_frac is not None:
        if not _has_queue_wait_evidence(lines):
            failures.append(
                f"--max-queue-wait-frac given but {path} carries no "
                f"queue-wait evidence (no serving_trace records, no "
                f"queue_wait_frac-stamped serving_batch records, no "
                f"serving.queue_wait_frac gauge in any snapshot) — was "
                f"the monitor enabled on the serving run?  (zero "
                f"evidence must not gate green)")
        else:
            frac = queue_wait_fraction(lines)
            if frac > max_queue_wait_frac:
                failures.append(
                    f"serving queue-wait fraction {frac:.4f} exceeds the "
                    f"--max-queue-wait-frac={max_queue_wait_frac} gate — "
                    f"completed requests spent most of their latency "
                    f"budget QUEUED, not computing; either offered load "
                    f"sits past capacity (scale out, or let admission "
                    f"control shed it) or batches got slower (check "
                    f"serving_batch t_infer_s and serve_trace --top's "
                    f"per-bucket queue column)")
            else:
                print(f"perf_report --check: serving queue-wait fraction "
                      f"{frac:.4f} <= {max_queue_wait_frac}")
    if max_pad_frac is not None:
        if not _has_pad_evidence(lines):
            failures.append(
                f"--max-pad-frac given but {path} carries no pad-waste "
                f"evidence (no serving_batch records and no "
                f"serving.pad_rows/padded_rows counters in any snapshot) "
                f"— was the monitor enabled on the serving run?  (zero "
                f"evidence must not gate green)")
        else:
            frac = pad_fraction(lines)
            if frac > max_pad_frac:
                failures.append(
                    f"serving pad fraction {frac:.4f} exceeds the "
                    f"--max-pad-frac={max_pad_frac} gate — too much of "
                    f"the device compute is pad rows no client asked "
                    f"for; the bucket ladder is too coarse for the "
                    f"traffic's batch-size mix (add intermediate "
                    f"FLAGS_serving_buckets rungs; serve_trace --top "
                    f"names the wasteful buckets)")
            else:
                print(f"perf_report --check: serving pad fraction "
                      f"{frac:.4f} <= {max_pad_frac}")
    if require_quant_parity:
        qevs = quant_parity_events(lines)
        qrej = [r for r in lines if r.get("kind") == "serving_event"
                and r.get("action") == "publish_rejected"
                and "quant parity" in str(r.get("detail", ""))]
        if qrej:
            failures.append(
                f"{len(qrej)} quantized publish(es) REJECTED on the "
                f"accuracy-parity gate "
                f"({qrej[0].get('detail', '')!r}) — the int8 snapshot "
                f"drifted past FLAGS_serving_quant_atol from its fp32 "
                f"parent; re-quantize (check the scales) rather than "
                f"raising the tolerance")
        elif not qevs:
            failures.append(
                f"--require-quant-parity given but {path} carries no "
                f"quant_parity serving_event — no quantized snapshot "
                f"went through the publish ladder's parity gate (was "
                f"`bench.py --serve --quant` the producer, with the "
                f"monitor enabled?); zero evidence must not gate green")
        else:
            worst = max(float(r.get("max_abs_diff", 0.0) or 0.0)
                        for r in qevs)
            drifted = [r for r in qevs
                       if float(r.get("max_abs_diff", 0.0) or 0.0)
                       > float(r.get("atol", 0.0) or 0.0)]
            if drifted:
                failures.append(
                    f"quant parity event carries max_abs_diff "
                    f"{drifted[0].get('max_abs_diff')} past its own atol "
                    f"{drifted[0].get('atol')} — the gate recorded drift "
                    f"it should have rejected; the publisher's parity "
                    f"rung is broken")
            else:
                print(f"perf_report --check: quant parity held across "
                      f"{len(qevs)} quantized publish(es) "
                      f"(worst max|diff| {worst:.3e})")
    if min_healthy_replicas is not None:
        if not _has_fleet_evidence(lines):
            failures.append(
                f"--min-healthy-replicas given but {path} carries no "
                f"serving-fleet evidence (no fleet_event records and no "
                f"serving.fleet.* counters/gauges in any snapshot) — was "
                f"this a fleet router.jsonl (ServingFleet telemetry)?  "
                f"(zero evidence must not gate green)")
        else:
            n = fleet_healthy_replicas(lines)
            if n is None:
                failures.append(
                    f"--min-healthy-replicas given but no snapshot in "
                    f"{path} carries the serving.fleet.healthy_replicas "
                    f"gauge — the fleet supervisor's snapshot loop never "
                    f"wrote one (zero evidence must not gate green)")
            elif n < min_healthy_replicas:
                failures.append(
                    f"fleet ended with {n:g} healthy replica(s), below "
                    f"the --min-healthy-replicas={min_healthy_replicas:g} "
                    f"gate — replicas died past their restart budget or "
                    f"never came up; see the replica_dead / "
                    f"replica_abandoned fleet_events and the replica "
                    f"stderr spools in the fleet's logs/ dir")
            else:
                print(f"perf_report --check: healthy replicas {n:g} >= "
                      f"{min_healthy_replicas:g}")
    if check_roll_convergence:
        if not _has_fleet_evidence(lines):
            failures.append(
                f"--check-roll-convergence given but {path} carries no "
                f"serving-fleet evidence (no fleet_event records and no "
                f"serving.fleet.* counters/gauges in any snapshot) — "
                f"(zero evidence must not gate green)")
        else:
            unconverged = roll_convergence_failures(lines)
            if unconverged:
                failures.append(
                    f"{len(unconverged)} rolling publish(es) halted "
                    f"WITHOUT converging ({unconverged[:3]}) — no "
                    f"roll_rolled_back/roll_converged followed the "
                    f"roll_halted, so replicas may be split between "
                    f"versions; `serve_trace --fleet` renders the "
                    f"episode, and ROLL.json in the fleet root holds "
                    f"the persisted state to resume_roll() from")
            else:
                n_rolls = sum(1 for r in lines
                              if r.get("kind") == "fleet_event"
                              and r.get("action") == "roll_started")
                print(f"perf_report --check: roll convergence holds "
                      f"({n_rolls} roll(s) on record)")
    if max_lock_wait_frac is not None:
        if not _has_lock_evidence(lines):
            failures.append(
                f"--max-lock-wait-frac given but {path} carries no lock.* "
                f"counters in any snapshot — was the run launched with "
                f"FLAGS_lock_telemetry=1 and a MonitorLogger snapshot "
                f"written?  (zero evidence must not gate green)")
        else:
            frac, per_lock = lock_wait_fraction(lines)
            if frac > max_lock_wait_frac:
                worst = sorted(per_lock.items(),
                               key=lambda kv: -kv[1]["wait_us"])[:3]
                worst_s = ", ".join(
                    f"{n} (wait {s['wait_us']/1e3:.1f} ms / hold "
                    f"{s['hold_us']/1e3:.1f} ms, {s['contended']} "
                    f"contended)" for n, s in worst)
                failures.append(
                    f"lock wait fraction {frac:.4f} exceeds the "
                    f"--max-lock-wait-frac={max_lock_wait_frac} gate — "
                    f"threads are queueing on named locks instead of "
                    f"working; worst: {worst_s}.  Shrink the critical "
                    f"section (the concurrency lint's blocking-under-lock "
                    f"registry is the usual culprit list) or split the "
                    f"lock")
            else:
                print(f"perf_report --check: lock wait fraction "
                      f"{frac:.4f} <= {max_lock_wait_frac}")
    if max_integrity_mismatches is not None:
        if not _has_integrity_evidence(lines):
            failures.append(
                f"--max-integrity-mismatches given but {path} carries no "
                f"integrity evidence (no integrity_event records and no "
                f"integrity.* counters/gauges in any snapshot) — was the "
                f"sentinel armed (FLAGS_integrity_check_period > 0) and "
                f"a snapshot written?  (zero evidence must not gate "
                f"green)")
        else:
            n = integrity_mismatches(lines)
            if n > max_integrity_mismatches:
                where = sorted({r.get("action") for r in lines
                                if r.get("kind") == "integrity_event"
                                and r.get("action")
                                in INTEGRITY_MISMATCH_ACTIONS})
                failures.append(
                    f"{n} integrity mismatch(es) exceed the "
                    f"--max-integrity-mismatches="
                    f"{max_integrity_mismatches} gate "
                    f"({where or 'counters only'}) — the sentinel caught "
                    f"real silent corruption beyond what the fault "
                    f"schedule explains; scrub the checkpoint tree "
                    f"(tools/scrub.py) and check the host's memory/disk "
                    f"health")
            else:
                print(f"perf_report --check: integrity mismatches {n} "
                      f"<= {max_integrity_mismatches}")
    if max_ckpt_lag_steps is not None:
        if not _has_storage_evidence(lines):
            failures.append(
                f"--max-ckpt-lag-steps given but {path} carries no "
                f"checkpoint-storage evidence (no storage resilience "
                f"events, no checkpoint.* counters, no "
                f"resilience.ckpt_lag_steps gauge in any snapshot) — was "
                f"a CheckpointManager attached and a snapshot written?  "
                f"(zero evidence must not gate green)")
        else:
            lag = ckpt_lag_steps(lines)
            if lag > max_ckpt_lag_steps:
                rounds = sum(1 for r in lines
                             if r.get("kind") == "resilience_event"
                             and r.get("action") in ("storage_degraded",
                                                     "ckpt_round_skipped"))
                failures.append(
                    f"checkpoint lag of {lag:g} step(s) exceeds the "
                    f"--max-ckpt-lag-steps={max_ckpt_lag_steps} gate "
                    f"({rounds} degraded/skipped save round(s)) — "
                    f"training ran unprotected past the budget while "
                    f"storage failed; check resilience.ckpt_storage_"
                    f"errors, the storage_degraded events' causes, and "
                    f"the store itself (full disk, read-only mount, "
                    f"flaky NFS)")
            else:
                print(f"perf_report --check: checkpoint lag {lag:g} <= "
                      f"{max_ckpt_lag_steps} steps")
    if max_publish_staleness_steps is not None:
        if not _has_publish_evidence(lines):
            failures.append(
                f"--max-publish-staleness-steps given but {path} carries "
                f"no publish-cadence evidence (no publish/publish_failed "
                f"resilience events, no serving.publishes counter, no "
                f"serving.publish_staleness_steps gauge in any snapshot) "
                f"— was resilient_train_loop's publish_hook armed with "
                f"FLAGS_publish_period_steps > 0?  (zero evidence must "
                f"not gate green)")
        else:
            st = publish_staleness_steps(lines)
            if st > max_publish_staleness_steps:
                fails = sum(1 for r in lines
                            if r.get("kind") == "resilience_event"
                            and r.get("action") == "publish_failed")
                failures.append(
                    f"publish-to-serving staleness of {st:g} step(s) "
                    f"exceeds the --max-publish-staleness-steps="
                    f"{max_publish_staleness_steps} gate ({fails} failed "
                    f"publish period(s)) — the serving fleet ran on a "
                    f"snapshot further behind training than the cadence "
                    f"SLO allows; check serving.publish_errors, the "
                    f"publish_failed events' details, and the store / "
                    f"publish ladder they name")
            else:
                print(f"perf_report --check: publish staleness {st:g} <= "
                      f"{max_publish_staleness_steps} steps")
    if max_host_lag_steps is not None:
        if not _has_sparse_evidence(lines):
            failures.append(
                f"--max-host-lag-steps given but {path} carries no "
                f"host-tier evidence (no sparse_event records, no "
                f"sparse.*/ps.* counters, no sparse.host_lag_steps gauge "
                f"in any snapshot) — did the run use HostTableEmbedding "
                f"/ TieredEmbedding at all?  (zero evidence must not "
                f"gate green)")
        else:
            lag = host_lag_steps(lines)
            if lag > max_host_lag_steps:
                n = sum(1 for r in lines
                        if r.get("kind") == "sparse_event"
                        and r.get("action") == "host_tier_degraded")
                failures.append(
                    f"host-tier lag of {lag:g} consecutive degraded "
                    f"step(s) exceeds the --max-host-lag-steps="
                    f"{max_host_lag_steps} gate ({n} degraded step "
                    f"record(s)) — the cold embedding tail trained "
                    f"hot-shard-only longer than the budget allows; "
                    f"check the pserver supervisor's restart budget "
                    f"(pserver_give_up fleet events) and ps.retries")
            else:
                print(f"perf_report --check: host-tier lag {lag:g} <= "
                      f"{max_host_lag_steps} steps")
    if max_replay_batches is not None:
        n = replayed_batches(lines)
        if n > max_replay_batches:
            failures.append(
                f"{n} batch(es) replayed to fast-forward on resume exceed "
                f"the --max-replay-batches={max_replay_batches} gate — the "
                f"data source is stateless, so every resume is O(dataset); "
                f"give the loop a checkpointable reader (stream-state "
                f"protocol) to make resume an O(1) seek")
        else:
            print(f"perf_report --check: replayed batches {n} <= "
                  f"{max_replay_batches}")
    if max_chaos_violations is not None:
        if not _has_chaos_evidence(lines):
            failures.append(
                f"--max-chaos-violations given but {path} carries no "
                f"chaos-campaign evidence (no chaos_event records, no "
                f"chaos.* counters in any snapshot) — was "
                f"tools/chaos_campaign.py run with --metrics pointed at "
                f"this file?  (zero evidence must not gate green)")
        else:
            n = chaos_violation_count(lines)
            if n > max_chaos_violations:
                sched = sum(1 for r in lines
                            if r.get("kind") == "chaos_event"
                            and r.get("event") == "schedule")
                failures.append(
                    f"{n} chaos invariant violation(s) over {sched} "
                    f"schedule(s) exceed the --max-chaos-violations="
                    f"{max_chaos_violations} gate — a seeded multi-fault "
                    f"schedule broke a cross-subsystem invariant; the "
                    f"failing chaos_event records name the spec, and the "
                    f"campaign's CHAOS_REPRO.json carries the shrunk "
                    f"minimal repro (replay it with tools/"
                    f"chaos_campaign.py --replay)")
            else:
                print(f"perf_report --check: chaos violations {n} <= "
                      f"{max_chaos_violations}")
    if failures:
        for f_ in failures:
            print(f"perf_report --check: {f_}")
        return 1
    print(f"perf_report --check: OK — {len(steps)} steps")
    return 0


# Ratcheted analytic-MFU floors (ISSUE 7).  Set from BENCH_r05 — resnet50's
# is EXCLUSIVE (the MFU campaign must land strictly above the level it set
# out to beat), bert's INCLUSIVE (hold the r05 line).  Each accepted bench
# round that clears a floor by a margin ratchets it here, in the same PR,
# so MFU can never regress silently.
MFU_FLOORS = {
    "resnet50": {"floor": 0.168, "strict": True},
    "bert": {"floor": 0.402, "strict": False},
}
# Per-model window-spread ceiling: above this the round's numbers are noise
# (BENCH_r05's NMT entry hit 26.3% from warm-in; tools/bench_kit.py
# timed_steps(spread_target=...) now extends warmup until stable).
MAX_SPREAD_PCT = 5.0
# Ceiling on the per-step cross-rank skew a multi-process bench round may
# embed (bench.py gangs compute it from worker telemetry via
# tools/trace_merge.py): mean arrival skew above one full mean step time
# means a rank spent every step waiting for a straggler — the round's
# gang numbers measure the straggler, not the framework.
MAX_BENCH_STEP_SKEW_FRAC = 1.0


def _bench_records(path):
    """{model: record} from a bench.py JSON line or a BENCH_rNN.json round
    wrapper ({"tail": "...last line is the record..."})."""
    with open(path) as f:
        doc = json.load(f)
    if "tail" in doc and "metric" not in doc:
        rec = None
        for line in doc["tail"].splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    cand = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "metric" in cand:
                    rec = cand
        if rec is None:
            raise ValueError(f"{path}: no bench JSON line in 'tail'")
        doc = rec
    out = {}
    extra = doc.get("extra", {})
    if doc.get("metric", "").startswith("resnet50"):
        out["resnet50"] = {**doc, **{k: v for k, v in extra.items()
                                     if k != "models"}}
    elif "metric" in doc:
        # a non-resnet50 anchor (e.g. a serving round's quant A/B) keys
        # itself in alongside any records riding its extra.models
        out[doc["metric"].split("_")[0]] = doc
    for name, rec in extra.get("models", {}).items():
        out[name] = rec
    return out


def check_bench(path, floors=None, max_spread_pct=None,
                require_overlap=False, min_roofline_frac=None) -> int:
    """Ratcheted bench-round gate: MFU floors, spread ceiling, zero frozen
    params, overlap A/B confirmation, and the predicted-MFU column — every
    record carrying the program's own static roofline prediction
    (mfu_predicted_roofline, stamped by bench.py from
    core/resource_plan.py) is printed as measured-vs-predicted so a
    measured MFU far under the program's roofline is NAMED, not averaged
    away; `min_roofline_frac` turns that naming into a hard gate.
    0 healthy / 1 failed, diagnosis printed either way.  `require_overlap`
    fails rounds that do not embed a dp_grad_overlap record (fresh-round
    acceptance; historical rounds predate the overlap path and check
    without it).

    A serving-only round (every record metric starts with "serving", e.g.
    BENCH_r06) skips the training MFU floors with a loud NOTE; the
    measured-vs-predicted roofline line, the off-device honesty contract
    (`throughput_claim`), and the quant parity ledger still gate it —
    a dirty ledger or a quant A/B whose publish ladder never recorded
    its `quant_parity` event FAILS."""
    floors = MFU_FLOORS if floors is None else floors
    max_spread = MAX_SPREAD_PCT if max_spread_pct is None else max_spread_pct
    try:
        recs = _bench_records(path)
    except (FileNotFoundError, ValueError, json.JSONDecodeError) as e:
        print(f"perf_report --check-bench: cannot read {path}: {e}")
        return 1
    if not recs:
        print(f"perf_report --check-bench: no model records in {path}")
        return 1
    failures = []
    # a serving round carries no training records for the floors to hold
    # against — skipping them silently would look like a green training
    # gate, so say it; the serving-specific gates below still apply
    serving_only = all(
        isinstance(r, dict)
        and str(r.get("metric", "")).startswith("serving")
        for r in recs.values())
    if serving_only:
        print("perf_report --check-bench: serving-only round — training "
              "MFU floors skipped (roofline line, throughput-claim "
              "honesty, and the quant parity ledger still gate it)")
    for model, gate in ([] if serving_only else floors.items()):
        rec = recs.get(model)
        if rec is None or "error" in rec:
            failures.append(f"{model}: no bench record to hold its MFU "
                            f"floor against (errored or missing)")
            continue
        mfu = rec.get("mfu_bf16_analytic")
        if mfu is None:
            failures.append(f"{model}: record carries no "
                            f"mfu_bf16_analytic")
            continue
        ok = mfu > gate["floor"] if gate["strict"] else mfu >= gate["floor"]
        cmp = ">" if gate["strict"] else ">="
        if not ok:
            failures.append(
                f"{model}: analytic MFU {mfu} fails the ratcheted floor "
                f"(needs {cmp} {gate['floor']}) — a kernel/donation/"
                f"overlap regression landed; bisect with tools/opbench.py "
                f"--fused and tools/donation_audit.py --check")
        else:
            print(f"perf_report --check-bench: {model} MFU {mfu} {cmp} "
                  f"floor {gate['floor']}")
    for model, rec in sorted(recs.items()):
        if not isinstance(rec, dict) or "error" in rec:
            continue
        # predicted-MFU column: the program's own static roofline
        # (core/resource_plan.py) is the denominator that makes a low
        # measured MFU attributable — "leaving 3x on the table" vs "this
        # program is bandwidth-bound and 0.2 IS its roofline"
        mfu = rec.get("mfu_bf16_analytic")
        pred = rec.get("mfu_predicted_roofline")
        if mfu is not None and pred:
            frac = mfu / pred
            print(f"perf_report --check-bench: {model} measured MFU {mfu} "
                  f"vs static roofline {pred} ({frac:.2f}x of predicted)")
            if min_roofline_frac is not None and frac < min_roofline_frac:
                failures.append(
                    f"{model}: measured MFU {mfu} is only {frac:.2f}x of "
                    f"the program's own static roofline {pred} (floor "
                    f"{min_roofline_frac}) — the gap is in the compiled "
                    f"step (fusion/layout/overlap), not the hardware; "
                    f"tools/resource_plan.py --bench names the per-model "
                    f"gaps")
            elif frac < 0.1:
                print(f"perf_report --check-bench: NOTE: {model} runs at "
                      f"{frac:.2f}x of its own static roofline — large "
                      f"compiled-step factors on the table")
        elif mfu is not None and min_roofline_frac is not None:
            # gating on a ratio no record carries would be a green gate
            # with no data (the PR-8/PR-10 class) — fail, don't skip
            failures.append(
                f"{model}: --min-roofline-frac set but the record carries "
                f"no mfu_predicted_roofline to hold measured MFU against "
                f"(bench.py stamps it; its roofline prediction failed or "
                f"the round predates it)")
        spread = rec.get("spread_pct")
        if spread is not None and spread > max_spread:
            failures.append(
                f"{model}: window spread {spread}% exceeds "
                f"{max_spread}% — the round's numbers are noise; rerun "
                f"with timed_steps(spread_target=...) warm-until-stable")
        pm = rec.get("params_moved")
        if pm and "subresolution" in pm and pm.get("frozen", 0):
            failures.append(
                f"{model}: {pm['frozen']} param(s) with DEAD optimizer "
                f"state (dropped-update class) — run tools/"
                f"donation_audit.py --program {model}")
        sk = rec.get("step_skew_frac")
        if sk is not None and sk > MAX_BENCH_STEP_SKEW_FRAC:
            failures.append(
                f"{model}: embedded gang skew record reports mean "
                f"per-step cross-rank skew {sk} > "
                f"{MAX_BENCH_STEP_SKEW_FRAC} (straggler rank "
                f"{rec.get('straggler_rank')}) — the round's gang "
                f"numbers measure a straggler, not the framework; rerun "
                f"on healthy workers (tools/trace_merge.py names the "
                f"offender)")
        elif sk is not None:
            print(f"perf_report --check-bench: {model} gang skew frac "
                  f"{sk} <= {MAX_BENCH_STEP_SKEW_FRAC}")
        if rec.get("throughput_claim") == "parity_only_off_device":
            print(f"perf_report --check-bench: NOTE: {model} ran "
                  f"off-device (device={rec.get('device')}) — parity "
                  f"evidence only; no throughput or MFU floor may "
                  f"ratchet from this record")
        par = rec.get("parity")
        if isinstance(par, dict) and "within_atol" in par:
            # a quant A/B is a speedup claim with no accuracy evidence
            # unless both halves of its ledger hold: the publish ladder's
            # own gate event ran, and the recorded drift sits inside atol
            if not par.get("gate_event_recorded", True):
                failures.append(
                    f"{model}: quant A/B but the publish ladder recorded "
                    f"no quant_parity event — the accuracy gate never ran "
                    f"on this snapshot (FLAGS_serving_quant_atol=0 "
                    f"disables it); an ungated quant round cannot land")
            if not par["within_atol"]:
                failures.append(
                    f"{model}: quant parity ledger DIRTY — max|diff| "
                    f"{par.get('max_abs_diff')} past atol "
                    f"{par.get('atol')}; the quantized snapshot drifted "
                    f"from its fp32 parent and the A/B's throughput is "
                    f"not evidence")
            elif par.get("gate_event_recorded", True):
                print(f"perf_report --check-bench: {model} quant parity "
                      f"ledger clean (max|diff| "
                      f"{par.get('max_abs_diff'):.2e} <= atol "
                      f"{par.get('atol'):g}, gate event recorded)")
    ov = next((r for r in recs.values() if isinstance(r, dict)
               and r.get("metric", "").startswith("dp_grad_overlap")), None)
    if ov is None:
        # a silent skip here would let an overlap regression through on any
        # round assembled without `bench.py --overlap`'s record — say so
        msg = ("no dp_grad_overlap record embedded — overlap gates "
               "skipped; embed the `bench.py --overlap` record under "
               "extra.models to hold the round to them")
        if require_overlap:
            failures.append(msg)
        else:
            print(f"perf_report --check-bench: NOTE: {msg}")
    if ov is not None:
        if not ov.get("overlap_confirmed"):
            # off-device (CPU gloo) records are parity evidence only —
            # overlap_confirmed stays false there by design, so an
            # unconfirmed record fails the gate only under
            # --require-overlap; without it the parity checks below still
            # hold the record and the gap is said out loud
            msg = (
                f"overlap A/B: bucketed all-reduce did not beat serial "
                f"({ov.get('speedup_vs_serial')}x) — either the backward "
                f"overlap regressed or the record is from an off-device "
                f"round (parity evidence only); a device round must "
                f"confirm overlap")
            if require_overlap:
                failures.append(msg)
            else:
                print(f"perf_report --check-bench: NOTE: {msg}")
        if not ov.get("bit_parity_serial_vs_bucketed", True):
            failures.append("overlap A/B: serial and bucketed arms ended "
                            "with different params — bucketing changed "
                            "numerics, which it must never do")
    if failures:
        for f_ in failures:
            print(f"perf_report --check-bench: {f_}")
        return 1
    print(f"perf_report --check-bench: OK — {sorted(recs)} hold the "
          f"ratcheted floors")
    return 0


def postmortem(root: str, last_n: int = 30) -> int:
    """Render a merged post-mortem from a gang's harvested telemetry
    (`perf_report --postmortem <telemetry_root>`): every rank's
    BLACKBOX.p<rank>.json flight-recorder dump plus the supervisor's
    INCIDENT.i<k>.json files, folded into one last-N-steps timeline that
    names the dead rank(s).  Returns 0 when at least one black box was
    found, 1 otherwise."""
    import glob as _glob

    boxes = []
    for p in sorted(_glob.glob(os.path.join(root, "**", "BLACKBOX.p*.json"),
                               recursive=True)):
        try:
            with open(p) as f:
                doc = json.load(f)
            doc["_path"] = p
            boxes.append(doc)
        except (OSError, json.JSONDecodeError):
            continue
    incidents = []
    for p in sorted(_glob.glob(os.path.join(root, "**", "INCIDENT*.json"),
                               recursive=True)):
        try:
            with open(p) as f:
                incidents.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    if not boxes and not incidents:
        print(f"perf_report --postmortem: no BLACKBOX.p*.json or "
              f"INCIDENT*.json under {root} — was the gang telemetry "
              f"plane armed (run_gang exports PADDLE_TELEMETRY_DIR)?")
        return 1

    print(f"# gang post-mortem  {root}")
    # who died: the supervisor's incident ledger is authoritative.  Exit
    # 43 (EXIT_PEER_FAILURE) is a survivor REACTING to someone else's
    # death — list it separately so "dead rank(s)" names the rank that
    # actually went down, not everyone its death took with it.
    details = {d["rank"]: d for inc in incidents for d in inc.get("dead", [])}
    reacting = sorted(r for r, d in details.items()
                      if d.get("returncode") == 43)
    dead = sorted(r for r in details if r not in set(reacting)) or reacting
    if details:
        print(f"dead rank(s): {dead} — " + "; ".join(
            f"rank {r}: returncode {details[r]['returncode']}"
            + (" (signaled)" if details[r].get("signaled") else "")
            + (" [classified]" if details[r].get("classified") else "")
            for r in dead))
        if reacting and reacting != dead:
            print(f"peer-failure reactions (exit 43): {reacting}")
    elif boxes:
        suspects = sorted({b.get("rank") for b in boxes
                           if not str(b.get("reason", "")).startswith(
                               ("peer_failure", "sigterm"))})
        if suspects:
            print(f"dead rank suspect(s) from black-box reasons: {suspects}")

    print(f"\n## black boxes ({len(boxes)})")
    rows = [("rank", "reason", "last_step", "records", "path")]
    for b in sorted(boxes, key=lambda b: (b.get("rank", -1), b["_path"])):
        steps = b.get("steps", [])
        last = max((s.get("step", 0) for s in steps
                    if isinstance(s.get("step"), int)), default="-")
        rows.append((b.get("rank", "?"), b.get("reason", "?"), last,
                     len(steps), os.path.relpath(b["_path"], root)))
    print(_fmt_table(rows[1:], list(rows[0])))

    # merged last-N timeline: every rank's ring, one stream, by wall time
    merged = []
    for b in boxes:
        for s in b.get("steps", []):
            if isinstance(s, dict) and s.get("ts") is not None:
                merged.append((float(s["ts"]),
                               s.get("lane", b.get("rank", "?")), s))
    merged.sort(key=lambda t: t[0])
    tail = merged[-last_n:]
    if tail:
        t0 = tail[0][0]
        print(f"\n## merged timeline (last {len(tail)} records across "
              f"ranks; t=0 at {t0:.3f})")
        rows = []
        for ts, rank, s in tail:
            kind = s.get("kind", "step")
            detail = ""
            if kind == "step":
                detail = (f"step {s.get('step')} "
                          f"exec {s.get('t_execute_s', s.get('t_dispatch_s', 0)) * 1e3:.1f}ms")
            elif kind == "dist_event":
                detail = f"{s.get('action')} {s.get('peers', s.get('rank', ''))}"
            elif kind == "pipeline_step":
                detail = f"pstep {s.get('pipeline_step')}"
            else:
                detail = str({k: v for k, v in s.items()
                              if k not in ("kind", "ts", "lane")})[:60]
            rows.append((f"{ts - t0:+8.3f}s", f"r{rank}", kind, detail))
        print(_fmt_table(rows, ["t", "rank", "kind", "detail"]))
    for b in boxes:
        c = b.get("counters", {})
        dist = {k: v for k, v in c.items() if k.startswith("dist.") and v}
        if dist:
            print(f"\nrank {b.get('rank')} dist counters: {dist}")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="snapshot.json (render mode)")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"),
                    help="diff two snapshots")
    ap.add_argument("--check", metavar="METRICS_JSONL",
                    help="CI gate over a MonitorLogger JSONL file")
    ap.add_argument("--postmortem", metavar="TELEMETRY_DIR",
                    help="render a merged gang post-mortem from harvested "
                         "BLACKBOX.p<rank>.json flight-recorder dumps + "
                         "INCIDENT files (paddle_tpu.launch telemetry "
                         "root), naming the dead rank(s) and the last-N-"
                         "steps timeline across ranks")
    ap.add_argument("--postmortem-last-n", type=int, default=30,
                    metavar="N",
                    help="--postmortem: merged-timeline depth (default 30)")
    ap.add_argument("--check-bench", metavar="BENCH_JSON",
                    help="ratcheted bench-round gate (MFU_FLOORS, spread "
                         "ceiling, zero frozen params, overlap A/B) over a "
                         "bench.py JSON line or BENCH_rNN.json wrapper")
    ap.add_argument("--max-spread-pct", type=float, default=None,
                    metavar="PCT",
                    help="--check-bench: override the per-model window-"
                         f"spread ceiling (default {MAX_SPREAD_PCT})")
    ap.add_argument("--min-roofline-frac", type=float, default=None,
                    help="--check-bench: fail any model whose measured MFU "
                         "is below this fraction of its own static roofline "
                         "prediction (mfu_predicted_roofline, stamped by "
                         "bench.py from core/resource_plan.py); without it "
                         "the gap is printed/NOTEd, never averaged away")
    ap.add_argument("--require-overlap", action="store_true",
                    help="--check-bench: fail rounds that do not embed a "
                         "dp_grad_overlap record (fresh-round acceptance)")
    ap.add_argument("--steady-after", type=int, default=2,
                    help="steps to skip before the recompile-flat gate "
                         "(default 2: startup + first real step)")
    ap.add_argument("--max-host-blocked-frac", type=float, default=None,
                    metavar="FRAC",
                    help="additionally gate the pipeline's steady-state "
                         "host-blocked fraction (pipeline_step records from "
                         "paddle_tpu.pipeline.train_loop) at <= FRAC")
    ap.add_argument("--max-retry-frac", type=float, default=None,
                    metavar="FRAC",
                    help="additionally gate recovery events per step "
                         "(resilience_event records from paddle_tpu."
                         "resilience.resilient_train_loop) at <= FRAC")
    ap.add_argument("--max-heartbeat-miss-frac", type=float, default=None,
                    metavar="FRAC",
                    help="gate heartbeat-miss transitions per beat sent "
                         "(dist.heartbeat.* counters from paddle_tpu."
                         "dist_resilience, newest snapshot in the file) "
                         "at <= FRAC")
    ap.add_argument("--max-gang-restarts", type=int, default=None,
                    metavar="N",
                    help="gate gang restarts (paddle_tpu.launch "
                         "gang_restart dist_event records / "
                         "dist.gang_restarts counter) at <= N")
    ap.add_argument("--max-gang-resizes", type=int, default=None,
                    metavar="N",
                    help="gate elastic world-size changes "
                         "(paddle_tpu.launch gang_resize dist_event "
                         "records / dist.gang_resizes counter) at <= N — "
                         "each shrink is capacity lost, each grow an "
                         "interruption of the shrunk gang")
    ap.add_argument("--max-data-corrupt-frac", type=float, default=None,
                    metavar="FRAC",
                    help="gate corrupt RecordIO chunks per chunk scanned "
                         "(data.corrupt_chunks / data.chunks_scanned "
                         "counters, newest snapshot) at <= FRAC")
    ap.add_argument("--max-replay-batches", type=int, default=None,
                    metavar="N",
                    help="gate the resume cost: batches replayed to "
                         "fast-forward a stateless data source "
                         "(replay_fast_forward resilience events) at <= N "
                         "— 0 asserts every source resumes via the O(1) "
                         "stream-state seek")
    ap.add_argument("--max-shed-frac", type=float, default=None,
                    metavar="FRAC",
                    help="gate serving admission-control sheds per "
                         "request offered (serving.shed / "
                         "serving.requests counters, shed serving_event "
                         "records as fallback) at <= FRAC — the overload "
                         "budget a serving round may spend")
    ap.add_argument("--max-p99-ms", type=float, default=None,
                    metavar="MS",
                    help="gate serving p99 request latency "
                         "(serving.p99_ms gauge, serving_batch "
                         "lat_ms_max fallback) at <= MS — the tail SLO "
                         "shedding must hold under overload")
    ap.add_argument("--require-quant-parity", action="store_true",
                    help="require the file to carry at least one "
                         "quant_parity serving_event (the publish "
                         "ladder's accuracy gate over a quantized "
                         "snapshot, paddle_tpu/serving/publisher.py) "
                         "with max_abs_diff within its atol, and no "
                         "quant-parity publish rejection — the "
                         "`bench.py --serve --quant` round's metrics "
                         "gate.  Fails on a file with no quant evidence "
                         "at all (zero evidence must not gate green)")
    ap.add_argument("--max-lock-wait-frac", type=float, default=None,
                    metavar="FRAC",
                    help="gate named-lock contention at <= FRAC: "
                         "wait/(wait+hold) over the lock.* counters "
                         "FLAGS_lock_telemetry records "
                         "(paddle_tpu/core/locks.py).  Fails on a file "
                         "with no lock telemetry at all — zero evidence "
                         "must not gate green")
    ap.add_argument("--max-integrity-mismatches", type=int, default=None,
                    metavar="N",
                    help="gate silent-corruption detections at <= N: "
                         "integrity_event records (live digest "
                         "divergences + at-rest file mismatches; "
                         "walk-back ckpt_rejected echoes render but "
                         "don't double-bill) with integrity.* counter "
                         "fallback (paddle_tpu/integrity.py).  Fails on "
                         "a file with no integrity evidence at all — "
                         "zero evidence must not gate green")
    ap.add_argument("--max-ckpt-lag-steps", type=float, default=None,
                    metavar="N",
                    help="gate the worst checkpoint lag — steps training "
                         "ran past its last committed checkpoint while "
                         "storage failed (storage_degraded / "
                         "ckpt_round_skipped resilience events, "
                         "resilience.ckpt_lag_steps gauge fallback; "
                         "paddle_tpu/checkpoint_manager.py degraded "
                         "mode) — at <= N.  0 asserts every save round "
                         "committed.  Fails on a file with no "
                         "checkpoint-storage evidence at all — zero "
                         "evidence must not gate green")
    ap.add_argument("--max-publish-staleness-steps", type=float,
                    default=None, metavar="N",
                    help="gate the worst publish-to-serving staleness — "
                         "steps training ran past the last snapshot the "
                         "serving tier had (publish_failed resilience "
                         "events' staleness, "
                         "serving.publish_staleness_steps gauge "
                         "fallback; resilient_train_loop's publish hook, "
                         "ISSUE 19) — at <= N.  Fails on a file with no "
                         "publish-cadence evidence at all — zero "
                         "evidence must not gate green")
    ap.add_argument("--max-host-lag-steps", type=float, default=None,
                    metavar="N",
                    help="gate the worst host-tier outage — consecutive "
                         "steps the sparse cold tail trained degraded "
                         "(hot-shard-only) while the parameter server "
                         "was down (host_tier_degraded sparse events, "
                         "sparse.host_lag_steps gauge fallback; "
                         "paddle_tpu/param_server.py degraded mode) — "
                         "at <= N.  Fails on a file with no host-tier "
                         "evidence at all — zero evidence must not gate "
                         "green")
    ap.add_argument("--max-queue-wait-frac", type=float, default=None,
                    metavar="FRAC",
                    help="gate serving latency attribution: the fraction "
                         "of completed requests' wall time spent QUEUED "
                         "(serving_trace span trees from the ISSUE-16 "
                         "request tracing; serving.queue_wait_frac gauge "
                         "and queue_wait_frac-stamped serving_batch "
                         "records as fallbacks) at <= FRAC.  Fails on a "
                         "file with no queue-wait evidence at all — zero "
                         "evidence must not gate green")
    ap.add_argument("--max-pad-frac", type=float, default=None,
                    metavar="FRAC",
                    help="gate pad-to-bucket waste: pad rows per "
                         "padded-batch row (serving.pad_rows / "
                         "(serving.rows + serving.pad_rows) counters, "
                         "serving_batch bucket-vs-rows fallback) at <= "
                         "FRAC — the device compute a serving round may "
                         "spend on rows no client asked for.  Fails on a "
                         "file with no pad evidence at all — zero "
                         "evidence must not gate green")
    ap.add_argument("--min-healthy-replicas", type=float, default=None,
                    metavar="N",
                    help="gate the serving fleet's final health: the "
                         "newest serving.fleet.healthy_replicas gauge "
                         "(ServingFleet router.jsonl snapshots) must be "
                         ">= N.  Fails on a file with no fleet evidence "
                         "at all — zero evidence must not gate green")
    ap.add_argument("--check-roll-convergence", action="store_true",
                    help="require every halted rolling publish to have "
                         "converged: a roll_halted fleet_event with no "
                         "matching roll_rolled_back/roll_converged "
                         "fails (per roll ctl id; counters-only files "
                         "fall back to the events[*] counter balance).  "
                         "Fails on a file with no fleet evidence at all")
    ap.add_argument("--max-step-skew-frac", type=float, default=None,
                    metavar="FRAC",
                    help="gate the MAX sustained straggler lag, in step "
                         "units (straggler dist_event records from the "
                         "live detector, dist.step_skew_frac gauge "
                         "fallback), at <= FRAC.  The live detector only "
                         "emits episodes at lag >= "
                         "FLAGS_dist_straggler_lag_steps (default 1.0), "
                         "so a gate under 1.0 means 'no straggler "
                         "episode at all'; tools/trace_merge.py --check "
                         "shares the flag name but gates the MEAN "
                         "arrival skew per correlated step instead")
    ap.add_argument("--max-chaos-violations", type=int, default=None,
                    metavar="N",
                    help="gate the chaos campaign's invariant violations "
                         "(chaos.invariant_violations counter, failed "
                         "schedule chaos_event records) at <= N.  Fails "
                         "on a file with no chaos evidence at all — zero "
                         "evidence must not gate green")
    args = ap.parse_args(argv)
    if args.postmortem:
        return postmortem(args.postmortem, last_n=args.postmortem_last_n)
    if args.check_bench:
        return check_bench(args.check_bench,
                           min_roofline_frac=args.min_roofline_frac,
                           max_spread_pct=args.max_spread_pct,
                           require_overlap=args.require_overlap)
    if args.check:
        return check(args.check, args.steady_after,
                     args.max_host_blocked_frac, args.max_retry_frac,
                     args.max_heartbeat_miss_frac, args.max_gang_restarts,
                     args.max_data_corrupt_frac, args.max_replay_batches,
                     args.max_step_skew_frac, args.max_gang_resizes,
                     args.max_shed_frac, args.max_p99_ms,
                     args.max_lock_wait_frac,
                     args.max_integrity_mismatches,
                     args.max_ckpt_lag_steps,
                     max_publish_staleness_steps=(
                         args.max_publish_staleness_steps),
                     max_host_lag_steps=args.max_host_lag_steps,
                     max_queue_wait_frac=args.max_queue_wait_frac,
                     max_pad_frac=args.max_pad_frac,
                     require_quant_parity=args.require_quant_parity,
                     min_healthy_replicas=args.min_healthy_replicas,
                     check_roll_convergence=args.check_roll_convergence,
                     max_chaos_violations=args.max_chaos_violations)
    if args.diff:
        print(diff(*args.diff))
        return 0
    if not args.paths:
        ap.print_help()
        return 2
    for p in args.paths:
        print(render(p))
    return 0


if __name__ == "__main__":
    sys.exit(main())
