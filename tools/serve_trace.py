#!/usr/bin/env python
"""Live inspection CLI for the serving request-flight traces (ISSUE 16).

Input is a monitor JSONL metrics stream (MonitorLogger output) from a
serving run with the monitor enabled: `serving_trace` records are the
closed per-request span trees `paddle_tpu/serving/tracing.py` renders
(admission -> queue -> batch_build -> device -> fetch -> respond, plus
the shed/timeout/error/shutdown/rejected early closes), `serving_batch`
/ `serving_event` records and counter snapshots ride along.

    python tools/serve_trace.py metrics.jsonl
        Outcome ledger + the most recent traces, one line each.

    python tools/serve_trace.py metrics.jsonl --request r000042
        Render one request's span tree: where its latency actually went.

    python tools/serve_trace.py metrics.jsonl --top
        Live-table view per model/bucket: traffic, p50/p99, queue-wait
        fraction, pad waste — the "which bucket is burning the SLO"
        table.  Falls back to serving_batch records on a stream whose
        trace ring rotated away.

    python tools/serve_trace.py metrics.jsonl --slow 5
        The N slowest completed requests (the exemplars worth reading).

    python tools/serve_trace.py metrics.jsonl --check \
            [--max-queue-wait-frac F] [--max-pad-frac F]
        CI gate: the trace stream must RECONCILE — every trace closed
        with a terminal outcome, terminal request traces and counted
        terminal outcomes both bounded by serving.requests (the server
        ledger identity, seen from the trace side) — and, when given,
        the queue-wait / pad-waste attribution gates must hold (same
        math as perf_report --check; both FAIL on a file with no
        evidence — the zero-evidence-fails convention).

    python tools/serve_trace.py --fleet FLEET_DIR [--check]
        Fleet view (ISSUE 18): merge the router's ledger stream
        (`telemetry/router.jsonl`) with every replica's per-incarnation
        `metrics.p<rank>.jsonl` (trace_merge's rank-lane pattern) into
        fleet-wide outcome/reason tables, per-replica lanes, and the
        roll episodes (one block per rolling-publish ctl id).  With
        `--check`: the router ledger must reconcile against the SUM of
        the replica ledgers (exact when no replica died; bounded by the
        classified replica_down losses otherwise), every roll_halted
        must have converged (roll_converged or roll_rolled_back), and a
        directory with no evidence at all fails.

`perf_report --check` gates the same stream on counters; this tool is
the per-request view: a failed gate there names a trace id to read here.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import perf_report as _pr  # noqa: E402  (stdlib-only; shares gate math)
import trace_merge as _tm  # noqa: E402  (rank-lane file discovery)

TERMINAL_OUTCOMES = ("completed", "shed", "timeout", "error", "shutdown",
                     "rejected")
# terminal outcomes that entered the server's `requests` ledger —
# "rejected" covers admission-door refusals raised BEFORE the request
# counted, so reconciliation excludes it
LEDGER_OUTCOMES = ("completed", "shed", "timeout", "error", "shutdown")


def load_lines(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def traces_of(lines):
    return [r for r in lines if r.get("kind") == "serving_trace"]


def _fmt_ms(v):
    return f"{float(v):.3f}"


def render_trace(t):
    """One request's span tree, durations bar-scaled against the total."""
    total = float(t.get("total_ms", 0.0) or 0.0)
    head = (f"{t.get('trace_id', '?')}  model={t.get('model', '?')}  "
            f"outcome={t.get('outcome', '?')}")
    if t.get("reason"):
        head += f" ({t['reason']})"
    head += f"  total {_fmt_ms(total)} ms"
    extras = [f"{k}={t[k]}" for k in ("rows", "bucket", "pad_rows",
                                      "deadline_ms", "lat_ms", "late_ms")
              if t.get(k) is not None]
    if extras:
        head += "  [" + " ".join(extras) + "]"
    out = [head]
    for s in t.get("spans", ()):
        dur = float(s.get("dur_ms", 0.0) or 0.0)
        frac = dur / total if total > 0 else 0.0
        bar = "#" * max(int(frac * 40), 1 if dur > 0 else 0)
        out.append(f"  {s.get('name', '?'):<12} {_fmt_ms(dur):>10} ms  "
                   f"{frac * 100:5.1f}%  {bar}")
    return "\n".join(out)


def summary(lines, last_n=10):
    ts = traces_of(lines)
    by = {}
    for t in ts:
        key = (t.get("outcome", "?"), t.get("reason", ""))
        by[key] = by.get(key, 0) + 1
    out = [f"serve_trace: {len(ts)} trace(s)"]
    for (outcome, reason), n in sorted(by.items()):
        out.append(f"  {outcome}{f' ({reason})' if reason else '':<20} {n}")
    c = _pr._latest_counters(lines, "serving.")
    if c:
        out.append(f"  counters: {c.get('serving.requests', 0):g} requests "
                   f"= {c.get('serving.completed', 0):g} completed + "
                   f"{c.get('serving.shed', 0):g} shed + "
                   f"{c.get('serving.timeouts', 0):g} timeouts + "
                   f"{c.get('serving.errors', 0):g} errors + "
                   f"{c.get('serving.shutdowns', 0):g} shutdowns")
    if ts:
        out.append(f"\nmost recent {min(last_n, len(ts))}:")
        for t in ts[-last_n:]:
            out.append(
                f"  {t.get('trace_id', '?'):<10} {t.get('model', '?'):<12} "
                f"{t.get('outcome', '?'):<10} "
                f"{_fmt_ms(t.get('total_ms', 0.0)):>10} ms"
                + (f"  bucket={t['bucket']}" if t.get("bucket") else "")
                + (f"  reason={t['reason']}" if t.get("reason") else ""))
    return "\n".join(out)


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(int(q * len(sorted_vals)), len(sorted_vals) - 1)]


def top_table(lines):
    """Per model/bucket attribution: requests, p50/p99 total latency,
    queue-wait fraction, pad fraction.  Exact from completed traces;
    serving_batch fallback keeps the table usable after ring rotation."""
    rows = {}
    for t in traces_of(lines):
        if t.get("outcome") != "completed":
            continue
        key = (t.get("model", "?"), t.get("bucket", "?"))
        r = rows.setdefault(key, {"n": 0, "tot": [], "q": 0.0, "wall": 0.0,
                                  "pad": 0, "rows": 0})
        r["n"] += 1
        total = float(t.get("total_ms", 0.0) or 0.0)
        r["tot"].append(total)
        r["wall"] += total
        r["q"] += sum(float(s.get("dur_ms", 0.0) or 0.0)
                      for s in t.get("spans", ())
                      if s.get("name") == "queue")
        r["pad"] += int(t.get("pad_rows", 0) or 0)
        r["rows"] += int(t.get("batch_rows", t.get("rows", 0)) or 0)
    src = "traces"
    if not rows:
        src = "serving_batch records"
        for b in lines:
            if b.get("kind") != "serving_batch":
                continue
            key = (b.get("model", "?"), b.get("bucket", "?"))
            r = rows.setdefault(key, {"n": 0, "tot": [], "q": 0.0,
                                      "wall": 0.0, "pad": 0, "rows": 0})
            n = int(b.get("requests", 0) or 0)
            r["n"] += n
            lat = float(b.get("lat_ms_max", 0.0) or 0.0)
            r["tot"].extend([lat] * max(n, 1))
            wall = lat * max(n, 1)
            r["wall"] += wall
            r["q"] += float(b.get("queue_wait_frac", 0.0) or 0.0) * wall
            bkt = int(b.get("bucket", 0) or 0)
            rw = int(b.get("rows", 0) or 0)
            r["pad"] += int(b.get("pad_rows", max(bkt - rw, 0)))
            r["rows"] += rw
    if not rows:
        return "serve_trace --top: no completed traces or serving_batch " \
               "records in the stream"
    table = []
    for (model, bucket), r in sorted(rows.items(),
                                     key=lambda kv: -kv[1]["n"]):
        tot = sorted(r["tot"])
        denom = r["rows"] + r["pad"]
        table.append((model, bucket, r["n"],
                      _fmt_ms(_pct(tot, 0.50)), _fmt_ms(_pct(tot, 0.99)),
                      f"{r['q'] / r['wall']:.3f}" if r["wall"] > 0
                      else "0.000",
                      f"{r['pad'] / denom:.3f}" if denom else "0.000"))
    return (f"serve_trace --top (from {src}):\n"
            + _pr._fmt_table(table, ["model", "bucket", "req", "p50_ms",
                                     "p99_ms", "queue_frac", "pad_frac"]))


def check(path, max_queue_wait_frac=None, max_pad_frac=None):
    """Exit 0 when the trace stream reconciles (and the optional
    attribution gates hold), 1 otherwise."""
    try:
        lines = load_lines(path)
    except FileNotFoundError:
        print(f"serve_trace --check: {path} does not exist "
              f"(was a MonitorLogger attached?)")
        return 1
    except json.JSONDecodeError as e:
        print(f"serve_trace --check: {path} is not valid JSONL: {e}")
        return 1
    ts = traces_of(lines)
    c = _pr._latest_counters(lines, "serving.")
    failures = []
    if not ts and not c:
        failures.append(
            f"{path} carries no serving traces and no serving.* counters "
            f"— was the monitor enabled on the serving run?  (zero "
            f"evidence must not gate green)")
    # 1. every trace must be CLOSED with a stable terminal outcome
    bad = [t.get("trace_id", "?") for t in ts
           if t.get("outcome") not in TERMINAL_OUTCOMES]
    if bad:
        failures.append(
            f"{len(bad)} trace(s) carry no terminal outcome "
            f"({bad[:5]}...) — a serving path closed a trace without an "
            f"outcome, or never closed it")
    # 2. ledger reconciliation, trace side: terminal request traces must
    # not exceed requests admitted (traces may UNDERcount — the ring is
    # bounded and a logger can attach late — but never overcount)
    if c:
        req = c.get("serving.requests", 0)
        parts = sum(c.get(f"serving.{k}", 0) for k in
                    ("completed", "shed", "timeouts", "errors",
                     "shutdowns"))
        if parts > req:
            failures.append(
                f"counter ledger does not reconcile: completed+shed+"
                f"timeouts+errors+shutdowns = {parts:g} exceeds "
                f"serving.requests = {req:g} — a terminal path "
                f"double-counted")
        n_ledger = sum(1 for t in ts
                       if t.get("outcome") in LEDGER_OUTCOMES)
        if n_ledger > req:
            failures.append(
                f"{n_ledger} ledger-outcome trace(s) exceed "
                f"serving.requests = {req:g} — a request closed more "
                f"than one trace")
        print(f"serve_trace --check: {len(ts)} trace(s), "
              f"{n_ledger} in-ledger vs {req:g} requests "
              f"({parts:g} terminal outcomes counted)")
    elif ts:
        print(f"serve_trace --check: {len(ts)} trace(s), no counter "
              f"snapshot to reconcile against")
    if max_queue_wait_frac is not None:
        if not _pr._has_queue_wait_evidence(lines):
            failures.append(
                f"--max-queue-wait-frac given but {path} carries no "
                f"queue-wait evidence (zero evidence must not gate green)")
        else:
            frac = _pr.queue_wait_fraction(lines)
            if frac > max_queue_wait_frac:
                failures.append(
                    f"queue-wait fraction {frac:.4f} exceeds "
                    f"--max-queue-wait-frac={max_queue_wait_frac} — see "
                    f"--top for the offending model/bucket")
            else:
                print(f"serve_trace --check: queue-wait fraction "
                      f"{frac:.4f} <= {max_queue_wait_frac}")
    if max_pad_frac is not None:
        if not _pr._has_pad_evidence(lines):
            failures.append(
                f"--max-pad-frac given but {path} carries no pad "
                f"evidence (zero evidence must not gate green)")
        else:
            frac = _pr.pad_fraction(lines)
            if frac > max_pad_frac:
                failures.append(
                    f"pad fraction {frac:.4f} exceeds "
                    f"--max-pad-frac={max_pad_frac} — the bucket ladder "
                    f"is too coarse for the traffic (see --top)")
            else:
                print(f"serve_trace --check: pad fraction {frac:.4f} <= "
                      f"{max_pad_frac}")
    if failures:
        for f_ in failures:
            print(f"serve_trace --check: {f_}")
        return 1
    print("serve_trace --check: OK")
    return 0


# ---- fleet view (ISSUE 18) --------------------------------------------------

def _fleet_telemetry_dir(path):
    """Accept the fleet root or its telemetry dir interchangeably."""
    if os.path.isdir(os.path.join(path, "telemetry")):
        return os.path.join(path, "telemetry")
    return path


def load_fleet(path):
    """Collect the fleet's streams: router ledger lines + per-replica
    metrics files (every incarnation, rank-keyed)."""
    tel = _fleet_telemetry_dir(path)
    router_path = os.path.join(tel, "router.jsonl")
    router_lines = []
    if os.path.exists(router_path):
        router_lines = _tm.load_records([router_path])
    ranks = _tm.find_rank_files(tel)["metrics"]
    replicas = {r: [(p, _tm.load_records([p])) for p in paths]
                for r, paths in sorted(ranks.items())}
    return {"dir": tel, "router": router_lines, "replicas": replicas}


def _fleet_events(router_lines):
    return [r for r in router_lines if r.get("kind") == "fleet_event"]


def _router_counters(router_lines):
    return _pr._latest_counters(router_lines, "serving.fleet.")


def _replica_ledgers(replicas):
    """Newest serving.* counter snapshot per metrics FILE (one file = one
    process incarnation; counters reset at restart, so summing the
    newest snapshot of every file is the fleet-wide total)."""
    out = {}
    for rank, files in replicas.items():
        rows = []
        for path, lines in files:
            c = _pr._latest_counters(lines, "serving.")
            rows.append((path, c, len(traces_of(lines))))
        out[rank] = rows
    return out


def _roll_episodes(events):
    """Group fleet_event records by roll ctl id, in stream order."""
    rolls = {}
    for e in events:
        ctl = e.get("ctl")
        if not ctl:
            continue
        rolls.setdefault(ctl, []).append(e)
    return rolls


def fleet_summary(fl, last_n=10):
    out = [f"serve_trace --fleet: {fl['dir']}"]
    c = _router_counters(fl["router"])
    if c:
        out.append(
            f"  router ledger: {c.get('serving.fleet.requests', 0):g} "
            f"requests = {c.get('serving.fleet.completed', 0):g} completed "
            f"+ {c.get('serving.fleet.errors', 0):g} classified errors "
            f"({c.get('serving.fleet.retries', 0):g} transparent retries)")
        reasons = sorted((k[len("serving.fleet.errors["):-1], v)
                         for k, v in c.items()
                         if k.startswith("serving.fleet.errors[") and v)
        for reason, n in reasons:
            out.append(f"    reason {reason:<18} {n:g}")
    else:
        out.append("  router ledger: no serving.fleet.* snapshot")
    # per-replica lanes: one line per incarnation, newest ledger each
    out.append("  replica lanes:")
    for rank, rows in _replica_ledgers(fl["replicas"]).items():
        for path, counters, n_traces in rows:
            inc = _tm._incarnation_of(path)
            out.append(
                f"    rank {rank} i{inc}: "
                f"{counters.get('serving.requests', 0):g} requests, "
                f"{counters.get('serving.completed', 0):g} completed, "
                f"{counters.get('serving.shed', 0):g} shed, "
                f"{counters.get('serving.errors', 0):g} errors, "
                f"{n_traces} trace(s)")
    if not fl["replicas"]:
        out.append("    (no replica metrics files)")
    events = _fleet_events(fl["router"])
    rolls = _roll_episodes(events)
    if rolls:
        out.append("  roll episodes:")
        for ctl, evs in rolls.items():
            steps = " -> ".join(
                e["action"] + (f"(r{e['rank']})" if "rank" in e else "")
                for e in evs)
            out.append(f"    {ctl}: {steps}")
    life = [e for e in events if not e.get("ctl")]
    if life:
        out.append(f"  lifecycle (last {min(last_n, len(life))}):")
        for e in life[-last_n:]:
            extra = {k: v for k, v in e.items()
                     if k not in ("kind", "action", "ts")}
            out.append(f"    {e['action']:<20} {extra}")
    return "\n".join(out)


def _sparse_digest_events(fl):
    """Every serving_event carrying a sparse_digest, across the router
    stream and every replica incarnation: the publisher stamps the
    digest it verified (`publish`/`publish_staged`), every loader stamps
    what it actually materialized (`load`/`activate_staged`)."""
    evs = []
    streams = [("router", fl["router"])]
    for rank, files in fl["replicas"].items():
        for _path, lines in files:
            streams.append((f"rank {rank}", lines))
    for who, lines in streams:
        for r in lines:
            if r.get("kind") == "serving_event" and r.get("sparse_digest"):
                evs.append((who, r))
    return evs


def fleet_check(path):
    """Exit 0 when the fleet's ledgers reconcile and every halted roll
    converged; 1 otherwise (zero evidence fails)."""
    fl = load_fleet(path)
    failures = []
    c = _router_counters(fl["router"])
    events = _fleet_events(fl["router"])
    if not c and not fl["replicas"]:
        failures.append(
            f"{fl['dir']} carries no router snapshot and no replica "
            f"metrics — was this a fleet telemetry dir?  (zero evidence "
            f"must not gate green)")
    if not c and fl["router"]:
        failures.append(
            "router.jsonl carries records but no serving.fleet.* counter "
            "snapshot — the supervisor's snapshot loop never ran")
    deaths = [e for e in events if e.get("action") == "replica_dead"]
    if c:
        req = c.get("serving.fleet.requests", 0)
        comp = c.get("serving.fleet.completed", 0)
        errs = c.get("serving.fleet.errors", 0)
        if comp + errs > req:
            failures.append(
                f"router ledger does not reconcile: completed+errors = "
                f"{comp + errs:g} exceeds requests = {req:g}")
        down = c.get("serving.fleet.errors[replica_down]", 0)
        led = _replica_ledgers(fl["replicas"])
        rep_comp = sum(counters.get("serving.completed", 0)
                       for rows in led.values()
                       for _p, counters, _t in rows)
        # a replica can complete a request whose reply the router lost
        # (counted replica_down router-side) but never the reverse
        if rep_comp > comp + down:
            failures.append(
                f"replica ledgers overcount: sum(replica completed) = "
                f"{rep_comp:g} exceeds router completed + replica_down "
                f"losses = {comp + down:g}")
        if not deaths and rep_comp < comp:
            failures.append(
                f"replica ledgers undercount with no replica death on "
                f"record: sum(replica completed) = {rep_comp:g} < router "
                f"completed = {comp:g} — a replica's final snapshot is "
                f"missing")
        print(f"serve_trace --fleet --check: router {req:g} requests = "
              f"{comp:g} completed + {errs:g} errors; replicas sum "
              f"{rep_comp:g} completed across "
              f"{sum(len(r) for r in led.values())} incarnation ledger(s)"
              f"{f'; {len(deaths)} replica death(s)' if deaths else ''}")
    # every halted roll must converge (same invariant perf_report gates)
    for ctl, evs in _roll_episodes(events).items():
        actions = [e["action"] for e in evs]
        if "roll_halted" in actions and not (
                "roll_rolled_back" in actions or "roll_converged" in actions):
            failures.append(
                f"roll {ctl} halted without converging (no "
                f"roll_rolled_back/roll_converged event) — the fleet may "
                f"be split-brained between versions")
    # sparse snapshot reconcile (ISSUE 19): every stream that touched a
    # published sparse snapshot stamped a content digest — the publisher
    # at verify time, every replica at load/activate time.  One src with
    # two digests means some process served DIFFERENT sparse bytes than
    # were verified: a torn publish, a rotted store copy, or a
    # half-written snapshot a replica picked up mid-copy.
    by_src = {}
    for who, e in _sparse_digest_events(fl):
        src = e.get("src")
        if not src:
            continue
        by_src.setdefault(src, {}).setdefault(
            e["sparse_digest"], []).append((who, e.get("action")))
    for src, digs in sorted(by_src.items()):
        if len(digs) > 1:
            sides = "; ".join(
                f"{d[:12]}… from " + ", ".join(
                    sorted({f"{w}:{a}" for w, a in whos}))
                for d, whos in sorted(digs.items()))
            failures.append(
                f"sparse snapshot digests disagree for {src}: {sides} — "
                f"a replica loaded different sparse bytes than were "
                f"published (torn publish / rotted store copy)")
    if by_src:
        print(f"serve_trace --fleet --check: {len(by_src)} sparse "
              f"snapshot(s) digest-reconciled across publisher and "
              f"loaders")
    if failures:
        for f_ in failures:
            print(f"serve_trace --fleet --check: {f_}")
        return 1
    print("serve_trace --fleet --check: OK")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="inspect serving request-flight traces "
                    "(serving_trace records in a monitor JSONL stream)")
    ap.add_argument("path", help="metrics JSONL stream (MonitorLogger "
                                 "output) from a serving run; with "
                                 "--fleet, a fleet root or telemetry dir")
    ap.add_argument("--fleet", action="store_true",
                    help="treat PATH as a fleet telemetry dir: merged "
                         "router + per-replica view (with --check: "
                         "ledger reconciliation + roll convergence)")
    ap.add_argument("--request", metavar="TRACE_ID",
                    help="render one request's span tree")
    ap.add_argument("--top", action="store_true",
                    help="per model/bucket attribution table")
    ap.add_argument("--slow", type=int, metavar="N", default=None,
                    help="render the N slowest completed requests")
    ap.add_argument("--last", type=int, metavar="N", default=10,
                    help="recent traces shown by the default summary")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: trace-stream reconciliation (+ the "
                         "attribution gates below when given)")
    ap.add_argument("--max-queue-wait-frac", type=float, default=None,
                    metavar="FRAC",
                    help="with --check: gate the completed-request "
                         "queue-wait fraction at <= FRAC")
    ap.add_argument("--max-pad-frac", type=float, default=None,
                    metavar="FRAC",
                    help="with --check: gate pad rows per padded row at "
                         "<= FRAC")
    args = ap.parse_args(argv)
    if args.fleet:
        if not os.path.isdir(args.path):
            print(f"serve_trace --fleet: {args.path} is not a directory")
            return 1
        if args.check:
            return fleet_check(args.path)
        print(fleet_summary(load_fleet(args.path), last_n=args.last))
        return 0
    if args.check:
        return check(args.path, args.max_queue_wait_frac, args.max_pad_frac)
    try:
        lines = load_lines(args.path)
    except FileNotFoundError:
        print(f"serve_trace: {args.path} does not exist")
        return 1
    except json.JSONDecodeError as e:
        print(f"serve_trace: {args.path} is not valid JSONL: {e}")
        return 1
    if args.request:
        hits = [t for t in traces_of(lines)
                if t.get("trace_id") == args.request]
        if not hits:
            print(f"serve_trace: no trace {args.request!r} in {args.path} "
                  f"(the ring is bounded — old traces rotate out)")
            return 1
        for t in hits:
            print(render_trace(t))
        return 0
    if args.top:
        print(top_table(lines))
        return 0
    if args.slow is not None:
        done = sorted((t for t in traces_of(lines)
                       if t.get("outcome") == "completed"),
                      key=lambda t: -float(t.get("total_ms", 0.0) or 0.0))
        if not done:
            print("serve_trace: no completed traces in the stream")
            return 1
        for t in done[:args.slow]:
            print(render_trace(t))
            print()
        return 0
    print(summary(lines, last_n=args.last))
    return 0


if __name__ == "__main__":
    sys.exit(main())
