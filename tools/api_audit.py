"""Audit the public API against the reference's API.spec (VERDICT r3 #6).

For every entry in /root/reference/paddle/fluid/API.spec (936 lines), the
name `paddle.fluid.X.y` must either RESOLVE on `paddle_tpu` (getattr chain —
this counts inherited methods the spec-dump tool doesn't enumerate) or be
RECORDED with a one-line rationale in API_DEVIATIONS.md.

Run:  python tools/api_audit.py           # print unresolved, unrecorded
      python tools/api_audit.py --counts  # summary numbers
The gate test (tests/test_api_audit.py) asserts the unrecorded set is empty.
"""
from __future__ import annotations

import os
import re
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_SPEC = "/root/reference/paddle/fluid/API.spec"
DEVIATIONS = os.path.join(REPO, "API_DEVIATIONS.md")


def reference_entries():
    names = []
    with open(REF_SPEC) as f:
        for line in f:
            name = line.split(" ")[0].strip()
            if name.startswith("paddle.fluid."):
                names.append(name[len("paddle.fluid."):])
            elif name == "paddle.fluid":
                continue
    return sorted(set(names))


def resolves(name: str) -> bool:
    import paddle_tpu

    obj = paddle_tpu
    for part in name.split("."):
        try:
            obj = getattr(obj, part)
        except AttributeError:
            return False
    return True


def recorded_deviations():
    """Entries claimed in API_DEVIATIONS.md: `code`-quoted names in the
    subject part of a bullet (before the em-dash rationale); prose mentions
    inside rationales don't count."""
    if not os.path.exists(DEVIATIONS):
        return set()
    out = set()
    in_subject = False
    for line in open(DEVIATIONS):
        if line.startswith("- "):
            in_subject = True
        elif not line.startswith("  "):
            in_subject = False
        if not in_subject:
            continue
        had_dash = "\u2014" in line
        subject = line.split("\u2014")[0]
        for m in re.finditer(r"`([A-Za-z_][\w.]*)`", subject):
            out.add(m.group(1))
        if had_dash:
            in_subject = False
    return out


def audit():
    entries = reference_entries()
    recorded = recorded_deviations()
    resolved, recorded_hits, unrecorded = [], [], []
    for name in entries:
        if resolves(name):
            resolved.append(name)
        elif name in recorded or any(
            name == r or name.startswith(r + ".") for r in recorded
        ):
            recorded_hits.append(name)
        else:
            unrecorded.append(name)
    return resolved, recorded_hits, unrecorded


def main():
    resolved, recorded, unrecorded = audit()
    total = len(resolved) + len(recorded) + len(unrecorded)
    if "--counts" in sys.argv:
        print(f"reference entries: {total}")
        print(f"resolved on paddle_tpu: {len(resolved)}")
        print(f"recorded in API_DEVIATIONS.md: {len(recorded)}")
        print(f"UNRECORDED (gate fails): {len(unrecorded)}")
        return
    for name in unrecorded:
        print(name)


if __name__ == "__main__":
    main()
