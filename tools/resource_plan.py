#!/usr/bin/env python
"""Render / CI-gate static resource plans (paddle_tpu/core/resource_plan.py).

    python tools/resource_plan.py
        Plan every model-zoo program (mnist, resnet50, bert, nmt, deepfm —
        the donation-audit zoo) at CI-size configs: per-program peak-HBM
        estimate with the watermark ops at the peak, FLOPs/traffic roll-up,
        analytic roofline step time, and predicted MFU.

    python tools/resource_plan.py --calibrate
        Additionally compile each zoo step (CPU XLA) and compare the plan's
        peak against measured truth: the executable's own buffer assignment
        (memory_analysis: arguments + outputs + temps - aliased) — or, when
        the attached device exposes allocator stats (TPU), the memstats
        `device_bytes_in_use` high-water around a real run.

    python tools/resource_plan.py --check [--min-coverage F]
        CI gate (tier-1 via tests/test_resource_plan.py): exit 1 when
          * any zoo program fails to plan, or
          * cost-rule coverage over the zoo drops below the floor
            (ratchet: raise, never lower), or
          * calibration drifts outside [CALIBRATION_RATIO_LO,
            CALIBRATION_RATIO_HI] on any zoo program (the stated-tolerance
            contract from docs/static_analysis.md — also a ratchet).

    python tools/resource_plan.py --bench BENCH_rNN.json
        Predicted-vs-measured roofline: for every model record carrying
        mfu_bf16_analytic, print measured MFU next to the program's own
        static roofline prediction and the fraction achieved.  A BENCH
        file with NO model records fails loudly (zero-evidence files must
        not gate green — the PR-8/PR-10 hardening precedent).

Exit codes: 0 clean, 1 gate failure / zero evidence.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Cost-rule coverage floor over the zoo's op types (the ratchet: landed
# coverage is 1.0; never lower).
COST_COVERAGE_FLOOR = 1.0

# Calibration contract: plan peak / measured peak must stay inside this
# band on every zoo program (measured r12: 0.89..1.41 on CPU XLA buffer
# assignment).  The band is the ratchet — tighten as the model improves,
# never widen.
CALIBRATION_RATIO_LO = 0.6
CALIBRATION_RATIO_HI = 2.0


def _fmt_table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def zoo_plans(tiny=True, only=None):
    """[(name, program, plan)] over the donation-audit zoo (main programs
    at their example feed shapes)."""
    from tools.donation_audit import build_zoo

    from paddle_tpu.core import resource_plan as rp

    out = []
    for name, main, startup, feed, fetches in build_zoo(tiny=tiny, only=only):
        feed_shapes = {n: tuple(v.shape) for n, v in feed.items()}
        plan = rp.plan_program(main, feed_shapes, fetches)
        out.append((name, main, plan))
    return out


def measured_peak_bytes(name, tiny=True):
    """Measured truth for one zoo program's step: prefer the live
    allocator high-water (device_bytes_in_use around a real run) when the
    backend exposes it; else the compiled executable's XLA buffer
    assignment (arguments + outputs + temps - aliased)."""
    import math

    import jax
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.dtypes import as_np_dtype
    from paddle_tpu.core.executor import _CompiledStep
    from paddle_tpu.core.scope import RNG_STATE_VAR
    from paddle_tpu.monitor import memstats
    from paddle_tpu.ops.common import canon_dtype
    from tools.donation_audit import build_zoo

    (_, main, startup, feed, fetches), = build_zoo(tiny=tiny, only=name)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    block = main.global_block()
    jfeed = {}
    for n, v in feed.items():
        arr = np.asarray(v)
        if block.has_var(n):
            want = as_np_dtype(block.var(n).dtype)
            if want is not None and arr.dtype != want:
                arr = arr.astype(want)
        c = canon_dtype(arr.dtype)
        if arr.dtype != c:
            arr = arr.astype(c)
        jfeed[n] = arr
    compiled = _CompiledStep(main, list(jfeed), list(fetches), scope,
                             platform="cpu",
                             feed_shapes={n: v.shape for n, v in jfeed.items()})
    srw = {n: scope.find_var(n) for n in compiled.rw_names}
    sro = {n: scope.find_var(n) for n in compiled.ro_names}
    key = scope.find_var(RNG_STATE_VAR)
    if key is None:
        key = jax.random.PRNGKey(main.random_seed or 0)
    built = compiled.jfn.trace(srw, sro, jfeed, key).lower().compile()
    live = memstats.device_bytes_in_use()
    if not math.isnan(live):
        base = live
        out = built(dict(srw), sro, jfeed, key)
        jax.block_until_ready(out)
        high = memstats.device_bytes_in_use()
        if not math.isnan(high) and high > base:
            return int(high), "device_bytes_in_use"
    ma = built.memory_analysis()
    measured = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
    return int(measured), "xla_buffer_assignment"


def render(tiny=True, only=None, calibrate=False):
    """(text, results) — results: {name: {plan..., ratio?...}}."""
    from paddle_tpu.core import resource_plan as rp

    plans = zoo_plans(tiny=tiny, only=only)
    rows = []
    results = {}
    for name, _, plan in plans:
        rows.append((name, f"{plan.peak_bytes / 1e6:.2f}",
                     f"{plan.persistable_bytes / 1e6:.2f}",
                     f"{plan.feed_bytes / 1e6:.2f}",
                     f"{plan.peak_temp_bytes / 1e6:.2f}",
                     f"#{plan.peak_op_idx}({plan.peak_op_type})",
                     f"{plan.roofline_step_s * 1e3:.3f}",
                     f"{plan.predicted_mfu:.3f}"))
        results[name] = {"plan": plan.to_dict()}
    parts = ["# resource plans  (zoo, %s configs)" % ("tiny" if tiny else "full"),
             "", _fmt_table(rows, ["program", "peak_MB", "persistable_MB",
                                   "feed_MB", "live_temp_MB", "peak_op",
                                   "roofline_ms", "pred_MFU"])]
    parts.append("\n## peak attribution (watermark ops)")
    for name, _, plan in plans:
        parts.append(f"- {name}: " + "; ".join(plan.watermark_ops()[:4]))
    cov = rp.cost_coverage([p for _, p, _ in plans])
    parts.append(f"\n## cost-rule coverage\nop types covered: "
                 f"{len(cov['covered_types'])} / "
                 f"{len(cov['covered_types']) + len(cov['missing_types'])} "
                 f"(frac {cov['frac']:.3f})")
    if cov["missing_types"]:
        parts.append("missing cost rules (default 1-flop/elem model used): "
                     + ", ".join(cov["missing_types"]))
    results["_coverage"] = cov
    if calibrate:
        parts.append("\n## calibration (plan peak vs measured)")
        crows = []
        for name, _, plan in plans:
            measured, how = measured_peak_bytes(name, tiny=tiny)
            ratio = plan.peak_bytes / measured if measured else float("inf")
            ok = CALIBRATION_RATIO_LO <= ratio <= CALIBRATION_RATIO_HI
            crows.append((name, f"{plan.peak_bytes / 1e6:.2f}",
                          f"{measured / 1e6:.2f}", f"{ratio:.3f}",
                          how, "OK" if ok else "DRIFT"))
            results[name]["measured_bytes"] = measured
            results[name]["ratio"] = ratio
            results[name]["calibration_ok"] = ok
        parts.append(_fmt_table(crows, ["program", "plan_MB", "measured_MB",
                                        "ratio", "truth", "verdict"]))
        parts.append(f"tolerance band: [{CALIBRATION_RATIO_LO}, "
                     f"{CALIBRATION_RATIO_HI}] (the ratchet)")
    return "\n".join(parts), results


def _bench_measured_mfu(bench_path):
    """{model: measured mfu_bf16_analytic} from a BENCH round file — the
    measured side of the gap ranking's time scaling.  Per-op timers don't
    exist off-device, so each program's static per-op roofline is scaled
    by the program-level measured/predicted ratio instead; that keeps the
    ranking evidence-based without pretending to per-op truth."""
    from tools.perf_report import _bench_records

    measured = {}
    try:
        recs = _bench_records(bench_path)
    except (FileNotFoundError, ValueError, json.JSONDecodeError) as e:
        print(f"resource_plan --gap-rank: cannot read {bench_path}: {e}",
              file=sys.stderr)
        return {}
    for model, rec in recs.items():
        if isinstance(rec, dict) and rec.get("mfu_bf16_analytic"):
            measured[model] = rec["mfu_bf16_analytic"]
    return measured


def gap_rank(tiny=True, only=None, bench=None):
    """(text, data) — rank op types by roofline-gap x estimated time over
    the zoo.  Per op row the roofline time is max(t_flops, t_traffic)
    (core/resource_plan.py's own formula); the GAP is the traffic-bound
    fraction 1 - t_flops/t_roof — the share of the op's time the compute
    units sit idle waiting on HBM, exactly what kernel fusion and narrower
    dtypes recover (Williams et al.).  Estimated time scales each
    program's roofline by its measured/predicted MFU ratio when a --bench
    round supplies one.  data: {"ranking": [...], "uncovered_rows": N,
    "total_rows": N, "bench": path|None, "programs": [...]}"""
    from paddle_tpu.core import resource_plan as rp

    measured = _bench_measured_mfu(bench) if bench else {}
    plans = zoo_plans(tiny=tiny, only=only)
    agg = {}          # op_type -> aggregate dict
    scales = {}       # model -> predicted/measured MFU ratio actually used
    uncovered = 0
    total_rows = 0
    for name, _, plan in plans:
        # measured step time = roofline time * (predicted / measured MFU);
        # the prediction is the plan's own (same formula as the per-op rows)
        scale = 1.0
        if measured.get(name) and plan.predicted_mfu:
            scale = plan.predicted_mfu / measured[name]
            scales[name] = round(scale, 4)
        for r in plan.rows:
            total_rows += 1
            t_flops = r.flops * r.grad_factor / rp.CHIP_PEAK_FLOPS
            t_traffic = (r.traffic_bytes * r.grad_factor
                         / rp.CHIP_HBM_BANDWIDTH)
            t_roof = max(t_flops, t_traffic)
            gap_frac = (1.0 - t_flops / t_roof) if t_roof > 0 else 0.0
            t_est = t_roof * scale
            a = agg.setdefault(r.op_type, {
                "op_type": r.op_type, "count": 0, "time_s": 0.0,
                "gap_time_s": 0.0, "uncovered": 0, "programs": set()})
            a["count"] += 1
            a["time_s"] += t_est
            a["gap_time_s"] += gap_frac * t_est
            a["programs"].add(name)
            if not r.cost_covered:
                a["uncovered"] += 1
                uncovered += 1
    ranking = sorted(agg.values(), key=lambda a: -a["gap_time_s"])
    total_time = sum(a["time_s"] for a in ranking) or 1.0
    rows = []
    for a in ranking:
        a["programs"] = sorted(a["programs"])
        a["gap_frac"] = a["gap_time_s"] / a["time_s"] if a["time_s"] else 0.0
        a["time_share"] = a["time_s"] / total_time
        rows.append((a["op_type"], a["count"],
                     f"{a['gap_time_s'] * 1e6:.1f}",
                     f"{a['gap_frac']:.2f}",
                     f"{a['time_share']:.3f}",
                     ",".join(a["programs"]),
                     a["uncovered"] or ""))
    parts = ["# roofline gap ranking  (zoo, %s configs%s)"
             % ("tiny" if tiny else "full",
                f", scaled by {os.path.basename(bench)}" if bench else
                ", unscaled roofline"),
             "",
             "score = traffic-bound fraction x estimated op time, summed "
             "over every zoo step.",
             "The top of this table is where the next fused kernel or "
             "narrower dtype pays.",
             "",
             _fmt_table(rows, ["op_type", "rows", "gap_us", "gap_frac",
                               "time_share", "programs", "uncov"])]
    if scales:
        parts.append("\ntime scaling (predicted/measured MFU): "
                     + ", ".join(f"{m}={s:.2f}"
                                 for m, s in sorted(scales.items())))
    elif bench:
        parts.append("\nWARNING: --bench file supplied but carried no "
                     "usable measured MFU — ranking is unscaled roofline "
                     "only")
    data = {"ranking": [{k: v for k, v in a.items()} for a in ranking],
            "uncovered_rows": uncovered, "total_rows": total_rows,
            "bench": bench,
            "bench_scales": scales,
            "programs": sorted({n for n, _, _ in plans})}
    return "\n".join(parts), data


def check_bench(path) -> int:
    """Predicted-vs-measured roofline over a BENCH round file.  Uses
    perf_report's record reader; a file with zero model records FAILS
    (zero evidence must not gate green)."""
    from tools.perf_report import _bench_records

    try:
        recs = _bench_records(path)
    except (FileNotFoundError, ValueError, json.JSONDecodeError) as e:
        print(f"resource_plan --bench: cannot read {path}: {e}")
        return 1
    rows = []
    for model, rec in sorted(recs.items()):
        if not isinstance(rec, dict):
            continue
        mfu = rec.get("mfu_bf16_analytic")
        pred = rec.get("mfu_predicted_roofline")
        if mfu is None:
            continue
        frac = (mfu / pred) if pred else None
        rows.append((model, mfu, pred if pred is not None else "-",
                     f"{frac:.2f}" if frac is not None else "-"))
    if not rows:
        print(f"resource_plan --bench: {path} carries no model records with "
              f"measured MFU — zero evidence, failing (embed bench.py model "
              f"records, which stamp mfu_predicted_roofline)")
        return 1
    print(_fmt_table(rows, ["model", "measured_MFU", "predicted_roofline_MFU",
                            "achieved_frac"]))
    for model, mfu, pred, frac in rows:
        if frac != "-" and float(frac) < 0.1:
            print(f"NOTE: {model} runs at {frac} of its own static roofline "
                  f"— the compiled step leaves large factors on the table "
                  f"(kernel fusion / layout / overlap), not the hardware")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--check", action="store_true",
                    help="CI gate: plans build, coverage >= floor, "
                         "calibration inside the tolerance band")
    ap.add_argument("--calibrate", action="store_true",
                    help="compare plan peaks against measured truth")
    ap.add_argument("--full", action="store_true",
                    help="full-size model configs (default: CI-size tiny)")
    ap.add_argument("--program", default=None,
                    help="plan one zoo program (mnist|resnet50|bert|nmt|deepfm)")
    ap.add_argument("--bench", default=None, metavar="BENCH.json",
                    help="predicted-vs-measured roofline over a bench round "
                         "(with --gap-rank: scale op times by each model's "
                         "measured/predicted MFU ratio)")
    ap.add_argument("--gap-rank", action="store_true",
                    help="rank op types by roofline-gap x time across the "
                         "zoo (with --check: gate on zero uncovered cost "
                         "rows; zero rows = zero evidence = FAIL)")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="with --gap-rank: also write the rendered ranking "
                         "to PATH (the committed artifact)")
    ap.add_argument("--min-coverage", type=float, default=COST_COVERAGE_FLOOR,
                    help=f"cost-rule coverage floor for --check "
                         f"(default {COST_COVERAGE_FLOOR})")
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if args.gap_rank:
        try:
            text, data = gap_rank(tiny=not args.full, only=args.program,
                                  bench=args.bench)
        except Exception as e:
            print(f"resource_plan --gap-rank: ranking FAILED: "
                  f"{type(e).__name__}: {e}")
            return 1
        if args.json:
            print(json.dumps(data, default=str))
        else:
            print(text)
        if args.out:
            with open(args.out, "w") as f:
                f.write(text + "\n")
            print(f"\nwrote {args.out}")
        if args.check:
            if data["total_rows"] == 0:
                print("\nCHECK FAILED: gap ranking rendered zero cost rows "
                      "— zero evidence must not gate green")
                return 1
            if data["uncovered_rows"]:
                bad = [a["op_type"] for a in data["ranking"]
                       if a["uncovered"]]
                print(f"\nCHECK FAILED: {data['uncovered_rows']} cost rows "
                      f"over the zoo use the default 1-flop/elem model "
                      f"(op types: {', '.join(bad)}) — the ranking cannot "
                      f"be trusted with uncovered rows in it")
                return 1
            print(f"\nCHECK OK: {data['total_rows']} cost rows ranked, "
                  f"zero uncovered")
        return 0
    if args.bench:
        return check_bench(args.bench)
    # NOTE: no persistent XLA compile cache here, deliberately — a
    # cache-deserialized executable's memory_analysis() loses alias_size
    # (donation), which silently inflates the calibration's "measured"
    # side (found when a cached run drifted nmt to ratio 0.57)

    try:
        text, results = render(tiny=not args.full, only=args.program,
                               calibrate=args.calibrate or args.check)
    except Exception as e:
        print(f"resource_plan: planning the zoo FAILED: {type(e).__name__}: {e}")
        return 1
    if args.json:
        print(json.dumps(results, default=str))
    else:
        print(text)

    if args.check:
        failed = False
        cov = results["_coverage"]
        if cov["frac"] < args.min_coverage:
            print(f"\nCHECK FAILED: cost-rule coverage {cov['frac']:.3f} < "
                  f"floor {args.min_coverage} (missing: "
                  f"{cov['missing_types']})")
            failed = True
        for name, r in results.items():
            if name.startswith("_"):
                continue
            if "calibration_ok" in r and not r["calibration_ok"]:
                print(f"\nCHECK FAILED: {name} plan/measured ratio "
                      f"{r['ratio']:.3f} outside "
                      f"[{CALIBRATION_RATIO_LO}, {CALIBRATION_RATIO_HI}] — "
                      f"the planner's liveness or cost model drifted from "
                      f"XLA's buffer assignment")
                failed = True
        if failed:
            return 1
        print(f"\nCHECK OK: {len([k for k in results if not k.startswith('_')])} "
              f"zoo plans clean, coverage {cov['frac']:.3f} >= "
              f"{args.min_coverage}, calibration inside "
              f"[{CALIBRATION_RATIO_LO}, {CALIBRATION_RATIO_HI}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
