"""ICI collective micro-benchmark (BASELINE.md last row: achieved allreduce
bandwidth vs roofline; reference shape: benchmark/fluid/fluid_benchmark.py
multi-GPU modes measuring NCCL throughput).

Sweeps psum / all_gather / reduce_scatter / ppermute over a jax.sharding
Mesh across a range of payload sizes, timing K chained collectives per
dispatch (one device sync at the end), and reports achieved algorithmic
bandwidth per chip:

  allreduce:      algo_bytes = 2 * (n-1)/n * payload   (ring)
  all_gather:     algo_bytes = (n-1)/n * result
  reduce_scatter: algo_bytes = (n-1)/n * payload
  ppermute:       algo_bytes = payload                 (one hop)

vs_roofline uses --ici-gbps (per-direction per-link; v5e ICI ~ 186 GB/s
bidirectional over 2 links -> pass the datasheet number for the target
topology).  On the 8-device virtual CPU mesh the absolute numbers are
host-memcpy speeds — the point there is validating the harness end to end
(tests/test_collective_bench.py + the dryrun), so the day multi-chip
hardware exists this file is the measurement, not a TODO.

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/collective_bench.py --sizes-mb 1,8 --iters 3
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _mesh(n=None):
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n or len(devs)
    return Mesh(np.array(devs[:n]), ("x",))


def bench_collective(kind, size_mb, mesh, iters=4, chain=8, dtype="float32"):
    """One (collective, size) point: per-chip payload `size_mb`, `chain`
    dependent collectives per dispatch."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.core.jax_compat import shard_map as _shard_map

    n = mesh.devices.size
    elems = int(size_mb * 1e6) // np.dtype(dtype).itemsize
    elems -= elems % n  # reduce_scatter needs n | elems
    x = jnp.ones((n, elems), dtype)
    x = jax.device_put(x, NamedSharding(mesh, P("x", None)))

    def body(v):
        if kind == "allreduce":
            return jax.lax.psum(v, "x") * (1.0 / n)  # keep values bounded
        if kind == "all_gather":
            g = jax.lax.all_gather(v, "x")           # [n, elems]
            return g[0]                               # keep carry shape
        if kind == "reduce_scatter":
            g = jax.lax.psum_scatter(v, "x", tiled=True)
            return jnp.tile(g, n)[:v.shape[0]]
        if kind == "ppermute":
            return jax.lax.ppermute(v, "x", [(i, (i + 1) % n) for i in range(n)])
        raise ValueError(kind)

    @jax.jit
    @lambda f: _shard_map(f, mesh=mesh, in_specs=P("x", None),
                          out_specs=P("x", None))
    def step(v):
        row = v[0]
        for _ in range(chain):
            row = body(row) + 1e-9  # data dependence between collectives
        return row[None, :]

    out = step(x)
    np.asarray(jax.device_get(out[0, :1]))
    best = 1e9
    for _ in range(iters):
        t0 = time.perf_counter()
        out = step(x)
        np.asarray(jax.device_get(out[0, :1]))
        best = min(best, (time.perf_counter() - t0) / chain)

    payload = elems * np.dtype(dtype).itemsize
    if kind == "allreduce":
        algo = 2 * (n - 1) / n * payload
    elif kind in ("all_gather", "reduce_scatter"):
        algo = (n - 1) / n * payload
    else:
        algo = payload
    return {"collective": kind, "payload_mb": round(payload / 1e6, 3),
            "devices": n, "time_us": round(best * 1e6, 1),
            "achieved_gbps": round(algo / best / 1e9, 3)}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--sizes-mb", default="0.25,1,4,16,64")
    p.add_argument("--collectives",
                   default="allreduce,all_gather,reduce_scatter,ppermute")
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--chain", type=int, default=8)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--ici-gbps", type=float, default=None,
                   help="per-chip ICI roofline for vs_roofline (e.g. 186 "
                        "for v5e bidirectional)")
    p.add_argument("--cpu-mesh", type=int, default=None, metavar="N",
                   help="run on an N-device virtual CPU mesh (the axon site "
                        "hook re-forces JAX_PLATFORMS=axon at interpreter "
                        "start, so the env var alone does not stick)")
    args = p.parse_args(argv)

    if args.cpu_mesh:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + f" --xla_force_host_platform_device_count={args.cpu_mesh}").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    mesh = _mesh(args.devices)
    for kind in args.collectives.split(","):
        for size in args.sizes_mb.split(","):
            rec = bench_collective(kind, float(size), mesh,
                                   iters=args.iters, chain=args.chain)
            if args.ici_gbps:
                rec["vs_roofline"] = round(rec["achieved_gbps"] / args.ici_gbps, 4)
            print(json.dumps(rec))


if __name__ == "__main__":
    main()
