"""ICI collective micro-benchmark (BASELINE.md last row: achieved allreduce
bandwidth vs roofline; reference shape: benchmark/fluid/fluid_benchmark.py
multi-GPU modes measuring NCCL throughput).

Sweeps psum / all_gather / reduce_scatter / ppermute over a jax.sharding
Mesh across a range of payload sizes, timing K chained collectives per
dispatch (one device sync at the end), and reports achieved algorithmic
bandwidth per chip:

  allreduce:      algo_bytes = 2 * (n-1)/n * payload   (ring)
  all_gather:     algo_bytes = (n-1)/n * result
  reduce_scatter: algo_bytes = (n-1)/n * payload
  ppermute:       algo_bytes = payload                 (one hop)

vs_roofline uses --ici-gbps (per-direction per-link; v5e ICI ~ 186 GB/s
bidirectional over 2 links -> pass the datasheet number for the target
topology).  On the 8-device virtual CPU mesh the absolute numbers are
host-memcpy speeds — the point there is validating the harness end to end
(tests/test_collective_bench.py + the dryrun), so the day multi-chip
hardware exists this file is the measurement, not a TODO.

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/collective_bench.py --sizes-mb 1,8 --iters 3

`--overlap` (ISSUE 7) A/Bs the backward-overlapped bucketed gradient
all-reduce against the serial single-flat-psum baseline through the
production bucketing code (parallel.distributed.make_grad_sync):

  python tools/collective_bench.py --overlap --layers 12 --grad-mb 4
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _mesh(n=None):
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n or len(devs)
    return Mesh(np.array(devs[:n]), ("x",))


def bench_collective(kind, size_mb, mesh, iters=4, chain=8, dtype="float32"):
    """One (collective, size) point: per-chip payload `size_mb`, `chain`
    dependent collectives per dispatch."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.core.jax_compat import shard_map as _shard_map

    n = mesh.devices.size
    elems = int(size_mb * 1e6) // np.dtype(dtype).itemsize
    elems -= elems % n  # reduce_scatter needs n | elems
    x = jnp.ones((n, elems), dtype)
    x = jax.device_put(x, NamedSharding(mesh, P("x", None)))

    def body(v):
        if kind == "allreduce":
            return jax.lax.psum(v, "x") * (1.0 / n)  # keep values bounded
        if kind == "all_gather":
            g = jax.lax.all_gather(v, "x")           # [n, elems]
            return g[0]                               # keep carry shape
        if kind == "reduce_scatter":
            g = jax.lax.psum_scatter(v, "x", tiled=True)
            return jnp.tile(g, n)[:v.shape[0]]
        if kind == "ppermute":
            return jax.lax.ppermute(v, "x", [(i, (i + 1) % n) for i in range(n)])
        raise ValueError(kind)

    @jax.jit
    @lambda f: _shard_map(f, mesh=mesh, in_specs=P("x", None),
                          out_specs=P("x", None))
    def step(v):
        row = v[0]
        for _ in range(chain):
            row = body(row) + 1e-9  # data dependence between collectives
        return row[None, :]

    out = step(x)
    np.asarray(jax.device_get(out[0, :1]))
    best = 1e9
    for _ in range(iters):
        t0 = time.perf_counter()
        out = step(x)
        np.asarray(jax.device_get(out[0, :1]))
        best = min(best, (time.perf_counter() - t0) / chain)

    payload = elems * np.dtype(dtype).itemsize
    if kind == "allreduce":
        algo = 2 * (n - 1) / n * payload
    elif kind in ("all_gather", "reduce_scatter"):
        algo = (n - 1) / n * payload
    else:
        algo = payload
    return {"collective": kind, "payload_mb": round(payload / 1e6, 3),
            "devices": n, "time_us": round(best * 1e6, 1),
            "achieved_gbps": round(algo / best / 1e9, 3)}


def bench_overlap(mesh, layers=8, grad_mb=1.0, bucket_mb=4.0, iters=4,
                  width=256, dtype="float32"):
    """Backward-overlapped vs serial gradient all-reduce A/B through the
    PRODUCTION bucketing code (parallel.distributed.make_grad_sync — the
    same callable CompiledProgram.with_grad_overlap installs on the
    lowering).

    Emulates a backward pass as `layers` dependent matmul segments, each
    yielding a `grad_mb`-sized gradient as it completes.  The bucketed arm
    psums size-capped buckets whose dataflow depends only on their member
    grads — XLA's latency-hiding scheduler can issue each bucket while
    later segments still compute; the serial arm's ONE flat psum depends
    on every grad, so it cannot start until the whole chain is done (the
    fetch-barrier-at-optimizer-boundary shape DDP replaced).  Both arms
    are element-wise identical; the A/B isolates scheduling.

    On the virtual CPU mesh the numbers validate the harness (like the
    raw-collective sweep above); on real multi-chip hardware the
    overlap_gain is the measurement."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.core.jax_compat import shard_map as _shard_map
    from paddle_tpu.parallel.distributed import make_grad_sync

    elems = max(int(grad_mb * 1e6) // np.dtype(dtype).itemsize, 1)
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(layers, width, width) * (width ** -0.5), dtype)
    x0 = jnp.asarray(rng.randn(width, width), dtype)

    def make_step(mode):
        sync = make_grad_sync("x", int(bucket_mb * 1e6), mode=mode)

        def worker(x, w_stack):
            grads = []
            h = x
            for i in range(layers):
                h = jnp.tanh(h @ w_stack[i])
                # grad_i's dataflow hangs off segment i's output: the
                # payload becomes available exactly when this "layer"
                # finishes, like a real backward
                g = jnp.full((elems,), 0.0, dtype) + h[0, 0]
                grads.append((f"g{i}", g))
            synced = sync(grads)
            acc = jnp.zeros((), jnp.float32)
            for v in synced.values():
                acc = acc + jnp.mean(v).astype(jnp.float32)
            return h, acc

        return jax.jit(_shard_map(worker, mesh=mesh,
                                  in_specs=(P(), P()), out_specs=(P(), P())))

    out = {}
    parity = {}
    for mode in ("serial", "bucketed"):
        step = make_step(mode)
        h, acc = step(x0, ws)
        np.asarray(jax.device_get(acc))
        best = 1e9
        for _ in range(iters):
            t0 = time.perf_counter()
            h, acc = step(x0, ws)
            np.asarray(jax.device_get(acc))
            best = min(best, time.perf_counter() - t0)
        out[mode] = best
        parity[mode] = float(np.asarray(jax.device_get(acc)))

    # the schedule actually measured: make_grad_sync plans greedy buckets
    # over f32 comm sizes (g.size * 4), not a flat ceil over total bytes
    from paddle_tpu.parallel.distributed import plan_buckets
    n_buckets = len(plan_buckets([(f"g{i}", elems * 4)
                                  for i in range(layers)],
                                 int(bucket_mb * 1e6)))
    return {"metric": "grad_allreduce_overlap_ab",
            "devices": int(mesh.devices.size),
            "layers": layers, "grad_mb": grad_mb, "bucket_mb": bucket_mb,
            "n_buckets": n_buckets,
            "serial_ms": round(out["serial"] * 1e3, 3),
            "bucketed_ms": round(out["bucketed"] * 1e3, 3),
            "overlap_gain": round(out["serial"] / out["bucketed"], 4)
            if out["bucketed"] else None,
            "parity": bool(np.isclose(parity["serial"], parity["bucketed"],
                                      rtol=1e-6))}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--sizes-mb", default="0.25,1,4,16,64")
    p.add_argument("--collectives",
                   default="allreduce,all_gather,reduce_scatter,ppermute")
    p.add_argument("--iters", type=int, default=4)
    p.add_argument("--chain", type=int, default=8)
    p.add_argument("--devices", type=int, default=None)
    p.add_argument("--ici-gbps", type=float, default=None,
                   help="per-chip ICI roofline for vs_roofline (e.g. 186 "
                        "for v5e bidirectional)")
    p.add_argument("--cpu-mesh", type=int, default=None, metavar="N",
                   help="run on an N-device virtual CPU mesh (the axon site "
                        "hook re-forces JAX_PLATFORMS=axon at interpreter "
                        "start, so the env var alone does not stick)")
    p.add_argument("--overlap", action="store_true",
                   help="backward-overlapped vs serial gradient all-reduce "
                        "A/B through parallel.distributed.make_grad_sync "
                        "(the ISSUE-7 measurement); prints one JSON line "
                        "with both walls + overlap_gain")
    p.add_argument("--layers", type=int, default=8,
                   help="--overlap: emulated backward segments")
    p.add_argument("--grad-mb", type=float, default=1.0,
                   help="--overlap: per-segment gradient payload (MB)")
    p.add_argument("--bucket-mb", type=float, default=4.0,
                   help="--overlap: bucket size cap (MB), as "
                        "FLAGS_dp_bucket_mb")
    args = p.parse_args(argv)

    if args.cpu_mesh:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + f" --xla_force_host_platform_device_count={args.cpu_mesh}").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")

    mesh = _mesh(args.devices)
    if args.overlap:
        print(json.dumps(bench_overlap(mesh, layers=args.layers,
                                       grad_mb=args.grad_mb,
                                       bucket_mb=args.bucket_mb,
                                       iters=args.iters)))
        return
    for kind in args.collectives.split(","):
        for size in args.sizes_mb.split(","):
            rec = bench_collective(kind, float(size), mesh,
                                   iters=args.iters, chain=args.chain)
            if args.ici_gbps:
                rec["vs_roofline"] = round(rec["achieved_gbps"] / args.ici_gbps, 4)
            print(json.dumps(rec))


if __name__ == "__main__":
    main()
