"""Buffer-donation audit over the model zoo's compiled train steps.

The executor donates every read+written persistable (params, optimizer
accumulators, BN running stats) to the XLA executable, so the update aliases
in place in HBM (`core/executor.py` `_CompiledStep.rw_names`,
donate_argnums).  A persistable that is written but NOT donated-and-aliased
is silently double-buffered: the step allocates a second copy of the buffer
and pays an extra HBM write every step — at BERT-base scale that is ~0.5 GB
of wasted traffic and residency per step.  BENCH_r05's `params_moved`
reported 18/198 BERT params "frozen", which is either exactly this class of
drop or a bench-probe artifact; this tool decides which, statically, for
every program in the zoo (verdict: probe artifact — see docs/performance.md
and tests/test_donation_audit.py).

Classification per written persistable (program order):

  donated            read + written, input/output avals identical -> XLA
                     aliases the update in place (donate_argnums covers it)
  copied_aval_drift  donated, but the written value's shape/dtype differs
                     from the input's -> XLA CANNOT alias; the "update" is
                     a fresh allocation every step (the r5 bf16+Adam freeze
                     shipped inside this class before register_opt pinned
                     output dtypes)
  copied_not_read    written but never read -> outside the donation set
                     entirely (steps>1 rejects these; steps=1 silently
                     double-buffers)

Trainable parameters that are never written at all are reported as
`never_updated` — the program's optimizer does not touch them (a genuinely
frozen param, as opposed to a bench probe reading sub-resolution updates
as frozen).

    python tools/donation_audit.py                 # report, full-size zoo
    python tools/donation_audit.py --tiny          # CI-size configs
    python tools/donation_audit.py --check --tiny  # exit 1 on any drop
    python tools/donation_audit.py --program bert --json
"""
from __future__ import annotations

import argparse
import json
import os
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


# --------------------------------------------------------------------------
# zoo builders (program + startup + example feed + fetch names)
# --------------------------------------------------------------------------


def build_zoo(tiny: bool = False, only=None):
    """[(name, main, startup, feed {name: np.ndarray}, fetch_names)].

    `tiny` shrinks every config to CI size (the audit is structural — the
    donation set does not depend on widths, so tiny results transfer)."""
    import paddle_tpu as fluid

    out = []

    def want(n):
        return only is None or n == only

    if want("mnist"):
        from paddle_tpu.models import mnist

        main, startup, feeds, fetches = mnist.build(learning_rate=1e-3)
        rng = np.random.RandomState(0)
        feed = {"img": rng.rand(4, 1, 28, 28).astype("f4"),
                "label": rng.randint(0, 10, (4, 1)).astype("i8")}
        out.append(("mnist", main, startup, feed, [fetches["loss"].name]))

    if want("resnet50"):
        from paddle_tpu.models import resnet

        if tiny:
            main, startup, feeds, fetches = resnet.build(
                depth=50, class_dim=10, image_shape=(3, 32, 32),
                with_optimizer=True)
            img = np.random.RandomState(0).rand(2, 3, 32, 32).astype("f4")
        else:
            main, startup, feeds, fetches = resnet.build(
                dtype="bfloat16", class_dim=1000, with_optimizer=True,
                stem="space_to_depth")
            img = np.random.RandomState(0).rand(2, 3, 224, 224).astype("f4")
        feed = {"img": img,
                "label": np.zeros((img.shape[0], 1), "i8")}
        out.append(("resnet50", main, startup, feed, [fetches["loss"].name]))

    if want("bert"):
        from paddle_tpu.models import transformer

        kw = (dict(vocab_size=200, seq_len=16, d_model=32, n_layers=2,
                   n_heads=2, d_ff=64) if tiny else
              dict(vocab_size=30522, seq_len=128, d_model=768, n_layers=12,
                   n_heads=12, d_ff=3072, dtype="bfloat16"))
        main, startup, feeds, fetches = transformer.build_bert(
            with_optimizer=True, **kw)
        b = transformer.make_fake_batch(2, kw["seq_len"], kw["vocab_size"],
                                        rng=np.random.RandomState(0))
        out.append(("bert", main, startup, dict(b), [fetches["loss"].name]))

    if want("nmt"):
        from paddle_tpu.lod import lod_var_name
        from paddle_tpu.models import nmt

        kw = (dict(src_vocab=80, tgt_vocab=80, d_model=32, n_layers=1,
                   n_heads=2, d_ff=64) if tiny else
              dict(src_vocab=8000, tgt_vocab=8000, d_model=512, n_layers=6,
                   n_heads=8, d_ff=2048))
        main, startup, feeds, fetches = nmt.build_transformer_nmt(
            dropout=0.1, learning_rate=2.0, **kw)
        rng = np.random.RandomState(0)
        b, T = 2, 12
        feed = {}
        for nm in ("src_word", "trg_word", "lbl_word"):
            feed[nm] = rng.randint(1, 80, (b, T, 1)).astype("i4")
            feed[lod_var_name(nm)] = np.full((b,), T, "i4")
        out.append(("nmt", main, startup, feed, [fetches["loss"].name]))

    if want("deepfm"):
        from paddle_tpu.models import deepfm

        kw = (dict(num_fields=4, vocab_size=50, embed_dim=4,
                   mlp_dims=(8,)) if tiny else
              dict(num_fields=26, vocab_size=200000, embed_dim=16,
                   mlp_dims=(400, 400, 400)))
        main, startup, feeds, fetches = deepfm.build(learning_rate=0.05, **kw)
        rng = np.random.RandomState(0)
        nf = kw["num_fields"]
        feed = {"feat_ids": rng.randint(0, kw["vocab_size"], (4, nf)).astype("i4"),
                "label": (rng.rand(4, 1) < 0.3).astype("f4")}
        out.append(("deepfm", main, startup, feed, [fetches["loss"].name]))

    return out


# --------------------------------------------------------------------------
# the audit
# --------------------------------------------------------------------------


def _aval(v):
    return (tuple(np.shape(v)), str(np.asarray(v).dtype) if not hasattr(v, "dtype") else str(v.dtype))


def audit_program(main, startup, feed, fetch_names, place=None):
    """Audit one program's compiled step; returns the classification dict.

    Builds the SAME `_CompiledStep` the executor would (no compile, no
    execute) and abstract-evaluates the step function to compare each
    written persistable's output aval against its input — identical avals
    inside the donation set is what lets XLA alias the update in place."""
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.core.executor import _CompiledStep
    from paddle_tpu.core.scope import RNG_STATE_VAR

    scope = fluid.Scope()
    exe = fluid.Executor(place or fluid.CPUPlace())
    exe.run(startup, scope=scope)

    block = main.global_block()
    jfeed = {}
    for n, v in feed.items():
        arr = np.asarray(v)
        if block.has_var(n):
            from paddle_tpu.core.dtypes import as_np_dtype

            want = as_np_dtype(block.var(n).dtype)
            if want is not None and arr.dtype != want:
                arr = arr.astype(want)
        from paddle_tpu.ops.common import canon_dtype

        canon = canon_dtype(arr.dtype)
        if arr.dtype != canon:
            arr = arr.astype(canon)
        jfeed[n] = arr
    compiled = _CompiledStep(main, list(jfeed), list(fetch_names), scope,
                             platform="cpu",
                             feed_shapes={n: v.shape for n, v in jfeed.items()})

    state_rw = {n: scope.find_var(n) for n in compiled.rw_names}
    state_ro = {n: scope.find_var(n) for n in compiled.ro_names}
    key = scope.find_var(RNG_STATE_VAR)
    if key is None:
        key = jax.random.PRNGKey(main.random_seed or 0)
    _, out_state, _ = jax.eval_shape(compiled.jfn, state_rw, state_ro,
                                     jfeed, key)

    rw = set(compiled.rw_names)
    donated, drift, not_read = [], [], []
    for n in compiled.written_names:
        if n not in rw:
            not_read.append(n)
            continue
        in_aval = _aval(state_rw[n])
        out_aval = _aval(out_state[n])
        (donated if in_aval == out_aval else drift).append(n)

    written = set(compiled.written_names)
    trainable = [p.name for p in main.all_parameters()
                 if getattr(p, "trainable", True)]
    has_optimizer = any(op.type == "backward"
                        for op in main.global_block().ops)
    never = [p for p in trainable if p not in written] if has_optimizer else []

    return {
        "persistable_written": len(compiled.written_names),
        "donated": len(donated),
        "copied_aval_drift": sorted(drift),
        "copied_not_read": sorted(not_read),
        "never_updated": sorted(never),
        "trainable_params": len(trainable),
        "read_only_state": len(compiled.ro_names),
    }


def audit_zoo(tiny=False, only=None, place=None):
    """{model: report} over the zoo; each report gains `clean`."""
    reports = {}
    for name, main, startup, feed, fetches in build_zoo(tiny, only):
        r = audit_program(main, startup, feed, fetches, place=place)
        r["clean"] = not (r["copied_aval_drift"] or r["copied_not_read"]
                         or r["never_updated"])
        reports[name] = r
    return reports


def render(reports) -> str:
    lines = ["# donation audit (non-donated persistable updates are wasted "
             "HBM traffic + residency every step)"]
    for name, r in reports.items():
        verdict = "OK" if r["clean"] else "DROPS"
        lines.append(
            f"{name:10s} {verdict:6s} donated {r['donated']}/"
            f"{r['persistable_written']} written persistables, "
            f"{r['trainable_params']} trainable params, "
            f"{r['read_only_state']} read-only")
        for k in ("copied_aval_drift", "copied_not_read", "never_updated"):
            if r[k]:
                lines.append(f"  {k}: {r[k][:8]}"
                             + (" ..." if len(r[k]) > 8 else ""))
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every zoo program donates every "
                         "persistable update (the perf_report-adjacent CI "
                         "gate for ISSUE 7)")
    ap.add_argument("--tiny", action="store_true",
                    help="CI-size model configs (donation sets are "
                         "structural, so results transfer to full size)")
    ap.add_argument("--program", default=None,
                    help="audit one zoo program (mnist|resnet50|bert|nmt|"
                         "deepfm)")
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    reports = audit_zoo(tiny=args.tiny, only=args.program)
    if args.json:
        print(json.dumps(reports))
    else:
        print(render(reports))
    if args.check:
        dirty = {n: r for n, r in reports.items() if not r["clean"]}
        if dirty:
            print(f"donation_audit --check: FAILED — non-donated updates in "
                  f"{sorted(dirty)}", file=sys.stderr)
            return 1
        print(f"donation_audit --check: OK — every persistable update in "
              f"{sorted(reports)} is donated and aliased in place",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
