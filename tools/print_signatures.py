"""API-freeze tooling (reference: tools/print_signatures.py + API.spec +
tools/diff_api.py — CI fails when a public signature changes without the
spec being updated).

Usage:
    python tools/print_signatures.py            # print current surface
    python tools/print_signatures.py --update   # rewrite API.spec
The pytest gate (tests/test_api_spec.py) diffs the live surface against
API.spec.
"""
from __future__ import annotations

import inspect
import os
import sys

MODULES = [
    "paddle_tpu",
    "paddle_tpu.layers",
    "paddle_tpu.optimizer",
    "paddle_tpu.io",
    "paddle_tpu.nets",
    "paddle_tpu.recordio",
    "paddle_tpu.dataset",
    "paddle_tpu.inference",
    "paddle_tpu.parallel",
    "paddle_tpu.contrib.mixed_precision",
    "paddle_tpu.dygraph",
    "paddle_tpu.metrics",
    "paddle_tpu.profiler",
    "paddle_tpu.flags",
    "paddle_tpu.errors",
    "paddle_tpu.faults",
    "paddle_tpu.resilience",
    "paddle_tpu.core.analysis",
    # named lock registry + contention telemetry (ISSUE 13): the
    # concurrency lint's runtime half is public contract
    "paddle_tpu.core.locks",
    # static resource planner (ISSUE 12): liveness peak-HBM + cost model
    "paddle_tpu.core.resource_plan",
    # the distributed observability surface (ISSUE 8): the monitor's
    # telemetry plane + flight recorder, the gang launcher, and the
    # health layer's straggler/telemetry API are public contract now
    "paddle_tpu.monitor",
    "paddle_tpu.launch",
    "paddle_tpu.dist_resilience",
    # elastic N->M resume (ISSUE 9): the cursor-repartition module
    "paddle_tpu.elastic",
    "paddle_tpu.integrity",
    # serving runtime (ISSUE 11): batching server, model registry,
    # verified hot reload
    "paddle_tpu.serving",
    # fault-hardened host-tiered sparse tables (ISSUE 19): the pserver,
    # its exactly-once client, the supervisor, and the tiered embedding
    "paddle_tpu.param_server",
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def collect():
    import importlib

    lines = []
    for modname in MODULES:
        mod = importlib.import_module(modname)
        # a module that declares __all__ freezes exactly that surface;
        # otherwise every public attribute (imports included) counts
        names = getattr(mod, "__all__", None)
        for name in sorted(names) if names is not None else sorted(dir(mod)):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            if inspect.ismodule(obj):
                continue
            qual = f"{modname}.{name}"
            if inspect.isclass(obj):
                # classes: constructor + public methods
                lines.append(f"{qual} (class) __init__{_sig(obj.__init__)}")
                for m in sorted(vars(obj)):
                    if m.startswith("_"):
                        continue
                    f = vars(obj)[m]
                    if callable(f):
                        lines.append(f"{qual}.{m} {_sig(f)}")
            elif callable(obj):
                lines.append(f"{qual} {_sig(obj)}")
    return lines


def main():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, here)
    lines = collect()
    spec_path = os.path.join(here, "API.spec")
    if "--update" in sys.argv:
        with open(spec_path, "w") as f:
            f.write("\n".join(lines) + "\n")
        print(f"wrote {len(lines)} signatures to API.spec")
    else:
        print("\n".join(lines))


if __name__ == "__main__":
    main()
