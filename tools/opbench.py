"""Op/model micro-benchmark driver (reference role:
paddle/fluid/operators/benchmark/op_tester.cc:1 — a standalone per-op timing
tool fed by config files).

The TPU rebuild's version packages the interleaved-A/B methodology from
docs/perf_r03.md into a reusable library + CLI instead of ad-hoc
experiments/ scripts:

  * variants are timed round-robin (A,B,A,B,...) so shared-chip throughput
    drift hits every variant equally — single measurements on the tunnel
    chip show +/-20% run-to-run variance and are not evidence;
  * each round times a window of `iters` dispatches ended by one device
    sync; per-variant stats report best / median / spread over rounds.

Library use (what experiments/*_ab_*.py scripts should call):

    from tools.opbench import interleave
    stats = interleave({"conv7": dispatch_a, "s2d": dispatch_b}, rounds=5)

CLI use (single-op timing through the real program/executor path):

    python tools/opbench.py --op relu --input X=256x1024 --grad
    python tools/opbench.py --op conv2d --input Input=64x64x56x56 \
        --input Filter=64x64x3x3 --attr strides=1,1 --attr paddings=1,1

Fused-kernel A/B (ISSUE 7): each registered Pallas kernel
(ops/pallas_kernels.py FUSED_KERNELS) timed interleaved against the XLA
composite it replaces, after a parity check at the registry tolerance:

    python tools/opbench.py --fused                       # all kernels
    python tools/opbench.py --fused ln_residual --grad    # fwd+bwd arm
    python tools/opbench.py --fused --interpret           # CPU/CI parity
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
import zlib

if __name__ == "__main__":  # `python tools/opbench.py` from the repo root
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from typing import Callable, Dict

import numpy as np


# --------------------------------------------------------------------------
# core: interleaved A/B timing
# --------------------------------------------------------------------------

def _sync(x):
    """Block until the dispatch's result is real (device->host copy)."""
    if isinstance(x, (list, tuple)):
        for v in x:
            _sync(v)
        return
    np.asarray(x)


def interleave(variants: Dict[str, Callable], rounds: int = 4, iters: int = 8,
               warmup: int = 2) -> Dict[str, dict]:
    """Time each zero-arg dispatch callable round-robin.

    Returns {name: {best_ms, median_ms, spread_pct, windows_ms}} where each
    window is (wall time of `iters` dispatches + one sync) / iters and
    spread_pct = (max-min)/median over windows.
    """
    order = list(variants.items())
    for name, fn in order:  # compile + warm every variant before timing any
        out = None
        for _ in range(warmup):
            out = fn()
        if out is not None:
            _sync(out)
    windows: Dict[str, list] = {name: [] for name, _ in order}
    for _ in range(rounds):
        for name, fn in order:
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn()
            _sync(out)
            windows[name].append((time.perf_counter() - t0) / iters)
    stats = {}
    for name, ws in windows.items():
        med = statistics.median(ws)
        stats[name] = {
            "best_ms": round(min(ws) * 1e3, 4),
            "median_ms": round(med * 1e3, 4),
            "spread_pct": round((max(ws) - min(ws)) / med * 100, 1),
            "windows_ms": [round(w * 1e3, 4) for w in ws],
        }
    return stats


# --------------------------------------------------------------------------
# per-op timing through the program/executor path
# --------------------------------------------------------------------------

def build_op_dispatch(op_type: str, inputs: Dict[str, np.ndarray],
                      attrs: dict | None = None, grad: bool = False,
                      place=None) -> Callable:
    """One-op program -> executor dispatch closure.

    With grad=True the op's (mean-reduced) first output is differentiated
    w.r.t. every floating input via append_backward, so the window times
    fwd+bwd — the shape that matters for training-path ops.
    """
    import paddle_tpu as fluid
    from paddle_tpu.core.program import Program, program_guard

    attrs = dict(attrs or {})
    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        block = prog.global_block()
        in_io, feed = {}, {}
        for slot, arr in inputs.items():
            arr = np.asarray(arr)
            name = f"in_{slot}"
            block.create_var(name, shape=arr.shape, dtype=str(arr.dtype),
                             is_data=True)
            feed[name] = arr
            in_io[slot] = [name]
        fluid.core.registry.get_op_def(op_type)  # fail early on unknown op
        out_slots = _probe_output_slots(op_type)
        out_io = {}
        for slot in out_slots:
            v = block.create_var(f"out_{slot}")
            out_io[slot] = [v.name]
        block.append_op(op_type, inputs=in_io, outputs=out_io, attrs=attrs)
        fetch_name = out_io[out_slots[0]][0]
        if grad:
            loss = fluid.layers.mean(block.var(fetch_name))
            float_ins = [n for n, a in feed.items()
                         if np.issubdtype(a.dtype, np.floating)]
            grads = fluid.calc_gradient(loss, [block.var(n) for n in float_ins])
            fetch_list = [loss.name] + [g.name for g in grads if g is not None]
        else:
            fetch_list = [fetch_name]

    exe = fluid.Executor(place or fluid.TPUPlace(0))
    scope = fluid.Scope()
    exe.run(startup, scope=scope)

    def dispatch():
        return exe.run(prog, feed=feed, fetch_list=fetch_list, scope=scope,
                       return_numpy=False)

    return dispatch


_KNOWN_OUT_SLOTS = {
    # ops whose primary output slot is not "Out"
    "conv2d": ["Output"], "conv3d": ["Output"], "conv2d_transpose": ["Output"],
    "batch_norm": ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    "layer_norm": ["Y", "Mean", "Variance"],
    "softmax_with_cross_entropy": ["Loss", "Softmax"],
    "cross_entropy": ["Y"], "matmul": ["Out"], "mul": ["Out"],
    "pool2d": ["Out"], "pool3d": ["Out"], "dropout": ["Out", "Mask"],
    "lrn": ["Out", "MidOut"], "maxout": ["Out"],
    "hinge_loss": ["Loss"], "log_loss": ["Loss"], "rank_loss": ["Out"],
    "huber_loss": ["Out", "Residual"], "kldiv_loss": ["Loss"],
    "warpctc": ["Loss", "WarpCTCGrad"], "topk": ["Out", "Indices"],
    "linear_chain_crf": ["TransitionExps", "Alpha", "EmissionExps",
                         "LogLikelihood"],
}


def _probe_output_slots(op_type: str):
    return _KNOWN_OUT_SLOTS.get(op_type, ["Out"])


# --------------------------------------------------------------------------
# fused-kernel A/B (ops/pallas_kernels.py registry)
# --------------------------------------------------------------------------

def build_fused_dispatches(kernel: str, dtype: str = "float32",
                           interpret: bool = False, grad: bool = False):
    """(dispatches, tol) for one registered fused kernel: `pallas` (the
    hand-fused kernel; `interpret=True` runs it through the Pallas
    interpreter — the CPU/CI mode, which validates semantics but not
    speed) vs `xla` (the composite lowering the kernel replaces), both
    jitted over the registry's example shapes.  With grad=True both arms
    differentiate sum(out**2) over the kernel's grad_argnums, so the
    window times fwd+bwd — the training-path shape."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas_kernels import FUSED_KERNELS

    spec = FUSED_KERNELS[kernel]
    args = spec["example"](jnp.dtype(dtype))
    tol = spec["tol"][dtype]
    if grad:
        if not spec["grad_argnums"]:
            raise ValueError(f"--grad: fused kernel {kernel!r} is a state "
                             f"update, not a differentiable layer")

        def _loss(fn):
            def wrapped(*a):
                out = fn(a)
                leaves = out if isinstance(out, (list, tuple)) else [out]
                return sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                           for l in leaves)
            return wrapped

        # argnums restricted to non-None example args, ORIGINAL positions
        # kept — dropping Nones from the tuple would shift every later
        # arg under the registry lambdas' positional indexing
        argnums = tuple(i for i in spec["grad_argnums"]
                        if args[i] is not None)
        fused = jax.jit(jax.grad(
            _loss(lambda a: spec["fused"](a, interpret=interpret)),
            argnums=argnums))
        ref = jax.jit(jax.grad(_loss(spec["reference"]), argnums=argnums))
    else:
        fused = jax.jit(lambda *a: spec["fused"](a, interpret=interpret))
        ref = jax.jit(lambda *a: spec["reference"](a))
    live = list(args)  # full example tuple, None placeholders included

    # parity before timing: an A/B between divergent kernels is meaningless
    def _flat(out):
        leaves = out if isinstance(out, (list, tuple)) else [out]
        return [np.asarray(l.astype(jnp.float32)) for l in leaves]

    for got, want in zip(_flat(fused(*live)), _flat(ref(*live))):
        err = float(np.max(np.abs(got - want))) if got.size else 0.0
        # scale-aware on the grad arm: reduced grads (dscale/dmul row-sums)
        # carry accumulation-order noise proportional to their magnitude,
        # same bound as tests/test_pallas_kernels.py test_grad_parity_fp32
        scale = 1.0 + (float(np.max(np.abs(want))) if grad and want.size
                       else 0.0)
        if err > tol * scale:
            raise AssertionError(
                f"fused kernel {kernel!r} ({dtype}, grad={grad}) diverged "
                f"from its composite: max|d|={err:.3e} > "
                f"tol={tol:.0e}*{scale:.1f}")
    return {"pallas": lambda: fused(*live), "xla": lambda: ref(*live)}, tol


def run_fused_ab(kernels=None, dtypes=("float32",), interpret=False,
                 grad=False, rounds=4, iters=8):
    """[{kernel, dtype, grad, parity_tol, pallas: stats, xla: stats,
    speedup}] — one interleaved A/B per (kernel, dtype)."""
    from paddle_tpu.ops.pallas_kernels import (FUSED_KERNELS,
                                               registered_fused_kernels)

    recs = []
    for kernel in (kernels or registered_fused_kernels()):
        if grad and not FUSED_KERNELS[kernel]["grad_argnums"]:
            # announced, not silent: `--fused adam_slab --grad` printing
            # nothing and exiting 0 would be indistinguishable from a
            # harness bug (unknown kernels/dtypes still raise loudly)
            print(f"opbench --fused: skipping {kernel!r} under --grad "
                  f"(state update, not a differentiable layer)",
                  file=sys.stderr)
            continue
        for dtype in dtypes:
            dispatches, tol = build_fused_dispatches(
                kernel, dtype, interpret=interpret, grad=grad)
            stats = interleave(dispatches, rounds=rounds, iters=iters)
            rec = {
                "kernel": kernel, "dtype": dtype, "grad": grad,
                "interpret": interpret, "parity_tol": tol,
                "pallas": stats["pallas"], "xla": stats["xla"],
                "speedup": round(stats["xla"]["best_ms"]
                                 / stats["pallas"]["best_ms"], 4)
                if stats["pallas"]["best_ms"] else None,
            }
            rec.update(_roofline_frac(kernel, dtype, grad, stats))
            recs.append(rec)
    return recs


def _roofline_frac(kernel, dtype, grad, stats):
    """roofline_ms + per-arm roofline_frac from the registry's analytic
    (flops, bytes) for the example shapes — the kernel's cost-rule-units
    roofline time divided by measured time, so an A/B win is stated in the
    same units the MFU floors ratchet in (ISSUE-17).  Forward arm only:
    the analytic model prices one fwd pass, and a fwd/bwd window would
    flatter the frac by ~the grad factor."""
    import jax.numpy as jnp

    from paddle_tpu.core.resource_plan import (CHIP_HBM_BANDWIDTH,
                                               CHIP_PEAK_FLOPS)
    from paddle_tpu.ops.pallas_kernels import FUSED_KERNELS

    ana = FUSED_KERNELS[kernel].get("analytic")
    if ana is None or grad:
        return {}
    flops, bts = ana(FUSED_KERNELS[kernel]["example"](jnp.dtype(dtype)))
    t_ms = max(flops / CHIP_PEAK_FLOPS, bts / CHIP_HBM_BANDWIDTH) * 1e3
    out = {"roofline_ms": round(t_ms, 6), "roofline_frac": {}}
    for arm in ("pallas", "xla"):
        best = stats[arm]["best_ms"]
        out["roofline_frac"][arm] = round(t_ms / best, 4) if best else None
    return out


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _parse_input(spec: str):
    """X=64x3x224x224[:float32] -> (slot, random ndarray)."""
    slot, shape = spec.split("=", 1)
    dtype = "float32"
    if ":" in shape:
        shape, dtype = shape.rsplit(":", 1)
    dims = tuple(int(d) for d in shape.split("x"))
    rng = np.random.RandomState(zlib.crc32(slot.encode()) % (2**31))
    if np.issubdtype(np.dtype(dtype), np.integer):
        arr = rng.randint(0, 10, dims).astype(dtype)
    else:
        arr = rng.rand(*dims).astype(dtype)
    return slot, arr


def _parse_attr(spec: str):
    """k=v with v parsed as bool/int/float/int-list/str."""
    k, v = spec.split("=", 1)
    if v in ("true", "True"):
        return k, True
    if v in ("false", "False"):
        return k, False
    if "," in v:
        parts = v.split(",")
        try:
            return k, [int(x) for x in parts]
        except ValueError:
            try:
                return k, [float(x) for x in parts]
            except ValueError:
                return k, v
    try:
        return k, int(v)
    except ValueError:
        pass
    try:
        return k, float(v)
    except ValueError:
        return k, v


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--op", default=None, help="registered op type")
    p.add_argument("--input", action="append", default=[],
                   metavar="SLOT=DIMxDIM[:dtype]")
    p.add_argument("--attr", action="append", default=[], metavar="K=V")
    p.add_argument("--grad", action="store_true",
                   help="time fwd+bwd (append_backward over mean of output)")
    p.add_argument("--cpu", action="store_true", help="run on CPUPlace")
    p.add_argument("--rounds", type=int, default=4)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--fused", nargs="?", const="all", default=None,
                   metavar="KERNEL",
                   help="interleaved Pallas-vs-XLA A/B over the fused-"
                        "kernel registry (ops/pallas_kernels.py); optional "
                        "KERNEL narrows to one, default all.  One JSON "
                        "line per (kernel, dtype) with both arms' stats, "
                        "after a parity check at the registry tolerance")
    p.add_argument("--dtype", default="float32,bfloat16",
                   help="--fused dtypes (comma-separated)")
    p.add_argument("--interpret", action="store_true",
                   help="--fused: run the Pallas arm in interpret mode "
                        "(the CPU/CI path — validates semantics, not "
                        "speed; timing numbers are NOT kernel evidence)")
    args = p.parse_args(argv)

    if args.fused:
        import jax

        from paddle_tpu.ops.pallas_kernels import (pallas_supported,
                                                   registered_fused_kernels)

        interpret = args.interpret
        if not interpret and not pallas_supported(jax.default_backend()):
            print(f"opbench --fused: backend {jax.default_backend()!r} has "
                  f"no Pallas support; forcing --interpret (parity evidence "
                  f"only — time the real kernels on TPU)", file=sys.stderr)
            interpret = True
        kernels = (registered_fused_kernels() if args.fused == "all"
                   else [args.fused])
        recs = run_fused_ab(kernels, dtypes=args.dtype.split(","),
                            interpret=interpret, grad=args.grad,
                            rounds=args.rounds, iters=args.iters)
        for rec in recs:
            print(json.dumps(rec))
        return
    if not args.op:
        p.error("--op is required unless --fused is given")

    import paddle_tpu as fluid

    inputs = dict(_parse_input(s) for s in args.input)
    attrs = dict(_parse_attr(s) for s in args.attr)
    place = fluid.CPUPlace() if args.cpu else fluid.TPUPlace(0)
    dispatch = build_op_dispatch(args.op, inputs, attrs, grad=args.grad,
                                 place=place)
    stats = interleave({args.op: dispatch}, rounds=args.rounds,
                       iters=args.iters)
    rec = {"op": args.op, "grad": args.grad,
           "inputs": {k: list(v.shape) for k, v in inputs.items()},
           "attrs": attrs, **stats[args.op]}
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
