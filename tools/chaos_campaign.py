#!/usr/bin/env python
"""Chaos campaign CLI (paddle_tpu/chaos.py — ISSUE 20).

    python tools/chaos_campaign.py --campaign --seed 7 --per-scenario 4
        Generate and run seeded multi-fault schedules against each
        scenario (train / online / serving; add gang with
        --scenarios ...,gang and PADDLE_CHAOS_GANG_WORKER pointing at a
        gang worker script), evaluate the invariant registry after every
        run, shrink any failing schedule to a minimal still-failing
        FLAGS_fault_spec, and write CHAOS_REPRO.json artifacts + a
        CAMPAIGN.json summary under --out.  --metrics writes the
        chaos_event / counter JSONL that `perf_report --check
        --max-chaos-violations` gates on.

    python tools/chaos_campaign.py --check --smoke [--out DIR]
        The tier-1 gate: a few seeded compound schedules per scenario,
        every invariant must hold, PLUS the planted-bug arm —
        PADDLE_CHAOS_PLANTED_BUG re-enables a simulated stale-restore
        race that only a nan+device compound exposes, and the gate
        asserts a seeded campaign catches it and the shrinker converges
        to a <=2-fault spec that STILL fails (and passes again with the
        bug unplanted).  Fixed seeds, CPU, time-budgeted.  Exit 1 on any
        unexpected violation, a missed planted bug, or a non-minimal
        shrink.

    python tools/chaos_campaign.py --replay --scenario train \
        --spec 'preempt@4;enospc@6' --seed 7
        Replay one schedule through the ordinary single-run path (the
        same path the campaign used — seeded determinism makes the
        verdict reproduce) and print the invariant verdict.  Exit 1 on
        violation.  This is how a CHAOS_REPRO.json is replayed.

Exit codes: 0 green, 1 violations / planted-bug escape, 2 usage.
"""
from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

SMOKE_SEED = 20          # the smoke's campaign seed (fixed: tier-1 replays)
PLANTED_SEED = 8         # first train draw is the nan@S;device@T pairing


def _campaign(args) -> int:
    from paddle_tpu import chaos

    scenarios = tuple(s for s in args.scenarios.split(",") if s)
    res = chaos.run_campaign(
        scenarios=scenarios, seed=args.seed,
        per_scenario=args.per_scenario, out_dir=args.out,
        metrics_path=args.metrics, do_shrink=not args.no_shrink,
        max_faults=args.max_faults)
    for s in res.schedules:
        print(f"{s['verdict']:4s}  {s['scenario']:8s} {s['spec']}")
    print(f"chaos campaign: {res.schedules_run} schedule(s), "
          f"{res.invariants_checked} invariant check(s), "
          f"{len(res.violations)} violation(s) -> {res.out_dir}")
    for v in res.violations:
        print(f"  VIOLATION [{v['class']}] {v['invariant']} "
              f"({v['scenario']}): {v['message']}")
        if "shrunk_spec" in v:
            print(f"    shrunk to: {v['shrunk_spec']} "
                  f"({v['shrink_runs']} probe runs)")
    for p in res.repro_paths:
        print(f"  repro: {p}")
    return 1 if res.violations else 0


def _replay(args) -> int:
    from paddle_tpu import chaos

    run = chaos.run_one(args.scenario, args.spec, seed=args.seed)
    vs = chaos.evaluate(run)
    checked = len(chaos.invariants_for(args.scenario)) if run.ok else 1
    print(f"replay {args.scenario} seed={args.seed} "
          f"spec={args.spec!r}: {checked} invariant(s) checked, "
          f"fired={run.fired}")
    for v in vs:
        print(f"  VIOLATION [{v.cls}] {v.invariant}: {v.message}")
    if not vs:
        print("  all invariants hold")
    return 1 if vs else 0


def _smoke(args) -> int:
    """The tier-1 smoke: green campaign + planted-bug convergence."""
    from paddle_tpu import chaos

    t0 = time.monotonic()
    out = args.out or tempfile.mkdtemp(prefix="pt-chaos-smoke-")
    metrics = args.metrics or os.path.join(out, "chaos_metrics.jsonl")
    failures = []

    # arm 1: the seeded compound campaign — every invariant must hold
    res = chaos.run_campaign(
        scenarios=("train", "online", "serving"), seed=SMOKE_SEED,
        per_scenario=args.per_scenario, out_dir=out, metrics_path=metrics)
    for s in res.schedules:
        print(f"{s['verdict']:4s}  {s['scenario']:8s} {s['spec']}")
    if res.violations:
        for v in res.violations:
            failures.append(
                f"smoke campaign violated {v['invariant']} "
                f"[{v['class']}] on {v['scenario']} {v['spec']!r}: "
                f"{v['message']}")

    # arm 2: the planted defect — a seeded campaign must CATCH it and
    # the shrinker must converge to a <=2-fault spec that still fails
    os.environ[chaos.PLANTED_BUG_ENV] = "1"
    try:
        planted = chaos.run_campaign(
            scenarios=("train",), seed=PLANTED_SEED, per_scenario=1,
            out_dir=os.path.join(out, "planted"), metrics_path=None)
        caught = [v for v in planted.violations
                  if v["invariant"] == "bit_identical_recovery"]
        if not caught:
            failures.append(
                "planted-bug arm: the seeded campaign did NOT catch the "
                f"planted stale-restore race (seed {PLANTED_SEED})")
        else:
            v = caught[0]
            shrunk = v.get("shrunk_spec", v["spec"])
            n = len([e for e in shrunk.split(";") if e.strip()])
            print(f"planted bug caught by {v['spec']!r}, shrunk to "
                  f"{shrunk!r} ({v.get('shrink_runs', 0)} probe runs)")
            if n > 2:
                failures.append(
                    f"shrinker did not converge: {shrunk!r} still has "
                    f"{n} faults (want <=2)")
            # the shrunk spec must still fail with the bug planted...
            r = chaos.run_one("train", shrunk, seed=PLANTED_SEED)
            if not any(x.invariant == "bit_identical_recovery"
                       for x in chaos.evaluate(r)):
                failures.append(
                    f"shrunk spec {shrunk!r} no longer reproduces the "
                    f"violation (shrinker verdict drifted)")
    finally:
        os.environ.pop(chaos.PLANTED_BUG_ENV, None)
    # ...and pass again with the bug unplanted (the defect, not the
    # harness, is what the schedule detects)
    if not failures:
        r = chaos.run_one("train", shrunk, seed=PLANTED_SEED)
        if chaos.evaluate(r):
            failures.append(
                f"shrunk spec {shrunk!r} fails even without the planted "
                f"bug — the repro names the wrong culprit")

    wall = time.monotonic() - t0
    print(f"chaos smoke: {res.schedules_run} schedule(s), "
          f"{res.invariants_checked} invariant check(s), planted-bug arm "
          f"{'ok' if not failures else 'FAILED'}, {wall:.1f}s")
    print(f"metrics: {metrics}")
    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    print("OK")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--campaign", action="store_true",
                    help="run a full seeded campaign")
    ap.add_argument("--check", action="store_true",
                    help="gate mode: exit 1 on any violation")
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 smoke campaign + planted-bug arm")
    ap.add_argument("--replay", action="store_true",
                    help="replay one schedule through the single-run path")
    ap.add_argument("--scenario", default="train",
                    help="scenario for --replay")
    ap.add_argument("--spec", default=None,
                    help="FLAGS_fault_spec string for --replay")
    ap.add_argument("--scenarios", default="train,online,serving",
                    help="comma list for --campaign")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--per-scenario", type=int, default=2)
    ap.add_argument("--max-faults", type=int, default=3)
    ap.add_argument("--out", default=None,
                    help="artifact dir (CHAOS_REPRO.json, CAMPAIGN.json)")
    ap.add_argument("--metrics", default=None,
                    help="metrics JSONL path (perf_report gates on it)")
    ap.add_argument("--no-shrink", action="store_true",
                    help="skip shrinking failing schedules")
    args = ap.parse_args(argv)

    if args.replay:
        if not args.spec:
            ap.error("--replay needs --spec")
        return _replay(args)
    if args.smoke or (args.check and not args.campaign):
        return _smoke(args)
    if args.campaign:
        return _campaign(args)
    ap.error("pick one of --campaign / --check --smoke / --replay")


if __name__ == "__main__":
    sys.exit(main())
