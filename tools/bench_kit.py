"""Shared bench/experiment dispatch builders (reference role:
benchmark/fluid/fluid_benchmark.py model setup helpers).

bench.py and the experiments/*_ab_*.py scripts all need the same
"build model -> Executor -> device-resident feeds -> steps=K scan closure"
block; this is the single copy, so a protocol change (feed dtype, K, stem)
cannot silently diverge between the bench and the A/Bs that justify it.

Import as `from tools.bench_kit import ...` from the repo root, or with
sys.path bootstrap from experiments/.
"""
from __future__ import annotations

import statistics
import time

import numpy as np


def timed_steps(dispatch, K=1, n_warm=2, iters=3, windows=1,
                spread_target=None, max_windows=12, clock=None):
    """Best-of-N timing windows, per-OPTIMIZER-step results.

    The shared-chip pool shows ~±20% run-to-run throughput variance, so the
    minimum window is the honest compute time; all windows are returned so
    results report spread.  K = optimizer steps per dispatch (the scan
    length): returned dt and windows are divided by it exactly once.

    spread_target (percent): warmup-until-stable windowing — keep timing
    windows (up to `max_windows` total) until the LAST `windows` of them
    agree to within spread_target%, then report exactly those.  The fix for
    BENCH_r05's NMT entry, whose first window still carried compile/cache
    warm-in and swung the reported spread to 26% (30.3 -> 22.8 ms): the
    early windows are treated as extended warmup instead of evidence.  When
    the budget runs out before stabilizing, the trailing windows are
    returned as-is — callers see the honest spread and their own gate
    decides (`spread_pct(ws)`); `clock` injects a fake timer for tests.
    """
    clock = clock or time.perf_counter
    out = None
    for _ in range(n_warm):
        out = dispatch()
    np.asarray(out[0])
    ws = []

    def one_window():
        nonlocal out
        t0 = clock()
        for _ in range(iters):
            out = dispatch()
        np.asarray(out[0])
        ws.append((clock() - t0) / iters / K)

    for _ in range(windows):
        one_window()
    if spread_target is not None:
        while (spread_pct([w * 1e3 for w in ws[-windows:]]) > spread_target
               and len(ws) < max_windows):
            one_window()
        ws = ws[-windows:]
    return min(ws), out, [round(w * 1e3, 3) for w in ws]


def spread_pct(windows_ms):
    """(max-min)/median over windows, %; same stat as tools/opbench.py."""
    if len(windows_ms) < 2:
        return 0.0
    return round((max(windows_ms) - min(windows_ms))
                 / statistics.median(windows_ms) * 100, 1)




def attach_param_probe(dispatch, main, scope):
    """Attach `dispatch.probe_param()` returning {param: f8 snapshot} of
    EVERY trainable param — the bench-level liveness gate.  All params (not
    just the first) so a partial optimizer freeze — the r5 bf16+Adam bug
    froze every encoder param while the f32 embeddings kept moving — cannot
    pass by luck of program order."""
    def _probe_param():
        snap = {}
        for p in main.all_parameters():
            v = scope.find_var(p.name)
            if v is not None:
                snap[p.name] = np.asarray(v).astype("f8")
        if not snap:
            raise RuntimeError("no parameters in scope")
        return snap

    # First-order optimizer accumulators per param ({param}_moment1_0 /
    # _moment_0 / _velocity_0 ... — optimizer.py _add_accumulator naming).
    # The moment is the tie-breaker when a param snapshot doesn't move: a
    # LIVE moment means the optimizer ran and the update rounded away below
    # the param dtype's resolution (bf16 q/k early-training stalls), while
    # a dead moment alongside a dead param is a genuinely dropped update —
    # the class tools/donation_audit.py pins statically.
    # _mean_grad_0 LAST: rmsprop only updates it under centered=True (the
    # non-default), so probing it first would misreport every non-centered
    # RMSProp param as dropped-update; _momentum_0 is the live accumulator
    # there and must win the tie
    _MOMENT_SUFFIXES = ("_moment1_0", "_moment_0", "_velocity_0",
                        "_momentum_0", "_avg_squared_grad_0", "_squared_0",
                        "_mean_grad_0")

    def _probe_moments():
        snap = {}
        names = set(scope.var_names())
        for p in main.all_parameters():
            for suf in _MOMENT_SUFFIXES:
                n = p.name + suf
                if n in names:
                    snap[p.name] = np.asarray(scope.find_var(n)).astype("f8")
                    break
        return snap

    dispatch.probe_param = _probe_param
    dispatch.probe_moments = _probe_moments
    return dispatch

def make_resnet_dispatch(batch_size=256, K=4, stem="space_to_depth",
                         data_format="NCHW", dtype="bfloat16"):
    """ResNet-50 train-step closure: returns (dispatch, loss_name)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    main, startup, feeds, fetches = resnet.build(
        dtype=dtype, class_dim=1000, learning_rate=0.1, with_optimizer=True,
        stem=stem, data_format=data_format)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    dev = fluid.TPUPlace(0).jax_device()
    shape = ((K, batch_size, 3, 224, 224) if data_format == "NCHW"
             else (K, batch_size, 224, 224, 3))
    feed = {
        "img": jax.device_put(jnp.asarray(rng.rand(*shape), jnp.float32), dev),
        "label": jax.device_put(
            jnp.asarray(rng.randint(0, 1000, (K, batch_size, 1)), jnp.int32), dev),
    }
    loss_name = fetches["loss"].name

    def dispatch():
        return exe.run(main, feed=feed, fetch_list=[loss_name], scope=scope,
                       steps=K, return_numpy=False)

    # compile now (under whatever lowering flags the caller has set) and
    # fail fast on a broken model
    out = dispatch()
    assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[-1]))
    attach_param_probe(dispatch, main, scope)
    _attach_plan_inputs(dispatch, main, feed, loss_name, K)
    return dispatch, loss_name


def _attach_plan_inputs(dispatch, main, feed, loss_name, K):
    """Expose the EXACT program + feed shapes this dispatch measures, so
    bench.py's static-roofline prediction (core/resource_plan.py) plans
    the same computation instead of rebuilding from a copied config."""
    dispatch.main_program = main
    dispatch.feed_shapes = {n: tuple(np.shape(v)) for n, v in feed.items()}
    dispatch.loss_name = loss_name
    dispatch.steps = K
    return dispatch


def make_bert_dispatch(batch_size=256, seq_len=128, K=2, dtype="bfloat16",
                       use_fused_attention=True):
    """BERT-base train-step closure: returns (dispatch, loss_name).

    Default fused attention: one op for scale/bias/softmax/context (mixed-
    precision XLA formulation; attention-prob dropout becomes output
    dropout — the substitution documented in models/transformer.py).
    r5 A/B: 255.1 vs 273.8 ms/step vs the unfused op stack."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer

    main, startup, feeds, fetches = transformer.build_bert(
        vocab_size=30522, seq_len=seq_len, d_model=768, n_layers=12,
        n_heads=12, d_ff=3072, dropout_prob=0.1, with_optimizer=True,
        dtype=dtype, use_fused_attention=use_fused_attention)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    batches = [transformer.make_fake_batch(batch_size, seq_len, 30522,
                                           rng=np.random.RandomState(k))
               for k in range(K)]
    dev = fluid.TPUPlace(0).jax_device()
    feed = {k: jax.device_put(jnp.asarray(np.stack([b[k] for b in batches])), dev)
            for k in batches[0]}
    loss_name = fetches["loss"].name

    def dispatch():
        return exe.run(main, feed=feed, fetch_list=[loss_name], scope=scope,
                       steps=K, return_numpy=False)

    out = dispatch()
    assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[-1]))
    attach_param_probe(dispatch, main, scope)
    _attach_plan_inputs(dispatch, main, feed, loss_name, K)
    return dispatch, loss_name


def make_nmt_dispatch(K=8, b=32, T=64, dtype="float32"):
    """Transformer-NMT ragged train-step closure: returns (dispatch, loss_name).

    Pre-padded [K,b,T,1] id feeds + `@LOD` lengths companions — the executed
    program is the same ragged program the LoDTensor path runs; only the
    harness avoids per-step host dispatch."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu.lod import lod_var_name
    from paddle_tpu.models import nmt

    main, startup, feeds, fetches = nmt.build_transformer_nmt(
        src_vocab=8000, tgt_vocab=8000, d_model=512, n_layers=6, n_heads=8,
        d_ff=2048, dropout=0.1, learning_rate=2.0, dtype=dtype)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    dev = fluid.TPUPlace(0).jax_device()
    feed = {}
    lens = {}
    for name in ("src_word", "trg_word", "lbl_word"):
        side = "src" if name == "src_word" else "tgt"
        if side not in lens:
            lens[side] = rng.randint(20, T, size=(K, b)).astype("int32")
        ids = rng.randint(1, 8000, size=(K, b, T, 1)).astype("int32")
        # zero the padding region so the padded carrier matches what the
        # LoDTensor expansion would produce
        mask = np.arange(T)[None, None, :] < lens[side][..., None]
        ids = ids * mask[..., None]
        feed[name] = jax.device_put(jnp.asarray(ids), dev)
        feed[lod_var_name(name)] = jax.device_put(jnp.asarray(lens[side]), dev)
    loss_name = fetches["loss"].name

    def dispatch():
        return exe.run(main, feed=feed, fetch_list=[loss_name], scope=scope,
                       steps=K, return_numpy=False)

    out = dispatch()
    assert np.isfinite(float(np.asarray(out[0]).reshape(-1)[-1]))
    attach_param_probe(dispatch, main, scope)
    mean_tokens = float(lens["src"].mean() + lens["tgt"].mean())
    return dispatch, loss_name, mean_tokens
