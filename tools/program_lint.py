#!/usr/bin/env python
"""Render / CI-gate static analysis over Program IR (paddle_tpu/core/analysis.py).

    python tools/program_lint.py
        Build the model-zoo programs (ResNet-50, BERT, DeepFM: main +
        startup each) and render every diagnostic the analysis suite
        produces at --level (default full), plus the shape/dtype inference
        coverage table (`analysis.infer_coverage_frac`).

    python tools/program_lint.py prog.json [prog2.json ...]
        Same, over serialized programs (Program.to_string() output) —
        lint a saved inference model's program without building it.

    python tools/program_lint.py --check [--min-coverage 0.8]
        CI gate (same shape as perf_report --check): exit 1 if any
        error-severity diagnostic is found OR the zoo's op-type inference
        coverage drops below the floor.  Wired into the tier-1 flow via
        tests/test_program_lint.py, so a new op landing in the zoo without
        an infer rule fails CI instead of silently shrinking the verified
        surface.

    python tools/program_lint.py --level structural
        Verifier-only (def-before-use, dangling vars, unregistered ops,
        orphan sub-blocks, duplicate param writes); skips shape
        re-inference and the hazard lints.

Exit codes: 0 clean (warnings allowed), 1 errors or coverage below floor.
"""
from __future__ import annotations

import argparse
import os
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The landed floor for model-zoo op-type inference coverage.  Raise it when
# coverage improves; never lower it (the ratchet that keeps the verified
# surface from eroding).  1.0 since the resource-plan PR: every op type in
# the zoo (including the sequence ops the cost model exposed as uncovered —
# attention_bias, position_encoding, sequence_pool) has an infer rule.
COVERAGE_FLOOR = 1.0


def _fmt_table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def zoo_programs():
    """The model-zoo programs the acceptance coverage is measured over."""
    from paddle_tpu.models import deepfm, resnet, transformer

    out = []
    m, s, _, _ = resnet.build(depth=50, class_dim=100, image_shape=(3, 32, 32))
    out += [("resnet50/main", m), ("resnet50/startup", s)]
    m, s, _, _ = transformer.build_bert(vocab_size=1000, seq_len=32,
                                        d_model=64, n_layers=2, n_heads=4,
                                        d_ff=128)
    out += [("bert/main", m), ("bert/startup", s)]
    m, s, _, _ = deepfm.build()
    out += [("deepfm/main", m), ("deepfm/startup", s)]
    return out


def load_programs(paths):
    from paddle_tpu.core.program import Program

    out = []
    for p in paths:
        with open(p) as f:
            out.append((os.path.basename(p), Program.parse_from_string(f.read())))
    return out


def lint(named_programs, level="full"):
    """Run the analysis suite; returns (diag rows, coverage dict, n_errors)."""
    from paddle_tpu.core import analysis

    rows = []
    n_errors = 0
    for name, prog in named_programs:
        for d in analysis.verify_program(prog, level=level):
            if d.severity == analysis.SEV_ERROR:
                n_errors += 1
            rows.append((name, d.severity, d.code, d.block,
                         "-" if d.op_idx is None else d.op_idx,
                         d.op_type or "-", d.var or "-", d.message))
    cov = analysis.infer_coverage([p for _, p in named_programs])
    return rows, cov, n_errors


def render(named_programs, level="full"):
    from paddle_tpu.monitor import MONITOR

    rows, cov, n_errors = lint(named_programs, level)
    parts = [f"# program lint  level={level}  programs={len(named_programs)}"]
    if rows:
        parts.append("\n## diagnostics\n" + _fmt_table(
            [r[:7] for r in rows],
            ["program", "severity", "code", "block", "op", "type", "var"]))
        parts.append("\n## messages")
        for r in rows:
            parts.append(f"- {r[0]}: [{r[1]}:{r[2]}] {r[7]}")
    else:
        parts.append("\nno diagnostics")
    parts.append(
        f"\n## shape/dtype inference coverage\n"
        f"op types covered: {len(cov['covered_types'])} / "
        f"{len(cov['covered_types']) + len(cov['missing_types'])} "
        f"(frac {cov['frac']:.3f}; per-op {cov['op_frac']:.3f})")
    if cov["missing_types"]:
        parts.append("missing infer rules: " + ", ".join(cov["missing_types"]))
    MONITOR.gauge("analysis.infer_coverage_frac").set(cov["frac"])
    return "\n".join(parts), cov, n_errors


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("programs", nargs="*",
                    help="serialized Program JSON files (default: build the "
                         "model zoo)")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: exit 1 on error diagnostics or coverage "
                         "below the floor")
    ap.add_argument("--level", default="full",
                    choices=["structural", "full"])
    ap.add_argument("--min-coverage", type=float, default=COVERAGE_FLOOR,
                    help=f"coverage floor for --check (default "
                         f"{COVERAGE_FLOOR})")
    args = ap.parse_args(argv)

    named = (load_programs(args.programs) if args.programs else zoo_programs())
    text, cov, n_errors = render(named, args.level)
    print(text)

    if args.check:
        failed = False
        if n_errors:
            print(f"\nCHECK FAILED: {n_errors} error-severity diagnostic(s)")
            failed = True
        if cov["frac"] < args.min_coverage:
            print(f"\nCHECK FAILED: analysis.infer_coverage_frac "
                  f"{cov['frac']:.3f} < floor {args.min_coverage}")
            failed = True
        if failed:
            return 1
        print(f"\nCHECK OK: 0 errors, coverage {cov['frac']:.3f} >= "
              f"{args.min_coverage}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
