"""Op-registry audit gate (VERDICT r4 #7).

Mechanically extracts the reference's operator inventory (every
REGISTER_OPERATOR / REGISTER_OP_WITHOUT_GRADIENT / REGISTER_ELEMWISE_* /
REGISTER_OP_CPU_KERNEL registration plus the FOR_EACH_ACTIVATION_OP macro
list) and requires every non-grad name to be either

  * registered in paddle_tpu.core.registry, or
  * recorded in OP_DEVIATIONS.md with a category + rationale
    (categories: alias — differently factored, with the covering name;
     design — subsumed by the XLA/JAX architecture; nonpublic — no API.spec
     surface in the reference itself; infra — device/runtime plumbing with
     an architectural replacement).

Stale deviation rows (name now registered, or gone from the reference) fail
the gate too, so the file cannot rot.  Reference precedent for freezing
internals: op_use_default_grad_op_maker.spec.

  python tools/op_audit.py            # human summary, exit 1 on failure
  python tools/op_audit.py --json     # machine-readable
"""
from __future__ import annotations

import json
import os
import re
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF_OPS_DIR = "/root/reference/paddle/fluid/operators"
DEVIATIONS = os.path.join(REPO, "OP_DEVIATIONS.md")
SNAPSHOT = os.path.join(REPO, "tools", "ref_op_inventory.txt")

_PATTERNS = [
    re.compile(r"REGISTER_OPERATOR\(\s*([a-z0-9_]+)"),
    re.compile(r"REGISTER_OP_WITHOUT_GRADIENT\(\s*([a-z0-9_]+)"),
    re.compile(r"REGISTER_ELEMWISE_[A-Z_]*OP[A-Z_]*\(\s*([a-z0-9_]+)"),
    re.compile(r"REGISTER_OP_CPU_KERNEL\(\s*([a-z0-9_]+)"),
]
_ACT_MACRO = re.compile(r"__macro\(\s*([a-z0-9_]+)\s*,")


def reference_inventory():
    """Scan the reference tree; fall back to the committed snapshot when the
    reference checkout is absent (CI on a bare clone)."""
    names = set()
    if os.path.isdir(REF_OPS_DIR):
        for root, _dirs, files in os.walk(REF_OPS_DIR):
            for f in files:
                if not (f.endswith(".cc") or f.endswith(".h") or f.endswith(".cu.cc")):
                    continue
                try:
                    text = open(os.path.join(root, f), errors="ignore").read()
                except OSError:
                    continue
                for pat in _PATTERNS:
                    names.update(pat.findall(text))
                if f == "activation_op.h":
                    names.update(_ACT_MACRO.findall(text))
        names = {n for n in names
                 if not n.endswith("_grad") and not n.endswith("_grad2")}
        # macro-template placeholders, not ops (e.g. isfinite_op.cc's
        # `REGISTER_OPERATOR(op_type, ...)` inside a #define)
        names -= {"op_type", "op_name"}
        with open(SNAPSHOT, "w") as fh:
            fh.write("\n".join(sorted(names)) + "\n")
        return names
    if os.path.exists(SNAPSHOT):
        return set(open(SNAPSHOT).read().split())
    raise SystemExit("neither the reference tree nor the snapshot exists")


def load_deviations():
    """Parse OP_DEVIATIONS.md table rows: | op | category | rationale |."""
    devs = {}
    if not os.path.exists(DEVIATIONS):
        return devs
    for line in open(DEVIATIONS):
        m = re.match(r"\|\s*`?([a-z0-9_]+)`?\s*\|\s*(\w+)\s*\|\s*(.+?)\s*\|\s*$",
                     line)
        if m and m.group(2) in ("alias", "design", "nonpublic", "infra"):
            devs[m.group(1)] = (m.group(2), m.group(3))
    return devs


def audit():
    import paddle_tpu  # noqa: F401  (populates the registry)
    from paddle_tpu.core import registry

    ref = reference_inventory()
    ours = set(registry._REGISTRY)
    devs = load_deviations()

    registered = sorted(ref & ours)
    recorded = sorted(n for n in ref - ours if n in devs)
    uncovered = sorted(n for n in ref - ours if n not in devs)
    stale = sorted(n for n in devs if n in ours or n not in ref)
    return {
        "ref_total": len(ref),
        "registered": len(registered),
        "recorded": len(recorded),
        "uncovered": uncovered,
        "stale_deviations": stale,
        "ok": not uncovered and not stale,
    }


def main():
    res = audit()
    if "--json" in sys.argv:
        print(json.dumps(res, indent=1))
    else:
        print(f"reference non-grad ops: {res['ref_total']}")
        print(f"registered:             {res['registered']}")
        print(f"recorded deviations:    {res['recorded']}")
        if res["uncovered"]:
            print(f"UNCOVERED ({len(res['uncovered'])}): {' '.join(res['uncovered'])}")
        if res["stale_deviations"]:
            print(f"STALE deviation rows: {' '.join(res['stale_deviations'])}")
        print("GATE:", "PASS" if res["ok"] else "FAIL")
    sys.exit(0 if res["ok"] else 1)


if __name__ == "__main__":
    main()
