#!/usr/bin/env python
"""Concurrency lint: static analysis over paddle_tpu's OWN source.

The framework is a multi-threaded system (serving workers, batcher,
heartbeat/watchdog threads, pipeline prefetch, monitor loggers), and the
last several PRs each needed hand review to catch the same defect
classes: blocking work held under a hot lock, lost-update counter races,
lock-order inversions.  This tool makes those classes build-time
failures.  Same render/--check CLI shape as program_lint/resource_plan:

    python tools/concurrency_lint.py
        Lint the whole paddle_tpu/ tree: render the lock rank table, the
        observed acquisition graph, every diagnostic, and the allowlist.

    python tools/concurrency_lint.py path.py [dir ...]
        Lint specific files/directories (how the planted-defect tests
        exercise each diagnostic class on scratch modules).

    python tools/concurrency_lint.py --check [--max-allowlist N]
        CI gate: exit 1 on any error-severity diagnostic, any unnamed
        raw threading primitive, or an allowlist grown past the ratchet.
        Wired into tier-1 via tests/test_concurrency_lint.py.

Three analyses (all static, nothing is imported or executed):

1. **Lock graph / rank order.**  Every framework lock is created through
   `paddle_tpu.core.locks.named_lock("name", rank=N)` (or named_rlock /
   named_condition) — the lint collects every creation site, maps lock
   variables (module globals and `self._x` attributes) to their names,
   then walks `with`/`.acquire()` nesting through every function,
   following calls ONE level deep (self-methods, module functions, and
   attribute/parameter types inferred from `self.x = ClassName(...)`
   assignments and parameter annotations).  Any acquisition whose rank
   is not strictly greater than every lock already held is a potential
   deadlock: `lock_order_inversion` (or `self_deadlock` for nested
   acquisition of a non-reentrant name), named with file:line and BOTH
   lock names + declared ranks.

2. **Blocking-under-lock.**  A registry of blocking calls — XLA
   compile/_CompiledStep build, file/socket I/O, subprocess, time.sleep,
   collective dispatch, Future.result, `.wait()` on anything that is not
   the held lock itself — flagged whenever reachable (one call level
   deep) while a named lock is held.  The registry mechanically encodes
   the PR-10/PR-11 review findings (Predictor construction and
   plan_model_bytes under the serving registry lock) so the class can
   never land again.  Audited deliberate cases carry a `# lock-ok:
   <reason>` pragma on the `with` (or call) line — the allowlist — and
   the --check gate ratchets the allowlist count so it can only shrink.

3. **Unguarded shared state.**  Per class: instance attributes written
   from more than one thread entry point (methods launched via
   `threading.Thread(target=self.m)`, atexit/excepthook hooks, plus the
   public API surface as one combined entry) without a common named
   lock.  An augmented write (`self.x += 1`, the PR-10 lost-update
   class) is an error; plain multi-entry writes are warnings.

Exit codes: 0 clean (warnings allowed), 1 errors / unnamed locks /
allowlist above the ratchet.
"""
from __future__ import annotations

import argparse
import ast
import os
import sys

SEV_ERROR = "error"
SEV_WARNING = "warning"

# The allowlist ratchet: the number of `# lock-ok:` pragma SITES in
# paddle_tpu/ may only go DOWN (each is an audited, justified case of
# deliberate blocking-under-lock).  Raising it requires the same review
# a new lock would get.  Current sites: predictor run serialization
# (x2), executor build lock, monitor blackbox latch, monitor JSONL
# logger (x2), recordio g++ one-shot build, ps client protocol framing
# (exchange + connect), ps drain barrier, pserver snapshot consistency
# cut (x2: stop-the-world + op-cadence), pserver supervisor lifecycle
# (x2: start + watch-respawn).
ALLOWLIST_MAX = 14

PRAGMA = "# lock-ok:"

NAMED_LOCK_FACTORIES = {"named_lock", "named_rlock", "named_condition"}
RAW_PRIMITIVES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore", "Barrier"}

# ---- the blocking-call registry ---------------------------------------------
# Exact dotted call paths that block.
BLOCKING_DOTTED = {
    "time.sleep",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "os.fsync",
    "socket.create_connection",
    "shutil.copyfile", "shutil.copytree", "shutil.rmtree",
}
# Terminal method names that block on any receiver (socket/file/thread/
# future/collective vocabulary).  ".wait" is handled specially: waiting
# on the HELD lock's own condition is the point of a condition variable.
BLOCKING_METHODS = {
    "result", "join",
    "recv", "recvfrom", "accept", "connect", "sendall", "sendto",
    "fsync", "flush",
    "compile",
    "all_reduce", "all_gather", "all_to_all", "barrier", "broadcast",
    "psum",
}
# Dotted paths that merely LOOK like blocking methods.
NOT_BLOCKING_DOTTED = {"os.path.join"}
# Callables (functions/constructors, matched by terminal name) whose
# bodies block on disk or XLA — the PR-10/PR-11 review findings encoded:
# Predictor() streams weights and compiles; plan_model_bytes reads and
# plans a saved program; _CompiledStep() builds the step closure;
# Heartbeat() binds sockets and starts threads.
BLOCKING_CALLABLES = {
    "open",
    "Predictor", "_CompiledStep", "Heartbeat",
    "plan_model_bytes", "manifest_weight_bytes",
    "load_inference_model", "load_sharded", "load_vars",
}

# Files the scanner skips: the lock wrapper itself builds the raw
# primitives every other file is forbidden to touch.
SKIP_RELPATHS = {os.path.join("core", "locks.py")}


def _dotted(node) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class Diag:
    __slots__ = ("severity", "code", "file", "line", "locks", "message",
                 "allowed", "reason")

    def __init__(self, severity, code, file, line, locks, message,
                 allowed=False, reason=""):
        self.severity = severity
        self.code = code
        self.file = file
        self.line = line
        self.locks = locks  # tuple of lock names involved
        self.message = message
        self.allowed = allowed  # pragma-allowlisted
        self.reason = reason    # the pragma's justification text

    def where(self):
        return f"{self.file}:{self.line}"


class LockDef:
    __slots__ = ("name", "rank", "reentrant", "file", "line", "kind")

    def __init__(self, name, rank, reentrant, file, line, kind):
        self.name = name
        self.rank = rank
        self.reentrant = reentrant
        self.file = file
        self.line = line
        self.kind = kind


class FuncInfo:
    __slots__ = ("module", "cls", "name", "file",
                 "acquires", "blocking", "all_blocking", "calls", "writes")

    def __init__(self, module, cls, name, file):
        self.module = module
        self.cls = cls          # class name or None
        self.name = name
        self.file = file
        # (lockname, line, held_names_tuple) — every acquisition
        self.acquires = []
        # (desc, line, held_tuple, with_lines) — blocking call while held
        self.blocking = []
        # (desc, line) — every blocking-registry call, held or not (what
        # a caller holding a lock inherits, one level deep)
        self.all_blocking = []
        # (callee_ref, line, held_tuple, with_lines)
        self.calls = []
        # (attr, line, frozenset(held), is_aug)
        self.writes = []


class ClassInfo:
    __slots__ = ("name", "module", "file", "attr_locks", "attr_types",
                 "methods", "thread_entries")

    def __init__(self, name, module, file):
        self.name = name
        self.module = module
        self.file = file
        self.attr_locks = {}     # attr -> lock name
        self.attr_types = {}     # attr -> class name (from ClassName(...))
        self.methods = {}        # name -> FuncInfo
        self.thread_entries = set()


class ModuleInfo:
    __slots__ = ("name", "file", "tree", "mod_locks", "classes",
                 "functions", "import_aliases", "pragmas")

    def __init__(self, name, file):
        self.name = name
        self.file = file
        self.tree = None
        self.mod_locks = {}      # var -> lock name
        self.classes = {}
        self.functions = {}      # name -> FuncInfo
        self.import_aliases = {} # alias -> module dotted path
        self.pragmas = {}        # line -> reason text


class Analyzer:
    def __init__(self):
        self.modules = {}        # module name -> ModuleInfo
        self.class_index = {}    # class name -> ClassInfo (global)
        self.lock_defs = {}      # lock name -> LockDef
        self.diags = []
        self.edges = []          # (from_lock, to_lock, file, line, note)

    # -- pass 1: parse, collect lock defs / maps / raw primitives ----------
    def load(self, files):
        for path, relname in files:
            mi = ModuleInfo(relname, path)
            try:
                with open(path) as f:
                    src = f.read()
                mi.tree = ast.parse(src)
            except (OSError, SyntaxError) as e:
                self.diags.append(Diag(
                    SEV_ERROR, "parse_error", relname, 0, (),
                    f"cannot parse: {e}"))
                continue
            # pragmas come from COMMENT tokens only: the text '# lock-ok:'
            # inside a docstring or string literal documents the
            # convention, it does not grant (or count against) the
            # allowlist ratchet
            import io as _io
            import tokenize

            try:
                for tok in tokenize.generate_tokens(
                        _io.StringIO(src).readline):
                    if tok.type == tokenize.COMMENT and PRAGMA in tok.string:
                        mi.pragmas[tok.start[0]] = \
                            tok.string.split(PRAGMA, 1)[1].strip()
            except tokenize.TokenError:
                pass
            self.modules[mi.name] = mi
            self._collect_module(mi)

    def _lock_from_call(self, node):
        """(name, rank, reentrant, kind) for a named_lock-family Call,
        else None."""
        if not isinstance(node, ast.Call):
            return None
        fn = node.func
        term = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if term not in NAMED_LOCK_FACTORIES:
            return None
        name = rank = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            name = node.args[0].value
        if len(node.args) > 1 and isinstance(node.args[1], ast.Constant):
            rank = node.args[1].value
        reentrant = term == "named_rlock"
        for kw in node.keywords:
            if kw.arg == "rank" and isinstance(kw.value, ast.Constant):
                rank = kw.value.value
            if kw.arg == "reentrant" and isinstance(kw.value, ast.Constant):
                reentrant = bool(kw.value.value)
        return name, rank, reentrant, term

    def _register_lock(self, lock, file, line):
        name, rank, reentrant, kind = lock
        if name is None or not isinstance(rank, int):
            self.diags.append(Diag(
                SEV_ERROR, "unresolvable_lock", file, line, (name or "?",),
                "named_lock name and rank must be literal constants — the "
                "lint (and any reader) must be able to see the declared "
                "order without executing the program"))
            return
        prev = self.lock_defs.get(name)
        if prev is not None and prev.rank != rank:
            self.diags.append(Diag(
                SEV_ERROR, "rank_conflict", file, line, (name,),
                f"lock {name!r} declared with rank {rank} here but rank "
                f"{prev.rank} at {prev.file}:{prev.line} — one rank per "
                f"name"))
            return
        if prev is None:
            self.lock_defs[name] = LockDef(name, rank, reentrant, file,
                                           line, kind)
        elif reentrant and not prev.reentrant:
            prev.reentrant = True

    def _collect_module(self, mi):
        # import aliases (for resolving _bk.coalesce-style calls)
        for node in ast.walk(mi.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    mi.import_aliases[a.asname or a.name] = \
                        f"{node.module}.{a.name}"
            elif isinstance(node, ast.Import):
                for a in node.names:
                    mi.import_aliases[a.asname or a.name] = a.name
        # module-level lock vars
        for node in mi.tree.body:
            if isinstance(node, ast.Assign):
                lock = self._lock_from_call(node.value)
                if lock:
                    self._register_lock(lock, mi.name, node.lineno)
                    for t in node.targets:
                        if isinstance(t, ast.Name) and lock[0]:
                            mi.mod_locks[t.id] = lock[0]
        # raw threading primitives anywhere in the file — including
        # through module aliases (`import threading as th; th.Lock()`)
        from_threading = {a.asname or a.name
                          for n in ast.walk(mi.tree)
                          if isinstance(n, ast.ImportFrom)
                          and n.module == "threading"
                          for a in n.names if a.name in RAW_PRIMITIVES}
        threading_aliases = {"threading"} | {
            a.asname or a.name
            for n in ast.walk(mi.tree) if isinstance(n, ast.Import)
            for a in n.names if a.name == "threading"}
        for node in ast.walk(mi.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            raw = None
            if isinstance(fn, ast.Attribute) and fn.attr in RAW_PRIMITIVES \
                    and isinstance(fn.value, ast.Name) \
                    and fn.value.id in threading_aliases:
                raw = fn.attr
            elif isinstance(fn, ast.Name) and fn.id in from_threading:
                raw = fn.id
            if raw:
                # NO pragma escape for this class: the unnamed-lock floor
                # is zero, full stop — a '# lock-ok:' comment allowlists
                # audited blocking-under-lock, never a raw primitive
                self.diags.append(Diag(
                    SEV_ERROR, "unnamed_lock", mi.name, node.lineno, (),
                    f"raw threading.{raw}() — framework locks go through "
                    f"paddle_tpu.core.locks.named_lock(name, rank) so they "
                    f"carry an identity, a declared order, and telemetry"))
        # classes and functions
        for node in mi.tree.body:
            if isinstance(node, ast.ClassDef):
                ci = ClassInfo(node.name, mi.name, mi.name)
                mi.classes[node.name] = ci
                self.class_index.setdefault(node.name, ci)
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        ci.methods[sub.name] = None  # filled in pass 2
                        if sub.name == "__init__":
                            self._collect_init(mi, ci, sub)
                        self._collect_thread_entries(ci, sub)
            elif isinstance(node, ast.FunctionDef):
                mi.functions[node.name] = None

    def _collect_init(self, mi, ci, fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                lock = self._lock_from_call(node.value)
                if lock:
                    self._register_lock(lock, mi.name, node.lineno)
                    if lock[0]:
                        ci.attr_locks[t.attr] = lock[0]
                    continue
                # attr type from any ClassName(...) call in the value
                for c in ast.walk(node.value):
                    if isinstance(c, ast.Call):
                        cn = c.func.id if isinstance(c.func, ast.Name) \
                            else (c.func.attr
                                  if isinstance(c.func, ast.Attribute)
                                  else "")
                        if cn and cn[0].isupper():
                            ci.attr_types.setdefault(t.attr, cn)
                            break

    def _collect_thread_entries(self, ci, fn):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            target = None
            if d.endswith("Thread"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = kw.value
            elif d == "atexit.register" and node.args:
                target = node.args[0]
            if (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"):
                ci.thread_entries.add(target.attr)
        # sys.excepthook = self.m
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and any(_dotted(t) == "sys.excepthook"
                            for t in node.targets) \
                    and isinstance(node.value, ast.Attribute) \
                    and isinstance(node.value.value, ast.Name) \
                    and node.value.value.id == "self":
                ci.thread_entries.add(node.value.attr)

    # -- pass 2: per-function walk -----------------------------------------
    def analyze_functions(self):
        for mi in self.modules.values():
            if mi.tree is None:
                continue
            for node in mi.tree.body:
                if isinstance(node, ast.FunctionDef):
                    fi = FuncInfo(mi.name, None, node.name, mi.name)
                    mi.functions[node.name] = fi
                    _FuncWalker(self, mi, None, fi).run(node)
                elif isinstance(node, ast.ClassDef):
                    ci = mi.classes[node.name]
                    for sub in node.body:
                        if isinstance(sub, ast.FunctionDef):
                            fi = FuncInfo(mi.name, ci.name, sub.name,
                                          mi.name)
                            ci.methods[sub.name] = fi
                            _FuncWalker(self, mi, ci, fi).run(sub)

    # -- pass 3: interprocedural (one level) + checks ----------------------
    def _resolve_callee(self, mi, ref):
        kind, a, b = ref
        if kind == "cls":
            ci = self.class_index.get(a)
            if ci is None:
                return None
            fi = ci.methods.get(b)
            return fi
        if kind == "mod":
            m = self.modules.get(a)
            if m is None:
                return None
            if b in m.functions:
                return m.functions[b]
            if b in m.classes:
                return m.classes[b].methods.get("__init__")
            return None
        return None

    def _local_callees(self, fi):
        """Callees that count as fi's own internals: same-class self-calls
        and same-module functions — their behavior folds transitively into
        fi's effective surface (a private helper must not hide blocking
        work from fi's callers)."""
        mi = self.modules[fi.module]
        for ref, line, _held, _wl in fi.calls:
            g = None
            if ref[0] == "cls" and fi.cls is not None and ref[1] == fi.cls:
                ci = mi.classes.get(fi.cls)
                g = ci.methods.get(ref[2]) if ci else None
            elif ref[0] == "mod" and ref[1] == fi.module:
                g = mi.functions.get(ref[2])
            if g is not None and g is not fi:
                yield g, line

    def _eff_blocking(self, fi, _stack=None):
        """fi's blocking calls, with same-class/same-module helpers folded
        in transitively; entries re-anchored to fi's own call lines."""
        memo = self._memo_blocking
        got = memo.get(id(fi))
        if got is not None:
            return got
        stack = _stack or set()
        if id(fi) in stack:
            return []
        stack = stack | {id(fi)}
        out = list(fi.all_blocking)
        for g, line in self._local_callees(fi):
            gname = f"{g.cls}.{g.name}" if g.cls else g.name
            for desc, _bl in self._eff_blocking(g, stack):
                out.append((f"call to {gname}() which does {desc}", line))
        memo[id(fi)] = out
        return out

    def _eff_acquires(self, fi, _stack=None):
        memo = self._memo_acquires
        got = memo.get(id(fi))
        if got is not None:
            return got
        stack = _stack or set()
        if id(fi) in stack:
            return []
        stack = stack | {id(fi)}
        out = [(lockname, line) for lockname, line, _h in fi.acquires]
        for g, line in self._local_callees(fi):
            out.extend((lockname, line)
                       for lockname, _l in self._eff_acquires(g, stack))
        memo[id(fi)] = out
        return out

    def expand_calls(self):
        """One call level deep from the caller's perspective: a caller
        holding locks inherits its callee's effective acquisitions and
        blocking calls (the callee's own private-helper structure is
        folded — see _eff_blocking)."""
        self._memo_blocking = {}
        self._memo_acquires = {}
        all_funcs = []
        for mi in self.modules.values():
            all_funcs.extend(f for f in mi.functions.values() if f)
            for ci in mi.classes.values():
                all_funcs.extend(f for f in ci.methods.values() if f)
        for fi in all_funcs:
            mi = self.modules[fi.module]
            for ref, line, held, wlines in fi.calls:
                if not held:
                    continue
                callee = self._resolve_callee(mi, ref)
                if callee is None or callee is fi:
                    continue
                cname = (f"{callee.cls}.{callee.name}" if callee.cls
                         else callee.name)
                for lockname, _cline in self._eff_acquires(callee):
                    fi.acquires.append((lockname, line, held))
                for desc, bline in self._eff_blocking(callee):
                    fi.blocking.append(
                        (f"call to {cname}() which does {desc} "
                         f"[{callee.file}:{bline}]", line, held, wlines))
        return all_funcs

    def check_edges(self, all_funcs):
        ranks = {n: d.rank for n, d in self.lock_defs.items()}
        reent = {n: d.reentrant for n, d in self.lock_defs.items()}
        seen = set()
        for fi in all_funcs:
            for lockname, line, held in fi.acquires:
                if not held:
                    continue
                if lockname in held:
                    if not reent.get(lockname, False):
                        key = (fi.file, line, lockname, lockname)
                        if key not in seen:
                            seen.add(key)
                            self.diags.append(Diag(
                                SEV_ERROR, "self_deadlock", fi.file, line,
                                (lockname, lockname),
                                f"re-acquiring non-reentrant lock "
                                f"{lockname!r} (rank "
                                f"{ranks.get(lockname, '?')}) while already "
                                f"holding it — guaranteed deadlock; use "
                                f"named_rlock if re-entry is intended"))
                    continue
                known = [(h, ranks[h]) for h in held if h in ranks]
                if not known or lockname not in ranks:
                    continue
                top_name, top_rank = max(known, key=lambda kv: kv[1])
                self.edges.append((top_name, lockname, fi.file, line))
                if ranks[lockname] <= top_rank:
                    key = (fi.file, line, top_name, lockname)
                    if key not in seen:
                        seen.add(key)
                        self.diags.append(Diag(
                            SEV_ERROR, "lock_order_inversion", fi.file,
                            line, (top_name, lockname),
                            f"acquiring lock {lockname!r} (rank "
                            f"{ranks[lockname]}) while holding "
                            f"{top_name!r} (rank {top_rank}) inverts the "
                            f"declared order — another thread nesting "
                            f"these the other way deadlocks; re-rank or "
                            f"restructure"))

    def check_blocking(self, all_funcs):
        seen = set()
        for fi in all_funcs:
            mi = self.modules[fi.module]
            for desc, line, held, wlines in fi.blocking:
                key = (fi.file, line, desc)
                if key in seen:
                    continue
                seen.add(key)
                reason = None
                for ln in (line,) + tuple(wlines):
                    if ln in mi.pragmas:
                        reason = mi.pragmas[ln]
                        break
                self.diags.append(Diag(
                    SEV_ERROR, "blocking_under_lock", fi.file, line,
                    tuple(held),
                    f"{desc} while holding "
                    f"{' -> '.join(repr(h) for h in held)} — blocking work "
                    f"under a lock stalls every thread that wants it; move "
                    f"the work outside the critical section or add "
                    f"'# lock-ok: <reason>' after audit",
                    allowed=reason is not None, reason=reason or ""))

    def check_unguarded(self):
        for mi in self.modules.values():
            for ci in mi.classes.values():
                self._check_class_unguarded(mi, ci)

    def _entry_writes(self, ci, fi):
        """fi's writes plus one level of self-call expansion; callee
        writes inherit the locks held at the call site."""
        out = list(fi.writes)
        for ref, line, held, _wl in fi.calls:
            if ref[0] != "cls" or ref[1] != ci.name:
                continue
            callee = ci.methods.get(ref[2])
            if callee is None or callee is fi or callee.name == "__init__":
                continue
            for attr, wline, locks, aug in callee.writes:
                out.append((attr, wline, locks | frozenset(held), aug))
        return out

    def _check_class_unguarded(self, mi, ci):
        entries = {}  # entry label -> list of (attr, line, locks, aug)
        for m in ci.thread_entries:
            fi = ci.methods.get(m)
            if fi is not None:
                entries[f"thread:{m}"] = self._entry_writes(ci, fi)
        api_writes = []
        for name, fi in ci.methods.items():
            if fi is None or name.startswith("_") \
                    or name in ci.thread_entries:
                continue
            api_writes.extend(self._entry_writes(ci, fi))
        if api_writes:
            entries["api"] = api_writes
        if len(entries) < 2 and not ci.thread_entries:
            return
        attrs = {}
        for entry, writes in entries.items():
            for attr, line, locks, aug in writes:
                if attr in ci.attr_locks:
                    continue
                attrs.setdefault(attr, []).append((entry, line, locks, aug))
        for attr, ws in sorted(attrs.items()):
            ents = {e for e, _l, _k, _a in ws}
            if len(ents) < 2 or not any(e.startswith("thread:")
                                        for e in ents):
                continue
            common = None
            for _e, _l, locks, _a in ws:
                common = locks if common is None else (common & locks)
            if common:
                continue
            has_aug = any(a for _e, _l, _k, a in ws)
            lines = sorted({(e, l) for e, l, _k, _a in ws})
            self.diags.append(Diag(
                SEV_ERROR if has_aug else SEV_WARNING,
                "unguarded_shared_write", mi.name,
                min(l for _e, l in lines), (),
                f"{ci.name}.{attr} written from multiple thread entry "
                f"points without a common named lock: "
                f"{', '.join(f'{e}@{l}' for e, l in lines)}"
                + (" — includes a read-modify-write (+=), the lost-update "
                   "race" if has_aug else
                   " — concurrent plain stores; last writer wins "
                   "silently")))


class _FuncWalker(ast.NodeVisitor):
    """Walks one function tracking the held-lock stack."""

    def __init__(self, az, mi, ci, fi):
        self.az = az
        self.mi = mi
        self.ci = ci
        self.fi = fi
        self.held = []       # lock names
        self.with_lines = [] # line numbers of active lock-withs
        self.param_types = {}

    def run(self, node):
        for arg in list(node.args.args) + list(node.args.kwonlyargs):
            ann = arg.annotation
            if isinstance(ann, ast.Name):
                self.param_types[arg.arg] = ann.id
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                self.param_types[arg.arg] = ann.value.strip("'\"")
        for stmt in node.body:
            self.visit(stmt)

    # nested defs/classes analyzed separately (closures are out of scope)
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    # -- lock expression resolution ----------------------------------------
    def _lock_name(self, expr):
        if isinstance(expr, ast.Name):
            return self.mi.mod_locks.get(expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                          ast.Name):
            base = expr.value.id
            if base == "self" and self.ci is not None:
                return self.ci.attr_locks.get(expr.attr)
            pt = self.param_types.get(base)
            if pt and pt in self.az.class_index:
                return self.az.class_index[pt].attr_locks.get(expr.attr)
        return None

    def _record_acquire(self, lockname, line):
        self.fi.acquires.append((lockname, line, tuple(self.held)))

    # -- with ---------------------------------------------------------------
    def visit_With(self, node):
        base = len(self.held)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            ln = self._lock_name(item.context_expr)
            if ln is not None:
                self._record_acquire(ln, node.lineno)
                self.held.append(ln)
                self.with_lines.append(node.lineno)
        for stmt in node.body:
            self.visit(stmt)
        # truncate to entry depth: releases the with's own locks AND any
        # unbalanced manual acquire left open inside the body
        del self.held[base:]
        del self.with_lines[base:]

    # -- calls ---------------------------------------------------------------
    def _blocking(self, desc, line):
        self.fi.all_blocking.append((desc, line))
        if self.held:
            self.fi.blocking.append((desc, line, tuple(self.held),
                                     tuple(self.with_lines)))

    def _callee_ref(self, fn):
        if isinstance(fn, ast.Name):
            return ("mod", self.mi.name, fn.id)
        if isinstance(fn, ast.Attribute):
            v = fn.value
            if isinstance(v, ast.Name):
                if v.id == "self" and self.ci is not None:
                    return ("cls", self.ci.name, fn.attr)
                pt = self.param_types.get(v.id)
                if pt:
                    return ("cls", pt, fn.attr)
                tgt = self.mi.import_aliases.get(v.id)
                if tgt:
                    leaf = tgt.rsplit(".", 1)[-1]
                    for modname in (tgt, leaf):
                        if modname in self.az.modules:
                            return ("mod", modname, fn.attr)
            if isinstance(v, ast.Attribute) and isinstance(v.value,
                                                           ast.Name) \
                    and v.value.id == "self" and self.ci is not None:
                t = self.ci.attr_types.get(v.attr)
                if t:
                    return ("cls", t, fn.attr)
        return None

    def visit_Call(self, node):
        fn = node.func
        dotted = _dotted(fn)
        term_attr = fn.attr if isinstance(fn, ast.Attribute) else None
        # lock method calls.  Manual acquire()/release() pairs track the
        # held stack just like `with`: everything between them (in
        # statement order) is analyzed as under the lock.  This
        # OVERAPPROXIMATES conditional acquires (`ok = X.acquire(False)`)
        # — a linter prefers a false positive over a hole — and a lock
        # held past the end of the function simply stops being tracked
        # there (cross-function holds are the caller's with-block to see).
        if term_attr == "acquire":
            ln = self._lock_name(fn.value)
            if ln is not None:
                self._record_acquire(ln, node.lineno)
                self.held.append(ln)
                self.with_lines.append(node.lineno)
        elif term_attr == "release":
            ln = self._lock_name(fn.value)
            if ln is not None and ln in self.held:
                i = len(self.held) - 1 - self.held[::-1].index(ln)
                del self.held[i]
                del self.with_lines[i]
        elif term_attr == "wait":
            ln = self._lock_name(fn.value)
            if ln is not None and ln in self.held:
                pass  # condition wait on the held lock releases it: legal
            elif self.held:
                what = dotted or "<expr>.wait"
                if ln is not None:
                    self._blocking(
                        f"{what}() waits on lock {ln!r}, which this thread "
                        f"does NOT hold", node.lineno)
                else:
                    self._blocking(f"blocking {what}()", node.lineno)
        elif dotted in BLOCKING_DOTTED:
            self._blocking(f"blocking call {dotted}()", node.lineno)
        elif dotted not in NOT_BLOCKING_DOTTED and term_attr is not None \
                and term_attr in BLOCKING_METHODS \
                and not isinstance(fn.value, ast.Constant):
            self._blocking(f"blocking call {dotted or term_attr}()",
                           node.lineno)
        elif isinstance(fn, ast.Name) and fn.id in BLOCKING_CALLABLES:
            self._blocking(f"blocking call {fn.id}()", node.lineno)
        elif term_attr in BLOCKING_CALLABLES:
            self._blocking(f"blocking call {dotted or term_attr}()",
                           node.lineno)
        ref = self._callee_ref(fn)
        if ref is not None:
            self.fi.calls.append((ref, node.lineno, tuple(self.held),
                                  tuple(self.with_lines)))
        for a in node.args:
            self.visit(a)
        for kw in node.keywords:
            self.visit(kw.value)
        if isinstance(fn, ast.Attribute):
            self.visit(fn.value)

    # -- writes --------------------------------------------------------------
    def _write_target_attr(self, t):
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return t.attr
        if isinstance(t, ast.Subscript):
            v = t.value
            if isinstance(v, ast.Attribute) and isinstance(v.value,
                                                           ast.Name) \
                    and v.value.id == "self":
                return v.attr
        return None

    def visit_Assign(self, node):
        for t in node.targets:
            attr = self._write_target_attr(t)
            if attr is not None:
                self.fi.writes.append((attr, node.lineno,
                                       frozenset(self.held), False))
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        attr = self._write_target_attr(node.target)
        if attr is not None:
            self.fi.writes.append((attr, node.lineno,
                                   frozenset(self.held), True))
        self.generic_visit(node)


# ---- driver -----------------------------------------------------------------

def collect_files(paths):
    out = []
    for p in paths:
        if os.path.isfile(p):
            out.append((p, os.path.splitext(os.path.basename(p))[0]))
            continue
        root = os.path.abspath(p)
        for dirpath, _dirs, names in os.walk(root):
            for n in sorted(names):
                if not n.endswith(".py"):
                    continue
                full = os.path.join(dirpath, n)
                rel = os.path.relpath(full, root)
                if rel in SKIP_RELPATHS:
                    continue
                # module key: the dotted-ish relative path without .py
                mod = os.path.splitext(rel)[0].replace(os.sep, "/")
                out.append((full, mod))
    return out


def lint(paths):
    az = Analyzer()
    az.load(collect_files(paths))
    az.analyze_functions()
    all_funcs = az.expand_calls()
    az.check_edges(all_funcs)
    az.check_blocking(all_funcs)
    az.check_unguarded()
    return az


def _fmt_table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def render(az):
    parts = [f"# concurrency lint  modules={len(az.modules)}  "
             f"locks={len(az.lock_defs)}"]
    if az.lock_defs:
        rows = [(d.rank, n, d.kind + (" (reentrant)" if d.reentrant else ""),
                 f"{d.file}:{d.line}")
                for n, d in sorted(az.lock_defs.items(),
                                   key=lambda kv: kv[1].rank)]
        parts.append("\n## lock rank table (ascending = outer -> inner)\n"
                     + _fmt_table(rows, ["rank", "name", "kind", "defined"]))
    edges = sorted({(a, b) for a, b, _f, _l in az.edges})
    if edges:
        parts.append("\n## observed acquisition edges\n" + "\n".join(
            f"- {a} -> {b}" for a, b in edges))
    active = [d for d in az.diags if not d.allowed]
    allowed = [d for d in az.diags if d.allowed]
    if active:
        parts.append("\n## diagnostics\n" + _fmt_table(
            [(d.severity, d.code, d.where(),
              " -> ".join(d.locks) if d.locks else "-") for d in active],
            ["severity", "code", "where", "locks"]))
        parts.append("\n## messages")
        for d in active:
            parts.append(f"- {d.where()}: [{d.severity}:{d.code}] "
                         f"{d.message}")
    else:
        parts.append("\nno active diagnostics")
    # the ratchet counts pragma SITES (one audited decision each), used
    # or not — a dormant pragma is still standing permission
    sites = sorted((mi.name, ln, reason)
                   for mi in az.modules.values()
                   for ln, reason in mi.pragmas.items())
    if sites:
        parts.append(f"\n## allowlist ({len(sites)} '# lock-ok:' sites, "
                     f"ratchet {ALLOWLIST_MAX}; "
                     f"{len(allowed)} finding(s) covered)")
        for f, ln, reason in sites:
            parts.append(f"- {f}:{ln} — {reason}")
        for d in allowed:
            parts.append(f"  · covered: {d.where()} [{d.code}]")
    n_err = sum(1 for d in active if d.severity == SEV_ERROR)
    n_warn = sum(1 for d in active if d.severity == SEV_WARNING)
    n_unnamed = sum(1 for d in active if d.code == "unnamed_lock")
    parts.append(f"\n## summary\nerrors={n_err} warnings={n_warn} "
                 f"unnamed_locks={n_unnamed} allowlist_sites={len(sites)}")
    return "\n".join(parts), n_err, n_unnamed, len(sites)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the paddle_tpu/ "
                         "tree next to this tool)")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: exit 1 on errors, unnamed locks, or an "
                         "allowlist above the ratchet")
    ap.add_argument("--max-allowlist", type=int, default=ALLOWLIST_MAX,
                    help=f"allowlist ratchet for --check (default "
                         f"{ALLOWLIST_MAX}); lower it as entries retire, "
                         f"never raise it without review")
    args = ap.parse_args(argv)

    paths = args.paths
    if not paths:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = [os.path.join(repo, "paddle_tpu")]
    az = lint(paths)
    text, n_err, n_unnamed, n_allowed = render(az)
    print(text)
    if args.check:
        failed = False
        if n_err:
            print(f"\nCHECK FAILED: {n_err} error-severity diagnostic(s)")
            failed = True
        if n_unnamed:
            print(f"\nCHECK FAILED: {n_unnamed} unnamed raw threading "
                  f"primitive(s) — floor is zero")
            failed = True
        if n_allowed > args.max_allowlist:
            print(f"\nCHECK FAILED: {n_allowed} allowlist entries exceed "
                  f"the ratchet ({args.max_allowlist}) — new "
                  f"blocking-under-lock keeps need the same review a new "
                  f"lock would get")
            failed = True
        if failed:
            return 1
        print(f"\nCHECK OK: 0 errors, 0 unnamed locks, "
              f"{n_allowed}/{args.max_allowlist} allowlist entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
