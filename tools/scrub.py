#!/usr/bin/env python
"""Scrub checkpoint trees and inference-model dirs for silent corruption
(paddle_tpu/integrity.py — ISSUE 14).

    python tools/scrub.py ROOT [ROOT2 ...]
        Walk each root, find every checkpoint / inference-model directory
        (anything carrying a __manifest__.json, __sharded_manifest__.json,
        or __model__.json) plus every RecordIO file (identified by chunk
        magic, not extension), and render a findings table: re-hash every
        manifest-stamped file against its recorded sha256 + byte length,
        flag files a manifest names but the disk lost, and run the native
        CRC scanner over the RecordIO chunks.

    python tools/scrub.py --check ROOT [...]
        CI gate (same shape as program_lint/concurrency_lint --check):
        exit 1 on any error-class finding — digest_mismatch,
        bytes_mismatch, missing_file, unreadable_file (EACCES/EIO
        mid-scan, ISSUE 15), manifest_error, corrupt RecordIO
        chunks.  Warnings (undigested legacy manifest entries,
        uncommitted pending dirs the restore walk-back already refuses)
        never fail the gate.  Wired into tier-1 via
        tests/test_integrity.py, so a clean tree stays provably clean.

This is the OFFLINE half of the corruption defense: the live digests
catch in-memory rot between checkpoints, the load-path verification
catches rot at restore/publish time, and the scrub finds it while the
data merely sits — before any restore has to discover it the hard way.

Exit codes: 0 clean (warnings allowed), 1 error findings.
"""
from __future__ import annotations

import argparse
import os
import struct
import sys

if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RECORDIO_MAGIC = 0x01020304

# error classes fail --check; anything else renders as a warning.
# unreadable_file (EACCES/EIO mid-scan, ISSUE 15) is an error: a file
# the scrub cannot hash is a file a restore cannot trust
ERROR_CLASSES = ("digest_mismatch", "bytes_mismatch", "missing_file",
                 "unreadable_file", "manifest_error", "corrupt_chunks")


def _fmt_table(rows, headers):
    widths = [max(len(str(r[i])) for r in rows + [headers])
              for i in range(len(headers))]
    out = ["  ".join(str(h).ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(out)


def is_recordio(path: str) -> bool:
    """RecordIO files are identified by their chunk-header magic, not by
    extension — dataset files are named whatever the producer liked."""
    try:
        with open(path, "rb") as f:
            head = f.read(4)
    except OSError:
        return False
    return len(head) == 4 and struct.unpack("<I", head)[0] == RECORDIO_MAGIC


def _is_snapshot_dir(d: str) -> bool:
    from paddle_tpu import io as _io

    return any(os.path.exists(os.path.join(d, m))
               for m in (_io.MANIFEST, _io.SHARDED_MANIFEST,
                         _io.MODEL_FILENAME))


def _count_chunks(path: str) -> int:
    """Framed chunks in a RecordIO file (header walk, tolerant of a
    broken tail — the same framing faults._mutate_chunk navigates)."""
    n = 0
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off + 20 <= len(data):
        magic, _nrecs = struct.unpack_from("<II", data, off)
        (plen,) = struct.unpack_from("<Q", data, off + 8)
        if magic != RECORDIO_MAGIC or off + 20 + plen > len(data):
            break
        n += 1
        off += 20 + int(plen)
    return n


def scan_recordio(path: str):
    """(records, chunks, corrupt_chunks) via the native tolerant scanner
    — the same CRC path production reads take, not a reimplementation.
    The per-run corrupt budget is parked out of the way for the scan (a
    scrub COUNTS corruption, it does not spend a training run's budget)
    and restored after."""
    from paddle_tpu import recordio
    from paddle_tpu.flags import get_flags, set_flags

    prev = get_flags("FLAGS_data_corrupt_budget")["FLAGS_data_corrupt_budget"]
    set_flags({"FLAGS_data_corrupt_budget": 1 << 30})
    try:
        recordio.reset_corrupt_spent()
        sc = recordio.Scanner(path, tolerant=True)
        records = sum(1 for _ in sc)
        # the scanner closes itself at exhaustion; the property reports
        # the settled count
        corrupt = int(sc.corrupt_chunks)
        return records, _count_chunks(path), corrupt
    finally:
        set_flags({"FLAGS_data_corrupt_budget": prev})
        recordio.reset_corrupt_spent()


def scan_roots(roots):
    """Walk the roots; returns (findings, stats).  A finding is
    (where, class, detail); stats counts what was covered so the report
    can say "clean" with a denominator instead of a shrug."""
    from paddle_tpu import integrity
    from paddle_tpu.checkpoint_manager import COMMITTED_MARKER, DIST_MARKER

    findings = []
    stats = {"dirs": 0, "files_hashed": 0, "recordio_files": 0,
             "recordio_chunks": 0}
    for root in roots:
        if os.path.isfile(root):
            candidates = [root]
            walk = []
        else:
            walk = sorted(os.walk(root))
            candidates = []
        for dirpath, _dirnames, filenames in walk:
            if _is_snapshot_dir(dirpath):
                stats["dirs"] += 1
                if dirpath.rstrip(os.sep).endswith(".tmp"):
                    findings.append((dirpath, "pending_tmp",
                                     "uncommitted pending dir (restore "
                                     "already refuses it)"))
                elif (os.path.exists(os.path.join(dirpath, DIST_MARKER))
                      and not os.path.exists(
                          os.path.join(dirpath, COMMITTED_MARKER))):
                    findings.append((dirpath, "uncommitted",
                                     "distributed save without COMMITTED "
                                     "marker (torn commit)"))
                dir_findings = integrity.scan_snapshot_dir(dirpath)
                for f in dir_findings:
                    findings.append((os.path.join(dirpath, f["file"])
                                     if f["class"] != "manifest_error"
                                     else f["file"],
                                     f["class"], f["detail"]))
                # count entries only when the manifests parsed — a torn
                # manifest is already a manifest_error finding, and
                # re-walking it here would crash the whole scan (one
                # rotted manifest must never mask every other root)
                if not any(f["class"] == "manifest_error"
                           for f in dir_findings):
                    try:
                        stats["files_hashed"] += sum(
                            1 for _ in
                            integrity._manifest_file_entries(dirpath))
                    except Exception:
                        pass
            candidates.extend(os.path.join(dirpath, fn)
                              for fn in sorted(filenames))
        for path in candidates:
            if not is_recordio(path):
                continue
            stats["recordio_files"] += 1
            try:
                _records, chunks, corrupt = scan_recordio(path)
            except Exception as e:
                findings.append((path, "corrupt_chunks",
                                 f"scan died: {type(e).__name__}: {e}"))
                continue
            stats["recordio_chunks"] += chunks
            if corrupt:
                findings.append((path, "corrupt_chunks",
                                 f"{corrupt} CRC-failed chunk(s) of "
                                 f"{chunks}"))
    return findings, stats


def render(roots):
    findings, stats = scan_roots(roots)
    errors = [f for f in findings if f[1] in ERROR_CLASSES]
    parts = [f"# scrub  roots={list(roots)}  snapshot dirs={stats['dirs']}  "
             f"files hashed={stats['files_hashed']}  "
             f"recordio files={stats['recordio_files']} "
             f"(chunks {stats['recordio_chunks']})"]
    if findings:
        parts.append("\n## findings\n" + _fmt_table(
            [(w, c, d) for w, c, d in findings],
            ["where", "class", "detail"]))
    else:
        parts.append("\nno findings — tree is clean")
    return "\n".join(parts), findings, len(errors)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("roots", nargs="+",
                    help="checkpoint roots / model dirs / dataset dirs "
                         "(or single RecordIO files) to scrub")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: exit 1 on any error-class finding "
                         f"({', '.join(ERROR_CLASSES)})")
    args = ap.parse_args(argv)

    text, findings, n_errors = render(args.roots)
    print(text)
    if args.check:
        if n_errors:
            print(f"\nCHECK FAILED: {n_errors} error finding(s)")
            return 1
        warn = len(findings) - n_errors
        print(f"\nCHECK OK: 0 errors"
              + (f" ({warn} warning(s))" if warn else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
