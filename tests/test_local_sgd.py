"""LocalSGD (reference transpiler/collective.py:249): k local steps then one
parameter-averaging collective per round."""
import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.local_sgd import local_sgd_train


def _step(lr=0.1):
    def step(params, batch):
        x, y = batch["x"], batch["y"]

        def loss_fn(p):
            pred = x @ p["w"] + p["b"]
            return jnp.mean((pred - y) ** 2)

        loss, g = jax.value_and_grad(loss_fn)(params)
        new = jax.tree_util.tree_map(lambda p, gg: p - lr * gg, params, g)
        return new, loss

    return step


def _data(n_workers, rounds, k, d=6, mb=8, seed=0):
    rng = np.random.RandomState(seed)
    w_true = rng.rand(d, 1).astype("f4")
    x = rng.rand(n_workers, rounds, k, mb, d).astype("f4")
    y = x @ w_true
    return {"x": jnp.asarray(x), "y": jnp.asarray(y)}, w_true


def test_local_sgd_sync1_matches_full_sync():
    """sync_every=1 is plain synchronous data parallelism: every worker's
    params stay identical to a sequential run over the averaged updates."""
    mesh = make_mesh((4,), ("dp",))
    params = {"w": jnp.zeros((6, 1)), "b": jnp.zeros(())}
    batches, _ = _data(4, rounds=6, k=1)
    final, losses = local_sgd_train(_step(), params, batches, mesh, sync_every=1)

    # manual reference: each round, average the 4 workers' single-step params
    ref = {"w": np.zeros((6, 1), "f4"), "b": np.zeros((), "f4")}
    step = _step()
    for r in range(6):
        outs = []
        for wkr in range(4):
            b = {"x": np.asarray(batches["x"][wkr, r, 0]),
                 "y": np.asarray(batches["y"][wkr, r, 0])}
            p2, _ = step({k: jnp.asarray(v) for k, v in ref.items()}, b)
            outs.append(jax.tree_util.tree_map(np.asarray, p2))
        ref = {k: np.mean([o[k] for o in outs], axis=0) for k in ref}
    np.testing.assert_allclose(np.asarray(final["w"]), ref["w"], atol=1e-5)
    assert losses.shape == (4, 6, 1)


def test_local_sgd_k4_converges_and_averages():
    mesh = make_mesh((4,), ("dp",))
    params = {"w": jnp.zeros((6, 1)), "b": jnp.zeros(())}
    batches, w_true = _data(4, rounds=30, k=4, seed=1)
    final, losses = local_sgd_train(_step(0.2), params, batches, mesh, sync_every=4)
    l = np.asarray(losses)  # [4, 30, 4]
    assert l.mean(axis=(0, 2))[-1] < l.mean(axis=(0, 2))[0] * 0.1
    np.testing.assert_allclose(np.asarray(final["w"]), w_true, atol=0.15)
