"""Gang worker for the telemetry-plane chaos suite (ISSUE 8).

Trains RUN_STEPS sync-SGD steps through `resilient_train_loop` under the
full stack: `fleet.init()` arms the heartbeat + watchdog AND the
telemetry plane (the supervisor's PADDLE_TELEMETRY_DIR names this rank's
metrics stream + flight recorder), faults come from FLAGS_fault_spec.

The suite drives all four flight-recorder trigger paths through this one
script:

    kill_worker@S:RANK   the victim dumps (fsynced) before its SIGKILL;
                         the survivor dumps on the peer-failure path
    stall_worker@S:R:SECS with SECS > the watchdog deadline: the blocked
                         peer dumps on watchdog expiry (and its live
                         straggler detector names the stalled rank first)
    preempt@S            SIGTERM -> resilient drain -> sigterm_drain dump
    device@S             TransientDeviceError with a zero retry budget ->
                         uncaught -> the crash excepthook dumps
"""
import json
import os
import sys

# must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=1").strip()

import numpy as np  # noqa: E402


def build_model():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 17
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 32, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.SGD(0.05).minimize(loss)
    return main, startup, loss


def main():
    import paddle_tpu as fluid
    from paddle_tpu import dist_resilience as dres
    from paddle_tpu.errors import DistributedError
    from paddle_tpu.fleet import fleet

    run_steps = int(os.environ.get("RUN_STEPS", "6"))
    try:
        fleet.init()  # heartbeat + watchdog + telemetry plane
        rank, world = fleet.worker_index(), fleet.worker_num()

        main_p, startup, loss = build_model()
        compiled = fleet.main_program(main_p) if world > 1 else main_p
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)

        per = 32 // world
        rng = np.random.RandomState(99)
        batches = []
        for _ in range(run_steps):
            xg = rng.rand(32, 16).astype("f4")
            batches.append({"x": xg[rank * per:(rank + 1) * per],
                            "y": xg.sum(1, keepdims=True)[
                                rank * per:(rank + 1) * per]})

        stats = fluid.resilient_train_loop(
            exe, compiled, lambda: list(batches), [loss], scope=scope,
            policy=fluid.RetryPolicy(max_device_retries=0,
                                     backoff_base_s=0.0),
            max_inflight=1, log_period=1)
    except DistributedError as e:
        print(f"DIST_FAILURE {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        dres.shutdown_health(mark_down=True)
        os._exit(dres.exit_code_for(e))

    print("RESULT " + json.dumps({
        "rank": rank, "world": world, "steps": stats.steps,
        "preempted": stats.preempted}), flush=True)
    dres.shutdown_health()


if __name__ == "__main__":
    main()
