"""Control flow tests (reference: test_while_op.py, test_cond / conditional
block tests, tensor array tests)."""
import numpy as np

import paddle_tpu as fluid


def test_while_loop_sum():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        n = fluid.layers.fill_constant([1], "float32", 10.0)
        acc = fluid.layers.fill_constant([1], "float32", 0.0)
        i.stop_gradient = True
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            new_acc = fluid.layers.elementwise_add(acc, i)
            fluid.layers.assign(new_acc, acc)
            fluid.layers.increment(i, 1.0)
            fluid.layers.less_than(i, n, cond=cond)
    exe = fluid.Executor(fluid.CPUPlace())
    (out,) = exe.run(main, fetch_list=[acc])
    assert float(out[0]) == 45.0  # 0+1+...+9


def test_while_matmul_power():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        n = fluid.layers.fill_constant([1], "float32", 3.0)
        cond = fluid.layers.less_than(i, n)
        w = fluid.layers.While(cond)
        with w.block():
            doubled = fluid.layers.scale(x, scale=2.0)
            fluid.layers.assign(doubled, x)
            fluid.layers.increment(i, 1.0)
            fluid.layers.less_than(i, n, cond=cond)
        out = fluid.layers.scale(x, scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 4), "f4")
    (r,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    np.testing.assert_allclose(r, xv * 8.0)


def test_cond_branches():
    for flag, expect in [(1.0, 30.0), (-1.0, 10.0)]:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("flag", [1], dtype="float32", append_batch_size=False)
            zero = fluid.layers.fill_constant([1], "float32", 0.0)
            pred = fluid.layers.greater_than(x, zero)
            t = fluid.layers.fill_constant([1], "float32", 30.0)
            f = fluid.layers.fill_constant([1], "float32", 10.0)
            out = fluid.layers.cond(
                pred,
                lambda: fluid.layers.scale(t, 1.0),
                lambda: fluid.layers.scale(f, 1.0),
            )
        exe = fluid.Executor(fluid.CPUPlace())
        (r,) = exe.run(main, feed={"flag": np.array([flag], "f4")}, fetch_list=[out])
        assert float(r[0]) == expect, (flag, r)


def test_tensor_array_outside_loop():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3], dtype="float32")
        i0 = fluid.layers.fill_constant([1], "int32", 0)
        i1 = fluid.layers.fill_constant([1], "int32", 1)
        arr = fluid.layers.array_write(x, i0)
        y = fluid.layers.scale(x, 2.0)
        fluid.layers.array_write(y, i1, array=arr)
        ln = fluid.layers.array_length(arr)
        r0 = fluid.layers.array_read(arr, i0)
        r1 = fluid.layers.array_read(arr, i1)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((2, 3), "f4")
    l, a, b = exe.run(main, feed={"x": xv}, fetch_list=[ln, r0, r1])
    assert int(l[0]) == 2
    np.testing.assert_allclose(a, xv)
    np.testing.assert_allclose(b, xv * 2)
