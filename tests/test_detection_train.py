"""Detection training-path ops: yolov3_loss, roi_pool, bipartite_match,
target_assign, rpn_target_assign, generate_proposals, detection_map.

Goldens are independent numpy transcriptions of the reference kernels
(operators/detection/yolov3_loss_op.h, roi_pool_op.h, bipartite_match_op.cc,
target_assign_op.h), following the reference OpTest files."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _run_prog(build, feed):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    outs = exe.run(main, feed=feed, fetch_list=list(fetches), scope=scope)
    return [np.asarray(o) for o in outs]


# --------------------------------------------------------------------------
# yolov3_loss golden (numpy transcription of yolov3_loss_op.h loops)
# --------------------------------------------------------------------------

def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


def _sce(x, label):
    return np.maximum(x, 0.0) - x * label + np.log1p(np.exp(-abs(x)))


def _ciou(b1, b2):
    inter_w = max(0.0, min(b1[0] + b1[2] / 2, b2[0] + b2[2] / 2)
                  - max(b1[0] - b1[2] / 2, b2[0] - b2[2] / 2))
    inter_h = max(0.0, min(b1[1] + b1[3] / 2, b2[1] + b2[3] / 2)
                  - max(b1[1] - b1[3] / 2, b2[1] - b2[3] / 2))
    inter = inter_w * inter_h
    return inter / max(b1[2] * b1[3] + b2[2] * b2[3] - inter, 1e-10)


def _np_yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, C,
                  ignore_thresh, downsample, smooth):
    n, _, h, w = x.shape
    m = len(anchor_mask)
    an_num = len(anchors) // 2
    b = gt_box.shape[1]
    input_size = downsample * h
    xr = x.reshape(n, m, 5 + C, h, w)
    loss = np.zeros(n)
    if smooth:
        delta = min(1.0 / C, 1.0 / 40)
        pos, neg = 1.0 - delta, delta
    else:
        pos, neg = 1.0, 0.0
    for i in range(n):
        obj_mask = np.zeros((m, h, w))
        for j in range(m):
            for k in range(h):
                for l in range(w):
                    a = anchor_mask[j]
                    pb = [(l + _sig(xr[i, j, 0, k, l])) / w,
                          (k + _sig(xr[i, j, 1, k, l])) / h,
                          np.exp(xr[i, j, 2, k, l]) * anchors[2 * a] / input_size,
                          np.exp(xr[i, j, 3, k, l]) * anchors[2 * a + 1] / input_size]
                    best = 0.0
                    for t in range(b):
                        if gt_box[i, t, 2] <= 0 or gt_box[i, t, 3] <= 0:
                            continue
                        best = max(best, _ciou(pb, gt_box[i, t]))
                    if best > ignore_thresh:
                        obj_mask[j, k, l] = -1
        for t in range(b):
            g = gt_box[i, t]
            if g[2] <= 0 or g[3] <= 0:
                continue
            gi, gj = int(g[0] * w), int(g[1] * h)
            best_iou, best_n = 0.0, 0
            for a in range(an_num):
                an = [0, 0, anchors[2 * a] / input_size, anchors[2 * a + 1] / input_size]
                iou = _ciou(an, [0, 0, g[2], g[3]])
                if iou > best_iou:
                    best_iou, best_n = iou, a
            if best_n not in anchor_mask:
                continue
            mi = anchor_mask.index(best_n)
            tx, ty = g[0] * w - gi, g[1] * h - gj
            tw = np.log(g[2] * input_size / anchors[2 * best_n])
            th = np.log(g[3] * input_size / anchors[2 * best_n + 1])
            scale = 2.0 - g[2] * g[3]
            loss[i] += _sce(xr[i, mi, 0, gj, gi], tx) * scale
            loss[i] += _sce(xr[i, mi, 1, gj, gi], ty) * scale
            loss[i] += abs(xr[i, mi, 2, gj, gi] - tw) * scale
            loss[i] += abs(xr[i, mi, 3, gj, gi] - th) * scale
            obj_mask[mi, gj, gi] = 1.0
            lab = gt_label[i, t]
            for c in range(C):
                loss[i] += _sce(xr[i, mi, 5 + c, gj, gi], pos if c == lab else neg)
        for j in range(m):
            for k in range(h):
                for l in range(w):
                    o = obj_mask[j, k, l]
                    if o > 1e-5:
                        loss[i] += _sce(xr[i, j, 4, k, l], 1.0) * o
                    elif o > -0.5:
                        loss[i] += _sce(xr[i, j, 4, k, l], 0.0)
    return loss


ANCHORS = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119]
MASK = [0, 1, 2]


def test_yolov3_loss_golden():
    rng = np.random.RandomState(5)
    n, h, w, C = 2, 5, 5, 4
    m = len(MASK)
    x = rng.randn(n, m * (5 + C), h, w).astype("f4") * 0.5
    gt_box = rng.uniform(0.1, 0.9, (n, 3, 4)).astype("f4")
    gt_box[:, :, 2:] = rng.uniform(0.05, 0.4, (n, 3, 2))
    gt_box[1, 2] = 0.0  # invalid gt row (w = h = 0)
    gt_label = rng.randint(0, C, (n, 3)).astype("int32")

    expect = _np_yolo_loss(x, gt_box, gt_label, ANCHORS, MASK, C, 0.7, 32, True)

    def build():
        xv = fluid.layers.data("x", [m * (5 + C), h, w], dtype="float32")
        gb = fluid.layers.data("gb", [3, 4], dtype="float32")
        gl = fluid.layers.data("gl", [3], dtype="int32")
        loss = fluid.layers.yolov3_loss(xv, gb, gl, ANCHORS, MASK, C,
                                        ignore_thresh=0.7, downsample_ratio=32)
        return [loss]

    (got,) = _run_prog(build, {"x": x, "gb": gt_box, "gl": gt_label})
    np.testing.assert_allclose(got.reshape(-1), expect, rtol=2e-4, atol=2e-4)


def test_yolov3_trains():
    """tiny conv head + yolov3_loss trains to decreasing loss (the e2e gate
    VERDICT r3 asked for)."""
    rng = np.random.RandomState(0)
    n, h, w, C = 4, 4, 4, 3
    m = len(MASK)
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [3, 32, 32], dtype="float32")
        gb = fluid.layers.data("gb", [2, 4], dtype="float32")
        gl = fluid.layers.data("gl", [2], dtype="int32")
        c1 = fluid.layers.conv2d(img, 16, 3, stride=2, padding=1, act="relu")
        c2 = fluid.layers.conv2d(c1, 32, 3, stride=2, padding=1, act="relu")
        head = fluid.layers.conv2d(c2, m * (5 + C), 3, stride=2, padding=1)
        loss = fluid.layers.mean(fluid.layers.yolov3_loss(
            head, gb, gl, ANCHORS, MASK, C, ignore_thresh=0.7,
            downsample_ratio=8))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    imgs = rng.rand(n, 3, 32, 32).astype("f4")
    boxes = rng.uniform(0.2, 0.8, (n, 2, 4)).astype("f4")
    boxes[:, :, 2:] = rng.uniform(0.1, 0.5, (n, 2, 2))
    labels = rng.randint(0, C, (n, 2)).astype("int32")
    feed = {"img": imgs, "gb": boxes, "gl": labels}
    losses = []
    for _ in range(40):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


# --------------------------------------------------------------------------
# roi_pool golden
# --------------------------------------------------------------------------

def _np_roi_pool(x, rois, batch_idx, ph, pw, scale):
    R = rois.shape[0]
    C, H, W = x.shape[1:]
    out = np.zeros((R, C, ph, pw), "f4")
    for r in range(R):
        x0 = int(round(rois[r, 0] * scale))
        y0 = int(round(rois[r, 1] * scale))
        x1 = int(round(rois[r, 2] * scale))
        y1 = int(round(rois[r, 3] * scale))
        rh, rw = max(y1 - y0 + 1, 1), max(x1 - x0 + 1, 1)
        bh, bw = rh / ph, rw / pw
        for i in range(ph):
            for j in range(pw):
                hs = min(max(int(np.floor(i * bh)) + y0, 0), H)
                he = min(max(int(np.ceil((i + 1) * bh)) + y0, 0), H)
                ws = min(max(int(np.floor(j * bw)) + x0, 0), W)
                we = min(max(int(np.ceil((j + 1) * bw)) + x0, 0), W)
                if he <= hs or we <= ws:
                    continue
                out[r, :, i, j] = x[batch_idx[r], :, hs:he, ws:we].max(axis=(1, 2))
    return out


def test_roi_pool_golden():
    rng = np.random.RandomState(11)
    x = rng.randn(2, 3, 8, 8).astype("f4")
    rois = np.array([[0, 0, 7, 7], [2, 2, 11, 11], [1, 0, 5, 3]], "f4")
    bidx = np.array([0, 1, 1], "int32")
    expect = _np_roi_pool(x, rois, bidx, 2, 2, 0.5)

    def build():
        xv = fluid.layers.data("x", [3, 8, 8], dtype="float32")
        rv = fluid.layers.data("rois", [4], dtype="float32")
        bv = fluid.layers.data("bidx", [], dtype="int32")
        out = fluid.layers.roi_pool(xv, rv, 2, 2, 0.5, rois_batch=bv)
        return [out]

    (got,) = _run_prog(build, {"x": x, "rois": rois, "bidx": bidx})
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# bipartite_match golden (reference greedy algorithm in numpy)
# --------------------------------------------------------------------------

def _np_bipartite(dist, match_type="bipartite", thresh=0.5):
    R, C = dist.shape
    idx = np.full(C, -1, "int32")
    dst = np.zeros(C, "f4")
    row_pool = list(range(R))
    while row_pool:
        best = (-1, -1, -1.0)
        for j in range(C):
            if idx[j] != -1:
                continue
            for r in row_pool:
                if dist[r, j] < 1e-6:
                    continue
                if dist[r, j] > best[2]:
                    best = (r, j, dist[r, j])
        if best[0] == -1:
            break
        idx[best[1]] = best[0]
        dst[best[1]] = best[2]
        row_pool.remove(best[0])
    if match_type == "per_prediction":
        for j in range(C):
            if idx[j] != -1:
                continue
            best_r, best_d = -1, -1.0
            for r in range(R):
                d = dist[r, j]
                if d >= 1e-6 and d >= thresh and d > best_d:
                    best_r, best_d = r, d
            if best_r != -1:
                idx[j] = best_r
                dst[j] = best_d
    return idx, dst


@pytest.mark.parametrize("mtype", ["bipartite", "per_prediction"])
def test_bipartite_match_golden(mtype):
    rng = np.random.RandomState(4)
    dist = rng.rand(2, 4, 7).astype("f4")
    dist[0, :, 5] = 0.0  # col with no usable row

    def build():
        d = fluid.layers.data("d", [4, 7], dtype="float32")
        idx, dst = fluid.layers.bipartite_match(d, match_type=mtype,
                                                dist_threshold=0.6)
        return [idx, dst]

    gi, gd = _run_prog(build, {"d": dist})
    for i in range(2):
        ei, ed = _np_bipartite(dist[i], mtype, 0.6)
        np.testing.assert_array_equal(gi[i], ei)
        np.testing.assert_allclose(gd[i], ed, rtol=1e-6)


def test_target_assign_golden():
    rng = np.random.RandomState(6)
    x = rng.randn(2, 3, 4).astype("f4")
    match = np.array([[0, -1, 2, 1], [-1, -1, 0, 0]], "int32")
    neg = np.array([[1, -1], [0, 1]], "int32")

    def build():
        xv = fluid.layers.data("x", [3, 4], dtype="float32")
        mv = fluid.layers.data("m", [4], dtype="int32")
        nv = fluid.layers.data("n", [2], dtype="int32")
        out, wt = fluid.layers.target_assign(xv, mv, negative_indices=nv,
                                             mismatch_value=0)
        return [out, wt]

    out, wt = _run_prog(build, {"x": x, "m": match, "n": neg})
    for i in range(2):
        for j in range(4):
            if match[i, j] >= 0:
                np.testing.assert_allclose(out[i, j], x[i, match[i, j]])
                assert wt[i, j, 0] == 1.0
            else:
                assert (out[i, j] == 0).all()
                expected_w = 1.0 if j in neg[i] else 0.0
                assert wt[i, j, 0] == expected_w, (i, j)


# --------------------------------------------------------------------------
# rpn_target_assign properties
# --------------------------------------------------------------------------

def _grid_anchors():
    # 4x4 grid of 16px cells, one 24x24 anchor per cell
    xs, ys = np.meshgrid(np.arange(4) * 16 + 8, np.arange(4) * 16 + 8)
    ctr = np.stack([xs.ravel(), ys.ravel()], 1).astype("f4")
    return np.concatenate([ctr - 12, ctr + 12], 1)  # [16, 4]


def test_rpn_target_assign_rules():
    anchors = _grid_anchors()
    gt = np.array([[[6, 6, 26, 26], [40, 40, 60, 60]]], "f4")
    im_info = np.array([[64, 64, 1.0]], "f4")

    def build():
        av = fluid.layers.data("a", [4], dtype="float32")
        gv = fluid.layers.data("g", [2, 4], dtype="float32")
        iv = fluid.layers.data("i", [3], dtype="float32")
        bp = fluid.layers.data("bp", [16, 4], dtype="float32")
        cl = fluid.layers.data("cl", [16, 1], dtype="float32")
        rets = fluid.layers.rpn_target_assign(
            bp, cl, av, None, gv, im_info=iv, rpn_batch_size_per_im=8,
            rpn_straddle_thresh=100.0, use_random=False)
        return rets[2:]  # label, tgt, inw, score_w

    feed = {"a": anchors, "g": gt, "i": im_info,
            "bp": np.zeros((1, 16, 4), "f4"), "cl": np.zeros((1, 16, 1), "f4")}
    label, tgt, inw, score_w = _run_prog(build, feed)
    # per-gt best anchors are positive even below the overlap threshold
    assert label.sum() >= 2
    # sampled set bounded by batch size
    assert score_w.sum() <= 8
    # fg rows have inside weight and finite bbox targets; bg rows are zero
    fg = label[0] == 1
    assert (inw[0][fg] == 1).all() and (inw[0][~fg] == 0).all()
    assert np.isfinite(tgt).all()
    # every fg anchor is also counted in the score weights
    assert (score_w[0][fg] == 1).all()


def test_rpn_target_assign_random_reproducible():
    anchors = _grid_anchors()
    gt = np.tile(np.array([[[6, 6, 26, 26]]], "f4"), (1, 1, 1))
    im_info = np.array([[64, 64, 1.0]], "f4")

    def build():
        av = fluid.layers.data("a", [4], dtype="float32")
        gv = fluid.layers.data("g", [1, 4], dtype="float32")
        iv = fluid.layers.data("i", [3], dtype="float32")
        bp = fluid.layers.data("bp", [16, 4], dtype="float32")
        cl = fluid.layers.data("cl", [16, 1], dtype="float32")
        rets = fluid.layers.rpn_target_assign(
            bp, cl, av, None, gv, im_info=iv, rpn_batch_size_per_im=4,
            rpn_straddle_thresh=100.0, use_random=True)
        return [rets[2], rets[5]]

    feed = {"a": anchors, "g": gt, "i": im_info,
            "bp": np.zeros((1, 16, 4), "f4"), "cl": np.zeros((1, 16, 1), "f4")}
    label, score_w = _run_prog(build, feed)
    assert score_w.sum() <= 4


# --------------------------------------------------------------------------
# generate_proposals
# --------------------------------------------------------------------------

def test_generate_proposals_identity_deltas():
    """zero deltas decode back to (clipped) anchors; padding slots have
    prob 0; min_size filters degenerate anchors."""
    rng = np.random.RandomState(9)
    N, A, H, W = 1, 2, 3, 3
    K = A * H * W
    scores = rng.rand(N, A, H, W).astype("f4")
    deltas = np.zeros((N, 4 * A, H, W), "f4")
    # anchors laid out [H, W, A, 4]
    anchors = np.zeros((H, W, A, 4), "f4")
    for h in range(H):
        for w in range(W):
            for a in range(A):
                cx, cy = w * 8 + 4, h * 8 + 4
                sz = 6 + 6 * a
                anchors[h, w, a] = [cx - sz / 2, cy - sz / 2, cx + sz / 2, cy + sz / 2]
    variances = np.ones((H, W, A, 4), "f4")
    im_info = np.array([[24, 24, 1.0]], "f4")

    def build():
        sv = fluid.layers.data("s", [A, H, W], dtype="float32")
        dv = fluid.layers.data("d", [4 * A, H, W], dtype="float32")
        iv = fluid.layers.data("i", [3], dtype="float32")
        av = fluid.layers.data("anc", [W, A, 4], dtype="float32")
        vv = fluid.layers.data("var", [W, A, 4], dtype="float32")
        rois, probs = fluid.layers.generate_proposals(
            sv, dv, iv, av, vv, pre_nms_top_n=K, post_nms_top_n=6,
            nms_thresh=0.9, min_size=1.0)
        return [rois, probs]

    rois, probs = _run_prog(build, {"s": scores, "d": deltas, "i": im_info,
                                    "anc": anchors, "var": variances})
    probs = probs[0, :, 0]
    rois = rois[0]
    valid = probs > 0
    assert valid.sum() > 0
    # every valid roi lies inside the image and meets min_size
    v = rois[valid]
    assert (v[:, 0] >= 0).all() and (v[:, 2] <= 23).all()
    assert ((v[:, 2] - v[:, 0] + 1) >= 1).all()
    # probs sorted descending over valid slots
    pv = probs[valid]
    assert (np.diff(pv) <= 1e-6).all()
    # the top-scoring surviving anchor decodes to itself (zero deltas)
    flat_scores = scores.transpose(0, 2, 3, 1).reshape(-1)
    top_anchor = anchors.reshape(-1, 4)[flat_scores.argmax()]
    expect = np.array([max(top_anchor[0], 0), max(top_anchor[1], 0),
                       min(top_anchor[2], 23), min(top_anchor[3], 23)])
    np.testing.assert_allclose(rois[0], expect, atol=1e-4)


# --------------------------------------------------------------------------
# detection_map
# --------------------------------------------------------------------------

def test_detection_map_perfect_and_mixed():
    # 2 classes (1, 2); image 0 has one gt of each; detections: one perfect
    # match per gt plus one false positive of class 1 (normalized boxes —
    # the reference ClipBBox clamps to [0, 1])
    det = np.array([[[1, 0.9, .1, .1, .2, .2],
                     [2, 0.8, .3, .3, .4, .4],
                     [1, 0.7, .5, .5, .6, .6],
                     [-1, 0.0, 0, 0, 0, 0]]], "f4")
    gt = np.array([[[1, .1, .1, .2, .2],
                    [2, .3, .3, .4, .4]]], "f4")

    def build():
        dv = fluid.layers.data("det", [4, 6], dtype="float32")
        gv = fluid.layers.data("gt", [2, 5], dtype="float32")
        m = fluid.layers.detection_map(dv, gv, class_num=3,
                                       overlap_threshold=0.5,
                                       ap_version="integral")
        return [m]

    (m,) = _run_prog(build, {"det": det, "gt": gt})
    # class 1: det .9 TP, det .7 FP -> AP = 1.0 (recall reached at rank 1)
    # class 2: perfect -> AP = 1.0
    np.testing.assert_allclose(float(m.reshape(-1)[0]), 1.0, atol=1e-6)


def test_detection_map_difficult_excluded():
    """6-col labels carry the difficult flag; evaluate_difficult=False
    drops difficult gts from npos and their matches from TP/FP."""
    det = np.array([[[1, 0.9, .1, .1, .2, .2],
                     [1, 0.8, .5, .5, .6, .6]]], "f4")
    gt = np.array([[[1, 0, .1, .1, .2, .2],
                    [1, 1, .5, .5, .6, .6]]], "f4")  # second gt difficult

    def build():
        dv = fluid.layers.data("det", [2, 6], dtype="float32")
        gv = fluid.layers.data("gt", [2, 6], dtype="float32")
        m1 = fluid.layers.detection_map(dv, gv, class_num=2,
                                        evaluate_difficult=False)
        m2 = fluid.layers.detection_map(dv, gv, class_num=2,
                                        evaluate_difficult=True)
        return [m1, m2]

    m1, m2 = _run_prog(build, {"det": det, "gt": gt})
    # excluded: npos=1, the difficult match is skipped -> AP 1.0
    np.testing.assert_allclose(float(m1.reshape(-1)[0]), 1.0, atol=1e-6)
    # included: both gts count, both dets TP -> AP 1.0 as well
    np.testing.assert_allclose(float(m2.reshape(-1)[0]), 1.0, atol=1e-6)


def test_yolov3_padding_gt_does_not_clobber_real_gt():
    """regression: a zero padding gt row used to scatter a stale value over
    a real gt's objectness score at cell (0, 0)/anchor 0."""
    rng = np.random.RandomState(2)
    n, h, w, C = 1, 4, 4, 2
    m = len(MASK)
    x = rng.randn(n, m * (5 + C), h, w).astype("f4") * 0.3
    # real gt centered in cell (0, 0), sized to match anchor 0 exactly
    gt_box = np.zeros((n, 2, 4), "f4")
    gt_box[0, 0] = [0.1, 0.1, 10 / 32.0, 13 / 32.0]
    gt_label = np.zeros((n, 2), "int32")

    expect = _np_yolo_loss(x, gt_box, gt_label, ANCHORS, MASK, C, 0.7, 8, True)

    def build():
        xv = fluid.layers.data("x", [m * (5 + C), h, w], dtype="float32")
        gb = fluid.layers.data("gb", [2, 4], dtype="float32")
        gl = fluid.layers.data("gl", [2], dtype="int32")
        loss = fluid.layers.yolov3_loss(xv, gb, gl, ANCHORS, MASK, C,
                                        ignore_thresh=0.7, downsample_ratio=8)
        return [loss]

    (got,) = _run_prog(build, {"x": x, "gb": gt_box, "gl": gt_label})
    np.testing.assert_allclose(got.reshape(-1), expect, rtol=2e-4, atol=2e-4)


def test_rpn_target_assign_without_im_info():
    anchors = _grid_anchors()
    gt = np.array([[[6, 6, 26, 26]]], "f4")

    def build():
        av = fluid.layers.data("a", [4], dtype="float32")
        gv = fluid.layers.data("g", [1, 4], dtype="float32")
        bp = fluid.layers.data("bp", [16, 4], dtype="float32")
        cl = fluid.layers.data("cl", [16, 1], dtype="float32")
        rets = fluid.layers.rpn_target_assign(
            bp, cl, av, None, gv, rpn_batch_size_per_im=8, use_random=False)
        return [rets[2], rets[5]]

    label, score_w = _run_prog(build, {
        "a": anchors, "g": gt,
        "bp": np.zeros((1, 16, 4), "f4"), "cl": np.zeros((1, 16, 1), "f4")})
    assert label.sum() >= 1 and score_w.sum() <= 8


def test_roi_pool_argmax_golden():
    """Argmax holds the flat h*W+w index of each bin's max (reference
    roi_pool_op.h records it for the backward; here it's an output-parity
    check — autodiff owns the gradient)."""
    rng = np.random.RandomState(13)
    x = rng.randn(1, 2, 6, 6).astype("f4")
    rois = np.array([[0, 0, 5, 5]], "f4")

    def build():
        xv = fluid.layers.data("x", [2, 6, 6], dtype="float32")
        rv = fluid.layers.data("rois", [4], dtype="float32")
        out = fluid.layers.roi_pool(xv, rv, 2, 2, 1.0)
        prog = fluid.default_main_program()
        argmax_name = [o for o in prog.global_block().ops
                       if o.type == "roi_pool"][0].output("Argmax")[0]
        return [out, argmax_name]

    out, arg = _run_prog(build, {"x": x, "rois": rois})
    H = W = 6
    for c in range(2):
        for i in range(2):
            for j in range(2):
                flat = int(arg[0, c, i, j])
                assert x[0, c, flat // W, flat % W] == out[0, c, i, j]


def test_ssd_end_to_end_trains():
    """multi_box_head + ssd_loss assemble a small SSD that trains to
    decreasing loss; detection_output emits padded static detections
    (VERDICT r3 #4's end-to-end gate for the SSD path)."""
    rng = np.random.RandomState(7)
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 13
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [3, 32, 32], dtype="float32")
        gb = fluid.layers.data("gb", [2, 4], dtype="float32")
        gl = fluid.layers.data("gl", [2], dtype="int32")
        c1 = fluid.layers.conv2d(img, 8, 3, stride=2, padding=1, act="relu")
        c2 = fluid.layers.conv2d(c1, 16, 3, stride=2, padding=1, act="relu")
        locs, confs, boxes, variances = fluid.layers.multi_box_head(
            [c1, c2], img, base_size=32, num_classes=4,
            aspect_ratios=[[1.0], [1.0, 2.0]],
            min_sizes=[8.0, 16.0], max_sizes=[16.0, 28.0], clip=True)
        loss = fluid.layers.mean(fluid.layers.ssd_loss(
            locs, confs, gb, gl, boxes, variances))
        fluid.optimizer.Adam(2e-3).minimize(loss)
    infer_prog = main.clone(for_test=True)
    with fluid.program_guard(infer_prog):
        blk = infer_prog.global_block()
        nmsed = fluid.layers.detection_output(
            blk.var(locs.name), blk.var(confs.name), blk.var(boxes.name),
            blk.var(variances.name), keep_top_k=10)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    n = 4
    imgs = rng.rand(n, 3, 32, 32).astype("f4")
    gt = rng.uniform(0.1, 0.6, (n, 2, 4)).astype("f4")
    gt[:, :, 2:] = gt[:, :, :2] + rng.uniform(0.2, 0.4, (n, 2, 2))
    gt = np.clip(gt, 0, 1)
    labels = rng.randint(1, 4, (n, 2)).astype("int32")  # 0 = background
    feed = {"img": imgs, "gb": gt, "gl": labels}
    losses = []
    for _ in range(40):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    (det,) = exe.run(infer_prog, feed=feed, fetch_list=[nmsed], scope=scope)
    det = np.asarray(det)
    assert det.shape == (n, 10, 6)


def test_retinanet_target_assign_and_focal_training():
    """RetinaNet assignment rules + a focal-loss head training end-to-end
    (class targets, no subsampling, fg_num normalizer)."""
    anchors = _grid_anchors()
    gt = np.array([[[6, 6, 26, 26], [40, 40, 60, 60]]], "f4")
    gt_lab = np.array([[1, 2]], "int32")

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        av = fluid.layers.data("a", [4], dtype="float32")
        gv = fluid.layers.data("g", [2, 4], dtype="float32")
        lv = fluid.layers.data("gl", [2], dtype="int32")
        bp = fluid.layers.data("bp", [16, 4], dtype="float32")
        cl = fluid.layers.data("cl", [16, 3], dtype="float32")
        rets = fluid.layers.retinanet_target_assign(
            bp, cl, av, None, gv, lv, positive_overlap=0.5,
            negative_overlap=0.4)
        _, _, label, tgt, inw, fg_num, score_w = rets
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    feed = {"a": anchors, "g": gt, "gl": gt_lab,
            "bp": np.zeros((1, 16, 4), "f4"), "cl": np.zeros((1, 16, 3), "f4")}
    lab, t, w_in, fg, sw = exe.run(
        main, feed=feed, fetch_list=[label, tgt, inw, fg_num, score_w],
        scope=scope)
    lab = np.asarray(lab)[0]
    # best anchors carry the gt CLASS labels
    assert set(lab[lab > 0].tolist()) == {1, 2}
    assert int(np.asarray(fg).reshape(-1)[0]) == (lab > 0).sum() + 1
    # no subsampling: every anchor is fg or bg or ignored, none dropped
    sw = np.asarray(sw)[0]
    assert ((lab == -1) == (sw == 0)).all()


def test_retinanet_detection_output_shapes():
    rng = np.random.RandomState(11)
    anchors = _grid_anchors()  # [16, 4]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        b1 = fluid.layers.data("b1", [16, 4], dtype="float32")
        s1 = fluid.layers.data("s1", [16, 3], dtype="float32")
        av = fluid.layers.data("a", [4], dtype="float32")
        im = fluid.layers.data("im", [3], dtype="float32")
        out = fluid.layers.retinanet_detection_output(
            [b1], [s1], [av], im, keep_top_k=5, score_threshold=0.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (det,) = exe.run(main, feed={
        "b1": rng.randn(2, 16, 4).astype("f4") * 0.1,
        "s1": rng.randn(2, 16, 3).astype("f4"),
        "a": anchors, "im": np.array([[64, 64, 1.0]] * 2, "f4")},
        fetch_list=[out], scope=scope)
    det = np.asarray(det)
    assert det.shape == (2, 5, 6)
    valid = det[det[:, :, 0] >= 0]
    assert np.isfinite(valid).all()


def test_generate_proposal_labels_and_faster_rcnn_stage2():
    """proposals + gts sampled into a fixed-size RoI batch with per-class
    regression targets; a stage-2 head (roi_pool -> fc) trains on them —
    the Faster-RCNN assembly gate."""
    rng = np.random.RandomState(12)
    R, C = 16, 3
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 21
    with fluid.program_guard(main, startup):
        feat = fluid.layers.data("feat", [4, 16, 16], dtype="float32")
        props = fluid.layers.data("props", [12, 4], dtype="float32")
        gcls = fluid.layers.data("gcls", [2], dtype="int32")
        gbox = fluid.layers.data("gbox", [2, 4], dtype="float32")
        rois, labels, tgt, inw, outw, sw = fluid.layers.generate_proposal_labels(
            props, gcls, None, gbox, batch_size_per_im=R, fg_thresh=0.5,
            class_nums=C, use_random=False)
        flat_rois = fluid.layers.reshape(rois, [-1, 4])
        pooled = fluid.layers.roi_pool(feat, flat_rois, 4, 4,
                                       spatial_scale=0.25)
        fcin = fluid.layers.reshape(pooled, [-1, 4 * 16])
        cls_logits = fluid.layers.fc(fcin, C)
        flat_lab = fluid.layers.reshape(labels, [-1, 1])
        ce = fluid.layers.softmax_with_cross_entropy(
            cls_logits, fluid.layers.cast(flat_lab, "int64"))
        w = fluid.layers.reshape(sw, [-1, 1])
        loss = fluid.layers.reduce_sum(ce * w) / (fluid.layers.reduce_sum(w) + 1.0)
        fluid.optimizer.Adam(1e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    props_v = rng.uniform(0, 40, (1, 12, 4)).astype("f4")
    props_v[..., 2:] = props_v[..., :2] + rng.uniform(8, 20, (1, 12, 2))
    gt_v = np.array([[[4, 4, 20, 20], [30, 30, 50, 50]]], "f4")
    feed = {"feat": rng.rand(1, 4, 16, 16).astype("f4"),
            "props": props_v, "gcls": np.array([[1, 2]], "int32"),
            "gbox": gt_v}
    out = exe.run(main, feed=feed,
                  fetch_list=[rois, labels, tgt, inw, sw, loss], scope=scope)
    rois_v, lab_v, tgt_v, inw_v, sw_v, _ = [np.asarray(o) for o in out]
    assert rois_v.shape == (1, R, 4) and lab_v.shape == (1, R)
    assert tgt_v.shape == (1, R, 4 * C)
    # the gt boxes themselves are fg candidates, so fg exists with class 1/2
    assert set(lab_v[0][lab_v[0] > 0].tolist()) <= {1, 2}
    assert (lab_v[0] > 0).sum() >= 2
    # inside weights fire exactly on the label's 4-col block for fg rows
    fg_rows = np.where(lab_v[0] > 0)[0]
    for r in fg_rows[:3]:
        c = lab_v[0, r]
        blk = inw_v[0, r].reshape(C, 4)
        assert (blk[c] == 1).all() and blk.sum() == 4
    losses = []
    for _ in range(20):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_fpn_distribute_and_collect():
    rng = np.random.RandomState(13)
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        rois = fluid.layers.data("rois", [8, 4], dtype="float32")
        flat = fluid.layers.reshape(rois, [-1, 4])
        multi_rois, restore, masks = fluid.layers.distribute_fpn_proposals(
            flat, 2, 5, 4, 224)
        r1 = fluid.layers.data("r1", [6, 4], dtype="float32")
        s1 = fluid.layers.data("s1", [6, 1], dtype="float32")
        r2 = fluid.layers.data("r2", [6, 4], dtype="float32")
        s2 = fluid.layers.data("s2", [6, 1], dtype="float32")
        fs1 = fluid.layers.reshape(s1, [0, -1])
        fs2 = fluid.layers.reshape(s2, [0, -1])
        collected = fluid.layers.collect_fpn_proposals(
            [r1, r2], [fs1, fs2], 2, 5, post_nms_top_n=5)
        fetches = masks + [collected]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    # sizes chosen to land on distinct levels: 224 -> level 4
    sizes = [16, 32, 64, 112, 224, 224, 448, 900]
    rois_v = np.zeros((1, 8, 4), "f4")
    for i, s in enumerate(sizes):
        rois_v[0, i] = [0, 0, s - 1, s - 1]  # +1-offset area convention
    r1v = rng.uniform(0, 50, (1, 6, 4)).astype("f4")
    r2v = rng.uniform(0, 50, (1, 6, 4)).astype("f4")
    s1v = rng.rand(1, 6, 1).astype("f4")
    s2v = rng.rand(1, 6, 1).astype("f4")
    out = exe.run(main, feed={"rois": rois_v, "r1": r1v, "s1": s1v,
                              "r2": r2v, "s2": s2v},
                  fetch_list=fetches, scope=scope)
    m = [np.asarray(o) for o in out[:4]]
    # every roi routed to exactly one level
    total = sum(mm for mm in m)
    np.testing.assert_allclose(total, np.ones(8), atol=1e-6)
    # small rois to low levels, big to high
    assert m[0][0] == 1.0 and m[3][-1] == 1.0
    col = np.asarray(out[4])[0]
    assert col.shape == (5, 4)
    # collected rois are the 5 highest-scoring across both levels
    all_s = np.concatenate([s1v.reshape(-1), s2v.reshape(-1)])
    all_r = np.concatenate([r1v.reshape(-1, 4), r2v.reshape(-1, 4)])
    expect = all_r[np.argsort(-all_s)[:5]]
    np.testing.assert_allclose(col, expect, rtol=1e-6)


def test_box_decoder_and_assign_golden():
    prior = np.array([[0, 0, 9, 9]], "f4")
    deltas = np.zeros((1, 8), "f4")  # C=2, zero deltas decode to the prior
    score = np.array([[0.1, 0.9]], "f4")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pv = fluid.layers.data("p", [4], dtype="float32")
        dv = fluid.layers.data("d", [8], dtype="float32")
        sv = fluid.layers.data("s", [2], dtype="float32")
        dec, asg = fluid.layers.box_decoder_and_assign(pv, [0.1, 0.1, 0.2, 0.2],
                                                       dv, sv)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    d_out, a_out = exe.run(main, feed={"p": prior, "d": deltas, "s": score},
                           fetch_list=[dec, asg], scope=scope)
    np.testing.assert_allclose(np.asarray(a_out)[0], [0, 0, 9, 9], atol=1e-4)
    np.testing.assert_allclose(np.asarray(d_out)[0].reshape(2, 4)[1],
                               [0, 0, 9, 9], atol=1e-4)


def test_generate_mask_labels_square_polygon():
    """a square polygon rasterizes to a filled block in the matched fg
    roi's class slice."""
    rois = np.array([[[0, 0, 8, 8], [20, 20, 28, 28]]], "f4")
    labels = np.array([[2, 0]], "int32")  # roi 0 fg class 2, roi 1 bg
    # polygon covering the left half of roi 0: x in [0, 4], y in [0, 8]
    segms = np.array([[[[0, 0], [4, 0], [4, 8], [0, 8]]]], "f4")

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        rv = fluid.layers.data("r", [2, 4], dtype="float32")
        lv = fluid.layers.data("l", [2], dtype="int32")
        sv = fluid.layers.data("s", [1, 4, 2], dtype="float32")
        mask_rois, has, masks = fluid.layers.generate_mask_labels(
            None, None, None, sv, rv, lv, num_classes=3, resolution=4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    hv, mv = exe.run(main, feed={"r": rois, "l": labels, "s": segms},
                     fetch_list=[has, masks], scope=scope)
    hv, mv = np.asarray(hv), np.asarray(mv)
    assert hv[0].tolist() == [1, 0]
    m = mv[0, 0].reshape(3, 4, 4)
    assert (m[0] == 0).all() and (m[1] == 0).all()  # only class 2 block
    # left half of the roi (columns 0-1 at res 4) filled, right half empty
    assert (m[2][:, :2] == 1).all() and (m[2][:, 2:] == 0).all()
