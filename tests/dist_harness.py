"""Shared multiprocess-on-localhost harness (reference: test_dist_base.py
_run_cluster) used by tests/test_dist_multiprocess.py and
__graft_entry__.dryrun_multiprocess — one copy of the port allocation,
PADDLE_* env contract, axon-shim scrubbing, and LOSSES parsing."""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "dist_worker.py")


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def worker_env(extra=None, devices_per_proc=2):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # the axon tunnel shim (.axon_site) monkeypatches jax.distributed for
    # its loopback relay; workers must run with a clean PYTHONPATH
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={devices_per_proc}"
    env.update(extra or {})
    return env


def spawn_workers(n_procs: int, devices_per_proc: int = 2, extra_env=None):
    """Start n_procs dist_worker.py processes wired through one coordinator."""
    port = free_port()
    eps = ",".join(f"127.0.0.1:{port + i}" for i in range(n_procs))
    procs = []
    for tid in range(n_procs):
        env = worker_env(extra_env, devices_per_proc)
        env["PADDLE_TRAINER_ID"] = str(tid)
        env["PADDLE_TRAINER_ENDPOINTS"] = eps
        env["PADDLE_CURRENT_ENDPOINT"] = eps.split(",")[tid]
        procs.append(subprocess.Popen(
            [sys.executable, WORKER],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True))
    return procs


def parse_losses(out: str, err: str, tag: str) -> dict:
    for line in out.splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError(
        f"{tag}: worker produced no LOSSES line.\nstdout:\n{out}\nstderr:\n{err[-3000:]}")


def collect(procs, timeout=600):
    """communicate() every worker; on any failure kill the stragglers so no
    orphan sits blocked in jax.distributed.initialize."""
    results = []
    try:
        for tid, p in enumerate(procs):
            out, err = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError(f"worker {tid} failed:\n{err[-4000:]}")
            results.append(parse_losses(out, err, f"worker{tid}"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return results
