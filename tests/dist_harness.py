"""Shared multiprocess-on-localhost harness (reference: test_dist_base.py
_run_cluster) used by tests/test_dist_multiprocess.py, tests/
test_dist_chaos.py, and __graft_entry__.dryrun_multiprocess.

The mechanics (port-block allocation with EADDRINUSE retry, the
PADDLE_* env contract, axon-shim scrubbing, kill-and-reap spawning) now
live in `paddle_tpu.launch` — the harness keeps only the test-facing
conveniences: `worker_gang` (a context manager that can never leak live
subprocesses, even when a later spawn or the test body raises) and the
LOSSES-line parsing the parity tests key on."""
from __future__ import annotations

import contextlib
import json
import os

from paddle_tpu.launch import (Gang, allocate_port_block,  # noqa: F401
                               run_gang, worker_env as _launch_worker_env)

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "dist_worker.py")
RESILIENT_WORKER = os.path.join(HERE, "dist_worker_resilient.py")


def free_port() -> int:
    """One free port (TOCTOU-shrunk: verified by bind, like the block
    allocator).  Kept for callers that need a single ad-hoc port."""
    return allocate_port_block(1)


def worker_env(extra=None, devices_per_proc=2, rank=0, endpoints=None):
    """Back-compat shim over paddle_tpu.launch.worker_env for callers that
    build their own env (e.g. the RUN_LOCAL single-process reference)."""
    endpoints = endpoints or [f"127.0.0.1:{free_port()}"]
    env = _launch_worker_env(rank, endpoints, devices_per_proc, extra or {})
    if extra and "RUN_LOCAL" in extra:
        # the local reference run is not part of any gang: drop the contract
        for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINER_ENDPOINTS",
                  "PADDLE_CURRENT_ENDPOINT"):
            env.pop(k, None)
    return env


@contextlib.contextmanager
def worker_gang(n_procs: int, devices_per_proc: int = 2, extra_env=None,
                worker: str = WORKER):
    """Spawn n_procs workers wired through one coordinator; ALWAYS kills
    and reaps them on exit (bounded join, SIGTERM then SIGKILL) — the old
    `spawn_workers` list leaked live subprocesses whenever a later spawn
    or the test body failed before `collect`'s finally ran.  Yields the
    Gang; pass it to `collect` for the LOSSES-parsing result list."""
    import sys

    with Gang([sys.executable, worker], n_procs,
              devices_per_proc=devices_per_proc, extra_env=extra_env) as g:
        yield g


def parse_losses(out: str, err: str, tag: str) -> dict:
    for line in out.splitlines():
        if line.startswith("LOSSES "):
            return json.loads(line[len("LOSSES "):])
    raise AssertionError(
        f"{tag}: worker produced no LOSSES line.\nstdout:\n{out}\nstderr:\n{err[-3000:]}")


def collect(gang_or_procs, timeout=600):
    """Wait out every worker of a `worker_gang` Gang (or a legacy Popen
    list) and parse its LOSSES line; on any failure the stragglers are
    killed so no orphan sits blocked in jax.distributed.initialize."""
    if isinstance(gang_or_procs, Gang):
        results = []
        for tid, (code, out, err) in enumerate(
                gang_or_procs.communicate(timeout=timeout)):
            if code != 0:
                raise RuntimeError(f"worker {tid} failed:\n{(err or '')[-4000:]}")
            results.append(parse_losses(out, err or "", f"worker{tid}"))
        return results
    procs = gang_or_procs
    results = []
    try:
        for tid, p in enumerate(procs):
            out, err = p.communicate(timeout=timeout)
            if p.returncode != 0:
                raise RuntimeError(f"worker {tid} failed:\n{err[-4000:]}")
            results.append(parse_losses(out, err, f"worker{tid}"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return results
