"""fused_attention op: parity vs the unfused matmul/softmax/matmul program
path, causal masking, bias, grad flow, and the bf16 BERT builder.

Reference role: operators/fused/ attention fusion ambitions; here the TPU
lowering is the Pallas flash kernel (paddle_tpu/ops/nn_ops.py) and these
CPU tests exercise the identical-math fallback plus the program plumbing.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.program import Program, program_guard


def _run(build_fn, feeds, fetch):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        out = build_fn()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (val,) = exe.run(main, feed=feeds, fetch_list=[out], scope=scope)
    return val


def _plain_attention(q, k, v, bias=None, causal=False):
    d = q.shape[-1]
    scores = layers.matmul(q, k, transpose_y=True, alpha=1.0 / np.sqrt(d))
    if bias is not None:
        scores = layers.elementwise_add(scores, bias)
    if causal:
        L = q.shape[2]
        mask_np = np.triu(np.full((L, L), -1e30, np.float32), k=1).reshape(1, 1, L, L)
        mask = layers.assign(mask_np)
        scores = layers.elementwise_add(scores, mask)
    attn = layers.softmax(scores)
    return layers.matmul(attn, v)


@pytest.mark.parametrize("causal", [False, True])
def test_fused_matches_plain(causal):
    rng = np.random.RandomState(0)
    B, H, L, D = 2, 3, 16, 8
    q = rng.randn(B, H, L, D).astype(np.float32)
    k = rng.randn(B, H, L, D).astype(np.float32)
    v = rng.randn(B, H, L, D).astype(np.float32)

    def build_fused():
        qv = layers.data("q", [H, L, D])
        kv = layers.data("k", [H, L, D])
        vv = layers.data("v", [H, L, D])
        return layers.fused_attention(qv, kv, vv, causal=causal)

    def build_plain():
        qv = layers.data("q", [H, L, D])
        kv = layers.data("k", [H, L, D])
        vv = layers.data("v", [H, L, D])
        return _plain_attention(qv, kv, vv, causal=causal)

    feeds = {"q": q, "k": k, "v": v}
    fused = _run(build_fused, feeds, "out")
    plain = _run(build_plain, feeds, "out")
    np.testing.assert_allclose(fused, plain, rtol=1e-5, atol=1e-5)


def test_fused_with_bias_broadcasts_heads():
    rng = np.random.RandomState(1)
    B, H, L, D = 2, 4, 8, 8
    q = rng.randn(B, H, L, D).astype(np.float32)
    k = rng.randn(B, H, L, D).astype(np.float32)
    v = rng.randn(B, H, L, D).astype(np.float32)
    bias = np.where(rng.rand(B, 1, L, L) < 0.2, -1e30, 0.0).astype(np.float32)

    def build(fused):
        qv = layers.data("q", [H, L, D])
        kv = layers.data("k", [H, L, D])
        vv = layers.data("v", [H, L, D])
        bv = layers.data("bias", [1, L, L])
        if fused:
            return layers.fused_attention(qv, kv, vv, bias=bv)
        return _plain_attention(qv, kv, vv, bias=bv)

    feeds = {"q": q, "k": k, "v": v, "bias": bias}
    np.testing.assert_allclose(
        _run(lambda: build(True), feeds, "out"),
        _run(lambda: build(False), feeds, "out"),
        rtol=1e-5, atol=1e-5)


def test_fused_attention_grad_flows():
    """Gradients through fused_attention match the unfused composition."""
    rng = np.random.RandomState(2)
    B, H, L, D = 2, 2, 8, 4
    x_np = rng.randn(B, H, L, D).astype(np.float32)

    def losses(fused):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = layers.data("x", [H, L, D])
            q = layers.fc(x, D, num_flatten_dims=3)
            out = (layers.fused_attention(q, x, x)
                   if fused else _plain_attention(q, x, x))
            loss = layers.mean(out)
            fluid.optimizer.SGD(learning_rate=0.5).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        startup.random_seed = 3
        exe.run(startup, scope=scope)
        vals = []
        for _ in range(3):
            (lv,) = exe.run(main, feed={"x": x_np}, fetch_list=[loss], scope=scope)
            vals.append(float(np.asarray(lv).reshape(-1)[0]))
        return vals

    np.testing.assert_allclose(losses(True), losses(False), rtol=1e-5, atol=1e-6)


def test_bert_bf16_fused_builds_and_trains():
    from paddle_tpu.models import transformer

    main, startup, feeds, fetches = transformer.build_bert(
        vocab_size=100, seq_len=16, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        dropout_prob=0.0, use_fused_attention=True, dtype="bfloat16")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    batch = transformer.make_fake_batch(4, 16, 100)
    losses = []
    for _ in range(5):
        (lv,) = exe.run(main, feed=batch, fetch_list=[fetches["loss"]], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]  # memorizes the tiny fake batch


def test_fused_attention_bf16_score_dtype():
    """Opt-in bf16 score materialization: fwd + grad must match the f32
    path within bf16-logit tolerance, including bias and causal masking
    (fully-masked tail positions)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as fluid
    from paddle_tpu import layers
    from paddle_tpu.core.program import Program, program_guard

    rng = np.random.RandomState(0)
    B, H, L, dh = 2, 2, 8, 4
    qv = rng.randn(B, H, L, dh).astype("f4")
    kv = rng.randn(B, H, L, dh).astype("f4")
    vv = rng.randn(B, H, L, dh).astype("f4")
    bias = np.where(np.arange(L)[None, None, None, :] < 6, 0.0, -1e9).astype("f4")
    bias = np.broadcast_to(bias, (B, 1, L, L)).copy()

    outs = {}
    grads = {}
    for sd in (None, "bfloat16"):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            q = layers.data("q", [H, L, dh], dtype="float32")
            k = layers.data("k", [H, L, dh], dtype="float32")
            v = layers.data("v", [H, L, dh], dtype="float32")
            b = layers.data("b", [1, L, L], dtype="float32")
            o = layers.fused_attention(q, k, v, bias=b, causal=True,
                                       score_dtype=sd)
            loss = layers.mean(o)
            g = fluid.calc_gradient(loss, [main.global_block().var("q")])[0]
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        res = exe.run(main, feed={"q": qv, "k": kv, "v": vv, "b": bias},
                      fetch_list=[o, g], scope=scope)
        outs[sd] = np.asarray(res[0])
        grads[sd] = np.asarray(res[1])
    # bf16 logits: ~2^-8 relative on scores -> small prob/ctx perturbation
    assert np.allclose(outs[None], outs["bfloat16"], atol=2e-2), \
        np.abs(outs[None] - outs["bfloat16"]).max()
    assert np.allclose(grads[None], grads["bfloat16"], atol=2e-2), \
        np.abs(grads[None] - grads["bfloat16"]).max()
    assert np.isfinite(outs["bfloat16"]).all()


def test_fused_attention_score_dtype_validation():
    import pytest as _pytest

    from paddle_tpu import layers
    from paddle_tpu.core.program import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        q = layers.data("q", [2, 8, 4], dtype="float32")
        with _pytest.raises(ValueError, match="score_dtype"):
            layers.fused_attention(q, q, q, score_dtype="float16")
        # aliases normalize instead of silently no-op'ing
        layers.fused_attention(q, q, q, score_dtype="bf16")
