"""Fault-hardened online learning (ISSUE 19): the host-tiered sparse
table + supervised pserver + publish-cadence contract, chaos-tested.

The invariants pinned here are the round's acceptance criteria:
  - SIGKILLing the pserver child mid-stream loses NOTHING: the journal
    replays to a BIT-IDENTICAL table (server-side content digest equal
    across the kill) and the client's reconnect-retry rides the restart
    out on the same endpoint.
  - A retried push is applied EXACTLY ONCE (per-client sequence numbers;
    the dedup is observable as ps.push_dedup).
  - A rotted SelectedRows values shard (rot_row) is REJECTED by the
    publish ladder and the last good snapshot keeps serving.
  - A dead host tier degrades boundedly: hot-shard-only steps with the
    sparse.host_lag_steps gauge rising, terminal past
    FLAGS_max_host_lag_steps.
  - The publish cadence survives storage faults: a failed publish is
    absorbed + counted, staleness is measured, and the perf_report
    --max-publish-staleness-steps / --max-host-lag-steps gates hold the
    declared bounds (zero evidence fails).
"""
import glob
import json
import os
import struct
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io, layers, monitor
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.errors import ParamServerError
from paddle_tpu.faults import FaultInjector
from paddle_tpu.parallel.embedding import TieredEmbedding
from paddle_tpu.param_server import (KVClient, ParameterServer,
                                     PServerSupervisor)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import perf_report  # noqa: E402


# --- exactly-once + durability (in-process server) --------------------------

def test_resent_push_applied_exactly_once():
    """The same sequenced frame delivered twice (a retry whose first
    reply was lost) must mutate the table once; the duplicate is counted
    on ps.push_dedup."""
    monitor.enable()
    srv = ParameterServer(optimizer="sgd", lr=1.0).start()
    try:
        c = KVClient(srv.endpoint)
        c.create("t", np.zeros((4, 2), "f4"))
        ids = np.array([1, 2], np.int64)
        grads = np.ones((2, 2), "f4")
        c.push("t", ids, grads)  # seq 1
        # replay the exact wire message (client_id, seq=1) — the retry
        # path after a lost reply re-sends precisely this
        c._call(b"S", "t", ids, grads,
                seq_prefix=struct.pack("<QQ", c.client_id, 1))
        after = c.fetch_table("t")
        exp = np.zeros((4, 2), "f4")
        exp[[1, 2]] -= 1.0  # ONE sgd application, not two
        np.testing.assert_allclose(after, exp)
        assert monitor.counter("ps.push_dedup").value >= 1
        c.close()
    finally:
        srv.stop()
        monitor.disable()
        monitor.reset()


def test_stale_sequence_push_ignored_fresh_applied():
    """Out-of-date sequence numbers from the same client stream are
    dropped; a NEW client object is a new stream and applies."""
    srv = ParameterServer(optimizer="sgd", lr=1.0).start()
    try:
        c = KVClient(srv.endpoint)
        c.create("t", np.zeros((3, 1), "f4"))
        c.push("t", np.array([0], np.int64), np.ones((1, 1), "f4"))
        c.push("t", np.array([1], np.int64), np.ones((1, 1), "f4"))
        # seq 1 again: stale, dropped
        c._call(b"S", "t", np.array([2], np.int64), np.ones((1, 1), "f4"),
                seq_prefix=struct.pack("<QQ", c.client_id, 1))
        c2 = KVClient(srv.endpoint)
        c2.push("t", np.array([2], np.int64), np.ones((1, 1), "f4"))
        np.testing.assert_allclose(c.fetch_table("t"),
                                   [[-1.0], [-1.0], [-1.0]])
        c.close()
        c2.close()
    finally:
        srv.stop()


def test_journal_recovery_bit_identical(tmp_path):
    """Snapshot + journal replay reconstructs the table bit-identically:
    a fresh server over the same snapshot_dir reports the same content
    digest the dying server held."""
    snap = str(tmp_path / "ps")
    srv = ParameterServer(optimizer="adagrad", lr=0.5, snapshot_dir=snap,
                          snapshot_every_ops=3).start()
    c = KVClient(srv.endpoint)
    rng = np.random.RandomState(0)
    c.create("t", rng.rand(16, 4).astype("f4"))
    for i in range(8):  # crosses a snapshot boundary; journal tail replays
        c.push("t", rng.randint(0, 16, 5).astype(np.int64),
               rng.rand(5, 4).astype("f4"))
    want_digest = c.table_digest("t")
    want_table = c.fetch_table("t")
    c.close()
    # simulate a CRASH: tear the sockets down without the graceful
    # stop()-time snapshot — recovery must come from snap + journal tail
    srv._srv.shutdown()
    srv._srv.server_close()

    srv2 = ParameterServer(optimizer="adagrad", lr=0.5, snapshot_dir=snap,
                           snapshot_every_ops=3).start()
    try:
        c2 = KVClient(srv2.endpoint)
        assert c2.table_digest("t") == want_digest
        np.testing.assert_array_equal(c2.fetch_table("t"), want_table)
        c2.close()
    finally:
        srv2.stop()


def test_frame_cap_rejects_oversized_terminal():
    """A frame past FLAGS_ps_max_frame_mb is a protocol violation:
    terminal ParamServerError (no retry storm), counted."""
    monitor.enable()
    fluid.set_flags({"FLAGS_ps_max_frame_mb": 1})
    srv = ParameterServer().start()
    try:
        c = KVClient(srv.endpoint, retries=3)
        with pytest.raises(ParamServerError) as ei:
            c.create("big", np.zeros((1024, 512), "f4"))  # 2 MB frame
        assert not ei.value.transient
        c.close()
    finally:
        fluid.set_flags({"FLAGS_ps_max_frame_mb": 256})
        srv.stop()
        monitor.disable()
        monitor.reset()


# --- supervised child process: SIGKILL recovery -----------------------------

def test_supervisor_sigkill_bit_identical_and_exactly_once(tmp_path):
    """The full tentpole invariant in one life: SIGKILL the pserver
    child mid-stream; the supervisor respawns it on the SAME endpoint,
    the journal replays bit-identically (digest equality across the
    kill), and the client's retried pushes land exactly once."""
    sup = PServerSupervisor(str(tmp_path / "ps"), optimizer="sgd", lr=0.1,
                            snapshot_every_ops=4, max_restarts=2).start()
    try:
        sup.wait_ready()
        c = KVClient(sup.endpoint, retries=8, backoff_base_s=0.2)
        rng = np.random.RandomState(1)
        c.create("t", rng.rand(32, 4).astype("f4"))
        for _ in range(6):
            c.push("t", rng.randint(0, 32, 4).astype(np.int64),
                   rng.rand(4, 4).astype("f4"))
        before = c.table_digest("t")
        sup.kill()
        # the client's retry loop must ride the restart out by itself
        after = c.table_digest("t")
        assert after == before, \
            "journal replay did not reconstruct the table bit-identically"
        # pushes against the RESTARTED incarnation still apply (the
        # client's sequence stream continues across the restart)
        t0 = c.fetch_table("t")
        c.push("t", np.array([0], np.int64), np.ones((1, 4), "f4"))
        t1 = c.fetch_table("t")
        np.testing.assert_allclose(t1[0], t0[0] - 0.1)
        np.testing.assert_array_equal(t1[1:], t0[1:])
        assert sup.restarts == 1 and not sup.failed
        c.close()
    finally:
        sup.stop()


# --- degraded mode ----------------------------------------------------------

def test_degraded_mode_bounded_then_terminal():
    """With the host tier dead and degraded_ok=True, lookups run
    hot-shard-only (cold rows zero) while host_lag_steps rises; past
    FLAGS_max_host_lag_steps the next failure is TERMINAL."""
    monitor.enable()
    srv = ParameterServer(optimizer="sgd", lr=0.1).start()
    c = KVClient(srv.endpoint, retries=1, timeout_s=2.0,
                 backoff_base_s=0.0)
    emb = TieredEmbedding(c, "tbl", vocab_size=16, dim=2, hot_rows=8,
                          degraded_ok=True, seed=0)
    ids = np.array([[1, 9]])  # one hot row, one cold row
    warm = emb.lookup(ids)
    assert np.abs(warm[0, 1]).sum() > 0  # cold row served while healthy
    srv.stop()  # host tier dies...
    c.close()   # ...and the next op must reconnect (and fail)
    fluid.set_flags({"FLAGS_max_host_lag_steps": 3})
    try:
        for k in (1, 2):
            out = emb.lookup(ids)
            np.testing.assert_array_equal(out[0, 1], np.zeros(2, "f4"))
            np.testing.assert_allclose(out[0, 0], warm[0, 0])  # hot intact
            assert emb.host_lag_steps == k
        # a push during the outage drops the COLD slab only, counted —
        # it is itself one degraded step against the budget (lag 3)
        emb.apply_grad(ids.reshape(-1), np.ones((2, 2), "f4"))
        assert monitor.counter("sparse.dropped_pushes").value >= 1
        assert emb.host_lag_steps == 3
        with pytest.raises(ParamServerError) as ei:
            emb.lookup(ids)  # lag 4 > bound: terminal
        assert not ei.value.transient
        assert "host_lag_steps" in str(ei.value) or "lag" in str(ei.value)
    finally:
        fluid.set_flags({"FLAGS_max_host_lag_steps": 0})
        c.close()
        monitor.disable()
        monitor.reset()


# --- sparse publish ladder: rot_row quarantine ------------------------------

def _sparse_serving_model(tmp_path, vocab=24, dim=4, feat=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", [feat], dtype="int64")
        e = layers.embedding(ids, size=[vocab, dim], is_sparse=True,
                             param_attr=fluid.ParamAttr(name="q_tbl"))
        pred = layers.fc(layers.reshape(e, [-1, feat * dim]), 1,
                         param_attr=fluid.ParamAttr(name="q_fc"),
                         bias_attr=False)
    startup.random_seed = 5
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    d0 = str(tmp_path / "model-0")
    io.save_inference_model(d0, ["ids"], [pred], exe, main, scope)
    return main, scope, d0


def _sparse_snapshot(tmp_path, name, main, scope, bump=0.0):
    vocab = 24
    table = np.asarray(scope.find_var("q_tbl")).copy() + bump
    s = fluid.Scope()
    s.set_var("q_tbl", SelectedRows(np.arange(vocab, dtype=np.int64),
                                    table, vocab))
    names = [v.name for v in io._persistables(main)]
    for n in names:
        if n != "q_tbl":
            s.set_var(n, np.asarray(scope.find_var(n)))
    d = str(tmp_path / name)
    io.save_sharded(d, names, s, program=main, process_index=0)
    return d


def test_rot_row_rejected_last_good_serves(tmp_path):
    """rot_row flips a byte of a committed SelectedRows VALUES shard;
    the publish ladder must reject + quarantine it and the previous
    sparse snapshot keeps serving, digest-stamped."""
    from paddle_tpu.serving import ModelRegistry, publish
    from paddle_tpu.errors import ServingError

    monitor.enable()
    main, scope, d0 = _sparse_serving_model(tmp_path)
    reg = ModelRegistry(place=fluid.CPUPlace())
    reg.load("q", d0)
    feeds = {"ids": np.array([[1, 2, 3]], np.int64)}

    good = _sparse_snapshot(tmp_path, "snap-1", main, scope, bump=0.25)
    inj = FaultInjector("rot_row@1")
    inj.on_commit(good)  # ordinal 0: not the target
    publish(reg, "q", good)
    out_good = np.asarray(reg.acquire("q").run(feeds)[0]).copy()

    bad = _sparse_snapshot(tmp_path, "snap-2", main, scope, bump=0.5)
    inj.on_commit(bad)  # ordinal 1: flips a byte in the .vals. shard
    rotted = [f for f in os.listdir(bad) if ".vals." in f]
    assert rotted, "rot_row must target the SelectedRows values shard"
    with pytest.raises(ServingError, match="REJECTED"):
        publish(reg, "q", bad)
    out_after = np.asarray(reg.acquire("q").run(feeds)[0])
    np.testing.assert_array_equal(out_after, out_good)
    evs = [r for r in monitor.step_records()
           if r.get("kind") == "serving_event"]
    assert any(r.get("action") == "publish" and r.get("sparse_digest")
               for r in evs), "publish event must carry the sparse digest"
    assert any(r.get("action") == "publish_rejected" for r in evs)
    monitor.disable()
    monitor.reset()


def test_sparse_rung_rejects_structural_defects(tmp_path):
    """Non-monotone row ids and non-finite values both fail the sparse
    rung with a named defect (not a generic load error)."""
    from paddle_tpu.serving import ModelRegistry, publish
    from paddle_tpu.errors import ServingError

    main, scope, d0 = _sparse_serving_model(tmp_path)
    reg = ModelRegistry(place=fluid.CPUPlace())
    reg.load("q", d0)
    vocab = 24
    table = np.asarray(scope.find_var("q_tbl")).copy()
    table[3, 0] = np.nan
    s = fluid.Scope()
    s.set_var("q_tbl", SelectedRows(np.arange(vocab, dtype=np.int64),
                                    table, vocab))
    names = [v.name for v in io._persistables(main)]
    for n in names:
        if n != "q_tbl":
            s.set_var(n, np.asarray(scope.find_var(n)))
    d = str(tmp_path / "snap-nan")
    io.save_sharded(d, names, s, program=main, process_index=0)
    with pytest.raises(ServingError, match="sparse table rung"):
        publish(reg, "q", d)


# --- publish cadence under storage faults -----------------------------------

def _cadence_run(tmp_path, fault_spec, steps=12, period=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(
            layers.fc(x, 1, param_attr=fluid.ParamAttr(name="cw")), y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    startup.random_seed = 3
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(8, 4).astype("f4"),
              "y": rng.rand(8, 1).astype("f4")} for _ in range(steps)]
    pubs = []

    def hook(step):
        # through the io.py choke point: the injector's enospc window
        # fails this write exactly like a full disk would
        d = str(tmp_path / f"pub-{step}")
        io.save_vars(d, ["cw"], scope)
        pubs.append(step)

    stats = fluid.resilient_train_loop(
        exe, main, lambda: list(feeds), [loss], scope=scope,
        injector=FaultInjector(fault_spec) if fault_spec else None,
        publish_hook=hook, publish_period_steps=period,
        max_inflight=1, policy=fluid.RetryPolicy(backoff_base_s=0.0))
    return stats, pubs


def test_publish_cadence_survives_enospc(tmp_path):
    """enospc during a publish step fails THAT publish only: counted,
    staleness recorded on the publish_failed event, cadence resumes next
    period, training never stops."""
    monitor.enable()
    stats, pubs = _cadence_run(tmp_path, "enospc@6", steps=12, period=3)
    try:
        assert stats.steps == 12
        assert stats.publish_failures == 1
        assert stats.publishes >= 2 and 6 not in pubs
        evs = [r for r in monitor.step_records()
               if r.get("kind") == "resilience_event"]
        failed = [r for r in evs if r.get("action") == "publish_failed"]
        assert len(failed) == 1 and failed[0]["at_step"] == 6
        # staleness on the failure: step 6 ran 3 past the step-3 publish
        assert failed[0]["staleness"] == 3
        assert monitor.counter("serving.publish_errors").value == 1
    finally:
        monitor.disable()
        monitor.reset()


def test_publish_cadence_clean(tmp_path):
    # publish fires at the DISPATCH boundary, so 10 batches dispatch
    # steps 0..9 and the period-3 cadence lands on 3, 6, 9
    monitor.enable()
    stats, pubs = _cadence_run(tmp_path, None, steps=10, period=3)
    try:
        assert pubs == [3, 6, 9]
        assert stats.publishes == 3 and stats.publish_failures == 0
    finally:
        monitor.disable()
        monitor.reset()


# --- perf_report gates ------------------------------------------------------

def _write_stream(tmp_path, lines):
    p = str(tmp_path / "metrics.jsonl")
    with open(p, "w") as f:
        for r in lines:
            f.write(json.dumps(r) + "\n")
    return p


_STEPS = [{"kind": "step", "step": i, "recompiles_total": 1}
          for i in range(4)]


def test_gate_publish_staleness(tmp_path):
    ok = _write_stream(tmp_path, _STEPS + [
        {"kind": "resilience_event", "action": "publish", "at_step": 8},
        {"kind": "resilience_event", "action": "publish_failed",
         "at_step": 12, "staleness": 4},
    ])
    assert perf_report.check(ok, max_publish_staleness_steps=4) == 0
    assert perf_report.check(ok, max_publish_staleness_steps=3) == 1


def test_gate_publish_staleness_zero_evidence_fails(tmp_path):
    empty = _write_stream(tmp_path, _STEPS)
    assert perf_report.check(empty, max_publish_staleness_steps=100) == 1


def test_gate_host_lag(tmp_path):
    ok = _write_stream(tmp_path, _STEPS + [
        {"kind": "sparse_event", "action": "host_tier_degraded",
         "table": "t", "lag_steps": 2},
        {"kind": "sparse_event", "action": "host_tier_recovered",
         "table": "t", "lag_steps": 2},
    ])
    assert perf_report.check(ok, max_host_lag_steps=2) == 0
    assert perf_report.check(ok, max_host_lag_steps=1) == 1
    empty = _write_stream(tmp_path, _STEPS)
    assert perf_report.check(empty, max_host_lag_steps=5) == 1


def test_bench_r08_round_holds_its_declared_bounds():
    """The committed BENCH_r08.json is the online-learning round: every
    arm (table curve + kill-pserver chaos) must have held its declared
    staleness bound and passed its own perf gate."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, "BENCH_r08.json")
    with open(path) as f:
        doc = json.load(f)
    rec = doc["parsed"]
    assert rec["metric"] == "online_learning_examples_per_sec"
    arms = list(rec["table_curve"].values()) + [rec["chaos"]]
    for a in arms:
        assert a["staleness_bound_ok"], a
        assert a["max_staleness_steps"] <= rec["staleness_bound_steps"]
        assert a["perf_gate_rc"] == 0, a
    assert rec["chaos"]["survived"]
    assert rec["chaos"]["pserver_restarts"] >= 1
