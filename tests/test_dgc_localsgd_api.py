"""DGC + LocalSGD reachable from the fluid API (VERDICT r3 item 5):
fluid.optimizer.DGCMomentumOptimizer (reference optimizer.py:786) and
CompiledProgram.with_local_sgd / DistributedStrategy.use_local_sgd
(reference transpiler/collective.py:249)."""
import numpy as np

import paddle_tpu as fluid

D = 132  # 132*132 = 17424 >= the 16384 DGC eligibility threshold


def _build_reg(opt):
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 21
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [D], dtype="float32")
        y = fluid.layers.data("y", [D], dtype="float32")
        h = fluid.layers.fc(x, D, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(h, y))
        opt.minimize(loss)
    return main, startup, loss


def _data(steps=1, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(D, D).astype("f4") * 0.1
    xs = rng.rand(steps, batch, D).astype("f4")
    ys = xs @ w
    return xs, ys


def _train(main, startup, loss, xs, ys, scope=None):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = scope or fluid.Scope()
    exe.run(startup, scope=scope)
    losses = []
    for i in range(xs.shape[0]):
        (lv,) = exe.run(main, feed={"x": xs[i], "y": ys[i]},
                        fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses, scope


def test_dgc_before_rampup_matches_plain_momentum():
    """rampup_begin_step in the future -> bit-identical to Momentum."""
    xs, ys = _data(steps=5)
    m1, s1, l1 = _build_reg(fluid.optimizer.MomentumOptimizer(0.05, 0.9))
    m2, s2, l2 = _build_reg(fluid.optimizer.DGCMomentumOptimizer(
        0.05, 0.9, rampup_begin_step=1000, sparsity=[0.99]))
    r1, _ = _train(m1, s1, l1, xs, ys)
    r2, _ = _train(m2, s2, l2, xs, ys)
    np.testing.assert_allclose(r1, r2, rtol=1e-6, atol=1e-7)


def test_dgc_first_update_is_topk_sparse():
    """rampup_begin_step=0: the first param delta touches <= k coordinates."""
    sparsity = 0.99
    xs, ys = _data(steps=1)
    main, startup, loss = _build_reg(fluid.optimizer.DGCMomentumOptimizer(
        0.05, 0.9, rampup_begin_step=0, sparsity=[sparsity]))
    pname = [v.name for v in main.list_vars()
             if isinstance(v, fluid.core.program.Parameter)][0]
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    before = np.asarray(scope.find_var(pname)).copy()
    exe.run(main, feed={"x": xs[0], "y": ys[0]}, fetch_list=[loss], scope=scope)
    after = np.asarray(scope.find_var(pname))
    delta_nnz = int((np.abs(after - before) > 0).sum())
    k = max(1, int(D * D * (1 - sparsity)))
    assert 0 < delta_nnz <= k, (delta_nnz, k)
    # error-feedback buffer holds the unsent residual
    v_buf = np.asarray(scope.find_var(f"{pname}_dgc_v_0"))
    assert (np.abs(v_buf) > 0).sum() > 0


def test_dgc_converges_close_to_momentum():
    """convergence parity within tolerance.  Note the compounding: the dgc
    op's output is the top-k of the momentum-corrected V buffer (the
    reference feeds the decoded sparse V into the momentum op —
    dgc_op.h k_select over v_out), so the effective step is larger than
    plain momentum's at the same lr; a warmup-free small lr keeps both
    stable, matching how the reference is deployed (rampup warmup)."""
    xs, ys = _data(steps=80)
    lr = 0.002
    m1, s1, l1 = _build_reg(fluid.optimizer.MomentumOptimizer(lr, 0.9))
    m2, s2, l2 = _build_reg(fluid.optimizer.DGCMomentumOptimizer(
        lr, 0.9, rampup_begin_step=0, rampup_step=30,
        sparsity=[0.8, 0.9, 0.99]))
    r1, _ = _train(m1, s1, l1, xs, ys)
    r2, _ = _train(m2, s2, l2, xs, ys)
    assert r2[-1] < r2[0] * 0.5, (r2[0], r2[-1])
    assert r2[-1] < max(r1[-1] * 5.0, r1[0] * 0.5), (r1[-1], r2[-1])


def test_local_sgd_round_trains_and_tracks_sync_dp():
    """8-dev mesh: with_local_sgd(k) runs k diverging local steps + one
    pmean per dispatch; converges within tolerance of plain sync dp."""
    import jax

    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs the 8-device virtual mesh")

    def build():
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = 3
        with fluid.program_guard(main, startup):
            x = fluid.layers.data("x", [13], dtype="float32")
            y = fluid.layers.data("y", [1], dtype="float32")
            h = fluid.layers.fc(x, 1, bias_attr=False)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(h, y))
            fluid.optimizer.SGD(0.1).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    w = rng.randn(13, 1).astype("f4")
    k, rounds, B = 4, 10, 32  # B divisible by 8 devices

    def feeds():
        xs = rng.rand(rounds, k, B, 13).astype("f4")
        return xs, xs @ w

    xs, ys = feeds()

    # LocalSGD path
    main, startup, loss = build()
    cp = (fluid.CompiledProgram(main)
          .with_data_parallel(loss_name=loss.name)
          .with_local_sgd(sync_every=k))
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    ls_losses = []
    for r in range(rounds):
        (lv,) = exe.run(cp, feed={"x": xs[r], "y": ys[r]},
                        fetch_list=[loss], scope=scope)
        # fetches come back stacked [k]; track the round's last step
        ls_losses.append(float(np.asarray(lv).reshape(-1)[-1]))
    assert ls_losses[-1] < ls_losses[0] * 0.3, ls_losses

    # plain sync dp on the same data stream (steps=k per dispatch)
    main2, startup2, loss2 = build()
    cp2 = fluid.CompiledProgram(main2).with_data_parallel(loss_name=loss2.name)
    scope2 = fluid.Scope()
    exe.run(startup2, scope=scope2)
    dp_losses = []
    for r in range(rounds):
        (lv,) = exe.run(cp2, feed={"x": xs[r], "y": ys[r]},
                        fetch_list=[loss2], scope=scope2, steps=k)
        dp_losses.append(float(np.asarray(lv).reshape(-1)[-1]))
    # parity within tolerance: LocalSGD pays staleness, not divergence
    assert ls_losses[-1] < max(dp_losses[-1] * 5.0, dp_losses[0] * 0.3), (
        ls_losses[-1], dp_losses[-1])


def test_fleet_strategy_local_sgd_knob():
    from paddle_tpu.fleet import DistributedStrategy, Fleet

    f = Fleet()
    strat = DistributedStrategy()
    strat.use_local_sgd = True
    strat.local_sgd_steps = 6

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.fc(x, 1))
        opt = f.distributed_optimizer(fluid.optimizer.SGD(0.1), strat)
        opt.minimize(loss)
    cp = f.main_program(main)
    assert cp.local_sgd_every == 6
