"""API-surface audit gate (VERDICT r3 #6): every entry of the reference
/root/reference/paddle/fluid/API.spec must either resolve on paddle_tpu or
be recorded with a rationale in API_DEVIATIONS.md — exactly one of the two."""
import os
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, os.path.join(REPO, "tools"))


@pytest.fixture(scope="module")
def audit():
    import api_audit

    if not os.path.exists(api_audit.REF_SPEC):
        pytest.skip("reference API.spec not available")
    return api_audit.audit()


def test_every_reference_entry_resolved_or_recorded(audit):
    resolved, recorded, unrecorded = audit
    assert not unrecorded, (
        f"{len(unrecorded)} reference API entries neither resolve on "
        f"paddle_tpu nor appear in API_DEVIATIONS.md: {unrecorded[:15]}"
    )


def test_audit_covers_the_full_reference_surface(audit):
    resolved, recorded, unrecorded = audit
    total = len(resolved) + len(recorded) + len(unrecorded)
    assert total > 900, total  # the reference spec has ~921 entries
    # the deviations file must not swallow entries that actually resolve
    # (a recorded name that now resolves should be deleted from the file)
    import api_audit

    stale = [n for n in api_audit.recorded_deviations()
             if "." not in n and api_audit.resolves(n)]
    assert not stale, f"API_DEVIATIONS.md records now-resolving names: {stale}"
