"""Per-op golden corpus round 3: every registered op the round-2 review
flagged as untested gets a numpy-computed golden (+ check_grad where the op
is differentiable).

Reference pattern: unittests/test_*_op.py over op_test.py:134 (numpy inputs,
numpy expected outputs, finite-difference gradient checks)."""
import numpy as np
import pytest

from op_test import OpTest

RNG = np.random.RandomState(7)


def _x(shape, lo=-2.0, hi=2.0, dtype="float32"):
    return (RNG.rand(*shape) * (hi - lo) + lo).astype(dtype)


def _golden(op_type, inputs, outputs, attrs=None, **kw):
    class T(OpTest):
        def setUp(self):
            self.op_type = op_type
            self.inputs = inputs
            self.outputs = outputs
            self.attrs = attrs or {}

    T().check_output(**kw)


def _grad(op_type, inputs, outputs, attrs, wrt, out_name, **kw):
    class T(OpTest):
        def setUp(self):
            self.op_type = op_type
            self.inputs = inputs
            self.outputs = outputs
            self.attrs = attrs or {}

    T().check_grad(wrt, out_name, **kw)


# --- conv family -----------------------------------------------------------

def _np_conv2d(x, w, stride, pad, dilation=1, groups=1):
    n, cin, h, wd = x.shape
    cout, cpg, kh, kw = w.shape
    xh = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    oh = (h + 2 * pad - (dilation * (kh - 1) + 1)) // stride + 1
    ow = (wd + 2 * pad - (dilation * (kw - 1) + 1)) // stride + 1
    out = np.zeros((n, cout, oh, ow), dtype=np.float64)
    cin_g = cin // groups
    cout_g = cout // groups
    for nn in range(n):
        for oo in range(cout):
            g = oo // cout_g
            for ii in range(cin_g):
                ci = g * cin_g + ii
                for i in range(oh):
                    for j in range(ow):
                        for a in range(kh):
                            for b in range(kw):
                                out[nn, oo, i, j] += (
                                    xh[nn, ci, i * stride + a * dilation, j * stride + b * dilation]
                                    * w[oo, ii, a, b]
                                )
    return out.astype(np.float32)


@pytest.mark.parametrize("stride,pad,dilation", [(1, 0, 1), (2, 1, 1), (1, 1, 2)])
def test_conv2d_golden(stride, pad, dilation):
    x = _x((2, 3, 7, 7))
    w = _x((4, 3, 3, 3), -0.5, 0.5)
    out = _np_conv2d(x, w, stride, pad, dilation)
    _golden("conv2d", {"Input": x, "Filter": w}, {"Output": out},
            {"strides": [stride, stride], "paddings": [pad, pad],
             "dilations": [dilation, dilation], "groups": 1}, atol=1e-4, rtol=1e-4)


def test_conv2d_groups_golden():
    x = _x((2, 4, 5, 5))
    w = _x((6, 2, 3, 3), -0.5, 0.5)
    out = _np_conv2d(x, w, 1, 1, 1, groups=2)
    _golden("conv2d", {"Input": x, "Filter": w}, {"Output": out},
            {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 2},
            atol=1e-4, rtol=1e-4)


def test_conv2d_grad():
    x = _x((1, 2, 4, 4), -1, 1)
    w = _x((3, 2, 3, 3), -0.5, 0.5)
    out = _np_conv2d(x, w, 1, 1)
    _grad("conv2d", {"Input": x, "Filter": w}, {"Output": out},
          {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 1},
          ["Input", "Filter"], "Output", max_relative_error=0.02)


def _np_conv2d_transpose(x, w, stride, pad):
    n, cin, h, wd = x.shape
    _, cout, kh, kw = w.shape
    oh = (h - 1) * stride + kh - 2 * pad
    ow = (wd - 1) * stride + kw - 2 * pad
    full = np.zeros((n, cout, (h - 1) * stride + kh, (wd - 1) * stride + kw), dtype=np.float64)
    for nn in range(n):
        for ci in range(cin):
            for oo in range(cout):
                for i in range(h):
                    for j in range(wd):
                        full[nn, oo, i * stride:i * stride + kh, j * stride:j * stride + kw] += (
                            x[nn, ci, i, j] * w[ci, oo]
                        )
    out = full[:, :, pad:pad + oh, pad:pad + ow]
    return out.astype(np.float32)


@pytest.mark.parametrize("stride,pad", [(1, 0), (2, 1)])
def test_conv2d_transpose_golden(stride, pad):
    x = _x((2, 3, 4, 4))
    w = _x((3, 5, 3, 3), -0.5, 0.5)  # fluid layout (in, out, kh, kw)
    out = _np_conv2d_transpose(x, w, stride, pad)
    _golden("conv2d_transpose", {"Input": x, "Filter": w}, {"Output": out},
            {"strides": [stride, stride], "paddings": [pad, pad],
             "dilations": [1, 1], "groups": 1}, atol=1e-4, rtol=1e-4)


def test_conv2d_transpose_grad():
    x = _x((1, 2, 3, 3), -1, 1)
    w = _x((2, 3, 3, 3), -0.5, 0.5)
    out = _np_conv2d_transpose(x, w, 2, 1)
    _grad("conv2d_transpose", {"Input": x, "Filter": w}, {"Output": out},
          {"strides": [2, 2], "paddings": [1, 1], "dilations": [1, 1], "groups": 1},
          ["Input", "Filter"], "Output", max_relative_error=0.02)


def test_depthwise_conv2d_golden():
    x = _x((2, 3, 6, 6))
    w = _x((3, 1, 3, 3), -0.5, 0.5)
    out = _np_conv2d(x, w, 1, 1, 1, groups=3)
    _golden("depthwise_conv2d", {"Input": x, "Filter": w}, {"Output": out},
            {"strides": [1, 1], "paddings": [1, 1], "dilations": [1, 1], "groups": 3},
            atol=1e-4, rtol=1e-4)


# --- pooling ----------------------------------------------------------------

def _np_pool2d(x, k, stride, pad, ptype, ceil_mode=False, exclusive=True):
    n, c, h, w = x.shape
    if ceil_mode:
        oh = -(-(h + 2 * pad - k) // stride) + 1
        ow = -(-(w + 2 * pad - k) // stride) + 1
    else:
        oh = (h + 2 * pad - k) // stride + 1
        ow = (w + 2 * pad - k) // stride + 1
    out = np.zeros((n, c, oh, ow), dtype=np.float64)
    for i in range(oh):
        for j in range(ow):
            hs, ws = i * stride - pad, j * stride - pad
            he, we = min(hs + k, h), min(ws + k, w)
            hs, ws = max(hs, 0), max(ws, 0)
            patch = x[:, :, hs:he, ws:we]
            if ptype == "max":
                out[:, :, i, j] = patch.max(axis=(2, 3))
            else:
                s = patch.sum(axis=(2, 3))
                if exclusive and (pad or ceil_mode):
                    out[:, :, i, j] = s / ((he - hs) * (we - ws))
                else:
                    out[:, :, i, j] = s / (k * k)
    return out.astype(np.float32)


@pytest.mark.parametrize("ptype", ["max", "avg"])
@pytest.mark.parametrize("k,stride,pad", [(2, 2, 0), (3, 2, 1)])
def test_pool2d_golden(ptype, k, stride, pad):
    x = _x((2, 3, 7, 7))
    out = _np_pool2d(x, k, stride, pad, ptype)
    _golden("pool2d", {"X": x}, {"Out": out},
            {"pooling_type": ptype, "ksize": [k, k], "strides": [stride, stride],
             "paddings": [pad, pad], "global_pooling": False, "ceil_mode": False,
             "exclusive": True}, atol=1e-5)


def test_pool2d_ceil_mode_golden():
    x = _x((1, 2, 7, 7))
    out = _np_pool2d(x, 3, 2, 0, "max", ceil_mode=True)
    _golden("pool2d", {"X": x}, {"Out": out},
            {"pooling_type": "max", "ksize": [3, 3], "strides": [2, 2],
             "paddings": [0, 0], "global_pooling": False, "ceil_mode": True,
             "exclusive": True}, atol=1e-5)


def test_pool2d_global_golden():
    x = _x((2, 3, 5, 5))
    out = x.mean(axis=(2, 3), keepdims=True)
    _golden("pool2d", {"X": x}, {"Out": out},
            {"pooling_type": "avg", "ksize": [1, 1], "strides": [1, 1],
             "paddings": [0, 0], "global_pooling": True, "ceil_mode": False,
             "exclusive": True}, atol=1e-5)


def test_pool2d_avg_grad():
    x = _x((1, 2, 4, 4))
    out = _np_pool2d(x, 2, 2, 0, "avg")
    _grad("pool2d", {"X": x}, {"Out": out},
          {"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2],
           "paddings": [0, 0], "global_pooling": False, "ceil_mode": False,
           "exclusive": True}, ["X"], "Out", max_relative_error=0.01)


# --- norms -------------------------------------------------------------------

def test_batch_norm_is_test_golden():
    x = _x((3, 4, 5, 5))
    scale = _x((4,), 0.5, 1.5)
    bias = _x((4,), -0.5, 0.5)
    mean = _x((4,), -0.2, 0.2)
    var = _x((4,), 0.5, 1.5)
    eps = 1e-5
    bshape = (1, 4, 1, 1)
    y = (x - mean.reshape(bshape)) / np.sqrt(var.reshape(bshape) + eps)
    y = y * scale.reshape(bshape) + bias.reshape(bshape)
    _golden("batch_norm",
            {"X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
            {"Y": y, "MeanOut": mean, "VarianceOut": var, "SavedMean": mean,
             "SavedVariance": var},
            {"epsilon": eps, "momentum": 0.9, "is_test": True, "data_layout": "NCHW",
             "use_global_stats": False},
            atol=1e-4, rtol=1e-4)


def test_batch_norm_training_stats_golden():
    x = _x((4, 3, 2, 2))
    scale = np.ones(3, "float32")
    bias = np.zeros(3, "float32")
    mean_in = np.zeros(3, "float32")
    var_in = np.ones(3, "float32")
    eps, mom = 1e-5, 0.9
    m = x.mean(axis=(0, 2, 3))
    v = x.var(axis=(0, 2, 3))
    y = (x - m.reshape(1, 3, 1, 1)) / np.sqrt(v.reshape(1, 3, 1, 1) + eps)
    _golden("batch_norm",
            {"X": x, "Scale": scale, "Bias": bias, "Mean": mean_in, "Variance": var_in},
            {"Y": y, "MeanOut": mom * mean_in + (1 - mom) * m,
             "VarianceOut": mom * var_in + (1 - mom) * v, "SavedMean": m,
             "SavedVariance": v},
            {"epsilon": eps, "momentum": mom, "is_test": False, "data_layout": "NCHW",
             "use_global_stats": False},
            atol=1e-4, rtol=1e-4)


def test_layer_norm_golden():
    x = _x((3, 4, 5))
    scale = _x((20,), 0.5, 1.5)
    bias = _x((20,), -0.5, 0.5)
    eps = 1e-5
    m = x.reshape(3, -1).mean(axis=1)
    v = x.reshape(3, -1).var(axis=1)
    y = (x - m.reshape(3, 1, 1)) / np.sqrt(v.reshape(3, 1, 1) + eps)
    y = y * scale.reshape(1, 4, 5) + bias.reshape(1, 4, 5)
    _golden("layer_norm", {"X": x, "Scale": scale, "Bias": bias},
            {"Y": y, "Mean": m, "Variance": v},
            {"epsilon": eps, "begin_norm_axis": 1}, atol=1e-4, rtol=1e-4)


def test_layer_norm_grad():
    x = _x((2, 6))
    scale = _x((6,), 0.5, 1.5)
    bias = _x((6,), -0.5, 0.5)
    eps = 1e-5
    m = x.mean(axis=1)
    v = x.var(axis=1)
    y = (x - m[:, None]) / np.sqrt(v[:, None] + eps) * scale + bias
    _grad("layer_norm", {"X": x, "Scale": scale, "Bias": bias},
          {"Y": y, "Mean": m, "Variance": v},
          {"epsilon": eps, "begin_norm_axis": 1},
          ["X", "Scale", "Bias"], "Y", max_relative_error=0.05)


# --- losses ------------------------------------------------------------------

def test_huber_loss_golden_and_grad():
    x = _x((4, 1))
    y = _x((4, 1))
    d = 1.0
    r = y - x
    a = np.abs(r)
    loss = np.where(a <= d, 0.5 * r * r, d * (a - 0.5 * d)).astype("float32")
    _golden("huber_loss", {"X": x, "Y": y}, {"Out": loss, "Residual": r}, {"delta": d})
    _grad("huber_loss", {"X": x, "Y": y}, {"Out": loss, "Residual": r}, {"delta": d},
          ["X"], "Out", max_relative_error=0.02)


def test_smooth_l1_loss_golden():
    x = _x((3, 4))
    y = _x((3, 4))
    sigma = 2.0
    s2 = sigma * sigma
    dd = x - y
    a = np.abs(dd)
    elem = np.where(a < 1.0 / s2, 0.5 * s2 * dd * dd, a - 0.5 / s2)
    out = elem.sum(axis=1).reshape(-1, 1).astype("float32")
    _golden("smooth_l1_loss", {"X": x, "Y": y}, {"Out": out, "Diff": dd}, {"sigma": sigma},
            no_check_set={"Diff"})


def test_smooth_l1_loss_grad():
    x = _x((2, 3))
    y = _x((2, 3))
    sigma = 1.0
    dd = x - y
    a = np.abs(dd)
    elem = np.where(a < 1.0, 0.5 * dd * dd, a - 0.5)
    out = elem.sum(axis=1).reshape(-1, 1).astype("float32")
    _grad("smooth_l1_loss", {"X": x, "Y": y}, {"Out": out, "Diff": dd}, {"sigma": sigma},
          ["X"], "Out", max_relative_error=0.02)


def test_cross_entropy_hard_golden():
    p = RNG.rand(4, 5).astype("float32") + 0.1
    p /= p.sum(axis=1, keepdims=True)
    label = RNG.randint(0, 5, (4, 1)).astype("int64")
    loss = -np.log(p[np.arange(4), label[:, 0]]).reshape(4, 1)
    _golden("cross_entropy", {"X": p, "Label": label}, {"Y": loss}, {})


def test_cross_entropy_soft_golden():
    p = RNG.rand(3, 4).astype("float32") + 0.1
    p /= p.sum(axis=1, keepdims=True)
    soft = RNG.rand(3, 4).astype("float32")
    soft /= soft.sum(axis=1, keepdims=True)
    loss = -(soft * np.log(p)).sum(axis=1, keepdims=True)
    _golden("cross_entropy", {"X": p, "Label": soft}, {"Y": loss}, {"soft_label": True},
            atol=1e-5)


def test_softmax_with_cross_entropy_golden():
    logits = _x((4, 6))
    label = RNG.randint(0, 6, (4, 1)).astype("int64")
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    sm = e / e.sum(axis=1, keepdims=True)
    loss = -np.log(sm[np.arange(4), label[:, 0]]).reshape(4, 1)
    _golden("softmax_with_cross_entropy", {"Logits": logits, "Label": label},
            {"Loss": loss, "Softmax": sm}, {}, atol=1e-5)


def test_sigmoid_cross_entropy_with_logits_golden():
    x = _x((3, 4))
    label = RNG.rand(3, 4).astype("float32")
    loss = np.maximum(x, 0) - x * label + np.log1p(np.exp(-np.abs(x)))
    _golden("sigmoid_cross_entropy_with_logits", {"X": x, "Label": label}, {"Out": loss}, {})


def test_square_error_cost_golden():
    x = _x((5, 2))
    y = _x((5, 2))
    _golden("square_error_cost", {"X": x, "Y": y}, {"Out": (x - y) ** 2}, {})


# --- prelu / label_smooth / one_hot -----------------------------------------

def test_prelu_all_golden():
    x = _x((3, 4))
    alpha = np.array([0.25], "float32")
    out = np.where(x > 0, x, 0.25 * x)
    _golden("prelu", {"X": x, "Alpha": alpha}, {"Out": out}, {"mode": "all"})


def test_prelu_channel_golden():
    x = _x((2, 3, 4, 4))
    alpha = _x((3,), 0.1, 0.5)
    out = np.where(x > 0, x, alpha.reshape(1, 3, 1, 1) * x)
    _golden("prelu", {"X": x, "Alpha": alpha}, {"Out": out}, {"mode": "channel"})


def test_prelu_grad():
    x = _x((2, 3))
    alpha = np.array([0.3], "float32")
    out = np.where(x > 0, x, 0.3 * x)
    _grad("prelu", {"X": x, "Alpha": alpha}, {"Out": out}, {"mode": "all"},
          ["X", "Alpha"], "Out", max_relative_error=0.02)


def test_label_smooth_golden_and_grad():
    x = RNG.rand(4, 5).astype("float32")
    eps = 0.1
    out = (1 - eps) * x + eps / 5
    _golden("label_smooth", {"X": x}, {"Out": out}, {"epsilon": eps})
    _grad("label_smooth", {"X": x}, {"Out": out}, {"epsilon": eps}, ["X"], "Out")


def test_label_smooth_prior_golden():
    x = RNG.rand(3, 4).astype("float32")
    prior = RNG.rand(4).astype("float32")
    eps = 0.2
    out = (1 - eps) * x + eps * prior
    _golden("label_smooth", {"X": x, "PriorDist": prior}, {"Out": out}, {"epsilon": eps})


def test_one_hot_golden():
    x = RNG.randint(0, 6, (5, 1)).astype("int64")
    out = np.zeros((5, 6), "float32")
    out[np.arange(5), x[:, 0]] = 1.0
    _golden("one_hot", {"X": x}, {"Out": out}, {"depth": 6})


# --- tensor manipulation ------------------------------------------------------

def test_expand_golden_and_grad():
    x = _x((2, 3))
    out = np.tile(x, (2, 2))
    _golden("expand", {"X": x}, {"Out": out}, {"expand_times": [2, 2]})
    _grad("expand", {"X": x}, {"Out": out}, {"expand_times": [2, 2]}, ["X"], "Out")


def test_gather_golden_and_grad():
    x = _x((5, 3))
    idx = np.array([0, 2, 4, 2], "int32")
    out = x[idx]
    _golden("gather", {"X": x, "Index": idx}, {"Out": out}, {})
    _grad("gather", {"X": x, "Index": idx}, {"Out": out}, {}, ["X"], "Out")


def test_pad_golden_and_grad():
    x = _x((2, 3))
    out = np.pad(x, ((1, 0), (0, 2)), constant_values=1.5)
    _golden("pad", {"X": x}, {"Out": out}, {"paddings": [1, 0, 0, 2], "pad_value": 1.5})
    _grad("pad", {"X": x}, {"Out": out}, {"paddings": [1, 0, 0, 2], "pad_value": 1.5},
          ["X"], "Out")


def test_slice_golden():
    x = _x((4, 5, 6))
    out = x[1:3, :, 2:5]
    _golden("slice", {"Input": x}, {"Out": out},
            {"axes": [0, 2], "starts": [1, 2], "ends": [3, 5]})


def test_concat_golden():
    a, b, c = _x((2, 3)), _x((2, 2)), _x((2, 4))
    out = np.concatenate([a, b, c], axis=1)
    _golden("concat", {"X": [("ca", a), ("cb", b), ("cc", c)]}, {"Out": out}, {"axis": 1})


def test_split_golden():
    x = _x((6, 4))
    parts = np.split(x, [2, 5], axis=0)
    _golden("split", {"X": x},
            {"Out": [("s0", parts[0]), ("s1", parts[1]), ("s2", parts[2])]},
            {"axis": 0, "sections": [2, 3, 1], "num": 0})


def test_stack_unstack_golden():
    a, b = _x((3, 4)), _x((3, 4))
    _golden("stack", {"X": [("sa", a), ("sb", b)]}, {"Y": np.stack([a, b], axis=1)},
            {"axis": 1})
    x = _x((2, 3, 4))
    _golden("unstack", {"X": x},
            {"Y": [("u0", x[:, 0]), ("u1", x[:, 1]), ("u2", x[:, 2])]}, {"axis": 1})


def test_squeeze_unsqueeze_golden():
    x = _x((3, 1, 4, 1))
    _golden("squeeze2", {"X": x}, {"Out": x.reshape(3, 4)}, {"axes": [1, 3]},
            no_check_set={"XShape"})
    y = _x((3, 4))
    _golden("unsqueeze2", {"X": y}, {"Out": y.reshape(3, 1, 4, 1)}, {"axes": [1, 3]},
            no_check_set={"XShape"})


def test_reshape_zero_and_infer_golden():
    x = _x((2, 3, 4))
    _golden("reshape2", {"X": x}, {"Out": x.reshape(2, 12)}, {"shape": [0, -1]},
            no_check_set={"XShape"})


def test_transpose_golden():
    x = _x((2, 3, 4))
    _golden("transpose2", {"X": x}, {"Out": x.transpose(2, 0, 1)}, {"axis": [2, 0, 1]},
            no_check_set={"XShape"})


def test_assign_value_fill_golden():
    _golden("fill_constant", {}, {"Out": np.full((2, 3), 2.5, "float32")},
            {"shape": [2, 3], "value": 2.5, "dtype": "float32"})
    x = _x((3, 2))
    _golden("fill_zeros_like", {"X": x}, {"Out": np.zeros_like(x)}, {})
    vals = [1.0, 2.0, 3.0, 4.0]
    _golden("assign_value", {}, {"Out": np.array(vals, "float32").reshape(2, 2)},
            {"values": vals, "shape": [2, 2], "dtype": "float32"})


def test_increment_range_shape_golden():
    x = np.array([3.0], "float32")
    _golden("increment", {"X": x}, {"Out": x + 2.0}, {"step": 2.0})
    _golden("range", {"Start": np.array([1], "int32"), "End": np.array([7], "int32"),
                      "Step": np.array([2], "int32")},
            {"Out": np.arange(1, 7, 2, "int32")}, {"start_v": 1, "end_v": 7, "step_v": 2})
    x2 = _x((3, 4, 5))
    _golden("shape", {"Input": x2}, {"Out": np.array([3, 4, 5], "int32")}, {})


def test_cast_scale_clip_golden():
    x = _x((3, 4))
    _golden("cast", {"X": x}, {"Out": x.astype("int32")}, {"out_dtype": "int32"})
    _golden("scale", {"X": x}, {"Out": x * 3.0 + 1.0}, {"scale": 3.0, "bias": 1.0})
    _golden("scale", {"X": x}, {"Out": (x + 1.0) * 3.0},
            {"scale": 3.0, "bias": 1.0, "bias_after_scale": False})
    _golden("clip", {"X": x}, {"Out": np.clip(x, -0.5, 0.5)}, {"min": -0.5, "max": 0.5})


def test_clip_by_norm_golden():
    x = _x((3, 4))
    norm = np.sqrt((x ** 2).sum())
    maxn = float(norm) / 2
    _golden("clip_by_norm", {"X": x}, {"Out": x * (maxn / norm)}, {"max_norm": maxn})
    _golden("clip_by_norm", {"X": x}, {"Out": x}, {"max_norm": float(norm) * 2})


def test_pow_isfinite_golden():
    x = _x((3, 3), 0.5, 2.0)
    _golden("pow", {"X": x}, {"Out": x ** 2.5}, {"factor": 2.5})
    _golden("isfinite", {"X": x}, {"Out": np.array([True])}, {})
    bad = x.copy()
    bad[0, 0] = np.inf
    _golden("isfinite", {"X": bad}, {"Out": np.array([False])}, {})


# --- matmul / reductions ------------------------------------------------------

@pytest.mark.parametrize("tx,ty", [(False, False), (True, False), (False, True)])
def test_matmul_golden(tx, ty):
    a = _x((4, 3) if tx else (3, 4))
    b = _x((5, 4) if ty else (4, 5))
    out = (a.T if tx else a) @ (b.T if ty else b)
    _golden("matmul", {"X": a, "Y": b}, {"Out": out},
            {"transpose_X": tx, "transpose_Y": ty}, atol=1e-5)


def test_matmul_batched_alpha_golden():
    a = _x((2, 3, 4))
    b = _x((2, 4, 5))
    _golden("matmul", {"X": a, "Y": b}, {"Out": 0.5 * (a @ b)}, {"alpha": 0.5}, atol=1e-5)


def test_matmul_grad():
    a = _x((2, 3))
    b = _x((3, 4))
    _grad("matmul", {"X": a, "Y": b}, {"Out": a @ b}, {}, ["X", "Y"], "Out",
          max_relative_error=0.02)


def test_mul_flatten_golden():
    x = _x((2, 3, 4))
    y = _x((12, 5))
    out = (x.reshape(2, 12) @ y).reshape(2, 5)
    _golden("mul", {"X": x, "Y": y}, {"Out": out},
            {"x_num_col_dims": 1, "y_num_col_dims": 1}, atol=1e-5)


@pytest.mark.parametrize("op,fn", [
    ("reduce_sum", np.sum), ("reduce_mean", np.mean), ("reduce_max", np.max),
    ("reduce_min", np.min), ("reduce_prod", np.prod)])
def test_reduce_golden(op, fn):
    x = _x((3, 4, 5), 0.5, 1.5)
    _golden(op, {"X": x}, {"Out": fn(x, axis=(1,))}, {"dim": [1]}, atol=1e-4, rtol=1e-4)
    _golden(op, {"X": x}, {"Out": fn(x, axis=(0, 2), keepdims=True)},
            {"dim": [0, 2], "keep_dim": True}, atol=1e-4, rtol=1e-4)
    _golden(op, {"X": x}, {"Out": np.asarray(fn(x))}, {"reduce_all": True},
            atol=1e-4, rtol=1e-4)


def test_reduce_mean_grad():
    x = _x((3, 4))
    _grad("reduce_mean", {"X": x}, {"Out": x.mean(axis=1)}, {"dim": [1]}, ["X"], "Out")


def test_mean_frobenius_golden():
    x = _x((3, 4))
    _golden("mean", {"X": x}, {"Out": np.array([x.mean()], "float32")}, {})
    _golden("frobenius_norm", {"X": x}, {"Out": np.sqrt((x ** 2).sum())}, {}, atol=1e-5)


def test_softmax_golden_and_grad():
    # local RNG: the FD grad check is sensitive to the draw (near-ties in
    # the softmax max make the numeric gradient noisy), so this test must
    # not depend on how many draws earlier tests consumed from the module
    # RNG — with `-k` selections that ordering shifts and produced flakes
    x = (np.random.RandomState(11).rand(3, 5) * 4 - 2).astype("float32")
    e = np.exp(x - x.max(axis=1, keepdims=True))
    sm = e / e.sum(axis=1, keepdims=True)
    _golden("softmax", {"X": x}, {"Out": sm}, {}, atol=1e-5)
    _golden("log_softmax", {"X": x}, {"Out": np.log(sm)}, {}, atol=1e-5)
    # softmax grad is checked through log_softmax (sum-of-softmax has an
    # identically-zero gradient, so FD on it measures only noise)
    # 4%: f32 central differences at delta=1e-3 carry ~2-3% relative noise
    # on the small-magnitude entries of the log_softmax jacobian
    _grad("log_softmax", {"X": x}, {"Out": np.log(sm)}, {}, ["X"], "Out",
          max_relative_error=0.04)


# --- embedding / topk / metrics ------------------------------------------------

def test_lookup_table_golden_and_grad():
    w = _x((10, 4))
    ids = RNG.randint(0, 10, (5, 1)).astype("int64")
    out = w[ids[:, 0]]
    _golden("lookup_table", {"W": w, "Ids": ids}, {"Out": out}, {})
    _grad("lookup_table", {"W": w, "Ids": ids}, {"Out": out}, {}, ["W"], "Out")


def test_lookup_table_padding_idx_golden():
    w = _x((8, 3))
    ids = np.array([[1], [2], [2], [5]], "int64")
    out = w[ids[:, 0]].copy()
    out[1] = 0
    out[2] = 0
    _golden("lookup_table", {"W": w, "Ids": ids}, {"Out": out}, {"padding_idx": 2})


def test_top_k_golden():
    x = _x((3, 6))
    k = 2
    idx = np.argsort(-x, axis=1)[:, :k]
    vals = np.take_along_axis(x, idx, axis=1)
    _golden("top_k", {"X": x}, {"Out": vals, "Indices": idx.astype("int64")}, {"k": k})


def test_argmax_argmin_golden():
    x = _x((3, 5))
    _golden("arg_max", {"X": x}, {"Out": x.argmax(axis=1).astype("int64")}, {"axis": 1})
    _golden("arg_min", {"X": x}, {"Out": x.argmin(axis=0).astype("int64")}, {"axis": 0})


def test_accuracy_golden():
    label = np.array([[1], [0], [3]], "int64")
    indices = np.array([[1, 2], [2, 3], [3, 0]], "int64")
    correct = 2  # rows 0 and 2 contain the label
    _golden("accuracy", {"Indices": indices, "Label": label},
            {"Accuracy": np.array([correct / 3.0], "float32"),
             "Correct": np.array([correct], "int32"),
             "Total": np.array([3], "int32")},
            {})


def test_gaussian_and_uniform_random_moments():
    """Random ops: distribution moments, not exact values."""
    import paddle_tpu as fluid
    from paddle_tpu.core.program import Program, program_guard
    from paddle_tpu.core.scope import Scope

    prog, startup = Program(), Program()
    with program_guard(prog, startup):
        blk = prog.global_block()
        blk.create_var("u", dtype="float32")
        blk.create_var("g", dtype="float32")
        blk.append_op("uniform_random", inputs={}, outputs={"Out": ["u"]},
                      attrs={"shape": [2000], "min": -2.0, "max": 4.0, "seed": 5})
        blk.append_op("gaussian_random", inputs={}, outputs={"Out": ["g"]},
                      attrs={"shape": [2000], "mean": 1.5, "std": 0.5, "seed": 9})
    exe = fluid.Executor(fluid.CPUPlace())
    u, g = exe.run(prog, feed={}, fetch_list=["u", "g"], scope=Scope())
    assert -2.0 <= u.min() and u.max() <= 4.0 and abs(u.mean() - 1.0) < 0.2
    assert abs(g.mean() - 1.5) < 0.05 and abs(g.std() - 0.5) < 0.05


# --- optimizer single-step goldens ---------------------------------------------

LR = np.array([0.1], "float32")


def test_sgd_golden():
    p, g = _x((4, 3)), _x((4, 3))
    _golden("sgd", {"Param": p, "Grad": g, "LearningRate": LR},
            {"ParamOut": p - 0.1 * g}, {})


@pytest.mark.parametrize("nesterov", [False, True])
def test_momentum_golden(nesterov):
    p, g, v = _x((4,)), _x((4,)), _x((4,))
    mu = 0.9
    vn = mu * v + g
    pn = p - 0.1 * (g + mu * vn) if nesterov else p - 0.1 * vn
    _golden("momentum", {"Param": p, "Grad": g, "Velocity": v, "LearningRate": LR},
            {"ParamOut": pn, "VelocityOut": vn}, {"mu": mu, "use_nesterov": nesterov},
            atol=1e-5)


def test_adam_golden():
    p, g = _x((5,)), _x((5,))
    m1, m2 = _x((5,)), np.abs(_x((5,)))
    b1p = np.array([0.9], "float32")
    b2p = np.array([0.999], "float32")
    b1, b2, eps = 0.9, 0.999, 1e-8
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    lr_t = 0.1 * np.sqrt(1 - b2p[0]) / (1 - b1p[0])
    pn = p - lr_t * m1n / (np.sqrt(m2n) + eps)
    _golden("adam",
            {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
             "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": LR},
            {"ParamOut": pn, "Moment1Out": m1n, "Moment2Out": m2n,
             "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2},
            {"beta1": b1, "beta2": b2, "epsilon": eps}, atol=1e-5)


def test_adagrad_golden():
    p, g = _x((4,)), _x((4,))
    mom = np.abs(_x((4,)))
    eps = 1e-6
    mn = mom + g * g
    _golden("adagrad", {"Param": p, "Grad": g, "Moment": mom, "LearningRate": LR},
            {"ParamOut": p - 0.1 * g / (np.sqrt(mn) + eps), "MomentOut": mn},
            {"epsilon": eps}, atol=1e-5)


@pytest.mark.parametrize("centered", [False, True])
def test_rmsprop_golden(centered):
    p, g = _x((4,)), _x((4,))
    # keep E[g^2] well above E[g]^2 so the centered denom stays positive
    ms, mg, mom = np.abs(_x((4,))) + 1.0, 0.1 * _x((4,)), _x((4,))
    rho, eps, momentum = 0.95, 1e-6, 0.8
    msn = rho * ms + (1 - rho) * g * g
    if centered:
        mgn = rho * mg + (1 - rho) * g
        denom = np.sqrt(msn - mgn * mgn + eps)
    else:
        mgn = mg
        denom = np.sqrt(msn + eps)
    momn = momentum * mom + 0.1 * g / denom
    _golden("rmsprop",
            {"Param": p, "Grad": g, "MeanSquare": ms, "MeanGrad": mg, "Moment": mom,
             "LearningRate": LR},
            {"ParamOut": p - momn, "MeanSquareOut": msn, "MeanGradOut": mgn,
             "MomentOut": momn},
            {"decay": rho, "epsilon": eps, "momentum": momentum, "centered": centered},
            atol=1e-5)


def test_adamax_golden():
    p, g = _x((4,)), _x((4,))
    m, inf = _x((4,)), np.abs(_x((4,)))
    b1p = np.array([0.9], "float32")
    b1, b2, eps = 0.9, 0.999, 1e-8
    mn = b1 * m + (1 - b1) * g
    infn = np.maximum(b2 * inf, np.abs(g))
    pn = p - (0.1 / (1 - b1p[0])) * mn / (infn + eps)
    _golden("adamax",
            {"Param": p, "Grad": g, "Moment": m, "InfNorm": inf, "Beta1Pow": b1p,
             "LearningRate": LR},
            {"ParamOut": pn, "MomentOut": mn, "InfNormOut": infn},
            {"beta1": b1, "beta2": b2, "epsilon": eps}, atol=1e-5)


def test_adadelta_golden():
    p, g = _x((4,)), _x((4,))
    asg, asu = np.abs(_x((4,))), np.abs(_x((4,)))
    rho, eps = 0.95, 1e-6
    g2 = rho * asg + (1 - rho) * g * g
    upd = -np.sqrt((asu + eps) / (g2 + eps)) * g
    u2 = rho * asu + (1 - rho) * upd * upd
    _golden("adadelta",
            {"Param": p, "Grad": g, "AvgSquaredGrad": asg, "AvgSquaredUpdate": asu,
             "LearningRate": LR},
            {"ParamOut": p + upd, "AvgSquaredGradOut": g2, "AvgSquaredUpdateOut": u2},
            {"rho": rho, "epsilon": eps}, atol=1e-5)


def test_ftrl_golden():
    p, g = _x((4,)), _x((4,))
    sq, lin = np.abs(_x((4,))) + 0.1, _x((4,))
    l1, l2, lrp = 0.1, 0.2, -0.5
    nsq = sq + g * g
    sigma = (nsq ** 0.5 - sq ** 0.5) / 0.1
    nlin = lin + g - sigma * p
    quad = nsq ** 0.5 / 0.1 + 2 * l2
    pre = np.clip(nlin, -l1, l1) - nlin
    pn = np.where(np.abs(nlin) > l1, pre / quad, np.zeros_like(p))
    _golden("ftrl",
            {"Param": p, "Grad": g, "SquaredAccumulator": sq, "LinearAccumulator": lin,
             "LearningRate": LR},
            {"ParamOut": pn, "SquaredAccumOut": nsq, "LinearAccumOut": nlin},
            {"l1": l1, "l2": l2, "lr_power": lrp}, atol=1e-4, rtol=1e-4)


def test_lamb_golden():
    p, g = _x((4,)), _x((4,))
    m1, m2 = _x((4,)), np.abs(_x((4,)))
    b1p = np.array([0.9], "float32")
    b2p = np.array([0.999], "float32")
    b1, b2, eps, wd = 0.9, 0.999, 1e-6, 0.01
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * g * g
    mhat = m1n / (1 - b1p[0])
    vhat = m2n / (1 - b2p[0])
    r = mhat / (np.sqrt(vhat) + eps) + wd * p
    wn = np.sqrt((p ** 2).sum())
    rn = np.sqrt((r ** 2).sum())
    ratio = wn / rn if wn > 0 and rn > 0 else 1.0
    _golden("lamb",
            {"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
             "Beta1Pow": b1p, "Beta2Pow": b2p, "LearningRate": LR},
            {"ParamOut": p - 0.1 * ratio * r, "Moment1Out": m1n, "Moment2Out": m2n,
             "Beta1PowOut": b1p * b1, "Beta2PowOut": b2p * b2},
            {"beta1": b1, "beta2": b2, "epsilon": eps, "weight_decay": wd},
            atol=1e-4, rtol=1e-4)


def test_batch_norm_extreme_mean_stability():
    """Single-sweep BN stats must not cancel catastrophically: activations
    with |mean|/std ~ 3e4 (the classic E[x^2]-E[x]^2 failure mode) must
    still produce accurate SavedVariance."""
    x = (300.0 + 0.01 * RNG.randn(8, 3, 4, 4)).astype("float32")
    scale = np.ones(3, "float32")
    bias = np.zeros(3, "float32")
    mean_in = np.zeros(3, "float32")
    var_in = np.ones(3, "float32")
    m = x.mean(axis=(0, 2, 3))
    v = x.var(axis=(0, 2, 3))
    eps = 1e-5
    y = (x - m.reshape(1, 3, 1, 1)) / np.sqrt(v.reshape(1, 3, 1, 1) + eps)
    _golden("batch_norm",
            {"X": x, "Scale": scale, "Bias": bias, "Mean": mean_in, "Variance": var_in},
            {"Y": y, "MeanOut": 0.9 * mean_in + 0.1 * m, "VarianceOut": 0.9 * var_in + 0.1 * v,
             "SavedMean": m, "SavedVariance": v},
            {"epsilon": eps, "momentum": 0.9, "is_test": False, "data_layout": "NCHW",
             "use_global_stats": False},
            atol=6e-3, rtol=5e-2)


# --- round-3c batch: scatter/gather_nd/cumsum/argsort/norm variants ----------

def test_gather_nd_golden():
    x = _x((3, 4, 5))
    idx = np.array([[0, 1], [2, 3]], "int32")
    _golden("gather_nd", {"X": x, "Index": idx}, {"Out": x[[0, 2], [1, 3]]}, {})


def test_scatter_golden():
    x = _x((5, 3))
    ids = np.array([1, 3], "int32")
    upd = _x((2, 3))
    over = x.copy()
    over[ids] = upd
    _golden("scatter", {"X": x, "Ids": ids, "Updates": upd}, {"Out": over},
            {"overwrite": True})
    add = x.copy()
    for i, r in zip(ids, upd):
        add[i] += r
    _golden("scatter", {"X": x, "Ids": ids, "Updates": upd}, {"Out": add},
            {"overwrite": False})


def test_scatter_nd_add_golden():
    x = _x((4, 3))
    idx = np.array([[1], [1], [3]], "int32")
    upd = _x((3, 3))
    ref = x.copy()
    for i, r in zip(idx[:, 0], upd):
        ref[i] += r
    _golden("scatter_nd_add", {"X": x, "Index": idx, "Updates": upd}, {"Out": ref},
            {}, atol=1e-5)


def test_cumsum_variants():
    x = _x((3, 4))
    _golden("cumsum", {"X": x}, {"Out": np.cumsum(x, axis=1)}, {"axis": 1}, atol=1e-5)
    ref = np.cumsum(x[:, ::-1], axis=1)[:, ::-1]
    _golden("cumsum", {"X": x}, {"Out": ref}, {"axis": 1, "reverse": True}, atol=1e-5)
    excl = np.cumsum(x, axis=1) - x
    _golden("cumsum", {"X": x}, {"Out": excl}, {"axis": 1, "exclusive": True}, atol=1e-5)


def test_argsort_golden():
    x = _x((2, 5))
    idx = np.argsort(-x, axis=1)
    _golden("argsort", {"X": x},
            {"Out": np.take_along_axis(x, idx, 1), "Indices": idx.astype("int64")},
            {"axis": 1, "descending": True})


def test_norm_l2_normalize_golden():
    x = _x((3, 4), 0.5, 2.0)
    n = np.sqrt((x ** 2).sum(axis=1, keepdims=True) + 1e-10)
    _golden("norm", {"X": x}, {"Out": x / n, "Norm": n}, {"axis": 1, "epsilon": 1e-10},
            atol=1e-5)


def test_group_instance_norm_golden():
    x = _x((2, 4, 3, 3))
    scale = np.ones(4, "f4")
    bias = np.zeros(4, "f4")
    # group_norm, 2 groups
    xr = x.reshape(2, 2, 2, 3, 3)
    m = xr.mean(axis=(2, 3, 4), keepdims=True)
    v = xr.var(axis=(2, 3, 4), keepdims=True)
    y = ((xr - m) / np.sqrt(v + 1e-5)).reshape(x.shape)
    _golden("group_norm", {"X": x, "Scale": scale, "Bias": bias},
            {"Y": y, "Mean": m.reshape(2, 2), "Variance": v.reshape(2, 2)},
            {"epsilon": 1e-5, "groups": 2}, atol=1e-4, rtol=1e-4)
    # instance_norm
    mi = x.mean(axis=(2, 3), keepdims=True)
    vi = x.var(axis=(2, 3), keepdims=True)
    yi = (x - mi) / np.sqrt(vi + 1e-5)
    _golden("instance_norm", {"X": x, "Scale": scale, "Bias": bias},
            {"Y": yi, "SavedMean": mi.reshape(2, 4), "SavedVariance": vi.reshape(2, 4)},
            {"epsilon": 1e-5}, atol=1e-4, rtol=1e-4)


def test_flatten_shard_index_linspace():
    x = _x((2, 3, 4))
    _golden("flatten2", {"X": x}, {"Out": x.reshape(2, 12)}, {"axis": 1},
            no_check_set={"XShape"})
    ids = np.array([0, 5, 9, 14], "int64")
    # index_num 16, 4 shards -> shard size 4; shard 1 owns [4, 8)
    exp = np.where((ids // 4) == 1, ids % 4, -1)
    _golden("shard_index", {"X": ids}, {"Out": exp},
            {"index_num": 16, "nshards": 4, "shard_id": 1})
    _golden("linspace", {"Start": np.array([0.0], "f4"), "Stop": np.array([1.0], "f4"),
                         "Num": np.array([5], "i4")},
            {"Out": np.linspace(0, 1, 5, dtype="f4")}, {"num_v": 5}, atol=1e-6)


@pytest.mark.parametrize("name,fn", [
    ("tan", np.tan), ("asin", np.arcsin), ("acos", np.arccos),
    ("atan", np.arctan), ("sinh", np.sinh), ("cosh", np.cosh),
    ("log1p", np.log1p), ("expm1", np.expm1),
])
def test_unary_extras(name, fn):
    x = _x((3, 4), -0.9, 0.9) if name in ("asin", "acos") else _x((3, 4), 0.1, 0.9)
    _golden(name, {"X": x}, {"Out": fn(x)}, {}, atol=1e-5, rtol=1e-4)


def test_hard_shrink_stanh_attrs():
    x = _x((3, 4))
    _golden("hard_shrink", {"X": x}, {"Out": np.where(np.abs(x) > 0.3, x, 0.0)},
            {"threshold": 0.3})
    _golden("stanh", {"X": x}, {"Out": 1.7159 * np.tanh(0.67 * x)}, {}, atol=1e-5)
    _golden("stanh", {"X": x}, {"Out": 2.0 * np.tanh(0.5 * x)},
            {"scale_a": 0.5, "scale_b": 2.0}, atol=1e-5)


def test_expand_as_with_target_tensor():
    x = _x((2, 3))
    target = _x((4, 6))
    _golden("expand_as", {"X": x, "target_tensor": target},
            {"Out": np.tile(x, (2, 2))}, {})
