"""Elastic-gang chaos suite (ISSUE 9 acceptance): a real 2-process gang,
one rank SIGKILLed mid-run.

The properties under test:

  1. after the PR-4 grace window the gang CONTINUES at N-1 — the next
     incarnation runs at 1 worker (elastic shrink), never a same-size
     relaunch into the missing capacity;
  2. once the shrunk gang commits fresh progress and capacity returns,
     the supervisor drains it gracefully and grows back to N;
  3. the full N->M->N cycle is loss-parity with an uninterrupted run
     (per-step losses allclose; the world-1 segment reassociates the dp
     mean, so bit-equality across world sizes is impossible by
     construction — docs/robustness.md records the caveat) and both
     grown ranks end bit-identical to each other;
  4. ZERO samples dropped or double-trained, verified by stream-cursor
     accounting: every logged step's id-sum — fetched THROUGH the
     training feed — must equal the canonical sum of its global batch,
     over the effective (post-rollback) trajectory.

Assertions key on the KILL incident and the resize ledger, not on
incarnation indices: a loaded CI box can lose a whole incarnation to a
bootstrap timeout, which the restart machinery absorbs at unchanged
size (classified exits are not lost capacity)."""
import json
import os
import sys

import numpy as np
import pytest

from dist_harness import run_gang

HERE = os.path.dirname(os.path.abspath(__file__))
ELASTIC_WORKER = os.path.join(HERE, "dist_worker_elastic.py")

pytestmark = pytest.mark.skipif(
    not os.path.exists(ELASTIC_WORKER), reason="worker script missing")

RUN_STEPS = 14
GBS = 16

CHAOS_ENV = {
    "RUN_STEPS": str(RUN_STEPS),
    "SAVE_EVERY": "2",
    "GLOBAL_BS": str(GBS),
    # keep the shrunk incarnation alive long enough for the supervisor
    # to observe its commit and initiate the grow
    "PT_STEP_SLEEP": "0.08",
    "FLAGS_dist_heartbeat_interval_s": "0.25",
    "FLAGS_dist_heartbeat_miss_factor": "12",
    "FLAGS_dist_watchdog_timeout_s": "60",
    "FLAGS_dist_bootstrap_timeout_s": "120",
}


def _results(workers):
    out = {}
    for rank, (code, o, _e) in enumerate(workers):
        for line in (o or "").splitlines():
            if line.startswith("RESULT "):
                out[rank] = json.loads(line[len("RESULT "):])
    return out


def _read_ledgers(led_dir):
    """{incarnation: [records]} from rank 0's ledgers (the id-sum is a
    GLOBAL quantity — dp-mean-combined — so one rank's view suffices)."""
    out = {}
    if not os.path.isdir(led_dir):
        return out
    for name in os.listdir(led_dir):
        if not (name.startswith("ledger.r0.i") and name.endswith(".jsonl")):
            continue
        inc = int(name[len("ledger.r0.i"):-len(".jsonl")])
        with open(os.path.join(led_dir, name)) as f:
            out[inc] = [json.loads(l) for l in f if l.strip()]
    return out


def _effective_trajectory(ledgers):
    """The steps that actually shaped the final params: later
    incarnations rewind to their restore point, so their records
    overwrite earlier ones from their start step on."""
    eff = {}
    for inc in sorted(ledgers):
        for rec in ledgers[inc]:
            eff[rec["step"]] = rec
    return eff


def _lost_to_bootstrap_load(res):
    for inc in res.incidents:
        for tail in inc.get("stderr_tails", {}).values():
            if ("Gloo context initialization failed" in tail
                    or "GetKeyValue" in tail):
                return True
    return False


def test_elastic_cycle_kill_shrink_grow_parity(tmp_path):
    from paddle_tpu import monitor
    from paddle_tpu.monitor import MonitorLogger

    # --- uninterrupted world-2 reference -------------------------------
    ref_led = str(tmp_path / "refled")
    ref = run_gang([sys.executable, ELASTIC_WORKER], 2,
                   checkpoint_root=str(tmp_path / "refck"),
                   extra_env={**CHAOS_ENV, "PT_LEDGER_DIR": ref_led,
                              "PT_STEP_SLEEP": "0"},
                   max_restarts=0, timeout=240)
    assert ref.ok, ref.workers
    ref_out = _results(ref.workers)
    assert ref_out[0]["params_sha"] == ref_out[1]["params_sha"]
    ref_losses = {r["step"]: r["loss"]
                  for r in _read_ledgers(ref_led).get(0, [])}
    assert sorted(ref_losses) == list(range(RUN_STEPS))

    # --- elastic chaos: kill rank 1 at step 5 --------------------------
    metrics = str(tmp_path / "gang.jsonl")
    monitor.enable()
    logger = monitor.get_monitor().attach_logger(MonitorLogger(metrics))
    led = str(tmp_path / "led")
    try:
        res = None
        for attempt in range(3):  # bounded retries absorb pure load flakes
            led = str(tmp_path / f"led{attempt}")
            res = run_gang(
                [sys.executable, ELASTIC_WORKER], 2,
                checkpoint_root=str(tmp_path / f"ck{attempt}"),
                extra_env={**CHAOS_ENV, "PT_LEDGER_DIR": led,
                           "FLAGS_fault_spec": "kill_worker@5:1"},
                max_restarts=3, elastic=True, min_procs=1, timeout=240)
            if res.ok and not _lost_to_bootstrap_load(res):
                break
    finally:
        logger.write_snapshot()
        monitor.get_monitor().detach_logger(logger)
    assert res.ok, (res.incidents, res.workers)

    # the injected death really happened: rank 1 SIGKILLed, the survivor
    # classified (exit 43) instead of hanging
    kill = next(i for i in res.incidents
                if any(d["rank"] == 1 and d["returncode"] == -9
                       and d["signaled"] for d in i["dead"]))
    survivor = next(d for d in kill["dead"] if d["rank"] == 0)
    assert survivor["returncode"] == 43 and survivor["classified"]

    # 1+2. shrink to N-1 (no same-size relaunch into missing capacity),
    # then grow back to N: the resize ledger shows exactly one of each
    shrinks = [e for e in res.resize_events if e["direction"] == "shrink"]
    grows = [e for e in res.resize_events if e["direction"] == "grow"]
    assert len(shrinks) == 1 and len(grows) == 1, res.resize_events
    assert (shrinks[0]["from_nprocs"], shrinks[0]["to_nprocs"]) == (2, 1)
    assert (grows[0]["from_nprocs"], grows[0]["to_nprocs"]) == (1, 2)
    assert res.resizes == 2
    # the incarnation right after the kill ran at 1 worker — the gang
    # never relaunched at 2 while the capacity was gone
    ki = kill["incarnation"]
    assert res.size_history[ki] == 2 and res.size_history[ki + 1] == 1
    assert res.size_history[-1] == 2 and res.final_nprocs == 2

    out = _results(res.workers)
    assert out[0]["world"] == out[1]["world"] == 2
    # the final incarnation grew out of a world-1 checkpoint: elastic
    # restore really crossed a world-size boundary in BOTH directions
    assert out[0]["restored_world"] == 1
    mid = _results(res.history[ki + 1])
    assert mid and mid[0]["world"] == 1 and mid[0]["restored_world"] == 2
    assert mid[0]["preempted"], "the shrunk gang should exit via the drain"

    # 4. zero dropped / double-trained samples: the effective trajectory
    # covers every step exactly once, and each step's id-sum (fetched
    # through the training feed) equals its canonical global batch
    eff = _effective_trajectory(_read_ledgers(led))
    assert sorted(eff) == list(range(RUN_STEPS)), sorted(eff)
    for s in range(RUN_STEPS):
        want = sum(range(s * GBS, (s + 1) * GBS))
        assert eff[s]["idsum"] == want, (s, eff[s]["idsum"], want)

    # 3. loss parity with the uninterrupted run over the whole effective
    # trajectory, and the grown ranks end bit-identical to each other
    for s in range(RUN_STEPS):
        np.testing.assert_allclose(eff[s]["loss"], ref_losses[s],
                                   rtol=1e-4, atol=1e-6)
    assert out[0]["params_sha"] == out[1]["params_sha"]
    np.testing.assert_allclose(out[0]["params_l2"], ref_out[0]["params_l2"],
                               rtol=1e-4)

    # CI gate: the resize ledger rides the launcher's metrics stream
    sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))
    import perf_report

    assert perf_report.check(metrics, max_gang_resizes=2) == 0
    assert perf_report.check(metrics, max_gang_resizes=1) == 1
    lines = [json.loads(l) for l in open(metrics) if l.strip()]
    assert any(r.get("action") == "gang_resize"
               and r.get("direction") == "shrink" for r in lines)
