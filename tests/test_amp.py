"""Mixed-precision decorator: dynamic loss scaling, overflow skip, state
machine (reference contrib/mixed_precision/decorator.py:26)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib.mixed_precision import decorate


def _build(dtype="float16", incr_every=4, init_scale=8.0, lr=0.05):
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        net_in = fluid.layers.cast(x, dtype) if dtype != "float32" else x
        h = fluid.layers.fc(net_in, 16, act="relu")
        pred = fluid.layers.fc(h, 1)
        pred32 = fluid.layers.cast(pred, "float32") if dtype != "float32" else pred
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred32, y))
        opt = decorate(fluid.optimizer.Momentum(lr, 0.9),
                       init_loss_scaling=init_scale,
                       incr_every_n_steps=incr_every,
                       decr_every_n_nan_or_inf=1)
        opt.minimize(loss)
    return main, startup, loss, opt


def test_amp_fp16_converges():
    main, startup, loss, opt = _build("float16")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    w = rng.rand(6, 1).astype("f4")
    losses = []
    for _ in range(60):
        xv = rng.rand(16, 6).astype("f4")
        yv = xv @ w
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.3, f"{losses[0]} -> {losses[-1]}"


def test_amp_scaling_grows_on_finite_steps():
    main, startup, loss, opt = _build("float32", incr_every=3, init_scale=4.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(1)
    for _ in range(6):
        xv = rng.rand(8, 6).astype("f4")
        yv = rng.rand(8, 1).astype("f4")
        exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
    # 6 finite steps with incr_every=3 => scale doubled twice: 4 -> 16
    s = float(np.asarray(scope.find_var("loss_scaling_0"))[0])
    assert s == 16.0, s


def test_amp_overflow_skips_update_and_halves_scale():
    main, startup, loss, opt = _build("float32", incr_every=100, init_scale=8.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    # params before
    pnames = [v.name for v in main.all_parameters()]
    rng = np.random.RandomState(2)
    xv = rng.rand(8, 6).astype("f4")
    yv = rng.rand(8, 1).astype("f4")
    exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
    before = {n: np.asarray(scope.find_var(n)).copy() for n in pnames}
    # poison one feed -> non-finite loss/grads -> step must be a no-op
    bad = xv.copy()
    bad[0, 0] = np.inf
    exe.run(main, feed={"x": bad, "y": yv}, fetch_list=[loss], scope=scope)
    after = {n: np.asarray(scope.find_var(n)) for n in pnames}
    for n in pnames:
        np.testing.assert_array_equal(before[n], after[n], err_msg=f"param {n} changed on overflow")
    s = float(np.asarray(scope.find_var("loss_scaling_0"))[0])
    assert s == 4.0, s  # decr_every_n_nan_or_inf=1 => halved immediately
    # and a following clean step trains again
    exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
    moved = any(np.abs(np.asarray(scope.find_var(n)) - after[n]).max() > 0 for n in pnames)
    assert moved


def test_amp_loss_scale_floor():
    main, startup, loss, opt = _build("float32", incr_every=100, init_scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(3)
    xv = rng.rand(4, 6).astype("f4")
    bad = xv.copy()
    bad[0, 0] = np.nan
    yv = rng.rand(4, 1).astype("f4")
    for _ in range(5):
        exe.run(main, feed={"x": bad, "y": yv}, fetch_list=[loss], scope=scope)
    s = float(np.asarray(scope.find_var("loss_scaling_0"))[0])
    assert s == 1.0, s  # floored, never reaches 0


def test_amp_with_sparse_embedding():
    """AMP + is_sparse lookup_table: SelectedRows grads pass through the
    isfinite/unscale pipeline (SelectedRows-aware elementwise lowerings)."""
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", [4], dtype="int64")
        y = fluid.layers.data("y", [1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[50, 8], is_sparse=True)
        pooled = fluid.layers.reshape(emb, [-1, 32])
        pred = fluid.layers.fc(pooled, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt = decorate(fluid.optimizer.SGD(0.1), init_loss_scaling=8.0,
                       incr_every_n_steps=100, decr_every_n_nan_or_inf=1)
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    ids_v = rng.randint(0, 50, (16, 4)).astype("int64")  # fixed batch: memorize
    yv = rng.randn(16, 1).astype("f4")
    losses = []
    for _ in range(40):
        (lv,) = exe.run(main, feed={"ids": ids_v, "y": yv}, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, f"{losses[0]} -> {losses[-1]}"


def test_sparse_adam_lazy_mode_semantics():
    """lazy_mode=False (reference default): untouched rows' moments decay and
    the param still moves; lazy_mode=True touches only gradient rows."""
    def run(lazy):
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = 3
        with fluid.program_guard(main, startup):
            ids = fluid.layers.data("ids", [2], dtype="int64")
            emb = fluid.layers.embedding(ids, size=[10, 4], is_sparse=True)
            loss = fluid.layers.mean(emb)
            fluid.optimizer.Adam(learning_rate=0.1, lazy_mode=lazy).minimize(loss)
        wname = next(v.name for v in main.list_vars()
                     if v.persistable and v.name.startswith("embedding"))
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        w0 = np.array(scope.find_var(wname))
        # step 1 touches rows {0,1}; step 2 touches {2,3}
        for step_ids in ([[0, 1]], [[2, 3]]):
            exe.run(main, feed={"ids": np.array(step_ids, "int64")},
                    fetch_list=[loss], scope=scope)
        w = np.array(scope.find_var(wname))
        return w0, w

    w0l, wl = run(True)
    # lazy: row 9 never touched -> unchanged
    np.testing.assert_allclose(wl[9], w0l[9])
    w0d, wd = run(False)
    # dense-default: row 0's adam moment from step 1 keeps moving row 0 in
    # step 2 even though step 2's grad for row 0 is zero
    assert not np.allclose(wd[9], w0d[9]) or not np.allclose(wd[0], wl[0]), \
        "lazy and non-lazy should diverge"
