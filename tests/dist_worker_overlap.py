"""Worker process for the bench.py --overlap A/B (ISSUE 7): trains the same
seeded MLP under one grad-sync arm (GRAD_SYNC_MODE = gspmd | serial |
bucketed) and prints RESULT json — wall time over the timed steps plus the
final-params sha, so the parent can assert the bucketed arm beats the
serial baseline at bit-identical final params."""
import hashlib
import json
import os
import sys
import time

# must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=1").strip()

import numpy as np  # noqa: E402


def build_model(d_in=64, width=256, depth=3):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = main.random_seed = 90
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [d_in], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = x
        for _ in range(depth):
            h = fluid.layers.fc(h, width, act="relu")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    return main, startup, loss


def main():
    import paddle_tpu as fluid
    from paddle_tpu.parallel import distributed as dist

    mode = os.environ.get("GRAD_SYNC_MODE", "gspmd")
    steps = int(os.environ.get("RUN_STEPS", "16"))
    warm = int(os.environ.get("WARM_STEPS", "4"))
    bucket_mb = float(os.environ.get("BUCKET_MB", "0.25"))
    batch = int(os.environ.get("BATCH_SIZE", "64"))
    width = int(os.environ.get("MODEL_WIDTH", "256"))
    depth = int(os.environ.get("MODEL_DEPTH", "3"))

    dist.init_distributed()  # PADDLE_TRAINER_* env contract
    tid = dist.trainer_id()
    # telemetry plane: this worker skips fleet.init (no health layer in the
    # A/B), so arm the rank-stamped stream directly — no-op outside a
    # run_gang telemetry dir; gives bench.py --overlap its skew record
    from paddle_tpu import monitor as _monitor

    _monitor.init_worker_telemetry(rank=tid)
    nproc = dist.num_trainers()
    mesh = dist.global_mesh()
    n_dp = mesh.devices.size

    prog, startup, loss = build_model(width=width, depth=depth)
    compiled = fluid.CompiledProgram(prog).with_mesh(mesh)
    if mode != "gspmd":
        compiled = compiled.with_grad_overlap(bucket_mb=bucket_mb, mode=mode)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)

    rng = np.random.RandomState(1234)  # same global stream in every worker
    per = batch // nproc
    losses = []
    wall = 0.0
    for step in range(warm + steps):
        xg = rng.rand(batch, 64).astype("f4")
        yg = xg.sum(1, keepdims=True) * 0.1
        xl = xg[tid * per:(tid + 1) * per]
        yl = yg[tid * per:(tid + 1) * per]
        if step == warm:
            t0 = time.perf_counter()
        (lv,) = exe.run(compiled, feed={"x": xl, "y": yl},
                        fetch_list=[loss], scope=scope)
        lv = float(np.asarray(lv).reshape(-1)[0])
        if step >= warm:
            losses.append(lv)
    wall = time.perf_counter() - t0

    h = hashlib.sha256()
    for p in sorted(pp.name for pp in prog.all_parameters()):
        h.update(np.asarray(scope.find_var(p)).tobytes())
    print("RESULT " + json.dumps({
        "trainer": tid, "mode": mode, "n_dp": int(n_dp), "steps": steps,
        "wall_s": round(wall, 4), "steps_per_sec": round(steps / wall, 3),
        "first_loss": round(losses[0], 6), "last_loss": round(losses[-1], 6),
        "params_sha": h.hexdigest(),
    }), flush=True)


if __name__ == "__main__":
    main()
