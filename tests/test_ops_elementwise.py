"""Per-op golden corpus: elementwise binaries, activations, logical/compare.

Reference pattern: unittests/test_elementwise_*_op.py, test_activation_op.py
(each declares numpy inputs + numpy-computed expected outputs; OpTest builds
a one-op program and compares; check_grad vs finite differences)."""
import numpy as np
import pytest
from scipy import special

from op_test import OpTest

RNG = np.random.RandomState(42)


def _x(shape=(3, 4), lo=-2.0, hi=2.0, dtype="float32"):
    return (RNG.rand(*shape) * (hi - lo) + lo).astype(dtype)


# --- elementwise binaries with fluid axis-broadcast semantics -------------

ELEMENTWISE = {
    "elementwise_add": np.add,
    "elementwise_sub": np.subtract,
    "elementwise_mul": np.multiply,
    "elementwise_div": np.divide,
    "elementwise_max": np.maximum,
    "elementwise_min": np.minimum,
    "elementwise_pow": np.power,
    "elementwise_mod": np.mod,
    "elementwise_floordiv": np.floor_divide,
}


@pytest.mark.parametrize("op_name", sorted(ELEMENTWISE))
def test_elementwise_same_shape(op_name):
    fn = ELEMENTWISE[op_name]
    x = _x((3, 4), 1.0, 3.0)
    y = _x((3, 4), 1.0, 3.0)

    class T(OpTest):
        def setUp(self):
            self.op_type = op_name
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": fn(x, y)}
            self.attrs = {}

    T().check_output(rtol=1e-5)


@pytest.mark.parametrize("op_name", ["elementwise_add", "elementwise_mul"])
def test_elementwise_axis_broadcast(op_name):
    """fluid semantics: Y's shape matches X's dims starting at `axis`
    (reference elementwise_op_function.h)."""
    fn = ELEMENTWISE[op_name]
    x = _x((2, 3, 4, 5))
    y = _x((3, 4))
    expected = fn(x, y.reshape(1, 3, 4, 1))

    class T(OpTest):
        def setUp(self):
            self.op_type = op_name
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": expected}
            self.attrs = {"axis": 1}

    T().check_output(rtol=1e-5)


def test_elementwise_add_grad():
    x = _x((3, 4))
    y = _x((4,))

    class T(OpTest):
        def setUp(self):
            self.op_type = "elementwise_add"
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": x + y}
            self.attrs = {"axis": 1}

    T().check_grad(["X", "Y"], "Out")


def test_elementwise_div_grad():
    x = _x((3, 4), 1.0, 2.0)
    y = _x((3, 4), 1.0, 2.0)

    class T(OpTest):
        def setUp(self):
            self.op_type = "elementwise_div"
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": x / y}
            self.attrs = {}

    T().check_grad(["X", "Y"], "Out", max_relative_error=0.02)


# --- activations ----------------------------------------------------------

ACTIVATIONS = {
    "abs": (lambda x: np.abs(x), {}),
    "ceil": (np.ceil, {}),
    "cos": (np.cos, {}),
    "erf": (special.erf, {}),
    "exp": (np.exp, {}),
    "floor": (np.floor, {}),
    "log": (np.log, {"positive": True}),
    "reciprocal": (lambda x: 1.0 / x, {"positive": True}),
    "relu": (lambda x: np.maximum(x, 0), {}),
    "relu6": (lambda x: np.clip(x, 0, 6), {}),
    "round": (np.round, {}),
    "rsqrt": (lambda x: x ** -0.5, {"positive": True}),
    "sigmoid": (lambda x: 1 / (1 + np.exp(-x)), {}),
    "sign": (np.sign, {}),
    "sin": (np.sin, {}),
    "sqrt": (np.sqrt, {"positive": True}),
    "square": (np.square, {}),
    "softsign": (lambda x: x / (1 + np.abs(x)), {}),
    "tanh": (np.tanh, {}),
    "logsigmoid": (lambda x: np.log(1 / (1 + np.exp(-x))), {}),
    "softplus": (lambda x: np.log1p(np.exp(x)), {}),
    "tanh_shrink": (lambda x: x - np.tanh(x), {}),
    "gelu": (lambda x: 0.5 * x * (1 + special.erf(x / np.sqrt(2.0))), {}),
}


@pytest.mark.parametrize("op_name", sorted(ACTIVATIONS))
def test_activation(op_name):
    fn, opts = ACTIVATIONS[op_name]
    x = _x((3, 5), 0.2, 3.0) if opts.get("positive") else _x((3, 5))

    class T(OpTest):
        def setUp(self):
            self.op_type = op_name
            self.inputs = {"X": x}
            self.outputs = {"Out": fn(x)}
            self.attrs = {}

    T().check_output(rtol=2e-5, atol=1e-5)


@pytest.mark.parametrize("op_name", ["tanh", "sigmoid", "exp", "square"])
def test_activation_grad(op_name):
    fn, _ = ACTIVATIONS[op_name]
    x = _x((2, 3))

    class T(OpTest):
        def setUp(self):
            self.op_type = op_name
            self.inputs = {"X": x}
            self.outputs = {"Out": fn(x)}
            self.attrs = {}

    T().check_grad(["X"], "Out", max_relative_error=0.01)


def test_leaky_relu():
    x = _x((3, 4))

    class T(OpTest):
        def setUp(self):
            self.op_type = "leaky_relu"
            self.inputs = {"X": x}
            self.outputs = {"Out": np.where(x > 0, x, 0.1 * x)}
            self.attrs = {"alpha": 0.1}

    T().check_output()


def test_elu():
    x = _x((3, 4))

    class T(OpTest):
        def setUp(self):
            self.op_type = "elu"
            self.inputs = {"X": x}
            self.outputs = {"Out": np.where(x > 0, x, 0.5 * (np.exp(x) - 1))}
            self.attrs = {"alpha": 0.5}

    T().check_output()


def test_hard_sigmoid():
    x = _x((3, 4))

    class T(OpTest):
        def setUp(self):
            self.op_type = "hard_sigmoid"
            self.inputs = {"X": x}
            self.outputs = {"Out": np.clip(x * 0.2 + 0.5, 0.0, 1.0)}
            self.attrs = {"slope": 0.2, "offset": 0.5}

    T().check_output()


def test_swish():
    x = _x((3, 4))

    class T(OpTest):
        def setUp(self):
            self.op_type = "swish"
            self.inputs = {"X": x}
            self.outputs = {"Out": x / (1 + np.exp(-2.0 * x))}
            self.attrs = {"beta": 2.0}

    T().check_output()


def test_softshrink():
    x = _x((3, 4))
    lam = 0.5

    class T(OpTest):
        def setUp(self):
            self.op_type = "softshrink"
            self.inputs = {"X": x}
            self.outputs = {"Out": np.where(x > lam, x - lam, np.where(x < -lam, x + lam, 0.0))}
            self.attrs = {"lambda": lam}

    T().check_output()


def test_prelu_modes():
    x = _x((2, 3, 4))
    for mode, alpha in (("all", np.array([0.25], "f4")),
                        ("channel", (RNG.rand(3) * 0.5).astype("f4")),
                        ("element", (RNG.rand(3, 4) * 0.5).astype("f4"))):
        if mode == "all":
            a = alpha.reshape(())
        elif mode == "channel":
            a = alpha.reshape(1, 3, 1)
        else:
            a = alpha.reshape(1, 3, 4)
        expected = np.where(x > 0, x, a * x)

        class T(OpTest):
            def setUp(self):
                self.op_type = "prelu"
                self.inputs = {"X": x, "Alpha": alpha}
                self.outputs = {"Out": expected}
                self.attrs = {"mode": mode}

        T().check_output()


# --- logical / compare ----------------------------------------------------

def test_logical_ops():
    a = RNG.rand(3, 4) > 0.5
    b = RNG.rand(3, 4) > 0.5
    for op_name, fn in (("logical_and", np.logical_and), ("logical_or", np.logical_or)):
        class T(OpTest):
            def setUp(self):
                self.op_type = op_name
                self.inputs = {"X": a, "Y": b}
                self.outputs = {"Out": fn(a, b)}
                self.attrs = {}

        T().check_output()

    class TN(OpTest):
        def setUp(self):
            self.op_type = "logical_not"
            self.inputs = {"X": a}
            self.outputs = {"Out": np.logical_not(a)}
            self.attrs = {}

    TN().check_output()


COMPARES = {
    "equal": np.equal,
    "not_equal": np.not_equal,
    "less_than": np.less,
    "less_equal": np.less_equal,
    "greater_than": np.greater,
    "greater_equal": np.greater_equal,
}


@pytest.mark.parametrize("op_name", sorted(COMPARES))
def test_compare(op_name):
    x = RNG.randint(0, 3, (4, 5)).astype("float32")
    y = RNG.randint(0, 3, (4, 5)).astype("float32")

    class T(OpTest):
        def setUp(self):
            self.op_type = op_name
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": COMPARES[op_name](x, y)}
            self.attrs = {}

    T().check_output()
