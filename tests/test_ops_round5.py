"""Round-5 op tests: BN fused single-pass stats (bf16 path) numerics.

Reference numerics: batch_norm_op.cc training mode (mean/var over N,H,W).
The bf16 activation path now computes E[x]/E[x^2] in one fused pass with
f32 accumulators (docs/perf_r05.md); these tests pin its accuracy against
float64 numpy at bf16-appropriate tolerances, including a shifted-mean case
where naive cancellation would show up first.
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.program import Program, program_guard


def _run_bn_bf16(x_np, scale, bias):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", list(x_np.shape[1:]), dtype="float32")
        xb = layers.cast(x, "bfloat16")
        y = layers.batch_norm(xb, is_test=False,
                              param_attr=fluid.ParamAttr(
                                  initializer=fluid.initializer.NumpyArrayInitializer(scale)),
                              bias_attr=fluid.ParamAttr(
                                  initializer=fluid.initializer.NumpyArrayInitializer(bias)))
        yf = layers.cast(y, "float32")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (out,) = exe.run(main, feed={"x": x_np}, fetch_list=[yf], scope=scope)
    return np.asarray(out)


def _ref_bn(x_np, scale, bias, eps=1e-5):
    x64 = x_np.astype(np.float64)
    m = x64.mean(axis=(0, 2, 3), keepdims=True)
    v = x64.var(axis=(0, 2, 3), keepdims=True)
    return ((x64 - m) / np.sqrt(v + eps) * scale.reshape(1, -1, 1, 1)
            + bias.reshape(1, -1, 1, 1))


def test_bn_bf16_fused_pass_centered():
    rng = np.random.RandomState(0)
    x = rng.randn(8, 4, 6, 6).astype("float32")
    scale = rng.uniform(0.5, 1.5, 4).astype("float32")
    bias = rng.uniform(-0.5, 0.5, 4).astype("float32")
    got = _run_bn_bf16(x, scale, bias)
    want = _ref_bn(x, scale, bias)
    # bf16 activations: ~2^-8 relative representation error dominates
    assert np.allclose(got, want, atol=5e-2, rtol=5e-2), np.abs(got - want).max()


def test_bn_bf16_fused_pass_shifted_mean():
    # |mean|/std = 10: cancellation in E[x^2]-mean^2 must stay below the
    # bf16 representation error of the input itself
    rng = np.random.RandomState(1)
    x = (rng.randn(8, 4, 6, 6) * 1.0 + 10.0).astype("float32")
    scale = np.ones(4, "float32")
    bias = np.zeros(4, "float32")
    got = _run_bn_bf16(x, scale, bias)
    want = _ref_bn(x, scale, bias)
    # shifted input quantized to bf16 loses ~10*2^-8 absolute on (x-mean);
    # the normalized output tolerance reflects that input-level error
    assert np.allclose(got, want, atol=0.15, rtol=0.1), np.abs(got - want).max()


def test_bn_f32_stays_two_pass_exact():
    # f32 default path is unchanged: exact vs the two-pass numpy reference
    from paddle_tpu.ops import nn_ops
    assert nn_ops._BN_STATS_FUSED_PASS is False
    rng = np.random.RandomState(2)
    x = rng.randn(4, 3, 5, 5).astype("float32")
    main, startup = Program(), Program()
    with program_guard(main, startup):
        xv = layers.data("x", [3, 5, 5], dtype="float32")
        y = layers.batch_norm(xv, is_test=False)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (out,) = exe.run(main, feed={"x": x}, fetch_list=[y], scope=scope)
    m = x.mean(axis=(0, 2, 3), keepdims=True)
    v = x.var(axis=(0, 2, 3), keepdims=True)
    want = (x - m) / np.sqrt(v + 1e-5)
    assert np.allclose(np.asarray(out), want, atol=1e-4, rtol=1e-4)
