"""GPipe pipeline: S-stage pipelined result must equal sequential
application of the stages (reference: PipelineTrainer semantics)."""
import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.pipeline import gpipe


def _stage(params, x):
    w, b = params["w"], params["b"]
    return jnp.tanh(x @ w + b)


def test_gpipe_matches_sequential():
    rng = np.random.RandomState(0)
    S, M, mb, d = 4, 8, 4, 16
    ws = rng.randn(S, d, d).astype("f4") * 0.3
    bs = rng.randn(S, d).astype("f4") * 0.1
    xs = rng.randn(M, mb, d).astype("f4")

    # sequential reference
    ref = xs.copy()
    out = []
    for m in range(M):
        h = xs[m]
        for s in range(S):
            h = np.tanh(h @ ws[s] + bs[s])
        out.append(h)
    ref = np.stack(out)

    mesh = make_mesh((S,), ("pp",))
    got = gpipe(_stage, {"w": jnp.asarray(ws), "b": jnp.asarray(bs)}, jnp.asarray(xs), mesh)
    np.testing.assert_allclose(np.asarray(got), ref, atol=1e-5, rtol=1e-5)


def test_gpipe_differentiable():
    """Backward through the pipeline (vjp of ppermute) gives usable grads."""
    rng = np.random.RandomState(1)
    S, M, mb, d = 2, 4, 2, 8
    params = {
        "w": jnp.asarray(rng.randn(S, d, d).astype("f4") * 0.3),
        "b": jnp.asarray(rng.randn(S, d).astype("f4") * 0.1),
    }
    xs = jnp.asarray(rng.randn(M, mb, d).astype("f4"))
    mesh = make_mesh((S,), ("pp",), jax.devices()[:S])

    def loss_fn(p):
        ys = gpipe(_stage, p, xs, mesh)
        return jnp.sum(ys ** 2)

    g = jax.grad(loss_fn)(params)
    assert np.isfinite(np.asarray(g["w"])).all()
    assert np.abs(np.asarray(g["w"])).sum() > 0
    # numeric check on one coordinate
    eps = 1e-3
    p2 = {"w": params["w"].at[0, 0, 0].add(eps), "b": params["b"]}
    p3 = {"w": params["w"].at[0, 0, 0].add(-eps), "b": params["b"]}
    num = (loss_fn(p2) - loss_fn(p3)) / (2 * eps)
    np.testing.assert_allclose(float(g["w"][0, 0, 0]), float(num), rtol=2e-2)
