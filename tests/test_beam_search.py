"""In-program beam search: the compiled While decode (models/nmt.py
build_beam_decode) vs the host-loop reference (nmt.beam_search_decode),
greedy==beam-1 equivalence, and beam_search op unit goldens.

Reference capability: operators/math/beam_search.cc:24 + layers/nn.py
beam_search / beam_search_decode (LoD state redesigned as static [b,k]
tensors in a lax.while_loop)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.program import Program, program_guard
from paddle_tpu.models import nmt

from op_test import OpTest


def test_beam_search_op_step_golden():
    """One selection step against a numpy transcription."""
    rng = np.random.RandomState(31)
    b, k, L, V = 2, 3, 6, 10
    t = 2
    logits = rng.randn(b * k, L, V).astype("float32")
    seqs = rng.randint(3, V, (b, k, L)).astype("int64")
    scores = rng.randn(b, k).astype("float32")
    finished = np.zeros((b, k), bool)
    finished[1, 2] = True
    eos = 2

    step = logits[:, t - 1, :].reshape(b, k, V)
    m = step.max(-1, keepdims=True)
    logp = step - m - np.log(np.exp(step - m).sum(-1, keepdims=True))
    logp_f = np.full_like(logp, -1e9)
    logp_f[:, :, eos] = 0.0
    logp = np.where(finished[:, :, None], logp_f, logp)
    cand = (scores[:, :, None] + logp).reshape(b, k * V)
    order = np.argsort(-cand, axis=1)[:, :k]
    exp_scores = np.take_along_axis(cand, order, axis=1).astype("float32")
    parent = order // V
    token = order % V
    exp_seqs = np.empty_like(seqs)
    exp_fin = np.empty_like(finished)
    for i in range(b):
        exp_seqs[i] = seqs[i, parent[i]]
        exp_seqs[i, :, t] = token[i]
        exp_fin[i] = finished[i, parent[i]] | (token[i] == eos)

    class T(OpTest):
        def setUp(self):
            self.op_type = "beam_search"
            self.inputs = {"Logits": logits, "Seqs": seqs, "Scores": scores,
                           "Finished": finished,
                           "StepIdx": np.asarray([t], "int32")}
            self.attrs = {"beam_size": k, "end_id": eos}
            self.outputs = {"SelectedSeqs": exp_seqs,
                            "SelectedScores": exp_scores,
                            "FinishedOut": exp_fin}

    T().check_output(atol=1e-5)


def _trained_scope_and_programs(beam_size, max_len=8, b=3, src_len=7):
    """Train the tiny NMT a few steps, then build the compiled decode over
    the SAME scope (param names match by construction)."""
    kw = dict(src_vocab=40, tgt_vocab=40, d_model=32, n_layers=1, n_heads=2,
              d_ff=64)
    main, startup, feeds, fetches = nmt.build_transformer_nmt(
        dropout=0.0, with_optimizer=True, **kw)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    startup.random_seed = 5
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(1)
    for _ in range(3):
        batch = nmt.make_fake_nmt_batch([5, 6, 4], [5, 4, 6], 40, 40)
        exe.run(main, feed=batch, fetch_list=[fetches["loss"]], scope=scope)

    dec_main, dec_startup, dfeeds, dfetches = nmt.build_beam_decode(
        batch_size=b, src_len=src_len, beam_size=beam_size, max_len=max_len,
        bos=1, eos=2, **kw)
    # decode programs share the trained scope; startup would re-init params,
    # so DON'T run dec_startup — all decode vars are assign-initialized
    infer_main, _, ifeeds, ifetches = nmt.build_nmt_infer(**kw)
    return exe, scope, (dec_main, dfetches), (infer_main, ifetches)


def _src_batch(b=3, src_len=7, seed=3):
    rng = np.random.RandomState(seed)
    lens = rng.randint(3, src_len + 1, b)
    rows = [rng.randint(3, 40, (l, 1)).astype("int64") for l in lens]
    padded = np.zeros((b, src_len), "int64")
    for i, r in enumerate(rows):
        padded[i, :len(r)] = r[:, 0]
    return rows, padded, lens.astype("int32")


def test_compiled_beam_decode_matches_host_loop():
    for beam in (1, 3):
        exe, scope, (dec_main, dfetches), (infer_main, ifetches) = \
            _trained_scope_and_programs(beam)
        rows, padded, lens = _src_batch()
        (ids, sc) = exe.run(
            dec_main, feed={"src_word": padded, "src_len_vec": lens},
            fetch_list=[dfetches["out_ids"], dfetches["out_scores"]],
            scope=scope)
        host_ids, host_scores = nmt.beam_search_decode(
            exe, infer_main, ifetches["logits"], scope, rows,
            bos=1, eos=2, beam_size=beam, max_len=8)
        np.testing.assert_array_equal(np.asarray(ids), host_ids)
        np.testing.assert_allclose(np.asarray(sc), host_scores, rtol=1e-3,
                                   atol=1e-4)


def test_greedy_equals_beam_one():
    """beam_size=1 is exact greedy: each step's token equals the argmax of
    that step's logits given the emitted prefix (checked via the infer
    program on the same weights)."""
    exe, scope, (dec_main, dfetches), (infer_main, ifetches) = \
        _trained_scope_and_programs(1)
    rows, padded, lens = _src_batch(seed=4)
    (ids,) = exe.run(dec_main, feed={"src_word": padded, "src_len_vec": lens},
                     fetch_list=[dfetches["out_ids"]], scope=scope)
    ids = np.asarray(ids)
    assert ids.shape == (3, 8)
    assert (ids[:, 0] == 1).all()  # starts with BOS
    from paddle_tpu.lod import LoDTensor

    for t in range(1, 4):  # spot-check the first steps against raw argmax
        trg = LoDTensor([row[:t].reshape(-1, 1) for row in ids])
        feed = {"src_word": LoDTensor(rows), "trg_word": trg, "lbl_word": trg}
        (logits,) = exe.run(infer_main, feed=feed,
                            fetch_list=[ifetches["logits"]], scope=scope)
        step = np.asarray(logits)[:, t - 1, :]
        greedy = step.argmax(-1)
        done = (ids[:, :t] == 2).any(axis=1)  # rows already at EOS keep EOS
        expect = np.where(done, 2, greedy)
        np.testing.assert_array_equal(ids[:, t], expect)
