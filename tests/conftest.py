"""Test config: run on an 8-device virtual CPU mesh (SURVEY.md §4.8 — the
always-on 'fake TPU'); real-TPU runs happen via bench.py."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override axon: tests run on virtual mesh
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Persistent XLA compile cache: repeated test runs skip recompiles.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # in case jax was imported pre-conftest
jax.config.update("jax_compilation_cache_dir", "/tmp/paddle_tpu_xla_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


@pytest.fixture(autouse=True)
def _fresh_programs():
    """Each test builds into fresh default programs and a fresh scope."""
    import paddle_tpu as fluid
    from paddle_tpu.core import program as prog_mod
    from paddle_tpu.core import scope as scope_mod
    from paddle_tpu.core import unique_name

    old_main, old_startup = prog_mod._main_program, prog_mod._startup_program
    old_scope = scope_mod._global_scope
    prog_mod._main_program = fluid.Program()
    prog_mod._startup_program = fluid.Program()
    scope_mod._global_scope = scope_mod.Scope()
    with unique_name.guard():
        yield
    prog_mod._main_program, prog_mod._startup_program = old_main, old_startup
    scope_mod._global_scope = old_scope
