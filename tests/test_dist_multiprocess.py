"""Multi-process distributed training on localhost (reference:
test_dist_base.py:366 check_with_place — fork trainer subprocesses, compare
per-step losses against the single-process run)."""
import subprocess
import sys

import numpy as np

from dist_harness import WORKER, collect, parse_losses, worker_env, worker_gang


def test_two_process_loss_parity_with_single_process():
    """2 procs x 2 virtual devices == 1 proc x 4 virtual devices, same data
    stream => identical per-step losses (sync-SGD parity, the
    test_dist_base contract)."""
    with worker_gang(2, devices_per_proc=2) as gang:
        outs = collect(gang)

    # both workers must observe the same (global) losses and 4 global devices
    assert outs[0]["n_dev"] == 4 and outs[1]["n_dev"] == 4
    np.testing.assert_allclose(outs[0]["losses"], outs[1]["losses"], rtol=1e-6)

    # single-process reference on the same 4-device topology
    env = worker_env({"RUN_LOCAL": "1"}, devices_per_proc=4)
    local = subprocess.Popen([sys.executable, WORKER], stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, env=env, text=True)
    try:
        out, err = local.communicate(timeout=600)
    except subprocess.TimeoutExpired:
        local.kill()
        raise
    assert local.returncode == 0, f"local run failed:\n{err[-4000:]}"
    ref = parse_losses(out, err, "local")
    assert ref["n_dev"] == 4
    np.testing.assert_allclose(outs[0]["losses"], ref["losses"], rtol=2e-5, atol=1e-6)
