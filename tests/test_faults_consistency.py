"""Self-consistency of the fault-injection surface (ISSUE 20).

Three descriptions of the fault grammar exist and must agree forever:
the module docstring's human-readable table, the `_KINDS` tuples the
parser enforces, and the `KIND_INFO` metadata the chaos-campaign
generator draws schedules from.  Drift between them is how a campaign
quietly stops covering a kind — these tests pin them together, plus the
compound-validation and ledger-hygiene helpers KIND_INFO ships with."""
import os

import pytest

from paddle_tpu import faults
from paddle_tpu.faults import (KIND_INFO, parse_fault_spec,
                               sweep_stale_ledgers, validate_schedule)


def test_kind_info_covers_exactly_the_parser_kinds():
    assert set(KIND_INFO) == set(faults._KINDS), \
        "KIND_INFO and _KINDS drifted — the campaign generator and the " \
        "parser disagree about what faults exist"


def test_groupings_are_subsets_of_kinds():
    for name in ("_RANKED_KINDS", "_STORAGE_KINDS", "_FILE_KINDS",
                 "_PSERVER_KINDS", "_LEDGER_KINDS"):
        group = getattr(faults, name)
        assert set(group) <= set(faults._KINDS), \
            f"{name} names kinds the parser does not know"


def test_ledgered_flag_matches_ledger_kinds():
    for kind, info in KIND_INFO.items():
        assert info["ledgered"] == (kind in faults._LEDGER_KINDS), \
            f"{kind}: KIND_INFO.ledgered disagrees with _LEDGER_KINDS"


def test_every_grammar_line_appears_in_the_docstring():
    doc = faults.__doc__
    for kind, info in KIND_INFO.items():
        assert info["grammar"] in doc, \
            f"{kind}: grammar {info['grammar']!r} is not in the module " \
            f"docstring table — the human-readable spec drifted"


def test_every_example_parses_and_round_trips():
    for kind, info in KIND_INFO.items():
        parsed = parse_fault_spec(info["example"])
        assert len(parsed) == 1 and parsed[0].kind == kind, \
            f"{kind}: example {info['example']!r} does not parse to " \
            f"itself"
        # grammar's kind prefix must match the key it documents
        assert info["grammar"].split("@", 1)[0] == kind


def test_every_needs_token_is_a_known_capability():
    known = {"loader", "feed", "dispatch", "scope", "commit", "files",
             "io", "gang", "pserver"}
    for kind, info in KIND_INFO.items():
        extra = set(info["needs"]) - known
        assert not extra, \
            f"{kind}: needs {sorted(extra)} name no documented capability"


def test_every_scope_token_is_documented():
    for kind, info in KIND_INFO.items():
        assert info["scope"] in ("batch", "step", "chunk", "commit",
                                 "op"), f"{kind}: unknown scope"


def test_docstring_examples_parse():
    """The `e.g.` spec lines in the docstring must stay valid specs."""
    for line in faults.__doc__.splitlines():
        line = line.strip()
        if 'FLAGS_fault_spec="' not in line:
            continue
        spec = line.split('"')[1]
        assert parse_fault_spec(spec), f"docstring example {spec!r} " \
                                       f"no longer parses"


def test_validate_schedule_accepts_a_compound():
    fs = validate_schedule("nan@2;device@5:UNAVAILABLE;enospc@7",
                           capabilities=("feed", "dispatch", "io"))
    assert [f.kind for f in fs] == ["nan", "device", "enospc"]


def test_validate_schedule_rejects_exact_duplicates():
    with pytest.raises(ValueError, match="duplicate"):
        validate_schedule("nan@2;nan@2")
    # same kind at a DIFFERENT index is a legitimate compound
    assert len(validate_schedule("nan@2;nan@4")) == 2


def test_validate_schedule_rejects_capability_mismatch():
    with pytest.raises(ValueError, match="needs"):
        validate_schedule("kill_pserver@3", capabilities=("dispatch",))
    # without a capability set, needs are not checked (parse-only mode)
    assert validate_schedule("kill_pserver@3")


def test_validate_schedule_rejects_enospc_shadowed_by_ro_fs():
    with pytest.raises(ValueError, match="unreachable|ro_fs"):
        validate_schedule("ro_fs@3;enospc@5")
    # an enospc window BEFORE the mount goes read-only is reachable
    assert validate_schedule("enospc@2;ro_fs@5")
    # different explicit ranks never shadow each other
    assert validate_schedule("ro_fs@3:0;enospc@5:1")


def test_sweep_reclaims_dead_markers_and_keeps_live_ones(tmp_path):
    d = str(tmp_path)
    # a marker from this (alive) process must survive the sweep
    with open(os.path.join(d, "fired-kill_worker@3-1"), "w") as fh:
        fh.write(str(os.getpid()))
    # a marker from a dead PID must be reclaimed (PID 1 is init — alive —
    # so synthesize a guaranteed-dead one by spawning and reaping)
    import subprocess

    p = subprocess.Popen(["true"])
    p.wait()
    with open(os.path.join(d, "fired-enospc@4-"), "w") as fh:
        fh.write(str(p.pid))
    # unreadable marker: treated as dead
    with open(os.path.join(d, "fired-eio@0-"), "w") as fh:
        fh.write("not-a-pid")
    # non-marker files are never touched
    with open(os.path.join(d, "RESULT.json"), "w") as fh:
        fh.write("{}")
    out = sweep_stale_ledgers(state_dir=d, scan_tmp=False)
    assert out["markers"] == 2
    left = sorted(os.listdir(d))
    assert "fired-kill_worker@3-1" in left, \
        "sweep reclaimed a LIVE gang's marker — it would re-fire a " \
        "spent kill on the next incarnation"
    assert "RESULT.json" in left
    assert not any(n.startswith("fired-enospc") for n in left)
    assert not any(n.startswith("fired-eio") for n in left)


def test_sweep_without_state_dir_is_safe(monkeypatch):
    monkeypatch.delenv("PADDLE_FAULT_STATE_DIR", raising=False)
    out = sweep_stale_ledgers(state_dir=None, scan_tmp=False)
    assert out == {"markers": 0, "dirs": 0}
