"""Slim depth (VERDICT r3 #9): structured pruning prune-retrain,
distillation (L2 / FSP / soft-label over the fsp op), channel-wise QAT.
Reference: contrib/slim/prune/pruner.py, distillation/distiller.py,
fake_quantize_op.cc fake_channel_wise_quantize_abs_max."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.contrib import slim


def _mnist_scale_net():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [16], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        h = fluid.layers.fc(x, 32, act="relu",
                            param_attr=fluid.ParamAttr(name="fc1_w"))
        logits = fluid.layers.fc(h, 4, param_attr=fluid.ParamAttr(name="fc2_w"))
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    return main, startup, loss


def _data(rng, n=64):
    y = rng.randint(0, 4, (n, 1)).astype("int64")
    x = (rng.rand(n, 16) * 0.2).astype("f4")
    x[np.arange(n), y[:, 0] * 4] += 2.0  # class k lights up feature 4k
    return x, y


def test_structure_pruner_group_selection():
    pruner = slim.StructurePruner(pruning_axis={"*": 1}, criterions={"*": "l1_norm"})
    w = np.array([[1.0, 0.1, 5.0, 0.2]] * 3, "f4")  # col l1: 3, .3, 15, .6
    idx = pruner.cal_pruned_idx("w", w, 0.5, axis=1)
    assert sorted(idx.tolist()) == [1, 3]
    pruned = pruner.prune_tensor(w, idx, 1, lazy=True)
    assert (pruned[:, [1, 3]] == 0).all() and (pruned[:, [0, 2]] != 0).all()
    hard = pruner.prune_tensor(w, idx, 1, lazy=False)
    assert hard.shape == (3, 2)


def test_prune_retrain_keeps_structure_and_recovers():
    rng = np.random.RandomState(0)
    main, startup, loss = _mnist_scale_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    x, y = _data(rng)
    for _ in range(40):
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss], scope=scope)
    (base,) = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss], scope=scope)
    base = float(np.asarray(base).reshape(-1)[0])

    masks = slim.prune_parameters(main, scope, ["fc1_w"], [0.5])
    assert abs(slim.sparsity(scope, masks) - 0.5) < 0.05
    (pruned_loss,) = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss],
                             scope=scope)
    # retrain with masks re-applied each step
    for _ in range(60):
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss], scope=scope)
        slim.apply_masks(scope, masks)
    eval_prog = main.clone(for_test=True)
    (rec,) = exe.run(eval_prog, feed={"x": x, "y": y}, fetch_list=[loss],
                     scope=scope)
    rec = float(np.asarray(rec).reshape(-1)[0])
    w = np.asarray(scope.find_var("fc1_w"))
    assert (w[masks["fc1_w"] == 0] == 0).all()  # structure preserved
    assert rec < float(np.asarray(pruned_loss).reshape(-1)[0])
    assert rec < base * 3  # recovers to the ballpark of the dense model


def test_distillation_student_learns_teacher():
    """student trained ONLY on distillation losses (L2 + FSP + soft label)
    matches the frozen teacher better than at init."""
    rng = np.random.RandomState(1)
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 4
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [1, 8, 8], dtype="float32")
        # frozen teacher
        t1 = fluid.layers.conv2d(x, 4, 3, padding=1, act="relu",
                                 param_attr=fluid.ParamAttr(name="t1w"))
        t2 = fluid.layers.conv2d(t1, 4, 3, padding=1,
                                 param_attr=fluid.ParamAttr(name="t2w"))
        t_logits = fluid.layers.fc(t2, 4, param_attr=fluid.ParamAttr(name="t3w"))
        # student
        s1 = fluid.layers.conv2d(x, 4, 3, padding=1, act="relu",
                                 param_attr=fluid.ParamAttr(name="s1w"))
        s2 = fluid.layers.conv2d(s1, 4, 3, padding=1,
                                 param_attr=fluid.ParamAttr(name="s2w"))
        s_logits = fluid.layers.fc(s2, 4, param_attr=fluid.ParamAttr(name="s3w"))

        l2 = slim.L2Distiller(s2, t2).distiller_loss()
        fsp = slim.FSPDistiller([(s1, s2)], [(t1, t2)]).distiller_loss()
        soft = slim.SoftLabelDistiller(
            s_logits, t_logits, student_temperature=1.0,
            teacher_temperature=2.0).distiller_loss()
        total = l2 + fsp + soft
        student_params = [main.global_block().var(n)
                          for n in ("s1w", "s2w", "s3w")]
        fluid.optimizer.Adam(0.01).minimize(total, parameter_list=student_params)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    t_before = np.asarray(scope.find_var("t1w")).copy()
    xs = rng.rand(16, 1, 8, 8).astype("f4")
    totals, l2s = [], []
    for _ in range(50):
        lv, l2v = exe.run(main, feed={"x": xs}, fetch_list=[total, l2],
                          scope=scope)
        totals.append(float(np.asarray(lv).reshape(-1)[0]))
        l2s.append(float(np.asarray(l2v).reshape(-1)[0]))
    # the soft-label CE floors at the teacher's entropy; the feature-match
    # terms must collapse and the total must strictly improve
    assert totals[-1] < totals[0], (totals[0], totals[-1])
    assert l2s[-1] < l2s[0] * 0.3, (l2s[0], l2s[-1])
    # teacher stayed frozen
    np.testing.assert_array_equal(t_before, np.asarray(scope.find_var("t1w")))


def test_channel_wise_qat():
    rng = np.random.RandomState(2)
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [1, 8, 8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        c = fluid.layers.conv2d(x, 8, 3, padding=1, act="relu",
                                param_attr=fluid.ParamAttr(name="qw"))
        logits = fluid.layers.fc(c, 4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    n = slim.quant_aware(main, weight_quantize_type="channel_wise_abs_max")
    assert n >= 2
    ops = [o.type for o in main.global_block().ops]
    assert "fake_channel_wise_quantize_abs_max" in ops
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xs = rng.rand(8, 1, 8, 8).astype("f4")
    ys = rng.randint(0, 4, (8, 1)).astype("int64")
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss],
                        scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    w = np.asarray(scope.find_var("qw"))
    assert w.shape[0] == 8
