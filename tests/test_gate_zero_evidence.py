"""Zero-evidence audit of EVERY `perf_report --check` gate (ISSUE 20).

Each gate documents a contract for a metrics file that carries no
evidence for it:

  * fail-class gates ("zero evidence must not gate green") must return
    rc 1 on an empty file AND on a file whose only content is evidence
    for OTHER subsystems;
  * count-class gates (dist restart/resize/corrupt/replay/skew counts,
    heartbeat fractions) read 0 from silence by design — a gang that
    never restarted writes no gang_restart event — and must return rc 0;
  * the base --check (recompile gate) needs step records, so the two
    step-coupled gates (--max-retry-frac, --max-host-blocked-frac) fail
    on an empty file via the "no step records" diagnosis.

This file pins every flag to its documented verdict across three
shapes of starvation: empty, counters-only (one snapshot line, evidence
present and healthy), events-only (record lines, evidence present and
healthy).  A gate whose evidence can only travel one modality keeps its
zero-evidence verdict on the other — that asymmetry is part of the
contract (e.g. lock telemetry is counters-only; quant parity is
events-only)."""
import json

import pytest

from tools.perf_report import check

# one entry per --check gate flag:
#   (flag kwargs for check(),
#    counters-only snapshot ({"counters":..., "gauges":...}) carrying
#      HEALTHY evidence, or None when counters cannot carry it,
#    events-only records carrying HEALTHY evidence, or None,
#    rc expected on an evidence-free file)
GATES = [
    ("max_retry_frac", dict(max_retry_frac=0.5),
     None,
     [{"kind": "step", "recompiles_total": 0}] * 4,
     1),   # step-coupled: empty file fails the base "no step records"
    ("max_host_blocked_frac", dict(max_host_blocked_frac=0.9),
     None,
     [{"kind": "step", "recompiles_total": 0}] * 4
     + [{"kind": "pipeline_step", "t_host_blocked_s": 0.01,
         "t_step_wall_s": 1.0}] * 4,
     1),
    ("max_heartbeat_miss_frac", dict(max_heartbeat_miss_frac=0.1),
     {"counters": {"dist.heartbeat.sent": 100,
                   "dist.heartbeat.missed": 0}},
     [{"kind": "dist_event", "action": "heartbeat_resumed"}],
     0),   # count-class: silence reads as 0
    ("max_gang_restarts", dict(max_gang_restarts=1),
     {"counters": {"dist.gang_restarts": 1}},
     [{"kind": "dist_event", "action": "gang_restart"}],
     0),
    ("max_gang_resizes", dict(max_gang_resizes=1),
     {"counters": {"dist.gang_resizes": 1}},
     [{"kind": "dist_event", "action": "gang_resize",
       "direction": "shrink"}],
     0),
    ("max_data_corrupt_frac", dict(max_data_corrupt_frac=0.1),
     {"counters": {"data.chunks_scanned": 100, "data.corrupt_chunks": 0}},
     None,
     0),
    ("max_replay_batches", dict(max_replay_batches=0),
     {"counters": {"resilience.replayed_batches": 0}},
     [{"kind": "resilience_event", "action": "replay_fast_forward",
       "batches": 0}],
     0),
    ("max_step_skew_frac", dict(max_step_skew_frac=1.0),
     {"gauges": {"dist.step_skew_frac": 0.0}},
     [{"kind": "dist_event", "action": "straggler", "skew_frac": 0.5}],
     0),
    ("max_shed_frac", dict(max_shed_frac=0.5),
     {"counters": {"serving.requests": 100, "serving.shed": 0}},
     [{"kind": "serving_batch", "requests": 8, "rows": 8, "bucket": 8}],
     1),   # fail-class from here down
    ("max_p99_ms", dict(max_p99_ms=1000.0),
     {"counters": {"serving.requests": 100},
      "gauges": {"serving.p99_ms": 5.0}},
     [{"kind": "serving_batch", "requests": 8, "lat_ms_max": 5.0}],
     1),
    ("max_queue_wait_frac", dict(max_queue_wait_frac=0.5),
     {"gauges": {"serving.queue_wait_frac": 0.1}},
     [{"kind": "serving_trace", "outcome": "completed", "total_ms": 10.0,
       "spans": [{"name": "queue", "dur_ms": 1.0}]}],
     1),
    ("max_pad_frac", dict(max_pad_frac=0.9),
     {"counters": {"serving.pad_rows": 0, "serving.rows": 100}},
     [{"kind": "serving_batch", "requests": 4, "rows": 4, "bucket": 4}],
     1),
    ("require_quant_parity", dict(require_quant_parity=True),
     None,   # parity travels as serving_event records only
     [{"kind": "serving_event", "action": "quant_parity",
       "max_abs_diff": 0.0, "atol": 0.1}],
     1),
    ("min_healthy_replicas", dict(min_healthy_replicas=1),
     {"gauges": {"serving.fleet.healthy_replicas": 2}},
     None,   # fleet_events alone carry no healthy-count gauge -> still 1
     1),
    ("check_roll_convergence", dict(check_roll_convergence=True),
     {"counters": {"serving.fleet.events[roll_halted]": 0,
                   "serving.fleet.events[roll_converged]": 0}},
     [{"kind": "fleet_event", "action": "roll_started", "ctl": "r1"},
      {"kind": "fleet_event", "action": "roll_converged", "ctl": "r1"}],
     1),
    ("max_lock_wait_frac", dict(max_lock_wait_frac=0.5),
     {"counters": {"lock.monitor.wait_us": 1,
                   "lock.monitor.hold_us": 99}},
     None,   # lock telemetry is counters-only by construction
     1),
    ("max_integrity_mismatches", dict(max_integrity_mismatches=0),
     {"counters": {"integrity.digests": 3, "integrity.divergences": 0,
                   "integrity.file_mismatches": 0}},
     [{"kind": "integrity_event", "action": "ckpt_rejected"}],
     1),
    ("max_ckpt_lag_steps", dict(max_ckpt_lag_steps=5.0),
     {"counters": {"checkpoint.saves": 3}},
     [{"kind": "resilience_event", "action": "storage_recovered",
       "lag_steps": 0}],
     1),
    ("max_publish_staleness_steps", dict(max_publish_staleness_steps=5.0),
     {"counters": {"serving.publishes": 3}},
     [{"kind": "resilience_event", "action": "publish", "at_step": 3}],
     1),
    ("max_host_lag_steps", dict(max_host_lag_steps=5.0),
     {"counters": {"ps.retries": 0}},
     [{"kind": "sparse_event", "action": "host_tier_recovered"}],
     1),
    ("max_chaos_violations", dict(max_chaos_violations=0),
     {"counters": {"chaos.schedules_run": 3,
                   "chaos.invariants_checked": 12}},
     [{"kind": "chaos_event", "event": "schedule", "scenario": "train",
       "spec": "nan@1", "verdict": "pass"}],
     1),
]


def _write(tmp_path, name, records):
    p = tmp_path / name
    p.write_text("".join(json.dumps(r) + "\n" for r in records))
    return str(p)


@pytest.mark.parametrize(
    "name,kwargs,counters_rec,event_recs,empty_rc",
    GATES, ids=[g[0] for g in GATES])
def test_gate_contract(tmp_path, capsys, name, kwargs, counters_rec,
                       event_recs, empty_rc):
    # empty file: the documented zero-evidence verdict
    empty = _write(tmp_path, "empty.jsonl", [])
    assert check(empty, **kwargs) == empty_rc, \
        f"{name}: empty-file verdict drifted from documented contract"

    # counters-only file with HEALTHY evidence for this gate -> rc 0;
    # when the gate's evidence cannot travel as counters, a counters-only
    # file (other subsystems' counters) keeps the zero-evidence verdict
    if counters_rec is not None:
        p = _write(tmp_path, "counters.jsonl", [counters_rec])
        assert check(p, **kwargs) == 0, \
            f"{name}: healthy counters-only evidence must gate green"
    else:
        p = _write(tmp_path, "counters.jsonl",
                   [{"counters": {"unrelated.subsystem": 7}}])
        assert check(p, **kwargs) == empty_rc, \
            f"{name}: unrelated counters are still zero evidence"

    # events-only file with HEALTHY evidence -> rc 0; a gate whose
    # evidence never travels as events keeps the zero-evidence verdict
    if event_recs is not None:
        p = _write(tmp_path, "events.jsonl", event_recs)
        assert check(p, **kwargs) == 0, \
            f"{name}: healthy events-only evidence must gate green"
    else:
        p = _write(tmp_path, "events.jsonl",
                   [{"kind": "unrelated_event", "action": "noop"}])
        assert check(p, **kwargs) == empty_rc, \
            f"{name}: unrelated events are still zero evidence"
    capsys.readouterr()  # keep the per-gate prints out of pytest noise


def test_fail_class_gates_name_the_starvation(tmp_path, capsys):
    """Every fail-class gate's zero-evidence diagnosis must SAY it is a
    zero-evidence failure, so CI logs distinguish 'never measured' from
    'measured and bad'."""
    empty = _write(tmp_path, "empty.jsonl", [])
    for name, kwargs, _c, _e, empty_rc in GATES:
        if empty_rc != 1 or name in ("max_retry_frac",
                                     "max_host_blocked_frac"):
            continue  # step-coupled gates diagnose "no step records"
        rc = check(empty, **kwargs)
        out = capsys.readouterr().out
        assert rc == 1
        assert "evidence" in out, \
            f"{name}: zero-evidence failure does not name the starvation"


def test_chaos_gate_fires_on_violations(tmp_path, capsys):
    """The --max-chaos-violations gate must fire on BOTH evidence
    modalities: failed-schedule chaos_event records and the
    chaos.invariant_violations counter."""
    by_events = _write(tmp_path, "viol_events.jsonl", [
        {"kind": "chaos_event", "event": "schedule", "scenario": "train",
         "spec": "nan@1;device@2:UNAVAILABLE", "verdict": "fail",
         "invariant": "bit_identical_recovery"}])
    assert check(by_events, max_chaos_violations=0) == 1
    assert check(by_events, max_chaos_violations=1) == 0
    by_counters = _write(tmp_path, "viol_counters.jsonl", [
        {"counters": {"chaos.schedules_run": 4,
                      "chaos.invariant_violations": 2}}])
    assert check(by_counters, max_chaos_violations=1) == 1
    assert check(by_counters, max_chaos_violations=2) == 0
    capsys.readouterr()
