"""CI gate: every reference operator is registered or recorded
(tools/op_audit.py — the op-level analog of tests/test_api_audit.py).

Also pins goldens for the round-5 registry fill-ins (reference:
minus_op.cc, l1_norm_op.cc, squared_l2_norm_op.cc,
squared_l2_distance_op.cc, fill_op.cc, proximal_gd_op.h,
proximal_adagrad_op.h)."""
import numpy as np

from op_test import OpTest

import tools.op_audit as op_audit


def test_op_registry_audit_gate():
    res = op_audit.audit()
    assert res["ok"], {
        "uncovered": res["uncovered"], "stale": res["stale_deviations"]}
    # sanity floor so a broken extraction can't silently pass
    assert res["ref_total"] >= 300
    assert res["registered"] >= 240


def _golden(op_type, inputs, outputs, attrs=None, **kw):
    class T(OpTest):
        def setUp(self):
            self.op_type = op_type
            self.inputs = inputs
            self.outputs = outputs
            self.attrs = attrs or {}

    T().check_output(**kw)


RNG = np.random.RandomState(5)


def test_minus_golden():
    x = RNG.rand(3, 4).astype("f4")
    y = RNG.rand(3, 4).astype("f4")
    _golden("minus", {"X": x, "Y": y}, {"Out": x - y})


def test_l1_and_squared_l2_norm_golden():
    x = (RNG.rand(4, 5).astype("f4") - 0.5)
    _golden("l1_norm", {"X": x}, {"Out": np.abs(x).sum()}, atol=1e-5)
    _golden("squared_l2_norm", {"X": x}, {"Out": (x * x).sum()}, atol=1e-5)


def test_squared_l2_distance_golden():
    x = RNG.rand(4, 3).astype("f4")
    y = RNG.rand(4, 3).astype("f4")
    sub = x - y
    _golden("squared_l2_distance", {"X": x, "Y": y},
            {"sub_result": sub, "Out": (sub * sub).sum(1, keepdims=True)},
            atol=1e-5)
    # broadcast Y [1, D]
    y1 = RNG.rand(1, 3).astype("f4")
    sub1 = x - y1
    _golden("squared_l2_distance", {"X": x, "Y": y1},
            {"sub_result": sub1, "Out": (sub1 * sub1).sum(1, keepdims=True)},
            atol=1e-5)


def test_fill_golden():
    vals = [1.5, -2.0, 3.25, 0.0, 7.0, -1.0]
    _golden("fill", {}, {"Out": np.asarray(vals, "f4").reshape(2, 3)},
            {"shape": [2, 3], "value": vals, "dtype": "float32"})


def test_fill_zeros_like2_golden():
    x = RNG.rand(2, 3).astype("f4")
    _golden("fill_zeros_like2", {"X": x}, {"Out": np.zeros_like(x)},
            {"dtype": "float32"})


def test_proximal_gd_golden():
    p = RNG.rand(5).astype("f4")
    g = (RNG.rand(5).astype("f4") - 0.5)
    lr = np.array([0.1], "f4")
    l1, l2 = 0.05, 0.1
    prox = p - 0.1 * g
    want = np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * l1, 0) / (1 + 0.1 * l2)
    _golden("proximal_gd", {"Param": p, "Grad": g, "LearningRate": lr},
            {"ParamOut": want.astype("f4")}, {"l1": l1, "l2": l2}, atol=1e-6)


def test_proximal_adagrad_golden():
    p = RNG.rand(5).astype("f4")
    g = (RNG.rand(5).astype("f4") - 0.5)
    m = RNG.rand(5).astype("f4") + 0.1
    lr = np.array([0.1], "f4")
    l1, l2 = 0.05, 0.1
    # reference proximal_adagrad_op.h: raw lr in the shrinkage; only the
    # gradient step is scaled by 1/sqrt(m_new)
    m_new = m + g * g
    prox = p - (0.1 / np.sqrt(m_new)) * g
    want = np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * l1, 0) / (1 + 0.1 * l2)
    _golden("proximal_adagrad",
            {"Param": p, "Grad": g, "Moment": m, "LearningRate": lr},
            {"ParamOut": want.astype("f4"), "MomentOut": m_new.astype("f4")},
            {"l1": l1, "l2": l2}, atol=1e-6)
