"""Unit tier of the distributed-resilience chaos suite (ISSUE 4): the
heartbeat/watchdog health layer, the distributed fault-spec grammar, the
coordinated-checkpoint commit protocol, the gang launcher's port/reap
mechanics, and the perf_report dist gates — all in-process, CPU-only,
sub-second.  The multi-process integration tier lives in
tests/test_dist_chaos.py."""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import dist_resilience as dres
from paddle_tpu.checkpoint_manager import (COMMITTED_MARKER, DIST_MARKER,
                                           CheckpointManager)
from paddle_tpu.dist_resilience import (CollectiveWatchdog, Heartbeat,
                                        HeartbeatConfig, dump_stacks,
                                        guard_blocking)
from paddle_tpu.errors import (CollectiveTimeoutError, DistributedError,
                               NumericError, PeerFailureError, TrainingError,
                               classify)
from paddle_tpu.faults import FaultInjector, parse_fault_spec
from paddle_tpu.launch import Gang, allocate_port_block

FAST = HeartbeatConfig(interval_s=0.02, miss_factor=4, startup_grace_s=5.0)


# --- taxonomy ---------------------------------------------------------------

def test_distributed_error_taxonomy():
    e = PeerFailureError("w", rank=0, peers=[1, 3], collective="allreduce",
                         step=7)
    assert isinstance(e, DistributedError) and isinstance(e, TrainingError)
    assert isinstance(e, RuntimeError)  # legacy catch sites keep working
    assert classify(e) is e  # already classified: returned untouched
    s = str(e)
    assert "rank=0" in s and "peers=[1, 3]" in s and "allreduce" in s
    t = CollectiveTimeoutError("t", rank=2, collective="barrier")
    assert classify(t) is t and "barrier" in str(t)
    assert dres.exit_code_for(e) == dres.EXIT_PEER_FAILURE == 43
    assert dres.exit_code_for(t) == dres.EXIT_COLLECTIVE_TIMEOUT == 44
    assert dres.exit_code_for(ValueError("x")) == 1


# --- fault spec grammar -----------------------------------------------------

def test_distributed_fault_spec_grammar():
    fs = parse_fault_spec("kill_worker@3:1;stall_worker@6:0:0.25;nan@2")
    assert [str(f) for f in fs] == ["kill_worker@3:1", "stall_worker@6:0:0.25",
                                    "nan@2"]
    assert fs[0].target_rank == 1
    assert fs[1].target_rank == 0 and fs[1].stall_s == 0.25
    assert fs[2].target_rank is None
    for bad in ("kill_worker@3", "kill_worker@3:x", "stall_worker@3:1",
                "stall_worker@3:1:fast", "kill_worker3:1"):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)


def test_ranked_faults_fire_only_on_matching_rank(monkeypatch):
    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append((pid, sig)))
    # wrong rank: entry stays pending, nothing fires
    inj = FaultInjector("kill_worker@3:1", rank=0)
    inj.on_dispatch(3)
    assert not kills and [str(f) for f in inj.pending()] == ["kill_worker@3:1"]
    # matching rank: SIGKILL delivered (the hard death, not SIGTERM)
    inj = FaultInjector("kill_worker@3:1", rank=1)
    inj.on_dispatch(3)
    assert kills == [(os.getpid(), signal.SIGKILL)]
    assert inj.summary() == {"kill_worker": 1}


def test_stall_worker_sleeps_for_spec_duration(monkeypatch):
    import paddle_tpu.faults as faults_mod

    naps = []
    monkeypatch.setattr(faults_mod.time, "sleep", lambda s: naps.append(s))
    inj = FaultInjector("stall_worker@5:0:0.4", rank=0)
    inj.on_dispatch(4)
    assert naps == []
    inj.on_dispatch(5)
    assert naps == [0.4]
    inj.on_dispatch(5)  # fires exactly once
    assert naps == [0.4]


def test_fault_state_dir_spends_ranked_entries_across_incarnations(
        tmp_path, monkeypatch):
    """A gang restart replays the failed step; the once-per-gang ledger
    must keep the same kill from firing in every incarnation (the bug the
    first end-to-end run of run_gang hit)."""
    monkeypatch.setenv("PADDLE_FAULT_STATE_DIR", str(tmp_path))
    kills = []
    monkeypatch.setattr(os, "kill", lambda pid, sig: kills.append(sig))
    FaultInjector("kill_worker@3:0", rank=0).on_dispatch(3)  # incarnation 0
    assert len(kills) == 1
    assert any(n.startswith("fired-kill_worker@3") for n in os.listdir(tmp_path))
    inj2 = FaultInjector("kill_worker@3:0", rank=0)  # incarnation 1
    inj2.on_dispatch(3)
    assert len(kills) == 1  # spent: did not fire again
    assert inj2.pending() == []


# --- heartbeat --------------------------------------------------------------

def _wait_for(pred, timeout=3.0, every=0.01):
    t0 = time.monotonic()
    while not pred():
        assert time.monotonic() - t0 < timeout, "condition never held"
        time.sleep(every)


def test_heartbeat_liveness_and_staleness_death(tmp_path):
    h0 = Heartbeat(0, 2, config=FAST, hb_dir=str(tmp_path)).start()
    h1 = Heartbeat(1, 2, config=FAST, hb_dir=str(tmp_path)).start()
    try:
        _wait_for(lambda: h0.observe().get(1) is not None)
        assert h0.dead_peers() == [] and h1.dead_peers() == []
        h1.stop()  # silent death: no tombstone, peers see staleness
        t0 = time.monotonic()
        _wait_for(lambda: h0.dead_peers() == [1])
        # detected within a few liveness deadlines, not by luck of a long wait
        assert time.monotonic() - t0 < FAST.deadline_s * 10
    finally:
        h0.stop()
        h1.stop()


def test_heartbeat_udp_transport_on_endpoint_contract():
    """Multi-host path: beats as UDP datagrams to the PADDLE_TRAINER_
    ENDPOINTS ports (a separate namespace from the coordinator's TCP
    bind, so the ports are free to reuse)."""
    base = allocate_port_block(2)
    eps = [f"127.0.0.1:{base}", f"127.0.0.1:{base + 1}"]
    h0 = Heartbeat(0, 2, endpoints=eps, config=FAST, hb_dir="").start()
    h1 = Heartbeat(1, 2, endpoints=eps, config=FAST, hb_dir="").start()
    try:
        _wait_for(lambda: h0.observe().get(1) is not None
                  and h1.observe().get(0) is not None)
        assert h0.dead_peers() == [] and h1.dead_peers() == []
        h1.stop(mark_down=True)  # FIN datagram: immediate tombstone
        _wait_for(lambda: h0.dead_peers() == [1])
    finally:
        h0.stop()
        h1.stop()


def test_heartbeat_tombstone_is_immediate_death(tmp_path):
    h0 = Heartbeat(0, 2, config=FAST, hb_dir=str(tmp_path)).start()
    h1 = Heartbeat(1, 2, config=FAST, hb_dir=str(tmp_path)).start()
    try:
        _wait_for(lambda: h0.observe().get(1) is not None)
        h1.stop(mark_down=True)  # classified death: explicit tombstone
        _wait_for(lambda: h0.dead_peers() == [1], timeout=1.0)
    finally:
        h0.stop()
        h1.stop()


# --- watchdog ---------------------------------------------------------------

def test_watchdog_timeout_raises_instead_of_hanging():
    from paddle_tpu import monitor

    wd = CollectiveWatchdog(heartbeat=None, timeout_s=0.1, poll_s=0.01)
    dumps_before = monitor.counter("dist.stack_dumps").value
    t0 = time.monotonic()
    with pytest.raises(CollectiveTimeoutError) as ei:
        wd.run(lambda: time.sleep(30), what="barrier")
    assert time.monotonic() - t0 < 5.0  # raised promptly, no 30s hang
    assert ei.value.collective == "barrier"
    # stack dump only ticks counters when the monitor is enabled; assert
    # the call path ran by checking it against an enabled monitor
    monitor.enable()
    try:
        with pytest.raises(CollectiveTimeoutError):
            wd.run(lambda: time.sleep(30), what="barrier2")
        assert monitor.counter("dist.stack_dumps").value > dumps_before
    finally:
        monitor.disable()


def test_watchdog_dead_peer_raises_peer_failure(tmp_path):
    h0 = Heartbeat(0, 2, config=FAST, hb_dir=str(tmp_path)).start()
    h1 = Heartbeat(1, 2, config=FAST, hb_dir=str(tmp_path)).start()
    try:
        _wait_for(lambda: h0.observe().get(1) is not None)
        h1.stop()
        wd = CollectiveWatchdog(heartbeat=h0, timeout_s=30, poll_s=0.01)
        with pytest.raises(PeerFailureError) as ei:
            wd.run(lambda: time.sleep(30), what="allreduce")
        assert ei.value.peers == [1] and ei.value.rank == 0
    finally:
        h0.stop()
        h1.stop()


def test_watchdog_reclassifies_collective_error_after_peer_death(tmp_path):
    """A SIGKILLed peer tears its sockets down, so the collective's raw
    connection error usually races ahead of heartbeat staleness — the
    watchdog must wait out one liveness deadline and reclassify, not
    surface the raw error as if it were transient."""
    h0 = Heartbeat(0, 2, config=FAST, hb_dir=str(tmp_path)).start()
    h1 = Heartbeat(1, 2, config=FAST, hb_dir=str(tmp_path)).start()
    try:
        _wait_for(lambda: h0.observe().get(1) is not None)
        h1.stop()  # dies silently...
        wd = CollectiveWatchdog(heartbeat=h0, timeout_s=30, poll_s=0.01)

        def gloo_like_failure():
            raise RuntimeError("Connection closed by peer [127.0.0.1]:1234")

        with pytest.raises(PeerFailureError) as ei:
            wd.run(gloo_like_failure, what="executor.fetch")
        assert isinstance(ei.value.__cause__, RuntimeError)
    finally:
        h0.stop()
        h1.stop()


def test_watchdog_exonerates_alive_peers_quickly(tmp_path):
    """The flip side of reclassification: a raw error with every peer
    provably alive (sequence advanced after the error) must re-raise as
    itself — promptly, not after the whole liveness deadline, and never
    as PeerFailureError."""
    slow = HeartbeatConfig(interval_s=0.05, miss_factor=40,  # 2s deadline
                           startup_grace_s=5.0)
    h0 = Heartbeat(0, 2, config=slow, hb_dir=str(tmp_path)).start()
    h1 = Heartbeat(1, 2, config=slow, hb_dir=str(tmp_path)).start()
    try:
        _wait_for(lambda: h0.observe().get(1) is not None)
        wd = CollectiveWatchdog(heartbeat=h0, timeout_s=30, poll_s=0.01)

        def raw_failure():
            raise RuntimeError("transient wobble, nobody died")

        t0 = time.monotonic()
        with pytest.raises(RuntimeError) as ei:
            wd.run(raw_failure, what="executor.fetch")
        held = time.monotonic() - t0
        assert not isinstance(ei.value, TrainingError)
        # exonerated after ~2 beats, far inside the 2s liveness deadline
        assert held < slow.deadline_s / 2, f"held re-raise {held:.2f}s"
    finally:
        h0.stop()
        h1.stop()


def test_watchdog_passes_results_and_errors_through(tmp_path):
    wd = CollectiveWatchdog(heartbeat=None, timeout_s=5, poll_s=0.01)
    assert wd.run(lambda: 42) == 42
    with pytest.raises(ZeroDivisionError):
        wd.run(lambda: 1 // 0)
    # TrainingErrors skip the dead-peer reclassification wait entirely
    h0 = Heartbeat(0, 2, config=FAST, hb_dir=str(tmp_path)).start()
    try:
        wd = CollectiveWatchdog(heartbeat=h0, timeout_s=5, poll_s=0.01)

        def numeric():
            raise NumericError("NaN")

        t0 = time.monotonic()
        with pytest.raises(TrainingError):
            wd.run(numeric)
        assert time.monotonic() - t0 < FAST.deadline_s  # no liveness wait
    finally:
        h0.stop()


def test_guard_blocking_and_health_lifecycle(tmp_path, monkeypatch):
    assert guard_blocking(lambda: 7) == 7  # inactive: direct call
    assert dres.active_watchdog() is None
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    wd = dres.init_health(rank=0, world=1, config=FAST)
    try:
        assert dres.active_watchdog() is wd
        assert dres.init_health(rank=0, world=1) is wd  # idempotent
        assert guard_blocking(lambda: 9) == 9  # routed through the watchdog
    finally:
        dres.shutdown_health()
    assert dres.active_watchdog() is None and dres.active_heartbeat() is None


def test_dump_stacks_names_every_thread():
    text = dump_stacks("unit test", file=open(os.devnull, "w"))
    assert "MainThread" in text and "unit test" in text


# --- coordinated checkpoint commit ------------------------------------------

def _model(seed=7):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.fc(x, 2))
        fluid.optimizer.SGD(0.1).minimize(loss)
    startup.random_seed = seed
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    return main, scope


def test_coordinated_commit_requires_every_rank(tmp_path):
    root = str(tmp_path)
    m0, s0 = _model()
    m1, s1 = _model()
    cm0 = CheckpointManager(root, program=m0, scope=s0, rank=0, world_size=2,
                            commit_timeout_s=10)
    cm1 = CheckpointManager(root, program=m1, scope=s1, rank=1, world_size=2,
                            commit_timeout_s=10)
    # rank 1 alone: shards land in the pending dir, nothing committed
    cm1.save(step=2)
    pending = os.path.join(root, "ckpt-0000000002.tmp")
    assert os.path.exists(os.path.join(pending, "SHARD_DONE.p1"))
    assert cm0.checkpoints() == []
    # rank 0 joins: rank-0 commit renames into place with the marker
    cm0.save(step=2)
    final = os.path.join(root, "ckpt-0000000002")
    assert os.path.exists(os.path.join(final, COMMITTED_MARKER))
    assert os.path.exists(os.path.join(final, DIST_MARKER))
    assert not os.path.exists(pending)
    # restore round-trips state; a world-1 reader of a world-2 checkpoint
    # must opt into the elastic consolidation (ISSUE 9: the silent
    # world-size assumption now raises CheckpointError)
    m2, s2 = _model(seed=9)
    assert CheckpointManager(root, program=m2, scope=s2).restore(
        scope=s2, elastic=True) == 2
    w_name = next(n for n in s0.local_var_names() if "w" in n or "fc" in n)
    np.testing.assert_array_equal(np.asarray(s2.find_var(w_name)),
                                  np.asarray(s0.find_var(w_name)))


def test_restore_skips_uncommitted_distributed_checkpoint(tmp_path):
    """The satellite scenario verbatim: a worker crashes after its own
    shard commits, leaving a mixed-step directory; restore must walk back
    to the last coordinated step instead of loading it."""
    root = str(tmp_path)
    m0, s0 = _model()
    m1, s1 = _model()
    cm0 = CheckpointManager(root, program=m0, scope=s0, rank=0, world_size=2,
                            commit_timeout_s=10)
    cm1 = CheckpointManager(root, program=m1, scope=s1, rank=1, world_size=2,
                            commit_timeout_s=10)
    cm1.save(step=2)
    cm0.save(step=2)  # committed at step 2
    cm1.save(step=4)  # rank 0 "crashed": step 4 never commits
    fresh = CheckpointManager(root, program=m0, scope=s0, elastic=True)
    assert fresh.restore(scope=s0) == 2
    # a mixed-step dir that somehow LOOKS final (legacy non-atomic rename)
    # is still refused without its COMMITTED marker
    bad = os.path.join(root, "ckpt-0000000006")
    os.makedirs(bad)
    with open(os.path.join(bad, "STEP"), "w") as f:
        f.write("6")
    with open(os.path.join(bad, DIST_MARKER), "w") as f:
        f.write("2")
    assert CheckpointManager(root, program=m0, scope=s0).restore(
        scope=s0, elastic=True) == 2


def test_rank0_commit_wait_is_bounded_and_classified(tmp_path):
    m0, s0 = _model()
    cm0 = CheckpointManager(str(tmp_path), program=m0, scope=s0, rank=0,
                            world_size=2, commit_timeout_s=0.3)
    t0 = time.monotonic()
    with pytest.raises(CollectiveTimeoutError):
        cm0.save(step=2)  # rank 1 never arrives
    assert time.monotonic() - t0 < 5.0
    # heartbeat-aware: a DEAD peer short-circuits the timeout
    hb_dir = str(tmp_path / "hb")
    h0 = Heartbeat(0, 2, config=FAST, hb_dir=hb_dir).start()
    h1 = Heartbeat(1, 2, config=FAST, hb_dir=hb_dir).start()
    try:
        _wait_for(lambda: h0.observe().get(1) is not None)
        h1.stop(mark_down=True)
        dres._HEARTBEAT = h0  # arm the process-global oracle
        cm0.commit_timeout_s = 30
        t0 = time.monotonic()
        with pytest.raises(PeerFailureError):
            cm0.save(step=4)
        assert time.monotonic() - t0 < 10.0
    finally:
        dres._HEARTBEAT = None
        h0.stop()
        h1.stop()


def test_single_process_checkpoints_unaffected(tmp_path):
    """world_size=1 keeps the PR-3 contract: atomic rename, no DIST
    marker, restore without commit ceremony."""
    m0, s0 = _model()
    cm = CheckpointManager(str(tmp_path), program=m0, scope=s0)
    d = cm.save(step=3)
    assert not os.path.exists(os.path.join(d, DIST_MARKER))
    assert os.path.exists(os.path.join(d, COMMITTED_MARKER))
    assert cm.restore(scope=s0) == 3


# --- launcher mechanics -----------------------------------------------------

def test_allocate_port_block_returns_bindable_contiguous_block():
    import socket

    base = allocate_port_block(4)
    socks = []
    try:
        for i in range(4):
            s = socket.socket()
            socks.append(s)
            s.bind(("127.0.0.1", base + i))  # every port genuinely free
    finally:
        for s in socks:
            s.close()


def test_gang_context_manager_reaps_on_body_failure():
    """The spawn-leak satellite: a raising test body (or failed later
    spawn) must leave zero live workers behind."""
    leaked = []
    try:
        with Gang([sys.executable, "-c", "import time; time.sleep(600)"],
                  n_procs=2, grace_s=2.0) as g:
            procs = list(g.procs)
            assert all(p.poll() is None for p in procs)
            raise RuntimeError("test body failed")
    except RuntimeError:
        pass
    leaked = [p.pid for p in procs if p.poll() is None]
    assert not leaked, f"gang leaked live workers: {leaked}"


# --- perf_report gates ------------------------------------------------------

def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


def test_perf_report_dist_gates(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import perf_report

    steps = [{"kind": "step", "recompiles_total": 1} for _ in range(6)]
    snap = {"kind": "snapshot",
            "counters": {"dist.heartbeat.sent": 200,
                         "dist.heartbeat.missed": 2,
                         "dist.gang_restarts": 1}}
    events = [{"kind": "dist_event", "action": "gang_restart",
               "incarnation": 1},
              {"kind": "dist_event", "action": "peer_failure", "peers": [1]}]
    p = str(tmp_path / "m.jsonl")
    _write_jsonl(p, steps + events + [snap])
    assert perf_report.heartbeat_miss_fraction(
        [json.loads(l) for l in open(p)]) == pytest.approx(0.01)
    assert perf_report.check(p, max_heartbeat_miss_frac=0.05,
                             max_gang_restarts=1) == 0
    assert perf_report.check(p, max_heartbeat_miss_frac=0.001) == 1
    assert perf_report.check(p, max_gang_restarts=0) == 1
    # a launcher-side file has no step records but must still be gateable
    p2 = str(tmp_path / "launcher.jsonl")
    _write_jsonl(p2, events + [snap])
    assert perf_report.check(p2, max_gang_restarts=2) == 0
    assert perf_report.check(p2, max_gang_restarts=0) == 1
    # ...while the non-dist gates still demand step records
    assert perf_report.check(p2, max_retry_frac=0.5) == 1
