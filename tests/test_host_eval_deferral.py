"""Fetch-time host evaluation of sink ops on callback-less platforms
(VERDICT r4 #5) + in-graph XXH64 goldens.

Reference context: chunk_eval_op.cc / detection_map_op.cc / py_func_op.cc
run in-process on the program's device.  On the axon tunnel (no host
send/recv) the executor prunes these sink ops from the device program,
fetches their inputs, and evaluates them on CPU — validated on the real
chip during r5; these tests force the same code path on the CPU backend by
patching the platform predicate.
"""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import LoDTensor
from paddle_tpu.core.program import program_guard
from paddle_tpu.ops.misc_ops import _xxh64


@pytest.fixture
def forced_deferral(monkeypatch):
    """Force the executor's platform probe to report callback-less (as the
    axon device does) while the CPU host-eval lowering context stays
    callback-capable — exercising the full split/fetch/host-eval path on
    the CPU backend."""
    from unittest.mock import patch as _patch

    from paddle_tpu.core import executor as ex
    from paddle_tpu.ops import common

    orig = ex.Executor._split_host_eval

    def patched(self, program, fetch_names, feed):
        with _patch.object(common, "_platform_lacks_callbacks", lambda p: True):
            return orig(self, program, fetch_names, feed)

    monkeypatch.setattr(ex.Executor, "_split_host_eval", patched)


def test_chunk_eval_defers_to_fetch_time(forced_deferral):
    label = np.array([[0], [1], [4], [2], [3]], "int64")
    pred = np.array([[0], [1], [4], [2], [4]], "int64")
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        iv = fluid.layers.data("i", [1], dtype="int64", lod_level=1)
        lv = fluid.layers.data("l", [1], dtype="int64", lod_level=1)
        outs = fluid.layers.chunk_eval(iv, lv, "IOB", 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    for _ in range(2):  # second run exercises the pruned-program cache
        res = exe.run(main, feed={"i": LoDTensor([pred]), "l": LoDTensor([label])},
                      fetch_list=list(outs), scope=scope)
    p, r, f1, ni, nl, nc = [np.asarray(v).reshape(-1)[0] for v in res]
    assert (ni, nl, nc) == (2, 2, 1)
    np.testing.assert_allclose([p, r, f1], [0.5, 0.5, 0.5])


def test_deferred_metric_with_device_compute_upstream(forced_deferral):
    # the metric's input is PRODUCED by device ops (scale of the feed), so
    # the executor must add the intermediate to the device fetch set; a
    # second device-side fetch (mean) rides the same dispatch
    det = np.array([[[1, 0.9, .1, .1, .2, .2],
                     [2, 0.8, .3, .3, .4, .4],
                     [-1, 0.0, 0, 0, 0, 0]]], "f4")
    gt = np.array([[[1, .1, .1, .2, .2],
                    [2, .3, .3, .4, .4]]], "f4")
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        dv = fluid.layers.data("det", [3, 6], dtype="float32")
        gv = fluid.layers.data("gt", [2, 5], dtype="float32")
        dv2 = fluid.layers.scale(dv, scale=1.0)  # device-produced input
        m = fluid.layers.detection_map(dv2, gv, class_num=3,
                                       overlap_threshold=0.5,
                                       ap_version="integral")
        mean_det = fluid.layers.mean(dv2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    res = exe.run(main, feed={"det": det, "gt": gt},
                  fetch_list=[mean_det, m], scope=scope)
    assert np.isfinite(np.asarray(res[0]).reshape(-1)[0])
    np.testing.assert_allclose(float(np.asarray(res[1]).reshape(-1)[0]), 1.0,
                               atol=1e-6)


def test_detection_map_defers(forced_deferral):
    det = np.array([[[1, 0.9, .1, .1, .2, .2],
                     [2, 0.8, .3, .3, .4, .4],
                     [1, 0.7, .5, .5, .6, .6],
                     [-1, 0.0, 0, 0, 0, 0]]], "f4")
    gt = np.array([[[1, .1, .1, .2, .2],
                    [2, .3, .3, .4, .4]]], "f4")
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        dv = fluid.layers.data("det", [4, 6], dtype="float32")
        gv = fluid.layers.data("gt", [2, 5], dtype="float32")
        m = fluid.layers.detection_map(dv, gv, class_num=3,
                                       overlap_threshold=0.5,
                                       ap_version="integral")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (mv,) = exe.run(main, feed={"det": det, "gt": gt}, fetch_list=[m], scope=scope)
    np.testing.assert_allclose(float(np.asarray(mv).reshape(-1)[0]), 1.0, atol=1e-6)


# --- in-graph XXH64 (runs on any backend; no callback) ---------------------

@pytest.mark.parametrize("last,mod", [
    (2, 1000),            # short input (n < 32)
    (8, 2_000_000_011),   # exactly one 32-byte block, mod near 2^31
    (11, 999_983),        # block + 8-byte lane + 4-byte tail
    (9, 2**31 - 1),       # block + 4-byte tail, max mod
])
def test_hash_in_graph_matches_spec_oracle(last, mod):
    rng = np.random.RandomState(last)
    x = rng.randint(-2**31, 2**31, size=(5, last)).astype("int32")
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [last], dtype="int32")
        out = fluid.layers.hash(xv, hash_size=mod, num_hash=2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    got = np.asarray(got)
    for r in range(5):
        for j in range(2):
            assert got[r, j] == _xxh64(x[r].tobytes(), j) % mod


def test_xxh64_published_vectors_via_jnp():
    # XXH64 official test vectors (xxhash spec): empty-seed cases need
    # byte granularity we don't feed, so pin 4- and 8-byte inputs against
    # the numpy oracle which itself is pinned to published vectors in
    # tests/test_ops_round4.py
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.misc_ops import _xxh64_jnp

    for words_np, seed in [(np.array([[0x04030201]], np.int32), 0),
                           (np.array([[0x04030201, 0x08070605]], np.int32), 7)]:
        words = jax.lax.bitcast_convert_type(jnp.asarray(words_np), jnp.uint32)
        hi, lo = _xxh64_jnp(words, seed)
        got = (int(np.asarray(hi)[0]) << 32) | int(np.asarray(lo)[0])
        want = _xxh64(words_np.tobytes(), seed)
        assert got == want, (hex(got), hex(want))


def test_py_func_sink_defers(forced_deferral):
    # py_func as a pure sink (host-side metric transform): the executor
    # must defer it to fetch time on the callback-less platform, feeding it
    # a device-produced intermediate
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.scale(x, scale=2.0)  # device compute upstream

        def host_metric(arr):
            return np.asarray(arr).sum(axis=1, keepdims=True).astype("f4")

        out = main.current_block().create_var("pf_out", shape=(3, 1),
                                              dtype="float32")
        fluid.layers.py_func(host_metric, y, out)
        dev_fetch = fluid.layers.mean(y)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xv = np.arange(12, dtype="f4").reshape(3, 4)
    res = exe.run(main, feed={"x": xv}, fetch_list=[dev_fetch, out], scope=scope)
    np.testing.assert_allclose(np.asarray(res[1]),
                               (2 * xv).sum(1, keepdims=True), rtol=1e-6)
    np.testing.assert_allclose(float(np.asarray(res[0]).reshape(-1)[0]),
                               (2 * xv).mean(), rtol=1e-6)
