"""Request-flight tracing matrix (ISSUE 16).

The contract under test, per docs/observability.md: with the monitor
enabled every serving submit gets a trace id and a span tree (admission
-> queue -> batch_build -> device -> fetch -> respond), EVERY terminal
outcome — completed, shed, timeout, error, shutdown, and the
admission-door rejections — closes its trace with the same stable
reason code the raised ServingError carries, shed/timeout/error
episodes land exemplars in the flight-recorder black box, pad waste and
queue wait are attributed per bucket (counters + gauges + serving_batch
stamps), SLO burn is accounted against the request deadlines, the
Chrome-trace export grows per-request lanes, `tools/serve_trace.py`
renders and gates the stream — and with the monitor DISABLED the whole
layer is one branch returning a shared null singleton (the PR-8
µs-scale hot-path contract).

Everything runs on CPU (conftest pins JAX_PLATFORMS=cpu); tier-1.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, monitor, serving
from paddle_tpu.errors import ServingError
from paddle_tpu.monitor import EXEMPLAR_CAP, MONITOR, TRACE_RING_CAP
from paddle_tpu.serving import tracing

D_IN, D_OUT = 8, 4


@pytest.fixture
def mon():
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


def _build_net():
    from paddle_tpu.core import unique_name

    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [D_IN], dtype="float32")
            out = layers.fc(x, D_OUT, act=None)
    return main, startup, out


def _save_model(dirname, w_scale=1.0, poison_nan=False):
    main, startup, out = _build_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 3
    exe.run(startup, scope=scope)
    for v in main.list_vars():
        if v.persistable:
            arr = np.full(np.asarray(scope.find_var(v.name)).shape, w_scale,
                          dtype="float32")
            if poison_nan:
                arr.flat[0] = np.nan
            scope.set_var(v.name, arr)
    fluid.io.save_inference_model(dirname, ["x"], [out], exe, main, scope)
    return dirname


def _server(tmp_path, name="m", buckets=(2, 4), w_scale=1.0, **kw):
    d = _save_model(str(tmp_path / f"model_{name}_{w_scale}"), w_scale)
    reg = serving.ModelRegistry(place=fluid.CPUPlace())
    srv = serving.Server(reg, buckets=buckets, **kw)
    srv.load_model(name, d, warm=kw.get("start", True))
    return srv, d


def _traces(outcome=None):
    ts = monitor.request_traces()
    if outcome is None:
        return ts
    return [t for t in ts if t.get("outcome") == outcome]


# --------------------------------------------------------------------------
# the span tree of a completed request
# --------------------------------------------------------------------------

def test_completed_trace_full_span_tree(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        xv = np.ones((1, D_IN), "f4")
        srv.infer("m", {"x": xv})
    finally:
        srv.stop()
    (t,) = _traces("completed")
    assert t["kind"] == "serving_trace"
    assert t["trace_id"].startswith("r")
    assert t["model"] == "m" and t["rows"] == 1
    names = [s["name"] for s in t["spans"]]
    assert names == list(tracing.TRACE_PHASES)  # the full canonical tree
    # span arithmetic: contiguous, and the durations cover the total
    total = sum(s["dur_ms"] for s in t["spans"])
    assert total == pytest.approx(t["total_ms"], abs=0.01)
    for prev, nxt in zip(t["spans"], t["spans"][1:]):
        assert nxt["t_ms"] == pytest.approx(
            prev["t_ms"] + prev["dur_ms"], abs=0.01)
    # batch_build carried the pad attribution annotations
    assert t["bucket"] == 2 and t["pad_rows"] == 1 and t["batch_rows"] == 1
    assert t["lat_ms"] > 0 and t["slo_miss"] is False


def test_serving_batch_record_stamped_with_attribution(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        srv.infer("m", {"x": np.ones((1, D_IN), "f4")})
    finally:
        srv.stop()
    (b,) = [r for r in monitor.step_records()
            if r.get("kind") == "serving_batch"]
    assert b["pad_rows"] == 1 and b["pad_frac"] == 0.5
    assert 0.0 <= b["queue_wait_frac"] <= 1.0
    assert b["queue_ms_mean"] >= 0 and b["queue_ms_max"] >= b["queue_ms_mean"]
    for k in ("t_build_s", "t_infer_s", "t_fetch_s"):
        assert b[k] >= 0
    (t,) = _traces("completed")
    assert b["trace_ids"] == [t["trace_id"]]


# --------------------------------------------------------------------------
# every terminal outcome closes a trace (the reconciliation satellite)
# --------------------------------------------------------------------------

def test_all_terminal_outcomes_close_traces(tmp_path, mon):
    """One server driven through completed/shed/timeout/rejected/shutdown:
    the trace stream reconciles with the ledger, outcome by outcome."""
    srv, _ = _server(tmp_path, buckets=(2, 4), max_queue=2, start=False)
    srv.registry.warm("m", (2, 4))
    xv = np.ones((1, D_IN), "f4")
    completed = srv.submit("m", {"x": xv})
    doomed = srv.submit("m", {"x": xv}, deadline_ms=5)
    with pytest.raises(ServingError) as shed_ei:
        srv.submit("m", {"x": xv})  # queue bound = 2: shed
    with pytest.raises(ServingError) as rej_ei:
        srv.submit("nope", {"x": xv})  # unknown model: door rejection
    time.sleep(0.08)  # deadline lapses while queued
    srv.start()
    (out,) = completed.result(timeout=30)
    with pytest.raises(ServingError):
        doomed.result(timeout=30)
    # leave one queued and stop without workers draining it -> shutdown
    srv.stop()
    srv2, _ = _server(tmp_path, name="m2", buckets=(2,), start=False)
    leftover = srv2.submit("m2", {"x": xv})
    srv2.stop()
    with pytest.raises(ServingError) as sd_ei:
        leftover.result(timeout=5)
    assert sd_ei.value.reason == "shutdown"

    by = {}
    for t in _traces():
        by[t["outcome"]] = by.get(t["outcome"], 0) + 1
    assert by == {"completed": 1, "shed": 1, "timeout": 1, "rejected": 1,
                  "shutdown": 1}
    # stable reason codes ride both the trace and the raised error, and
    # the error names the trace
    reasons = {t["outcome"]: t.get("reason") for t in _traces()}
    assert reasons["shed"] == shed_ei.value.reason == "overload"
    assert reasons["rejected"] == rej_ei.value.reason == "model_missing"
    assert reasons["timeout"] == "timeout"
    assert shed_ei.value.trace_id == next(
        t["trace_id"] for t in _traces("shed"))
    # ledger identity, trace side: in-ledger traces == requests admitted
    in_ledger = [t for t in _traces() if t["outcome"] != "rejected"]
    admitted = (srv.stats()["requests"] + srv2.stats()["requests"])
    assert len(in_ledger) == admitted == 4
    # early closes end on the phase that killed them
    assert _traces("shed")[0]["spans"][-1]["name"] == "admission"
    assert _traces("timeout")[0]["spans"][-1]["name"] == "batch_build"
    assert _traces("shutdown")[0]["spans"][-1]["name"] == "queue"


def test_error_path_closes_traces_classified(tmp_path, mon,
                                             monkeypatch):
    """A worker-side bomb (result splitting) fails the batch's futures
    AND closes their traces as errors with a stable reason."""
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        def bomb(*a, **k):
            raise OSError("simulated result-split disaster")

        monkeypatch.setattr("paddle_tpu.serving.batcher.split_rows", bomb)
        with pytest.raises(Exception):
            srv.infer("m", {"x": np.ones((1, D_IN), "f4")})
    finally:
        srv.stop()
    (t,) = _traces("error")
    assert t["spans"][-1]["name"] == "error"
    assert t.get("reason")  # classified, not empty
    assert srv.stats()["errors"] == 1


# --------------------------------------------------------------------------
# exemplars into the black box
# --------------------------------------------------------------------------

def test_shed_and_timeout_exemplars_in_blackbox(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,), max_queue=1, start=False)
    srv.registry.warm("m", (2,))
    xv = np.ones((1, D_IN), "f4")
    doomed = srv.submit("m", {"x": xv}, deadline_ms=5)
    with pytest.raises(ServingError):
        srv.submit("m", {"x": xv})  # shed
    time.sleep(0.08)
    srv.start()
    with pytest.raises(ServingError):
        doomed.result(timeout=30)
    srv.stop()
    exes = monitor.blackbox_snapshot()["exemplars"]
    outcomes = sorted(e["outcome"] for e in exes)
    assert outcomes == ["shed", "timeout"]
    assert all(e["kind"] == "serving_trace" for e in exes)


# --------------------------------------------------------------------------
# control-plane trace ids (publish / rollback mid-flight)
# --------------------------------------------------------------------------

def test_publish_and_rollback_carry_control_ids(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        v2 = _save_model(str(tmp_path / "v2"), w_scale=2.0)
        srv.publish("m", v2)
        srv.rollback("m")
        events = {r["action"]: r for r in monitor.step_records()
                  if r.get("kind") == "serving_event"}
        assert events["publish"]["trace_id"].startswith("pub-")
        assert events["rollback"]["trace_id"].startswith("rb-")
    finally:
        srv.stop()


def test_rejected_publish_mid_flight_traced_and_exemplared(tmp_path, mon):
    """A publish rejected while requests flow: the rejection event and
    the raised error share a pub- control id, an exemplar lands in the
    black box, and traffic's own traces keep completing."""
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        xv = np.ones((1, D_IN), "f4")
        srv.infer("m", {"x": xv})
        bad = _save_model(str(tmp_path / "bad"), w_scale=2.0,
                          poison_nan=True)
        with pytest.raises(ServingError) as ei:
            srv.publish("m", bad)
        assert ei.value.reason == "publish_rejected"
        assert ei.value.trace_id.startswith("pub-")
        (ev,) = [r for r in monitor.step_records()
                 if r.get("kind") == "serving_event"
                 and r.get("action") == "publish_rejected"]
        assert ev["trace_id"] == ei.value.trace_id
        exes = [e for e in monitor.blackbox_snapshot()["exemplars"]
                if e.get("reason") == "publish_rejected"]
        assert exes and exes[0]["trace_id"] == ei.value.trace_id
        srv.infer("m", {"x": xv})  # old version serves on
        assert len(_traces("completed")) == 2
    finally:
        srv.stop()


# --------------------------------------------------------------------------
# disabled-monitor zero-overhead guard (the PR-8 contract)
# --------------------------------------------------------------------------

def _per_call(fn, n):
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def test_disabled_monitor_null_trace_zero_overhead(tmp_path):
    monitor.disable()
    # one branch, one shared singleton — no per-request allocation
    tr = tracing.maybe_trace(MONITOR, "m")
    assert tr is tracing.NULL_TRACE
    assert tr is tracing.maybe_trace(MONITOR, "other", deadline_ms=5.0)
    assert tr.trace_id is None and tr.enabled is False
    assert tr.close("completed") is None  # and closing records nothing
    n = 20000
    assert _per_call(lambda: tracing.maybe_trace(MONITOR, "m"), n) < 5e-6
    assert _per_call(lambda: tr.phase("queue"), n) < 5e-6
    # a disabled serving round produces NO traces and still serves
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        xv = np.ones((1, D_IN), "f4")
        (out,) = srv.infer("m", {"x": xv})
        assert out.shape == (1, D_OUT)
    finally:
        srv.stop()
    assert monitor.request_traces() == []
    assert srv.stats()["requests"] == 1  # exact ledger even when dark


# --------------------------------------------------------------------------
# pad-waste + queue-wait attribution (counters, gauges, ledger)
# --------------------------------------------------------------------------

def test_pad_counter_and_bucket_pad_frac_gauge(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2, 4))
    try:
        srv.infer("m", {"x": np.ones((1, D_IN), "f4")})  # bucket 2, pad 1
        srv.infer("m", {"x": np.ones((3, D_IN), "f4")})  # bucket 4, pad 1
        assert monitor.counter("serving.pad_rows").value == 2
        assert monitor.gauge("serving.bucket[2].pad_frac").read() \
            == pytest.approx(0.5)
        assert monitor.gauge("serving.bucket[4].pad_frac").read() \
            == pytest.approx(0.25)
        assert 0.0 <= monitor.gauge("serving.queue_wait_frac").read() <= 1.0
        attr = srv.bucket_attribution()
        assert attr[2]["pad_rows"] == 1 and attr[4]["pad_rows"] == 1
        assert attr[2]["requests"] == 1 and attr[4]["rows"] == 3
        assert attr[4]["occupancy"] == pytest.approx(0.75)
        assert 0.0 <= srv.queue_wait_frac() <= 1.0
    finally:
        srv.stop()


def test_slo_burn_accounting(tmp_path, mon):
    """Timeouts and sheds burn the SLO budget; on-time completions with
    no deadline do not.  The windowed gauges agree with the ledger."""
    srv, _ = _server(tmp_path, buckets=(2,), max_queue=1, start=False)
    srv.registry.warm("m", (2,))
    xv = np.ones((1, D_IN), "f4")
    doomed = srv.submit("m", {"x": xv}, deadline_ms=5)
    with pytest.raises(ServingError):
        srv.submit("m", {"x": xv})  # shed -> slo_bad
    time.sleep(0.08)
    srv.start()
    with pytest.raises(ServingError):
        doomed.result(timeout=30)  # timeout -> slo_bad
    srv.infer("m", {"x": xv})  # completed, no deadline -> slo_good
    s = srv.stats()
    assert s["slo"]["good"] == 1 and s["slo"]["bad"] == 2
    assert s["slo"]["good"] + s["slo"]["bad"] == s["requests"]
    assert s["slo"]["good_frac"] == pytest.approx(1.0 / 3.0, abs=1e-3)
    # burn rate vs the default 0.99 target: 2/3 bad is ~66x the budget
    assert s["slo"]["burn_rate"] == pytest.approx(
        (2.0 / 3.0) / (1.0 - s["slo"]["target"]), rel=1e-3)
    assert monitor.counter("serving.slo_bad").value == 2
    assert monitor.counter("serving.slo_good").value == 1
    assert monitor.gauge("serving.slo_good_frac").read() \
        == pytest.approx(1.0 / 3.0, abs=1e-3)
    assert monitor.gauge("serving.slo_burn_rate").read() > 1.0
    srv.stop()


# --------------------------------------------------------------------------
# bounded rings (flight-recorder discipline)
# --------------------------------------------------------------------------

def test_trace_and_exemplar_rings_bounded(mon):
    for i in range(TRACE_RING_CAP + 50):
        monitor.record_trace({"trace_id": f"r{i}", "outcome": "completed",
                              "spans": []})
    assert len(monitor.request_traces()) == TRACE_RING_CAP
    assert monitor.request_traces()[-1]["trace_id"] \
        == f"r{TRACE_RING_CAP + 49}"
    for i in range(EXEMPLAR_CAP + 20):
        monitor.record_exemplar({"trace_id": f"e{i}"})
    assert len(monitor.exemplars()) == EXEMPLAR_CAP
    # reset clears both rings
    monitor.reset()
    assert monitor.request_traces() == [] and monitor.exemplars() == []


def test_record_trace_disabled_is_noop():
    monitor.disable()
    monitor.reset()
    monitor.record_trace({"trace_id": "r1", "outcome": "completed"})
    monitor.record_exemplar({"trace_id": "r1"})
    assert monitor.request_traces() == [] and monitor.exemplars() == []


# --------------------------------------------------------------------------
# RequestTrace unit behavior
# --------------------------------------------------------------------------

def test_request_trace_first_close_wins_and_phases_freeze():
    tr = tracing.RequestTrace("m", rows=1)
    tr.phase("admission").phase("queue")
    rec = tr.close("timeout", reason="timeout", final="batch_build")
    assert rec["outcome"] == "timeout"
    assert [s["name"] for s in rec["spans"]] \
        == ["admission", "queue", "batch_build"]
    # the worker catch-all racing a deadline cancel: repeat close is None
    assert tr.close("error", reason="boom") is None
    tr.phase("device")  # frozen after close
    assert len(tr.marks) == 3
    # control ids are namespaced per prefix
    assert tracing.control_trace_id("pub").startswith("pub-")
    assert tracing.control_trace_id("rb").startswith("rb-")


# --------------------------------------------------------------------------
# Chrome-trace request lanes
# --------------------------------------------------------------------------

def test_chrome_trace_request_lanes(tmp_path, mon):
    srv, _ = _server(tmp_path, buckets=(2,))
    try:
        srv.infer("m", {"x": np.ones((1, D_IN), "f4")})
    finally:
        srv.stop()
    path = str(tmp_path / "trace.json")
    n = monitor.export_chrome_trace(path)
    assert n > 0
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    req = [e for e in events if e.get("cat") == "request"]
    (t,) = _traces("completed")
    begins = [e for e in req if e["ph"] == "b"]
    ends = [e for e in req if e["ph"] == "e"]
    assert len(begins) == len(ends) == len(t["spans"])
    assert {e["id"] for e in req} == {t["trace_id"]}
    assert {e["name"] for e in begins} \
        == {f"req.{s['name']}" for s in t["spans"]}
    # async lanes merge with per-rank traces through the existing path
    merged = str(tmp_path / "merged.json")
    monitor.merge_chrome_traces({"r0": path}, merged)
    with open(merged) as f:
        assert any(e.get("cat") == "request"
                   for e in json.load(f)["traceEvents"])


# --------------------------------------------------------------------------
# serve_trace CLI
# --------------------------------------------------------------------------

def _run_round(tmp_path, mon):
    """A small mixed round logged to JSONL: 3 completed + 1 shed."""
    from paddle_tpu.monitor import MonitorLogger

    path = str(tmp_path / "metrics.jsonl")
    logger = monitor.attach_logger(MonitorLogger(path))
    srv, _ = _server(tmp_path, buckets=(2,), max_queue=1, start=False)
    srv.registry.warm("m", (2,))
    xv = np.ones((1, D_IN), "f4")
    first = srv.submit("m", {"x": xv})
    with pytest.raises(ServingError):
        srv.submit("m", {"x": xv})  # shed
    srv.start()
    first.result(timeout=30)
    srv.infer("m", {"x": xv})
    srv.infer("m", {"x": xv})
    logger.write_snapshot()
    monitor.detach_logger(logger)
    srv.stop()
    return path


def test_serve_trace_cli_render_top_and_check(tmp_path, mon, capsys):
    from tools import serve_trace

    path = _run_round(tmp_path, mon)
    assert serve_trace.main([path]) == 0
    out = capsys.readouterr().out
    assert "completed" in out and "shed" in out
    # span-tree render of a named trace
    tid = _traces("completed")[0]["trace_id"]
    assert serve_trace.main([path, "--request", tid]) == 0
    out = capsys.readouterr().out
    assert "device" in out and "queue" in out and tid in out
    assert serve_trace.main([path, "--request", "r999999"]) == 1
    capsys.readouterr()
    # per-bucket live table
    assert serve_trace.main([path, "--top"]) == 0
    out = capsys.readouterr().out
    assert "bucket" in out and "queue_frac" in out and "pad_frac" in out
    assert serve_trace.main([path, "--slow", "2"]) == 0
    capsys.readouterr()
    # reconciliation + attribution gates pass on the round's own output
    assert serve_trace.main([path, "--check", "--max-queue-wait-frac",
                             "0.999", "--max-pad-frac", "0.9"]) == 0
    capsys.readouterr()
    # tight gates fail loudly (pad frac is exactly 0.5 here)
    assert serve_trace.main([path, "--check", "--max-pad-frac",
                             "0.1"]) == 1
    capsys.readouterr()


def test_serve_trace_check_zero_evidence_fails(tmp_path, capsys):
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"kind": "step", "step": 0}) + "\n")
    from tools import serve_trace

    assert serve_trace.main([str(empty), "--check"]) == 1
    out = capsys.readouterr().out
    assert "zero evidence" in out
    assert serve_trace.main([str(tmp_path / "nope.jsonl"), "--check"]) == 1
    capsys.readouterr()


def test_serve_trace_check_catches_overcounting(tmp_path, capsys):
    """A stream whose terminal traces exceed the requests counter (a
    double-closed request) fails reconciliation; unterminated traces
    fail too."""
    path = tmp_path / "bad.jsonl"
    snap = {"counters": {"serving.requests": 1, "serving.completed": 1},
            "gauges": {}}
    lines = [
        {"kind": "serving_trace", "trace_id": "r1", "outcome": "completed",
         "spans": []},
        {"kind": "serving_trace", "trace_id": "r2", "outcome": "completed",
         "spans": []},
        snap,
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in lines))
    from tools import serve_trace

    assert serve_trace.main([str(path), "--check"]) == 1
    assert "exceed" in capsys.readouterr().out
    bad2 = tmp_path / "bad2.jsonl"
    bad2.write_text(json.dumps(
        {"kind": "serving_trace", "trace_id": "r1", "outcome": None,
         "spans": []}) + "\n" + json.dumps(snap) + "\n")
    assert serve_trace.main([str(bad2), "--check"]) == 1
    assert "terminal outcome" in capsys.readouterr().out


def test_serve_trace_cli_subprocess_smoke(tmp_path, mon):
    """The tier-1 CLI smoke: `python tools/serve_trace.py --check` runs
    standalone (sys.path bootstrap) against a real stream."""
    path = _run_round(tmp_path, mon)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "serve_trace.py"),
         path, "--check", "--max-queue-wait-frac", "0.999"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


# --------------------------------------------------------------------------
# perf_report gate integration
# --------------------------------------------------------------------------

def test_perf_report_attribution_gates(tmp_path, mon):
    from tools.perf_report import check

    path = _run_round(tmp_path, mon)
    assert check(path, max_queue_wait_frac=0.999, max_pad_frac=0.9) == 0
    assert check(path, max_pad_frac=0.1) == 1  # 0.5 > 0.1: loud fail


def test_perf_report_gates_counters_only_and_zero_evidence(tmp_path):
    """The gates work on a counters/gauges-only snapshot file (no trace
    records — gauge/counter fallbacks) and FAIL on a file with no
    evidence at all."""
    from tools.perf_report import check

    path = str(tmp_path / "counters.jsonl")
    snap = {"counters": {"serving.pad_rows": 30, "serving.rows": 70,
                         "serving.requests": 10},
            "gauges": {"serving.queue_wait_frac": 0.25}}
    with open(path, "w") as f:
        f.write(json.dumps(snap) + "\n")
    assert check(path, max_queue_wait_frac=0.5, max_pad_frac=0.5) == 0
    assert check(path, max_queue_wait_frac=0.1) == 1  # 0.25 > 0.1
    assert check(path, max_pad_frac=0.2) == 1         # 0.3 > 0.2
    bare = str(tmp_path / "bare.jsonl")
    with open(bare, "w") as f:
        f.write(json.dumps({"kind": "step", "step": 0,
                            "recompiles_total": 0}) + "\n")
    assert check(bare, max_queue_wait_frac=0.9) == 1
    assert check(bare, max_pad_frac=0.9) == 1
