"""Multi-worker chaos suite (ISSUE 4 acceptance): real 2-process gangs on
the CPU backend, driven by `paddle_tpu.launch.run_gang` and the
deterministic distributed fault specs.

The two properties every line of dist_resilience exists for:

  1. killing one worker mid-run makes every surviving peer RAISE a
     classified error (exit 43, PeerFailureError in stderr) within the
     watchdog deadline — nobody hangs tier-1;
  2. gang restart resumes from the last COORDINATED checkpoint with
     global step numbering, ending bit-identical to an uninterrupted run.

Wall-clock is bounded by run_gang's own supervision timeout plus
explicit asserts — a hang here fails fast instead of eating the tier-1
budget.  The assertions key on the KILL incident (rank 1 signaled -9),
not on incarnation indices: under heavy machine load a slow worker can
occasionally lose a whole incarnation to a collective-bootstrap timeout,
which the gang-restart machinery absorbs exactly as designed — the
restart budget below leaves headroom for one such absorbed incident."""
import json
import os
import sys
import time

import pytest

from dist_harness import RESILIENT_WORKER, run_gang

pytestmark = pytest.mark.skipif(
    not os.path.exists(RESILIENT_WORKER), reason="worker script missing")

# Chaos knobs: 3s liveness deadline — fast enough that detection is a
# small slice of the test envelope, wide enough that a beat thread
# starving behind a GIL-heavy import/bootstrap phase on a loaded CI box
# cannot fake a death (observed at 0.5s: a live worker declared dead
# during jax.distributed.initialize).  The watchdog deadline stays far
# above it: the kill path must be won by heartbeat detection, not the
# timeout.  NO persistent compile cache here: cached cross-process
# executables corrupt the heap on this jaxlib (init_distributed
# force-disables it and says so).
CHAOS_ENV = {
    "RUN_STEPS": "8",
    "SAVE_EVERY": "2",
    "FLAGS_dist_heartbeat_interval_s": "0.25",
    "FLAGS_dist_heartbeat_miss_factor": "12",
    "FLAGS_dist_watchdog_timeout_s": "60",
    "FLAGS_dist_bootstrap_timeout_s": "120",
}


def _results(res):
    out = {}
    for rank, (code, o, _e) in enumerate(res.workers):
        for line in (o or "").splitlines():
            if line.startswith("RESULT "):
                out[rank] = json.loads(line[len("RESULT "):])
    return out


def _run(tmp_path, tag, fault_spec=None, max_restarts=0, metrics=None):
    root = str(tmp_path / tag)
    env = dict(CHAOS_ENV)
    if fault_spec:
        env["FLAGS_fault_spec"] = fault_spec
    if metrics:
        env["PADDLE_METRICS_PATH"] = metrics
    return run_gang([sys.executable, RESILIENT_WORKER], 2,
                    checkpoint_root=root, extra_env=env,
                    max_restarts=max_restarts, timeout=240), root


def _kill_incident(res):
    """The incident where rank 1 died by the injected SIGKILL."""
    for inc in res.incidents:
        dead = {d["rank"]: d for d in inc["dead"]}
        if dead.get(1, {}).get("signaled") and dead[1]["returncode"] == -9:
            return inc
    raise AssertionError(
        f"no SIGKILL incident recorded: {res.incidents}")


def _lost_to_bootstrap_load(res):
    """True when the incarnation died to machine-load startup skew (gloo
    context handshake timeout), not to anything under test here."""
    for inc in res.incidents:
        for tail in inc.get("stderr_tails", {}).values():
            if ("Gloo context initialization failed" in tail
                    or "GetKeyValue" in tail):
                return True
    return False


def _survivor_report_raced(res, wall):
    """True when the kill incident has rank 1's SIGKILL but no entry for
    rank 0 yet: under machine load the incident snapshot can land before
    the survivor's classified exit is reaped.  Only a FAST incarnation
    qualifies — a genuine survivor hang rides to the 240s gang timeout
    and must fail loudly, not retry."""
    if wall >= 120:
        return False
    try:
        inc = _kill_incident(res)
    except AssertionError:
        return False
    return 0 not in {d["rank"] for d in inc["dead"]}


def test_kill_worker_survivor_classifies_instead_of_hanging(tmp_path):
    res = None
    for attempt in range(3):  # bounded retries absorb pure load flakes
        t0 = time.monotonic()
        res, _root = _run(tmp_path, f"kill{attempt}",
                          fault_spec="kill_worker@3:1", max_restarts=0)
        wall = time.monotonic() - t0
        if _lost_to_bootstrap_load(res) or _survivor_report_raced(res, wall):
            continue
        break
    assert not res.ok and res.incarnations == 1
    inc = _kill_incident(res)
    dead = {d["rank"]: d for d in inc["dead"]}
    # the survivor: raised PeerFailureError and exited with the
    # classified code — it did NOT sit in the step-3 allreduce forever
    assert dead[0]["returncode"] == 43 and dead[0]["classified"], inc
    tail = inc["stderr_tails"][0]
    assert "PeerFailureError" in tail
    assert "stack dump" in tail  # debuggability contract
    # bootstrap + 3 steps + detection settled inside the supervision
    # envelope — nobody waited out the 240s gang timeout
    assert wall < 240, f"gang took {wall:.0f}s — the watchdog never fired"


def test_gang_restart_resumes_bit_identical(tmp_path):
    metrics = str(tmp_path / "metrics.jsonl")
    ref, _ = _run(tmp_path, "ref", max_restarts=1)
    assert ref.ok, ref.workers
    ref_out = _results(ref)
    assert ref_out[0]["params_sha"] == ref_out[1]["params_sha"]

    chaos, root = _run(tmp_path, "chaos", fault_spec="kill_worker@5:1",
                       max_restarts=3, metrics=metrics)
    assert chaos.ok, chaos.workers
    assert chaos.restarts >= 1
    _kill_incident(chaos)  # the injected death really happened
    out = _results(chaos)
    # the final incarnation resumed from the last coordinated checkpoint
    # (step 4: committed before the step-5 kill), with both workers on
    # the same global step — never from a step its peer doesn't have
    assert out[0]["start_step"] == out[1]["start_step"] == 4
    assert out[0]["restart_num"] == chaos.restarts
    # every committed checkpoint in the root carries the commit marker
    ckpts = [d for d in os.listdir(root) if d.startswith("ckpt-")
             and not d.endswith(".tmp")]
    assert ckpts, "no committed checkpoints on disk"
    for d in ckpts:
        assert os.path.exists(os.path.join(root, d, "COMMITTED"))
    # the acceptance bit: end state identical to the uninterrupted run
    assert out[0]["params_sha"] == out[1]["params_sha"]
    assert out[0]["params_sha"] == ref_out[0]["params_sha"], (
        "gang-restart run diverged from the uninterrupted reference")
    # ...including the exact loss tail over the replayed steps
    assert out[0]["losses"] == ref_out[0]["losses"][out[0]["start_step"]:]

    # worker-side metrics feed the dist gates: each incarnation of rank 0
    # writes step records, dist_event records, and the dist.* counter
    # snapshot perf_report checks; the kill incarnation's file carries
    # the peer_failure + heartbeat_miss transitions
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import perf_report

    r0_files = sorted(f for f in os.listdir(tmp_path)
                      if f.startswith("metrics.jsonl.r0"))
    assert r0_files
    lines = []
    for f in r0_files:
        p = str(tmp_path / f)
        assert perf_report.check(p, max_heartbeat_miss_frac=0.5) == 0
        lines += [json.loads(l) for l in open(p) if l.strip()]
    assert any(r.get("kind") == "dist_event"
               and r.get("action") == "peer_failure" for r in lines)
    assert any(r.get("kind") == "dist_event"
               and r.get("action") == "heartbeat_miss" for r in lines)

def test_enospc_at_commit_skips_round_then_recovers_and_resumes(tmp_path):
    """Storage-fault acceptance (ISSUE 15): `enospc@3:1` fails rank 1's
    shard writes at the step-4 commit boundary.  The contract:

      * NO worker exit and NO watchdog wedge — rank 1 publishes
        SHARD_SKIP, rank 0 abandons the round gang-wide
        (ckpt_rounds_skipped == 1 on both ranks), training continues;
      * checkpointing RECOVERS when the fault window passes (the step-6
        commit lands, ckpt_recoveries == 1, degraded latch clear);
      * a hard kill + gang restart AFTER recovery resumes from the
        recovered checkpoint, bit-identical to an uninterrupted run —
        the degraded window left no scar in training semantics."""
    ref, _ = _run(tmp_path, "storage_ref", max_restarts=1)
    assert ref.ok, ref.workers
    ref_out = _results(ref)

    chaos, root = _run(tmp_path, "storage_chaos",
                       fault_spec="enospc@3:1;kill_worker@7:1",
                       max_restarts=3)
    assert chaos.ok, chaos.workers
    assert chaos.restarts >= 1
    _kill_incident(chaos)  # the injected death really happened
    out = _results(chaos)
    # the enospc round was skipped, not fatal: ckpt-4 never committed,
    # the recovering step-6 commit did, and the restart resumed from it
    ckpts = sorted(d for d in os.listdir(root) if d.startswith("ckpt-")
                   and not d.endswith(".tmp"))
    assert "ckpt-0000000004" not in ckpts, ckpts
    assert "ckpt-0000000006" in ckpts, ckpts
    assert out[0]["start_step"] == out[1]["start_step"] == 6
    # bit-identical to the uninterrupted reference
    assert out[0]["params_sha"] == out[1]["params_sha"]
    assert out[0]["params_sha"] == ref_out[0]["params_sha"], (
        "storage-chaos run diverged from the uninterrupted reference")
    assert out[0]["losses"] == ref_out[0]["losses"][6:]
    # the final incarnation saw a clean store (the fault ledger spent the
    # entry in incarnation 0): no degraded rounds after the restart
    assert not out[0]["ckpt_degraded"] and not out[1]["ckpt_degraded"]


def test_enospc_round_skip_without_restart(tmp_path):
    """The pure degraded-window half (no kill): one gang run straight
    through an enospc commit window — both ranks count exactly one
    skipped round and one recovery, nobody dies, end state agrees."""
    res, root = _run(tmp_path, "storage_skip", fault_spec="enospc@3:1",
                     max_restarts=0)
    assert res.ok, res.workers
    assert res.incarnations == 1 and res.restarts == 0
    out = _results(res)
    for r in (0, 1):
        assert out[r]["ckpt_rounds_skipped"] == 1, out[r]
        assert out[r]["ckpt_recoveries"] == 1, out[r]
        assert not out[r]["ckpt_degraded"]
    assert out[0]["params_sha"] == out[1]["params_sha"]
    ckpts = sorted(d for d in os.listdir(root) if d.startswith("ckpt-")
                   and not d.endswith(".tmp"))
    assert "ckpt-0000000004" not in ckpts and "ckpt-0000000006" in ckpts
