"""Gang telemetry plane: 2-process chaos suite (ISSUE 8 acceptance).

Real 2-process gangs on the CPU backend drive every flight-recorder
trigger path through tests/dist_worker_telemetry.py:

  1. kill_worker chaos — the victim's fsynced dump survives its SIGKILL,
     the survivor dumps on the peer-failure path, the supervisor harvests
     both, and `perf_report --postmortem` renders a merged timeline
     naming the dead rank;
  2. watchdog expiry — a stalled peer (stall > watchdog deadline) makes
     the blocked rank dump on CollectiveTimeoutError, and the LIVE
     straggler detector names the stalled rank in the survivor's metrics
     stream before the watchdog ever fires;
  3. SIGTERM drain — preemption drains the resilient loop and dumps;
  4. crash — an uncaught classified error hits the telemetry excepthook.

Wall-clock bounded by run_gang's supervision timeout, same as the PR-4
chaos suite; bootstrap-load flakes are absorbed with bounded retries.
"""
import json
import os
import sys

import pytest

from dist_harness import run_gang

HERE = os.path.dirname(os.path.abspath(__file__))
TELEMETRY_WORKER = os.path.join(HERE, "dist_worker_telemetry.py")
sys.path.insert(0, os.path.join(os.path.dirname(HERE), "tools"))

pytestmark = pytest.mark.skipif(
    not os.path.exists(TELEMETRY_WORKER), reason="worker script missing")

BASE_ENV = {
    "RUN_STEPS": "6",
    "FLAGS_dist_heartbeat_interval_s": "0.25",
    "FLAGS_dist_heartbeat_miss_factor": "12",
    "FLAGS_dist_watchdog_timeout_s": "60",
    "FLAGS_dist_bootstrap_timeout_s": "120",
}


def _run(tmp_path, tag, fault_spec, extra=None):
    root = str(tmp_path / tag)
    env = dict(BASE_ENV)
    env["FLAGS_fault_spec"] = fault_spec
    env.update(extra or {})
    res = run_gang([sys.executable, TELEMETRY_WORKER], 2,
                   checkpoint_root=root, extra_env=env,
                   max_restarts=0, timeout=240)
    return res, os.path.join(root, "telemetry")


def _lost_to_bootstrap_load(res):
    for inc in res.incidents:
        for tail in inc.get("stderr_tails", {}).values():
            if ("Gloo context initialization failed" in tail
                    or "GetKeyValue" in tail):
                return True
    return False


def _blackboxes(tel_root):
    """{rank: blackbox doc} across incarnation dirs."""
    out = {}
    for dirpath, _dirs, files in os.walk(tel_root):
        for f in files:
            if f.startswith("BLACKBOX.p") and f.endswith(".json"):
                rank = int(f[len("BLACKBOX.p"):-len(".json")])
                with open(os.path.join(dirpath, f)) as fh:
                    out[rank] = json.load(fh)
    return out


def _worker_stderr(res):
    return "\n".join((e or "") for _c, _o, e in res.workers)


def _retry(tmp_path, tag, fault_spec, extra=None, attempts=3, fired=None):
    """Bounded retries absorb pure load flakes: a loaded CI box can lose a
    whole incarnation to bootstrap skew or a coordination-service abort
    BEFORE the injected fault ever fires — then the incident under test
    never happened and the attempt proves nothing.  `fired(res)` says
    whether the scheduled fault actually went off."""
    res = tel = None
    for attempt in range(attempts):
        res, tel = _run(tmp_path, f"{tag}{attempt}", fault_spec, extra)
        if _lost_to_bootstrap_load(res):
            continue
        if fired is None or fired(res):
            break
    return res, tel


def test_kill_worker_blackbox_on_every_rank_and_postmortem(tmp_path, capsys):
    # `fired` also requires the survivor's CLASSIFIED exit: under heavy
    # machine load the gloo collective can abort (XlaRuntimeError) before
    # the heartbeat detector marks the peer dead, so the dump rides the
    # crash excepthook instead of the peer-failure path — a pure timing
    # race the PR-4 chaos suite absorbs the same way (bounded retries;
    # a genuine classification regression fails all attempts)
    res, tel = _retry(
        tmp_path, "kill", "kill_worker@3:1",
        fired=lambda r: ("firing (SIGKILL)" in _worker_stderr(r)
                         and "DIST_FAILURE PeerFailureError"
                         in _worker_stderr(r)))
    assert not res.ok
    assert res.telemetry_dir and os.path.isdir(res.telemetry_dir)

    boxes = _blackboxes(tel)
    # ISSUE 8 acceptance: BLACKBOX.p*.json on EVERY rank — the victim's
    # own pre-SIGKILL dump and the survivor's peer-failure dump
    assert set(boxes) == {0, 1}, sorted(boxes)
    assert boxes[1]["reason"].startswith("kill_worker@3:1")
    assert boxes[0]["reason"] == "peer_failure"
    # both rings carry the last steps before death, rank-stamped
    assert boxes[1]["rank"] == 1 and boxes[1]["steps"]
    assert any(s.get("kind", "step") == "step" for s in boxes[1]["steps"])
    # the survivor's ring includes the peer_failure dist_event with the
    # offender's last telemetry snapshot
    pf = [s for s in boxes[0]["steps"] if s.get("kind") == "dist_event"
          and s.get("action") == "peer_failure"]
    assert pf and pf[0]["peers"] == [1]
    assert "telemetry" in pf[0]

    # the supervisor harvested the boxes into its incident ledger
    inc_files = [f for f in os.listdir(tel) if f.startswith("INCIDENT.")]
    assert inc_files
    inc = json.load(open(os.path.join(tel, inc_files[0])))
    assert len(inc["blackboxes"]) == 2

    # perf_report --postmortem renders a merged timeline naming rank 1
    import perf_report

    assert perf_report.postmortem(tel) == 0
    out = capsys.readouterr().out
    assert "dead rank(s): [1]" in out  # the KILLED rank, not the reactor
    assert "peer-failure reactions (exit 43): [0]" in out
    assert "merged timeline" in out
    assert "peer_failure" in out

    # the per-rank metrics streams merge: the survivor streamed
    # csig-stamped step records trace_merge can correlate
    import trace_merge

    files = trace_merge.find_rank_files(tel)
    assert set(files["metrics"]) == {0, 1}
    recs0 = trace_merge.load_records(files["metrics"][0])
    assert any(r.get("csig") for r in recs0 if r.get("kind") == "step")
    assert any(r.get("kind") == "dist_event" for r in recs0)


def test_watchdog_expiry_blackbox_and_live_straggler_naming(tmp_path):
    # rank 1 stalls 20s at step 2; the watchdog deadline is 8s, so rank 0
    # dumps on expiry — but its straggler detector (3 consecutive 0.1s
    # beats of sustained lag) must have named rank 1 FIRST.  The deadline
    # must clear a cold XLA compile (~3s, worse on a loaded box): the
    # watchdog guards EVERY blocking dispatch, compiles included, and a
    # deadline under compile time fires before the stall even happens.
    res, tel = _retry(
        tmp_path, "stall", "stall_worker@2:1:20",
        extra={"FLAGS_dist_watchdog_timeout_s": "8",
               "FLAGS_dist_heartbeat_interval_s": "0.1",
               "FLAGS_dist_heartbeat_miss_factor": "150"},
        fired=lambda r: "exceeded watchdog deadline" in _worker_stderr(r))
    assert not res.ok
    boxes = _blackboxes(tel)
    assert 0 in boxes, sorted(boxes)
    assert boxes[0]["reason"] == "watchdog_timeout"
    # the expiry record carries the whole gang's telemetry table
    to = [s for s in boxes[0]["steps"] if s.get("kind") == "dist_event"
          and s.get("action") == "collective_timeout"]
    assert to and "telemetry" in to[0]

    # live straggler attribution, before any deadline fired: rank 0's
    # stream names rank 1 with the step lag as the skew metric
    import trace_merge

    files = trace_merge.find_rank_files(tel)
    recs0 = trace_merge.load_records(files["metrics"][0])
    stragglers = [r for r in recs0 if r.get("kind") == "dist_event"
                  and r.get("action") == "straggler"]
    assert stragglers, "live detector never fired"
    assert stragglers[0]["rank"] == 1
    assert stragglers[0]["skew_frac"] >= 1
    counters = boxes[0]["counters"]
    assert counters.get("dist.straggler_suspects", 0) >= 1

    # the skew gate reads the same stream
    import perf_report

    path = files["metrics"][0][0]
    assert perf_report.check(path, max_step_skew_frac=0.5) == 1
    assert perf_report.check(path, max_step_skew_frac=10.0) == 0


def test_sigterm_drain_dumps_blackbox(tmp_path):
    # preempt@2 fires in BOTH ranks: each drains its resilient loop and
    # exits 0 — the gang completes "ok" with two sigterm_drain boxes
    res, tel = _retry(tmp_path, "drain", "preempt@2", fired=lambda r: r.ok)
    assert res.ok, res.workers
    boxes = _blackboxes(tel)
    assert set(boxes) == {0, 1}
    assert all(b["reason"] == "sigterm_drain" for b in boxes.values())
    for code, out, _err in res.workers:
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][0]
        assert json.loads(line[len("RESULT "):])["preempted"] is True


def test_crash_excepthook_dumps_blackbox(tmp_path):
    # device@2 with a zero retry budget: both ranks raise an uncaught
    # TransientDeviceError -> the telemetry excepthook dumps, then the
    # traceback prints and the worker dies unclassified (exit 1)
    res, tel = _retry(tmp_path, "crash", "device@2",
                      fired=lambda r: "TransientDeviceError" in _worker_stderr(r))
    assert not res.ok
    boxes = _blackboxes(tel)
    assert boxes, "no crash blackbox written"
    # both ranks inject at step 2, but one can lose the race and die on
    # the peer-failure path instead — at least one must be a crash dump,
    # and nothing else is a legal reason here
    reasons = {b["reason"] for b in boxes.values()}
    assert any(r.startswith("crash:TransientDeviceError") for r in reasons)
    assert all(r.startswith(("crash:TransientDeviceError", "peer_failure"))
               for r in reasons), reasons
