"""Chaos suite (ISSUE 3): every fault class the resilience layer claims
to survive — bad batch, NaN, transient device error, preemption — is
injected deterministically (paddle_tpu/faults.py) and must be survived
per its configured policy, with monitor counters asserting exactly how
many recoveries happened and end-state parity pinned bit-for-bit.
CPU-only, deterministic — runs in tier-1."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import monitor
from paddle_tpu.checkpoint_manager import CheckpointManager
from paddle_tpu.errors import (DataError, NumericError, PreemptionError,
                               TransientDeviceError, attach_context, classify)
from paddle_tpu.faults import FaultInjector, parse_fault_spec

# backoff-free policy: chaos tests must not sleep
FAST = dict(backoff_base_s=0.0)


def _build(seed=11):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 8, act="relu")
        h = fluid.layers.dropout(h, dropout_prob=0.3)  # exercises RNG rewind
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(h, 1), y))
        fluid.optimizer.Adam(1e-2).minimize(loss)
    startup.random_seed = seed
    main.random_seed = seed
    return main, startup, loss


def _feeds(n, batch=8):
    rng = np.random.RandomState(0)
    out = []
    for _ in range(n):
        xv = rng.rand(batch, 4).astype("f4")
        out.append({"x": xv, "y": xv.sum(1, keepdims=True)})
    return out


def _run_resilient(main, startup, loss, feeds, **kw):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    stats = fluid.resilient_train_loop(exe, main, lambda: list(feeds),
                                       [loss], scope=scope, **kw)
    return stats, scope


def _params(scope):
    return {n: np.asarray(scope.find_var(n)).copy()
            for n in scope.local_var_names()}


def _assert_state_equal(scope, ref, msg=""):
    for n, v in ref.items():
        np.testing.assert_array_equal(
            np.asarray(scope.find_var(n)), v,
            err_msg=f"{msg}: state var {n} diverged")


# --- taxonomy ---------------------------------------------------------------

def test_classify_taxonomy():
    assert isinstance(classify(NumericError("x")), NumericError)
    nan = classify(RuntimeError("fetch 'loss' contains NaN/Inf"))
    assert isinstance(nan, NumericError)
    assert isinstance(nan, RuntimeError)  # legacy catch sites keep working
    dev = classify(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert isinstance(dev, TransientDeviceError) and dev.resource_exhausted
    assert classify(RuntimeError("UNAVAILABLE: socket closed")).code == "UNAVAILABLE"
    # unmapped exceptions pass through untouched (sticky errors keep type)
    boring = ValueError("user bug")
    assert classify(boring) is boring
    # ... unless routed via the loader breadcrumb
    marked = attach_context(ValueError("bad row"), batch_index=7, phase="loader")
    ce = classify(marked)
    assert isinstance(ce, DataError) and ce.batch_index == 7
    assert ce.__cause__ is marked
    # wrap_unknown promotes leftovers to FatalError
    from paddle_tpu.errors import FatalError
    assert isinstance(classify(ValueError("x"), wrap_unknown=True), FatalError)
    # control-flow exceptions are never classified
    ki = KeyboardInterrupt()
    assert classify(ki, wrap_unknown=True) is ki


def test_fault_spec_grammar():
    faults = parse_fault_spec(
        " bad_batch@2; nan@5 ;device@7:RESOURCE_EXHAUSTED;preempt@9;")
    assert [(f.kind, f.at, f.arg) for f in faults] == [
        ("bad_batch", 2, None), ("nan", 5, None),
        ("device", 7, "RESOURCE_EXHAUSTED"), ("preempt", 9, None)]
    with pytest.raises(ValueError, match="kind@N"):
        parse_fault_spec("explode@3")
    with pytest.raises(ValueError, match="not an integer"):
        parse_fault_spec("nan@soon")
    inj = FaultInjector("bad_batch@1")
    with pytest.raises(DataError):
        inj.on_batch(1, {})
    assert inj.on_batch(1, {}) == {}  # fires exactly once
    assert inj.summary() == {"bad_batch": 1}


def test_injector_from_flags():
    fluid.set_flags({"FLAGS_fault_spec": "nan@3"})
    try:
        inj = FaultInjector.from_flags()
        assert [f.kind for f in inj.pending()] == ["nan"]
    finally:
        fluid.set_flags({"FLAGS_fault_spec": ""})
    assert FaultInjector.from_flags() is None


# --- fault class: bad batch -------------------------------------------------

def test_bad_batches_skipped_with_parity():
    main, startup, loss = _build()
    feeds = _feeds(10)
    monitor.reset()
    monitor.enable()
    try:
        stats, scope = _run_resilient(
            main, startup, loss, feeds, max_inflight=3,
            injector=FaultInjector("bad_batch@2;bad_batch@6"),
            policy=fluid.RetryPolicy(max_bad_batches=2, **FAST))
    finally:
        monitor.disable()
    assert stats.steps == 8 and stats.skipped_batches == 2
    assert monitor.counter("resilience.skipped_batches").value == 2
    assert monitor.counter("faults.bad_batch").value == 2
    # params identical to a fault-free run over the surviving batches
    surviving = [f for i, f in enumerate(feeds) if i not in (2, 6)]
    _, ref_scope = _run_resilient(main, startup, loss, surviving,
                                  max_inflight=3)
    _assert_state_equal(scope, _params(ref_scope), "bad-batch skip")


def test_bad_batch_budget_exhausted_raises():
    main, startup, loss = _build()
    with pytest.raises(DataError, match="injected bad batch"):
        _run_resilient(main, startup, loss, _feeds(8), max_inflight=2,
                       injector=FaultInjector("bad_batch@1;bad_batch@3"),
                       policy=fluid.RetryPolicy(max_bad_batches=1, **FAST))


# --- fault class: NaN -------------------------------------------------------

def test_nan_mode_raise_surfaces_numeric_error():
    main, startup, loss = _build()
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(NumericError, match="NaN/Inf"):
            _run_resilient(main, startup, loss, _feeds(8), max_inflight=2,
                           injector=FaultInjector("nan@3"))
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_nan_skip_step_parity():
    """The poisoned step's update is undone (state snapshot + RNG rewind),
    its batch dropped, and the run ends bit-identical to a fault-free run
    over the surviving batches — the ISSUE acceptance criterion."""
    main, startup, loss = _build()
    feeds = _feeds(10)
    monitor.reset()
    monitor.enable()
    try:
        stats, scope = _run_resilient(
            main, startup, loss, feeds, max_inflight=3,
            injector=FaultInjector("nan@4"), nan_mode="skip_step",
            policy=fluid.RetryPolicy(**FAST))
    finally:
        monitor.disable()
    assert stats.steps == 9 and stats.skipped_steps == 1
    assert stats.segments == 2
    assert monitor.counter("resilience.skipped_steps").value == 1
    events = [r for r in monitor.step_records()
              if r.get("kind") == "resilience_event"]
    assert [e["action"] for e in events] == ["skip_step"]
    assert events[0]["at_step"] == 4
    surviving = [f for i, f in enumerate(feeds) if i != 4]
    _, ref_scope = _run_resilient(main, startup, loss, surviving,
                                  max_inflight=3)
    _assert_state_equal(scope, _params(ref_scope), "nan skip_step")
    # the guard flag was force-enabled for the run, then restored
    assert fluid.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False


def test_nan_skip_step_budget_exhausted():
    main, startup, loss = _build()
    with pytest.raises(NumericError):
        _run_resilient(main, startup, loss, _feeds(10), max_inflight=2,
                       injector=FaultInjector("nan@1;nan@5"),
                       nan_mode="skip_step",
                       policy=fluid.RetryPolicy(max_skipped_steps=1, **FAST))


def test_nan_rollback_replays_to_full_parity(tmp_path):
    """Rollback restores the newest checkpoint at/before the failing step
    (never a later, already-poisoned one), rewinds the data stream via
    the factory, and — since the injected NaN fires once — the replay is
    clean: final params match an uninterrupted fault-free run."""
    main, startup, loss = _build()
    feeds = _feeds(12)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope,
                           save_every_steps=3)
    monitor.reset()
    monitor.enable()
    try:
        stats = fluid.resilient_train_loop(
            exe, main, lambda: list(feeds), [loss], scope=scope,
            injector=FaultInjector("nan@7"), nan_mode="rollback",
            checkpoint_manager=cm, policy=fluid.RetryPolicy(**FAST),
            max_inflight=3)
    finally:
        monitor.disable()
    assert stats.steps == 12 and stats.rollbacks == 1
    assert monitor.counter("resilience.rollbacks").value == 1
    _, ref_scope = _run_resilient(main, startup, loss, feeds, max_inflight=3)
    _assert_state_equal(scope, _params(ref_scope), "nan rollback")


def test_rollback_requires_factory_and_manager():
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(ValueError, match="checkpoint_manager"):
        fluid.resilient_train_loop(exe, main, iter(_feeds(2)), [loss],
                                   nan_mode="rollback")
    with pytest.raises(ValueError, match="factory"):
        fluid.resilient_train_loop(
            exe, main, iter(_feeds(2)), [loss], nan_mode="rollback",
            checkpoint_manager=CheckpointManager("/tmp/_unused_cm"))


# --- fault class: transient device error ------------------------------------

def test_transient_device_error_retried_with_parity():
    main, startup, loss = _build()
    feeds = _feeds(10)
    monitor.reset()
    monitor.enable()
    try:
        stats, scope = _run_resilient(
            main, startup, loss, feeds, max_inflight=3,
            injector=FaultInjector("device@5:UNAVAILABLE"),
            policy=fluid.RetryPolicy(**FAST))
    finally:
        monitor.disable()
    assert stats.steps == 10 and stats.retries == 1
    assert stats.degraded_inflight == 0  # UNAVAILABLE does not shed depth
    assert monitor.counter("resilience.retries").value == 1
    _, ref_scope = _run_resilient(main, startup, loss, feeds, max_inflight=3)
    _assert_state_equal(scope, _params(ref_scope), "device retry")


def test_oom_degrades_inflight_depth():
    main, startup, loss = _build()
    feeds = _feeds(10)
    monitor.reset()
    monitor.enable()
    try:
        stats, scope = _run_resilient(
            main, startup, loss, feeds, max_inflight=4,
            injector=FaultInjector("device@3:RESOURCE_EXHAUSTED"),
            policy=fluid.RetryPolicy(**FAST))
    finally:
        monitor.disable()
    assert stats.steps == 10 and stats.retries == 1
    assert stats.degraded_inflight == 1 and stats.final_max_inflight == 2
    assert monitor.counter("resilience.degraded_inflight").value == 1
    assert monitor.gauge("resilience.max_inflight").read() == 2
    _, ref_scope = _run_resilient(main, startup, loss, feeds, max_inflight=4)
    _assert_state_equal(scope, _params(ref_scope), "OOM degrade")


def test_device_retry_budget_exhausted():
    main, startup, loss = _build()
    with pytest.raises(TransientDeviceError):
        _run_resilient(main, startup, loss, _feeds(8), max_inflight=2,
                       injector=FaultInjector("device@1;device@3"),
                       policy=fluid.RetryPolicy(max_device_retries=1, **FAST))


def test_backoff_is_seeded_and_exponential():
    p = fluid.RetryPolicy(backoff_base_s=0.1, backoff_factor=2.0,
                          backoff_jitter=0.5, seed=3)
    a = [p.backoff_s(i) for i in range(3)]
    b = [p.backoff_s(i) for i in range(3)]
    assert a == b  # deterministic
    assert a[1] > a[0] and a[2] > a[1]  # grows despite jitter at these sizes
    for i, v in enumerate(a):
        assert abs(v - 0.1 * 2 ** i) <= 0.5 * 0.1 * 2 ** i + 1e-12
    assert fluid.RetryPolicy(backoff_base_s=0.0).backoff_s(5) == 0.0


# --- fault class: preemption ------------------------------------------------

def test_preemption_flush_and_resume_bit_identical(tmp_path):
    """The satellite acceptance test: a seeded run interrupted by injected
    SIGTERM flushes a snapshot (with RNG key + data position), and a
    fresh-process resume reaches bit-identical params to an uninterrupted
    run at the same step count."""
    main, startup, loss = _build()
    feeds = _feeds(12)
    # reference: uninterrupted
    _, ref_scope = _run_resilient(main, startup, loss, feeds, max_inflight=3)
    ref = _params(ref_scope)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    monitor.reset()
    monitor.enable()
    try:
        stats = fluid.resilient_train_loop(
            exe, main, lambda: list(feeds), [loss], scope=scope,
            injector=FaultInjector("preempt@5"), checkpoint_manager=cm,
            max_inflight=3)
    finally:
        monitor.disable()
    assert stats.preempted and stats.resume_step == 5
    assert stats.steps == 5
    assert monitor.counter("resilience.preemptions").value == 1
    assert stats.checkpoint_dir and os.path.isdir(stats.checkpoint_dir)
    with open(os.path.join(stats.checkpoint_dir, "RESUME.json")) as f:
        info = json.load(f)
    assert info["step"] == 5 and info["next_batch"] == 5

    # "new process": fresh scope + executor, restore and continue
    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.Scope()
    exe2.run(startup, scope=scope2)
    cm2 = CheckpointManager(str(tmp_path), program=main, scope=scope2)
    stats2 = fluid.resilient_train_loop(
        exe2, main, lambda: list(feeds), [loss], scope=scope2,
        checkpoint_manager=cm2, resume=True, max_inflight=3)
    assert stats2.steps == 12 and not stats2.preempted
    _assert_state_equal(scope2, ref, "preemption resume")


# --- the whole menagerie at once --------------------------------------------

def test_chaos_all_fault_classes_survived():
    """One run, one of each recoverable fault class, exact counter
    assertions, and end-state parity vs the fault-free run over the
    surviving batches (the ISSUE 3 acceptance criterion)."""
    main, startup, loss = _build()
    feeds = _feeds(14)
    spec = "bad_batch@2;nan@6;device@9:UNAVAILABLE;device@11:RESOURCE_EXHAUSTED"
    monitor.reset()
    monitor.enable()
    try:
        stats, scope = _run_resilient(
            main, startup, loss, feeds, max_inflight=3,
            injector=FaultInjector(spec), nan_mode="skip_step",
            policy=fluid.RetryPolicy(**FAST))
    finally:
        monitor.disable()
    # 14 batches - 1 bad batch - 1 skipped NaN step = 12 committed steps
    assert stats.steps == 12
    assert stats.skipped_batches == 1
    assert stats.skipped_steps == 1
    assert stats.retries == 2
    assert stats.degraded_inflight == 1 and stats.final_max_inflight == 1
    assert not stats.preempted
    c = monitor.get_monitor().counter_values()
    assert c["resilience.skipped_batches"] == 1
    assert c["resilience.skipped_steps"] == 1
    assert c["resilience.retries"] == 2
    assert c["resilience.degraded_inflight"] == 1
    assert c["faults.bad_batch"] == 1 and c["faults.nan"] == 1
    assert c["faults.device"] == 2
    actions = [r["action"] for r in monitor.step_records()
               if r.get("kind") == "resilience_event"]
    assert sorted(actions) == ["degrade_inflight", "retry", "retry",
                               "skip_batch", "skip_step"]
    # parity: fault-free run over surviving batches (raw batch 2 dropped
    # by the loader; step 6 — which consumed raw batch 7 after the bad
    # batch shifted the mapping — dropped with its NaN)
    surviving = [f for i, f in enumerate(feeds) if i not in (2, 7)]
    _, ref_scope = _run_resilient(main, startup, loss, surviving,
                                  max_inflight=3)
    _assert_state_equal(scope, _params(ref_scope), "chaos")


def test_resilient_loop_logged_steps_use_global_indices():
    main, startup, loss = _build()
    feeds = _feeds(9)
    seen = []
    stats, _ = _run_resilient(
        main, startup, loss, feeds, max_inflight=2, log_period=3,
        injector=FaultInjector("nan@4"), nan_mode="skip_step",
        policy=fluid.RetryPolicy(**FAST),
        on_logged=lambda s, v: seen.append(s))
    # 8 committed steps; global numbering survives the recovery restart
    assert stats.steps == 8
    assert seen == [0, 3, 6]


def test_perf_report_retry_frac_gate(tmp_path):
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent))
    from tools.perf_report import check, retry_fraction

    rows = [{"kind": "step", "recompiles_total": 0} for _ in range(10)]
    rows += [{"kind": "resilience_event", "action": "retry",
              "class": "TransientDeviceError", "at_step": 4},
             {"kind": "resilience_event", "action": "skip_batch",
              "class": "DataError", "at_batch": 2},
             {"kind": "resilience_event", "action": "degrade_inflight",
              "class": "TransientDeviceError", "at_step": 4}]
    assert retry_fraction(rows) == pytest.approx(0.2)  # degrade not counted
    path = tmp_path / "metrics.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert check(str(path), max_retry_frac=0.3) == 0
    assert check(str(path), max_retry_frac=0.1) == 1
    # healthy run with zero events passes
    bare = tmp_path / "bare.jsonl"
    bare.write_text("\n".join(json.dumps(r) for r in rows[:10]) + "\n")
    assert check(str(bare), max_retry_frac=0.0) == 0


def test_bad_batch_inside_inflight_window_of_nan():
    """Regression: a bad batch consumed inside the in-flight window of a
    later-failing step leaves a hole in the replay range; recovery must
    re-feed around the hole, not abort."""
    main, startup, loss = _build()
    feeds = _feeds(10)
    stats, scope = _run_resilient(
        main, startup, loss, feeds, max_inflight=3,
        injector=FaultInjector("nan@4;bad_batch@6"), nan_mode="skip_step",
        policy=fluid.RetryPolicy(**FAST))
    # 10 batches - 1 bad - 1 nan-skipped = 8 committed steps
    assert stats.steps == 8
    assert stats.skipped_batches == 1 and stats.skipped_steps == 1
    surviving = [f for i, f in enumerate(feeds) if i not in (4, 6)]
    _, ref_scope = _run_resilient(main, startup, loss, surviving,
                                  max_inflight=3)
    _assert_state_equal(scope, _params(ref_scope), "hole in replay window")


def test_skip_step_rejects_snapshot_state_false():
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    with pytest.raises(ValueError, match="snapshot_state"):
        fluid.resilient_train_loop(exe, main, iter([]), [loss],
                                   nan_mode="skip_step",
                                   snapshot_state=False)


def test_sigterm_after_last_dispatch_still_flushes(tmp_path):
    """Regression: a preemption notice landing after the final dispatch
    (tail drain) must still flush a checkpoint and report preempted, not
    be silently dropped with the loop 'completing'."""
    import signal

    main, startup, loss = _build()
    feeds = _feeds(6)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)

    def logged(s, v):
        if s == 5:  # resolution of the last step: all dispatches done
            os.kill(os.getpid(), signal.SIGTERM)

    stats = fluid.resilient_train_loop(
        exe, main, lambda: list(feeds), [loss], scope=scope,
        checkpoint_manager=cm, max_inflight=2, on_logged=logged)
    assert stats.steps == 6
    assert stats.preempted and stats.resume_step == 6
    assert stats.checkpoint_dir and os.path.isdir(stats.checkpoint_dir)


def test_resume_ignores_corrupt_newer_checkpoint_sidecar(tmp_path):
    """Regression: resume must read RESUME.json from the checkpoint that
    actually restored, not from a corrupt newer one restore walked past —
    a stale sidecar would misalign the data stream with the state."""
    main, startup, loss = _build()
    feeds = _feeds(12)
    _, ref_scope = _run_resilient(main, startup, loss, feeds, max_inflight=3)
    ref = _params(ref_scope)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    stats = fluid.resilient_train_loop(
        exe, main, lambda: list(feeds), [loss], scope=scope,
        injector=FaultInjector("preempt@5"), checkpoint_manager=cm,
        max_inflight=3)
    assert stats.preempted
    # plant a corrupt "newer" checkpoint whose sidecar points way ahead
    fake = tmp_path / "ckpt-0000000009"
    os.makedirs(str(fake))
    (fake / "RESUME.json").write_text(
        json.dumps({"step": 9, "next_batch": 9, "skipped_batches": 0}))
    # no STEP / manifest -> restore() walks past it to ckpt-5

    exe2 = fluid.Executor(fluid.CPUPlace())
    scope2 = fluid.Scope()
    exe2.run(startup, scope=scope2)
    cm2 = CheckpointManager(str(tmp_path), program=main, scope=scope2)
    stats2 = fluid.resilient_train_loop(
        exe2, main, lambda: list(feeds), [loss], scope=scope2,
        checkpoint_manager=cm2, resume=True, max_inflight=3)
    assert stats2.steps == 12
    _assert_state_equal(scope2, ref, "resume past corrupt sidecar")


def test_corrupt_chunk_training_within_budget(tmp_path):
    """ISSUE 5 acceptance: a RecordIO file with one corrupted chunk
    completes training with data.corrupt_chunks == 1 under budget, and
    aborts with a classified DataError when the budget is exceeded."""
    from paddle_tpu import reader as rd
    from paddle_tpu import recordio

    main, startup, loss = _build()
    p = str(tmp_path / "train.rio")
    recordio.write_arrays(
        p, [(np.full(4, i, "f4"),) for i in range(48)], max_chunk_records=6)

    def factory():
        def to_feed(samples):
            xv = np.stack([s[0] for s in samples])
            return {"x": xv, "y": xv.sum(1, keepdims=True)}

        return rd.map_readers(
            to_feed, rd.batch(recordio.reader_creator(p), 4, drop_last=True))

    inj = FaultInjector("corrupt_chunk@2")
    inj.on_files([p])
    fluid.set_flags({"FLAGS_data_corrupt_budget": 1})
    monitor.reset()
    monitor.enable()
    try:
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        stats = fluid.resilient_train_loop(
            exe, main, factory, [loss], scope=scope,
            policy=fluid.RetryPolicy(**FAST), max_inflight=3)
        # 48 samples - chunk 2's six = 42 -> 10 full batches of 4
        assert stats.steps == 10
        assert monitor.counter("data.corrupt_chunks").value == 1
    finally:
        monitor.disable()
        fluid.set_flags({"FLAGS_data_corrupt_budget": 0})

    # a second corrupt chunk blows the budget of 1: terminal DataError,
    # NOT one more skippable bad batch
    FaultInjector("corrupt_chunk@5").on_files([p])
    fluid.set_flags({"FLAGS_data_corrupt_budget": 1})
    try:
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        with pytest.raises(DataError, match="budget exceeded"):
            fluid.resilient_train_loop(
                exe, main, factory, [loss], scope=scope,
                policy=fluid.RetryPolicy(max_bad_batches=100, **FAST),
                max_inflight=3)
    finally:
        fluid.set_flags({"FLAGS_data_corrupt_budget": 0})


def test_classify_prefers_transient_code_over_loader_phase():
    """An XLA RESOURCE_EXHAUSTED raised in the producer thread is an HBM
    problem, not skippable data — the code match outranks the breadcrumb."""
    e = attach_context(RuntimeError("RESOURCE_EXHAUSTED: while staging"),
                       batch_index=3, phase="loader")
    ce = classify(e)
    assert isinstance(ce, TransientDeviceError) and ce.resource_exhausted


def test_dead_stream_after_producer_error_is_flagged(caplog):
    """A generator that raises mid-run ends the stream; the run must flag
    the early end instead of 'completing' silently."""
    import logging

    main, startup, loss = _build()
    feeds = _feeds(8)

    def dying_feeds():
        # a DataLoader/xmap producer marks its exceptions with the loader
        # breadcrumb before re-raising; simulate that contract directly
        for i, f in enumerate(feeds):
            if i == 5:
                raise attach_context(ValueError("generator bug at batch 5"),
                                     phase="loader")
            yield f

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    monitor.reset()
    monitor.enable()
    try:
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.resilience"):
            stats = fluid.resilient_train_loop(
                exe, main, lambda: dying_feeds(), [loss], scope=scope,
                max_inflight=2, policy=fluid.RetryPolicy(**FAST))
    finally:
        monitor.disable()
    assert stats.steps == 5 and stats.skipped_batches == 1
    assert monitor.counter("resilience.stream_died").value == 1
    assert "ended early" in caplog.text or "died mid-run" in caplog.text
