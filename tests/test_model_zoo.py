"""Model zoo smoke training: VGG, SE-ResNeXt, stacked dynamic LSTM build
and take optimizer steps (reference benchmark/fluid model list parity)."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.lod import LoDTensor
from paddle_tpu.models import vision


def _steps(main, startup, feeds, fetches, batches, n=3):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    out = []
    for i in range(n):
        (lv,) = exe.run(main, feed=batches[i % len(batches)],
                        fetch_list=[fetches["loss"]], scope=scope)
        out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out


def test_vgg_trains():
    main, startup, feeds, fetches = vision.build_vgg(
        class_dim=10, image_shape=(3, 32, 32), learning_rate=0.01)
    rng = np.random.RandomState(0)
    batches = [{"img": rng.rand(4, 3, 32, 32).astype("f4"),
                "label": rng.randint(0, 10, (4, 1)).astype("int64")}]
    losses = _steps(main, startup, feeds, fetches, batches)
    assert all(np.isfinite(losses))


def test_se_resnext_builds_and_steps():
    main, startup, feeds, fetches = vision.build_se_resnext(
        class_dim=10, image_shape=(3, 64, 64), learning_rate=0.05)
    types = [op.type for op in main.global_block().ops]
    assert types.count("conv2d") > 50  # grouped + SE structure present
    rng = np.random.RandomState(1)
    batches = [{"img": rng.rand(2, 3, 64, 64).astype("f4"),
                "label": rng.randint(0, 10, (2, 1)).astype("int64")}]
    losses = _steps(main, startup, feeds, fetches, batches, n=2)
    assert all(np.isfinite(losses))


def test_stacked_dynamic_lstm_converges():
    main, startup, feeds, fetches = vision.build_stacked_dynamic_lstm(
        vocab_size=200, emb_dim=16, hidden_dim=16, stacked_num=2,
        learning_rate=0.02)
    rng = np.random.RandomState(2)

    def batch():
        rows, labels = [], []
        for _ in range(8):
            lab = rng.randint(0, 2)
            lo, hi = (0, 100) if lab else (100, 200)
            length = rng.randint(3, 10)
            rows.append(rng.randint(lo, hi, (length, 1)).astype("int64"))
            labels.append([lab])
        return {"words": LoDTensor(rows), "label": np.asarray(labels, "int64")}

    batches = [batch() for _ in range(4)]
    losses = _steps(main, startup, feeds, fetches, batches, n=16)
    assert losses[-1] < losses[0], (losses[0], losses[-1])
