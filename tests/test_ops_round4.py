"""Round-4 op batch goldens: loss family, selu/lrn/maxout/affine_channel,
multiplex/reverse/diag, conv3d/pool3d, affine_grid/grid_sampler,
spectral_norm, row_conv, im2sequence, edit_distance.

Expected values are numpy transcriptions of the reference kernels
(paddle/fluid/operators/*_op.h) following the reference OpTest files
(tests/unittests/test_<op>_op.py)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.core.program import Program, program_guard

from op_test import OpTest




# --- loss family -----------------------------------------------------------

def test_hinge_loss_golden():
    rng = np.random.RandomState(101)
    x = rng.rand(10, 1).astype("float32")
    y = (rng.rand(10, 1) > 0.5).astype("float32")

    class T(OpTest):
        def setUp(self):
            self.op_type = "hinge_loss"
            self.inputs = {"Logits": x, "Labels": y}
            self.outputs = {"Loss": np.maximum(1 - x * (2 * y - 1), 0)}

    T().check_output()
    T().check_grad(["Logits"], "Loss")


def test_log_loss_golden():
    rng = np.random.RandomState(102)
    p = rng.uniform(0.05, 0.95, (12, 1)).astype("float32")
    y = (rng.rand(12, 1) > 0.5).astype("float32")
    eps = 1e-4

    class T(OpTest):
        def setUp(self):
            self.op_type = "log_loss"
            self.inputs = {"Predicted": p, "Labels": y}
            self.attrs = {"epsilon": eps}
            self.outputs = {"Loss": -y * np.log(p + eps) - (1 - y) * np.log(1 - p + eps)}

    T().check_output()
    T().check_grad(["Predicted"], "Loss")


def test_rank_loss_golden():
    rng = np.random.RandomState(103)
    label = (rng.rand(8, 1) > 0.5).astype("float32")
    left = rng.randn(8, 1).astype("float32")
    right = rng.randn(8, 1).astype("float32")

    class T(OpTest):
        def setUp(self):
            self.op_type = "rank_loss"
            self.inputs = {"Label": label, "Left": left, "Right": right}
            self.outputs = {
                "Out": np.log(1 + np.exp(left - right)) - label * (left - right)}

    T().check_output()
    T().check_grad(["Left", "Right"], "Out")


def test_margin_rank_loss_golden():
    rng = np.random.RandomState(104)
    label = np.where(rng.rand(9, 1) > 0.5, 1.0, -1.0).astype("float32")
    x1 = rng.randn(9, 1).astype("float32")
    x2 = rng.randn(9, 1).astype("float32")
    margin = 0.1
    out = np.maximum(-label * (x1 - x2) + margin, 0)

    class T(OpTest):
        def setUp(self):
            self.op_type = "margin_rank_loss"
            self.inputs = {"Label": label, "X1": x1, "X2": x2}
            self.attrs = {"margin": margin}
            self.outputs = {"Out": out, "Activated": (out > 0).astype("float32")}

    T().check_output()


def test_bpr_loss_golden():
    rng = np.random.RandomState(105)
    x = rng.randn(5, 4).astype("float32")
    lbl = rng.randint(0, 4, (5, 1)).astype("int64")
    expect = np.zeros((5, 1), "float32")
    for i in range(5):
        pos = lbl[i, 0]
        s = 0.0
        for j in range(4):
            if j == pos:
                continue
            s += -np.log(1.0 + np.exp(x[i, j] - x[i, pos]))
        expect[i, 0] = -s / 3.0

    class T(OpTest):
        def setUp(self):
            self.op_type = "bpr_loss"
            self.inputs = {"X": x, "Label": lbl}
            self.outputs = {"Y": expect}

    T().check_output(atol=1e-4)
    T().check_grad(["X"], "Y")


@pytest.mark.parametrize("red", ["none", "mean", "sum", "batchmean"])
def test_kldiv_loss_golden(red):
    rng = np.random.RandomState(106)
    x = rng.randn(4, 6).astype("float32")
    t = rng.uniform(-0.2, 1.0, (4, 6)).astype("float32")
    raw = np.where(t > 0, t * (np.log(np.where(t > 0, t, 1.0)) - x), 0.0)
    if red == "none":
        expect = raw
    elif red == "sum":
        expect = raw.sum()
    elif red == "batchmean":
        expect = raw.sum() / 4
    else:
        expect = raw.mean()

    class T(OpTest):
        def setUp(self):
            self.op_type = "kldiv_loss"
            self.inputs = {"X": x, "Target": t}
            self.attrs = {"reduction": red}
            self.outputs = {"Loss": np.asarray(expect, "float32")}

    T().check_output(atol=1e-5)


def test_modified_huber_loss_golden():
    rng = np.random.RandomState(107)
    x = rng.uniform(-2.5, 2.5, (10, 1)).astype("float32")
    y = (rng.rand(10, 1) > 0.5).astype("float32")
    inter = x * (2 * y - 1)
    loss = np.where(inter < -1, -4.0 * inter,
                    np.where(inter < 1, (1 - inter) ** 2, 0.0)).astype("float32")

    class T(OpTest):
        def setUp(self):
            self.op_type = "modified_huber_loss"
            self.inputs = {"X": x, "Y": y}
            self.outputs = {"Out": loss, "IntermediateVal": inter}

    T().check_output()


# --- activations / norms ---------------------------------------------------

def test_selu_golden():
    rng = np.random.RandomState(108)
    x = rng.randn(3, 5).astype("float32")
    alpha, scale = 1.6732632423543772, 1.0507009873554805
    expect = scale * np.where(x > 0, x, alpha * np.exp(x) - alpha)

    class T(OpTest):
        def setUp(self):
            self.op_type = "selu"
            self.inputs = {"X": x}
            self.outputs = {"Out": expect.astype("float32")}

    T().check_output()
    T().check_grad(["X"], "Out")


def test_lrn_golden():
    rng = np.random.RandomState(109)
    """Windowed-channel-sum transcription of lrn_op.cc LRNFunctor."""
    x = rng.rand(2, 6, 3, 3).astype("float32")
    n, k, alpha, beta = 5, 2.0, 1e-4, 0.75
    N, C, H, W = x.shape
    pre = (n - 1) // 2
    mid = np.full_like(x, k)
    sq = np.square(x)
    for c in range(C):
        lo = max(0, c - pre)
        hi = min(C, c - pre + n)
        mid[:, c] += alpha * sq[:, lo:hi].sum(axis=1)
    expect = x * np.power(mid, -beta)

    class T(OpTest):
        def setUp(self):
            self.op_type = "lrn"
            self.inputs = {"X": x}
            self.attrs = {"n": n, "k": k, "alpha": alpha, "beta": beta}
            self.outputs = {"Out": expect, "MidOut": mid}

    T().check_output(atol=1e-5)
    T().check_grad(["X"], "Out", max_relative_error=0.01)


def test_maxout_golden():
    rng = np.random.RandomState(110)
    x = rng.rand(2, 8, 3, 3).astype("float32")
    g = 4
    expect = x.reshape(2, 2, g, 3, 3).max(axis=2)

    class T(OpTest):
        def setUp(self):
            self.op_type = "maxout"
            self.inputs = {"X": x}
            self.attrs = {"groups": g}
            self.outputs = {"Out": expect}

    T().check_output()


def test_affine_channel_golden():
    rng = np.random.RandomState(111)
    x = rng.randn(2, 4, 3, 3).astype("float32")
    s = rng.randn(4).astype("float32")
    b = rng.randn(4).astype("float32")
    expect = x * s.reshape(1, 4, 1, 1) + b.reshape(1, 4, 1, 1)

    class T(OpTest):
        def setUp(self):
            self.op_type = "affine_channel"
            self.inputs = {"X": x, "Scale": s, "Bias": b}
            self.outputs = {"Out": expect}

    T().check_output()
    T().check_grad(["X"], "Out")


# --- tensor utilities ------------------------------------------------------

def test_multiplex_golden():
    rng = np.random.RandomState(112)
    xs = [rng.rand(6, 3).astype("float32") for _ in range(4)]
    ids = rng.randint(0, 4, (6, 1)).astype("int32")
    expect = np.stack([xs[ids[i, 0]][i] for i in range(6)])

    class T(OpTest):
        def setUp(self):
            self.op_type = "multiplex"
            self.inputs = {"X": [(f"x{i}", xs[i]) for i in range(4)],
                           "Ids": ids}
            self.outputs = {"Out": expect}

    T().check_output()


def test_reverse_golden():
    rng = np.random.RandomState(113)
    x = rng.rand(3, 4, 5).astype("float32")

    class T(OpTest):
        def setUp(self):
            self.op_type = "reverse"
            self.inputs = {"X": x}
            self.attrs = {"axis": [0, 2]}
            self.outputs = {"Out": x[::-1, :, ::-1].copy()}

    T().check_output()


def test_diag_golden():
    rng = np.random.RandomState(114)
    d = rng.rand(5).astype("float32")

    class T(OpTest):
        def setUp(self):
            self.op_type = "diag"
            self.inputs = {"Diagonal": d}
            self.outputs = {"Out": np.diag(d)}

    T().check_output()


# --- conv3d / pool3d -------------------------------------------------------

def _conv3d_ref(x, w, stride, pad):
    N, C, D, H, W = x.shape
    O, _, kd, kh, kw = w.shape
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad), (pad, pad)))
    od = (D + 2 * pad - kd) // stride + 1
    oh = (H + 2 * pad - kh) // stride + 1
    ow = (W + 2 * pad - kw) // stride + 1
    out = np.zeros((N, O, od, oh, ow), "float32")
    for d in range(od):
        for i in range(oh):
            for j in range(ow):
                patch = xp[:, :, d * stride:d * stride + kd,
                           i * stride:i * stride + kh, j * stride:j * stride + kw]
                out[:, :, d, i, j] = np.einsum("ncdhw,ocdhw->no", patch, w)
    return out


def test_conv3d_golden():
    rng = np.random.RandomState(115)
    x = rng.rand(2, 3, 5, 5, 5).astype("float32")
    w = (rng.randn(4, 3, 3, 3, 3) * 0.2).astype("float32")
    expect = _conv3d_ref(x, w, stride=1, pad=1)

    class T(OpTest):
        def setUp(self):
            self.op_type = "conv3d"
            self.inputs = {"Input": x, "Filter": w}
            self.attrs = {"strides": [1, 1, 1], "paddings": [1, 1, 1],
                          "dilations": [1, 1, 1], "groups": 1}
            self.outputs = {"Output": expect}

    T().check_output(atol=1e-4)
    # f32 finite differences on a conv-sized accumulation are pure rounding
    # noise (measured: fd=0 at delta 1e-3); gradient flow is covered by
    # test_conv3d_trains instead.


def test_conv3d_trains():
    rng = np.random.RandomState(120)
    main, startup = Program(), Program()
    startup.random_seed = 4
    with program_guard(main, startup):
        x = layers.data("x", [2, 4, 6, 6])
        y = layers.data("y", [1], dtype="int64")
        c = layers.conv3d(x, num_filters=4, filter_size=3, padding=1, act="relu")
        p = layers.pool3d(c, pool_size=2, pool_stride=2)
        flat = layers.reshape(p, [-1, 4 * 2 * 3 * 3])
        logits = layers.fc(flat, 3)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xv = rng.rand(6, 2, 4, 6, 6).astype("float32")
    yv = rng.randint(0, 3, (6, 1)).astype("int64")
    losses = []
    for _ in range(15):
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9


def test_pool3d_golden():
    rng = np.random.RandomState(116)
    x = rng.rand(2, 2, 4, 4, 4).astype("float32")
    expect = x.reshape(2, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))

    class T(OpTest):
        def setUp(self):
            self.op_type = "pool3d"
            self.inputs = {"X": x}
            self.attrs = {"pooling_type": "max", "ksize": [2, 2, 2],
                          "strides": [2, 2, 2], "paddings": [0, 0, 0]}
            self.outputs = {"Out": expect}

    T().check_output()


def test_pool3d_avg_global():
    rng = np.random.RandomState(117)
    x = rng.rand(2, 3, 3, 4, 5).astype("float32")
    expect = x.mean(axis=(2, 3, 4), keepdims=True)

    class T(OpTest):
        def setUp(self):
            self.op_type = "pool3d"
            self.inputs = {"X": x}
            self.attrs = {"pooling_type": "avg", "global_pooling": True,
                          "ksize": [1, 1, 1], "strides": [1, 1, 1],
                          "paddings": [0, 0, 0]}
            self.outputs = {"Out": expect}

    T().check_output(atol=1e-5)


# --- spatial transforms ----------------------------------------------------

def test_affine_grid_identity():
    rng = np.random.RandomState(118)
    """Identity theta yields the base [-1,1] meshgrid."""
    theta = np.tile(np.array([[[1, 0, 0], [0, 1, 0]]], "float32"), (2, 1, 1))
    h, w = 4, 5
    ys = np.linspace(-1, 1, h)
    xs = np.linspace(-1, 1, w)
    gx, gy = np.meshgrid(xs, ys)
    expect = np.tile(np.stack([gx, gy], -1)[None].astype("float32"), (2, 1, 1, 1))

    class T(OpTest):
        def setUp(self):
            self.op_type = "affine_grid"
            self.inputs = {"Theta": theta}
            self.attrs = {"output_shape": [2, 3, h, w]}
            self.outputs = {"Output": expect}

    T().check_output(atol=1e-6)


def test_grid_sampler_identity_grid_recovers_input():
    rng = np.random.RandomState(119)
    x = rng.rand(2, 3, 6, 6).astype("float32")
    h = w = 6
    ys = np.linspace(-1, 1, h)
    xs = np.linspace(-1, 1, w)
    gx, gy = np.meshgrid(xs, ys)
    grid = np.tile(np.stack([gx, gy], -1)[None].astype("float32"), (2, 1, 1, 1))

    class T(OpTest):
        def setUp(self):
            self.op_type = "grid_sampler"
            self.inputs = {"X": x, "Grid": grid}
            self.outputs = {"Output": x}

    T().check_output(atol=1e-5)


def test_grid_sampler_matches_numpy_bilinear():
    rng = np.random.RandomState(120)
    x = rng.rand(1, 2, 5, 7).astype("float32")
    grid = rng.uniform(-1.2, 1.2, (1, 3, 4, 2)).astype("float32")
    N, C, H, W = x.shape
    gx = (grid[..., 0] + 1) / 2 * (W - 1)
    gy = (grid[..., 1] + 1) / 2 * (H - 1)
    x0 = np.floor(gx)
    y0 = np.floor(gy)
    expect = np.zeros((N, C, 3, 4), "float32")
    for (dy, dx) in ((0, 0), (0, 1), (1, 0), (1, 1)):
        yi = y0 + dy
        xi = x0 + dx
        wgt = (1 - np.abs(gy - yi)) * (1 - np.abs(gx - xi))
        valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yi_c = np.clip(yi, 0, H - 1).astype(int)
        xi_c = np.clip(xi, 0, W - 1).astype(int)
        for n in range(N):
            v = x[n][:, yi_c[n], xi_c[n]] * (wgt[n] * valid[n])[None]
            expect[n] += v

    class T(OpTest):
        def setUp(self):
            self.op_type = "grid_sampler"
            self.inputs = {"X": x, "Grid": grid}
            self.outputs = {"Output": expect}

    T().check_output(atol=1e-5)


# --- spectral norm ---------------------------------------------------------

def test_spectral_norm_normalizes_largest_singular_value():
    rng = np.random.RandomState(121)
    w = rng.randn(6, 4).astype("float32")
    u = rng.randn(1, 6).astype("float32")
    v = rng.randn(1, 4).astype("float32")

    class T(OpTest):
        def setUp(self):
            self.op_type = "spectral_norm"
            self.inputs = {"Weight": w, "U": u, "V": v}
            self.attrs = {"dim": 0, "power_iters": 50, "eps": 1e-12}
            # after enough power iters sigma -> top singular value
            self.outputs = {"Out": w / np.linalg.svd(w, compute_uv=False)[0]}

    T().check_output(atol=1e-4)


def test_spectral_norm_layer_runs():
    rng = np.random.RandomState(122)
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [6])
        w = None
        fcout = layers.fc(x, 4)
        # normalize the fc weight through the layer surface
        wvar = next(v for v in main.list_vars() if v.persistable and ".w_" in v.name)
        out = layers.spectral_norm(wvar, dim=0, power_iters=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": np.zeros((2, 6), "float32")},
                     fetch_list=[out], scope=scope)
    sv = np.linalg.svd(np.asarray(got), compute_uv=False)[0]
    assert abs(sv - 1.0) < 0.2  # few iters: approximately unit spectral norm


# --- sequence utilities ----------------------------------------------------

def test_row_conv_golden():
    rng = np.random.RandomState(123)
    B, T, D = 2, 6, 3
    fc = 2  # future context
    x = rng.randn(B, T, D).astype("float32")
    w = rng.randn(fc + 1, D).astype("float32")
    expect = np.zeros_like(x)
    for t in range(T):
        for j in range(fc + 1):
            if t + j < T:
                expect[:, t] += x[:, t + j] * w[j]

    class T_(OpTest):
        def setUp(self):
            self.op_type = "row_conv"
            self.inputs = {"X": x, "Filter": w}
            self.outputs = {"Out": expect}

    T_().check_output(atol=1e-5)
    T_().check_grad(["X", "Filter"], "Out", max_relative_error=0.01)


def test_im2sequence_golden():
    rng = np.random.RandomState(124)
    x = rng.rand(2, 2, 4, 4).astype("float32")
    kh = kw = 2
    # stride 2, no padding: patches in row-major order
    expect = []
    for n in range(2):
        for i in range(2):
            for j in range(2):
                patch = x[n, :, i * 2:i * 2 + 2, j * 2:j * 2 + 2]
                expect.append(patch.reshape(-1))
    expect = np.stack(expect)

    class T(OpTest):
        def setUp(self):
            self.op_type = "im2sequence"
            self.inputs = {"X": x}
            self.attrs = {"kernels": [kh, kw], "strides": [2, 2],
                          "paddings": [0, 0, 0, 0]}
            self.outputs = {"Out": expect}

    T().check_output()


def _levenshtein(a, b):
    la, lb = len(a), len(b)
    d = np.zeros((la + 1, lb + 1))
    d[:, 0] = np.arange(la + 1)
    d[0, :] = np.arange(lb + 1)
    for i in range(1, la + 1):
        for j in range(1, lb + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return d[la, lb]


def test_edit_distance_golden():
    rng = np.random.RandomState(125)
    hyps = [[1, 2, 3, 4], [5, 6], [7, 7, 7]]
    refs = [[1, 3, 3], [5, 6, 7, 8], [7, 7, 7]]
    Th = max(len(h) for h in hyps)
    Tr = max(len(r) for r in refs)
    hyp = np.zeros((3, Th), "int64")
    ref = np.zeros((3, Tr), "int64")
    for i, h in enumerate(hyps):
        hyp[i, :len(h)] = h
    for i, r in enumerate(refs):
        ref[i, :len(r)] = r
    hl = np.array([len(h) for h in hyps], "int32")
    rl = np.array([len(r) for r in refs], "int32")
    expect = np.array([[_levenshtein(h, r)] for h, r in zip(hyps, refs)], "float32")

    class T(OpTest):
        def setUp(self):
            self.op_type = "edit_distance"
            self.inputs = {"Hyps": hyp, "Refs": ref, "HypsLen": hl, "RefsLen": rl}
            self.attrs = {"normalized": False}
            self.outputs = {"Out": expect}

    T().check_output(no_check_set=["SequenceNum"])


def test_edit_distance_layer_ragged():
    rng = np.random.RandomState(126)
    from paddle_tpu.lod import LoDTensor

    main, startup = Program(), Program()
    with program_guard(main, startup):
        hyp = layers.data("hyp", [1], dtype="int64", lod_level=1)
        ref = layers.data("ref", [1], dtype="int64", lod_level=1)
        dist, seq_num = layers.edit_distance(hyp, ref, normalized=True)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    hyps = [np.array([[1], [2], [3]], "int64"), np.array([[4], [5]], "int64")]
    refs = [np.array([[1], [3]], "int64"), np.array([[4], [5], [6]], "int64")]
    (d,) = exe.run(main, feed={"hyp": LoDTensor(hyps), "ref": LoDTensor(refs)},
                   fetch_list=[dist], scope=scope)
    # [1,2,3] vs [1,3]: 1 edit / 2; [4,5] vs [4,5,6]: 1 edit / 3
    np.testing.assert_allclose(np.asarray(d).reshape(-1), [0.5, 1 / 3], rtol=1e-5)


def test_pool3d_ceil_mode():
    rng = np.random.RandomState(130)
    x = rng.rand(1, 1, 8, 8, 8).astype("float32")
    # ceil mode keeps the last partial window: out dim = ceil((8-3)/2)+1 = 4
    # (floor mode would give 3); the trailing window is a partial [6:8] slice
    expect = np.zeros((1, 1, 4, 4, 4), "float32")
    for d in range(4):
        for i in range(4):
            for j in range(4):
                expect[0, 0, d, i, j] = x[0, 0, d*2:d*2+3, i*2:i*2+3, j*2:j*2+3].max()

    class T(OpTest):
        def setUp(self):
            self.op_type = "pool3d"
            self.inputs = {"X": x}
            self.attrs = {"pooling_type": "max", "ksize": [3, 3, 3],
                          "strides": [2, 2, 2], "paddings": [0, 0, 0],
                          "ceil_mode": True}
            self.outputs = {"Out": expect}

    T().check_output()


# --- late round-4 additions: ctc_greedy_decoder, chunk_eval ---------------

def test_ctc_greedy_decoder_golden():
    from paddle_tpu import LoDTensor

    # probs crafted so argmax = [1, 1, 0, 2, 2, 0] -> collapse -> [1, 2]
    T, C = 6, 3
    path = [1, 1, 0, 2, 2, 0]
    x = np.full((T, C), 0.1, "f4")
    for t, c in enumerate(path):
        x[t, c] = 0.9
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        inp = fluid.layers.data("x", [C], dtype="float32", lod_level=1)
        out = fluid.layers.ctc_greedy_decoder(inp, blank=0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": LoDTensor([x, x[:3]])},
                     fetch_list=[out], scope=scope)
    got = np.asarray(got)
    assert got[0, :2, 0].tolist() == [1, 2]
    assert got[1, :1, 0].tolist() == [1]  # first 3 steps: 1,1,0 -> [1]


def test_chunk_eval_iob_golden():
    from paddle_tpu import LoDTensor

    # IOB, 2 chunk types: tags B-0=0, I-0=1, B-1=2, I-1=3, O=4
    label = np.array([[0], [1], [4], [2], [3]], "int64")   # chunks (0-1, t0), (3-4, t1)
    pred = np.array([[0], [1], [4], [2], [4]], "int64")    # chunks (0-1, t0), (3-3, t1)
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        iv = fluid.layers.data("i", [1], dtype="int64", lod_level=1)
        lv = fluid.layers.data("l", [1], dtype="int64", lod_level=1)
        outs = fluid.layers.chunk_eval(iv, lv, "IOB", 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    res = exe.run(main, feed={"i": LoDTensor([pred]), "l": LoDTensor([label])},
                  fetch_list=list(outs), scope=scope)
    p, r, f1, ni, nl, nc = [np.asarray(v).reshape(-1)[0] for v in res]
    assert ni == 2 and nl == 2 and nc == 1  # only the t0 chunk matches
    np.testing.assert_allclose(p, 0.5)
    np.testing.assert_allclose(r, 0.5)
    np.testing.assert_allclose(f1, 0.5)


def test_dynamic_lstmp_shapes_and_training():
    from paddle_tpu import LoDTensor

    rng = np.random.RandomState(3)
    D, P = 4, 3
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 2
    with program_guard(main, startup):
        x = fluid.layers.data("x", [6], dtype="float32", lod_level=1)
        proj_in = fluid.layers.fc(x, 4 * D, num_flatten_dims=2)
        proj, cell = fluid.layers.dynamic_lstmp(proj_in, 4 * D, P)
        last = fluid.layers.sequence_pool(proj, "last")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(fluid.layers.square_error_cost(
            fluid.layers.fc(last, 1), y))
        fluid.optimizer.Adam(0.02).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rows = [rng.rand(5, 6).astype("f4"), rng.rand(3, 6).astype("f4")]
    tgt = np.array([[r.sum() * 0.05] for r in rows], "f4")
    losses = []
    for _ in range(40):
        out = exe.run(main, feed={"x": LoDTensor(rows), "y": tgt},
                      fetch_list=[loss, proj], scope=scope)
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    pv = np.asarray(out[1])
    assert pv.shape[-1] == P
    assert (pv[1, 3:] == 0).all()  # frozen past length
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_lstm_multilayer_bidirectional():
    rng = np.random.RandomState(4)
    b, T, I, D, L = 3, 5, 6, 4, 2
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 8
    with program_guard(main, startup):
        x = fluid.layers.data("x", [T, I], dtype="float32")
        h0 = fluid.layers.data("h0", [2 * L, 0, D], dtype="float32")
        c0 = fluid.layers.data("c0", [2 * L, 0, D], dtype="float32")
        out, lh, lc = fluid.layers.lstm(x, h0, c0, T, D, L, is_bidirec=True)
        loss = fluid.layers.mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": rng.rand(b, T, I).astype("f4"),
            "h0": np.zeros((2 * L, b, D), "f4"),
            "c0": np.zeros((2 * L, b, D), "f4")}
    o, h, c, l1 = exe.run(main, feed=feed, fetch_list=[out, lh, lc, loss],
                          scope=scope)
    assert np.asarray(o).shape == (b, T, 2 * D)
    assert np.asarray(h).shape == (2 * L, b, D)
    (l2,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
    assert np.isfinite(np.asarray(l2)).all()


def test_psroi_pool_golden():
    rng = np.random.RandomState(6)
    # C_in = oc * ph * pw = 2 * 2 * 2 = 8
    x = rng.randn(1, 8, 6, 6).astype("f4")
    rois = np.array([[0, 0, 5, 5]], "f4")

    def np_psroi(x, roi, oc, PH, PW, scale):
        _, C, H, W = x.shape
        out = np.zeros((oc, PH, PW), "f8")
        x0, y0 = round(roi[0]) * scale, round(roi[1]) * scale
        x1, y1 = (round(roi[2]) + 1) * scale, (round(roi[3]) + 1) * scale
        rh, rw = max(y1 - y0, 0.1), max(x1 - x0, 0.1)
        bh, bw = rh / PH, rw / PW
        for c in range(oc):
            for ph in range(PH):
                for pw in range(PW):
                    hs = min(max(int(np.floor(ph * bh + y0)), 0), H)
                    he = min(max(int(np.ceil((ph + 1) * bh + y0)), 0), H)
                    ws = min(max(int(np.floor(pw * bw + x0)), 0), W)
                    we = min(max(int(np.ceil((pw + 1) * bw + x0)), 0), W)
                    ch = (c * PH + ph) * PW + pw
                    if he <= hs or we <= ws:
                        continue
                    out[c, ph, pw] = x[0, ch, hs:he, ws:we].mean()
        return out

    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [8, 6, 6], dtype="float32")
        rv = fluid.layers.data("r", [4], dtype="float32")
        out = fluid.layers.psroi_pool(xv, rv, 2, 1.0, 2, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x, "r": rois}, fetch_list=[out],
                     scope=scope)
    np.testing.assert_allclose(np.asarray(got)[0],
                               np_psroi(x, rois[0], 2, 2, 2, 1.0),
                               rtol=1e-4, atol=1e-5)


def test_sequence_scatter_golden():
    from paddle_tpu import LoDTensor

    x = np.ones((2, 6), "f4")
    ids = [np.array([[1], [3], [1]], "int64"), np.array([[0]], "int64")]
    upd = [np.array([[1.0], [2.0], [3.0]], "f4"), np.array([[5.0]], "f4")]
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [6], dtype="float32")
        iv = fluid.layers.data("i", [1], dtype="int64", lod_level=1)
        uv = fluid.layers.data("u", [1], dtype="float32", lod_level=1)
        out = fluid.layers.sequence_scatter(xv, iv, uv)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x, "i": LoDTensor(ids),
                                 "u": LoDTensor(upd)},
                     fetch_list=[out], scope=scope)
    got = np.asarray(got)
    # row 0: +1 and +3 at col 1, +2 at col 3; row 1: +5 at col 0
    np.testing.assert_allclose(got[0], [1, 5, 1, 3, 1, 1])
    np.testing.assert_allclose(got[1], [6, 1, 1, 1, 1, 1])


def test_sampled_softmax_trains():
    rng = np.random.RandomState(8)
    C = 500
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 5
    with program_guard(main, startup):
        x = fluid.layers.data("x", [16], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        logits = fluid.layers.fc(x, C)
        loss = fluid.layers.mean(
            fluid.layers.sampled_softmax_with_cross_entropy(logits, y, 20))
        fluid.optimizer.Adam(0.02).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    yv = rng.randint(0, 8, (32, 1)).astype("int64")  # 8 live classes
    xv = np.zeros((32, 16), "f4")
    xv[np.arange(32), yv[:, 0]] = 2.0
    losses = []
    for _ in range(50):
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss],
                        scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_deformable_conv_zero_offset_equals_conv2d():
    """zero offsets + unit mask reduce deformable conv to plain conv2d."""
    rng = np.random.RandomState(9)
    x = rng.randn(2, 4, 7, 7).astype("f4")
    kh = kw = 3
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 6
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [4, 7, 7], dtype="float32")
        off = fluid.layers.data("off", [2 * kh * kw, 7, 7], dtype="float32")
        msk = fluid.layers.data("msk", [kh * kw, 7, 7], dtype="float32")
        dcn = fluid.layers.deformable_conv(
            xv, off, msk, 6, 3, padding=1,
            param_attr=fluid.ParamAttr(name="dcn_w"), bias_attr=False)
        ref = fluid.layers.conv2d(
            xv, 6, 3, padding=1,
            param_attr=fluid.ParamAttr(name="dcn_w"), bias_attr=False)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    feed = {"x": x, "off": np.zeros((2, 2 * kh * kw, 7, 7), "f4"),
            "msk": np.ones((2, kh * kw, 7, 7), "f4")}
    a, b = exe.run(main, feed=feed, fetch_list=[dcn, ref], scope=scope)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4,
                               atol=1e-4)


def test_deformable_conv_offsets_shift_sampling():
    """an integer offset of +1 in x equals sampling the shifted image, and
    the whole thing trains (grads flow to offsets too)."""
    rng = np.random.RandomState(10)
    x = rng.randn(1, 2, 6, 6).astype("f4")
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 2
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [2, 6, 6], dtype="float32")
        off_in = fluid.layers.data("off", [2 * 9, 6, 6], dtype="float32")
        dcn = fluid.layers.deformable_conv(
            xv, off_in, None, 3, 3, padding=1, modulated=False,
            bias_attr=False)
        loss = fluid.layers.mean(dcn)
        (g_off,) = fluid.calc_gradient(loss, [off_in])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    out0, g = exe.run(main, feed={
        "x": x, "off": np.zeros((1, 18, 6, 6), "f4")},
        fetch_list=[dcn, g_off], scope=scope)
    # offset grads exist and are finite (autodiff through bilinear coords)
    assert np.isfinite(np.asarray(g)).all()
    # +1 x-offset everywhere == conv over x shifted left by 1
    off1 = np.zeros((1, 18, 6, 6), "f4")
    off1[:, 1::2] = 1.0
    (out1,) = exe.run(main, feed={"x": x, "off": off1}, fetch_list=[dcn],
                      scope=scope)
    x_shift = np.zeros_like(x)
    x_shift[..., :-1] = x[..., 1:]
    (out_ref,) = exe.run(main, feed={
        "x": x_shift, "off": np.zeros((1, 18, 6, 6), "f4")},
        fetch_list=[dcn], scope=scope)
    got, ref = np.asarray(out1), np.asarray(out_ref)
    # interior columns match exactly (borders differ: zero-pad vs shift)
    np.testing.assert_allclose(got[..., 1:-2], ref[..., 1:-2], rtol=1e-4,
                               atol=1e-4)


def _np_tree_conv(nodes, edges, w, max_depth):
    """numpy transcription of math/tree2col.cc construct_patch + the
    interleaved eta accumulation."""
    N, F = nodes.shape
    tr = {}
    node_count = 0
    for u, v in edges:
        if u != 0 and v != 0:
            tr.setdefault(int(u), []).append(int(v))
            node_count += 1
    node_count += 1
    out_size, nf = w.shape[2], w.shape[3]
    out = np.zeros((N, out_size, nf))
    wflat = w.reshape(3 * F, out_size * nf)
    for root in range(1, node_count + 1):
        # DFS collecting (node, index, pclen, depth)
        patch = [(root, 1, 1, 0)]
        stack = [(root, 0)]
        visited = {root}
        while stack:
            node, depth = stack[-1]
            children = tr.get(node, [])
            advanced = False
            for i, v in enumerate(children):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, depth + 1))
                    patch.append((v, i + 1, len(children), depth + 1))
                    advanced = True
            if not advanced:
                stack.pop()
        vec = np.zeros(3 * F)
        for nd, idx, pclen, depth in patch:
            eta_t = (max_depth - depth) / max_depth
            temp = 0.5 if pclen == 1 else (idx - 1.0) / (pclen - 1.0)
            eta_l = (1 - eta_t) * temp
            eta_r = (1 - eta_t) * (1 - eta_l)
            f = nodes[nd - 1]
            vec[0::3] += eta_l * f
            vec[1::3] += eta_r * f
            vec[2::3] += eta_t * f
        out[root - 1] = (vec @ wflat).reshape(out_size, nf)
    return out


def test_tree_conv_golden_and_training():
    rng = np.random.RandomState(14)
    N, F, E = 6, 4, 5
    # tree: 1 -> (2, 3), 2 -> (4, 5)
    edges = np.zeros((1, E, 2), "int32")
    edges[0, :4] = [[1, 2], [1, 3], [2, 4], [2, 5]]
    nodes = rng.randn(1, N, F).astype("f4")

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 4
    with program_guard(main, startup):
        nv = fluid.layers.data("n", [N, F], dtype="float32")
        ev = fluid.layers.data("e", [E, 2], dtype="int32")
        out = fluid.layers.tree_conv(nv, ev, 3, 2, max_depth=2, act=None,
                                     bias_attr=False,
                                     param_attr=fluid.ParamAttr(name="tc_w"))
        loss = fluid.layers.mean(out)
        fluid.optimizer.SGD(0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    # owned copy, NOT np.asarray: that can be a zero-copy VIEW of the CPU
    # device buffer, which the next run DONATES — the SGD update then
    # rewrites the "snapshot" in place and the golden silently compares
    # against post-step weights (the donation-aliasing hazard class
    # core/analysis.py lint_donation documents)
    w = np.array(scope.find_var("tc_w"), copy=True)
    (got,) = exe.run(main, feed={"n": nodes, "e": edges}, fetch_list=[out],
                     scope=scope)
    expect = _np_tree_conv(nodes[0], edges[0], w, 2)
    np.testing.assert_allclose(np.asarray(got)[0], expect, rtol=1e-4,
                               atol=1e-4)
    (l2,) = exe.run(main, feed={"n": nodes, "e": edges}, fetch_list=[loss],
                    scope=scope)
    assert np.isfinite(np.asarray(l2)).all()


def test_tree_conv_depth3_golden():
    """exercises the multi-hop reach propagation (max_depth >= 3)."""
    rng = np.random.RandomState(16)
    N, F, E = 7, 3, 6
    # chain + branch: 1 -> (2, 3), 2 -> 4, 4 -> 5 (depth 3 from root), 3 -> 6
    edges = np.zeros((1, E, 2), "int32")
    edges[0, :5] = [[1, 2], [1, 3], [2, 4], [4, 5], [3, 6]]
    nodes = rng.randn(1, N, F).astype("f4")
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 9
    with program_guard(main, startup):
        nv = fluid.layers.data("n", [N, F], dtype="float32")
        ev = fluid.layers.data("e", [E, 2], dtype="int32")
        out = fluid.layers.tree_conv(nv, ev, 2, 2, max_depth=3, act=None,
                                     bias_attr=False,
                                     param_attr=fluid.ParamAttr(name="tc3_w"))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    w = np.asarray(scope.find_var("tc3_w"))
    (got,) = exe.run(main, feed={"n": nodes, "e": edges}, fetch_list=[out],
                     scope=scope)
    expect = _np_tree_conv(nodes[0], edges[0], w, 3)
    np.testing.assert_allclose(np.asarray(got)[0], expect, rtol=1e-4,
                               atol=1e-4)


def test_tree_conv_dygraph_matches_static():
    from paddle_tpu import dygraph

    rng = np.random.RandomState(15)
    edges = np.zeros((1, 4, 2), "int32")
    edges[0, :2] = [[1, 2], [1, 3]]
    nodes = rng.randn(1, 4, 3).astype("f4")
    with dygraph.guard():
        tc = dygraph.TreeConv(3, 2, 2, max_depth=2, act=None, bias_attr=False)
        dy = tc(dygraph.to_variable(nodes),
                dygraph.to_variable(edges)).numpy()
        w = np.asarray(tc.weight.value)
    expect = _np_tree_conv(nodes[0], edges[0], w, 2)
    np.testing.assert_allclose(dy[0], expect, rtol=1e-4, atol=1e-4)


def test_polygon_box_transform_golden():
    rng = np.random.RandomState(17)
    x = rng.randn(1, 8, 3, 4).astype("f4")
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [8, 3, 4], dtype="float32")
        out = fluid.layers.polygon_box_transform(xv)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    got = np.asarray(got)
    for g in range(8):
        for h in range(3):
            for w in range(4):
                ref = (4 * w - x[0, g, h, w]) if g % 2 == 0 else (4 * h - x[0, g, h, w])
                np.testing.assert_allclose(got[0, g, h, w], ref, rtol=1e-5)


def test_roi_perspective_transform_axis_aligned_identity():
    """an axis-aligned rect quad reduces the homography to plain bilinear
    resampling of that rect."""
    rng = np.random.RandomState(18)
    x = rng.rand(1, 2, 8, 8).astype("f4")
    # quad = rect (1,1)-(6,1)-(6,6)-(1,6), output 6x6 -> identity sampling
    rois = np.array([[1, 1, 6, 1, 6, 6, 1, 6]], "f4")
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [2, 8, 8], dtype="float32")
        rv = fluid.layers.data("r", [8], dtype="float32")
        out = fluid.layers.roi_perspective_transform(xv, rv, 6, 6, 1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x, "r": rois}, fetch_list=[out],
                     scope=scope)
    got = np.asarray(got)
    np.testing.assert_allclose(got[0, :, :6, :6], x[0, :, 1:7, 1:7],
                               rtol=1e-4, atol=1e-5)


def test_similarity_focus_golden():
    """numpy transcription of similarity_focus_op.h's greedy tagging."""
    rng = np.random.RandomState(19)
    x = rng.rand(2, 3, 4, 5).astype("f4")

    def np_ref(x, indexes):
        B, A, P, Q = x.shape
        out = np.zeros_like(x)
        for b in range(B):
            total = np.zeros((P, Q))
            for idx in indexes:
                plane = x[b, idx]
                order = np.argsort(-plane.reshape(-1))
                tag_p = np.zeros(P, bool)
                tag_q = np.zeros(Q, bool)
                for f in order:
                    p, q = f // Q, f % Q
                    if tag_p[p] or tag_q[q]:
                        continue
                    tag_p[p] = tag_q[q] = True
                    total[p, q] = 1.0
            out[b, :, :, :] = total[None]
        return out

    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [3, 4, 5], dtype="float32")
        out = fluid.layers.similarity_focus(xv, axis=1, indexes=[0, 2])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    np.testing.assert_allclose(np.asarray(got), np_ref(x, [0, 2]), atol=1e-6)


def test_roi_perspective_transform_masks_extrapolated_columns():
    """narrow quad: columns beyond the normalized width are zero
    (reference in_quad check)."""
    x = np.ones((1, 1, 10, 10), "f4")
    rois = np.array([[2, 2, 4, 2, 4, 8, 2, 8]], "f4")  # 2 wide, 6 tall
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [1, 10, 10], dtype="float32")
        rv = fluid.layers.data("r", [8], dtype="float32")
        out = fluid.layers.roi_perspective_transform(xv, rv, 7, 7, 1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x, "r": rois}, fetch_list=[out],
                     scope=scope)
    got = np.asarray(got)[0, 0]
    # nw = round(2 * 6 / 6) + 1 = 3: columns 0-2 sample, 3+ are zeroed
    assert (got[:, :3] > 0).all()
    assert (got[:, 3:] == 0).all()


def test_deformable_roi_pooling_zero_trans_is_average():
    """zero offsets + 1 sample per part: each bin averages its bilinear
    sample at the bin start (numpy transcription of the reference loop)."""
    rng = np.random.RandomState(20)
    x = rng.rand(1, 4, 8, 8).astype("f4")
    rois = np.array([[0, 0, 7, 7]], "f4")

    def np_ref(x, roi, PH, PW, S, scale):
        C, H, W = x.shape[1:]
        x0 = round(roi[0]) * scale - 0.5
        y0 = round(roi[1]) * scale - 0.5
        x1 = (round(roi[2]) + 1) * scale - 0.5
        y1 = (round(roi[3]) + 1) * scale - 0.5
        rw, rh = max(x1 - x0, 0.1), max(y1 - y0, 0.1)
        bw, bh = rw / PW, rh / PH
        swb, shb = bw / S, bh / S
        out = np.zeros((C, PH, PW))
        for c in range(C):
            for ph in range(PH):
                for pw in range(PW):
                    tot, n = 0.0, 0
                    for ih in range(S):
                        for iw in range(S):
                            w = pw * bw + x0 + iw * swb
                            h = ph * bh + y0 + ih * shb
                            if w < -0.5 or w > W - 0.5 or h < -0.5 or h > H - 0.5:
                                continue
                            w = min(max(w, 0), W - 1)
                            h = min(max(h, 0), H - 1)
                            xl, yl = int(np.floor(w)), int(np.floor(h))
                            xh, yh = min(xl + 1, W - 1), min(yl + 1, H - 1)
                            fx, fy = w - xl, h - yl
                            v = ((x[0, c, yl, xl] * (1 - fx) + x[0, c, yl, xh] * fx) * (1 - fy)
                                 + (x[0, c, yh, xl] * (1 - fx) + x[0, c, yh, xh] * fx) * fy)
                            tot += v
                            n += 1
                    out[c, ph, pw] = tot / n if n else 0.0
        return out

    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [4, 8, 8], dtype="float32")
        rv = fluid.layers.data("r", [4], dtype="float32")
        out = fluid.layers.deformable_roi_pooling(
            xv, rv, None, no_trans=True, pooled_height=2, pooled_width=2,
            sample_per_part=2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x, "r": rois}, fetch_list=[out],
                     scope=scope)
    np.testing.assert_allclose(np.asarray(got)[0],
                               np_ref(x, rois[0], 2, 2, 2, 1.0),
                               rtol=1e-4, atol=1e-5)


def test_deformable_roi_pooling_trans_shifts_and_grads():
    rng = np.random.RandomState(21)
    x = rng.rand(1, 4, 8, 8).astype("f4")
    rois = np.array([[0, 0, 7, 7]], "f4")
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [4, 8, 8], dtype="float32")
        rv = fluid.layers.data("r", [4], dtype="float32")
        tv = fluid.layers.data("t", [2, 2, 2], dtype="float32")
        out = fluid.layers.deformable_roi_pooling(
            xv, rv, tv, pooled_height=2, pooled_width=2, sample_per_part=2)
        loss = fluid.layers.mean(out)
        (g,) = fluid.calc_gradient(loss, [tv])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    t0 = np.zeros((1, 2, 2, 2), "f4")
    t1 = np.full((1, 2, 2, 2), 0.5, "f4")
    (o0,) = exe.run(main, feed={"x": x, "r": rois, "t": t0},
                    fetch_list=[out], scope=scope)
    o1, gv = exe.run(main, feed={"x": x, "r": rois, "t": t1},
                     fetch_list=[out, g], scope=scope)
    assert not np.allclose(np.asarray(o0), np.asarray(o1))
    assert np.isfinite(np.asarray(gv)).all()


def test_xxh64_published_vectors():
    from paddle_tpu.ops.misc_ops import _xxh64

    assert _xxh64(b"", 0) == 0xEF46DB3751D8E999
    assert _xxh64(b"a", 0) == 0xD24EC4F1A98C6E5B
    assert _xxh64(b"abc", 0) == 0x44BC2CF5AD770999
    # >= 32 bytes exercises the 4-lane path (published long-input vector)
    assert _xxh64(b"Nobody inspects the spammish repetition", 0) == \
        0xFBCEA83C8A378BF1


def test_hash_op_matches_spec():
    from paddle_tpu.ops.misc_ops import _xxh64

    x = np.array([[1, 2], [3, 4], [1, 2]], "int32")
    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        xv = fluid.layers.data("x", [2], dtype="int32")
        out = fluid.layers.hash(xv, hash_size=1000, num_hash=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (got,) = exe.run(main, feed={"x": x}, fetch_list=[out], scope=scope)
    got = np.asarray(got)
    for r in range(3):
        for j in range(3):
            assert got[r, j] == _xxh64(x[r].tobytes(), j) % 1000
    # identical rows hash identically; different rows differ somewhere
    assert (got[0] == got[2]).all() and not (got[0] == got[1]).all()
