"""nets.py composites + synthetic dataset corpus loaders."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import datasets, nets
from paddle_tpu.lod import LoDTensor


def test_datasets_shapes_and_determinism():
    a = list(datasets.uci_housing.train()())
    b = list(datasets.uci_housing.train()())
    assert len(a) == 404 and a[0][0].shape == (13,)
    np.testing.assert_array_equal(a[0][0], b[0][0])  # deterministic
    t = next(datasets.mnist.train()())
    assert t[0].shape == (784,) and 0 <= int(t[1]) <= 9
    s = next(datasets.imdb.train()())
    assert s[0].dtype == np.int64
    w = next(datasets.wmt14.train()())
    assert w[1][0] == 0 and w[2][-1] == 1  # bos / eos framing


def test_simple_img_conv_pool_and_glu():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 1
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [1, 12, 12], dtype="float32")
        cp = nets.simple_img_conv_pool(img, 4, 3, pool_size=2, pool_stride=2,
                                       conv_padding=1, act="relu")
        flat = fluid.layers.reshape(cp, [-1, 4 * 6 * 6])
        g = nets.glu(fluid.layers.fc(flat, 16), dim=1)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (gv,) = exe.run(main, feed={"img": np.ones((2, 1, 12, 12), "f4")},
                    fetch_list=[g], scope=scope)
    assert np.asarray(gv).shape == (2, 8)


def test_sequence_conv_pool_trains_on_imdb_sample():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 2
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", [1], dtype="int64", lod_level=1)
        label = fluid.layers.data("label", [1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=[5000, 16])
        feat = nets.sequence_conv_pool(emb, 16, 3, act="tanh")
        pred = fluid.layers.fc(feat, 1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(pred, label))
        fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    reader = datasets.imdb.train()
    batch, labels = [], []
    losses = []
    for i, (seq, lab) in enumerate(reader()):
        batch.append(seq.reshape(-1, 1))
        labels.append([float(lab)])
        if len(batch) == 16:
            (lv,) = exe.run(main, feed={"ids": LoDTensor(batch),
                                        "label": np.asarray(labels, "f4")},
                            fetch_list=[loss], scope=scope)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
            batch, labels = [], []
        if len(losses) >= 12:
            break
    assert losses[-1] < losses[0], losses
