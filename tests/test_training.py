"""End-to-end training tests (reference: tests/book/test_fit_a_line.py,
test_recognize_digits.py — train until loss threshold)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _fit_a_line(opt):
    main = fluid.Program()
    startup = fluid.Program()
    startup.random_seed = 42
    rng = np.random.RandomState(0)
    true_w = rng.rand(13, 1).astype("float32")
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [13], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for i in range(120):
        xv = rng.rand(32, 13).astype("float32")
        yv = xv @ true_w
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(lv[0]))
    assert losses[-1] < losses[0] * 0.2, f"no convergence: {losses[0]} -> {losses[-1]}"
    return losses


def test_fit_a_line_sgd():
    _fit_a_line(fluid.optimizer.SGD(learning_rate=0.05))


def test_fit_a_line_momentum():
    _fit_a_line(fluid.optimizer.Momentum(learning_rate=0.02, momentum=0.9))


def test_fit_a_line_adam():
    losses = _fit_a_line(fluid.optimizer.Adam(learning_rate=0.05))
    assert losses[-1] < 0.1


def test_fit_a_line_other_optimizers():
    for opt in [
        fluid.optimizer.Adagrad(learning_rate=0.3),
        fluid.optimizer.RMSProp(learning_rate=0.02),
        fluid.optimizer.Adamax(learning_rate=0.05),
        fluid.optimizer.Adadelta(learning_rate=1.0),
        fluid.optimizer.Lamb(learning_rate=0.02),
    ]:
        _fit_a_line(opt)


def test_regularization_changes_grads():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, size=1, bias_attr=False)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(
            learning_rate=0.1, regularization=fluid.regularizer.L2Decay(0.5)
        )
        opt.minimize(loss)
    # regularization must have inserted scale+sum ops before sgd
    types = [op.type for op in main.global_block().ops]
    assert "sum" in types and "backward" in types
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    exe.run(main, feed={"x": np.ones((2, 4), "float32"), "y": np.ones((2, 1), "float32")},
            fetch_list=[loss])


def test_mnist_mlp_converges():
    """Digit-recognition-style MLP on a synthetic separable task
    (reference: tests/book/test_recognize_digits.py mlp variant)."""
    main = fluid.Program()
    startup = fluid.Program()
    startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [64], dtype="float32")
        label = fluid.layers.data("label", [1], dtype="int64")
        h = fluid.layers.fc(img, size=32, act="relu")
        logits = fluid.layers.fc(h, size=4)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label)
        )
        acc = fluid.layers.accuracy(fluid.layers.softmax(logits), label)
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(3)
    protos = rng.randn(4, 64).astype("float32") * 2
    accs = []
    for i in range(100):
        lab = rng.randint(0, 4, size=(64, 1))
        xv = protos[lab[:, 0]] + rng.randn(64, 64).astype("float32") * 0.5
        lv, av = exe.run(main, feed={"img": xv, "label": lab}, fetch_list=[loss, acc])
        accs.append(float(av[0]))
    assert np.mean(accs[-10:]) > 0.9, f"poor accuracy: {np.mean(accs[-10:])}"


def test_conv_net_trains():
    """Small conv net (reference: test_recognize_digits.py conv variant)."""
    main = fluid.Program()
    startup = fluid.Program()
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", [1, 12, 12], dtype="float32")
        label = fluid.layers.data("label", [1], dtype="int64")
        c = fluid.layers.conv2d(img, num_filters=8, filter_size=3, act="relu")
        p = fluid.layers.pool2d(c, pool_size=2, pool_stride=2)
        bn = fluid.layers.batch_norm(p)
        flat = fluid.layers.reshape(bn, [-1, 8 * 5 * 5])
        logits = fluid.layers.fc(flat, size=2)
        loss = fluid.layers.mean(
            fluid.layers.softmax_with_cross_entropy(logits, label)
        )
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(5)
    losses = []
    for i in range(60):
        lab = rng.randint(0, 2, size=(16, 1))
        xv = rng.randn(16, 1, 12, 12).astype("float32") + lab[:, :, None, None] * 1.5
        (lv,) = exe.run(main, feed={"img": xv, "label": lab}, fetch_list=[loss])
        losses.append(float(lv[0]))
    assert losses[-1] < losses[0] * 0.5, f"{losses[0]} -> {losses[-1]}"


def test_clone_for_test_drops_optimizer():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.dropout(fluid.layers.fc(x, size=8), dropout_prob=0.5)
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        test_prog = main.clone(for_test=True)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    types = [op.type for op in test_prog.global_block().ops]
    assert "backward" not in types and "sgd" not in types
    # dropout must be in inference mode
    dropout_ops = [op for op in test_prog.global_block().ops if op.type == "dropout"]
    assert dropout_ops and dropout_ops[0].attr("is_test") is True
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.ones((3, 4), "float32")
    (a,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[pred])
    (b,) = exe.run(test_prog, feed={"x": xv}, fetch_list=[pred])
    np.testing.assert_allclose(a, b)  # deterministic in test mode
