"""Preemption-aware checkpoint manager (SURVEY §5.3 parity-plus)."""
import os
import signal

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.checkpoint_manager import CheckpointManager


def _model():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 6
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
        fluid.optimizer.Momentum(0.1, 0.9).minimize(loss)
    return main, startup, loss


def test_periodic_save_rotate_restore(tmp_path):
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope, keep=2,
                           save_every_steps=2)
    rng = np.random.RandomState(0)
    for _ in range(6):
        exe.run(main, feed={"x": rng.rand(4, 4).astype("f4"),
                            "y": rng.rand(4, 1).astype("f4")},
                fetch_list=[loss], scope=scope)
        cm.step()
    # steps 2,4,6 saved; keep=2 leaves {4, 6}
    assert cm.checkpoints() == ["ckpt-0000000004", "ckpt-0000000006"]

    params = {v.name: np.asarray(scope.find_var(v.name)).copy()
              for v in main.all_parameters()}
    # trash the scope, restore
    scope2 = fluid.Scope()
    exe.run(startup, scope=scope2)
    cm2 = CheckpointManager(str(tmp_path), program=main, scope=scope2)
    step = cm2.restore(scope=scope2)
    assert step == 6
    for n, v in params.items():
        np.testing.assert_allclose(np.asarray(scope2.find_var(n)), v, atol=1e-7)


def test_preemption_handler_flushes_snapshot(tmp_path):
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    cm._step = 41
    hits = []
    old = signal.signal(signal.SIGUSR1, lambda *a: hits.append(a))
    try:
        cm.install_preemption_handler(signals=(signal.SIGUSR1,))
        assert cm.checkpoints() == []
        os.kill(os.getpid(), signal.SIGUSR1)  # simulated preemption notice
        assert cm.checkpoints() == ["ckpt-0000000041"]
        assert hits  # previous handler chained (the re-raise contract)
    finally:
        cm.uninstall_preemption_handler()
        signal.signal(signal.SIGUSR1, old)


def test_half_written_save_is_ignored(tmp_path):
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    cm.save(step=1)
    os.makedirs(str(tmp_path / "ckpt-0000000002.tmp"))  # crashed mid-save
    assert cm.latest().endswith("ckpt-0000000001")
