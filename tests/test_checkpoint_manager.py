"""Preemption-aware checkpoint manager (SURVEY §5.3 parity-plus)."""
import os
import signal

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.checkpoint_manager import CheckpointManager


def _model():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 6
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
        fluid.optimizer.Momentum(0.1, 0.9).minimize(loss)
    return main, startup, loss


def test_periodic_save_rotate_restore(tmp_path):
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope, keep=2,
                           save_every_steps=2)
    rng = np.random.RandomState(0)
    for _ in range(6):
        exe.run(main, feed={"x": rng.rand(4, 4).astype("f4"),
                            "y": rng.rand(4, 1).astype("f4")},
                fetch_list=[loss], scope=scope)
        cm.step()
    # steps 2,4,6 saved; keep=2 leaves {4, 6}
    assert cm.checkpoints() == ["ckpt-0000000004", "ckpt-0000000006"]

    params = {v.name: np.asarray(scope.find_var(v.name)).copy()
              for v in main.all_parameters()}
    # trash the scope, restore
    scope2 = fluid.Scope()
    exe.run(startup, scope=scope2)
    cm2 = CheckpointManager(str(tmp_path), program=main, scope=scope2)
    step = cm2.restore(scope=scope2)
    assert step == 6
    for n, v in params.items():
        np.testing.assert_allclose(np.asarray(scope2.find_var(n)), v, atol=1e-7)


def test_preemption_handler_flushes_snapshot(tmp_path):
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    cm._step = 41
    hits = []
    old = signal.signal(signal.SIGUSR1, lambda *a: hits.append(a))
    try:
        cm.install_preemption_handler(signals=(signal.SIGUSR1,))
        assert cm.checkpoints() == []
        os.kill(os.getpid(), signal.SIGUSR1)  # simulated preemption notice
        assert cm.checkpoints() == ["ckpt-0000000041"]
        assert hits  # previous handler chained (the re-raise contract)
    finally:
        cm.uninstall_preemption_handler()
        signal.signal(signal.SIGUSR1, old)


def test_half_written_save_is_ignored(tmp_path):
    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    cm.save(step=1)
    os.makedirs(str(tmp_path / "ckpt-0000000002.tmp"))  # crashed mid-save
    assert cm.latest().endswith("ckpt-0000000001")


def test_save_not_reentrant_under_sigterm(tmp_path, monkeypatch):
    """A preemption notice landing mid-save() must not re-enter save on
    the half-written .tmp dir: the flush is deferred until the current
    save commits, then runs (ISSUE 3 satellite)."""
    from paddle_tpu import io as _io

    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    cm._step = 7
    depth = {"n": 0, "max": 0, "signals": 0}
    real_save = _io.save_sharded

    def save_with_signal(*a, **kw):
        depth["n"] += 1
        depth["max"] = max(depth["max"], depth["n"])
        if depth["signals"] == 0:
            depth["signals"] += 1
            os.kill(os.getpid(), signal.SIGUSR1)  # preemption mid-save
        try:
            return real_save(*a, **kw)
        finally:
            depth["n"] -= 1

    monkeypatch.setattr(_io, "save_sharded", save_with_signal)
    hits = []
    old = signal.signal(signal.SIGUSR1, lambda *a: hits.append(a))
    try:
        cm.install_preemption_handler(signals=(signal.SIGUSR1,))
        cm.save()
    finally:
        cm.uninstall_preemption_handler()
        signal.signal(signal.SIGUSR1, old)
    # never re-entered; the deferred flush ran as a SECOND, serial save
    # and chained the previous handler (the re-raise contract)
    assert depth["max"] == 1 and depth["signals"] == 1
    assert hits
    assert cm.checkpoints() == ["ckpt-0000000007"]
    assert not any(d.endswith(".tmp") for d in os.listdir(str(tmp_path)))
    # the committed checkpoint restores fine
    scope2 = fluid.Scope()
    exe.run(startup, scope=scope2)
    assert CheckpointManager(str(tmp_path), program=main,
                             scope=scope2).restore(scope=scope2) == 7


def test_restore_walks_past_corrupt_newest(tmp_path, caplog):
    """A corrupt newest checkpoint (missing STEP, unreadable shard) must
    not kill the resume: restore falls back to the previous valid one and
    logs what it skipped (ISSUE 3 satellite)."""
    import logging

    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    cm.save(step=1)
    good = {v.name: np.asarray(scope.find_var(v.name)).copy()
            for v in main.all_parameters()}
    exe.run(main, feed={"x": np.ones((4, 4), "f4"), "y": np.ones((4, 1), "f4")},
            fetch_list=[loss], scope=scope)
    cm.save(step=2)
    cm.save(step=3)
    os.remove(os.path.join(str(tmp_path), "ckpt-0000000003", "STEP"))
    manifest = os.path.join(str(tmp_path), "ckpt-0000000002",
                            "__sharded_manifest__.json")
    with open(manifest, "w") as f:
        f.write("{ truncated")

    scope2 = fluid.Scope()
    exe.run(startup, scope=scope2)
    cm2 = CheckpointManager(str(tmp_path), program=main, scope=scope2)
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.checkpoint"):
        step = cm2.restore(scope=scope2)
    assert step == 1
    assert "falling back" in caplog.text
    for n, v in good.items():
        np.testing.assert_array_equal(np.asarray(scope2.find_var(n)), v)

    # every candidate corrupt -> explicit error, not a silent None
    os.remove(os.path.join(str(tmp_path), "ckpt-0000000001", "STEP"))
    import pytest
    with pytest.raises(RuntimeError, match="no loadable checkpoint"):
        cm2.restore(scope=scope2)
    # max_step bounds the walk (rollback must not grab a later snapshot)
    assert CheckpointManager(str(tmp_path / "empty")).restore() is None


def test_checkpoint_carries_rng_state(tmp_path):
    """The scope's RNG key rides along in snapshots, so a restored run
    replays the exact random stream (rollback/resume determinism)."""
    from paddle_tpu.core.scope import RNG_STATE_VAR

    main, startup, loss = _model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    exe.run(main, feed={"x": np.ones((4, 4), "f4"), "y": np.ones((4, 1), "f4")},
            fetch_list=[loss], scope=scope)
    key = np.asarray(scope.find_var(RNG_STATE_VAR)).copy()
    cm = CheckpointManager(str(tmp_path), program=main, scope=scope)
    cm.save(step=5)
    scope2 = fluid.Scope()
    cm.restore(scope=scope2)
    np.testing.assert_array_equal(np.asarray(scope2.find_var(RNG_STATE_VAR)), key)
