"""Fleet API + DistributeTranspiler compat (reference fleet_base.py:37,
distribute_transpiler.py collective/nccl2 modes)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.fleet import Fleet, UserDefinedRoleMaker


def _model():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
    return main, startup, loss


def test_fleet_single_process_trains_on_global_mesh():
    f = Fleet()
    f.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    main, startup, loss = _model()
    with fluid.program_guard(main, startup):
        opt = f.distributed_optimizer(fluid.optimizer.SGD(0.1))
        ops, pg = opt.minimize(loss)  # reference 2-tuple contract
        compiled = opt.compiled_program
    assert compiled.mesh is not None and len(compiled.mesh.devices.flat) == 8
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(10):
        xv = rng.rand(16, 8).astype("f4")
        (lv,) = exe.run(compiled, feed={"x": xv, "y": xv.sum(1, keepdims=True)},
                        fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5
    assert f.is_first_worker() and f.worker_num() == 1


def test_transpiler_collective_mode_compiles_for_mesh():
    main, startup, loss = _model()
    with fluid.program_guard(main, startup):
        fluid.optimizer.SGD(0.1).minimize(loss)
    t = fluid.DistributeTranspiler()
    t.transpile(trainer_id=0, program=main, trainers=1)
    prog = t.get_trainer_program()
    assert prog.mesh is not None
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xv = np.ones((8, 8), "f4")
    (lv,) = exe.run(prog, feed={"x": xv, "y": xv.sum(1, keepdims=True)},
                    fetch_list=[loss], scope=scope)
    assert np.isfinite(np.asarray(lv)).all()


def test_transpiler_pserver_mode_raises_with_rationale():
    # a non-empty pservers list triggers the guard even with default config
    t = fluid.DistributeTranspiler()
    with pytest.raises(NotImplementedError, match="allreduce"):
        t.transpile(trainer_id=0, pservers="127.0.0.1:6000", trainers=2)
    cfg = fluid.DistributeTranspilerConfig()
    cfg.mode = "pserver"
    with pytest.raises(NotImplementedError, match="allreduce"):
        fluid.DistributeTranspiler(cfg).transpile(trainer_id=0, trainers=2)
    with pytest.raises(NotImplementedError, match="pserver"):
        fluid.DistributeTranspiler().get_pserver_program("127.0.0.1:6000")
