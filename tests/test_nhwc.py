"""Whole-model NHWC (channels-last) parity: the NHWC program must contain no
transpose ops and match the NCHW program's forward + training numerics with
the same parameters (reference data_format attr: conv_op.cc / pool_op.cc /
batch_norm_op.cc support NHWC kernels)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.models import resnet


def _build(data_format, depth=18, class_dim=7, hw=32, seed=1234):
    main, startup = (
        fluid.Program(),
        fluid.Program(),
    )
    startup.random_seed = seed
    shape = [3, hw, hw] if data_format == "NCHW" else [hw, hw, 3]
    with fluid.program_guard(main, startup):
        img = fluid.layers.data("img", shape, dtype="float32")
        label = fluid.layers.data("label", [1], dtype="int64")
        logits = resnet.resnet_imagenet(img, class_dim=class_dim, depth=depth,
                                        data_format=data_format)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, label))
        fluid.optimizer.Momentum(learning_rate=0.01, momentum=0.9).minimize(loss)
    return main, startup, loss


def test_nhwc_program_has_no_transposes():
    main, _, _ = _build("NHWC")
    types = [op.type for op in main.global_block().ops]
    assert "transpose" not in types and "transpose2" not in types
    assert types.count("conv2d") > 10


def test_nhwc_matches_nchw_training():
    rng = np.random.RandomState(0)
    img = rng.rand(4, 3, 32, 32).astype("float32")
    label = rng.randint(0, 7, size=(4, 1)).astype("int64")

    losses = {}
    for fmt in ("NCHW", "NHWC"):
        main, startup, loss = _build(fmt, seed=1234)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.scope.Scope()
        exe.run(startup, scope=scope)
        feed_img = img if fmt == "NCHW" else np.transpose(img, (0, 2, 3, 1))
        vals = []
        for _ in range(3):
            (lv,) = exe.run(main, feed={"img": feed_img, "label": label},
                            fetch_list=[loss], scope=scope)
            vals.append(float(np.asarray(lv).reshape(-1)[0]))
        losses[fmt] = vals
    # same params (seeded startup), same data => same losses in both layouts
    np.testing.assert_allclose(losses["NCHW"], losses["NHWC"], rtol=2e-4, atol=2e-4)


def test_nhwc_conv_pool_golden():
    """conv2d+pool2d NHWC vs numpy-free NCHW cross-check on random data."""
    rng = np.random.RandomState(3)
    x = rng.rand(2, 5, 9, 9).astype("float32")

    def run(fmt):
        main, startup = fluid.Program(), fluid.Program()
        startup.random_seed = 7
        shape = [5, 9, 9] if fmt == "NCHW" else [9, 9, 5]
        with fluid.program_guard(main, startup):
            inp = fluid.layers.data("x", shape, dtype="float32")
            c = fluid.layers.conv2d(inp, num_filters=6, filter_size=3, stride=2, padding=1,
                                    data_format=fmt)
            p = fluid.layers.pool2d(c, pool_size=2, pool_stride=2, pool_type="avg",
                                    data_format=fmt)
            g = fluid.layers.pool2d(p, global_pooling=True, pool_type="max", data_format=fmt)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.core.scope.Scope()
        exe.run(startup, scope=scope)
        feed = x if fmt == "NCHW" else np.transpose(x, (0, 2, 3, 1))
        (pv, gv) = exe.run(main, feed={"x": feed}, fetch_list=[p, g], scope=scope)
        pv = np.asarray(pv)
        gv = np.asarray(gv)
        if fmt == "NHWC":
            pv = np.transpose(pv, (0, 3, 1, 2))
            gv = np.transpose(gv, (0, 3, 1, 2))
        return pv, gv

    p_nchw, g_nchw = run("NCHW")
    p_nhwc, g_nhwc = run("NHWC")
    np.testing.assert_allclose(p_nchw, p_nhwc, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(g_nchw, g_nhwc, rtol=1e-5, atol=1e-5)
