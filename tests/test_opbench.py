"""tools/opbench.py smoke: the interleaved-A/B driver and the one-op CLI
path (reference role: operators/benchmark/op_tester.cc)."""
import json
import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])  # repo root for tools/

from tools import opbench


def test_interleave_stats_shape():
    calls = {"a": [], "b": []}

    def mk(name):
        def f():
            calls[name].append(1)
            return np.zeros(3)
        return f

    stats = opbench.interleave({"a": mk("a"), "b": mk("b")}, rounds=3, iters=2,
                               warmup=1)
    assert set(stats) == {"a", "b"}
    for s in stats.values():
        assert len(s["windows_ms"]) == 3
        assert s["best_ms"] <= s["median_ms"]
        assert s["spread_pct"] >= 0
    # warmup(1) + rounds*iters(6) dispatches per variant, interleaved equally
    assert len(calls["a"]) == len(calls["b"]) == 7


def test_op_dispatch_fwd_and_grad():
    import paddle_tpu as fluid

    d = opbench.build_op_dispatch(
        "relu", {"X": np.random.RandomState(0).randn(4, 8).astype("float32")},
        grad=True, place=fluid.CPUPlace())
    out = d()
    assert np.isfinite(np.asarray(out[0])).all()
    assert len(out) == 2  # loss + dX


def test_cli_json_line(capsys):
    opbench.main(["--op", "scale", "--input", "X=4x4", "--attr", "scale=2.0",
                  "--cpu", "--rounds", "2", "--iters", "2"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["op"] == "scale"
    assert rec["attrs"] == {"scale": 2.0}
    assert rec["best_ms"] > 0
