"""Sharded checkpoint: per-device-slice save + layout-preserving restore
(SURVEY §5.4; reference sliced-save precedent io.py:292
_save_distributed_persistables)."""
import glob
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh


def _train_a_bit(main, startup, loss, scope, exe, mesh=None, steps=3, seed=0):
    rng = np.random.RandomState(seed)
    prog = fluid.CompiledProgram(main).with_mesh(mesh) if mesh is not None else main
    for _ in range(steps):
        xv = rng.rand(16, 8).astype("f4")
        yv = xv.sum(1, keepdims=True).astype("f4")
        exe.run(prog, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)


def _model(seed=5):
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, 16, act="relu", param_attr=fluid.ParamAttr(name="w1"))
        pred = fluid.layers.fc(h, 1, param_attr=fluid.ParamAttr(name="w2"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return main, startup, loss


def test_sharded_roundtrip_preserves_shardings(tmp_path):
    mesh = make_mesh((4, 2), ("dp", "mp"))
    main, startup, loss = _model()
    # shard w1 over mp so the checkpoint really has per-device slices
    fluid.parallel.shard_parameters(main, {"w1": (None, "mp")})
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    _train_a_bit(main, startup, loss, scope, exe, mesh=mesh)

    before = {n: np.asarray(scope.find_var(n)) for n in ("w1", "w2")}
    ck = str(tmp_path / "ck")
    saved = fluid.io.save_sharded(ck, scope=scope, program=main)
    assert "w1" in saved and "w2" in saved
    # w1 must be stored as >1 slice files, none of them the full array
    w1_files = glob.glob(os.path.join(ck, "w1.*.npy"))
    assert len(w1_files) == 2  # mp=2 distinct slices (dp-replicated deduped)
    for f in w1_files:
        assert np.load(f).shape == (8, 8)  # (8,16) split over mp

    # restore into a fresh scope on the same mesh
    scope2 = fluid.Scope()
    exe.run(startup, scope=scope2)
    fluid.io.load_sharded(ck, scope=scope2, mesh=mesh)
    for n in ("w1", "w2"):
        np.testing.assert_allclose(np.asarray(scope2.find_var(n)), before[n], atol=1e-7)
    # layout restored without resharding
    v = scope2.find_var("w1")
    assert isinstance(v.sharding, NamedSharding)
    assert tuple(v.sharding.spec) == (None, "mp")

    # training resumes identically from the restored state
    _train_a_bit(main, startup, loss, scope, exe, mesh=mesh, steps=2, seed=9)
    _train_a_bit(main, startup, loss, scope2, exe, mesh=mesh, steps=2, seed=9)
    for n in ("w1", "w2"):
        np.testing.assert_allclose(np.asarray(scope.find_var(n)),
                                   np.asarray(scope2.find_var(n)), atol=1e-6)


def test_sharded_load_without_mesh_assembles_host_array(tmp_path):
    mesh = make_mesh((8,), ("mp",))
    t = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8),
                       NamedSharding(mesh, P("mp", None)))
    scope = fluid.Scope()
    scope.set_var("t", t)
    ck = str(tmp_path / "ck2")
    fluid.io.save_sharded(ck, var_names=["t"], scope=scope)
    scope2 = fluid.Scope()
    fluid.io.load_sharded(ck, scope=scope2)
    np.testing.assert_array_equal(np.asarray(scope2.find_var("t")),
                                  np.arange(64, dtype=np.float32).reshape(8, 8))


def test_sharded_load_onto_different_topology(tmp_path):
    """Shards saved from an 8-way layout restore onto a 2-way mesh: the
    region reader stitches overlapping slices."""
    mesh8 = make_mesh((8,), ("mp",))
    arr = np.random.RandomState(0).rand(16, 4).astype("f4")
    t = jax.device_put(jnp.asarray(arr), NamedSharding(mesh8, P("mp", None)))
    scope = fluid.Scope()
    scope.set_var("t", t)
    ck = str(tmp_path / "ck3")
    fluid.io.save_sharded(ck, var_names=["t"], scope=scope)

    mesh2 = make_mesh((2, 4), ("mp", "other"))
    scope2 = fluid.Scope()
    fluid.io.load_sharded(ck, scope=scope2, mesh=mesh2)
    got = scope2.find_var("t")
    np.testing.assert_allclose(np.asarray(got), arr, atol=0)
    assert tuple(got.sharding.spec) == ("mp", None)
