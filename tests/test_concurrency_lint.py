"""tools/concurrency_lint.py + paddle_tpu/core/locks.py: the concurrency
static-analysis CI gate (ISSUE 13) and its runtime half.

Covers: the golden whole-tree-is-clean gate, one planted defect per
diagnostic class (rank inversion, blocking-under-lock, unnamed raw lock,
unguarded shared write) each asserting the diagnostic names file:line and
the lock(s), the `# lock-ok:` allowlist contract, the lock-telemetry
counters, the classified lock-timeout error naming both locks, and the
perf_report --max-lock-wait-frac gate (zero-evidence-fails convention).
"""
import json
import os
import subprocess
import sys
import threading
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _run_lint(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "concurrency_lint.py"),
         *args],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)


def _run_perf(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_report.py"),
         *args],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)


# ---- the golden gate: the tree itself is clean ------------------------------

def test_whole_tree_is_clean_and_gated():
    r = _run_lint("--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CHECK OK" in r.stdout
    assert "0 errors" in r.stdout and "0 unnamed locks" in r.stdout
    # the rank table renders every registered lock class
    for name in ("serving.registry", "executor.build", "monitor.registry",
                 "dist.heartbeat", "inference.predictor"):
        assert name in r.stdout, f"rank table missing {name}"


def test_allowlist_ratchet_trips_when_lowered():
    # the ratchet works: pretending the allowlist budget is smaller than
    # the landed entries must fail the gate
    r = _run_lint("--check", "--max-allowlist", "0")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "allowlist" in r.stdout


# ---- planted defects: one per diagnostic class ------------------------------

def test_planted_rank_inversion_names_both_locks(tmp_path):
    p = tmp_path / "scratch_inv.py"
    p.write_text(
        "from paddle_tpu.core import locks\n"
        "A = locks.named_lock('scratch.outer', rank=10)\n"
        "B = locks.named_lock('scratch.inner', rank=20)\n"
        "def f():\n"
        "    with B:\n"
        "        with A:\n"           # line 6: rank 10 under rank 20
        "            pass\n")
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "lock_order_inversion" in r.stdout
    assert "scratch_inv:6" in r.stdout
    assert "scratch.outer" in r.stdout and "scratch.inner" in r.stdout
    assert "rank 10" in r.stdout and "rank 20" in r.stdout


def test_planted_blocking_under_lock_names_lock_and_line(tmp_path):
    p = tmp_path / "scratch_blk.py"
    p.write_text(
        "import time\n"
        "from paddle_tpu.core import locks\n"
        "L = locks.named_lock('scratch.hot', rank=10)\n"
        "def f():\n"
        "    with L:\n"
        "        time.sleep(1.0)\n")  # line 6
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "blocking_under_lock" in r.stdout
    assert "scratch_blk:6" in r.stdout
    assert "scratch.hot" in r.stdout


def test_planted_pr10_class_predictor_under_lock(tmp_path):
    # the mechanically encoded PR-10/PR-11 review findings: Predictor
    # construction / plan_model_bytes on the registry's lock
    p = tmp_path / "scratch_pr10.py"
    p.write_text(
        "from paddle_tpu.core import locks\n"
        "from paddle_tpu.inference import Predictor\n"
        "from paddle_tpu.serving.registry import plan_model_bytes\n"
        "class Reg:\n"
        "    def __init__(self):\n"
        "        self._lock = locks.named_lock('scratch.reg', rank=10)\n"
        "    def load(self, cfg, d):\n"
        "        with self._lock:\n"
        "            need = plan_model_bytes(d, 8)\n"   # line 9
        "            return Predictor(cfg), need\n")    # line 10
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "plan_model_bytes" in r.stdout and "Predictor" in r.stdout
    assert "scratch_pr10:9" in r.stdout and "scratch_pr10:10" in r.stdout
    assert "scratch.reg" in r.stdout


def test_planted_unnamed_raw_lock(tmp_path):
    p = tmp_path / "scratch_raw.py"
    p.write_text(
        "import threading\n"
        "L = threading.Lock()\n")     # line 2
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "unnamed_lock" in r.stdout
    assert "scratch_raw:2" in r.stdout
    assert "unnamed raw threading" in r.stdout


def test_unnamed_raw_lock_caught_through_module_alias(tmp_path):
    p = tmp_path / "scratch_alias.py"
    p.write_text(
        "import threading as th\n"
        "L = th.Lock()\n")
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "unnamed_lock" in r.stdout and "scratch_alias:2" in r.stdout


def test_pragma_in_docstring_does_not_count_toward_ratchet(tmp_path):
    p = tmp_path / "scratch_doc.py"
    p.write_text(
        '"""Module documenting the convention:\n'
        "put '# lock-ok: reason' on the with line.\n"
        '"""\n'
        "X = 1\n")
    r = _run_lint(str(p), "--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "allowlist_sites=0" in r.stdout


def test_unnamed_raw_lock_has_no_pragma_escape(tmp_path):
    # the unnamed-lock floor is zero, full stop: '# lock-ok:' allowlists
    # audited blocking-under-lock, never a raw primitive
    p = tmp_path / "scratch_sneaky.py"
    p.write_text(
        "import threading\n"
        "L = threading.Lock()  # lock-ok: sneaky\n")
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "unnamed_lock" in r.stdout


def test_planted_unguarded_lost_update(tmp_path):
    p = tmp_path / "scratch_race.py"
    p.write_text(
        "import threading\n"
        "from paddle_tpu.core import locks\n"
        "class Ledger:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "        self._lock = locks.named_lock('scratch.led', rank=10)\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "    def _loop(self):\n"
        "        self.n += 1\n"       # line 10: unlocked += in thread
        "    def bump(self):\n"
        "        self.n += 1\n")      # line 12: unlocked += from api
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "unguarded_shared_write" in r.stdout
    assert "Ledger.n" in r.stdout
    assert "lost-update" in r.stdout
    assert "thread:_loop@10" in r.stdout and "api@12" in r.stdout


def test_manual_acquire_release_tracks_held_stack(tmp_path):
    # acquire()/release() critical sections (try/finally style) must be
    # analyzed exactly like `with`: inversions and blocking inside them
    # cannot escape the gate
    p = tmp_path / "scratch_manual.py"
    p.write_text(
        "import time\n"
        "from paddle_tpu.core import locks\n"
        "A = locks.named_lock('scratch.m_outer', rank=9)\n"
        "B = locks.named_lock('scratch.m_inner', rank=1)\n"
        "def f():\n"
        "    A.acquire()\n"
        "    try:\n"
        "        with B:\n"           # line 8: rank 1 under rank 9
        "            pass\n"
        "        time.sleep(5)\n"     # line 10: blocking while A held
        "    finally:\n"
        "        A.release()\n"
        "    time.sleep(5)\n")        # line 13: after release — clean
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "lock_order_inversion" in r.stdout
    assert "scratch_manual:8" in r.stdout
    assert "blocking_under_lock" in r.stdout
    assert "scratch_manual:10" in r.stdout
    assert "scratch_manual:13" not in r.stdout  # release really popped


def test_locked_is_truthful_for_reentrant_holder():
    from paddle_tpu.core import locks

    rl = locks.named_rlock("test.locked_probe", rank=970)
    assert not rl.locked()
    with rl:
        assert rl.locked()  # a re-entrant probe would report False here
    assert not rl.locked()


def test_guarded_writes_and_pragma_are_clean(tmp_path):
    # the same shapes, done right: common named lock + an audited
    # `# lock-ok:` keep — zero diagnostics, allowlist counted
    p = tmp_path / "scratch_ok.py"
    p.write_text(
        "import threading, time\n"
        "from paddle_tpu.core import locks\n"
        "class Ledger:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "        self._lock = locks.named_lock('scratch.ok', rank=10)\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop).start()\n"
        "    def _loop(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def bump(self):\n"
        "        with self._lock:  # lock-ok: audited scratch keep\n"
        "            self.n += 1\n"
        "            time.sleep(0.0)\n")
    r = _run_lint(str(p), "--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "unguarded_shared_write" not in r.stdout
    assert "audited scratch keep" in r.stdout  # allowlist rendered
    assert "allowlist_sites=1" in r.stdout


def test_condition_wait_on_own_lock_is_legal(tmp_path):
    p = tmp_path / "scratch_cv.py"
    p.write_text(
        "import threading\n"
        "from paddle_tpu.core import locks\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._cv = locks.named_condition('scratch.cv', rank=10)\n"
        "        self._evt = threading.Event()\n"
        "    def take(self):\n"
        "        with self._cv:\n"
        "            self._cv.wait(0.05)\n"    # own lock: legal
        "    def bad(self):\n"
        "        with self._cv:\n"
        "            self._evt.wait(1.0)\n")   # line 12: other waitable
    r = _run_lint(str(p), "--check")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "scratch_cv:12" in r.stdout
    # exactly ONE blocking diagnostic: the own-lock wait did not fire
    assert "errors=1" in r.stdout
    assert "scratch_cv:9" not in r.stdout


# ---- runtime half: telemetry, timeout, registry ----------------------------

def test_lock_telemetry_counters():
    import paddle_tpu as fluid
    from paddle_tpu.core import locks
    from paddle_tpu.monitor import MONITOR

    was_enabled = MONITOR.enabled
    MONITOR.enable()
    fluid.set_flags({"FLAGS_lock_telemetry": True})
    try:
        lk = locks.named_lock("test.telemetry", rank=900)

        def worker():
            for _ in range(30):
                with lk:
                    time.sleep(0.001)

        ts = [threading.Thread(target=worker) for _ in range(3)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        c = MONITOR.counter_values()
        assert c["lock.test.telemetry.acquires"] == 90
        assert c["lock.test.telemetry.contended"] > 0
        assert c["lock.test.telemetry.wait_us"] > 0
        assert c["lock.test.telemetry.hold_us"] > 0
    finally:
        fluid.set_flags({"FLAGS_lock_telemetry": False})
        if not was_enabled:
            MONITOR.disable()


def test_lock_telemetry_observes_runtime_inversion():
    import paddle_tpu as fluid
    from paddle_tpu.core import locks
    from paddle_tpu.monitor import MONITOR

    was_enabled = MONITOR.enabled
    MONITOR.enable()
    fluid.set_flags({"FLAGS_lock_telemetry": True})
    try:
        lo = locks.named_lock("test.inv_lo", rank=901)
        hi = locks.named_lock("test.inv_hi", rank=902)
        before = MONITOR.counter("lock.order_inversions").value
        with hi:
            with lo:  # descending ranks: observed, never raised
                pass
        assert MONITOR.counter("lock.order_inversions").value == before + 1
    finally:
        fluid.set_flags({"FLAGS_lock_telemetry": False})
        if not was_enabled:
            MONITOR.disable()


def test_lock_timeout_raises_classified_error_naming_both_locks():
    import paddle_tpu as fluid
    from paddle_tpu import errors
    from paddle_tpu.core import locks

    a = locks.named_lock("test.timeout_a", rank=910)
    b = locks.named_lock("test.timeout_b", rank=911)
    release = threading.Event()

    def holder():
        with b:
            release.wait(5.0)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.05)
    fluid.set_flags({"FLAGS_lock_timeout_s": 0.2})
    try:
        with pytest.raises(errors.LockTimeoutError) as ei:
            with a:
                b.acquire()
        e = ei.value
        assert isinstance(e, errors.FatalError)  # classified, never retried
        assert e.wanted == "test.timeout_b" and e.wanted_rank == 911
        assert ("test.timeout_a", 910) in e.held
        msg = str(e)
        assert "test.timeout_b" in msg and "test.timeout_a" in msg
        assert "910" in msg and "911" in msg
    finally:
        fluid.set_flags({"FLAGS_lock_timeout_s": 0.0})
        release.set()
        t.join()


def test_duplicate_name_needs_same_rank():
    from paddle_tpu.core import locks

    locks.named_lock("test.dup", rank=920)
    locks.named_lock("test.dup", rank=920)  # same rank: a lock class
    with pytest.raises(ValueError):
        locks.named_lock("test.dup", rank=921)


def test_flag_toggle_mid_hold_does_not_strand_bookkeeping():
    # telemetry toggled OFF between acquire and release must not leave a
    # stale held-stack entry (it would poison this thread's later
    # inversion counts and timeout reports) or a stale hold start
    import paddle_tpu as fluid
    from paddle_tpu.core import locks
    from paddle_tpu.monitor import MONITOR

    was_enabled = MONITOR.enabled
    MONITOR.enable()
    lk = locks.named_lock("test.toggle", rank=940)
    try:
        fluid.set_flags({"FLAGS_lock_telemetry": True})
        lk.acquire()
        fluid.set_flags({"FLAGS_lock_telemetry": False})
        lk.release()
        assert locks.held_locks() == []
        # stale _t_hold must not leak into a wall-clock-sized hold_us
        # after re-enable
        time.sleep(0.05)
        fluid.set_flags({"FLAGS_lock_telemetry": True})
        with lk:
            pass
        hold = MONITOR.counter("lock.test.toggle.hold_us").value
        assert hold < 40_000, f"bogus hold_us {hold} from stale start"
    finally:
        fluid.set_flags({"FLAGS_lock_telemetry": False})
        if not was_enabled:
            MONITOR.disable()


def test_reentrant_hold_spans_first_acquire_to_last_release():
    import paddle_tpu as fluid
    from paddle_tpu.core import locks
    from paddle_tpu.monitor import MONITOR

    was_enabled = MONITOR.enabled
    MONITOR.enable()
    fluid.set_flags({"FLAGS_lock_telemetry": True})
    try:
        rl = locks.named_rlock("test.reent", rank=950)
        with rl:
            with rl:  # nested re-entry must not clobber the hold start
                time.sleep(0.02)
            time.sleep(0.02)
        hold = MONITOR.counter("lock.test.reent.hold_us").value
        assert hold >= 35_000, f"hold_us {hold} lost the outer span"
    finally:
        fluid.set_flags({"FLAGS_lock_telemetry": False})
        if not was_enabled:
            MONITOR.disable()


def test_condition_wait_reacquire_exempt_from_lock_timeout():
    # FLAGS_lock_timeout_s must not fire on Condition.wait's internal
    # lock re-acquisition — that would propagate with the lock UNHELD and
    # the enclosing with-block's release would mask the diagnostic
    import paddle_tpu as fluid
    from paddle_tpu.core import locks

    cv = locks.named_condition("test.cv_timeout", rank=960)
    fluid.set_flags({"FLAGS_lock_timeout_s": 0.05})
    try:
        got = []
        started = threading.Event()

        def waiter():
            with cv:  # enters while the cv is free: no entry contention
                started.set()
                got.append(cv.wait(0.2))

        t = threading.Thread(target=waiter)
        t.start()
        assert started.wait(5.0)
        # hold the cv across the waiter's wait-timeout: its REACQUIRE
        # queues behind us for ~0.1s > FLAGS_lock_timeout_s=0.05 — the
        # exemption is what keeps that from raising inside wait()
        with cv:
            time.sleep(0.3)
        t.join(5.0)
        assert got == [False], got  # timed-out wait returned, no raise
    finally:
        fluid.set_flags({"FLAGS_lock_timeout_s": 0.0})


def test_init_health_rearm_on_world_resize(tmp_path, monkeypatch):
    # a second init_health with a DIFFERENT world must re-arm against the
    # new membership, never hand back the stale watchdog
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    from paddle_tpu import dist_resilience as dr

    dr.shutdown_health()
    try:
        wd2 = dr.init_health(0, 2)
        assert dr.active_heartbeat().world == 2
        wd3 = dr.init_health(0, 3)
        assert wd3 is not wd2
        assert dr.active_heartbeat().world == 3
        assert dr.init_health(0, 3) is wd3  # idempotent at same membership
    finally:
        dr.shutdown_health()


def test_disabled_mode_is_raw_lock_fast_path():
    # with telemetry and timeout off, acquire must not touch per-thread
    # state (the held stack stays empty) — the hot-path budget
    from paddle_tpu.core import locks

    lk = locks.named_lock("test.fast", rank=930)
    with lk:
        assert locks.held_locks() == []


# ---- perf_report --max-lock-wait-frac ---------------------------------------

def _snapshot_line(counters):
    return json.dumps({"kind": "snapshot", "ts": time.time(),
                       "counters": counters, "gauges": {}, "spans": {}})


def test_perf_report_lock_gate_trips_on_contention(tmp_path):
    p = tmp_path / "metrics.jsonl"
    p.write_text(_snapshot_line({
        "lock.serving.registry.acquires": 100,
        "lock.serving.registry.contended": 80,
        "lock.serving.registry.wait_us": 900_000,
        "lock.serving.registry.hold_us": 100_000}) + "\n")
    r = _run_perf("--check", str(p), "--max-lock-wait-frac", "0.5")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "lock wait fraction 0.9000" in r.stdout
    assert "serving.registry" in r.stdout  # names the worst lock


def test_perf_report_lock_gate_passes_quiet_locks(tmp_path):
    p = tmp_path / "metrics.jsonl"
    p.write_text(_snapshot_line({
        "lock.executor.cache.acquires": 1000,
        "lock.executor.cache.contended": 1,
        "lock.executor.cache.wait_us": 50,
        "lock.executor.cache.hold_us": 10_000}) + "\n")
    r = _run_perf("--check", str(p), "--max-lock-wait-frac", "0.2")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lock wait fraction" in r.stdout


def test_perf_report_lock_gate_fails_on_zero_evidence(tmp_path):
    # the zero-evidence-fails convention: no lock.* counters anywhere
    p = tmp_path / "metrics.jsonl"
    p.write_text(_snapshot_line({"executor.steps": 5}) + "\n")
    r = _run_perf("--check", str(p), "--max-lock-wait-frac", "0.5")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "no lock.* counters" in r.stdout


# ---- audit-fix regressions (satellite: findings fixed this PR) --------------

def test_publish_ladders_serialize_per_model(monkeypatch):
    # two concurrent publishes into one model must run their ladders one
    # at a time (in-flight marker under serving.publish) — and the marker
    # is held WITHOUT any lock across the ladder, so a second model's
    # publish is free to proceed
    from paddle_tpu.serving import publisher
    from paddle_tpu.serving.registry import ModelRegistry

    reg = ModelRegistry()
    events = []
    ev_lock = threading.Lock()

    def fake_ladder(registry, name, src, *a, **kw):
        with ev_lock:
            events.append(("start", name))
        time.sleep(0.05)
        with ev_lock:
            events.append(("end", name))
        return "v-" + name

    monkeypatch.setattr(publisher, "_publish_ladder", fake_ladder)
    ts = [threading.Thread(target=publisher.publish, args=(reg, "m", "/x"))
          for _ in range(3)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    # strict alternation: every start is followed by its own end
    for i in range(0, len(events), 2):
        assert events[i][0] == "start" and events[i + 1][0] == "end", events
    assert len(events) == 6
    assert not reg._publishing  # marker always cleared


def test_init_health_concurrent_racers_converge(tmp_path, monkeypatch):
    # regression for the blocking-under-lock fix: heartbeat construction
    # (socket/dir I/O, thread start) now happens OUTSIDE _HEALTH_LOCK;
    # racing initializers must still converge on ONE watchdog and leak
    # no loser heartbeats
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    from paddle_tpu import dist_resilience as dr

    dr.shutdown_health()
    results = []

    def racer():
        results.append(dr.init_health(0, 2))

    ts = [threading.Thread(target=racer) for _ in range(4)]
    try:
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert len(results) == 4
        assert all(r is results[0] for r in results), \
            "racing init_health calls returned different watchdogs"
        assert dr.active_watchdog() is results[0]
    finally:
        dr.shutdown_health()
    # the losers' beat threads were stopped: no pt-heartbeat thread left
    time.sleep(0.1)
    assert not [t for t in threading.enumerate()
                if t.name == "pt-heartbeat"]


def test_heartbeat_observe_poll_rate_limit_is_guarded(tmp_path, monkeypatch):
    # regression for the unguarded _last_poll read-modify-write: the
    # rate-limit decision is now taken under the table lock, so N
    # concurrent observers perform ONE transport poll per window
    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path))
    from paddle_tpu.dist_resilience import Heartbeat, HeartbeatConfig

    hb = Heartbeat(0, 2, config=HeartbeatConfig(interval_s=10.0))
    polls = []
    orig = hb.transport.poll
    hb.transport.poll = lambda: (polls.append(1), orig())[1]
    try:
        hb.observe()          # first call past the -inf init: polls
        n_first = len(polls)
        ts = [threading.Thread(target=hb.observe) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert n_first == 1
        assert len(polls) == 1, \
            f"{len(polls)} transport polls inside one rate-limit window"
    finally:
        hb.stop()
