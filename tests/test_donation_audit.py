"""tools/donation_audit.py: the static buffer-donation audit over compiled
train steps, the planted-defect classes it must catch, and the bench-side
frozen-vs-subresolution param classification it informs (ISSUE 7's
resolution of BENCH_r05's '18/198 BERT params frozen').

Also covers the ratcheted bench-round gate (perf_report --check-bench) and
the warmup-until-stable bench windowing (tools/bench_kit.timed_steps),
which together make the MFU floors trustworthy."""
import json

import numpy as np
import pytest

from tools import donation_audit as da


# --------------------------------------------------------------------------
# the zoo donates everything (the ISSUE-7 acceptance gate, tier-1-wired)
# --------------------------------------------------------------------------


def test_zoo_donates_every_persistable_update():
    """Zero non-donated persistable updates across the model zoo — the
    static proof that BENCH_r05's 18 'frozen' BERT params were a probe
    artifact (sub-bf16-resolution updates), not a donation drop."""
    reports = da.audit_zoo(tiny=True)
    assert sorted(reports) == ["bert", "deepfm", "mnist", "nmt", "resnet50"]
    for name, r in reports.items():
        assert r["clean"], (name, r)
        assert r["donated"] == r["persistable_written"] > 0, (name, r)


def test_check_cli_exit_codes(capsys):
    assert da.main(["--check", "--tiny", "--program", "mnist"]) == 0
    out = capsys.readouterr()
    assert "OK" in out.err


# --------------------------------------------------------------------------
# planted defects: each non-donated class must be named
# --------------------------------------------------------------------------


def _mlp_program():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y))
        fluid.optimizer.Adam(1e-3).minimize(loss)
    return main, startup, loss


def _feed():
    rng = np.random.RandomState(0)
    return {"x": rng.rand(4, 8).astype("f4"),
            "y": rng.rand(4, 1).astype("f4")}


def test_clean_mlp_baseline():
    main, startup, loss = _mlp_program()
    r = da.audit_program(main, startup, _feed(), [loss.name])
    assert not r["copied_not_read"] and not r["copied_aval_drift"]
    assert not r["never_updated"]
    assert r["donated"] == r["persistable_written"]


def test_written_but_never_read_is_flagged():
    """A persistable written without being read sits outside the donation
    set entirely — the silently-double-buffered class."""
    main, startup, loss = _mlp_program()
    block = main.global_block()
    v = block.create_var("aux_counter", shape=(1,), dtype="float32",
                         persistable=True)
    # write it from a fresh constant: written, never read
    c = block.create_var("aux_src")
    block.append_op("fill_constant", inputs={}, outputs={"Out": [c.name]},
                    attrs={"shape": [1], "dtype": "float32", "value": 1.0})
    block.append_op("assign", inputs={"X": [c.name]},
                    outputs={"Out": [v.name]}, attrs={})
    r = da.audit_program(main, startup, _feed(), [loss.name])
    assert "aux_counter" in r["copied_not_read"]
    assert not r["clean"] if "clean" in r else True


def test_aval_drift_is_flagged():
    """A read+written persistable whose written dtype differs from the
    resident buffer cannot be aliased by XLA — the r5 bf16+Adam freeze
    class (optimizer lowerings now pin their output dtypes, so the plant
    needs an explicit cast writing back over the var)."""
    main, startup, loss = _mlp_program()
    # startup initializes `drifter` as f32; the main block declares it f16
    # and cast-writes it in place, so the step reads f32 and writes f16
    startup.global_block().create_var("drifter", shape=(4,), dtype="float32",
                                      persistable=True)
    startup.global_block().append_op(
        "fill_constant", inputs={}, outputs={"Out": ["drifter"]},
        attrs={"shape": [4], "dtype": "float32", "value": 1.0})
    block = main.global_block()
    block.create_var("drifter", shape=(4,), dtype="float16",
                     persistable=True)
    block.append_op("cast", inputs={"X": ["drifter"]},
                    outputs={"Out": ["drifter"]},
                    attrs={"out_dtype": "float16", "in_dtype": "float16"})
    r = da.audit_program(main, startup, _feed(), [loss.name])
    assert "drifter" in r["copied_aval_drift"], r


def test_never_updated_param_is_flagged():
    """A trainable param the optimizer does not touch is genuinely frozen
    (vs. the bench probe's sub-resolution artifact)."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = main.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        used = fluid.layers.fc(x, 1)
        fluid.layers.fc(x, 1)  # params exist, excluded from the update
        loss = fluid.layers.mean(fluid.layers.square_error_cost(used, y))
        fluid.optimizer.Adam(1e-3).minimize(
            loss, parameter_list=[p.name for p in main.all_parameters()
                                  if p.name.startswith("fc_0")])
    r = da.audit_program(main, startup, _feed(), [loss.name])
    assert r["never_updated"], r
    assert any(n.startswith("fc_1") for n in r["never_updated"])


# --------------------------------------------------------------------------
# bench-side classification: frozen (dead optimizer state) vs subresolution
# --------------------------------------------------------------------------


class _FakeDispatch:
    def __init__(self, after, moments):
        self._after, self._moments = after, moments

    def probe_param(self):
        return dict(self._after)

    def probe_moments(self):
        return dict(self._moments)


def test_params_moved_subresolution_vs_frozen():
    """A zero param delta with a LIVE first-order moment is a
    sub-resolution update (bf16 q/k stall), not a dropped update; a dead
    moment alongside a dead param fails the bench outright."""
    from bench import _params_moved

    before = {"a": np.zeros(4), "b": np.ones(4)}
    # a: moved; b: still but moment live -> subresolution
    ok = _params_moved(
        _FakeDispatch({"a": np.full(4, 0.1), "b": np.ones(4)},
                      {"a": np.full(4, 0.5), "b": np.full(4, 1e-3)}),
        before, max_frozen_frac=0.6)
    assert ok["frozen"] == 0 and ok["subresolution"] == 1

    # b still AND moment dead -> dropped-update class, hard failure
    with pytest.raises(AssertionError, match="DEAD optimizer state"):
        _params_moved(
            _FakeDispatch({"a": np.full(4, 0.1), "b": np.ones(4)},
                          {"a": np.full(4, 0.5), "b": np.zeros(4)}),
            before)


def test_params_moved_subresolution_budget():
    from bench import _params_moved

    before = {f"p{i}": np.ones(2) for i in range(4)}
    after = dict(before)          # nothing moved except p0
    after["p0"] = np.full(2, 2.0)
    moments = {n: np.full(2, 1e-4) for n in before}
    with pytest.raises(AssertionError, match="below update resolution"):
        _params_moved(_FakeDispatch(after, moments), before,
                      max_frozen_frac=0.25)


# --------------------------------------------------------------------------
# perf_report --check-bench: the ratcheted MFU floors
# --------------------------------------------------------------------------


def _round_doc(resnet_mfu=0.20, bert_mfu=0.45, nmt_spread=2.0, frozen=0,
               overlap=None):
    models = {
        "bert": {"metric": "bert_base_train_seqs_per_sec_per_chip",
                 "value": 1000.0, "mfu_bf16_analytic": bert_mfu,
                 "spread_pct": 0.5,
                 "params_moved": {"frozen": frozen, "subresolution": 18,
                                  "total": 198}},
        "nmt": {"metric": "transformer_nmt_train_seqs_per_sec_per_chip",
                "value": 1400.0, "spread_pct": nmt_spread},
    }
    if overlap is not None:
        models["overlap"] = overlap
    return {"metric": "resnet50_train_imgs_per_sec_per_chip", "value": 2800.0,
            "extra": {"mfu_bf16_analytic": resnet_mfu, "spread_pct": 0.4,
                      "models": models}}


def _check(tmp_path, doc, **kw):
    from tools.perf_report import check_bench

    p = tmp_path / "bench.json"
    p.write_text(json.dumps(doc))
    return check_bench(str(p), **kw)


def test_check_bench_passes_above_floors(tmp_path):
    assert _check(tmp_path, _round_doc()) == 0


def test_check_bench_fails_below_resnet_floor(tmp_path):
    # the floor is EXCLUSIVE: tying r05's 0.168 is not enough
    assert _check(tmp_path, _round_doc(resnet_mfu=0.168)) == 1
    assert _check(tmp_path, _round_doc(resnet_mfu=0.12)) == 1


def test_check_bench_fails_below_bert_floor(tmp_path):
    assert _check(tmp_path, _round_doc(bert_mfu=0.40)) == 1
    assert _check(tmp_path, _round_doc(bert_mfu=0.402)) == 0  # inclusive


def test_check_bench_fails_on_spread(tmp_path):
    assert _check(tmp_path, _round_doc(nmt_spread=26.3)) == 1
    assert _check(tmp_path, _round_doc(nmt_spread=26.3),
                  max_spread_pct=30.0) == 0


def test_check_bench_fails_on_frozen_params(tmp_path):
    assert _check(tmp_path, _round_doc(frozen=3)) == 1


def test_check_bench_fails_on_resnet_frozen_params(tmp_path):
    """The flagship's params_moved rides the round wrapper's extra (not
    extra.models), so the dead-optimizer-state gate must fire there too."""
    doc = _round_doc()
    doc["extra"]["params_moved"] = {"frozen": 2, "subresolution": 0,
                                    "total": 161}
    assert _check(tmp_path, doc) == 1


def test_check_bench_overlap_record(tmp_path):
    good = {"metric": "dp_grad_overlap_ab_steps_per_sec", "value": 6.3,
            "speedup_vs_serial": 1.07, "overlap_confirmed": True,
            "bit_parity_serial_vs_bucketed": True}
    assert _check(tmp_path, _round_doc(overlap=good)) == 0
    # unconfirmed overlap (the off-device parity-only record bench.py
    # produces on CPU gloo) passes by default — embedding the parity
    # evidence must not fail the round — but --require-overlap demands a
    # confirmed device record
    unconfirmed = dict(good, overlap_confirmed=False)
    assert _check(tmp_path, _round_doc(overlap=unconfirmed)) == 0
    assert _check(tmp_path, _round_doc(overlap=unconfirmed),
                  require_overlap=True) == 1
    # broken bit-parity fails unconditionally — bucketing changed numerics
    noparity = dict(good, bit_parity_serial_vs_bucketed=False)
    assert _check(tmp_path, _round_doc(overlap=noparity)) == 1


def _serving_round_doc(within_atol=True, gate_event=True):
    serve = {"metric": "serving_closed_loop_rps", "value": 2091.0,
             "device": "cpu", "mfu_bf16_analytic": 1e-06,
             "mfu_predicted_roofline": 0.0096}
    return {"metric": "serving_quant_ab_rps", "value": 2481.0,
            "device": "cpu",
            "throughput_claim": "parity_only_off_device",
            "parity": {"max_abs_diff": 7.8e-4, "atol": 0.05,
                       "within_atol": within_atol,
                       "gate_event_recorded": gate_event},
            "mfu_predicted_roofline": 0.0096,
            "extra": {"models": {"serving_closed_loop": serve}}}


def test_check_bench_serving_only_round(tmp_path, capsys):
    """A round with only serving_* records skips the training MFU floors
    (loudly) but still prints the measured-vs-predicted roofline line and
    the off-device honesty NOTE, and enforces the quant parity ledger."""
    assert _check(tmp_path, _serving_round_doc()) == 0
    out = capsys.readouterr().out
    assert "serving-only round" in out
    assert "MFU floors skipped" in out
    assert "no throughput or MFU floor may ratchet" in out
    assert "quant parity ledger clean" in out
    assert "vs static roofline" in out
    assert "no bench record to hold its MFU floor" not in out


def test_check_bench_serving_round_dirty_parity_fails(tmp_path, capsys):
    assert _check(tmp_path, _serving_round_doc(within_atol=False)) == 1
    assert "quant parity ledger DIRTY" in capsys.readouterr().out


def test_check_bench_serving_round_ungated_quant_fails(tmp_path, capsys):
    assert _check(tmp_path, _serving_round_doc(gate_event=False)) == 1
    assert "no quant_parity event" in capsys.readouterr().out


def test_check_bench_mixed_round_still_holds_floors(tmp_path):
    """A serving record riding a training round must NOT flip the round
    to serving-only — the training floors still hold (and still fail)."""
    doc = _round_doc(resnet_mfu=0.12)
    doc["extra"]["models"]["serving"] = _serving_round_doc()
    assert _check(tmp_path, doc) == 1


def test_bench_r06_serving_round_passes():
    """The committed BENCH_r06.json is a serving-only parity round: it
    must clear --check-bench as-is (floors skipped, ledger clean)."""
    import os

    from tools.perf_report import check_bench

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert check_bench(os.path.join(here, "BENCH_r06.json")) == 0


def test_check_bench_reads_round_wrapper(tmp_path):
    doc = {"n": 9, "tail": "noise\n" + json.dumps(_round_doc()) + "\n"}
    assert _check(tmp_path, doc) == 0


def test_bench_r05_fails_only_on_nmt_spread(capsys):
    """The committed BENCH_r05.json must clear the MFU floors (they were
    set from it) and fail exactly the spread gate its NMT entry motivated."""
    import os

    from tools.perf_report import check_bench

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert check_bench(os.path.join(here, "BENCH_r05.json")) == 1
    out = capsys.readouterr().out
    assert "nmt: window spread 26.3%" in out
    assert "fails the ratcheted floor" not in out


# --------------------------------------------------------------------------
# warmup-until-stable bench windowing (tools/bench_kit.timed_steps)
# --------------------------------------------------------------------------


def _fake_clock(durations_ms):
    """Clock yielding windows of the given durations: timed_steps calls it
    twice per window (start, end)."""
    t = [0.0]
    seq = iter(durations_ms)
    state = {"open": False, "dur": None}

    def clock():
        if not state["open"]:
            state["open"] = True
            state["dur"] = next(seq)
            return t[0]
        state["open"] = False
        t[0] += state["dur"] / 1e3
        return t[0]

    return clock


def test_timed_steps_extends_past_warm_in():
    """The BENCH_r05 NMT shape: a slow first window (compile/cache warm-in)
    must be treated as extended warmup, not evidence — windows extend until
    the trailing 3 agree, and exactly those are reported."""
    from tools.bench_kit import timed_steps

    calls = [0]

    def dispatch():
        calls[0] += 1
        return [np.zeros(1)]

    dt, _, ws = timed_steps(dispatch, K=1, n_warm=1, iters=1, windows=3,
                            spread_target=5.0,
                            clock=_fake_clock([30.0, 23.0, 23.1, 23.0]))
    assert ws == [23.0, 23.1, 23.0]
    assert dt == pytest.approx(0.023)


def test_timed_steps_budget_returns_honest_trailing_windows():
    """When the budget runs out before stabilizing, the trailing windows
    come back as-is — the caller's spread gate sees the honest noise."""
    from tools.bench_kit import timed_steps

    durations = [10.0 + 5 * (i % 2) for i in range(12)]  # never stabilizes
    dt, _, ws = timed_steps(lambda: [np.zeros(1)], K=1, n_warm=1, iters=1,
                            windows=3, spread_target=5.0, max_windows=6,
                            clock=_fake_clock(durations))
    assert len(ws) == 3
    from tools.bench_kit import spread_pct

    assert spread_pct(ws) > 5.0


def test_timed_steps_no_target_keeps_fixed_windows():
    from tools.bench_kit import timed_steps

    dt, _, ws = timed_steps(lambda: [np.zeros(1)], K=1, n_warm=1, iters=1,
                            windows=2, clock=_fake_clock([9.0, 11.0]))
    assert ws == [9.0, 11.0]
