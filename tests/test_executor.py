"""Core executor tests: feed/fetch, startup init, persistable state."""
import numpy as np

import paddle_tpu as fluid


def test_feed_fetch_arithmetic():
    x = fluid.layers.data("x", [3], dtype="float32")
    y = fluid.layers.data("y", [3], dtype="float32")
    z = fluid.layers.elementwise_add(x, y)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.random.rand(4, 3).astype("float32")
    yv = np.random.rand(4, 3).astype("float32")
    (out,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[z])
    np.testing.assert_allclose(out, xv + yv, rtol=1e-6)


def test_scalar_sugar():
    x = fluid.layers.data("x", [2], dtype="float32")
    y = (x * 2.0 + 1.0) / 2.0
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((1, 2), dtype="float32")
    (out,) = exe.run(feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, (xv * 2 + 1) / 2)


def test_startup_initialization_persists():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        out = fluid.layers.fc(x, size=8, bias_attr=fluid.ParamAttr(
            initializer=fluid.initializer.Constant(0.5)))
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    scope = fluid.global_scope()
    params = [p.name for p in main.all_parameters()]
    assert len(params) == 2
    for p in params:
        assert scope.has_var(p)
    bias = [p for p in main.all_parameters() if p.shape == (8,)][0]
    np.testing.assert_allclose(scope.to_numpy(bias.name), np.full((8,), 0.5), rtol=1e-6)
    (out_v,) = exe.run(main, feed={"x": np.zeros((2, 4), dtype="float32")}, fetch_list=[out])
    np.testing.assert_allclose(out_v, np.full((2, 8), 0.5), rtol=1e-6)


def test_fetch_multiple_and_cache():
    x = fluid.layers.data("x", [2], dtype="float32")
    a = fluid.layers.relu(x)
    b = fluid.layers.reduce_sum(x)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([[-1.0, 2.0]], dtype="float32")
    outs = exe.run(feed={"x": xv}, fetch_list=[a, b])
    np.testing.assert_allclose(outs[0], [[0.0, 2.0]])
    np.testing.assert_allclose(outs[1], 1.0)
    # second run hits the executable cache
    outs2 = exe.run(feed={"x": xv}, fetch_list=[a, b])
    np.testing.assert_allclose(outs2[0], outs[0])


def test_program_serialization_roundtrip():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.fc(x, size=3, act="relu")
    d = main.to_dict()
    import json

    restored = fluid.Program.from_dict(json.loads(json.dumps(d)))
    assert [op.type for op in restored.global_block().ops] == [
        op.type for op in main.global_block().ops
    ]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.rand(2, 4).astype("float32")
    (a,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    (b,) = exe.run(restored, feed={"x": xv}, fetch_list=[y.name])
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_rng_advances_between_runs():
    out = fluid.layers.data("x", [2], dtype="float32")
    d = fluid.layers.dropout(out, dropout_prob=0.5)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.ones((100, 2), dtype="float32")
    (a,) = exe.run(feed={"x": xv}, fetch_list=[d])
    (b,) = exe.run(feed={"x": xv}, fetch_list=[d])
    assert not np.array_equal(a, b)


def test_calc_gradient_multi_target():
    """VERDICT weak-item regression: calc_gradient over several targets
    (gradient of the summed targets, reference backward.py:672)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3], dtype="float32")
        t1 = fluid.layers.scale(x, scale=2.0)     # d sum(t1)/dx = 2
        t2 = fluid.layers.scale(x, scale=5.0)     # d sum(t2)/dx = 5
        grads = fluid.calc_gradient([t1, t2], [x])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xv = np.ones((2, 3), "float32")
    (g,) = exe.run(main, feed={"x": xv}, fetch_list=[grads[0]], scope=scope)
    np.testing.assert_allclose(g, np.full((2, 3), 7.0), atol=1e-6)


def test_calc_gradient_mixed_none_target_gradients():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3], dtype="float32")
        t1 = fluid.layers.scale(x, scale=2.0)
        t2 = fluid.layers.scale(x, scale=5.0)
        tg = fluid.layers.fill_constant([3], "float32", 3.0)
        grads = fluid.calc_gradient([t1, t2], [x], target_gradients=[tg, None])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (g,) = exe.run(main, feed={"x": np.ones((2, 3), "float32")},
                   fetch_list=[grads[0]], scope=scope)
    np.testing.assert_allclose(g, np.full((2, 3), 2 * 3 + 5.0), atol=1e-6)
