"""Integrity-sentinel gang worker (ISSUE 14): the chaos-suite worker for
the flip_bit silent-corruption matrix.

The run drives `resilient_train_loop` over a checkpointable stream with
`FLAGS_integrity_check_period` armed, so every rank's heartbeat carries
its amortized state-digest epochs.  A `flip_bit@S:RANK` fault plants a
wrong-but-FINITE value in rank RANK's parameters at the dispatch
boundary of step S — no NaN guard, CRC, or structure check can see it;
only the cross-rank digest comparison can.  The contract this worker
exists to prove:

  * the divergence is DETECTED (integrity.divergences > 0 on every
    observer) and the vote NAMES the flipped rank (the exponent-bit flip
    makes the corrupt chunk's max |value| astronomically implausible —
    the 2-rank tiebreak);
  * the corrupt timeline is DISCARDED: checkpoints newer than the
    proven-clean boundary are quarantined (INTEGRITY_REJECTED), every
    rank exits classified (EXIT_INTEGRITY=45 from the flagged rank's own
    raise; 43 from peers that classify off its tombstone), and the
    relaunched gang resumes from the newest clean checkpoint;
  * the replay is EXACT: the flip is ledger-spent (fires once per gang),
    so the restarted run ends bit-identical to an uninterrupted one —
    the params_sha on the RESULT line is the parity probe.

Batches for step S derive from the step index alone (same contract as
dist_worker_resilient.py) so any restore-and-replay consumes exactly the
batches an uninterrupted run would.
"""
import json
import os
import sys
import time

# must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=1").strip()

import numpy as np  # noqa: E402

GBS = int(os.environ.get("GLOBAL_BS", "16"))


class CountingBase:
    """Checkpointable base stream of global sample ids [0, n)."""

    def __init__(self, n: int):
        self.n = int(n)
        self._next = 0

    def state_dict(self):
        return {"pos": self._next}

    def load_state_dict(self, state):
        self._next = int(state["pos"])

    def __call__(self):
        i = self._next
        self._next = 0
        while i < self.n:
            self._next = i + 1
            yield i
            i += 1
            self._next = i


def sample(i: int):
    rng = np.random.RandomState(70000 + i)
    x = rng.rand(8).astype("f4")
    y = np.array([x.sum() * 0.5], "f4")
    return x, y


def build_model():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 92
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return main, startup, loss


def main():
    import paddle_tpu as fluid
    from paddle_tpu import dist_resilience as dres
    from paddle_tpu import integrity, monitor
    from paddle_tpu import reader as R
    from paddle_tpu.errors import DistributedError, IntegrityError
    from paddle_tpu.fleet import fleet

    run_steps = int(os.environ.get("RUN_STEPS", "24"))
    save_every = int(os.environ.get("SAVE_EVERY", "4"))
    period = int(os.environ.get("INTEGRITY_PERIOD", "2"))
    step_sleep = float(os.environ.get("PT_STEP_SLEEP", "0.02"))
    ckpt_root = os.environ.get("PADDLE_CHECKPOINT_ROOT")
    restart_num = int(os.environ.get("PADDLE_RESTART_NUM", "0"))
    total = run_steps * GBS

    fluid.set_flags({"FLAGS_integrity_check_period": period})
    monitor.enable()  # the test reads the integrity counters

    t0 = time.perf_counter()
    verdict_ranks = []
    try:
        fleet.init()
        rank, world = fleet.worker_index(), fleet.worker_num()
        per = GBS // world
        assert per * world == GBS

        main_p, startup, loss = build_model()
        compiled = fleet.main_program(main_p) if world > 1 else main_p
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)

        def make_feed(ids):
            xs, ys = zip(*(sample(i) for i in ids))
            return {"x": np.stack(xs), "y": np.stack(ys)}

        def make_loader():
            base = CountingBase(total)
            return R.map_readers(
                make_feed, R.batch(R.shard(base, rank, world), per,
                                   drop_last=True))

        cm = fluid.CheckpointManager(
            ckpt_root, program=main_p, scope=scope, rank=rank,
            world_size=world, mesh=fleet.mesh if world > 1 else None,
            save_every_steps=save_every, commit_timeout_s=30)

        def on_logged(step, vals):
            if step_sleep:
                # beats must interleave with steps: detection latency is
                # measured in beat intervals, and a run that finishes
                # before the divergent epoch's beats cross would prove
                # nothing
                time.sleep(step_sleep)

        try:
            stats = fluid.resilient_train_loop(
                exe, compiled, make_loader, [loss], scope=scope,
                checkpoint_manager=cm, resume=restart_num > 0,
                max_inflight=1, log_period=1, on_logged=on_logged,
                max_steps=run_steps)
        except IntegrityError as e:
            # the gang path: quarantine already happened inside the loop,
            # this rank exits classified for the supervisor's restart
            verdict_ranks = list(e.corrupt_ranks)
            print(f"INTEGRITY_FAILURE corrupt_ranks={e.corrupt_ranks} "
                  f"attributed={e.attributed} safe_step={e.safe_step}",
                  file=sys.stderr, flush=True)
            dres.shutdown_health(mark_down=True)
            os._exit(dres.EXIT_INTEGRITY)
    except DistributedError as e:
        print(f"DIST_FAILURE {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        dres.shutdown_health(mark_down=True)
        os._exit(dres.exit_code_for(e))

    counters = monitor.get_monitor().counter_values()
    events = [r for r in monitor.step_records()
              if r.get("kind") == "integrity_event"]
    for r in events:
        if r.get("action") == "divergence":
            verdict_ranks = list(r.get("corrupt_ranks", []))
    print("RESULT " + json.dumps({
        "rank": rank, "world": world, "restart_num": restart_num,
        "steps_total": stats.steps,
        "rollbacks": stats.rollbacks,
        "wall_s": round(time.perf_counter() - t0, 4),
        "divergences": int(counters.get("integrity.divergences", 0)),
        "digest_epochs": int(counters.get("integrity.digests", 0)),
        "ckpt_rejected": int(counters.get("integrity.ckpt_rejected", 0)),
        "corrupt_ranks": verdict_ranks,
        "params_sha": integrity.state_digest(scope)}), flush=True)
    dres.shutdown_health()


if __name__ == "__main__":
    main()
