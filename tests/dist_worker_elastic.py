"""Elastic gang worker (ISSUE 9): the chaos-suite worker for N->M->N
world-size cycles.

Differences from dist_worker_resilient.py, which pins the FIXED-size
restart contract:

  * the whole run drives `resilient_train_loop` over a CHECKPOINTABLE
    sharded data pipeline (`reader.shard` -> `batch` -> `map_readers`
    over a deterministic global sample stream), so every coordinated
    checkpoint carries per-rank RESUME sidecars with exact stream
    cursors;
  * the CheckpointManager is constructed `elastic=True`: a restart at a
    DIFFERENT world size consolidates the saved shards and re-splits
    them for the new rank set, and the resume path repartitions the
    stream cursors (paddle_tpu/elastic.py) so no sample is dropped or
    double-trained across the resize;
  * SIGTERM (the supervisor's grow-drain notice) is handled by the
    resilient loop: flush one coordinated checkpoint + cursors, print
    the RESULT line with `preempted=true`, exit 0;
  * every logged step appends `{"step", "loss", "idsum"}` to a per-rank,
    per-incarnation ledger file (PT_LEDGER_DIR) — `idsum` is computed
    THROUGH the training feed (the mean of the id column, fetched from
    the compiled step, times the global batch), so the chaos test can
    verify exact sample coverage from what the gang actually trained on,
    not from what the reader claims it handed over.

Batches are sample-sharded by global id (rank r of world M trains the
ids ≡ r mod M), so the GLOBAL batch of step s is ids
[s*GBS, (s+1)*GBS) at EVERY world size — the loss trajectory is
world-size invariant up to float summation order, which is the
loss-parity contract the elastic chaos test asserts (allclose, not
bit-equal: a different world size reassociates the mean).
"""
import json
import os
import sys
import time

# must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_xla_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _xla_flags:
    os.environ["XLA_FLAGS"] = (
        _xla_flags + " --xla_force_host_platform_device_count=1").strip()

import hashlib  # noqa: E402

import numpy as np  # noqa: E402

GBS = int(os.environ.get("GLOBAL_BS", "16"))


class CountingBase:
    """Checkpointable base stream of global sample ids [0, n)."""

    def __init__(self, n: int):
        self.n = int(n)
        self._next = 0

    def state_dict(self):
        return {"pos": self._next}

    def load_state_dict(self, state):
        self._next = int(state["pos"])

    def __call__(self):
        i = self._next
        self._next = 0
        while i < self.n:
            self._next = i + 1
            yield i
            i += 1
            self._next = i


def sample(i: int):
    """Deterministic global sample `i` — identical whichever rank, world
    size, or incarnation materializes it."""
    rng = np.random.RandomState(50000 + i)
    x = rng.rand(8).astype("f4")
    y = np.array([x.sum() * 0.5 + 0.05 * rng.rand()], "f4")
    return x, y


def build_model():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 91
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        idf = fluid.layers.data("idf", [1], dtype="float32")
        pred = fluid.layers.fc(x, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        # the accounting probe: mean of the id column over the GLOBAL
        # batch — fetched from the compiled step, so it reports what was
        # actually fed, dp-mean-combined across ranks
        idmean = fluid.layers.mean(idf)
        fluid.optimizer.Momentum(0.05, 0.9).minimize(loss)
    return main, startup, loss, idmean


def params_digest(scope) -> str:
    h = hashlib.sha256()
    for name in sorted(scope.local_var_names()):
        try:
            a = np.asarray(scope.find_var(name))
        except Exception:
            continue
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def params_l2(scope) -> float:
    total = 0.0
    for name in sorted(scope.local_var_names()):
        try:
            a = np.asarray(scope.find_var(name))
        except Exception:
            continue
        if a.dtype.kind != "f":
            continue  # RNG key etc. would drown the float params
        a = a.astype("f8")
        total += float((a * a).sum())
    return float(np.sqrt(total))


def main():
    import paddle_tpu as fluid
    from paddle_tpu import dist_resilience as dres
    from paddle_tpu import reader as R
    from paddle_tpu.errors import DistributedError
    from paddle_tpu.fleet import fleet

    run_steps = int(os.environ.get("RUN_STEPS", "12"))
    save_every = int(os.environ.get("SAVE_EVERY", "2"))
    step_sleep = float(os.environ.get("PT_STEP_SLEEP", "0"))
    ckpt_root = os.environ.get("PADDLE_CHECKPOINT_ROOT")
    restart_num = int(os.environ.get("PADDLE_RESTART_NUM", "0"))
    ledger_dir = os.environ.get("PT_LEDGER_DIR")
    total = run_steps * GBS

    t0 = time.perf_counter()
    try:
        fleet.init()
        rank, world = fleet.worker_index(), fleet.worker_num()
        per = GBS // world
        assert per * world == GBS, f"GLOBAL_BS={GBS} must divide world={world}"

        main_p, startup, loss, idmean = build_model()
        compiled = fleet.main_program(main_p) if world > 1 else main_p
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup, scope=scope)

        def make_feed(ids):
            xs, ys = zip(*(sample(i) for i in ids))
            return {"x": np.stack(xs), "y": np.stack(ys),
                    "idf": np.array(ids, "f4").reshape(-1, 1)}

        def make_loader():
            base = CountingBase(total)
            return R.map_readers(
                make_feed, R.batch(R.shard(base, rank, world), per,
                                   drop_last=True))

        cm = fluid.CheckpointManager(
            ckpt_root, program=main_p, scope=scope, rank=rank,
            world_size=world, mesh=fleet.mesh if world > 1 else None,
            save_every_steps=save_every, commit_timeout_s=30,
            elastic=True)

        ledger = None
        if ledger_dir:
            os.makedirs(ledger_dir, exist_ok=True)
            ledger = open(os.path.join(
                ledger_dir, f"ledger.r{rank}.i{restart_num}.jsonl"), "w")

        logged = []  # (global step, loss, idsum) this incarnation ran

        def on_logged(step, vals):
            lv = float(np.asarray(vals[0]).reshape(-1)[0])
            im = float(np.asarray(vals[1]).reshape(-1)[0])
            logged.append((step, lv))
            if ledger is not None:
                ledger.write(json.dumps(
                    {"step": step, "loss": lv,
                     "idsum": round(im * GBS)}) + "\n")
                ledger.flush()
            if step_sleep:
                time.sleep(step_sleep)

        stats = fluid.resilient_train_loop(
            exe, compiled, make_loader, [loss, idmean], scope=scope,
            checkpoint_manager=cm, resume=restart_num > 0,
            max_inflight=1, log_period=1, on_logged=on_logged,
            max_steps=run_steps)
    except DistributedError as e:
        print(f"DIST_FAILURE {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        dres.shutdown_health(mark_down=True)
        os._exit(dres.exit_code_for(e))

    start_step = min((s for s, _ in logged), default=stats.steps)
    print("RESULT " + json.dumps({
        "rank": rank, "world": world, "restart_num": restart_num,
        "start_step": start_step,
        "steps_run": len(logged), "steps_total": stats.steps,
        "preempted": bool(stats.preempted),
        "wall_s": round(time.perf_counter() - t0, 4),
        "restored_world": cm.restored_world,
        "params_sha": params_digest(scope),
        "params_l2": params_l2(scope)}), flush=True)
    if ledger is not None:
        ledger.close()
    dres.shutdown_health()


if __name__ == "__main__":
    main()
