"""Chaos campaign engine (ISSUE 20): tier-1 wiring + the acceptance
contracts.

The module-scoped fixture runs `tools/chaos_campaign.py --check --smoke`
ONCE as a subprocess — exactly the invocation CI runs — and the tests
unpack its guarantees: every seeded compound schedule leaves the
cross-subsystem invariants intact, the planted defect
(PADDLE_CHAOS_PLANTED_BUG) is caught by a seeded campaign and shrunk to
a <=2-fault spec that still fails, the emitted metrics stream passes
`perf_report --check --max-chaos-violations 0`, and replaying any
emitted spec through the ordinary single-run path reproduces the same
invariant verdict."""
import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def smoke(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("chaos-smoke"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PADDLE_CHAOS_PLANTED_BUG", None)  # the CLI plants its own
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_campaign.py"),
         "--check", "--smoke", "--per-scenario", "1", "--out", out],
        capture_output=True, text=True, timeout=420, env=env, cwd=REPO)
    return {"rc": p.returncode, "out": p.stdout, "err": p.stderr,
            "dir": out, "metrics": os.path.join(out, "chaos_metrics.jsonl")}


def test_smoke_gate_is_green(smoke):
    assert smoke["rc"] == 0, \
        f"--check --smoke failed:\n{smoke['out']}\n{smoke['err']}"
    assert "OK" in smoke["out"]


def test_planted_bug_caught_and_shrunk_to_two_faults(smoke):
    """The engine's own proof of power: a defect that only a COMPOUND
    schedule exposes (post-recovery corruption gated on nan AND device
    both firing) must be caught by the seeded campaign, and the shrinker
    must strip it to a spec of at most 2 faults that still fails."""
    m = re.search(r"planted bug caught by '([^']+)', shrunk to '([^']+)'",
                  smoke["out"])
    assert m, f"planted-bug arm left no trace in:\n{smoke['out']}"
    original, shrunk = m.group(1), m.group(2)
    n_orig = len([e for e in original.split(";") if e.strip()])
    n_shrunk = len([e for e in shrunk.split(";") if e.strip()])
    assert n_shrunk <= 2, f"shrinker stalled at {shrunk!r}"
    assert n_shrunk <= n_orig
    kinds = {e.split("@")[0].strip() for e in shrunk.split(";")}
    assert kinds == {"nan", "device"}, \
        f"shrinker dropped a load-bearing fault: {shrunk!r} (the " \
        f"planted defect needs nan AND device to manifest)"


def test_shrunk_spec_still_fails_with_bug_and_passes_without(smoke):
    """Replaying the shrunk spec through run_one (the ordinary
    single-run path) reproduces the violation with the bug planted and
    a clean verdict without — the repro names the defect, not the
    harness."""
    from paddle_tpu import chaos

    m = re.search(r"shrunk to '([^']+)'", smoke["out"])
    shrunk = m.group(1)
    os.environ[chaos.PLANTED_BUG_ENV] = "1"
    try:
        run = chaos.run_one("train", shrunk, seed=8)
        vs = chaos.evaluate(run)
    finally:
        os.environ.pop(chaos.PLANTED_BUG_ENV, None)
    assert any(v.invariant == "bit_identical_recovery" for v in vs), \
        f"shrunk spec {shrunk!r} no longer reproduces the planted defect"


def test_replay_reproduces_every_campaign_verdict(smoke):
    """Acceptance contract: any spec the campaign emitted, replayed
    through the ordinary single-run path with the recorded seed, yields
    the SAME invariant verdict."""
    from paddle_tpu import chaos

    with open(os.path.join(smoke["dir"], "CAMPAIGN.json")) as fh:
        campaign = json.load(fh)
    assert campaign["schedules"], "smoke campaign drew no schedules"
    for s in campaign["schedules"]:
        run = chaos.run_one(s["scenario"], s["spec"], seed=s["seed"])
        verdict = "fail" if chaos.evaluate(run) else "pass"
        assert verdict == s["verdict"], \
            f"replay of {s['scenario']} {s['spec']!r} seed={s['seed']} " \
            f"gave {verdict}, campaign recorded {s['verdict']} — the " \
            f"single-run path drifted from the campaign path"


def test_metrics_stream_carries_chaos_evidence(smoke):
    with open(smoke["metrics"]) as fh:
        lines = [json.loads(ln) for ln in fh if ln.strip()]
    events = [r for r in lines if r.get("kind") == "chaos_event"]
    assert len([r for r in events if r.get("event") == "schedule"]) \
        == len(set((r["scenario"], r["spec"]) for r in events
                   if r.get("event") == "schedule")), \
        "duplicate schedule events"
    assert events, "campaign wrote no chaos_event records"
    snaps = [r for r in lines if isinstance(r.get("counters"), dict)]
    assert snaps and snaps[-1]["counters"].get("chaos.schedules_run"), \
        "no final counter snapshot with chaos.* evidence"
    # the campaign's own runs must NOT leak executor step records into
    # the stream — they would trip the recompile gate on churn the
    # campaign caused on purpose
    assert not any(r.get("kind") == "step" for r in lines)


def test_perf_gate_passes_on_smoke_output_and_fails_on_silence(
        smoke, tmp_path, capsys):
    from tools.perf_report import check

    assert check(smoke["metrics"], max_chaos_violations=0) == 0
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert check(str(empty), max_chaos_violations=0) == 1
    capsys.readouterr()


def test_generate_schedule_is_seeded_and_validated():
    """Same seed -> same draw, every draw passes compound validation
    against the scenario's declared capabilities, and the avoid set is
    honored (no schedule drawn twice in one campaign)."""
    import random

    from paddle_tpu import chaos
    from paddle_tpu.faults import validate_schedule

    for sname, sc in chaos.SCENARIOS.items():
        a = [chaos.generate_schedule(sname, random.Random(3))
             for _ in range(4)]
        b = [chaos.generate_schedule(sname, random.Random(3))
             for _ in range(4)]
        assert a == b, f"{sname}: schedule generation is not seeded"
        drawn = set()
        rng = random.Random(5)
        for _ in range(6):
            spec = chaos.generate_schedule(sname, rng, avoid=drawn)
            fs = validate_schedule(spec, capabilities=sc.capabilities)
            assert all(f.kind in sc.kinds for f in fs)
            drawn.add(spec)


def test_run_one_rejects_bad_specs():
    from paddle_tpu import chaos

    with pytest.raises(ValueError):
        chaos.run_one("train", "not_a_kind@3", seed=0)
    with pytest.raises(KeyError):
        chaos.run_one("no_such_scenario", "nan@1", seed=0)
