"""Compound-fault regressions OUTSIDE the chaos engine (ISSUE 20).

The campaign generator draws adversarial pairings pseudo-randomly;
these two are pinned as plain deterministic tests so the pairings the
issue names stay covered even if the generator's weights drift:

  1. a storage fault inside a gang-restart window — SIGKILL rank 1 at
     step 3, then ENOSPC biting the first save of the RESTARTED
     incarnation's replay window; the run must still end bit-identical
     to an uninterrupted gang (the restart resumes from the last
     coordinated checkpoint, the failed round degrades then recovers,
     and the fault ledger keeps the spent kill from re-firing);
  2. a pserver kill interleaved with a rotted snapshot inside the
     publish cadence — the supervisor respawns the pserver
     bit-identically mid-stream, the publish ladder rejects the rotted
     commit, serving holds the LAST GOOD version, and the next clean
     publish converges.
"""
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import io, layers, monitor
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.errors import ServingError
from paddle_tpu.faults import FaultInjector
from paddle_tpu.param_server import KVClient, PServerSupervisor

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from dist_harness import RESILIENT_WORKER, run_gang  # noqa: E402

# same chaos knobs as test_dist_chaos (see the rationale there): 8 steps,
# coordinated saves after steps 1/3/5 (done=2/4/6), 3s liveness deadline
CHAOS_ENV = {
    "RUN_STEPS": "8",
    "SAVE_EVERY": "2",
    "FLAGS_dist_heartbeat_interval_s": "0.25",
    "FLAGS_dist_heartbeat_miss_factor": "12",
    "FLAGS_dist_watchdog_timeout_s": "60",
    "FLAGS_dist_bootstrap_timeout_s": "120",
}


def _results(res):
    out = {}
    for rank, (_code, o, _e) in enumerate(res.workers):
        for line in (o or "").splitlines():
            if line.startswith("RESULT "):
                out[rank] = json.loads(line[len("RESULT "):])
    return out


def _kill_incident(res):
    for inc in res.incidents:
        dead = {d["rank"]: d for d in inc["dead"]}
        if dead.get(1, {}).get("signaled") and dead[1]["returncode"] == -9:
            return inc
    raise AssertionError(f"no SIGKILL incident recorded: {res.incidents}")


@pytest.mark.skipif(not os.path.exists(RESILIENT_WORKER),
                    reason="worker script missing")
def test_enospc_inside_gang_restart_window_bit_identical(tmp_path):
    """`kill_worker@3:1;enospc@3:1`: the kill lands at the step-3
    dispatch of incarnation 0 — BEFORE that iteration's save — so the
    enospc entry is still unspent when the gang restarts from ckpt-2.
    The storage fault then bites the restarted incarnation's FIRST save
    (done=4, inside the replay window), the round skips gang-wide, the
    done=6 commit recovers, and the end state is bit-identical to an
    uninterrupted gang."""
    def one(tag, spec, restarts):
        env = dict(CHAOS_ENV)
        if spec:
            env["FLAGS_fault_spec"] = spec
        return run_gang([sys.executable, RESILIENT_WORKER], 2,
                        checkpoint_root=str(tmp_path / tag),
                        extra_env=env, max_restarts=restarts, timeout=240)

    ref = one("ref", None, 1)
    assert ref.ok, ref.workers
    ref_out = _results(ref)
    assert ref_out[0]["params_sha"] == ref_out[1]["params_sha"]

    res = one("chaos", "kill_worker@3:1;enospc@3:1", 3)
    assert res.ok, f"compound gang did not recover: {res.incidents}"
    assert res.restarts >= 1
    _kill_incident(res)  # the injected death really happened
    out = _results(res)
    # the final incarnation resumed from ckpt-2 (the step-3 kill beat
    # the done=4 save) and the enospc round skipped INSIDE that window
    assert out[0]["start_step"] == out[1]["start_step"] == 2
    for r in (0, 1):
        assert out[r]["ckpt_rounds_skipped"] == 1, out[r]
        assert out[r]["ckpt_recoveries"] == 1, out[r]
        assert not out[r]["ckpt_degraded"]
    root = str(tmp_path / "chaos")
    ckpts = sorted(d for d in os.listdir(root) if d.startswith("ckpt-")
                   and not d.endswith(".tmp"))
    assert "ckpt-0000000004" not in ckpts, ckpts  # the skipped round
    assert "ckpt-0000000006" in ckpts, ckpts      # the recovery
    # the acceptance bit: the compound left no scar in the math
    assert out[0]["params_sha"] == out[1]["params_sha"]
    assert out[0]["params_sha"] == ref_out[0]["params_sha"], (
        "compound kill+enospc run diverged from the uninterrupted gang — "
        "either the restart resumed from the wrong step or the degraded "
        "save window leaked into training semantics")
    assert out[0]["losses"] == ref_out[0]["losses"][2:]


def test_nan_adjacent_to_device_fault_keeps_skip_semantics():
    """Pins the two defects the first fresh-seed campaign caught (both
    fixed in this PR; the engine found them, these keep them dead):

      * nan@S;device@S+1 — the device fault at the step-S+1 dispatch used
        to discard step S's unresolved sticky-NaN guard (train_loop's
        finally block swallows resolution errors), so retry restored a
        snapshot that already embedded the unguarded poisoned update;
        train_loop now drains older in-flight resolutions before a
        dispatch error propagates, and the OLDER failure supersedes;
      * nan@S;device@S — the replay window used to store the feed
        BEFORE injection, so the retry replayed the corrupt batch clean
        (once-only latch spent) and trained the sample the
        uninterrupted run drops; the window now holds the batch as
        dispatched.

    Either regression re-breaks sample accounting AND bit-identical
    recovery on these exact specs."""
    from paddle_tpu import chaos

    for spec in ("nan@4;device@5:UNAVAILABLE", "nan@0;device@0:UNAVAILABLE"):
        run = chaos.run_one("train", spec, seed=11)
        vs = chaos.evaluate(run)
        assert not vs, f"{spec!r}: " + "; ".join(
            f"{v.invariant}: {v.detail}" for v in vs)
        assert run.fired == {"nan": 1, "device": 1}, run.fired


# --- pserver kill + rotted snapshot inside the publish cadence --------------

def _sparse_model(tmp_path, vocab=24, dim=4, feat=3):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", [feat], dtype="int64")
        e = layers.embedding(ids, size=[vocab, dim], is_sparse=True,
                             param_attr=fluid.ParamAttr(name="p_tbl"))
        pred = layers.fc(layers.reshape(e, [-1, feat * dim]), 1,
                         param_attr=fluid.ParamAttr(name="p_fc"),
                         bias_attr=False)
    startup.random_seed = 5
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    d0 = str(tmp_path / "model-0")
    io.save_inference_model(d0, ["ids"], [pred], exe, main, scope)
    return main, scope, d0


def _snapshot(tmp_path, name, main, scope, table):
    vocab = table.shape[0]
    s = fluid.Scope()
    s.set_var("p_tbl", SelectedRows(np.arange(vocab, dtype=np.int64),
                                    table, vocab))
    names = [v.name for v in io._persistables(main)]
    for n in names:
        if n != "p_tbl":
            s.set_var(n, np.asarray(scope.find_var(n)))
    d = str(tmp_path / name)
    io.save_sharded(d, names, s, program=main, process_index=0)
    return d


def test_kill_pserver_mid_publish_cadence_converges_on_last_good(tmp_path):
    """`kill_pserver@2;rot_row@1`: SIGKILL the pserver child between
    publish periods while rot_row corrupts the NEXT committed snapshot.
    The client's retried traffic rides the respawn (journal replay keeps
    the table), the ladder rejects the rotted commit — the previous
    version keeps serving bit-identically — and the following clean
    period converges on a new good version that reflects training done
    ACROSS the pserver restart."""
    from paddle_tpu.serving import ModelRegistry, publish

    monitor.enable()
    try:
        main, scope, d0 = _sparse_model(tmp_path)
        reg = ModelRegistry(place=fluid.CPUPlace())
        reg.load("m", d0)
        feeds = {"ids": np.array([[1, 2, 3]], np.int64)}
        sup = PServerSupervisor(str(tmp_path / "ps"), optimizer="sgd",
                                lr=0.1, snapshot_every_ops=4,
                                max_restarts=2).start()
        try:
            sup.wait_ready()
            c = KVClient(sup.endpoint, retries=8, backoff_base_s=0.2)
            c.create("p_tbl", np.asarray(scope.find_var("p_tbl")).copy())
            inj = FaultInjector("kill_pserver@2;rot_row@1")
            inj.set_pserver(sup)
            rng = np.random.RandomState(7)
            # the served rows (1,2,3) are pushed EVERY period so each
            # good publish is guaranteed to move the served output
            push_ids = np.array([1, 2, 3, 5], np.int64)
            outs, rejected = {}, []
            for step in range(4):
                inj.on_dispatch(step)  # step 2: SIGKILL the pserver child
                # the push right after the kill must ride the respawn out
                c.push("p_tbl", push_ids,
                       rng.rand(4, 4).astype("f4") + 0.1)
                d = _snapshot(tmp_path, f"snap-{step}", main, scope,
                              c.fetch_table("p_tbl"))
                inj.on_commit(d)  # commit ordinal 1 gets the rotted row
                try:
                    publish(reg, "m", d)
                except ServingError:
                    rejected.append(step)
                outs[step] = np.asarray(
                    reg.acquire("m").run(feeds)[0]).copy()
            assert sup.restarts == 1 and not sup.failed, \
                "kill_pserver never fired (or the respawn budget blew)"
            assert rejected == [1], \
                f"rot_row must reject exactly commit ordinal 1, " \
                f"got rejections at {rejected}"
            # the rejected period kept serving the LAST GOOD version
            np.testing.assert_array_equal(outs[1], outs[0])
            # the next clean period converged past it — the table kept
            # training across the pserver respawn
            assert not np.array_equal(outs[2], outs[1]), \
                "publish cadence never recovered after the rejection"
            assert not np.array_equal(outs[3], outs[2])
            evs = [r for r in monitor.step_records()
                   if r.get("kind") == "serving_event"]
            assert any(r.get("action") == "publish_rejected" for r in evs)
            c.close()
        finally:
            sup.stop()
    finally:
        monitor.disable()
        monitor.reset()
