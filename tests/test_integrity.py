"""Silent-corruption sentinel (ISSUE 14): one planted defect per
detector class.

The whole point of paddle_tpu/integrity.py is that a flipped-yet-FINITE
value passes every pre-existing guard — no NaN check, CRC, structure
verifier, or load exception sees it.  Each test here plants exactly that
class of defect and asserts the matching detector names it:

  * at-rest: manifest sha256 round-trip (dense + SelectedRows shards),
    a rotted shard failing the load with the FILE named, restore's
    walk-back rejecting a digest-mismatched checkpoint with an
    `integrity.ckpt_rejected` event;
  * live: the amortized digest's per-step byte budget, the majority
    vote + agreed-baseline plausibility tiebreak, a latched divergence
    verdict driving the resilient loop's rollback bit-identically;
  * quarantine: `reject_unsafe` marking committed AND pending dirs
    (the commit-rename race a real gang run found);
  * fault specs: flip_bit rank gating + finiteness, rot_shard's
    once-per-gang ledger replay safety;
  * tools: scrub --check on a clean tree and on each rot class,
    perf_report --max-integrity-mismatches (zero-evidence-fails);
  * the 2-process chaos matrix: flip_bit on a real gang names the
    corrupt rank, quarantines the poisoned checkpoints, and recovers
    bit-identical to an uninterrupted baseline.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import integrity, io, layers, monitor
from paddle_tpu.core.scope import Scope
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.errors import IntegrityError
from paddle_tpu.faults import FaultInjector

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _rot(path, offset=None):
    """Flip one byte of a file in place (finite rot, not truncation)."""
    size = os.path.getsize(path)
    off = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


@pytest.fixture
def mon():
    monitor.reset()
    monitor.enable()
    integrity.disarm_live_digests()  # fresh gang-observation state
    yield monitor
    integrity.disarm_live_digests()
    monitor.reset()
    monitor.disable()


# ---- at-rest digests -------------------------------------------------------

def test_manifest_digest_roundtrip_incl_selected_rows(tmp_path):
    d = str(tmp_path / "ck")
    s = Scope()
    s.set_var("w", np.arange(12, dtype="f4").reshape(3, 4))
    s.set_var("tbl", SelectedRows(np.array([1, 5]),
                                  np.ones((2, 3), "f4"), 10))
    io.save_sharded(d, var_names=["w", "tbl"], scope=s, process_index=0)
    # every file is stamped and verifies
    assert integrity.verify_manifest_digests(d) == 3  # w + rows + vals
    s2 = Scope()
    io.load_sharded(d, scope=s2)
    np.testing.assert_array_equal(np.asarray(s2.find_var("w")),
                                  np.asarray(s.find_var("w")))
    tbl = s2.find_var("tbl")
    np.testing.assert_array_equal(np.asarray(tbl.rows), [1, 5])
    # plain save_vars stamps too
    d2 = str(tmp_path / "vars")
    io.save_vars(d2, ["w"], scope=s)
    assert integrity.verify_manifest_digests(d2) == 1


def test_rotted_shard_fails_load_naming_the_file(tmp_path):
    d = str(tmp_path / "ck")
    s = Scope()
    s.set_var("w", np.arange(64, dtype="f4"))
    io.save_sharded(d, var_names=["w"], scope=s, process_index=0)
    victim = next(f for f in sorted(os.listdir(d)) if f.endswith(".npy"))
    _rot(os.path.join(d, victim))
    with pytest.raises(IntegrityError) as ei:
        io.load_sharded(d, scope=Scope())
    assert ei.value.file == victim
    assert ei.value.expected and ei.value.actual
    # escape hatch: verification off loads the rotted bytes (the
    # historical behavior, explicitly opted into)
    fluid.set_flags({"FLAGS_integrity_verify_load": False})
    try:
        io.load_sharded(d, scope=Scope())
    finally:
        fluid.set_flags({"FLAGS_integrity_verify_load": True})


def test_rotted_selected_rows_values_fail_load(tmp_path):
    d = str(tmp_path / "ck")
    s = Scope()
    s.set_var("tbl", SelectedRows(np.arange(4), np.ones((4, 8), "f4"), 16))
    io.save_sharded(d, var_names=["tbl"], scope=s, process_index=0)
    victim = next(f for f in sorted(os.listdir(d)) if ".vals." in f)
    _rot(os.path.join(d, victim))
    with pytest.raises(IntegrityError) as ei:
        io.load_sharded(d, scope=Scope())
    assert ei.value.file == victim


def _tiny_program():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 13
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(0.1).minimize(loss)
    return main, startup, loss


def test_restore_walkback_rejects_digest_mismatched_checkpoint(tmp_path, mon):
    main, startup, _ = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    cm = fluid.CheckpointManager(str(tmp_path / "root"), program=main,
                                 scope=scope)
    cm.save(step=2)
    w = scope.find_var("fc_0.w_0")
    scope.set_var("fc_0.w_0", np.asarray(w) + 1.0)
    newest = cm.save(step=4)
    # a flipped finite byte in the newest checkpoint: loads cleanly
    # without digests — today it MUST be rejected and the walk-back must
    # land one earlier, naming the file in an integrity_event
    victim = next(f for f in sorted(os.listdir(newest))
                  if f.startswith("fc_0.w_0") and f.endswith(".npy"))
    _rot(os.path.join(newest, victim))
    restored = cm.restore(scope=scope)
    assert restored == 2
    assert monitor.counter("integrity.ckpt_rejected").value == 1
    evs = [r for r in monitor.step_records()
           if r.get("kind") == "integrity_event"
           and r.get("action") == "ckpt_rejected"]
    assert evs and evs[0]["file"] == victim
    np.testing.assert_array_equal(np.asarray(scope.find_var("fc_0.w_0")),
                                  np.asarray(w))


def test_reject_unsafe_quarantines_committed_and_pending(tmp_path, mon):
    from paddle_tpu.checkpoint_manager import INTEGRITY_REJECTED_MARKER

    main, startup, _ = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    root = str(tmp_path / "root")
    cm = fluid.CheckpointManager(root, program=main, scope=scope)
    cm.save(step=2)
    cm.save(step=4)
    # a shared pending dir mid-commit (the rename race a real gang hit:
    # the detecting rank's own step-6 shards were already flushed, so a
    # peer could commit the poisoned dir AFTER this rank exited)
    pending = os.path.join(root, "ckpt-0000000006.tmp")
    os.makedirs(pending)
    assert cm.reject_unsafe(3) == 2  # ckpt-4 and the pending 6
    assert os.path.exists(os.path.join(root, "ckpt-0000000004",
                                       INTEGRITY_REJECTED_MARKER))
    assert os.path.exists(os.path.join(pending, INTEGRITY_REJECTED_MARKER))
    assert cm.restore(scope=scope) == 2
    assert monitor.counter("integrity.ckpt_rejected").value >= 1
    # a later save that reuses the step replaces the dir wholesale:
    # post-recovery checkpoints are trusted again
    cm.save(step=4)
    assert not os.path.exists(os.path.join(root, "ckpt-0000000004",
                                           INTEGRITY_REJECTED_MARKER))
    assert cm.restore(scope=scope) == 4


# ---- live digests ----------------------------------------------------------

def test_amortized_digest_overhead_budget(mon):
    period = 4
    s = Scope()
    rng = np.random.RandomState(0)
    for i in range(8):
        s.set_var(f"v{i}", rng.rand(64, 64).astype("f4"))
    total = sum(np.asarray(s.find_var(f"v{i}")).nbytes for i in range(8))
    d = integrity.StateDigester(s, period=period)
    c = monitor.counter("integrity.digest_bytes")
    per_step = []
    for step in range(period):
        before = c.value
        payload = d.on_step(step)
        per_step.append(c.value - before)
    # amortization contract: no single step hashes more than the worst
    # chunk (~total/period), and one full period covers every byte once
    assert max(per_step) <= d.max_step_digest_bytes()
    assert max(per_step) <= total // period + max(
        np.asarray(s.find_var(f"v{i}")).nbytes for i in range(8))
    assert sum(per_step) == total
    assert payload is not None and payload["e"] == 0
    assert monitor.counter("integrity.digests").value == 1
    # the composite equals a fresh full digest only chunk-wise — but the
    # SAME state digested twice must agree bit-exactly
    d2 = integrity.StateDigester(s, period=period)
    for step in range(period):
        p2 = d2.on_step(step)
    assert p2["d"] == payload["d"] and p2["c"] == payload["c"]


def test_disabled_sentinel_costs_nothing(mon):
    # FLAGS_integrity_check_period=0 (default): the resilient loop arms
    # no digester and no integrity counter ever moves
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(4, 4).astype("f4"),
              "y": rng.rand(4, 1).astype("f4")} for _ in range(4)]
    fluid.resilient_train_loop(exe, main, lambda: list(feeds), [loss],
                               scope=scope, max_inflight=1)
    counters = monitor.get_monitor().counter_values()
    assert not any(k.startswith("integrity.") and v
                   for k, v in counters.items()), counters
    assert integrity.current_payload() is None


def test_observe_gang_majority_vote_names_minority(mon):
    def pay(d, chunks, amax, step=3):
        return {"g": 0, "e": 1, "step": step, "p": 2, "n": 2,
                "d": d, "c": chunks, "amax": amax}

    tel = {0: {"dig": [pay("aaaa", ["x1", "y1"], [1.0, 1.0])]},
           1: {"dig": [pay("bbbb", ["x2", "y1"], [1.0, 1.0])]},
           2: {"dig": [pay("aaaa", ["x1", "y1"], [1.0, 1.0])]}}
    v = integrity.observe_gang(tel, world=3, observer_rank=0)
    assert v is not None
    assert v["corrupt_ranks"] == [1] and v["attributed"]
    assert v["chunk"] == 0
    assert monitor.counter("integrity.divergences").value == 1
    evs = [r for r in monitor.step_records()
           if r.get("kind") == "integrity_event"
           and r.get("action") == "divergence"]
    assert evs and evs[0]["corrupt_ranks"] == [1]


def test_observe_gang_tiebreak_against_agreed_baseline(mon):
    def pay(e, d, chunks, amax, step):
        return {"g": 0, "e": e, "step": step, "p": 2, "n": 2,
                "d": d, "c": chunks, "amax": amax}

    # epoch 0 agrees at amax ~1 (the baseline both ranks signed off on);
    # epoch 1 diverges with rank 1's chunk-0 amax at 1e37 — an
    # exponent-bit flip.  2 ranks cannot majority-vote; the baseline
    # jump names rank 1.
    tel = {0: {"dig": [pay(0, "eq", ["c0", "c1"], [1.0, 1.0], 1),
                       pay(1, "aaaa", ["x1", "y1"], [1.1, 1.0], 3)]},
           1: {"dig": [pay(0, "eq", ["c0", "c1"], [1.0, 1.0], 1),
                       pay(1, "bbbb", ["x2", "y1"], [1e37, 1.0], 3)]}}
    v = integrity.observe_gang(tel, world=2, observer_rank=0)
    assert v is not None
    assert v["corrupt_ranks"] == [1] and v["attributed"]
    # safe_step: the divergent chunk's digest point in the agreed epoch
    assert v["safe_step"] == 0 * 2 + 0
    # a tie with NO implausible jump stays unattributed (a low-mantissa
    # flip on a 2-rank gang is detected but not nameable)
    integrity.disarm_live_digests()
    monitor.reset()
    monitor.enable()
    tel2 = {0: {"dig": [pay(0, "eq", ["c0", "c1"], [1.0, 1.0], 1),
                        pay(1, "aaaa", ["x1", "y1"], [1.0, 1.0], 3)]},
            1: {"dig": [pay(0, "eq", ["c0", "c1"], [1.0, 1.0], 1),
                        pay(1, "bbbb", ["x2", "y1"], [1.0001, 1.0], 3)]}}
    v2 = integrity.observe_gang(tel2, world=2, observer_rank=0)
    assert v2 is not None and not v2["attributed"]
    assert v2["corrupt_ranks"] == [0, 1]


def test_divergence_verdict_drives_bit_identical_rollback(tmp_path, mon):
    """The single-process harness for the loop plumbing: a manufactured
    verdict latched mid-run must roll the resilient loop back to a
    checkpoint at or before safe_step and end bit-identical to an
    uninterrupted run (the gang-scale version lives in
    test_gang_flip_bit below)."""
    fluid.set_flags({"FLAGS_integrity_check_period": 2})
    try:
        main, startup, loss = _tiny_program()
        exe = fluid.Executor(fluid.CPUPlace())
        rng0 = np.random.RandomState(7)
        feeds = [{"x": rng0.rand(8, 4).astype("f4"),
                  "y": rng0.rand(8, 1).astype("f4")} for _ in range(16)]

        def run(root, poison):
            scope = fluid.Scope()
            exe.run(startup, scope=scope)
            cm = fluid.CheckpointManager(root, program=main, scope=scope,
                                         save_every_steps=4)
            fired = [False]

            def on_logged(step, vals):
                if poison and step == 9 and not fired[0]:
                    fired[0] = True
                    integrity.flag_divergence(
                        {"g": 0, "e": 4, "step": 9, "corrupt_ranks": [0],
                         "attributed": True, "chunk": 0, "safe_step": 8,
                         "digests": {0: "aa", 1: "bb"}})
            stats = fluid.resilient_train_loop(
                exe, main, lambda: list(feeds), [loss], scope=scope,
                checkpoint_manager=cm, max_inflight=1,
                on_logged=on_logged, max_steps=16)
            return stats, integrity.state_digest(scope)

        _, base_sha = run(str(tmp_path / "clean"), poison=False)
        stats, sha = run(str(tmp_path / "poisoned"), poison=True)
        assert stats.rollbacks == 1
        assert monitor.counter("integrity.rollbacks").value == 1
        assert sha == base_sha
        evs = [r for r in monitor.step_records()
               if r.get("kind") == "resilience_event"
               and r.get("action") == "rollback"
               and r.get("class") == "IntegrityError"]
        assert evs and evs[0]["corrupt_ranks"] == [0]
    finally:
        fluid.set_flags({"FLAGS_integrity_check_period": 0})


def test_payload_chunk_detail_capped_for_beat_transport(mon, monkeypatch):
    """Past _DETAIL_CHUNK_CAP chunks the payload drops per-chunk detail
    (beats ride single UDP datagrams and send() swallows EMSGSIZE — an
    unbounded payload would silently read as the rank going stale) but
    keeps the overall digest + overall amax: detection and the
    plausibility tiebreak still work, only chunk attribution degrades."""
    monkeypatch.setattr(integrity, "_DETAIL_CHUNK_CAP", 2)
    s = Scope()
    for i in range(4):
        s.set_var(f"v{i}", np.full((4,), float(i + 1), "f4"))
    d = integrity.StateDigester(s, period=4)
    for step in range(4):
        payload = d.on_step(step)
    assert payload is not None
    assert "c" not in payload and "amax" not in payload
    assert payload["amax_all"] == 4.0
    # chunkless payloads still vote: overall-amax jump vs the agreed
    # baseline names the corrupt rank
    def pay(e, dig, amax_all, step):
        return {"g": 0, "e": e, "step": step, "p": 4, "n": 4,
                "d": dig, "amax_all": amax_all}

    tel = {0: {"dig": [pay(0, "eq", 1.0, 3), pay(1, "aaaa", 1.0, 7)]},
           1: {"dig": [pay(0, "eq", 1.0, 3), pay(1, "bbbb", 1e30, 7)]}}
    v = integrity.observe_gang(tel, world=2, observer_rank=0)
    assert v is not None and v["corrupt_ranks"] == [1] and v["attributed"]
    assert v["chunk"] is None
    assert v["safe_step"] == 0  # degrades to the agreed epoch's start


def test_verdict_without_safe_step_is_terminal(tmp_path, mon):
    """No epoch ever agreed before the divergence => nothing on disk is
    provably clean; the loop must re-raise instead of restoring a
    checkpoint that may hold the corruption (docs: 'rather than
    guessing')."""
    fluid.set_flags({"FLAGS_integrity_check_period": 2})
    try:
        main, startup, loss = _tiny_program()
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        cm = fluid.CheckpointManager(str(tmp_path / "r"), program=main,
                                     scope=scope, save_every_steps=4)
        rng = np.random.RandomState(0)
        feeds = [{"x": rng.rand(4, 4).astype("f4"),
                  "y": rng.rand(4, 1).astype("f4")} for _ in range(12)]
        fired = [False]

        def on_logged(step, vals):
            if step == 6 and not fired[0]:
                fired[0] = True
                integrity.flag_divergence(
                    {"g": 0, "e": 3, "step": 6, "corrupt_ranks": [0],
                     "attributed": False, "chunk": None,
                     "safe_step": None, "digests": {0: "aa", 1: "bb"}})
        with pytest.raises(IntegrityError):
            fluid.resilient_train_loop(
                exe, main, lambda: list(feeds), [loss], scope=scope,
                checkpoint_manager=cm, max_inflight=1,
                on_logged=on_logged, max_steps=12)
    finally:
        fluid.set_flags({"FLAGS_integrity_check_period": 0})


# ---- fault specs -----------------------------------------------------------

def test_flip_bit_is_finite_and_rank_gated():
    s = Scope()
    s.set_var("b", np.zeros(1, "f4"))
    s.set_var("w", (np.random.RandomState(0).rand(16).astype("f4") - 0.5))
    before = np.asarray(s.find_var("w")).copy()
    # rank-gated: a non-matching rank leaves the state untouched
    inj = FaultInjector("flip_bit@3:1", rank=0)
    inj.on_state(3, s)
    np.testing.assert_array_equal(np.asarray(s.find_var("w")), before)
    assert inj.pending()
    # the matching rank flips ONE element of the LARGEST float var to a
    # wrong-but-FINITE value (the class every NaN guard waves through)
    inj = FaultInjector("flip_bit@3:1", rank=1)
    inj.on_state(3, s)
    after = np.asarray(s.find_var("w"))
    assert np.isfinite(after).all()
    diff = np.nonzero(after != before)[0]
    assert len(diff) == 1
    assert not inj.pending()
    inj.on_state(3, s)  # fires once


def test_rot_shard_ledger_replay_safety(tmp_path, monkeypatch):
    """rot_shard fires once per GANG: the ledger marker is created with
    O_EXCL before mutating, so a restarted incarnation (which replays
    the same commits) never re-rots, and two ranks observing the same
    commit race to exactly one mutation."""
    state = tmp_path / "faults"
    state.mkdir()
    monkeypatch.setenv("PADDLE_FAULT_STATE_DIR", str(state))
    ck = tmp_path / "ckpt-0000000002"
    ck.mkdir()
    np.save(str(ck / "w.p0s0.npy"), np.arange(32, dtype="f4"))
    pristine = open(str(ck / "w.p0s0.npy"), "rb").read()

    inj = FaultInjector("rot_shard@1")
    inj.on_commit(str(ck))           # commit 0: not the target
    assert open(str(ck / "w.p0s0.npy"), "rb").read() == pristine
    inj.on_commit(str(ck))           # commit 1: rots
    rotted = open(str(ck / "w.p0s0.npy"), "rb").read()
    assert rotted != pristine
    # a restarted incarnation replays the same commit sequence: the
    # ledger marker marks the entry spent, nothing re-rots
    inj2 = FaultInjector("rot_shard@1")
    inj2.on_commit(str(ck))
    inj2.on_commit(str(ck))
    assert open(str(ck / "w.p0s0.npy"), "rb").read() == rotted
    assert [f.kind for f in inj2.fired()] == ["rot_shard"]


def test_rot_shard_then_resume_walks_back_bit_identical(tmp_path, mon):
    """The rot_shard chaos closure, single-process: a committed-then-
    rotted checkpoint is rejected by digest on resume, the walk-back
    lands one earlier, and the resumed run ends bit-identical to a
    resume from a pristine tree."""
    main, startup, loss = _tiny_program()
    exe = fluid.Executor(fluid.CPUPlace())
    rng0 = np.random.RandomState(3)
    feeds = [{"x": rng0.rand(8, 4).astype("f4"),
              "y": rng0.rand(8, 1).astype("f4")} for _ in range(12)]

    def first_half(root, injector):
        scope = fluid.Scope()
        exe.run(startup, scope=scope)
        cm = fluid.CheckpointManager(root, program=main, scope=scope,
                                     save_every_steps=3)
        # 8 steps with save_every=3: commits land at the step-3 and
        # step-6 boundaries (a boundary only flushes when a later step
        # dispatches, so the run must outlive the second commit)
        fluid.resilient_train_loop(
            exe, main, lambda: list(feeds), [loss], scope=scope,
            checkpoint_manager=cm, injector=injector, max_inflight=1,
            max_steps=8)

    def resume(root):
        scope = fluid.Scope()
        cm = fluid.CheckpointManager(root, program=main, scope=scope,
                                     save_every_steps=3)
        fluid.resilient_train_loop(
            exe, main, lambda: list(feeds), [loss], scope=scope,
            checkpoint_manager=cm, resume=True, max_inflight=1,
            max_steps=12)
        return integrity.state_digest(scope)

    clean_root = str(tmp_path / "clean")
    rot_root = str(tmp_path / "rot")
    first_half(clean_root, None)
    # rot the SECOND commit (step 6) post-COMMIT; the resume must reject
    # it and restore step 3 instead
    first_half(rot_root, FaultInjector("rot_shard@1"))
    rej0 = monitor.counter("integrity.ckpt_rejected").value
    base_sha = resume(clean_root)
    sha = resume(rot_root)
    assert monitor.counter("integrity.ckpt_rejected").value == rej0 + 1
    assert sha == base_sha


# ---- publish fast-reject ---------------------------------------------------

def test_publish_digest_fast_reject_quarantines_before_staging(tmp_path, mon):
    from paddle_tpu import serving
    from paddle_tpu.errors import ServingError

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        out = layers.fc(x, 2, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    good = str(tmp_path / "good")
    io.save_inference_model(good, ["x"], [out], exe, main, scope)
    bad = str(tmp_path / "bad")
    scope.set_var("fc_0.w_0", np.asarray(scope.find_var("fc_0.w_0")) * 2)
    io.save_inference_model(bad, ["x"], [out], exe, main, scope)
    victim = next(f for f in sorted(os.listdir(bad))
                  if f.endswith(".npy"))
    _rot(os.path.join(bad, victim))

    registry = serving.ModelRegistry(place=fluid.CPUPlace())
    registry.load("m", good)
    xv = np.ones((1, 4), "f4")
    before = registry.acquire("m").run({"x": xv})[0]
    with pytest.raises(ServingError) as ei:
        serving.publish(registry, "m", bad)
    assert ei.value.reason == "publish_rejected"
    assert "manifest digest check failed" in str(ei.value)
    # the reject fired BEFORE the staging/smoke ladder: no staged scope,
    # no golden-smoke span was ever opened for the bad source
    spans = monitor.get_monitor().span_stats()
    assert "serving.publish_digest_check" in spans
    # old model keeps serving bit-identically
    np.testing.assert_array_equal(
        np.asarray(registry.acquire("m").run({"x": xv})[0]),
        np.asarray(before))
    # quarantined: the repeat publish rejects fast
    with pytest.raises(ServingError):
        serving.publish(registry, "m", bad)


# ---- tools: scrub + perf_report gate ---------------------------------------

def _run_tool(tool, *args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", tool), *args],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)


def test_scrub_check_clean_tree_and_each_rot_class(tmp_path):
    from paddle_tpu import recordio

    root = str(tmp_path / "tree")
    d = os.path.join(root, "ckpt-0000000002")
    s = Scope()
    s.set_var("w", np.arange(64, dtype="f4"))
    io.save_sharded(d, var_names=["w"], scope=s, process_index=0)
    rio = os.path.join(root, "data.rio")
    with recordio.Writer(rio, max_chunk_records=4) as w:
        for i in range(16):
            w.write(b"payload-%d" % i * 4)
    r = _run_tool("scrub.py", "--check", root)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CHECK OK" in r.stdout

    # rot class 1: flipped shard byte
    victim = next(f for f in sorted(os.listdir(d)) if f.endswith(".npy"))
    _rot(os.path.join(d, victim))
    r = _run_tool("scrub.py", "--check", root)
    assert r.returncode == 1 and "digest_mismatch" in r.stdout
    _rot(os.path.join(d, victim))  # un-rot (xor is its own inverse)

    # rot class 2: truncation (bytes mismatch)
    p = os.path.join(d, victim)
    payload = open(p, "rb").read()
    open(p, "wb").write(payload[:-8])
    r = _run_tool("scrub.py", "--check", root)
    assert r.returncode == 1 and "bytes_mismatch" in r.stdout
    open(p, "wb").write(payload)

    # rot class 3: a file the manifest names going missing
    os.rename(p, p + ".gone")
    r = _run_tool("scrub.py", "--check", root)
    assert r.returncode == 1 and "missing_file" in r.stdout
    os.rename(p + ".gone", p)

    # rot class 4: CRC-failed RecordIO chunk (the existing native path)
    from paddle_tpu.faults import _mutate_chunk

    assert _mutate_chunk([rio], 1, truncate=False)
    r = _run_tool("scrub.py", "--check", root)
    assert r.returncode == 1 and "corrupt_chunks" in r.stdout

    # rot class 5: a torn manifest is a finding, not a crash — and it
    # must not mask the other findings in the same tree
    with open(os.path.join(d, "__sharded_manifest__.json"), "w") as f:
        f.write('{"vars": [{"name": "tor')
    r = _run_tool("scrub.py", "--check", root)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "manifest_error" in r.stdout
    assert "corrupt_chunks" in r.stdout  # the walk survived past it


def test_perf_report_integrity_gate(tmp_path):
    # zero evidence must FAIL the gate
    empty = tmp_path / "empty.jsonl"
    empty.write_text(json.dumps({"kind": "step"}) + "\n")
    r = _run_tool("perf_report.py", "--check", str(empty),
                  "--max-integrity-mismatches", "0")
    assert r.returncode == 1 and "no integrity evidence" in r.stdout
    # counters-only evidence, clean: gate holds
    ok = tmp_path / "ok.jsonl"
    ok.write_text(json.dumps(
        {"counters": {"integrity.digests": 5,
                      "integrity.files_verified": 3}}) + "\n")
    r = _run_tool("perf_report.py", "--check", str(ok),
                  "--max-integrity-mismatches", "0")
    assert r.returncode == 0, r.stdout
    assert "integrity mismatches 0" in r.stdout
    # a divergence event past the budget fails, naming the action
    bad = tmp_path / "bad.jsonl"
    bad.write_text("\n".join([
        json.dumps({"kind": "integrity_event", "action": "divergence",
                    "corrupt_ranks": [1]}),
        json.dumps({"counters": {"integrity.divergences": 1}}),
    ]) + "\n")
    r = _run_tool("perf_report.py", "--check", str(bad),
                  "--max-integrity-mismatches", "0")
    assert r.returncode == 1 and "integrity mismatch" in r.stdout


# ---- the 2-process chaos matrix --------------------------------------------

GANG_ENV = {
    "RUN_STEPS": "24", "SAVE_EVERY": "2", "INTEGRITY_PERIOD": "2",
    "PT_STEP_SLEEP": "0.05",
    "FLAGS_dist_heartbeat_interval_s": "0.1",
    "FLAGS_dist_heartbeat_miss_factor": "40",
    "FLAGS_dist_watchdog_timeout_s": "60",
    "FLAGS_dist_bootstrap_timeout_s": "120",
}
INTEGRITY_WORKER = os.path.join(HERE, "dist_worker_integrity.py")


def _gang(tmp_path, tag, fault_spec=None, max_restarts=0):
    from paddle_tpu.launch import run_gang

    env = dict(GANG_ENV)
    if fault_spec:
        env["FLAGS_fault_spec"] = fault_spec
    return run_gang([sys.executable, INTEGRITY_WORKER], 2,
                    checkpoint_root=str(tmp_path / tag), extra_env=env,
                    max_restarts=max_restarts, timeout=240)


def _results(res):
    out = {}
    for rank, (code, o, _e) in enumerate(res.workers):
        for line in (o or "").splitlines():
            if line.startswith("RESULT "):
                out[rank] = json.loads(line[len("RESULT "):])
    return out


def test_gang_flip_bit_names_rank_and_recovers_bit_identical(tmp_path):
    """The acceptance pin: a flipped-yet-finite bit on rank 1 of a real
    2-process gang (a) diverges the live digests and the vote NAMES rank
    1, (b) quarantines every checkpoint the corruption could have
    reached, (c) restarts the gang, and (d) ends bit-identical to an
    uninterrupted baseline — the corruption leaves NO trace in the final
    model."""
    clean = _gang(tmp_path, "clean")
    assert clean.ok, clean.incidents
    base = _results(clean)
    assert len(set(r["params_sha"] for r in base.values())) == 1
    base_sha = base[0]["params_sha"]
    assert base[0]["digest_epochs"] > 0  # the sentinel actually ran

    chaos = _gang(tmp_path, "chaos", fault_spec="flip_bit@5:1",
                  max_restarts=2)
    assert chaos.ok, chaos.incidents
    assert chaos.restarts >= 1
    # SOME rank exits EXIT_INTEGRITY (45) on its own verdict — whichever
    # beat thread latches first; the OTHER rank follows as a classified
    # peer reaction (43) or is torn down by the coordination runtime.
    # The verdict itself is symmetric (computed from the same beat
    # payloads), so whoever raises, it must name rank 1 as corrupt.
    codes = {d["returncode"] for inc in chaos.incidents
             for d in inc["dead"]}
    assert 45 in codes, chaos.incidents
    all_stderr = "\n".join(e or "" for inc in chaos.history
                           for (_c, _o, e) in inc)
    assert "corrupt_ranks=[1]" in all_stderr
    assert "attributed=True" in all_stderr
    # quarantine + bit-identical recovery
    out = _results(chaos)
    assert all(r["ckpt_rejected"] >= 1 for r in out.values()), out
    shas = {r["params_sha"] for r in out.values()}
    assert shas == {base_sha}, (shas, base_sha)
