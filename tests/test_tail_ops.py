"""API-tail batch goldens (audit VERDICT r3 #6): numpy transcriptions of the
reference kernels (activation_op.h functors, smooth_l1_loss_op.h,
teacher_student_sigmoid_loss_op.h:26, pixel_shuffle_op.h, shuffle_channel_op.h,
temporal_shift_op.h, fsp_op.h, unfold_op.h, pool_op adaptive path, cvm_op.h,
add_position_encoding_op.h, bilinear_tensor_product_op.h, data_norm_op.cc)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import LoDTensor


def _run1(build, feed, n_fetch=1):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    outs = exe.run(main, feed=feed, fetch_list=list(fetches), scope=scope)
    return [np.asarray(o) for o in outs]


RNG = np.random.RandomState(0)
X = (RNG.randn(4, 6) * 3).astype("f4")


@pytest.mark.parametrize("fn,kw,ref", [
    ("brelu", {"t_min": -1.0, "t_max": 2.0}, lambda x: np.clip(x, -1, 2)),
    ("soft_relu", {"threshold": 3.0},
     lambda x: np.log1p(np.exp(np.clip(x, -3, 3)))),
    ("thresholded_relu", {"threshold": 0.5}, lambda x: np.where(x > 0.5, x, 0)),
    ("elu", {"alpha": 0.7},
     lambda x: np.where(x > 0, x, 0.7 * (np.exp(x) - 1))),
    ("hard_sigmoid", {"slope": 0.3, "offset": 0.4},
     lambda x: np.clip(0.3 * x + 0.4, 0, 1)),
    ("stanh", {"scale_a": 0.5, "scale_b": 2.0},
     lambda x: 2.0 * np.tanh(0.5 * x)),
    ("swish", {"beta": 1.5}, lambda x: x / (1 + np.exp(-1.5 * x))),
    ("hard_shrink", {"threshold": 1.0}, lambda x: np.where(np.abs(x) > 1, x, 0)),
    ("softshrink", {},
     lambda x: np.where(x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0))),
])
def test_unary_goldens(fn, kw, ref):
    def build():
        xv = fluid.layers.data("x", [6], dtype="float32")
        return [getattr(fluid.layers, fn)(xv, **kw)]

    (got,) = _run1(build, {"x": X})
    np.testing.assert_allclose(got, ref(X.astype("f8")), rtol=1e-5, atol=1e-5)


def test_rsqrt_sign_acos_family():
    xp = np.abs(X) + 0.5
    xu = np.clip(X / 10, -0.99, 0.99)

    def build():
        a = fluid.layers.data("a", [6], dtype="float32")
        u = fluid.layers.data("u", [6], dtype="float32")
        return [fluid.layers.rsqrt(a), fluid.layers.sign(a),
                fluid.layers.acos(u), fluid.layers.asin(u),
                fluid.layers.atan(u), fluid.layers.tanh_shrink(a)]

    rs, sg, ac, as_, at, ts = _run1(build, {"a": xp, "u": xu})
    np.testing.assert_allclose(rs, 1 / np.sqrt(xp), rtol=1e-5)
    np.testing.assert_allclose(sg, np.sign(xp), rtol=1e-6)
    np.testing.assert_allclose(ac, np.arccos(xu), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(as_, np.arcsin(xu), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(at, np.arctan(xu), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ts, xp - np.tanh(xp), rtol=1e-4, atol=1e-5)


def test_logic_and_probes():
    a = np.array([[1, 2], [3, 4]], "f4")
    b = np.array([[1, 3], [3, 3]], "f4")
    bad = np.array([1.0, np.inf, np.nan], "f4")

    def build():
        av = fluid.layers.data("a", [2], dtype="float32")
        bv = fluid.layers.data("b", [2], dtype="float32")
        cv = fluid.layers.data("c", [], dtype="float32")
        xb = fluid.layers.cast(av, "bool")
        yb = fluid.layers.cast(bv - 1.0, "bool")
        return [fluid.layers.less_equal(av, bv),
                fluid.layers.greater_equal(av, bv),
                fluid.layers.not_equal(av, bv),
                fluid.layers.logical_xor(xb, yb),
                fluid.layers.has_inf(cv), fluid.layers.has_nan(cv),
                fluid.layers.isfinite(cv),
                fluid.layers.reduce_all(fluid.layers.cast(av, "bool")),
                fluid.layers.reduce_any(fluid.layers.cast(av - 1.0, "bool"), dim=1)]

    le, ge, ne, lx, hi, hn, isf, ra, ry = _run1(
        build, {"a": a, "b": b, "c": bad})
    assert (le == (a <= b)).all() and (ge == (a >= b)).all()
    assert (ne == (a != b)).all()
    assert (lx == np.logical_xor(a != 0, (b - 1) != 0)).all()
    assert hi[0] and hn[0] and not isf[0]
    assert ra[()] == True  # noqa: E712
    assert (ry == np.any(a - 1 != 0, axis=1)).all()


def test_cos_sim_smooth_l1():
    x = RNG.randn(5, 8).astype("f4")
    y = RNG.randn(5, 8).astype("f4")

    def build():
        xv = fluid.layers.data("x", [8], dtype="float32")
        yv = fluid.layers.data("y", [8], dtype="float32")
        return [fluid.layers.cos_sim(xv, yv),
                fluid.layers.smooth_l1(xv, yv, sigma=2.0)]

    cs, sl = _run1(build, {"x": x, "y": y})
    ref_cs = (x * y).sum(1) / (np.linalg.norm(x, axis=1) * np.linalg.norm(y, axis=1))
    np.testing.assert_allclose(cs.reshape(-1), ref_cs, rtol=1e-4, atol=1e-5)
    s2 = 4.0
    d = (x - y).astype("f8")
    el = np.where(np.abs(d) < 1 / s2, 0.5 * d * d * s2, np.abs(d) - 0.5 / s2)
    np.testing.assert_allclose(sl.reshape(-1), el.sum(1), rtol=1e-4)


def test_teacher_student_sigmoid_loss_golden():
    x = np.array([0.5, -1.2, 2.0, -0.3], "f4").reshape(-1, 1)
    z = np.array([-2.0, -0.5, 0.7, 1.4], "f4").reshape(-1, 1)

    def np_ref(x, z):
        x = x.astype("f8")
        base = np.maximum(x, 0) + np.log1p(np.exp(-np.abs(x)))
        out = np.where(z < -1, base,
                       np.where(z < 0, base - x,
                                np.where(z < 1, 2 * base - x * z,
                                         2 * base - x - x * (z - 1))))
        return out

    def build():
        xv = fluid.layers.data("x", [1], dtype="float32")
        zv = fluid.layers.data("z", [1], dtype="float32")
        return [fluid.layers.teacher_student_sigmoid_loss(xv, zv)]

    (got,) = _run1(build, {"x": x, "z": z})
    np.testing.assert_allclose(got, np_ref(x, z), rtol=1e-5, atol=1e-6)


def test_pixel_shuffle_and_shuffle_channel_and_temporal_shift():
    x = RNG.randn(2, 8, 3, 3).astype("f4")  # r=2 -> [2, 2, 6, 6]
    xt = RNG.randn(6, 8, 2, 2).astype("f4")  # N=3 segs of T=2

    def build():
        xv = fluid.layers.data("x", [8, 3, 3], dtype="float32")
        tv = fluid.layers.data("t", [8, 2, 2], dtype="float32")
        return [fluid.layers.pixel_shuffle(xv, 2),
                fluid.layers.shuffle_channel(xv, 4),
                fluid.layers.temporal_shift(tv, 2, 0.25)]

    ps, sc, tsh = _run1(build, {"x": x, "t": xt})
    ref_ps = x.reshape(2, 2, 2, 2, 3, 3).transpose(0, 1, 4, 2, 5, 3).reshape(2, 2, 6, 6)
    np.testing.assert_allclose(ps, ref_ps)
    ref_sc = x.reshape(2, 4, 2, 3, 3).transpose(0, 2, 1, 3, 4).reshape(2, 8, 3, 3)
    np.testing.assert_allclose(sc, ref_sc)
    v = xt.reshape(3, 2, 8, 2, 2)
    ref_t = np.zeros_like(v)
    ref_t[:, :-1, :2] = v[:, 1:, :2]      # backward shift
    ref_t[:, 1:, 2:4] = v[:, :-1, 2:4]    # forward shift
    ref_t[:, :, 4:] = v[:, :, 4:]
    np.testing.assert_allclose(tsh, ref_t.reshape(6, 8, 2, 2))


def test_fsp_and_unfold():
    x = RNG.randn(2, 3, 4, 5).astype("f4")
    y = RNG.randn(2, 6, 4, 5).astype("f4")

    def build():
        xv = fluid.layers.data("x", [3, 4, 5], dtype="float32")
        yv = fluid.layers.data("y", [6, 4, 5], dtype="float32")
        return [fluid.layers.fsp_matrix(xv, yv),
                fluid.layers.unfold(xv, [3, 3], strides=1, paddings=1)]

    fsp, unf = _run1(build, {"x": x, "y": y})
    ref = np.einsum("bchw,bdhw->bcd", x, y) / 20.0
    np.testing.assert_allclose(fsp, ref, rtol=1e-4, atol=1e-5)
    # im2col reference: [N, C*kh*kw, oh*ow], (c, kh, kw)-major
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    cols = np.zeros((2, 3, 3, 3, 4, 5), "f4")
    for i in range(3):
        for j in range(3):
            cols[:, :, i, j] = xp[:, :, i:i + 4, j:j + 5]
    np.testing.assert_allclose(unf, cols.reshape(2, 27, 20), rtol=1e-6)


def test_adaptive_pools():
    x = RNG.randn(2, 3, 7, 5).astype("f4")

    def np_adaptive(x, oh, ow, op):
        out = np.zeros(x.shape[:2] + (oh, ow), "f8")
        for i in range(oh):
            for j in range(ow):
                hs, he = (i * 7) // oh, -(-((i + 1) * 7) // oh)
                ws, we = (j * 5) // ow, -(-((j + 1) * 5) // ow)
                blk = x[:, :, hs:he, ws:we]
                out[:, :, i, j] = blk.max((2, 3)) if op == "max" else blk.mean((2, 3))
        return out

    def build():
        xv = fluid.layers.data("x", [3, 7, 5], dtype="float32")
        return [fluid.layers.adaptive_pool2d(xv, [3, 2], "max"),
                fluid.layers.adaptive_pool2d(xv, [3, 2], "avg")]

    mx, av = _run1(build, {"x": x})
    np.testing.assert_allclose(mx, np_adaptive(x, 3, 2, "max"), rtol=1e-5)
    np.testing.assert_allclose(av, np_adaptive(x, 3, 2, "avg"), rtol=1e-5, atol=1e-6)


def test_batch_size_like_and_random_fillers():
    ref = np.zeros((5, 3), "f4")

    def build():
        rv = fluid.layers.data("r", [3], dtype="float32")
        fc = fluid.layers.fill_constant_batch_size_like(rv, [1, 7], "float32", 2.5)
        ur = fluid.layers.uniform_random_batch_size_like(rv, [1, 4], min=0.0, max=1.0)
        gr = fluid.layers.gaussian_random_batch_size_like(rv, [1, 4], mean=5.0, std=0.1)
        u = fluid.layers.uniform_random([6, 2], min=-2.0, max=-1.0)
        g = fluid.layers.gaussian_random([6, 2], mean=3.0, std=0.01)
        s = fluid.layers.sampling_id(fluid.layers.softmax(rv))
        return [fc, ur, gr, u, g, s]

    fc, ur, gr, u, g, s = _run1(build, {"r": ref})
    assert fc.shape == (5, 7) and (fc == 2.5).all()
    assert ur.shape == (5, 4) and (ur >= 0).all() and (ur <= 1).all()
    assert gr.shape == (5, 4) and abs(gr.mean() - 5.0) < 0.5
    assert (u >= -2).all() and (u <= -1).all()
    assert abs(g.mean() - 3.0) < 0.1
    assert s.shape == (5,) and (s >= 0).all() and (s < 3).all()


def test_shape_rank_sum_pad_unstack_range_is_empty():
    a = RNG.randn(3, 4).astype("f4")
    b = RNG.randn(3, 4).astype("f4")

    def build():
        av = fluid.layers.data("a", [4], dtype="float32")
        bv = fluid.layers.data("b", [4], dtype="float32")
        parts = fluid.layers.unstack(av, axis=1)
        return [fluid.layers.shape(av), fluid.layers.rank(av),
                fluid.layers.sum([av, bv]),
                fluid.layers.pad(av, [0, 1, 2, 0], pad_value=9.0),
                parts[1],
                fluid.layers.range(0, 10, 2, "int32"),
                fluid.layers.is_empty(av),
                fluid.layers.pad_constant_like(
                    fluid.layers.data("big", [6], dtype="float32"), av, 7.0)]

    sh, rk, sm, pd, p1, rg, ie, pcl = _run1(
        build, {"a": a, "b": b, "big": np.zeros((4, 6), "f4")})
    assert sh.tolist() == [3, 4] and rk[0] == 2
    np.testing.assert_allclose(sm, a + b, rtol=1e-6)
    assert pd.shape == (4, 6) and (pd[3] == 9.0).all() and (pd[:, :2] == 9.0).all()
    np.testing.assert_allclose(pd[:3, 2:], a, rtol=1e-6)
    np.testing.assert_allclose(p1, a[:, 1], rtol=1e-6)
    assert rg.tolist() == [0, 2, 4, 6, 8]
    assert not ie[0]
    # batch dim is dynamic (-1) at trace time -> unpadded; cols pad to 6
    assert pcl.shape == (3, 6)
    np.testing.assert_allclose(pcl[:, :4], a, rtol=1e-6)
    assert (pcl[:, 4:] == 7.0).all()


def test_add_position_encoding_and_bilinear_and_cvm():
    x = RNG.randn(2, 5, 8).astype("f4")
    cvm_x = np.abs(RNG.randn(4, 6)).astype("f4")
    cvm_sc = np.ones((4, 2), "f4")

    def build():
        xv = fluid.layers.data("x", [5, 8], dtype="float32")
        a = fluid.layers.data("a", [3], dtype="float32")
        b = fluid.layers.data("b", [4], dtype="float32")
        cx = fluid.layers.data("cx", [6], dtype="float32")
        cs = fluid.layers.data("cs", [2], dtype="float32")
        return [fluid.layers.add_position_encoding(xv, 0.5, 2.0),
                fluid.layers.bilinear_tensor_product(a, b, 7),
                fluid.layers.continuous_value_model(cx, cs, True),
                fluid.layers.continuous_value_model(cx, cs, False)]

    feed = {"x": x, "a": RNG.randn(2, 3).astype("f4"),
            "b": RNG.randn(2, 4).astype("f4"), "cx": cvm_x, "cs": cvm_sc}
    pe, btp, cvm1, cvm0 = _run1(build, feed)
    half = 4
    pos = np.arange(5, dtype="f8")[:, None]
    i = np.arange(half, dtype="f8")[None, :]
    ang = pos / np.power(10000.0, i / half)
    enc = np.concatenate([np.sin(ang), np.cos(ang)], axis=1)
    np.testing.assert_allclose(pe, 0.5 * x + 2.0 * enc[None], rtol=1e-4, atol=1e-5)
    assert btp.shape == (2, 7)
    show = np.log(cvm_x[:, 0:1] + 1)
    clk = np.log(cvm_x[:, 1:2] + 1) - show
    np.testing.assert_allclose(cvm1, np.concatenate([show, clk, cvm_x[:, 2:]], 1),
                               rtol=1e-5)
    np.testing.assert_allclose(cvm0, cvm_x[:, 2:], rtol=1e-6)


def test_sequence_reshape_golden():
    rows = [RNG.randn(2, 6).astype("f4"), RNG.randn(3, 6).astype("f4")]

    def build():
        xv = fluid.layers.data("x", [6], dtype="float32", lod_level=1)
        out = fluid.layers.sequence_reshape(xv, 3)
        pooled = fluid.layers.sequence_pool(out, "sum")
        return [out, pooled]

    out, pooled = _run1(build, {"x": LoDTensor(rows)})
    # row 0: 2 tokens * 6 = 12 values -> 4 tokens of 3
    np.testing.assert_allclose(out[0, :4], rows[0].reshape(4, 3), rtol=1e-6)
    np.testing.assert_allclose(out[1, :6], rows[1].reshape(6, 3), rtol=1e-6)
    np.testing.assert_allclose(pooled[0], rows[0].reshape(4, 3).sum(0), rtol=1e-5)


def test_data_norm_trains_stats():
    x = (RNG.randn(32, 5) * 2 + 3).astype("f4")

    def build():
        xv = fluid.layers.data("x", [5], dtype="float32")
        return [fluid.layers.data_norm(xv)]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        (y,) = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    # initial accumulators: size 1e4, sum 0, sqsum 1e4 -> mean 0, scale ~1
    (y1,) = exe.run(main, feed={"x": x}, fetch_list=[y], scope=scope)
    np.testing.assert_allclose(np.asarray(y1), x, rtol=1e-3, atol=1e-3)
    # after many repeats of the same batch the stats converge to the batch's
    for _ in range(3000):
        exe.run(main, feed={"x": x}, fetch_list=[y], scope=scope)
    (y2,) = exe.run(main, feed={"x": x}, fetch_list=[y], scope=scope)
    got = np.asarray(y2)
    np.testing.assert_allclose(got.mean(0), 0.0, atol=0.35)
    np.testing.assert_allclose(got.std(0), 1.0, atol=0.35)


def test_dice_and_npair_losses_composition():
    p = np.abs(RNG.rand(4, 10)).astype("f4")
    lab = (RNG.rand(4, 10) > 0.5).astype("f4")

    def build():
        pv = fluid.layers.data("p", [10], dtype="float32")
        lv = fluid.layers.data("l", [10], dtype="float32")
        anchor = fluid.layers.data("anc", [6], dtype="float32")
        pos = fluid.layers.data("pos", [6], dtype="float32")
        ids = fluid.layers.data("ids", [1], dtype="int64")
        return [fluid.layers.dice_loss(pv, lv),
                fluid.layers.npair_loss(anchor, pos, ids)]

    feed = {"p": p, "l": lab, "anc": RNG.randn(4, 6).astype("f4"),
            "pos": RNG.randn(4, 6).astype("f4"),
            "ids": np.arange(4, dtype="int64").reshape(4, 1)}
    dl, nl = _run1(build, feed)
    inse = (p * lab).sum(1)
    denom = p.sum(1) + lab.sum(1)
    ref = (1 - 2 * inse / (denom + 1e-5)).mean()
    np.testing.assert_allclose(float(dl), ref, rtol=1e-4)
    assert np.isfinite(nl).all()
