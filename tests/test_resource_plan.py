"""Static resource planner (paddle_tpu/core/resource_plan.py): liveness
peak-HBM + op cost model, and its four consumers.

Acceptance contract (ISSUE 12):
  * planted-defect tests per planner class — leaked live range,
    double-counted donated buffer, sub-block peak escaping to parent,
    persistable misclassified as temp — each asserting the WATERMARK names
    the offending op (same style as tests/test_analysis.py);
  * plan peak within the stated tolerance of measured truth on all 5 zoo
    programs (tools/resource_plan.py --check, the tier-1 calibration gate;
    the [CALIBRATION_RATIO_LO, CALIBRATION_RATIO_HI] band is the ratchet);
  * an over-budget program raises classified ResourceError naming the
    watermark ops BEFORE any XLA compile/allocate.
"""
import contextlib
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, monitor
from paddle_tpu.core import resource_plan as rp
from paddle_tpu.core.program import Operator
from paddle_tpu.errors import ResourceError, classify

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

F4 = 4  # float32 bytes


@contextlib.contextmanager
def _flag(name, value):
    old = fluid.get_flags([name])[name]
    fluid.set_flags({name: value})
    try:
        yield
    finally:
        fluid.set_flags({name: old})


def _watermark_vars(plan):
    return [w["var"] for w in plan.watermark]


# --------------------------------------------------------------------------
# planner semantics: planted defects, each naming the op
# --------------------------------------------------------------------------

def test_leaked_live_range_names_consumer_and_def_op():
    """A late reader of an early temp stretches its interval to itself —
    the watermark at the (now later) peak must name the leaked var AND its
    def op."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [256, 256], dtype="float32")
        y = layers.relu(x)    # big temp
        z = layers.relu(y)
        w = layers.relu(z)
    feed = {"x": (4, 256, 256)}
    base = rp.plan_program(main, feed, [w.name])
    # baseline: y dies after z's read; with a chain of equal-size temps the
    # peak holds ~2 temps + the fetched one
    blk = main.global_block()
    blk.ops.append(Operator(blk, "elementwise_add",
                            {"X": [w.name], "Y": [y.name]},
                            {"Out": [blk.create_var(
                                name="leak_out", shape=[-1, 256, 256],
                                dtype="float32").name]}))
    leaked = rp.plan_program(main, feed, ["leak_out"])
    assert leaked.peak_bytes > base.peak_bytes, \
        "a leaked live range must raise the planned peak"
    assert y.name in _watermark_vars(leaked)
    ent = next(w_ for w_ in leaked.watermark if w_["var"] == y.name)
    assert ent["def_op_type"] == "relu" and ent["def_op_idx"] == 0


def test_donated_inplace_update_counted_once():
    """An in-place persistable update (read + written, the executor's
    donation set) costs its buffer ONCE — the donation audit's `donated`
    class."""
    main = fluid.Program()
    blk = main.global_block()
    blk.create_parameter("w", shape=[512, 512], dtype="float32")
    blk.ops.append(Operator(blk, "scale", {"X": ["w"]}, {"Out": ["w"]},
                            {"scale": 1.1}))
    plan = rp.plan_program(main)
    W = 512 * 512 * F4
    assert plan.persistable_bytes == W
    assert plan.peak_bytes == W, \
        f"donated in-place update double-counted: {plan.peak_bytes} != {W}"
    assert plan.peak_temp_bytes == 0


def test_written_not_read_persistable_pays_double_buffer_and_names_op():
    """A persistable written but never read (donation audit's
    `copied_not_read`) CANNOT be aliased by XLA: its writer pays a
    transient second buffer and the watermark names that op."""
    main = fluid.Program()
    blk = main.global_block()
    blk.create_parameter("w", shape=[512, 512], dtype="float32")
    blk.create_parameter("w2", shape=[512, 512], dtype="float32")
    blk.ops.append(Operator(blk, "scale", {"X": ["w"]}, {"Out": ["w"]},
                            {"scale": 1.1}))
    blk.ops.append(Operator(blk, "assign", {"X": ["w"]}, {"Out": ["w2"]}))
    plan = rp.plan_program(main)
    W = 512 * 512 * F4
    assert plan.persistable_bytes == 2 * W
    assert plan.peak_bytes == 3 * W, \
        "copied_not_read persistable must cost a transient double buffer"
    assert plan.peak_op_type == "assign"
    assert "w2" in _watermark_vars(plan)


def test_sub_block_peak_charged_to_owner_and_does_not_escape():
    """Sub-block temps peak INSIDE the owning op (charged to it, named by
    it) and die at loop exit — an op after the loop must not carry them."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [16], dtype="float32")
        y = layers.relu(x)
    sub = main.create_block()
    sub.create_var(name="sub_big", shape=[1024, 1024], dtype="float32")
    sub.create_var(name="sub_out", shape=[1024, 1024], dtype="float32")
    sub.ops.append(Operator(sub, "fill_constant", {}, {"Out": ["sub_big"]},
                            {"shape": [1024, 1024], "value": 0.0,
                             "dtype": "float32"}))
    sub.ops.append(Operator(sub, "relu", {"X": ["sub_big"]},
                            {"Out": ["sub_out"]}))
    main.rollback()
    blk = main.global_block()
    blk.create_var(name="loop_out", shape=[-1, 16], dtype="float32")
    blk.ops.append(Operator(blk, "while", {"X": [y.name]},
                            {"Out": ["loop_out"]}, {"sub_block": sub.idx}))
    blk.ops.append(Operator(blk, "relu", {"X": [y.name]},
                            {"Out": [blk.create_var(
                                name="after", shape=[-1, 16],
                                dtype="float32").name]}))
    plan = rp.plan_program(main, {"x": (4, 16)}, ["after"])
    MB4 = 1024 * 1024 * F4
    assert plan.peak_op_type == "while", \
        "the sub-block peak must be charged to (and named by) the owner op"
    assert plan.peak_temp_bytes >= 2 * MB4  # sub_big + sub_out live together
    assert "sub_big" in _watermark_vars(plan)
    # the op AFTER the loop must not still carry the sub-block temps
    after_row = [r for r in plan.rows if r.op_type == "relu"][-1]
    assert after_row.live_bytes < MB4, \
        f"sub-block temps escaped to the parent: {after_row.live_bytes}"


def test_persistable_written_late_is_resident_not_a_temp():
    """A persistable written mid/late-block (BN stats, metric accumulators)
    is scope state resident for the WHOLE program — not an interval that
    starts at its writer."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8], dtype="float32")
        y = layers.relu(x)
    blk = main.global_block()
    blk.create_parameter("acc", shape=[1024, 256], dtype="float32")
    blk.ops.append(Operator(blk, "scale", {"X": ["acc"]}, {"Out": ["acc"]},
                            {"scale": 0.9}))
    plan = rp.plan_program(main, {"x": (4, 8)}, [y.name])
    ACC = 1024 * 256 * F4
    assert plan.persistable_bytes == ACC
    assert plan.peak_bytes >= ACC + plan.feed_bytes
    # resident state, not a live-range temp: it must not appear in the
    # temp watermark and the first op already pays for it via the base
    assert "acc" not in _watermark_vars(plan)
    assert all(r.live_bytes < ACC for r in plan.rows), \
        "persistable misclassified as a def/last-use temp"


def test_backward_extends_activations_and_defines_grads():
    """Ahead of a `backward` op every forward temp is potentially saved
    for the VJP (live until the backward), and the grad buffers its attrs
    name are defined there — the training-peak shape the zoo plans show."""
    from paddle_tpu import optimizer as opt

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [64], dtype="float32")
        h = layers.fc(x, 64, act="relu")
        loss = layers.mean(layers.fc(h, 1))
        opt.SGD(learning_rate=0.1).minimize(loss)
    plan = rp.plan_program(main, {"x": (8, 64)}, [loss.name])
    assert plan.peak_op_type == "backward"
    assert any(v.endswith("@GRAD") for v in _watermark_vars(plan))


# --------------------------------------------------------------------------
# cost model
# --------------------------------------------------------------------------

def test_matmul_cost_is_2mkn_and_coverage_complete():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [32, 64], dtype="float32")
        y = layers.fc(x, 128)  # mul + elementwise_add
    plan = rp.plan_program(main, {"x": (4, 32, 64)}, [y.name])
    mul = next(r for r in plan.rows if r.op_type == "mul")
    # fc flattens to [4*32, 64] @ [64, 128]
    assert mul.flops == 2 * (4 * 32) * 64 * 128
    assert plan.cost_coverage_frac == 1.0
    assert all(r.cost_covered for r in plan.rows)


def test_sub_block_body_rows_inherit_owner_grad_factor():
    """A sub-block executing ahead of a parent-block `backward` is
    differentiated too: its body rows must carry the owner's 3x factor
    (the planner once costed bodies at 1x — body-local liveness saw no
    backward)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [16], dtype="float32")
        y = layers.relu(x)
    sub = main.create_block()
    sub.create_var(name="body_out", shape=[-1, 16], dtype="float32")
    sub.ops.append(Operator(sub, "relu", {"X": [y.name]},
                            {"Out": ["body_out"]}))
    main.rollback()
    blk = main.global_block()
    blk.create_var(name="loop_out", shape=[-1, 16], dtype="float32")
    blk.ops.append(Operator(blk, "while", {"X": [y.name]},
                            {"Out": ["loop_out"]}, {"sub_block": sub.idx}))
    blk.create_var(name="loss", shape=[1], dtype="float32")
    blk.ops.append(Operator(blk, "mean", {"X": ["loop_out"]},
                            {"Out": ["loss"]}))
    blk.ops.append(Operator(blk, "backward", {"Loss": ["loss"]},
                            {"Grads": []},
                            {"loss_name": "loss", "param_names": [],
                             "grad_names": []}))
    plan = rp.plan_program(main, {"x": (4, 16)}, ["loss"])
    relu_rows = [r for r in plan.rows if r.op_type == "relu"]
    assert len(relu_rows) == 2  # parent x->y AND the body relu
    assert all(r.grad_factor == 3 for r in relu_rows), \
        "sub-block body ahead of backward must inherit the 3x factor"
    owner = next(r for r in plan.rows if r.op_type == "while")
    assert owner.grad_factor == 3


def test_grad_factor_3x_ahead_of_backward():
    from paddle_tpu import optimizer as opt

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [16], dtype="float32")
        loss = layers.mean(layers.fc(x, 4))
        opt.SGD(learning_rate=0.1).minimize(loss)
    plan = rp.plan_program(main, {"x": (2, 16)}, [loss.name])
    mul = next(r for r in plan.rows if r.op_type == "mul")
    sgd = next(r for r in plan.rows if r.op_type == "sgd")
    assert mul.grad_factor == 3   # fwd + 2x bwd
    assert sgd.grad_factor == 1   # the update itself runs once


# --------------------------------------------------------------------------
# consumer 1: the executor's OOM pre-check
# --------------------------------------------------------------------------

def test_over_budget_raises_resource_error_before_any_compile():
    """The acceptance bar: classified ResourceError (phase=build) naming
    the watermark ops, with ZERO compile-cache misses / recompiles — i.e.
    before any XLA work."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [256], dtype="float32")
        y = layers.fc(x, 256, act="relu")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with _flag("FLAGS_resource_precheck", "off"):
        exe.run(startup, scope=scope)
    miss0 = monitor.counter("executor.cache_miss").value
    rec0 = monitor.counter("executor.recompile").value
    with _flag("FLAGS_resource_hbm_limit_mb", 0.01):  # 10 KB: nothing fits
        with pytest.raises(ResourceError) as ei:
            exe.run(main, feed={"x": np.ones((4, 256), "f4")},
                    fetch_list=[y.name], scope=scope)
    e = ei.value
    assert e.phase == "build"
    assert e.watermark_ops, "the error must name the watermark ops"
    assert e.needed_bytes > e.limit_bytes
    assert classify(e) is e  # already classified; never re-wrapped
    assert monitor.counter("executor.cache_miss").value == miss0
    assert monitor.counter("executor.recompile").value == rec0, \
        "ResourceError must fire BEFORE any XLA compile"


def test_precheck_passes_and_program_runs_under_honest_limit():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8], dtype="float32")
        y = layers.relu(x)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    with _flag("FLAGS_resource_hbm_limit_mb", 64.0):
        out = exe.run(main, feed={"x": np.ones((2, 8), "f4")},
                      fetch_list=[y.name], scope=scope)
    assert np.allclose(out[0], 1.0)


def test_precheck_off_flag_skips_the_check():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [8], dtype="float32")
        y = layers.relu(x)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    with _flag("FLAGS_resource_precheck", "off"), \
            _flag("FLAGS_resource_hbm_limit_mb", 0.0001):
        out = exe.run(main, feed={"x": np.ones((2, 8), "f4")},
                      fetch_list=[y.name], scope=scope)
    assert np.allclose(out[0], 1.0)


# --------------------------------------------------------------------------
# consumer 2: serving budgets on plan bytes (weights + activations)
# --------------------------------------------------------------------------

def _save_serving_model(dirname, d_in=64, d_out=64):
    from paddle_tpu.core import unique_name

    with unique_name.guard():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", [d_in], dtype="float32")
            out = layers.fc(x, d_out, act="relu")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    fluid.io.save_inference_model(dirname, ["x"], [out], exe, main, scope)
    return dirname


def test_plan_model_bytes_counts_activations_past_manifest(tmp_path):
    from paddle_tpu import serving

    d = _save_serving_model(str(tmp_path / "m"))
    manifest = serving.manifest_weight_bytes(d)
    plan64 = serving.plan_model_bytes(d, 64)
    assert manifest > 0
    assert plan64 > manifest, \
        "the plan must see activations + feeds the manifest cannot"
    assert serving.plan_model_bytes(d, 256) > plan64  # scales with bucket


def test_serving_budget_refuses_on_plan_bytes_with_warm_buckets(tmp_path):
    """Budget sized between manifest weight bytes and the plan at the warm
    bucket: the manifest-only estimator would admit the load; the plan
    refuses it up front."""
    from paddle_tpu import serving
    from paddle_tpu.errors import ServingError

    d = _save_serving_model(str(tmp_path / "m"))
    manifest = serving.manifest_weight_bytes(d)
    plan = serving.plan_model_bytes(d, 64)
    budget_mb = (manifest + (plan - manifest) * 0.5) / 1e6
    reg = serving.ModelRegistry(place=fluid.CPUPlace(),
                                hbm_budget_mb=budget_mb)
    with pytest.raises(ServingError) as ei:
        reg.load("m", d, warm_buckets=(64,))
    assert ei.value.reason == "hbm_budget"
    # without warm buckets the documented fallback (manifest) admits it
    reg2 = serving.ModelRegistry(place=fluid.CPUPlace(),
                                 hbm_budget_mb=budget_mb)
    reg2.load("m", d)
    assert sorted(reg2.models()) == ["m"]


def test_unbudgeted_load_is_counted_and_evented(tmp_path):
    """The silent HBM-budget bypass, made loud: a model whose pre-load
    estimate is zero (empty/absent manifest, unplannable program) loads
    past FLAGS_serving_hbm_budget_mb unchecked — the registry counts it
    and records the event (fallback order: plan -> manifest -> post-load
    re-check only)."""
    from paddle_tpu import serving

    monitor.reset()
    monitor.enable()
    try:
        d = _save_serving_model(str(tmp_path / "m"))
        # blind both estimators: empty manifest vars + no plannable program
        with open(os.path.join(d, fluid.io.MANIFEST)) as f:
            man = json.load(f)
        man["vars"] = []
        with open(os.path.join(d, fluid.io.MANIFEST), "w") as f:
            json.dump(man, f)
        reg = serving.ModelRegistry(place=fluid.CPUPlace(), hbm_budget_mb=1.0)
        before = monitor.counter("serving.unbudgeted_loads").value
        reg.load("m", d)  # no warm_buckets: plan path not consulted
        assert monitor.counter("serving.unbudgeted_loads").value == before + 1
        evs = [r for r in monitor.step_records()
               if r.get("kind") == "serving_event"
               and r.get("action") == "unbudgeted_load"]
        assert evs and evs[-1]["model"] == "m"
    finally:
        monitor.disable()
        monitor.reset()


# --------------------------------------------------------------------------
# consumers 3+4: CLI gate (tier-1 wiring) + bench roofline column
# --------------------------------------------------------------------------

def _run_cli(*args, timeout=780):
    # single-device env like a standalone CLI run: conftest's 8-virtual-
    # device XLA_FLAGS would change XLA's buffer assignment (the
    # calibration truth) under the multi-device allocator
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "resource_plan.py"),
         *args],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=timeout)


def test_cli_check_zoo_plans_calibrate_within_tolerance():
    """THE acceptance gate: all 5 zoo programs plan cleanly, cost-rule
    coverage holds the floor, and plan peak stays inside the stated
    tolerance band of measured truth (XLA buffer assignment on CPU)."""
    r = _run_cli("--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CHECK OK" in r.stdout
    assert "calibration inside" in r.stdout


def test_cli_coverage_gate_trips_when_floor_unreachable():
    r = _run_cli("--check", "--program", "mnist", "--min-coverage", "1.01",
                 timeout=300)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "coverage" in r.stdout


def test_cli_bench_zero_evidence_fails(tmp_path):
    """The PR-8/PR-10 gate-hardening precedent: a BENCH file with no model
    records must FAIL the roofline comparison, not gate green."""
    p = tmp_path / "empty_bench.json"
    p.write_text(json.dumps({"metric": "nothing_useful", "value": 1}))
    r = _run_cli("--bench", str(p), timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "zero evidence" in r.stdout


def test_cli_bench_renders_predicted_vs_measured(tmp_path):
    p = tmp_path / "bench.json"
    p.write_text(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip", "value": 2704.0,
        "mfu_bf16_analytic": 0.168, "mfu_predicted_roofline": 0.196,
        "extra": {"models": {"bert": {"metric": "bert_...",
                                      "mfu_bf16_analytic": 0.402,
                                      "mfu_predicted_roofline": 0.368}}}}))
    r = _run_cli("--bench", str(p), timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "achieved_frac" in r.stdout and "0.86" in r.stdout


def test_cli_gap_rank_check_tiny_zoo():
    """ISSUE 17 tier-1 wiring: the gap ranking renders over the whole
    zoo with every cost row covered by a real FLOPs/traffic rule — an
    uncovered row (default 1-flop/elem model) would poison the ranking
    the kernel campaign walks, so --check fails on any."""
    r = _run_cli("--gap-rank", "--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CHECK OK" in r.stdout and "zero uncovered" in r.stdout
    # the campaign's own top targets from GAP_RANK.md stay in the table
    assert "matmul" in r.stdout and "op_type" in r.stdout


def test_cli_gap_rank_scales_by_bench_and_writes_artifact(tmp_path):
    """--bench supplies the measured side: op times scale by each model's
    predicted/measured MFU ratio, the scaling is disclosed in the render,
    and --out writes the committed artifact."""
    p = tmp_path / "bench.json"
    p.write_text(json.dumps({
        "metric": "resnet50_train_imgs_per_sec_per_chip", "value": 2704.0,
        "mfu_bf16_analytic": 0.168, "mfu_predicted_roofline": 0.196}))
    out = tmp_path / "gap_rank.md"
    r = _run_cli("--gap-rank", "--program", "resnet50", "--bench", str(p),
                 "--out", str(out), timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "time scaling (predicted/measured MFU)" in r.stdout
    assert "resnet50=" in r.stdout
    text = out.read_text()
    assert text.startswith("# roofline gap ranking")
    assert "scaled by bench.json" in text


def test_cli_gap_rank_zero_rows_fails(tmp_path):
    """Zero-evidence precedent: a ranking rendered from zero cost rows
    (nothing planned) must FAIL --check, not gate green."""
    r = _run_cli("--gap-rank", "--check", "--program", "no_such_model",
                 timeout=120)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "zero cost rows" in r.stdout


def test_cli_gap_rank_bench_without_mfu_warns_unscaled(tmp_path):
    """A bench file with no usable measured MFU must not silently render
    as if it were evidence-scaled."""
    p = tmp_path / "no_mfu.json"
    p.write_text(json.dumps({"metric": "x", "value": 1.0}))
    r = _run_cli("--gap-rank", "--program", "mnist", "--bench", str(p),
                 timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no usable measured MFU" in r.stdout


def test_perf_report_check_bench_names_roofline_gap(tmp_path):
    """perf_report --check-bench prints the predicted-MFU column and
    --min-roofline-frac turns a deep gap into a hard failure."""
    rec = {"metric": "resnet50_train_imgs_per_sec_per_chip", "value": 2704.0,
           "mfu_bf16_analytic": 0.169, "mfu_predicted_roofline": 0.9,
           "windows_ms": [10.0, 10.1], "spread_pct": 1.0,
           "extra": {"models": {"bert": {
               "metric": "bert_base_train_seqs_per_sec_per_chip",
               "mfu_bf16_analytic": 0.41, "spread_pct": 1.0}}}}
    p = tmp_path / "bench.json"
    p.write_text(json.dumps(rec))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    base = [sys.executable, os.path.join(REPO, "tools", "perf_report.py"),
            "--check-bench", str(p)]
    r = subprocess.run(base, capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "vs static roofline 0.9" in r.stdout
    r2 = subprocess.run(base + ["--min-roofline-frac", "0.5"],
                        capture_output=True, text=True, env=env, cwd=REPO,
                        timeout=120)
    assert r2.returncode == 1, r2.stdout + r2.stderr
    assert "static roofline" in r2.stdout


# --------------------------------------------------------------------------
# misc: serialized programs, plan dict round-trip
# --------------------------------------------------------------------------

def test_plan_serialized_program_roundtrip():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", [4], dtype="float32")
        y = layers.relu(x)
    clone = fluid.Program.parse_from_string(main.to_string())
    plan = rp.plan_program(clone, {"x": (2, 4)}, [y.name])
    d = plan.to_dict()
    assert d["peak_bytes"] == plan.peak_bytes
    json.dumps(d)  # JSON-serializable for the CLI --json path
