"""linear_chain_crf + crf_decoding vs brute-force enumeration (reference
kernels: operators/linear_chain_crf_op.h:54, crf_decoding_op.h:69; reference
tests: tests/unittests/test_linear_chain_crf_op.py, test_crf_decoding_op.py).

Transition layout: row 0 start, row 1 end, rows 2.. tag->tag."""
import itertools

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import LoDTensor


def _score(x, w, path):
    D = x.shape[1]
    s = w[0, path[0]] + w[1, path[-1]] + sum(x[t, p] for t, p in enumerate(path))
    s += sum(w[2 + path[t - 1], path[t]] for t in range(1, len(path)))
    return s


def _np_crf_nll(x, w, label):
    T, D = x.shape
    scores = [_score(x, w, p) for p in itertools.product(range(D), repeat=T)]
    m = max(scores)
    log_z = m + np.log(sum(np.exp(s - m) for s in scores))
    return log_z - _score(x, w, list(label))


def _np_viterbi(x, w):
    T, D = x.shape
    best, path = -np.inf, None
    for p in itertools.product(range(D), repeat=T):
        s = _score(x, w, p)
        if s > best:
            best, path = s, list(p)
    return path


def _build(with_label_decode=False):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        emis = fluid.layers.data("emis", [3], dtype="float32", lod_level=1)
        label = fluid.layers.data("label", [1], dtype="int64", lod_level=1)
        attr = fluid.ParamAttr(name="crfw")
        nll = fluid.layers.linear_chain_crf(emis, label, param_attr=attr)
        path = fluid.layers.crf_decoding(
            emis, param_attr=attr, label=label if with_label_decode else None)
    return main, startup, nll, path


def _run(main, startup, fetches, rows, lbls, w=None):
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    if w is not None:
        scope.set_var("crfw", w)
    outs = exe.run(main, feed={"emis": LoDTensor(rows), "label": LoDTensor(lbls)},
                   fetch_list=fetches, scope=scope)
    return [np.asarray(o) for o in outs]


RNG = np.random.RandomState(7)
ROWS = [RNG.randn(4, 3).astype("f4"), RNG.randn(2, 3).astype("f4"),
        RNG.randn(3, 3).astype("f4")]
LBLS = [np.array([[0], [2], [1], [1]], "int64"), np.array([[1], [0]], "int64"),
        np.array([[2], [2], [0]], "int64")]
W = (RNG.randn(5, 3) * 0.8).astype("f4")


def test_nll_matches_bruteforce():
    main, startup, nll, _ = _build()
    (got,) = _run(main, startup, [nll], ROWS, LBLS, w=W)
    got = got.reshape(-1)
    for i, (x, l) in enumerate(zip(ROWS, LBLS)):
        np.testing.assert_allclose(got[i], _np_crf_nll(x, W, l[:, 0]),
                                   rtol=1e-4, atol=1e-4)


def test_viterbi_matches_bruteforce():
    main, startup, _, path = _build()
    (got,) = _run(main, startup, [path], ROWS, LBLS, w=W)
    for i, x in enumerate(ROWS):
        T = x.shape[0]
        assert got[i, :T].tolist() == _np_viterbi(x, W), i
        assert (got[i, T:] == 0).all()


def test_decode_label_mode_is_correctness_indicator():
    main, startup, _, path = _build(with_label_decode=True)
    (got,) = _run(main, startup, [path], ROWS, LBLS, w=W)
    for i, (x, l) in enumerate(zip(ROWS, LBLS)):
        T = x.shape[0]
        expect = (np.array(_np_viterbi(x, W)) == l[:, 0]).astype("int64")
        assert got[i, :T].tolist() == expect.tolist(), i
        assert (got[i, T:] == 0).all()


def test_crf_grad_finite_difference():
    """d nll / d transition via autodiff vs central differences."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        emis = fluid.layers.data("emis", [3], dtype="float32", lod_level=1)
        label = fluid.layers.data("label", [1], dtype="int64", lod_level=1)
        nll = fluid.layers.linear_chain_crf(
            emis, label, param_attr=fluid.ParamAttr(name="crfw"))
        loss = fluid.layers.mean(nll)
        (gw,) = fluid.calc_gradient(loss, [main.global_block().var("crfw")])
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    scope.set_var("crfw", W)
    feed = {"emis": LoDTensor(ROWS), "label": LoDTensor(LBLS)}
    (g,) = exe.run(main, feed=feed, fetch_list=[gw], scope=scope)
    g = np.asarray(g)

    def f(wv):
        return float(np.mean([_np_crf_nll(x, wv, l[:, 0])
                              for x, l in zip(ROWS, LBLS)]))

    eps = 1e-3
    for idx in [(0, 1), (1, 2), (2, 0), (4, 1)]:
        wp, wm = W.astype("f8").copy(), W.astype("f8").copy()
        wp[idx] += eps
        wm[idx] -= eps
        num = (f(wp) - f(wm)) / (2 * eps)
        np.testing.assert_allclose(g[idx], num, rtol=2e-2, atol=1e-3)


def test_crf_trains_sequence_tagger():
    """label_semantic_roles-style slice: fc emissions + CRF loss trains to
    decreasing cost and the shared-param Viterbi decode fits the data."""
    rng = np.random.RandomState(3)
    D, C = 6, 4
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [D], dtype="float32", lod_level=1)
        label = fluid.layers.data("label", [1], dtype="int64", lod_level=1)
        emis = fluid.layers.fc(x, C, num_flatten_dims=2)
        attr = fluid.ParamAttr(name="crfw")
        nll = fluid.layers.linear_chain_crf(emis, label, param_attr=attr)
        loss = fluid.layers.mean(nll)
        fluid.optimizer.Adam(0.05).minimize(loss)
    decode_prog = main.clone(for_test=True)
    with fluid.program_guard(decode_prog):
        path = fluid.layers.crf_decoding(decode_prog.global_block().var(emis.name),
                                         param_attr=attr)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    # tokens carry their tag in a feature channel
    lens = [5, 3, 4, 6]
    lbls = [rng.randint(0, C, (t, 1)).astype("int64") for t in lens]
    rows = [(rng.randn(t, D) * 0.1).astype("f4") for t in lens]
    for r, l in zip(rows, lbls):
        r[np.arange(len(l)), l[:, 0]] += 2.0
    feed = {"x": LoDTensor(rows), "label": LoDTensor(lbls)}
    losses = []
    for _ in range(60):
        (lv,) = exe.run(main, feed=feed, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

    (paths,) = exe.run(decode_prog, feed=feed, fetch_list=[path], scope=scope)
    paths = np.asarray(paths)
    correct = total = 0
    for i, l in enumerate(lbls):
        correct += (paths[i, :len(l)] == l[:, 0]).sum()
        total += len(l)
    assert correct / total > 0.9, (correct, total)
