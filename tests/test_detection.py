"""Detection op subset: prior_box, iou_similarity, box_coder, yolo_box,
static-shape multiclass_nms (reference operators/detection/)."""
import numpy as np
import pytest

import paddle_tpu as fluid


def _run(build):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        feeds, fetches = build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    names = [f.name for f in fetches]
    return exe.run(main, feed=feeds, fetch_list=names, scope=scope)


def test_prior_box_shapes_and_geometry():
    def build():
        feat = fluid.layers.data("feat", [8, 4, 4], dtype="float32")
        img = fluid.layers.data("img", [3, 32, 32], dtype="float32")
        boxes, variances = fluid.layers.prior_box(
            feat, img, min_sizes=[8.0], max_sizes=[16.0],
            aspect_ratios=[2.0], flip=True, clip=True)
        feeds = {"feat": np.zeros((1, 8, 4, 4), "f4"),
                 "img": np.zeros((1, 3, 32, 32), "f4")}
        return feeds, [boxes, variances]

    boxes, variances = _run(build)
    # priors per cell: ars {1, 2, 1/2} x 1 min_size + 1 max_size = 4
    assert boxes.shape == (4, 4, 4, 4)
    assert variances.shape == (4, 4, 4, 4)
    np.testing.assert_allclose(variances[0, 0, 0], [0.1, 0.1, 0.2, 0.2])
    # first prior of cell (0,0): center (4,4) of 32x32, min_size 8 => square
    np.testing.assert_allclose(boxes[0, 0, 0],
                               [0.0, 0.0, 8.0 / 32, 8.0 / 32], atol=1e-6)
    assert boxes.min() >= 0.0 and boxes.max() <= 1.0  # clip


def test_iou_similarity_golden():
    def build():
        x = fluid.layers.data("x", [4], dtype="float32")
        y = fluid.layers.data("y", [4], dtype="float32")
        out = fluid.layers.iou_similarity(x, y)
        xv = np.array([[0, 0, 2, 2], [1, 1, 3, 3]], "f4")
        yv = np.array([[0, 0, 2, 2], [2, 2, 4, 4]], "f4")
        return {"x": xv, "y": yv}, [out]

    (iou,) = _run(build)
    np.testing.assert_allclose(iou, [[1.0, 0.0], [1 / 7, 1 / 7]], atol=1e-6)


def test_box_coder_encode_decode_roundtrip():
    rng = np.random.RandomState(0)
    priors = np.sort(rng.rand(5, 2, 2), axis=1).reshape(5, 4).astype("f4")
    targets = np.sort(rng.rand(5, 2, 2), axis=1).reshape(5, 4).astype("f4")
    pvar = np.full((5, 4), 0.1, "f4")

    def build_enc():
        p = fluid.layers.data("p", [4], dtype="float32")
        v = fluid.layers.data("v", [4], dtype="float32")
        t = fluid.layers.data("t", [4], dtype="float32")
        enc = fluid.layers.box_coder(p, v, t, code_type="encode_center_size")
        return {"p": priors, "v": pvar, "t": targets}, [enc]

    (enc,) = _run(build_enc)
    assert enc.shape == (5, 5, 4)
    deltas = enc[np.arange(5), np.arange(5)].astype("f4")  # diagonal: each target vs its prior

    def build_dec():
        p = fluid.layers.data("p", [4], dtype="float32")
        v = fluid.layers.data("v", [4], dtype="float32")
        t = fluid.layers.data("t", [4], dtype="float32")
        dec = fluid.layers.box_coder(p, v, t, code_type="decode_center_size")
        return {"p": priors, "v": pvar, "t": deltas}, [dec]

    (dec,) = _run(build_dec)
    np.testing.assert_allclose(dec, targets, atol=1e-5)


def test_yolo_box_shapes_and_center():
    A, C, H, W = 2, 3, 2, 2
    anchors = [10, 14, 23, 27]

    def build():
        x = fluid.layers.data("x", [A * (5 + C), H, W], dtype="float32")
        imgs = fluid.layers.data("imgs", [2], dtype="int64")
        boxes, scores = fluid.layers.yolo_box(x, imgs, anchors, C,
                                              conf_thresh=0.0,
                                              downsample_ratio=32)
        xv = np.zeros((1, A * (5 + C), H, W), "f4")
        return {"x": xv, "imgs": np.array([[64, 64]], "int64")}, [boxes, scores]

    boxes, scores = _run(build)
    assert boxes.shape == (1, A * H * W, 4)
    assert scores.shape == (1, A * H * W, C)
    # zero logits: sigmoid=0.5 -> first cell center at ((0+0.5)/2)*64 = 16
    cx = (boxes[0, 0, 0] + boxes[0, 0, 2]) / 2
    np.testing.assert_allclose(cx, 16.0, atol=1e-4)


def test_multiclass_nms_static_shape():
    def build():
        bb = fluid.layers.data("bb", [4, 4], dtype="float32")
        sc = fluid.layers.data("sc", [3, 4], dtype="float32")
        out = fluid.layers.multiclass_nms(bb, sc, score_threshold=0.1,
                                          nms_threshold=0.5, keep_top_k=5,
                                          background_label=0)
        boxes = np.array([[[0, 0, 1, 1], [0, 0, 1.05, 1.05],
                           [2, 2, 3, 3], [5, 5, 6, 6]]], "f4")
        scores = np.zeros((1, 3, 4), "f4")
        scores[0, 1] = [0.9, 0.8, 0.7, 0.05]   # class 1: two overlapping + one far
        scores[0, 2] = [0.0, 0.0, 0.0, 0.95]   # class 2: only the far box
        return {"bb": boxes, "sc": scores}, [out]

    (out,) = _run(build)
    assert out.shape == (1, 5, 6)
    dets = out[0]
    valid = dets[dets[:, 0] >= 0]
    # expected survivors: class2@0.95, class1@0.9, class1@0.7 (0.8 suppressed
    # by IoU with 0.9; 0.05 below threshold)
    assert len(valid) == 3
    np.testing.assert_allclose(valid[:, 1], [0.95, 0.9, 0.7], atol=1e-6)
    assert valid[0, 0] == 2 and valid[1, 0] == 1 and valid[2, 0] == 1


def test_roi_align_uniform_region():
    """A constant feature map must pool to that constant for any roi."""
    def build():
        x = fluid.layers.data("x", [2, 8, 8], dtype="float32")
        rois = fluid.layers.data("rois", [4], dtype="float32")
        out = fluid.layers.roi_align(x, rois, pooled_height=2, pooled_width=2,
                                     spatial_scale=1.0, sampling_ratio=2)
        xv = np.full((1, 2, 8, 8), 3.5, "f4")
        rv = np.array([[1.0, 1.0, 6.0, 6.0], [0.0, 0.0, 4.0, 4.0]], "f4")
        return {"x": xv, "rois": rv}, [out]

    (out,) = _run(build)
    assert out.shape == (2, 2, 2, 2)
    np.testing.assert_allclose(out, 3.5, atol=1e-5)


def test_roi_align_gradient_region():
    """A linear-in-x feature map pools to the bin centers' x coordinate."""
    def build():
        x = fluid.layers.data("x", [1, 8, 8], dtype="float32")
        rois = fluid.layers.data("rois", [4], dtype="float32")
        out = fluid.layers.roi_align(x, rois, pooled_height=1, pooled_width=2,
                                     spatial_scale=1.0, sampling_ratio=2)
        xv = np.tile(np.arange(8, dtype="f4")[None, None, None, :], (1, 1, 8, 1))
        rv = np.array([[2.0, 2.0, 6.0, 6.0]], "f4")
        return {"x": xv, "rois": rv}, [out]

    (out,) = _run(build)
    # roi x range [2, 6], two bins of width 2: centers at 3 and 5
    np.testing.assert_allclose(out.reshape(-1), [3.0, 5.0], atol=0.1)


def test_sigmoid_focal_loss_golden():
    def build():
        x = fluid.layers.data("x", [3], dtype="float32")
        label = fluid.layers.data("label", [1], dtype="int64")
        fg = fluid.layers.data("fg", [1], dtype="int32")
        out = fluid.layers.sigmoid_focal_loss(x, label, fg, gamma=2.0, alpha=0.25)
        xv = np.array([[0.5, -0.3, 1.2], [0.1, 0.8, -0.5]], "f4")
        lv = np.array([[1], [3]], "int64")  # class 1 / class 3 (cols 0, 2)
        return {"x": xv, "label": lv, "fg": np.array([[2]], "int32")}, [out]

    (out,) = _run(build)
    x = np.array([[0.5, -0.3, 1.2], [0.1, 0.8, -0.5]], "f4")
    t = np.zeros((2, 3), "f4")
    t[0, 0] = 1
    t[1, 2] = 1
    p = 1 / (1 + np.exp(-x))
    ce = np.maximum(x, 0) - x * t + np.log1p(np.exp(-np.abs(x)))
    pt = p * t + (1 - p) * (1 - t)
    at = 0.25 * t + 0.75 * (1 - t)
    ref = at * (1 - pt) ** 2 * ce / 2.0
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_roi_align_outside_image_is_zero():
    """ROIs past the border: samples beyond [-1, size] contribute zeros
    (reference roi_align_op.h), never extrapolated values."""
    def build():
        x = fluid.layers.data("x", [1, 4, 4], dtype="float32")
        rois = fluid.layers.data("rois", [4], dtype="float32")
        out = fluid.layers.roi_align(x, rois, pooled_height=1, pooled_width=1,
                                     sampling_ratio=1)
        xv = np.tile(np.arange(4, dtype="f4")[None, None, :, None], (1, 1, 1, 4))
        rv = np.array([[0.0, -8.0, 4.0, -4.0]], "f4")  # fully above the image
        return {"x": xv, "rois": rv}, [out]

    (out,) = _run(build)
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_sigmoid_focal_loss_ignore_label():
    def build():
        x = fluid.layers.data("x", [3], dtype="float32")
        label = fluid.layers.data("label", [1], dtype="int64")
        fg = fluid.layers.data("fg", [1], dtype="int32")
        out = fluid.layers.sigmoid_focal_loss(x, label, fg)
        xv = np.array([[2.0, -1.0, 0.5]], "f4")
        return {"x": xv, "label": np.array([[-1]], "int64"),
                "fg": np.array([[1]], "int32")}, [out]

    (out,) = _run(build)
    np.testing.assert_allclose(out, 0.0, atol=1e-7)  # ignored row: zero loss


def test_anchor_generator_geometry():
    def build():
        feat = fluid.layers.data("feat", [8, 2, 2], dtype="float32")
        anchors, variances = fluid.layers.anchor_generator(
            feat, anchor_sizes=[32.0], aspect_ratios=[1.0, 2.0], stride=[16, 16])
        return {"feat": np.zeros((1, 8, 2, 2), "f4")}, [anchors, variances]

    anchors, variances = _run(build)
    assert anchors.shape == (2, 2, 2, 4)
    # reference formula: x_ctr = 0*16 + 0.5*15 = 7.5; base 16x16 scaled by
    # 32/16 => 32x32; extents +/-0.5*31 => [-8, -8, 23, 23]
    np.testing.assert_allclose(anchors[0, 0, 0], [-8, -8, 23, 23], atol=1e-4)
    # ar = height/width = 2: base_w = round(sqrt(256/2)) = 11, base_h = 22
    w = anchors[0, 0, 1, 2] - anchors[0, 0, 1, 0] + 1
    h = anchors[0, 0, 1, 3] - anchors[0, 0, 1, 1] + 1
    np.testing.assert_allclose([w, h], [22.0, 44.0], atol=1e-4)


def test_box_clip():
    def build():
        b = fluid.layers.data("b", [2, 4], dtype="float32")
        info = fluid.layers.data("info", [3], dtype="float32")
        out = fluid.layers.box_clip(b, info)
        bv = np.array([[[-5, -5, 50, 50], [10, 10, 200, 300]]], "f4")
        iv = np.array([[200.0, 160.0, 2.0]], "f4")  # resized 200x160, scale 2
        return {"b": bv, "info": iv}, [out]

    (out,) = _run(build)
    # original image is 100x80: bounds h-1=99, w-1=79 (im_info/scale)
    np.testing.assert_allclose(out[0, 0], [0, 0, 50, 50])
    np.testing.assert_allclose(out[0, 1], [10, 10, 79, 99])


def test_density_prior_box_counts():
    def build():
        feat = fluid.layers.data("feat", [4, 2, 2], dtype="float32")
        img = fluid.layers.data("img", [3, 32, 32], dtype="float32")
        boxes, variances = fluid.layers.density_prior_box(
            feat, img, densities=[2], fixed_sizes=[8.0], fixed_ratios=[1.0])
        return {"feat": np.zeros((1, 4, 2, 2), "f4"),
                "img": np.zeros((1, 3, 32, 32), "f4")}, [boxes, variances]

    boxes, variances = _run(build)
    # density 2 => 4 shifted priors per cell
    assert boxes.shape == (2, 2, 4, 4)
    # reference grid: step_average=16, shift=8; cell (0,0) centers at
    # x in {4, 12}; 8x8 priors => first prior [0, 0, 8, 8]/32 (clamped)
    np.testing.assert_allclose(boxes[0, 0, 0], [0, 0, 8 / 32, 8 / 32], atol=1e-6)
    np.testing.assert_allclose(boxes[0, 0, 1, 0], (12 - 4) / 32, atol=1e-6)
    # interior prior is a full 8x8 square
    w = boxes[1, 1, 3, 2] - boxes[1, 1, 3, 0]
    np.testing.assert_allclose(w, 8.0 / 32, atol=1e-6)
