"""Host parameter service for out-of-HBM tables (reference pserver stack:
listen_and_serv sync loop + parameter_prefetch sparse pulls)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.param_server import HostTableEmbedding, KVClient, ParameterServer


def test_pull_push_roundtrip_sgd():
    srv = ParameterServer(optimizer="sgd", lr=0.5).start()
    try:
        c = KVClient(srv.endpoint)
        table = np.arange(12, dtype="f4").reshape(6, 2)
        c.create("t", table)
        rows = c.pull("t", np.array([1, 4]))
        np.testing.assert_allclose(rows, table[[1, 4]])
        # push grads (with a duplicate row: server must accumulate)
        c.push("t", np.array([1, 1, 4]), np.ones((3, 2), "f4"))
        after = c.fetch_table("t")
        exp = table.copy()
        exp[1] -= 0.5 * 2  # two grads on row 1
        exp[4] -= 0.5
        np.testing.assert_allclose(after, exp)
        c.close()
    finally:
        srv.stop()


def test_host_table_training_matches_in_hbm():
    """Training with the table on the HOST (pull rows -> device step ->
    push SelectedRows grad) must match the fully in-program sparse run."""
    V, D, F = 40, 4, 3
    rng = np.random.RandomState(0)
    table0 = rng.rand(V, D).astype("f4") * 0.2
    ids_stream = [rng.randint(0, V, size=(8, F)) for _ in range(6)]
    lbl_stream = [rng.rand(8, 1).astype("f4") for _ in range(6)]
    fc_w0 = rng.rand(F * D, 1).astype("f4") * 0.1

    def build(table_rows_feed):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            if table_rows_feed:
                # host-table variant: rows come in as a feed; ids are local
                rows = fluid.layers.data("rows", [D], dtype="float32")
                ids = fluid.layers.data("ids", [F], dtype="int64")
                label = fluid.layers.data("label", [1], dtype="float32")
                # device-side lookup over the PULLED block (is_sparse so the
                # grad comes back as SelectedRows over local positions);
                # feed 'rows' is a plain var, promoted to param-like by
                # passing it through the W slot directly
                emb = fluid.layers.reshape(
                    fluid.layers.gather(rows, fluid.layers.reshape(ids, [-1])),
                    [-1, F * D])
            else:
                ids = fluid.layers.data("ids", [F], dtype="int64")
                label = fluid.layers.data("label", [1], dtype="float32")
                e = fluid.layers.embedding(
                    ids, size=[V, D], is_sparse=True,
                    param_attr=fluid.ParamAttr(name="ps_tbl"))
                emb = fluid.layers.reshape(e, [-1, F * D])
            pred = fluid.layers.fc(emb, 1, param_attr=fluid.ParamAttr(name="ps_fc"),
                                   bias_attr=False)
            loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
            if table_rows_feed:
                grads = fluid.calc_gradient(loss, [rows])
                opt_ops, _ = fluid.optimizer.SGD(0.3).minimize(
                    loss, parameter_list=["ps_fc"])
                return main, startup, loss, grads[0]
            fluid.optimizer.SGD(0.3).minimize(loss)
            return main, startup, loss, None

    # --- reference: everything in-program (sparse embedding) -------------
    main_ref, startup_ref, loss_ref, _ = build(False)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup_ref, scope=scope)
    scope.set_var("ps_tbl", table0.copy())
    scope.set_var("ps_fc", fc_w0.copy())
    ref_losses = []
    for ids, lbl in zip(ids_stream, lbl_stream):
        (lv,) = exe.run(main_ref, feed={"ids": ids, "label": lbl},
                        fetch_list=[loss_ref], scope=scope)
        ref_losses.append(float(np.asarray(lv).reshape(-1)[0]))
    ref_table = np.asarray(scope.find_var("ps_tbl"))

    # --- host-table run ---------------------------------------------------
    srv = ParameterServer(optimizer="sgd", lr=0.3).start()
    try:
        client = KVClient(srv.endpoint)
        client.create("ps_tbl", table0.copy())
        hte = HostTableEmbedding(client, "ps_tbl", D)
        main_h, startup_h, loss_h, rows_grad = build(True)
        scope2 = fluid.Scope()
        exe.run(startup_h, scope=scope2)
        scope2.set_var("ps_fc", fc_w0.copy())
        host_losses = []
        for ids, lbl in zip(ids_stream, lbl_stream):
            uniq, local, rows = hte.prepare_batch(ids)
            (lv, gv) = exe.run(main_h,
                               feed={"rows": rows, "ids": local, "label": lbl},
                               fetch_list=[loss_h, rows_grad], scope=scope2)
            host_losses.append(float(np.asarray(lv).reshape(-1)[0]))
            hte.push_grad(uniq, np.asarray(gv))
        host_table = client.fetch_table("ps_tbl")
        client.close()
    finally:
        srv.stop()

    np.testing.assert_allclose(host_losses, ref_losses, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(host_table, ref_table, rtol=1e-4, atol=1e-5)


def test_server_error_reply_keeps_connection():
    srv = ParameterServer().start()
    try:
        c = KVClient(srv.endpoint)
        with pytest.raises(RuntimeError, match="KeyError"):
            c.pull("no_such_table", np.array([0]))
        # connection still usable after the error reply
        c.create("t2", np.ones((3, 2), "f4"))
        np.testing.assert_allclose(c.pull("t2", np.array([1])), [[1, 1]])
        c.close()
    finally:
        srv.stop()


def test_adagrad_push_merges_duplicates():
    srv = ParameterServer(optimizer="adagrad", lr=1.0).start()
    try:
        c = KVClient(srv.endpoint)
        c.create("t", np.zeros((3, 1), "f4"))
        c.push("t", np.array([1, 1]), np.array([[1.0], [2.0]], "f4"))
        after = c.fetch_table("t")
        # merged: g=3, acc=9, update=-1*3/(3+eps) ~ -1
        np.testing.assert_allclose(after[1], [-1.0], atol=1e-5)
        c.close()
    finally:
        srv.stop()


def test_async_communicator_converges_to_same_total():
    from paddle_tpu.param_server import AsyncCommunicator

    srv = ParameterServer(optimizer="sgd", lr=1.0).start()
    try:
        c = KVClient(srv.endpoint)
        c.create("t", np.zeros((5, 2), "f4"))
        comm = AsyncCommunicator(c, send_interval_s=0.002).start()
        rng = np.random.RandomState(0)
        total = np.zeros((5, 2), "f4")
        for _ in range(50):
            ids = rng.randint(0, 5, size=4)
            g = rng.rand(4, 2).astype("f4")
            comm.push_async("t", ids, g)
            np.add.at(total, ids, g)
        comm.stop()
        after = c.fetch_table("t")
        # async merging must not lose or double-count any gradient
        np.testing.assert_allclose(after, -total, rtol=1e-5, atol=1e-5)
        c.close()
    finally:
        srv.stop()
