"""Mixed-precision master-weight + optimizer-state regression tests.

Round-5 find (docs/perf_r05.md): bf16 models created bf16 parameters, whose
bf16 Adam beta-pow accumulators rounded 0.999 -> 1.0, making the bias-
corrected lr identically zero — bf16+Adam parameters silently never
trained (the r4 BERT bench trained only its f32 embedding/LN params).
Reference contract being pinned: mixed-precision training keeps f32 master
weights + f32 optimizer state (contrib/mixed_precision/decorator.py role).
"""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers, optimizer
from paddle_tpu.core.program import Program, program_guard


def _tiny_bf16_net():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = layers.data("x", [8], dtype="float32")
        y = layers.data("y", [1], dtype="float32")
        xb = layers.cast(x, "bfloat16")
        h = layers.fc(xb, 16, act="relu", param_attr=fluid.ParamAttr(name="w1"))
        o = layers.fc(h, 1, param_attr=fluid.ParamAttr(name="w2"))
        loss = layers.mean(layers.square_error_cost(layers.cast(o, "float32"), y))
        optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def test_bf16_params_are_f32_masters():
    main, _, _ = _tiny_bf16_net()
    block = main.global_block()
    assert str(block.var("w1").dtype) in ("float32", "fp32")
    assert str(block.var("w2").dtype) in ("float32", "fp32")


def test_bf16_adam_actually_trains():
    main, startup, loss = _tiny_bf16_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    xv = rng.rand(64, 8).astype("f4")
    yv = (xv.sum(1, keepdims=True) > 4).astype("f4")
    w0 = np.asarray(scope.find_var("w1")).copy()
    losses = []
    for _ in range(50):
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    w1 = np.asarray(scope.find_var("w1"))
    assert np.abs(w1 - w0).max() > 1e-4, "params froze (the r4 bf16+Adam bug)"
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_beta_pow_accumulators_are_f32():
    main, startup, _ = _tiny_bf16_net()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    names = [n for n in scope.var_names() if "beta1_pow" in n or "beta2_pow" in n]
    assert names, "no beta pow accumulators found"
    for n in names:
        v = np.asarray(scope.find_var(n))
        assert v.dtype == np.float32, (n, v.dtype)
        # the fatal symptom: bf16(0.999) == 1.0 exactly
        assert 0.0 < float(v.reshape(-1)[0]) < 1.0


def test_dygraph_params_are_f32_masters():
    import paddle_tpu.dygraph as dg

    with dg.guard():
        fc = dg.nn.Linear(4, 4, dtype="bfloat16")
        for p in fc.parameters():
            assert str(np.asarray(p.numpy()).dtype) == "float32", p.name
