"""Program-level PipelineOptimizer: device_guard-tagged repeated blocks cut
into a `pipeline` op; pp-mesh GPipe run matches the unpiped single-device
program (reference: optimizer.py:2661 PipelineOptimizer)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh


def _build(piped: bool, S=4, M=4, d=16, seed=7):
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [d], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        h = fluid.layers.fc(x, d, act="tanh")  # head (untagged)
        for s in range(S):
            ctx = fluid.device_guard(s) if piped else fluid.device_guard(None)
            with ctx:
                h = fluid.layers.fc(h, d, act="tanh",
                                    param_attr=fluid.ParamAttr(name=f"stage{s}_w"),
                                    bias_attr=fluid.ParamAttr(name=f"stage{s}_b"))
        pred = fluid.layers.fc(h, 1)  # tail
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        inner = fluid.optimizer.SGD(0.1)
        if piped:
            fluid.optimizer.PipelineOptimizer(inner, num_microbatches=M).minimize(loss)
        else:
            inner.minimize(loss)
    return main, startup, loss


def test_cut_structure():
    main, _, _ = _build(True)
    types = [op.type for op in main.global_block().ops]
    assert "pipeline" in types
    pipe = next(op for op in main.global_block().ops if op.type == "pipeline")
    assert pipe.attrs["num_stages"] == 4
    assert len(pipe.inputs["Params"]) == 8  # 4 stages x (w, b)
    assert len(main.blocks) >= 2
    # stage ops moved out of the main block
    assert types.count("mul") == 2  # head + tail fc only


def _train(main, startup, loss, mesh=None, steps=6, seed=0):
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    prog = fluid.CompiledProgram(main).with_mesh(mesh, batch_axis="dp") if mesh is not None else main
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        xv = rng.rand(16, 16).astype("f4")
        yv = np.tanh(xv.sum(1, keepdims=True)).astype("f4")
        (lv,) = exe.run(prog, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    return losses


def test_pipeline_sequential_matches_unpiped():
    ref = _train(*_build(False))
    got = _train(*_build(True))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_pipeline_pp4_matches_unpiped():
    ref = _train(*_build(False))
    mesh = make_mesh((4,), ("pp",))
    got = _train(*_build(True), mesh=mesh)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_pipeline_pp2_dp2_trains():
    mesh = make_mesh((2, 2, 2), ("dp", "pp", "mp"))
    losses = _train(*_build(True, S=2), mesh=mesh, steps=8)
    assert losses[-1] < losses[0]


def test_cut_rejects_heterogeneous_stages():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        with fluid.device_guard(0):
            h = fluid.layers.fc(x, 8, act="tanh")
        with fluid.device_guard(1):
            h = fluid.layers.fc(h, 8, act="relu")  # different act op
            h = fluid.layers.fc(h, 8)
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        with pytest.raises(ValueError, match="structurally identical"):
            fluid.optimizer.PipelineOptimizer(fluid.optimizer.SGD(0.1)).minimize(loss)


def test_cut_rejects_stateful_stage():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [8, 4, 4], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="float32")
        with fluid.device_guard(0):
            h = fluid.layers.batch_norm(fluid.layers.conv2d(x, 8, 3, padding=1))
        with fluid.device_guard(1):
            h = fluid.layers.batch_norm(fluid.layers.conv2d(h, 8, 3, padding=1))
        pool = fluid.layers.pool2d(h, global_pooling=True, pool_type="avg")
        pred = fluid.layers.fc(fluid.layers.reshape(pool, [-1, 8]), 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        with pytest.raises(ValueError, match="persistable"):
            fluid.optimizer.PipelineOptimizer(fluid.optimizer.SGD(0.1)).minimize(loss)
