"""fused_sdpa Pallas kernel goldens (interpret mode on the CPU mesh).

Reference semantics: scaled-dot-product attention as in the unfused
matmul/softmax stack (layers/nn.py multi-head attention) — the kernel must
match the jnp fallback in ops/nn_ops.py _fused_attention bit-for-bit-ish in
f32 (both compute f32 scores + f32 softmax).  Grads via the custom VJP's
recompute backward kernel vs jax.grad of the reference.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas_attention import fused_sdpa


def _ref(q, k, v, bias, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        Lq, Lk = s.shape[-2], s.shape[-1]
        s = jnp.where(jnp.tril(jnp.ones((Lq, Lk), bool), k=Lk - Lq), s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


@pytest.mark.parametrize("bias_kind,causal", [
    (None, False), ("bcast", False), ("per_head", True), (None, True),
])
def test_fused_sdpa_fwd_and_grad(bias_kind, causal):
    B, H, L, dh = 2, 4, 16, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, H, L, dh), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, L, dh), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, L, dh), jnp.float32)
    bias = None
    if bias_kind == "bcast":
        bias = jnp.asarray(rng.randn(B, 1, L, L) * 2, jnp.float32)
    elif bias_kind == "per_head":
        bias = jnp.asarray(rng.randn(B, H, L, L) * 2, jnp.float32)
    scale = 1.0 / np.sqrt(dh)

    out = fused_sdpa(q, k, v, bias, causal, scale, True)
    want = _ref(q, k, v, bias, causal, scale)
    assert np.allclose(out, want, atol=1e-5), np.abs(out - want).max()

    def f(q, k, v):
        return jnp.sum(jnp.sin(fused_sdpa(q, k, v, bias, causal, scale, True)))

    def g(q, k, v):
        return jnp.sum(jnp.sin(_ref(q, k, v, bias, causal, scale)))

    got = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    ref = jax.grad(g, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(got, ref):
        assert np.allclose(a, b, atol=1e-4), np.abs(a - b).max()


def test_fused_sdpa_cross_attention_lengths():
    # Lq != Lk (cross attention): kernel block specs carry distinct lengths
    B, H, Lq, Lk, dh = 1, 2, 8, 24, 8
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, H, Lq, dh), jnp.float32)
    k = jnp.asarray(rng.randn(B, H, Lk, dh), jnp.float32)
    v = jnp.asarray(rng.randn(B, H, Lk, dh), jnp.float32)
    out = fused_sdpa(q, k, v, None, False, 0.5, True)
    want = _ref(q, k, v, None, False, 0.5)
    assert np.allclose(out, want, atol=1e-5)
