"""Round-3b op batch: interpolate, pad2d, crop, Print, StaticRNN, warpctc."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.lod import LoDTensor

from op_test import OpTest


def test_nearest_interp_golden():
    x = np.arange(16, dtype="float32").reshape(1, 1, 4, 4)
    # exact 2x nearest upsample (align_corners=False): index floor(i/2)
    expected = x.repeat(2, axis=2).repeat(2, axis=3)

    class T(OpTest):
        def setUp(self):
            self.op_type = "nearest_interp"
            self.inputs = {"X": x}
            self.outputs = {"Out": expected}
            self.attrs = {"out_h": 8, "out_w": 8, "align_corners": False}

    T().check_output()


def test_bilinear_interp_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 5, 7).astype("float32")
    out_h, out_w = 9, 11

    def ref(x, oh, ow):  # align_corners=True bilinear
        n, c, h, w = x.shape
        ys = np.linspace(0, h - 1, oh)
        xs = np.linspace(0, w - 1, ow)
        y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0).reshape(1, 1, oh, 1)
        wx = (xs - x0).reshape(1, 1, 1, ow)
        g00 = x[:, :, y0][:, :, :, x0]
        g01 = x[:, :, y0][:, :, :, x1]
        g10 = x[:, :, y1][:, :, :, x0]
        g11 = x[:, :, y1][:, :, :, x1]
        return (g00 * (1 - wx) + g01 * wx) * (1 - wy) + (g10 * (1 - wx) + g11 * wx) * wy

    class T(OpTest):
        def setUp(self):
            self.op_type = "bilinear_interp"
            self.inputs = {"X": x}
            self.outputs = {"Out": ref(x, out_h, out_w).astype("float32")}
            self.attrs = {"out_h": out_h, "out_w": out_w, "align_corners": True}

    T().check_output(atol=1e-5)


def test_pad2d_modes():
    x = np.arange(12, dtype="float32").reshape(1, 1, 3, 4)
    for mode, np_mode in (("constant", "constant"), ("reflect", "reflect"), ("edge", "edge")):
        kw = {"constant_values": 2.5} if mode == "constant" else {}
        expected = np.pad(x, ((0, 0), (0, 0), (1, 2), (2, 1)), mode=np_mode, **kw)

        class T(OpTest):
            def setUp(self):
                self.op_type = "pad2d"
                self.inputs = {"X": x}
                self.outputs = {"Out": expected}
                self.attrs = {"paddings": [1, 2, 2, 1], "mode": mode, "pad_value": 2.5}

        T().check_output()


def test_crop_golden():
    x = np.arange(60, dtype="float32").reshape(3, 4, 5)

    class T(OpTest):
        def setUp(self):
            self.op_type = "crop"
            self.inputs = {"X": x}
            self.outputs = {"Out": x[1:3, 0:2, 2:5]}
            self.attrs = {"offsets": [1, 0, 2], "shape": [2, 2, 3]}

    T().check_output()


def test_print_layer_passthrough(capfd):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3], dtype="float32")
        y = fluid.layers.Print(x, message="dbg: ")
        z = y * 2.0
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    xv = np.ones((2, 3), "float32")
    (zv,) = exe.run(main, feed={"x": xv}, fetch_list=[z], scope=scope)
    np.testing.assert_allclose(zv, xv * 2)


def test_static_rnn_cumsum():
    """StaticRNN over a dense [b, T, f] input: running sum memory."""
    rng = np.random.RandomState(0)
    xv = rng.rand(3, 5, 4).astype("float32")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [5, 4], dtype="float32")
        rnn = fluid.layers.StaticRNN()
        with rnn.block():
            step = rnn.step_input(x)
            acc = rnn.memory(shape=[4], value=0.0)
            new = acc + step
            rnn.update_memory(acc, new)
            rnn.output(new)
        out = rnn()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (ov,) = exe.run(main, feed={"x": xv}, fetch_list=[out], scope=scope)
    np.testing.assert_allclose(np.asarray(ov), np.cumsum(xv, axis=1), atol=1e-5)


def _np_ctc_loss(logits, labels, blank=0):
    """Brute-force CTC by enumerating alignments (tiny T only)."""
    import itertools

    T, C = logits.shape
    m = logits.max(-1, keepdims=True)
    logp = logits - m - np.log(np.exp(logits - m).sum(-1, keepdims=True))
    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        # collapse: remove repeats then blanks
        collapsed = []
        prev = None
        for s in path:
            if s != prev:
                collapsed.append(s)
            prev = s
        collapsed = [s for s in collapsed if s != blank]
        if collapsed == list(labels):
            lp = sum(logp[t, path[t]] for t in range(T))
            total = np.logaddexp(total, lp)
    return -total


def test_warpctc_matches_bruteforce():
    rng = np.random.RandomState(0)
    T, C = 4, 3  # blank + 2 symbols; 3^4 = 81 paths to enumerate
    rows = [rng.randn(T, C).astype("f4"), rng.randn(3, C).astype("f4")]
    lbls = [np.array([[1], [2]], "int64"), np.array([[2]], "int64")]

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        logits = fluid.layers.data("logits", [C], dtype="float32", lod_level=1)
        label = fluid.layers.data("label", [1], dtype="int64", lod_level=1)
        loss = fluid.layers.warpctc(logits, label)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (lv,) = exe.run(main, feed={"logits": LoDTensor(rows), "label": LoDTensor(lbls)},
                    fetch_list=[loss], scope=scope)
    lv = np.asarray(lv).reshape(-1)
    for i, (row, lab) in enumerate(zip(rows, lbls)):
        ref = _np_ctc_loss(row, lab[:, 0].tolist())
        np.testing.assert_allclose(lv[i], ref, rtol=1e-4, atol=1e-4)


def test_warpctc_trainable():
    """CTC loss decreases when training toward a fixed target."""
    rng = np.random.RandomState(1)
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6], dtype="float32", lod_level=1)
        label = fluid.layers.data("label", [1], dtype="int64", lod_level=1)
        proj = fluid.layers.fc(x, 5, num_flatten_dims=2)
        loss = fluid.layers.mean(fluid.layers.warpctc(proj, label))
        fluid.optimizer.Adam(0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rows = [rng.rand(6, 6).astype("f4") for _ in range(4)]
    lbls = [np.array([[1], [3]], "int64") for _ in range(4)]
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"x": LoDTensor(rows), "label": LoDTensor(lbls)},
                        fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
