"""Transformer NMT on the ragged path (BASELINE.md: "Transformer-base NMT
(ragged/LoD path)").  Reference test pattern: book test_machine_translation
trains to a loss threshold; dist_transformer asserts loss trajectories."""
import numpy as np

import paddle_tpu as fluid
from paddle_tpu.models import nmt


def _build(**kw):
    cfg = dict(src_vocab=64, tgt_vocab=64, d_model=32, n_layers=1, n_heads=2,
               d_ff=64, dropout=0.0, warmup_steps=10, learning_rate=1.0)
    cfg.update(kw)
    return nmt.build_transformer_nmt(**cfg)


class TestNMTRagged:
    def test_trains_on_variable_length_batches(self):
        main, startup, feeds, fetches = _build()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        lens = [([3, 5, 2, 6], [4, 2, 5, 3]), ([7, 4, 3, 5], [6, 3, 4, 2]),
                ([2, 2, 4, 3], [3, 5, 2, 4])]
        losses = []
        for step in range(40):
            ls, lt = lens[step % len(lens)]
            feed = nmt.make_fake_nmt_batch(ls, lt, 64, 64, seed=step % 3)
            (lv,) = exe.run(main, feed=feed, fetch_list=[fetches["loss"]])
            losses.append(float(np.asarray(lv).ravel()[0]))
        assert np.isfinite(losses).all()
        # memorizes the 3 repeated fake batches: loss must drop materially
        # (40 steps: at 30 the run sat within noise of the 0.7 bound —
        # ratio 0.714 on this backend's unseeded-init draw)
        assert losses[-1] < losses[0] * 0.7, losses

    def test_bounded_recompiles_across_length_drift(self):
        main, startup, feeds, fetches = _build()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        feed = nmt.make_fake_nmt_batch([3, 5], [4, 2], 64, 64)
        exe.run(main, feed=feed, fetch_list=[fetches["loss"]])
        n = len(exe._cache)
        # same buckets (<=8), different max lens
        for ls, lt in (([2, 7], [5, 6]), ([8, 1], [8, 3])):
            feed = nmt.make_fake_nmt_batch(ls, lt, 64, 64)
            exe.run(main, feed=feed, fetch_list=[fetches["loss"]])
        assert len(exe._cache) == n

    def test_padding_invariance(self):
        """Same ragged content padded to different bucket lengths gives the
        same loss: proves no padded position leaks into loss or attention."""
        from paddle_tpu.lod import LoDTensor

        main, startup, feeds, fetches = _build(dropout=0.0, with_optimizer=False)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(startup)
        rng = np.random.RandomState(0)
        src = [rng.randint(1, 64, (l, 1)).astype("int64") for l in (3, 5)]
        tgt = [rng.randint(1, 64, (l, 1)).astype("int64") for l in (4, 2)]
        lbl = [rng.randint(1, 64, (l, 1)).astype("int64") for l in (4, 2)]

        def run(bucket_s, bucket_t):
            feed = {}
            for name, seqs, bucket in (("src_word", src, bucket_s),
                                       ("trg_word", tgt, bucket_t),
                                       ("lbl_word", lbl, bucket_t)):
                padded, lens = LoDTensor(seqs).padded(bucket=bucket)
                feed[name] = padded
                feed[name + "@LOD"] = lens
            (lv,) = exe.run(main, feed=feed, fetch_list=[fetches["loss"]])
            return float(np.asarray(lv).ravel()[0])

        l8 = run(8, 8)
        l16 = run(16, 24)
        np.testing.assert_allclose(l8, l16, rtol=1e-4)
