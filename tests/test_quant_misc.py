"""Quantization (contrib.slim), install_check, word2vec."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib.slim import post_training_quantize, quant_aware


def _mnist_ish():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 4
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [1, 8, 8], dtype="float32")
        y = fluid.layers.data("y", [1], dtype="int64")
        c = fluid.layers.conv2d(x, 4, 3, padding=1, act="relu")
        flat = fluid.layers.reshape(c, [-1, 4 * 8 * 8])
        logits = fluid.layers.fc(flat, 4)
        loss = fluid.layers.mean(fluid.layers.softmax_with_cross_entropy(logits, y))
        fluid.optimizer.Adam(0.01).minimize(loss)
    return main, startup, loss


def test_quant_aware_inserts_fake_quant_and_trains():
    main, startup, loss = _mnist_ish()
    n = quant_aware(main, weight_bits=8)
    assert n >= 4  # weights + activations of conv and the fc muls
    types = [op.type for op in main.global_block().ops]
    assert "fake_quantize_abs_max" in types
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(25):
        xv = rng.rand(16, 1, 8, 8).astype("f4")
        yv = (xv.mean(axis=(1, 2, 3)) * 4).astype("int64").clip(0, 3)[:, None]
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0], (losses[0], losses[-1])  # STE grads flow


def test_post_training_quantize_snaps_weights():
    main, startup, loss = _mnist_ish()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    params = [p.name for p in main.all_parameters()
              if np.asarray(scope.find_var(p.name)).ndim >= 2]
    before = {n: np.asarray(scope.find_var(n)).copy() for n in params}
    scales = post_training_quantize(scope, main)
    assert set(scales) >= set(params)  # every weight of a quantizable op
    for n, sc in scales.items():
        w = np.asarray(scope.find_var(n))
        q = w / sc * 127.0
        np.testing.assert_allclose(q, np.round(q), atol=1e-3)  # on the grid
        assert np.abs(w - before[n]).max() <= sc / 127.0 + 1e-7  # small error
    # program still runs
    exe.run(main, feed={"x": np.zeros((2, 1, 8, 8), "f4"),
                        "y": np.zeros((2, 1), "int64")},
            fetch_list=[loss], scope=scope)


def test_install_check(capsys):
    fluid.install_check.run_check()
    out = capsys.readouterr().out
    assert "install check passed" in out


def test_word2vec_converges():
    from paddle_tpu.models.vision import build_word2vec

    main, startup, feeds, fetches = build_word2vec(dict_size=50, embed_size=8,
                                                   hidden_size=16, n=4,
                                                   learning_rate=0.05)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(60):
        # deterministic rule: target = first context word (direct copy)
        ws = [rng.randint(0, 50, (32, 1)).astype("int64") for _ in range(3)]
        tgt = ws[0].copy()
        feed = {f"w{i}": w for i, w in enumerate(ws)}
        feed["target"] = tgt
        (lv,) = exe.run(main, feed=feed, fetch_list=[fetches["loss"]], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
