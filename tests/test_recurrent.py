"""dynamic_lstm / dynamic_gru fused recurrent layers + beam-search decode
(reference: layers/nn.py:420 dynamic_lstm, dynamic_gru; math/beam_search.cu)."""
import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.lod import LoDTensor


def _np_lstm(x_rows, w, bias, use_peepholes, D):
    """Row-by-row numpy LSTM matching the {c,i,f,o} fluid layout."""
    bias = bias.reshape(-1)
    gb = bias[:4 * D]
    w_ic = bias[4 * D:5 * D] if use_peepholes else 0
    w_fc = bias[5 * D:6 * D] if use_peepholes else 0
    w_oc = bias[6 * D:7 * D] if use_peepholes else 0
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    outs = []
    for row in x_rows:
        h = np.zeros(D)
        c = np.zeros(D)
        hs = []
        for xt in row:
            g = xt + h @ w + gb
            gc, gi, gf, go = g[:D], g[D:2 * D], g[2 * D:3 * D], g[3 * D:]
            i = sig(gi + w_ic * c)
            f = sig(gf + w_fc * c)
            cand = np.tanh(gc)
            c = f * c + i * cand
            o = sig(go + w_oc * c)
            h = o * np.tanh(c)
            hs.append(h.copy())
        outs.append(np.stack(hs))
    return outs


@pytest.mark.parametrize("use_peepholes", [False, True])
def test_dynamic_lstm_golden(use_peepholes):
    D = 5
    rng = np.random.RandomState(0)
    lengths = [4, 2, 6]
    rows = [rng.randn(l, 4 * D).astype("f4") * 0.3 for l in lengths]

    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4 * D], dtype="float32", lod_level=1)
        hidden, cell = fluid.layers.dynamic_lstm(
            x, size=4 * D, use_peepholes=use_peepholes,
            param_attr=fluid.ParamAttr(name=f"lstm_w_{use_peepholes}"),
            bias_attr=fluid.ParamAttr(name=f"lstm_b_{use_peepholes}"))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    w = np.asarray(scope.find_var(f"lstm_w_{use_peepholes}"))
    b = np.asarray(scope.find_var(f"lstm_b_{use_peepholes}"))
    (hv,) = exe.run(main, feed={"x": LoDTensor(rows)}, fetch_list=[hidden], scope=scope)
    hv = np.asarray(hv)  # [b, T, D] padded
    ref = _np_lstm(rows, w, b, use_peepholes, D)
    for i, l in enumerate(lengths):
        np.testing.assert_allclose(hv[i, :l], ref[i], atol=1e-5, rtol=1e-4)
        np.testing.assert_allclose(hv[i, l:], 0.0, atol=1e-7)  # masked tail


def test_dynamic_lstm_reverse_runs():
    D = 3
    rng = np.random.RandomState(1)
    rows = [rng.randn(l, 4 * D).astype("f4") * 0.3 for l in (3, 5)]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4 * D], dtype="float32", lod_level=1)
        hidden, _ = fluid.layers.dynamic_lstm(x, size=4 * D, is_reverse=True,
                                              use_peepholes=False)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    (hv,) = exe.run(main, feed={"x": LoDTensor(rows)}, fetch_list=[hidden], scope=scope)
    assert np.isfinite(np.asarray(hv)).all()


def test_dynamic_gru_golden():
    D = 4
    rng = np.random.RandomState(2)
    lengths = [3, 5]
    rows = [rng.randn(l, 3 * D).astype("f4") * 0.4 for l in lengths]
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 9
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [3 * D], dtype="float32", lod_level=1)
        h = fluid.layers.dynamic_gru(x, size=D,
                                     param_attr=fluid.ParamAttr(name="gru_w"),
                                     bias_attr=fluid.ParamAttr(name="gru_b"))
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    w = np.asarray(scope.find_var("gru_w"))
    b = np.asarray(scope.find_var("gru_b")).reshape(-1)
    (hv,) = exe.run(main, feed={"x": LoDTensor(rows)}, fetch_list=[h], scope=scope)
    hv = np.asarray(hv)
    sig = lambda v: 1.0 / (1.0 + np.exp(-v))
    for i, row in enumerate(rows):
        hprev = np.zeros(D)
        for t, xt in enumerate(row):
            ur = sig(xt[:2 * D] + hprev @ w[:, :2 * D] + b[:2 * D])
            u, r = ur[:D], ur[D:]
            cand = np.tanh(xt[2 * D:] + (r * hprev) @ w[:, 2 * D:] + b[2 * D:])
            hprev = (1 - u) * hprev + u * cand
            np.testing.assert_allclose(hv[i, t], hprev, atol=1e-5, rtol=1e-4)


def test_dynamic_lstm_trains():
    """stacked_dynamic_lstm-style classifier converges (reference
    benchmark/fluid/models/stacked_dynamic_lstm.py shape)."""
    D = 8
    rng = np.random.RandomState(4)
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [6], dtype="float32", lod_level=1)
        label = fluid.layers.data("label", [1], dtype="float32")
        proj = fluid.layers.fc(x, 4 * D, num_flatten_dims=2)
        hidden, _ = fluid.layers.dynamic_lstm(proj, size=4 * D, use_peepholes=False)
        last = fluid.layers.sequence_last_step(hidden)
        pred = fluid.layers.fc(last, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
        fluid.optimizer.Adam(0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    exe.run(startup, scope=scope)
    losses = []
    for _ in range(30):
        lengths = rng.randint(2, 6, size=8)
        rows = [rng.randn(l, 6).astype("f4") for l in lengths]
        y = np.asarray([[r.sum() > 0] for r in rows], dtype="f4")
        (lv,) = exe.run(main, feed={"x": LoDTensor(rows), "label": y},
                        fetch_list=[loss], scope=scope)
        losses.append(float(np.asarray(lv).reshape(-1)[0]))
    assert losses[-1] < losses[0]


def test_beam_search_beam1_equals_greedy():
    from paddle_tpu.models import nmt

    main, startup, feeds, fetches = nmt.build_nmt_infer(
        src_vocab=30, tgt_vocab=30, d_model=16, n_layers=1, n_heads=2, d_ff=32)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    startup.random_seed = 11
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    src = [rng.randint(3, 30, (4, 1)).astype("int64"),
           rng.randint(3, 30, (6, 1)).astype("int64")]
    seq1, sc1 = nmt.beam_search_decode(exe, main, fetches["logits"], scope, src,
                                       beam_size=1, max_len=6)
    seq4, sc4 = nmt.beam_search_decode(exe, main, fetches["logits"], scope, src,
                                       beam_size=4, max_len=6)
    assert seq1.shape == (2, 6) and seq4.shape == (2, 6)
    # beam search can only match or beat greedy on total log-prob
    assert (sc4 >= sc1 - 1e-6).all()
    assert (seq1[:, 0] == 1).all()  # bos
