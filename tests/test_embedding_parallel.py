"""Embedding parallelism: sharded lookup == dense lookup; and the
program-level path (hints + GSPMD) trains (reference: distributed
lookup-table / CTR path)."""
import numpy as np

import jax.numpy as jnp

import paddle_tpu as fluid
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.embedding import sharded_lookup


def test_sharded_lookup_matches_dense():
    rng = np.random.RandomState(0)
    V, D = 64, 12
    table = rng.randn(V, D).astype("f4")
    ids = rng.randint(0, V, size=(5, 7))
    mesh = make_mesh((8,), ("ep",))
    got = np.asarray(sharded_lookup(jnp.asarray(ids), jnp.asarray(table), mesh))
    np.testing.assert_allclose(got, table[ids], atol=1e-6)


def test_program_level_embedding_sharded_trains():
    main, startup = fluid.Program(), fluid.Program()
    startup.random_seed = 2
    with fluid.program_guard(main, startup):
        ids = fluid.layers.data("ids", [1], dtype="int64")
        label = fluid.layers.data("label", [1], dtype="float32")
        emb = fluid.layers.embedding(
            ids, size=[128, 16], is_distributed=True,
            param_attr=fluid.ParamAttr(name="dist_emb"),
        )
        pred = fluid.layers.fc(emb, 1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, label))
        fluid.optimizer.Adagrad(learning_rate=0.1).minimize(loss)
    n = fluid.parallel.shard_parameters(main, {"dist_emb": ("ep", None)})
    assert n == 1
    mesh = make_mesh((2, 4), ("dp", "ep"))
    compiled = fluid.CompiledProgram(main).with_mesh(mesh)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup, scope=scope)
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(12):
        iv = rng.randint(0, 128, size=(16, 1))
        lv = (iv % 3).astype("f4")
        (l,) = exe.run(compiled, feed={"ids": iv, "label": lv}, fetch_list=[loss], scope=scope)
        losses.append(float(l[0]))
    assert losses[-1] < losses[0]
    # table must be ep-sharded in the scope
    spec = scope.find_var("dist_emb").sharding.spec
    assert tuple(spec) == ("ep", None)
