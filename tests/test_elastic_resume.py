"""Elastic N->M resume (ISSUE 9), in-process tier-1 coverage.

Three layers, each with its own exactness contract:

  * parameters: `io.save_sharded` shards written under one world size /
    mesh split must consolidate and re-split BIT-IDENTICALLY for any
    other (the region reader stitches coverage; SelectedRows re-deal by
    row id);
  * stream cursors: N `reader.shard` cursors re-split into M cursors
    with exact sample coverage — nothing dropped, nothing double-
    trained — across the same N->M matrix;
  * the CheckpointManager contract: a world-size mismatch RAISES a
    classified CheckpointError on the default path and re-shards on the
    elastic path; commits garbage-collect stale pending dirs and
    ghost-rank artifacts (`resilience.ckpt_gc`).
"""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as fluid
from paddle_tpu import io as pio
from paddle_tpu import reader as R
from paddle_tpu.core.selected_rows import SelectedRows
from paddle_tpu.errors import CheckpointError
from paddle_tpu.monitor import MONITOR as _MON
from paddle_tpu.parallel import make_mesh
from paddle_tpu.parallel.sharding import (consolidate_selected_rows,
                                          repartition_selected_rows,
                                          row_range)
from paddle_tpu.resilience import resume_sidecar_name


@pytest.fixture(autouse=True)
def _mon_enabled():
    """Counter asserts need the monitor live (inc() is a no-op disabled)."""
    from paddle_tpu import monitor

    monitor.enable()
    yield


# --- helpers ----------------------------------------------------------------

class CountingBase:
    """Checkpointable base stream of ints [0, n) (the unit-test stand-in
    for a RecordIO scanner)."""

    def __init__(self, n):
        self.n = n
        self._next = 0

    def state_dict(self):
        return {"pos": self._next}

    def load_state_dict(self, state):
        self._next = int(state["pos"])

    def __call__(self):
        i = self._next
        self._next = 0
        while i < self.n:
            self._next = i + 1
            yield i
            i += 1
            self._next = i


class StatelessBase:
    """Deterministic but NOT checkpointable: resume must replay."""

    def __init__(self, n):
        self.n = n

    def __call__(self):
        yield from range(self.n)


def _prog_for(scope):
    """A program whose persistables are exactly the scope's numeric vars
    (CheckpointManager saves program persistables)."""
    prog = fluid.Program()
    blk = prog.global_block()
    for name in scope.local_var_names():
        a = np.asarray(scope.find_var(name))
        blk.create_parameter(name, list(a.shape), str(a.dtype))
    return prog


def _coordinated_save(root, scope, step, world=2, sidecars=None):
    """Drive one coordinated world-N save in-process (rank 0 commits)."""
    prog = _prog_for(scope)
    cms = [fluid.CheckpointManager(root, program=prog, scope=scope,
                                   rank=r, world_size=world,
                                   commit_timeout_s=10)
           for r in range(world)]
    for r in range(world - 1, -1, -1):  # rank 0 last: it waits + commits
        side = {resume_sidecar_name(r, world): sidecars[r]} \
            if sidecars else None
        cms[r].save(step=step, sidecars=side)
    return cms


# --- satellite: explicit world-size check -----------------------------------

def test_restore_world_mismatch_raises_classified(tmp_path):
    scope = fluid.Scope()
    scope.set_var("w", np.arange(12, dtype="f4").reshape(3, 4))
    root = str(tmp_path / "ck")
    _coordinated_save(root, scope, step=4, world=2)

    cm1 = fluid.CheckpointManager(root, scope=fluid.Scope(), world_size=1)
    with pytest.raises(CheckpointError) as ei:
        cm1.restore()
    assert "2" in str(ei.value) and "1" in str(ei.value)
    assert ei.value.saved_world == 2 and ei.value.current_world == 1
    # classified: classify() keeps it (a TrainingError the resilient loop
    # must never retry), and it names the checkpoint phase
    from paddle_tpu.errors import classify

    assert classify(ei.value) is ei.value
    assert ei.value.phase == "checkpoint"


def test_restore_world_mismatch_elastic_loads(tmp_path):
    scope = fluid.Scope()
    want = np.arange(12, dtype="f4").reshape(3, 4)
    scope.set_var("w", want)
    root = str(tmp_path / "ck")
    _coordinated_save(root, scope, step=4, world=2)

    scope1 = fluid.Scope()
    cm1 = fluid.CheckpointManager(root, scope=scope1, world_size=1,
                                  elastic=True)
    assert cm1.restore() == 4
    np.testing.assert_array_equal(np.asarray(scope1.find_var("w")), want)
    assert cm1.restored_world == 2
    assert cm1.last_restored_dir and cm1.last_restored_dir.endswith(
        "ckpt-0000000004")


# --- tentpole: N->M parameter re-sharding matrix ----------------------------

@pytest.mark.parametrize("n,m", [(1, 2), (2, 1), (2, 4), (4, 2),
                                 (3, 2), (2, 3)])
def test_param_resharding_matrix_bit_identical(tmp_path, n, m):
    """Shards saved from an n-way split restore bit-identically onto an
    m-way split (including the non-divisor 3<->2 'odd' transitions)."""
    rows = 12  # divisible by 1..4 and 6: every split in the matrix works
    arr = np.random.RandomState(7).rand(rows, 5).astype("f4")
    vec = np.random.RandomState(8).rand(rows).astype("f4")
    mesh_n = make_mesh((n,), ("mp",))
    scope = fluid.Scope()
    scope.set_var("w", jax.device_put(jnp.asarray(arr),
                                      NamedSharding(mesh_n, P("mp", None))))
    scope.set_var("v", jax.device_put(jnp.asarray(vec),
                                      NamedSharding(mesh_n, P("mp"))))
    ck = str(tmp_path / "ck")
    pio.save_sharded(ck, var_names=["w", "v"], scope=scope)

    # consolidate-and-resplit onto the m-way mesh
    mesh_m = make_mesh((m,), ("mp",))
    scope2 = fluid.Scope()
    pio.load_sharded(ck, scope=scope2, mesh=mesh_m)
    got_w = scope2.find_var("w")
    np.testing.assert_array_equal(np.asarray(got_w), arr)
    np.testing.assert_array_equal(np.asarray(scope2.find_var("v")), vec)
    assert tuple(got_w.sharding.spec) == ("mp", None)
    assert len({s.device for s in got_w.addressable_shards}) == m

    # ...and onto no mesh at all (host consolidation)
    scope3 = fluid.Scope()
    pio.load_sharded(ck, scope=scope3)
    np.testing.assert_array_equal(np.asarray(scope3.find_var("w")), arr)


# --- tentpole: SelectedRows repartitioned by row id -------------------------

def test_selected_rows_row_range_partition():
    assert row_range(12, 0, 2) == (0, 6)
    assert row_range(12, 1, 2) == (6, 12)
    # ceil split: remainder rows land on leading ranks, tail rank clips
    assert [row_range(10, r, 3) for r in range(3)] == [(0, 4), (4, 8),
                                                      (8, 10)]
    cover = set()
    for r in range(3):
        lo, hi = row_range(10, r, 3)
        cover.update(range(lo, hi))
    assert cover == set(range(10))


def test_selected_rows_elastic_resharding(tmp_path):
    """A row-slab table saved by 2 ranks re-deals exactly onto 3."""
    height, d = 12, 2
    vals = np.arange(height * d, dtype="f4").reshape(height, d)
    ck = str(tmp_path / "ck")
    for r in range(2):
        lo, hi = row_range(height, r, 2)
        sc = fluid.Scope()
        sc.set_var("tbl", SelectedRows(
            np.arange(lo, hi, dtype=np.int32), vals[lo:hi], height))
        pio.save_sharded(ck, var_names=["tbl"], scope=sc, process_index=r)

    for r in range(3):
        sc = fluid.Scope()
        pio.load_sharded(ck, scope=sc, row_shard=(r, 3))
        got = sc.find_var("tbl")
        assert isinstance(got, SelectedRows)
        lo, hi = row_range(height, r, 3)
        np.testing.assert_array_equal(np.asarray(got.rows),
                                      np.arange(lo, hi))
        np.testing.assert_array_equal(np.asarray(got.values), vals[lo:hi])
    # without row_shard: the full consolidated table
    sc = fluid.Scope()
    pio.load_sharded(ck, scope=sc)
    got = sc.find_var("tbl")
    np.testing.assert_array_equal(np.asarray(got.rows), np.arange(height))
    np.testing.assert_array_equal(np.asarray(got.values), vals)


def test_selected_rows_overlapping_shards_raise():
    with pytest.raises(CheckpointError):
        consolidate_selected_rows(
            [(np.array([0, 1]), np.ones((2, 2), "f4")),
             (np.array([1, 2]), np.ones((2, 2), "f4"))], height=4)


def test_repartition_selected_rows_is_exact():
    rows = np.array([0, 3, 5, 9, 11], np.int32)
    vals = np.arange(10, dtype="f4").reshape(5, 2)
    pieces = [repartition_selected_rows(rows, vals, 12, r, 3)
              for r in range(3)]
    got_rows = np.concatenate([p[0] for p in pieces])
    got_vals = np.concatenate([p[1] for p in pieces])
    np.testing.assert_array_equal(np.sort(got_rows), rows)
    order = np.argsort(got_rows)
    np.testing.assert_array_equal(got_vals[order], vals)


# --- tentpole: stream-cursor N->M matrix ------------------------------------

def _make_pipeline(rank, world, bs, total=96, base_cls=CountingBase):
    return R.batch(R.shard(base_cls(total), rank, world), bs,
                   drop_last=True)


@pytest.mark.parametrize("n,m", [(1, 2), (2, 1), (2, 4), (4, 2),
                                 (2, 3), (3, 2)])
def test_cursor_repartition_matrix_exact_coverage(n, m):
    """Consume k global batches at world n, repartition the cursors to
    world m, drain: every sample appears exactly once overall."""
    GBS, total = 12, 96
    readers = [_make_pipeline(r, n, GBS // n) for r in range(n)]
    its = [iter(rd()) for rd in readers]
    consumed = []
    for _ in range(3):  # 3 global steps in lockstep
        for it in its:
            consumed.extend(next(it))
    states = [rd.state_dict() for rd in readers]
    new_states = R.repartition_stream_states(states, m)
    rest = []
    for r, st in enumerate(new_states):
        rd = _make_pipeline(r, m, GBS // m)
        rd.load_state_dict(st)
        for b in rd():
            rest.extend(b)
    assert sorted(consumed) == list(range(3 * GBS))
    assert sorted(consumed + rest) == list(range(total)), \
        "elastic resplit dropped or duplicated samples"


def test_cursor_repartition_exact_seek_no_replay():
    """With a checkpointable base the resplit seeks O(1): the loud
    shard-replay counter must not move."""
    before = _MON.counter("data.shard_replay").value
    test_cursor_repartition_matrix_exact_coverage(2, 3)
    assert _MON.counter("data.shard_replay").value == before


def test_cursor_repartition_stateless_base_replays_loudly():
    GBS, total = 12, 48
    readers = [_make_pipeline(r, 2, GBS // 2, total, StatelessBase)
               for r in range(2)]
    its = [iter(rd()) for rd in readers]
    consumed = []
    for _ in range(2):
        for it in its:
            consumed.extend(next(it))
    states = [rd.state_dict() for rd in readers]
    assert all(st["src"]["base"] is None for st in states)
    new_states = R.repartition_stream_states(states, 1)
    before = _MON.counter("data.shard_replay").value
    rd = _make_pipeline(0, 1, GBS, total, StatelessBase)
    rd.load_state_dict(new_states[0])
    rest = [x for b in rd() for x in b]
    assert sorted(consumed + rest) == list(range(total))
    # the fallback replayed the consumed prefix — loudly
    assert _MON.counter("data.shard_replay").value == before + len(consumed)


def test_cursor_repartition_chained_non_aligned_watermark_stays_exact():
    """Second resize after a split at a watermark NOT divisible by the
    new world size: the rank->position assignment rotates by G mod M, so
    the validator must accept the position MULTISET per residue class —
    a fixed rank-ordered formula wrongly rejected this and silently
    degraded every non-divisor resize chain to O(dataset) replay."""
    total = 120
    # world 2, global batch 10 -> watermark 10 (10 % 3 == 1: non-aligned)
    gen1 = [_make_pipeline(r, 2, 5, total) for r in range(2)]
    its = [iter(rd()) for rd in gen1]
    consumed = []
    for it in its:
        consumed.extend(next(it))
    st2 = R.repartition_stream_states([rd.state_dict() for rd in gen1], 3)
    # world 3, per-rank batch 2: two lock-step global steps from pos 10
    gen2 = []
    for r, st in enumerate(st2):
        rd = _make_pipeline(r, 3, 2, total)
        rd.load_state_dict(st)
        gen2.append(rd)
    its = [iter(rd()) for rd in gen2]
    for _ in range(2):
        for it in its:
            consumed.extend(next(it))
    states = [rd.state_dict() for rd in gen2]
    # the rotated positions are a consistent prefix: must NOT raise, and
    # must stay an exact O(1) seek (no loud replay)
    before = _MON.counter("data.shard_replay").value
    st3 = R.repartition_stream_states(states, 2)
    rest = []
    for r, st in enumerate(st3):
        rd = _make_pipeline(r, 2, 11, total)
        rd.load_state_dict(st)
        for b in rd():
            rest.extend(b)
    assert _MON.counter("data.shard_replay").value == before
    assert sorted(consumed) == list(range(22))
    assert sorted(consumed + rest) == list(range(22 + 88)), \
        "chained resize dropped or duplicated samples"


def test_cursor_repartition_inconsistent_raises():
    readers = [_make_pipeline(r, 2, 6) for r in range(2)]
    its = [iter(rd()) for rd in readers]
    next(its[0])
    next(its[0])  # rank 0 two batches ahead: not a consistent prefix
    next(its[1])
    states = [rd.state_dict() for rd in readers]
    with pytest.raises(ValueError):
        R.repartition_stream_states(states, 3)


def test_shard_rejects_foreign_rank_cursor():
    rd = R.shard(CountingBase(10), 0, 2)
    st = rd.state_dict()
    rd2 = R.shard(CountingBase(10), 0, 3)
    with pytest.raises(ValueError):
        rd2.load_state_dict(st)


# --- RESUME sidecar repartition end-to-end ----------------------------------

def test_resume_sidecar_repartition_end_to_end(tmp_path):
    """Coordinated world-2 checkpoint with real sidecars -> elastic
    world-1 resume info with an exactly-repositioned cursor."""
    from paddle_tpu import elastic as EL

    GBS, total = 12, 60
    readers = [_make_pipeline(r, 2, GBS // 2, total) for r in range(2)]
    its = [iter(rd()) for rd in readers]
    consumed = []
    for _ in range(2):  # 2 global steps -> checkpoint at step 2
        for it in its:
            consumed.extend(next(it))
    sidecars = []
    for rd in readers:
        sidecars.append(json.dumps({
            "step": 2, "next_batch": 2, "skipped_batches": 0,
            "stream_state": pio.pack_stream_state(rd.state_dict())}))
    scope = fluid.Scope()
    scope.set_var("w", np.ones(3, "f4"))
    root = str(tmp_path / "ck")
    _coordinated_save(root, scope, step=2, world=2, sidecars=sidecars)
    d = os.path.join(root, "ckpt-0000000002")

    info = EL.repartition_resume_info(d, old_world=2, new_rank=0,
                                      new_world=1)
    assert info["step"] == 2 and info["next_batch"] == 2
    assert info["elastic_from"] == 2
    assert "stream_state" in info, "exact split expected for shard cursors"
    rd1 = _make_pipeline(0, 1, GBS, total)
    rd1.load_state_dict(pio.unpack_stream_state(info["stream_state"]))
    rest = [x for b in rd1() for x in b]
    assert sorted(consumed + rest) == list(range(total))


def test_resume_sidecar_repartition_inconsistent_raises(tmp_path):
    from paddle_tpu import elastic as EL

    d = str(tmp_path / "ckpt-0000000002")
    os.makedirs(d)
    for r, nb in enumerate([2, 5]):  # torn: ranks disagree on position
        with open(os.path.join(d, resume_sidecar_name(r, 2)), "w") as f:
            json.dump({"step": 2, "next_batch": nb}, f)
    with pytest.raises(CheckpointError):
        EL.repartition_resume_info(d, old_world=2, new_rank=0, new_world=1)


def test_resume_sidecar_repartition_fallback_without_stream_state(tmp_path):
    from paddle_tpu import elastic as EL

    d = str(tmp_path / "ckpt-0000000004")
    os.makedirs(d)
    for r in range(2):
        with open(os.path.join(d, resume_sidecar_name(r, 2)), "w") as f:
            json.dump({"step": 4, "next_batch": 4, "skipped_batches": 1}, f)
    info = EL.repartition_resume_info(d, old_world=2, new_rank=1,
                                      new_world=3)
    assert info["next_batch"] == 4 and "stream_state" not in info
    assert info["skipped_batches"] == 1


# --- satellite: checkpoint GC -----------------------------------------------

def test_commit_sweeps_stale_pending_dirs(tmp_path):
    root = str(tmp_path / "ck")
    os.makedirs(os.path.join(root, "ckpt-0000000002.tmp"))
    with open(os.path.join(root, "ckpt-0000000002.tmp", "junk"), "w") as f:
        f.write("debris of a dead incarnation")
    os.makedirs(os.path.join(root, "ckpt-0000000099.tmp"))  # future save
    scope = fluid.Scope()
    scope.set_var("w", np.ones(3, "f4"))
    cm = fluid.CheckpointManager(root, scope=scope)
    before = _MON.counter("resilience.ckpt_gc").value
    cm.save(step=6)
    assert not os.path.exists(os.path.join(root, "ckpt-0000000002.tmp"))
    # a pending dir for a LATER step may be a live writer: left alone
    assert os.path.exists(os.path.join(root, "ckpt-0000000099.tmp"))
    assert _MON.counter("resilience.ckpt_gc").value == before + 1


def test_coordinated_commit_sweeps_ghost_rank_artifacts(tmp_path):
    """A pending dir reused at the same step by a previously-LARGER
    incarnation: per-rank files of ranks >= the committing world size
    must not survive into the committed checkpoint."""
    root = str(tmp_path / "ck")
    tmp = os.path.join(root, "ckpt-0000000004.tmp")
    os.makedirs(tmp)
    ghosts = ["SHARD_DONE.p3", "RESUME.p2.json",
              "__sharded_manifest__.p2.json", "w.p3s0.npy"]
    for g in ghosts:
        with open(os.path.join(tmp, g), "w") as f:
            f.write("ghost of world 4")
    scope = fluid.Scope()
    scope.set_var("w", np.ones(3, "f4"))
    before = _MON.counter("resilience.ckpt_gc").value
    _coordinated_save(root, scope, step=4, world=2)
    final = os.path.join(root, "ckpt-0000000004")
    assert os.path.exists(os.path.join(final, "COMMITTED"))
    for g in ghosts:
        assert not os.path.exists(os.path.join(final, g)), g
    # current ranks' artifacts survive
    assert os.path.exists(os.path.join(final, "SHARD_DONE.p0"))
    assert os.path.exists(os.path.join(final, "SHARD_DONE.p1"))
    assert _MON.counter("resilience.ckpt_gc").value >= before + len(ghosts)


# --- satellite: health layer re-arms on resize ------------------------------

def test_init_health_rearms_on_world_change(tmp_path, monkeypatch):
    from paddle_tpu import dist_resilience as dres

    monkeypatch.setenv("PADDLE_HEARTBEAT_DIR", str(tmp_path / "hb"))
    try:
        wd2 = dres.init_health(0, 2)
        assert dres.init_health(0, 2) is wd2  # same membership: idempotent
        assert dres.active_heartbeat().world == 2
        wd3 = dres.init_health(0, 3)  # resized: re-armed
        assert wd3 is not wd2
        assert dres.active_heartbeat().world == 3
        assert dres.active_watchdog() is wd3
    finally:
        dres.shutdown_health()
