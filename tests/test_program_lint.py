"""tools/program_lint.py: the static-analysis CI gate over the model zoo
(tier-1 wiring for ISSUE 6 satellite: lint --check + coverage-floor gate)."""
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _run(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "program_lint.py"), *args],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)


def test_lint_check_zoo_is_clean_and_covered():
    r = _run("--check")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CHECK OK" in r.stdout
    assert "coverage" in r.stdout


def test_lint_coverage_gate_trips_when_floor_unreachable():
    # the ratchet works: an impossible floor must fail the gate
    r = _run("--check", "--min-coverage", "1.01")
    assert r.returncode == 1, r.stdout + r.stderr
    assert "infer_coverage_frac" in r.stdout


def test_lint_renders_serialized_programs(tmp_path):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data("x", [4], dtype="float32")
        fluid.layers.relu(x)
    p = tmp_path / "prog.json"
    p.write_text(main.to_string())
    r = _run(str(p))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "coverage" in r.stdout
